"""Deterministic fault injection (``PDT_TPU_FAULT``).

The recovery machinery in this repo — supervisor restarts, checkpoint
verification + fallback, preemption-safe shutdown, the hung-step watchdog —
is only a guarantee if every path is exercised end-to-end. This layer turns
one environment variable into reproducible failures at exact points of a
run, so CPU-only tests (and chaos drills on real pods) drive the real code
paths instead of mocks.

Syntax — comma-separated specs, each ``kind:arg`` with an optional ``@rank``
(the process index that fires; default 0):

- ``crash_at_step:7``    raise ``InjectedCrash`` right after update 7 — the
                         supervisor-retryable failure (``run_with_restarts``
                         catches it, restarts, resumes from checkpoint). For
                         a hard ``os._exit`` kill (no python cleanup) use the
                         ``--crash-at-step`` TrainConfig flag instead.
- ``sigterm_at_step:5``  deliver SIGTERM to this process after update 5 —
                         exercises the preemption path: emergency checkpoint,
                         ``preemption`` telemetry record, resumable exit code.
- ``hang_at_step:3``     block forever after update 3 inside a watchdog-
                         guarded section — exercises stall detection + abort.
- ``corrupt_ckpt:latest`` flip bytes in the newest committed checkpoint when
                         the Checkpointer closes (``corrupt_ckpt:12`` targets
                         step 12) — exercises manifest verification and the
                         fall-back-to-verified-step restore.
- ``slow_host:2x``       stretch this host's batch assembly by the given
                         factor — exercises straggler detection without a
                         slow machine.

Serve-scoped kinds (fired from the decode engine's tick loop, counted in
BUSY ticks — ticks that admitted/decoded work — so idle spinning never
advances the schedule and the failure lands at a deterministic point of
the request stream):

- ``replica_crash:3``     hard-kill this replica process (``os._exit``, no
                          python cleanup — sockets die mid-stream) right
                          after busy tick 3; the fleet supervisor's crash
                          path and the router's failover path run for real.
- ``replica_hang:3:2``    block the serve loop for 2s (default 2) after busy
                          tick 3 — the engine heartbeat goes stale, /healthz
                          flips to ``unhealthy``, the router's breaker trips,
                          and recovery via half-open probe is exercised when
                          the hang ends.
- ``replica_slow:3:4x``   from busy tick 3 onward stretch every tick to 4x
                          its real duration (stays armed, like a genuinely
                          slow replica) — drives deadline expiry and the
                          router's load-away-from-slow behavior.

The ``replica_*`` kinds also accept a time trigger — ``replica_crash:t3.5``
fires on the first busy tick at/after 3.5s from plan arm — for drills
where the busy-tick count is load-dependent (a replayed storm killing a
replica mid-burst lands the kill by wall clock, not by tick).

Swap-scoped kinds (fired from the hot-swap loader, serve/hotswap.py, when
it loads the named CHECKPOINT STEP for a live weight swap — the argument
is a checkpoint step, not a tick):

- ``corrupt_ckpt_swap:12`` raise mid-load of checkpoint step 12 (the
                          corrupt-array failure manifest verification
                          missed) — exercises swap rollback: the replica
                          stays serving its OLD weights, the step lands on
                          the watcher's blocklist, the fleet converges on
                          the next good step.
- ``swap_crash:12``       hard-kill this replica (``os._exit``) mid-load of
                          step 12 — a swap must never turn a replica crash
                          into an outage: the supervisor respawns it and
                          the fresh process boots on the newest verified
                          step.
- ``swap_slow:12:3``      sleep 3s (default 2) inside the load of step 12 —
                          stretches the rollout window, driving the
                          version-skew-duration telemetry and the
                          p99-under-swap bench.

Every spec fires AT MOST ONCE per process (a restarted attempt inside the
same process does not re-fire; ``slow_host``/``replica_slow`` stay armed but
record once), so an injected crash converges to recovery instead of
crash-looping.
"""

from __future__ import annotations

import dataclasses
import os
import re
import time

from pytorch_distributed_training_tpu.utils.logging import get_logger

ENV_VAR = "PDT_TPU_FAULT"

_STEP_KINDS = ("crash_at_step", "sigterm_at_step", "hang_at_step")
# serve-scoped: routed to fleet replicas by @rank (serve/fleet.py). The
# replica_* kinds count busy engine ticks; the swap kinds key on the
# checkpoint step the hot-swap loader is reading.
_SWAP_KINDS = ("corrupt_ckpt_swap", "swap_crash", "swap_slow")
_SERVE_KINDS = (
    "replica_crash", "replica_hang", "replica_slow",
) + _SWAP_KINDS
_KINDS = _STEP_KINDS + ("corrupt_ckpt", "slow_host") + _SERVE_KINDS

#: the exit status of a hard replica kill — anything but 0/75, so the fleet
#: supervisor counts it as a crash (burning a restart), never as graceful
REPLICA_CRASH_EXIT_CODE = 23

logger = get_logger(__name__)


class InjectedCrash(RuntimeError):
    """The deterministic stand-in for a dying host: raised at an exact step
    boundary so the supervisor's catch→restart→resume loop runs for real."""


@dataclasses.dataclass
class FaultSpec:
    kind: str
    step: int = 0          # *_at_step kinds
    at_s: float = 0.0      # replica_* kinds with a t<seconds> trigger
    target: str = ""       # corrupt_ckpt: "latest" or a step number
    factor: float = 1.0    # slow_host
    rank: int = 0          # process index that fires
    fired: bool = False


def _parse_spec(text: str) -> FaultSpec:
    text = text.strip()
    rank = 0
    if "@" in text:
        text, rank_s = text.rsplit("@", 1)
        rank = int(rank_s)
    if ":" not in text:
        raise ValueError(f"fault spec {text!r} needs kind:arg")
    kind, arg = text.split(":", 1)
    if kind not in _KINDS:
        raise ValueError(f"unknown fault kind {kind!r}; have {_KINDS}")
    spec = FaultSpec(kind=kind, rank=rank)
    if kind in _STEP_KINDS:
        spec.step = int(arg)
        if spec.step <= 0:
            raise ValueError(f"{kind} needs a positive step, got {arg!r}")
    elif kind in _SERVE_KINDS:
        parts = arg.split(":")
        if kind not in _SWAP_KINDS and parts[0][:1] == "t":
            # time-based trigger (replica_crash:t3.5): fire on the first
            # busy tick at/after this wall-clock offset from plan arm —
            # the handle a storm bench needs to land a kill inside a
            # replayed burst window, where the busy-tick count is load-
            # dependent and unknowable up front
            spec.at_s = float(parts[0][1:])
            if spec.at_s <= 0:
                raise ValueError(
                    f"{kind} needs a positive time offset, got {arg!r}"
                )
        else:
            spec.step = int(parts[0])
            if kind in _SWAP_KINDS:
                # checkpoint steps start at 0; busy ticks start at 1
                if spec.step < 0:
                    raise ValueError(
                        f"{kind} needs a checkpoint step >= 0, got {arg!r}"
                    )
            elif spec.step <= 0:
                raise ValueError(
                    f"{kind} needs a positive tick, got {arg!r}"
                )
        if kind in ("replica_hang", "swap_slow"):
            if len(parts) > 2:
                raise ValueError(
                    f"{kind} takes {'step' if kind == 'swap_slow' else 'tick'}"
                    f"[:seconds], got {arg!r}"
                )
            spec.factor = float(parts[1]) if len(parts) == 2 else 2.0
            if spec.factor <= 0:
                raise ValueError(
                    f"{kind} needs a positive duration, got {arg!r}"
                )
        elif kind == "replica_slow":
            if len(parts) != 2:
                raise ValueError(f"{kind} needs tick:factor (e.g. 3:4x), "
                                 f"got {arg!r}")
            m = re.fullmatch(r"([0-9.]+)x?", parts[1])
            if not m or float(m.group(1)) < 1.0:
                raise ValueError(
                    f"{kind} needs a factor >= 1 (e.g. 4x), got {arg!r}"
                )
            spec.factor = float(m.group(1))
        elif len(parts) != 1:
            raise ValueError(f"{kind} takes a bare tick/step, got {arg!r}")
    elif kind == "corrupt_ckpt":
        if arg != "latest" and not arg.isdigit():
            raise ValueError(
                f"corrupt_ckpt target must be 'latest' or a step, got {arg!r}"
            )
        spec.target = arg
    else:  # slow_host
        m = re.fullmatch(r"([0-9.]+)x?", arg)
        if not m or float(m.group(1)) < 1.0:
            raise ValueError(f"slow_host needs a factor >= 1 (e.g. 2x), got {arg!r}")
        spec.factor = float(m.group(1))
    return spec


def _process_index() -> int:
    try:
        import jax

        return jax.process_index()
    except Exception:  # pragma: no cover - jax always importable here
        return 0


def _emit(record: dict) -> None:
    from pytorch_distributed_training_tpu.telemetry.registry import get_registry

    reg = get_registry()
    reg.inc("faults/injected")
    reg.emit({"record": "fault_injected", **record})


class FaultPlan:
    """The parsed, per-process fault schedule. Hooks are called from the
    Trainer (step boundaries), the loaders (batch assembly) and the
    Checkpointer (close) — each is a no-op when no matching spec is armed."""

    def __init__(self, specs: list[FaultSpec]):
        self.specs = specs
        # reference clock for t<seconds> serve triggers: offsets count
        # from when this plan was armed (process start, in practice)
        self.armed_t = time.monotonic()

    @classmethod
    def parse(cls, text: str | None) -> "FaultPlan":
        if not text or not text.strip():
            return cls([])
        return cls([_parse_spec(s) for s in text.split(",") if s.strip()])

    def _take(self, kind: str, pred) -> FaultSpec | None:
        """The first unfired spec of ``kind`` matching ``pred`` on this
        process, marked fired."""
        pidx = _process_index()
        for spec in self.specs:
            if (
                spec.kind == kind
                and not spec.fired
                and spec.rank == pidx
                and pred(spec)
            ):
                spec.fired = True
                return spec
        return None

    # --------------------------------------------------------------- hooks

    def fire_step_fault(self, step: int) -> None:
        """Trainer hook, called right after completing update ``step``."""
        spec = self._take("crash_at_step", lambda s: s.step == step)
        if spec is not None:
            _emit({"fault": "crash_at_step", "step": step})
            raise InjectedCrash(f"injected crash after step {step}")
        spec = self._take("sigterm_at_step", lambda s: s.step == step)
        if spec is not None:
            import signal

            _emit({"fault": "sigterm_at_step", "step": step})
            logger.warning("injecting SIGTERM after step %d", step)
            os.kill(os.getpid(), signal.SIGTERM)
            return
        spec = self._take("hang_at_step", lambda s: s.step == step)
        if spec is not None:
            from pytorch_distributed_training_tpu.faults.watchdog import (
                watchdog_guard,
            )

            _emit({"fault": "hang_at_step", "step": step})
            logger.warning("injecting hang after step %d", step)
            with watchdog_guard("injected_hang", step=step):
                while True:  # a stuck collective never returns; nor do we —
                    time.sleep(60)  # the watchdog's hard timeout ends this

    def fire_serve_tick(self, busy_tick: int, elapsed_s: float) -> None:
        """Decode-engine hook, called after busy tick ``busy_tick`` (a tick
        that admitted or decoded work) took ``elapsed_s`` seconds. A spec
        matches by exact busy tick, or — ``t<seconds>`` triggers — on the
        first busy tick at/after its wall-clock offset from plan arm."""
        run_s = time.monotonic() - self.armed_t

        def due(s: FaultSpec) -> bool:
            if s.at_s > 0:
                return run_s >= s.at_s
            return s.step == busy_tick

        spec = self._take("replica_crash", due)
        if spec is not None:
            _emit({"fault": "replica_crash", "tick": busy_tick})
            logger.warning(
                "injecting replica crash after busy tick %d", busy_tick
            )
            self._flush_sink()
            os._exit(REPLICA_CRASH_EXIT_CODE)  # hard kill: no cleanup,
            # streams die mid-token — the failure the router must survive
        spec = self._take("replica_hang", due)
        if spec is not None:
            _emit({
                "fault": "replica_hang", "tick": busy_tick,
                "seconds": spec.factor,
            })
            logger.warning(
                "injecting %.1fs serve-loop hang after busy tick %d",
                spec.factor, busy_tick,
            )
            time.sleep(spec.factor)
            return
        pidx = _process_index()
        for spec in self.specs:
            if (
                spec.kind == "replica_slow"
                and spec.rank == pidx
                and (
                    run_s >= spec.at_s if spec.at_s > 0
                    else busy_tick >= spec.step
                )
            ):
                if not spec.fired:
                    spec.fired = True  # record the injection once; the
                    # stretch itself stays armed (a slow replica is slow
                    # on every tick, not once)
                    _emit({
                        "fault": "replica_slow", "tick": busy_tick,
                        "factor": spec.factor,
                    })
                time.sleep(max(0.0, elapsed_s) * (spec.factor - 1.0))
                return

    def fire_swap_load(self, ckpt_step: int) -> None:
        """Hot-swap loader hook (serve/hotswap.load_swap_params), called
        BEFORE any bytes of checkpoint ``ckpt_step`` are read — so the
        injected failure is deterministic and the engine's serving state
        is provably untouched when it fires."""
        spec = self._take("corrupt_ckpt_swap", lambda s: s.step == ckpt_step)
        if spec is not None:
            _emit({"fault": "corrupt_ckpt_swap", "ckpt_step": ckpt_step})
            logger.warning(
                "injecting corrupt-array failure into swap load of "
                "checkpoint step %d", ckpt_step,
            )
            raise InjectedCrash(
                f"injected corrupt checkpoint array during swap load of "
                f"step {ckpt_step}"
            )
        spec = self._take("swap_crash", lambda s: s.step == ckpt_step)
        if spec is not None:
            _emit({"fault": "swap_crash", "ckpt_step": ckpt_step})
            logger.warning(
                "injecting replica crash during swap load of checkpoint "
                "step %d", ckpt_step,
            )
            self._flush_sink()
            os._exit(REPLICA_CRASH_EXIT_CODE)  # the rollout must survive a
            # replica dying mid-swap: supervisor respawns, fresh process
            # boots on the newest verified step
        spec = self._take("swap_slow", lambda s: s.step == ckpt_step)
        if spec is not None:
            _emit({
                "fault": "swap_slow", "ckpt_step": ckpt_step,
                "seconds": spec.factor,
            })
            logger.warning(
                "injecting %.1fs stall into swap load of checkpoint step "
                "%d", spec.factor, ckpt_step,
            )
            time.sleep(spec.factor)

    @staticmethod
    def _flush_sink() -> None:
        """Best-effort telemetry flush before a hard ``os._exit`` (which
        skips every buffered-writer destructor)."""
        try:
            from pytorch_distributed_training_tpu.telemetry.registry import (
                get_registry,
            )

            sink = get_registry().sink
            if sink is not None:
                sink.flush(fsync=True)
        except Exception:  # pragma: no cover - dying anyway
            pass

    def slow_host_delay(self, elapsed_s: float) -> None:
        """Loader hook: stretch this host's batch work to ``factor`` × its
        real duration (the spec stays armed — a straggler is slow on every
        batch, not once)."""
        pidx = _process_index()
        for spec in self.specs:
            if spec.kind == "slow_host" and spec.rank == pidx:
                if not spec.fired:
                    spec.fired = True  # record the injection once
                    _emit({"fault": "slow_host", "factor": spec.factor})
                time.sleep(max(0.0, elapsed_s) * (spec.factor - 1.0))
                return

    def corrupt_checkpoint_target(self) -> str | None:
        """Checkpointer hook (at close): the step to corrupt, or None."""
        spec = self._take("corrupt_ckpt", lambda s: True)
        return spec.target if spec is not None else None


def corrupt_step_dir(step_path: str, *, flip_bytes: int = 64) -> str:
    """Corrupt a committed checkpoint step in place: overwrite the first
    ``flip_bytes`` of its largest data file (same length — the failure mode
    a size check alone cannot see). Returns the corrupted file's path."""
    victim, size = None, -1
    for root, _dirs, files in os.walk(step_path):
        for name in files:
            if name == "pdt_manifest.json":
                continue
            p = os.path.join(root, name)
            s = os.path.getsize(p)
            if s > size:
                victim, size = p, s
    if victim is None:
        raise FileNotFoundError(f"no data files under {step_path}")
    n = min(flip_bytes, size)
    with open(victim, "r+b") as f:
        head = f.read(n)
        f.seek(0)
        f.write(bytes(b ^ 0xFF for b in head))
    logger.warning("corrupted %d bytes of %s", n, victim)
    return victim


_active: FaultPlan | None = None


def get_plan() -> FaultPlan:
    """The process-wide plan, parsed from ``PDT_TPU_FAULT`` once (so each
    spec's fired-state survives supervisor restarts within the process)."""
    global _active
    if _active is None:
        _active = FaultPlan.parse(os.environ.get(ENV_VAR))
        if _active.specs:
            logger.warning(
                "fault injection armed: %s", os.environ.get(ENV_VAR)
            )
    return _active


def set_plan(plan: FaultPlan | None) -> FaultPlan | None:
    """Install ``plan`` (tests); returns the previous one. None re-arms
    lazy parsing from the environment."""
    global _active
    prev = _active
    _active = plan
    return prev
