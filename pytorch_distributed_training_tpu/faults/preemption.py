"""Preemption-safe shutdown: SIGTERM/SIGINT → stop at the next step boundary.

TPU pods are preempted constantly (maintenance events, spot reclamation,
queued-resource eviction) and the infra delivers SIGTERM with a short grace
window. The reference repo would simply die mid-step; here the Trainer
installs ``GracefulShutdown`` around its epoch loop: the handler only sets a
flag (async-signal-safe), the loop notices at the next step boundary, writes
an emergency checkpoint inside the grace window, emits a ``preemption``
telemetry record, and exits with ``RESUMABLE_EXIT_CODE`` — distinct from a
crash, so an external supervisor (k8s, the launch script, a restart loop)
can requeue the job without burning a failure-budget restart, and the
in-process ``run_with_restarts`` lets it propagate instead of retrying a
host that is about to disappear.
"""

from __future__ import annotations

import signal
import threading

from pytorch_distributed_training_tpu.utils.logging import get_logger

#: EX_TEMPFAIL — "transient, resubmit": the exit code of a preempted-but-
#: checkpointed run. Supervisors should restart it without counting it
#: against the restart budget.
RESUMABLE_EXIT_CODE = 75

logger = get_logger(__name__)


class Preempted(SystemExit):
    """Raised at the step boundary after a shutdown signal; carries
    ``RESUMABLE_EXIT_CODE`` so the process exit status says "resumable"."""

    def __init__(self, signum: int, step: int | None = None):
        super().__init__(RESUMABLE_EXIT_CODE)
        self.signum = signum
        self.step = step

    def __str__(self) -> str:  # SystemExit.__str__ prints the bare code
        name = signal.Signals(self.signum).name if self.signum else "?"
        return f"preempted by {name} (resumable, exit {RESUMABLE_EXIT_CODE})"


class GracefulShutdown:
    """Flag-setting SIGTERM/SIGINT handlers with install/uninstall.

    The handler body does nothing but record the signal — no I/O, no raise —
    so it is safe at any point of the run including inside jax dispatch. A
    SECOND SIGINT restores Python's default handler first, so a user who
    really means it gets an immediate KeyboardInterrupt instead of waiting
    out an emergency checkpoint.
    """

    def __init__(self, *, handle_sigint: bool = True):
        self._signals = [signal.SIGTERM] + (
            [signal.SIGINT] if handle_sigint else []
        )
        self._previous: dict[int, object] = {}
        self._requested: int | None = None
        self.installed = False

    # ------------------------------------------------------------ lifecycle

    def install(self) -> "GracefulShutdown":
        if threading.current_thread() is not threading.main_thread():
            # signal.signal only works on the main thread; a Trainer driven
            # from a worker thread just loses preemption handling, loudly
            logger.warning(
                "graceful-shutdown handlers not installed (not on the "
                "main thread)"
            )
            return self
        for sig in self._signals:
            self._previous[sig] = signal.signal(sig, self._handle)
        self.installed = True
        return self

    def uninstall(self) -> None:
        for sig, prev in self._previous.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):  # pragma: no cover - teardown
                pass
        self._previous.clear()
        self.installed = False

    def __enter__(self) -> "GracefulShutdown":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # -------------------------------------------------------------- signal

    def _handle(self, signum, frame) -> None:
        self._requested = signum
        if signum == signal.SIGINT:
            # next Ctrl-C is an ordinary KeyboardInterrupt
            signal.signal(signal.SIGINT, self._previous.get(
                signal.SIGINT, signal.default_int_handler
            ))

    @property
    def requested(self) -> int | None:
        """The signal number received, or None. Poll at step boundaries."""
        return self._requested
