"""Fault-tolerance subsystem: injection, preemption, and hung-step watchdog.

The reference repo's entire failure story is crash propagation
(``mp.spawn(..., join=True)`` re-raises and the run is over — SURVEY.md §5);
``utils/supervisor.py`` supplies the restart half. This package supplies the
rest of a production failure story:

- ``inject``     — deterministic fault injection (``PDT_TPU_FAULT``):
                   crash/SIGTERM/hang at a chosen step, checkpoint
                   corruption, a slowed host — so every recovery path is
                   exercised end-to-end in CPU-only tests;
- ``preemption`` — SIGTERM/SIGINT → graceful stop at the next step boundary
                   with an emergency checkpoint and a resumable exit code
                   (``RESUMABLE_EXIT_CODE``) an external supervisor can
                   recognize as "don't burn a restart";
- ``watchdog``   — a monitor thread armed around device-blocking sections
                   (step dispatch/block, checkpoint joins, host collectives)
                   that records a ``watchdog_stall`` with stack dumps after a
                   multiple of the rolling median step time, and aborts the
                   process past a hard timeout so the supervisor restarts a
                   hung job instead of waiting forever.
"""

from pytorch_distributed_training_tpu.faults.inject import (
    REPLICA_CRASH_EXIT_CODE,
    FaultPlan,
    InjectedCrash,
    corrupt_step_dir,
    get_plan,
    set_plan,
)
from pytorch_distributed_training_tpu.faults.preemption import (
    RESUMABLE_EXIT_CODE,
    GracefulShutdown,
    Preempted,
)
from pytorch_distributed_training_tpu.faults.watchdog import (
    WATCHDOG_EXIT_CODE,
    Watchdog,
    get_watchdog,
    set_watchdog,
    watchdog_guard,
)

__all__ = [
    "FaultPlan",
    "InjectedCrash",
    "REPLICA_CRASH_EXIT_CODE",
    "corrupt_step_dir",
    "get_plan",
    "set_plan",
    "GracefulShutdown",
    "Preempted",
    "RESUMABLE_EXIT_CODE",
    "Watchdog",
    "WATCHDOG_EXIT_CODE",
    "get_watchdog",
    "set_watchdog",
    "watchdog_guard",
]
