"""Host-sharded batching: dataset arrays → global device batches.

Replaces the reference's loader stack — ``DataLoader`` + ``accelerator.
prepare`` index sharding (reference test_data_parallelism.py:102-107,
125-127) / ``DistributedSampler`` (test_model_parallelism.py:254-269) — with
a TPU-shaped design:

- each host slices its contiguous shard of the dataset (by process index);
- one seeded global permutation per epoch (identical on every host, so
  global batches are consistent — divergent orders deadlock collectives,
  SURVEY.md §7 hard parts);
- train batches are assembled [grad_accum, local_micro, ...] and placed as
  ONE global sharded array per step via ``make_global_batch`` (micro dim over
  the (data, fsdp) axes), so the whole accumulation window ships to HBM in a
  single transfer and the step consumes it with zero further host traffic;
- eval keeps every example exactly once: the last batch pads to the static
  shape with ``valid=0`` rows (the masked-metric fix for the reference's
  uneven-last-batch gather skew, SURVEY.md §2c-6).
"""

from __future__ import annotations

import math
import time
from typing import Iterator

import jax
import numpy as np
from jax.sharding import Mesh

from pytorch_distributed_training_tpu.comms.ingest import make_global_batch
from pytorch_distributed_training_tpu.comms.mesh import TRAIN_BATCH_PSPEC, dp_degree
from pytorch_distributed_training_tpu.faults.inject import get_plan
from pytorch_distributed_training_tpu.telemetry.registry import get_registry


def resolve_batch_geometry(
    mesh: Mesh,
    *,
    global_batch_size: int,
    grad_accum_steps: int,
    train: bool,
    process_index: int | None = None,
    process_count: int | None = None,
):
    """Validate and derive the per-host batch geometry — THE shared contract
    between the Python and native loader engines (they must be
    interchangeable mid-run, so the rules live in exactly one place).

    Returns (pidx, pcount, micro_global, micro_local, local_per_step).
    """
    pidx = jax.process_index() if process_index is None else process_index
    pcount = jax.process_count() if process_count is None else process_count
    accum = grad_accum_steps if train else 1
    if global_batch_size % (accum * pcount):
        raise ValueError(
            f"global batch {global_batch_size} must divide by "
            f"accum*processes ({accum}*{pcount})"
        )
    dp = dp_degree(mesh)
    micro_global = global_batch_size // accum
    if micro_global % dp:
        raise ValueError(
            f"{'micro' if train else 'eval'} batch {micro_global} must "
            f"divide by data-parallel degree {dp}"
        )
    micro_local = micro_global // pcount
    return pidx, pcount, micro_global, micro_local, global_batch_size // pcount


class ShardedLoader:
    """Iterates global sharded batches from per-host numpy arrays.

    ``data`` holds the FULL dataset on every host (GLUE-scale); each host
    reads only its slice. ``train=True`` yields [accum, micro, ...] batches
    (dropping the ragged tail like the reference's implicit drop behavior for
    step-count consistency); ``train=False`` yields [batch, ...] with a
    ``valid`` mask and keeps every example.
    """

    def __init__(
        self,
        data: dict[str, np.ndarray],
        mesh: Mesh,
        *,
        global_batch_size: int,
        grad_accum_steps: int = 1,
        train: bool = True,
        seed: int = 42,
        process_index: int | None = None,
        process_count: int | None = None,
    ):
        self.data = data
        self.mesh = mesh
        self.train = train
        self.seed = seed
        self.global_batch = global_batch_size
        self.accum = grad_accum_steps if train else 1
        self.n = len(next(iter(data.values())))
        (
            self.pidx,
            self.pcount,
            _micro_global,
            _micro_local,
            self.local_per_step,
        ) = resolve_batch_geometry(
            mesh,
            global_batch_size=global_batch_size,
            grad_accum_steps=grad_accum_steps,
            train=train,
            process_index=process_index,
            process_count=process_count,
        )

    @property
    def steps_per_epoch(self) -> int:
        if self.train:
            return self.n // self.global_batch
        return math.ceil(self.n / self.global_batch)

    def batch_spec(self) -> dict:
        """Abstract (global) shapes/dtypes of one yielded batch — what AOT
        warm-start (train/compile.py) lowers the steps against. Shared
        contract with ``NativeShardedLoader.batch_spec`` (which serves
        int32 regardless of the source dtype)."""
        import jax

        if self.train:
            micro_global = self.global_batch // self.accum
            return {
                k: jax.ShapeDtypeStruct(
                    (self.accum, micro_global, *np.asarray(v).shape[1:]),
                    np.asarray(v).dtype,
                )
                for k, v in self.data.items()
            }
        spec = {
            k: jax.ShapeDtypeStruct(
                (self.global_batch, *np.asarray(v).shape[1:]),
                np.asarray(v).dtype,
            )
            for k, v in self.data.items()
        }
        spec["valid"] = jax.ShapeDtypeStruct((self.global_batch,), np.int32)
        return spec

    def epoch(self, epoch_index: int = 0) -> Iterator[dict]:
        if self.train:
            yield from self._train_epoch(epoch_index)
        else:
            yield from self._eval_epoch()

    # ------------------------------------------------------------- internal

    def _train_epoch(self, epoch_index: int) -> Iterator[dict]:
        # One global permutation, identical on all hosts; each host takes its
        # contiguous slice of every (accum-reshaped) global batch — matching
        # make_array_from_process_local_data's process-contiguous layout.
        rng = np.random.default_rng((self.seed, epoch_index))
        perm = rng.permutation(self.n)
        micro_global = self.global_batch // self.accum
        micro_local = micro_global // self.pcount
        reg = get_registry()
        for step in range(self.steps_per_epoch):
            t0 = time.perf_counter()
            idx = perm[step * self.global_batch : (step + 1) * self.global_batch]
            idx = idx.reshape(self.accum, micro_global)
            local = idx[:, self.pidx * micro_local : (self.pidx + 1) * micro_local]
            batch = {k: v[local] for k, v in self.data.items()}
            t1 = time.perf_counter()
            placed = make_global_batch(self.mesh, batch, pspec=TRAIN_BATCH_PSPEC)
            reg.observe("data/host_assemble_s", t1 - t0)
            reg.observe("data/h2d_place_s", time.perf_counter() - t1)
            # fault injection (PDT_TPU_FAULT=slow_host:2x): stretch THIS
            # host's batch work so straggler detection has a straggler
            get_plan().slow_host_delay(time.perf_counter() - t0)
            yield placed

    def _eval_epoch(self) -> Iterator[dict]:
        per_host = self.local_per_step
        reg = get_registry()
        for step in range(self.steps_per_epoch):
            t0 = time.perf_counter()
            lo = step * self.global_batch
            idx_global = np.arange(lo, min(lo + self.global_batch, self.n))
            valid_n = len(idx_global)
            if valid_n < self.global_batch:  # pad the ragged tail
                # pad with the LAST valid row, not row 0: padding with index
                # 0 re-read row 0 up to global_batch-1 times per epoch; the
                # last row is already hot in cache, and the ``valid`` mask
                # zeroes the pad rows out of every metric either way
                pad = np.full(
                    self.global_batch - valid_n, self.n - 1, np.int64
                )
                idx_global = np.concatenate([idx_global, pad])
            local_sel = idx_global[self.pidx * per_host : (self.pidx + 1) * per_host]
            batch = {k: v[local_sel] for k, v in self.data.items()}
            valid_global = (
                np.arange(self.global_batch) < valid_n
            ).astype(np.int32)
            batch["valid"] = valid_global[
                self.pidx * per_host : (self.pidx + 1) * per_host
            ]
            t1 = time.perf_counter()
            placed = make_global_batch(self.mesh, batch)
            reg.observe("data/eval_assemble_s", t1 - t0)
            reg.observe("data/h2d_place_s", time.perf_counter() - t1)
            get_plan().slow_host_delay(time.perf_counter() - t0)
            yield placed
