"""Batch WordPiece encoding through the native C++ encoder.

Drop-in for ``data.tokenizer.encode_pairs`` over a ``WordPieceTokenizer``:
the whole batch tokenizes in C++ across a thread pool
(native/src/wordpiece.cpp) with one ctypes call — the role HF's Rust "fast"
tokenizers play in the reference's stack (reference
test_data_parallelism.py:69 tokenizes the full dataset up front, which is
exactly the bulk-encode shape this accelerates).

Parity contract: byte-identical to the Python encoder for ASCII text
(pinned in tests/test_native_tokenizer.py). Rows containing non-ASCII bytes
are routed to the Python encoder row-by-row — Python's ``\\w`` is
unicode-aware and the C++ basic tokenizer is byte-level, so diverging
silently on unicode would be worse than a slower path.
"""

from __future__ import annotations

import ctypes
import os

import numpy as np

from pytorch_distributed_training_tpu.data.tokenizer import (
    WordPieceTokenizer,
    assemble_pair_row,
)
from pytorch_distributed_training_tpu.native import load_wordpiece_lib


class NativeWordPieceEncoder:
    """Owns a C++ vocab handle; encodes pair batches to fixed-length arrays."""

    def __init__(self, vocab_path: str, *, lower: bool = False,
                 n_threads: int | None = None):
        lib = load_wordpiece_lib()
        if lib is None:
            raise RuntimeError(
                "native wordpiece encoder unavailable (no C++ toolchain?) — "
                "use data.tokenizer.encode_pairs"
            )
        self._lib = lib
        with open(vocab_path, "rb") as f:
            blob = f.read()
        self._h = lib.wp_create(blob, len(blob), int(lower))
        self.n_threads = n_threads or min(8, os.cpu_count() or 1)
        self.pad_id = lib.wp_special_id(self._h, 0)
        self.unk_id = lib.wp_special_id(self._h, 1)
        self.cls_id = lib.wp_special_id(self._h, 2)
        self.sep_id = lib.wp_special_id(self._h, 3)
        # lazy Python twin for non-ASCII rows
        self._vocab_path = vocab_path
        self._lower = lower
        self._py: WordPieceTokenizer | None = None

    def _python_tok(self) -> WordPieceTokenizer:
        if self._py is None:
            self._py = WordPieceTokenizer(self._vocab_path, lower=self._lower)
        return self._py

    @staticmethod
    def _pack(texts: list[bytes]):
        off = np.zeros(len(texts) + 1, np.int64)
        for i, t in enumerate(texts):
            off[i + 1] = off[i] + len(t)
        return b"".join(texts), off

    def encode_pairs(self, texts_a, texts_b, max_length: int = 128):
        """Same output contract as ``data.tokenizer.encode_pairs``."""
        n = len(texts_a)
        # Per-row specials rule, matching the Python twin: a row needs
        # [CLS]+[SEP] (2) plus a second [SEP] only if its b tokenizes
        # non-empty (any non-whitespace char yields >= 1 token via [UNK]
        # fallback, so a strip() check is exact). The twin raises
        # IndexError for rows that cannot fit; we raise up front.
        if max_length < 2 or (
            texts_b is not None
            and max_length < 3
            and any(t.strip() for t in texts_b)
        ):
            raise ValueError(
                f"max_length={max_length} cannot hold a row's "
                "special tokens"
            )
        # C++ writes only the used prefix of each row; padding comes from
        # this pre-fill, so it must be pad_id (not 0) to match the Python
        # twin byte-for-byte on vocabs where [PAD] != 0.
        ids = np.full((n, max_length), self.pad_id, np.int32)
        types = np.zeros((n, max_length), np.int32)
        mask = np.zeros((n, max_length), np.int32)
        a_bytes = [t.encode("utf-8") for t in texts_a]
        b_bytes = (
            [t.encode("utf-8") for t in texts_b]
            if texts_b is not None
            else None
        )
        non_ascii = [
            i for i in range(n)
            if not texts_a[i].isascii()
            or (texts_b is not None and not texts_b[i].isascii())
        ]
        a_blob, a_off = self._pack(a_bytes)
        if b_bytes is not None:
            b_blob, b_off = self._pack(b_bytes)
            b_ptr = b_blob
            b_off_ptr = b_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        else:
            b_ptr = None
            b_off_ptr = None
        self._lib.wp_encode_pairs(
            self._h,
            a_blob, a_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            b_ptr, b_off_ptr,
            n, max_length, self.n_threads,
            ids.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            types.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            mask.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        )
        for i in non_ascii:  # unicode rows: Python semantics, overwrite
            tok = self._python_tok()
            a_ids = tok.text_ids(texts_a[i])
            b_ids = tok.text_ids(texts_b[i]) if texts_b is not None else []
            row_ids, row_types = assemble_pair_row(
                a_ids, b_ids, max_length, cls_id=tok.cls_id, sep_id=tok.sep_id
            )
            ids[i] = self.pad_id
            types[i] = 0
            mask[i] = 0
            ids[i, : len(row_ids)] = row_ids
            types[i, : len(row_ids)] = row_types
            mask[i, : len(row_ids)] = 1
        return {
            "input_ids": ids,
            "attention_mask": mask,
            "token_type_ids": types,
        }

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.wp_destroy(self._h)
            self._h = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass
