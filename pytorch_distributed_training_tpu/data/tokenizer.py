"""In-repo tokenization: WordPiece (when a vocab is available) with a
deterministic hashing fallback for fully-offline environments.

The reference delegates tokenization to
``AutoTokenizer.from_pretrained("bert-large-cased")`` and encodes sentence
pairs with truncation to model max length (reference
test_data_parallelism.py:69,73-76). This framework owns a WordPiece encoder
with the same pair-encoding contract ([CLS] a [SEP] b [SEP], token_type 0/1,
fixed-length padding — the reference's own TPU branch pads to max_length=128,
:96-98). When no ``vocab.txt`` exists (this image has no HF cache and no
egress), ``HashTokenizer`` maps whitespace/punct-split words onto stable ids
so the full text→arrays pipeline stays exercisable end-to-end.
"""

from __future__ import annotations

import hashlib
import re
from typing import Sequence

import numpy as np

PAD_ID = 0
UNK_ID = 100
CLS_ID = 101
SEP_ID = 102

_WORD_RE = re.compile(r"\w+|[^\w\s]")


def basic_tokenize(text: str, lower: bool = False) -> list[str]:
    if lower:
        text = text.lower()
    return _WORD_RE.findall(text)


class WordPieceTokenizer:
    """Greedy longest-match-first WordPiece over a BERT vocab file."""

    def __init__(self, vocab_path: str, lower: bool = False):
        self.vocab: dict[str, int] = {}
        with open(vocab_path, encoding="utf-8") as f:
            for i, line in enumerate(f):
                self.vocab[line.rstrip("\n")] = i
        self.lower = lower
        self.pad_id = self.vocab.get("[PAD]", PAD_ID)
        self.unk_id = self.vocab.get("[UNK]", UNK_ID)
        self.cls_id = self.vocab.get("[CLS]", CLS_ID)
        self.sep_id = self.vocab.get("[SEP]", SEP_ID)

    def word_ids(self, word: str) -> list[int]:
        ids, start = [], 0
        while start < len(word):
            end = len(word)
            piece_id = None
            while end > start:
                piece = word[start:end]
                if start > 0:
                    piece = "##" + piece
                if piece in self.vocab:
                    piece_id = self.vocab[piece]
                    break
                end -= 1
            if piece_id is None:
                return [self.unk_id]
            ids.append(piece_id)
            start = end
        return ids

    def text_ids(self, text: str) -> list[int]:
        out: list[int] = []
        for w in basic_tokenize(text, self.lower):
            out.extend(self.word_ids(w))
        return out


class HashTokenizer:
    """Deterministic word→id hashing into [first_regular_id, vocab_size).

    Not linguistically meaningful, but stable across hosts/runs (seeded by
    the word bytes only), which is what the offline pipeline and tests need.
    """

    def __init__(self, vocab_size: int = 28996, lower: bool = False):
        self.vocab_size = vocab_size
        self.lower = lower
        self.pad_id, self.unk_id = PAD_ID, UNK_ID
        self.cls_id, self.sep_id = CLS_ID, SEP_ID
        self._first = SEP_ID + 1

    def text_ids(self, text: str) -> list[int]:
        out = []
        for w in basic_tokenize(text, self.lower):
            h = int.from_bytes(hashlib.sha1(w.encode()).digest()[:4], "little")
            out.append(self._first + h % (self.vocab_size - self._first))
        return out


def assemble_pair_row(
    a: list[int],
    b: list[int],
    max_length: int,
    *,
    cls_id: int = CLS_ID,
    sep_id: int = SEP_ID,
) -> tuple[list[int], list[int]]:
    """The single pair-encoding contract: [CLS] a [SEP] (b [SEP]), truncated
    longest-first to fit ``max_length``. Returns (ids, token_types). Shared
    by text encoding AND the synthetic generator so both always produce the
    same tensor layout."""
    specials = 2 + (1 if b else 0)
    a, b = list(a), list(b)
    while len(a) + len(b) > max_length - specials:
        if len(a) >= len(b):
            a.pop()
        else:
            b.pop()
    ids = [cls_id] + a + [sep_id]
    types = [0] * len(ids)
    if b:
        ids += b + [sep_id]
        types += [1] * (len(b) + 1)
    return ids, types


def encode_pairs(
    tokenizer,
    texts_a: Sequence[str],
    texts_b: Sequence[str] | None,
    max_length: int = 128,
) -> dict[str, np.ndarray]:
    """[CLS] a [SEP] (b [SEP]) encoding, truncated + padded to max_length.

    Fixed-length by construction: TPU static shapes (the design the
    reference's TPU collate branch gestures at, test_data_parallelism.py:
    96-98) — one compiled program for every batch.
    """
    n = len(texts_a)
    input_ids = np.full((n, max_length), tokenizer.pad_id, np.int32)
    token_type = np.zeros((n, max_length), np.int32)
    mask = np.zeros((n, max_length), np.int32)
    for i in range(n):
        a = tokenizer.text_ids(texts_a[i])
        b = tokenizer.text_ids(texts_b[i]) if texts_b is not None else []
        ids, types = assemble_pair_row(
            a, b, max_length, cls_id=tokenizer.cls_id, sep_id=tokenizer.sep_id
        )
        input_ids[i, : len(ids)] = ids
        token_type[i, : len(ids)] = types
        mask[i, : len(ids)] = 1
    return {
        "input_ids": input_ids,
        "attention_mask": mask,
        "token_type_ids": token_type,
    }
