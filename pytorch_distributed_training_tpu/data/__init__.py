from pytorch_distributed_training_tpu.data.pipeline import ShardedLoader
from pytorch_distributed_training_tpu.data.prefetch import (
    PrefetchingIterator,
    PrefetchingLoader,
)
from pytorch_distributed_training_tpu.data.glue import load_task_arrays
from pytorch_distributed_training_tpu.data.bpe import (
    ByteLevelBPETokenizer,
    ByteTokenizer,
    encode_lm_rows,
)

__all__ = [
    "ShardedLoader",
    "PrefetchingIterator",
    "PrefetchingLoader",
    "load_task_arrays",
    "ByteLevelBPETokenizer",
    "ByteTokenizer",
    "encode_lm_rows",
]
