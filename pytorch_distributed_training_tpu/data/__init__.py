from pytorch_distributed_training_tpu.data.pipeline import ShardedLoader
from pytorch_distributed_training_tpu.data.glue import load_task_arrays

__all__ = ["ShardedLoader", "load_task_arrays"]
