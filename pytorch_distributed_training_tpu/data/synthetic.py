"""Deterministic synthetic GLUE-shaped tasks for offline/test runs.

This zero-egress image cannot reach the HF hub, so the framework ships a
synthetic sentence-pair classification task with the same tensor contract and
split sizes as GLUE/MRPC (3668 train / 408 validation — the uneven eval split
that forces pad-and-mask handling, SURVEY.md §7 hard parts). The task is
*learnable* (label = whether segment B is a noised copy of segment A) so
convergence tests and benchmarks exercise real learning dynamics, mirroring
the reference's only verification mode — watching the eval metric rise
(reference test_data_parallelism.py:164-166).
"""

from __future__ import annotations

import numpy as np

from pytorch_distributed_training_tpu.data.tokenizer import (
    PAD_ID,
    SEP_ID,
    assemble_pair_row,
)

MRPC_TRAIN_SIZE = 3668
MRPC_EVAL_SIZE = 408


def synthetic_lm_task(
    n_examples: int,
    *,
    max_length: int = 128,
    vocab_size: int = 50257,
    seed: int = 42,
    order: int = 1,
    row_seed: int | None = None,
) -> dict[str, np.ndarray]:
    """Learnable causal-LM corpus: a fixed random order-``order`` Markov
    chain over a small token alphabet, embedded in the full vocab.

    A model that learns the transition table drives next-token loss well
    below the uniform-over-alphabet floor, so LM convergence tests and
    benchmarks see real learning dynamics (the LM analogue of the
    paraphrase-shaped task above). Dense rows — no padding — matching
    packed-sequence LM training.

    The transition table depends only on ``seed``; ``row_seed`` (when given)
    seeds an independent stream for the row sampling, so disjoint splits of
    the same chain can each be generated directly at their own size.
    """
    rng = np.random.default_rng(seed)
    alphabet = 256  # tokens 2..258: leave 0/1 for pad/eos conventions
    # sparse-ish transition table: each context strongly prefers 4 tokens
    table = rng.dirichlet(np.full(4, 0.5), size=alphabet**order)
    cum = table.cumsum(axis=1)
    prefs = rng.integers(0, alphabet, size=(alphabet**order, 4))
    if row_seed is not None:
        rng = np.random.default_rng(row_seed)

    ids = np.empty((n_examples, max_length), np.int64)
    ids[:, :order] = rng.integers(0, alphabet, size=(n_examples, order))
    for t in range(order, max_length):
        ctx = ids[:, t - order]
        for k in range(1, order):
            ctx = ctx * alphabet + ids[:, t - order + k]
        u = rng.random(n_examples)
        choice = (u[:, None] > cum[ctx]).sum(axis=1).clip(0, 3)
        ids[:, t] = prefs[ctx, choice]
    ids = (ids + 2) % vocab_size
    return {
        "input_ids": ids.astype(np.int32),
        "attention_mask": np.ones((n_examples, max_length), np.int32),
    }


MARKER_BAND = 64  # per-class marker sub-vocab width for multi-class tasks


def synthetic_pair_task(
    n_examples: int,
    *,
    max_length: int = 128,
    vocab_size: int = 28996,
    num_labels: int = 2,
    seed: int = 42,
    seg_len_range: tuple[int, int] = (8, 40),
) -> dict[str, np.ndarray]:
    """Generate a paraphrase-detection-shaped dataset.

    Binary (MRPC-shaped): label 1 = segment B is segment A with ~15% token
    noise (a "paraphrase"), label 0 = unrelated random tokens. This branch
    is byte-stable across rounds — the bert-large recipe artifacts
    (HISTORY_bert_large_recipe*) compare runs of exactly this stream.

    Multi-class (MNLI-shaped): every class is a noised copy whose noise
    RATE grades with the class (15/30/45%…) and whose replacement tokens
    come from a class-specific marker band at the bottom of the vocab
    (segment A and the un-noised tokens draw from above the bands). The
    marker cue is deliberately TYPE-ID-FREE: the round-4 bisect
    (NOTES.md) proved the old graded-noise-only form was unlearnable from
    random init for models with a single-row token-type table (RoBERTa's
    HF-parity layout) — token-type embeddings tag every token with its
    segment, so BERT could learn "compare A to B" immediately while
    RoBERTa's only segment signal (the SEP boundary) was too weak to get
    the discrimination off the ground in ~100 updates. Marker identity is
    readable by ANY trunk from token embeddings alone, so the MNLI-recipe
    runs (BASELINE.json configs[3]) show a metric that moves — the
    reference's own verification style (test_data_parallelism.py:164-166).
    """
    rng = np.random.default_rng(seed)
    first = SEP_ID + 1
    input_ids = np.full((n_examples, max_length), PAD_ID, np.int32)
    token_type = np.zeros((n_examples, max_length), np.int32)
    mask = np.zeros((n_examples, max_length), np.int32)
    labels = rng.integers(0, num_labels, n_examples).astype(np.int32)
    # multi-class: reserve [first, first + num_labels*MARKER_BAND) for the
    # per-class marker bands; content tokens start above them
    content_lo = (
        first + num_labels * MARKER_BAND if num_labels > 2 else first
    )
    if content_lo >= vocab_size:
        raise ValueError(
            f"vocab_size {vocab_size} too small for {num_labels} marker "
            f"bands of {MARKER_BAND} tokens (content range starts at "
            f"{content_lo})"
        )

    for i in range(n_examples):
        la = int(rng.integers(*seg_len_range))
        lb = int(rng.integers(*seg_len_range))
        label = labels[i]
        if num_labels > 2:
            a = rng.integers(content_lo, vocab_size, la)
            noise = 0.15 * (label + 1)
            b = a.copy()
            flip = rng.random(la) < noise
            band_lo = first + int(label) * MARKER_BAND
            b[flip] = rng.integers(band_lo, band_lo + MARKER_BAND, flip.sum())
            lb = la
        else:
            a = rng.integers(first, vocab_size, la)
            if label == num_labels - 1:
                # unrelated
                b = rng.integers(first, vocab_size, lb)
            else:
                # copy of A with ~15% noise (the "paraphrase")
                noise = 0.15 * (label + 1)
                b = a.copy()
                flip = rng.random(la) < noise
                b[flip] = rng.integers(first, vocab_size, flip.sum())
                lb = la
        ids, types = assemble_pair_row(
            a[:la].tolist(), b[:lb].tolist(), max_length
        )
        input_ids[i, : len(ids)] = ids
        token_type[i, : len(ids)] = types
        mask[i, : len(ids)] = 1

    # For binary tasks flip so label 1 == "paraphrase" (MRPC convention);
    # generated above: label 0 = clean copy, last label = unrelated.
    if num_labels == 2:
        labels = 1 - labels
    return {
        "input_ids": input_ids,
        "attention_mask": mask,
        "token_type_ids": token_type,
        "labels": labels,
    }
