"""Latency-hiding prefetch over either loader engine.

The loaders (``pipeline.ShardedLoader`` and the C++-backed
``NativeShardedLoader``) run host assembly + ``make_global_batch`` placement
synchronously inside the Trainer's step loop: the accelerator sits idle for
the whole assemble→place window between steps, and the H2D transfer for
batch ``i+1`` cannot start until step ``i``'s dispatch returns. This module
moves that work onto a background thread with a bounded depth-``k`` queue:
while step ``i`` computes, batches ``i+1..i+k`` are assembled and placed
(JAX dispatches their H2D transfers asynchronously), so in steady state the
consumer's wait is a queue pop, not a full batch build — the role the
reference delegated to PyTorch ``DataLoader`` workers
(test_data_parallelism.py:102-107), owned TPU-natively here.

Contract:

- **Ordering is bitwise-identical** to the unwrapped loader: one worker,
  one FIFO queue — the consumer sees exactly the epoch stream the inner
  engine produced (mid-epoch resume's skip-first-N batches keeps working).
- **Exceptions propagate**: a worker-side error is re-raised at the
  consumer's next ``__next__`` call, not swallowed in a dead thread.
- **Shutdown is clean**: ``close()`` (or abandoning the iterator) stops the
  worker, drains queued batches, joins the thread and closes the inner
  generator so engine resources (native ring slots) are released — the
  Trainer's ``finally`` path (preemption exit 75, injected crashes, watchdog
  aborts) closes through the same API it uses for bare loaders.

Telemetry (per consumer pop, into the default registry):

- ``data/prefetch_occupancy`` — ready batches in the queue at pop time
  (depth = fully hidden; 0 = the consumer is about to stall);
- ``data/prefetch_stall_s`` + counter ``data/prefetch_stalls`` — time spent
  waiting on an empty queue (the producer fell behind the device).
"""

from __future__ import annotations

import queue
import threading
from time import perf_counter
from typing import Iterator

from pytorch_distributed_training_tpu.analysis import concurrency
from pytorch_distributed_training_tpu.telemetry.registry import get_registry

_ITEM, _DONE, _ERROR = 0, 1, 2


class PrefetchingIterator:
    """Bounded background iteration over one epoch's batch stream."""

    def __init__(self, source: Iterator, depth: int, *, name: str = "batch"):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.depth = depth
        self._src = source
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._finished = False  # consumer saw _DONE/_ERROR
        # close() races itself: the Trainer's finally and __del__ (GC, any
        # thread) may both tear down — the lock makes the drain+join run
        # exactly once (instrumented; analysis/concurrency)
        self._close_lock = concurrency.lock("data.prefetch.close")
        self._closed = False
        self.last_occupancy = 0
        self.last_wait_s = 0.0
        self._thread = threading.Thread(
            target=self._work, name=f"prefetch-{name}", daemon=True
        )
        self._thread.start()

    # ------------------------------------------------------------- producer

    def _put(self, msg) -> bool:
        """Enqueue, staying responsive to close(); False = told to stop."""
        while not self._stop.is_set():
            try:
                self._q.put(msg, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _work(self) -> None:
        try:
            for item in self._src:
                if not self._put((_ITEM, item)):
                    return
            self._put((_DONE, None))
        except BaseException as e:  # noqa: BLE001 — re-raised at the consumer
            self._put((_ERROR, e))
        finally:
            # the generator's finally (native ring-slot release, telemetry)
            # runs HERE, on the thread that advanced it
            close = getattr(self._src, "close", None)
            if close is not None:
                try:
                    close()
                except Exception:
                    pass

    # ------------------------------------------------------------- consumer

    def __iter__(self):
        return self

    def __next__(self):
        if self._finished or self._closed:
            raise StopIteration
        reg = get_registry()
        occupancy = self._q.qsize()
        t0 = perf_counter()
        while True:
            try:
                kind, val = self._q.get(timeout=0.1)
                break
            except queue.Empty:
                if self._closed or not self._thread.is_alive():
                    # a worker that died without posting a sentinel (killed
                    # interpreter teardown) must not hang the consumer
                    if self._q.qsize():
                        continue
                    raise StopIteration from None
        if kind == _ITEM:
            wait = perf_counter() - t0
            self.last_occupancy = occupancy
            self.last_wait_s = wait
            reg.observe("data/prefetch_occupancy", float(occupancy))
            if occupancy == 0:
                reg.inc("data/prefetch_stalls")
                reg.observe("data/prefetch_stall_s", wait)
            return val
        self._finished = True
        if kind == _ERROR:
            raise val
        raise StopIteration

    # ------------------------------------------------------------ lifecycle

    def close(self) -> None:
        """Stop the worker, drain the queue, join — idempotent."""
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        self._stop.set()
        # unblock a producer waiting on a full queue
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5.0)

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass


class PrefetchingLoader:
    """Drop-in wrapper giving any loader engine a prefetched ``epoch()``.

    Proxies the shared loader surface (``steps_per_epoch``, ``batch_spec``,
    ``close``); each ``epoch(i)`` returns a ``PrefetchingIterator`` over the
    inner engine's stream for that epoch. Starting a new epoch retires the
    previous epoch's iterator (a half-consumed one left by an exception
    path must not keep its worker alive).
    """

    def __init__(self, inner, *, depth: int = 2):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.inner = inner
        self.depth = depth
        self._active: PrefetchingIterator | None = None

    @property
    def steps_per_epoch(self) -> int:
        return self.inner.steps_per_epoch

    def batch_spec(self):
        return self.inner.batch_spec()

    @property
    def last_occupancy(self) -> int:
        return self._active.last_occupancy if self._active else 0

    @property
    def last_wait_s(self) -> float:
        return self._active.last_wait_s if self._active else 0.0

    def epoch(self, epoch_index: int = 0) -> PrefetchingIterator:
        self._retire()
        self._active = PrefetchingIterator(
            self.inner.epoch(epoch_index), self.depth,
            name=f"epoch{epoch_index}",
        )
        return self._active

    def _retire(self) -> None:
        if self._active is not None:
            self._active.close()
            self._active = None

    def close(self) -> None:
        self._retire()
        close = getattr(self.inner, "close", None)
        if close is not None:
            close()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
