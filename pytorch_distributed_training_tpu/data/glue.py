"""GLUE task loading → fixed-shape numpy arrays (with offline fallback).

Capability twin of the reference's data pipeline: ``load_dataset("glue",
"mrpc")`` → tokenize pairs → drop text columns → rename label→labels
(reference test_data_parallelism.py:69-87; test_model_parallelism.py:
194-216), but producing fixed-length arrays once up front instead of
re-padding every batch in a collate_fn (:95-99) — on TPU one shape means one
compiled program.

Tasks: MRPC (the reference's task), MNLI (driver config, BASELINE.json
configs[3]; both matched and mismatched validation splits), SST-2
(single-sentence), and QNLI. When the HF hub/cache is unreachable (this
image), falls back to the synthetic pair task with MRPC-shaped splits so
every entry point still runs end-to-end.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from pytorch_distributed_training_tpu.data import synthetic
from pytorch_distributed_training_tpu.data.tokenizer import (
    HashTokenizer,
    WordPieceTokenizer,
    encode_pairs,
)
from pytorch_distributed_training_tpu.utils.logging import log0

TASKS = {
    # task: (dataset args, text field a, text field b, num_labels)
    "mrpc": (("glue", "mrpc"), "sentence1", "sentence2", 2),
    "mnli": (("glue", "mnli"), "premise", "hypothesis", 3),
    # single-sentence task: field b is None (encoders emit [CLS] a [SEP])
    "sst2": (("glue", "sst2"), "sentence", None, 2),
    "qnli": (("glue", "qnli"), "question", "sentence", 2),
    "synthetic": (None, None, None, 2),
    # causal-LM corpus (synthetic Markov chain; BASELINE.json configs[4])
    "lm": (None, None, None, 0),
}


def eval_splits(task: str) -> list[tuple[str, str]]:
    """(metric name suffix, split) pairs a trainer should evaluate.

    MNLI's standard eval is BOTH validation splits — matched (same genres as
    train) and mismatched (held-out genres); reference anchor
    test_data_parallelism.py:70 (the task arg the metric follows). Every
    other task has the single ``"validation"`` split; its suffix is empty so
    metric keys stay unprefixed ("accuracy", not "accuracy_validation").
    """
    if task == "mnli":
        return [("matched", "validation"), ("mismatched", "validation_mismatched")]
    return [("", "validation")]


def make_tokenizer(vocab_path: Optional[str] = None, vocab_size: int = 28996):
    if vocab_path:
        return WordPieceTokenizer(vocab_path)
    return HashTokenizer(vocab_size=vocab_size)


def resolve_task(task: str) -> str:
    """Resolve ``"auto"`` to a concrete task ONCE (callers loading several
    splits must not re-resolve per split — a flaky hub could silently hand
    them different tasks for train vs validation)."""
    if task != "auto":
        return task
    try:
        import datasets

        datasets.load_dataset("glue", "mrpc", split="train[:1]")
        return "mrpc"
    except Exception as e:  # hub unreachable / no cache
        log0(f"glue/mrpc unavailable ({type(e).__name__}); using synthetic task")
        return "synthetic"


def load_task_arrays(
    task: str,
    split: str,
    *,
    max_length: int = 128,
    vocab_path: Optional[str] = None,
    vocab_size: int = 28996,
    seed: int = 42,
    synthetic_sizes: tuple[int, int] = (
        synthetic.MRPC_TRAIN_SIZE,
        synthetic.MRPC_EVAL_SIZE,
    ),
) -> tuple[dict[str, np.ndarray], int]:
    """Return ({input_ids, attention_mask, token_type_ids, labels}, num_labels).

    ``split`` is "train", "validation", or (MNLI only)
    "validation_mismatched"; "validation" maps to MNLI's
    ``validation_matched``. ``task="auto"`` tries MRPC and falls back to
    synthetic when the hub/cache is unavailable.
    """
    if task == "auto":
        task = resolve_task(task)

    if task == "synthetic":
        n_train, n_eval = synthetic_sizes
        n = n_train if split == "train" else n_eval
        data = synthetic.synthetic_pair_task(
            n, max_length=max_length, vocab_size=vocab_size,
            seed=seed if split == "train" else seed + 1,
        )
        return data, 2

    if task == "lm":
        # Both splits sample the SAME chain (transition table from ``seed``)
        # via independent row streams: eval measures how well the model
        # learned the shared table on rows it never saw, and each split is
        # generated directly at its own size (no discarded corpus half).
        n_train, n_eval = synthetic_sizes
        n = n_train if split == "train" else n_eval
        data = synthetic.synthetic_lm_task(
            n, max_length=max_length, vocab_size=vocab_size,
            seed=seed, row_seed=seed + (1 if split == "train" else 2),
        )
        return data, 0

    if task not in TASKS:
        raise KeyError(f"unknown task {task!r}; have {sorted(TASKS)}")
    ds_args, field_a, field_b, num_labels = TASKS[task]
    import datasets  # deferred: optional dependency

    hub_split = split
    if task == "mnli" and split == "validation":
        hub_split = "validation_matched"
    if split == "validation_mismatched" and task != "mnli":
        raise ValueError(f"task {task!r} has no mismatched validation split")
    try:
        ds = datasets.load_dataset(*ds_args, split=hub_split)
    except (ConnectionError, TimeoutError, OSError) as e:
        # Connectivity/cache failures only (this zero-egress image raises
        # ConnectionError) — anything else (bad split, broken install) must
        # propagate: an explicitly requested task silently swapping to
        # synthetic data would report metrics that look real but aren't.
        log0(
            f"glue/{task} unavailable ({type(e).__name__}); falling back to "
            f"the synthetic pair task with num_labels={num_labels}"
        )
        n_train, n_eval = synthetic_sizes
        n = n_train if split == "train" else n_eval
        # distinct seed per split: train / validation / validation_mismatched
        # must be three different samples of the synthetic task (any other
        # split string keeps the old eval-seed behavior, matching the hub
        # path's tolerance of arbitrary split names)
        split_seed = {
            "train": seed,
            "validation": seed + 1,
            "validation_mismatched": seed + 2,
        }.get(split, seed + 1)
        data = synthetic.synthetic_pair_task(
            n, max_length=max_length, vocab_size=vocab_size,
            num_labels=num_labels,
            seed=split_seed,
        )
        return data, num_labels
    arrays = None
    if vocab_path:
        # bulk-encode the split in C++ when the toolchain is available (the
        # HF-fast-tokenizer role; byte-identical to the Python encoder on
        # ASCII, unicode rows routed to Python — data/native_tokenizer.py)
        from pytorch_distributed_training_tpu.native import load_wordpiece_lib

        if load_wordpiece_lib() is not None:
            from pytorch_distributed_training_tpu.data.native_tokenizer import (
                NativeWordPieceEncoder,
            )

            enc = NativeWordPieceEncoder(vocab_path)
            try:
                arrays = enc.encode_pairs(
                    list(ds[field_a]),
                    list(ds[field_b]) if field_b else None,
                    max_length=max_length,
                )
            finally:
                enc.close()
            log0(f"glue/{task} {split}: native C++ WordPiece encode")
    if arrays is None:
        tokenizer = make_tokenizer(vocab_path, vocab_size)
        arrays = encode_pairs(
            tokenizer, ds[field_a], ds[field_b] if field_b else None,
            max_length=max_length,
        )
    arrays["labels"] = np.asarray(ds["label"], np.int32)
    return arrays, num_labels
