"""In-repo byte-level BPE tokenizer (the GPT-2 family's encoding).

The reference repo never tokenizes for a decoder — it only fine-tunes BERT
via ``AutoTokenizer`` (reference test_data_parallelism.py:69). This
framework's GPT-2 family (models/gpt2.py, BASELINE.json configs[4]) gets a
native encoder so the LM pipeline works without a transformers runtime
dependency: classic byte-level BPE — GPT-2's byte→unicode alphabet, its
pre-tokenization regex, greedy lowest-rank merges — loading the standard
``encoder.json`` + ``merges.txt`` (``vocab.json`` accepted too; same
format). Parity with ``transformers.GPT2Tokenizer`` over the same files is
pinned in tests/test_bpe.py.

Offline fallback (this image has no HF cache and zero egress): when no
vocab/merges files exist, ``ByteTokenizer`` maps raw UTF-8 bytes to ids
0..255 — not the GPT-2 segmentation, but a real, lossless, deterministic
byte-level encoding that keeps the text→arrays LM pipeline exercisable
end-to-end (the same role HashTokenizer plays for the encoder family,
data/tokenizer.py).
"""

from __future__ import annotations

import json
from functools import lru_cache
from typing import Iterable

import numpy as np

try:  # exact \p{L}/\p{N} classes need the `regex` module (baked in)
    import regex as _re

    _HAS_REGEX = True
except ImportError:  # pragma: no cover - regex is in the image
    import re as _re

    _HAS_REGEX = False

# GPT-2's pre-tokenization pattern (contractions, space-prefixed words /
# numbers / punctuation runs, whitespace).
_GPT2_PAT_P = r"""'s|'t|'re|'ve|'m|'ll|'d| ?\p{L}+| ?\p{N}+| ?[^\s\p{L}\p{N}]+|\s+(?!\S)|\s+"""
# re-compatible approximation when `regex` is unavailable: [^\W\d_]
# approximates \p{L} (unicode letters) and \d approximates \p{N}.
_GPT2_PAT_RE = r"""'s|'t|'re|'ve|'m|'ll|'d| ?[^\W\d_]+| ?\d+| ?(?:[^\s\w]|_)+|\s+(?!\S)|\s+"""

_PRETOK = _re.compile(_GPT2_PAT_P if _HAS_REGEX else _GPT2_PAT_RE)


@lru_cache(maxsize=1)
def bytes_to_unicode() -> dict[int, str]:
    """GPT-2's reversible byte→printable-unicode alphabet: the 188 printable
    latin-1 bytes map to themselves; the rest shift into 256+n."""
    bs = (
        list(range(ord("!"), ord("~") + 1))
        + list(range(ord("\xa1"), ord("\xac") + 1))
        + list(range(ord("\xae"), ord("\xff") + 1))
    )
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


def _get_pairs(word: tuple[str, ...]) -> set[tuple[str, str]]:
    return {(word[i], word[i + 1]) for i in range(len(word) - 1)}


class ByteLevelBPETokenizer:
    """GPT-2 byte-level BPE over standard ``encoder.json``/``merges.txt``."""

    def __init__(self, vocab_path: str, merges_path: str):
        with open(vocab_path, encoding="utf-8") as f:
            self.encoder: dict[str, int] = json.load(f)
        self.decoder = {v: k for k, v in self.encoder.items()}
        merges: list[tuple[str, str]] = []
        with open(merges_path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith("#version"):
                    continue
                a, _, b = line.partition(" ")
                merges.append((a, b))
        self.bpe_ranks = {pair: i for i, pair in enumerate(merges)}
        self.byte_encoder = bytes_to_unicode()
        self.byte_decoder = {v: k for k, v in self.byte_encoder.items()}
        self._cache: dict[str, tuple[str, ...]] = {}
        # GPT-2 conventions: <|endoftext|> is bos/eos/pad in one
        self.eot_id = self.encoder.get("<|endoftext|>", 0)
        self.pad_id = self.eot_id
        self.vocab_size = len(self.encoder)

    def _bpe(self, token: str) -> tuple[str, ...]:
        cached = self._cache.get(token)
        if cached is not None:
            return cached
        word = tuple(token)
        pairs = _get_pairs(word)
        while pairs:
            best = min(pairs, key=lambda p: self.bpe_ranks.get(p, float("inf")))
            if best not in self.bpe_ranks:
                break
            a, b = best
            out: list[str] = []
            i = 0
            while i < len(word):
                try:
                    j = word.index(a, i)
                except ValueError:
                    out.extend(word[i:])
                    break
                out.extend(word[i:j])
                if j < len(word) - 1 and word[j + 1] == b:
                    out.append(a + b)
                    i = j + 2
                else:
                    out.append(word[j])
                    i = j + 1
            word = tuple(out)
            if len(word) == 1:
                break
            pairs = _get_pairs(word)
        self._cache[token] = word
        return word

    def text_ids(self, text: str) -> list[int]:
        ids: list[int] = []
        for tok in _PRETOK.findall(text):
            mapped = "".join(self.byte_encoder[b] for b in tok.encode("utf-8"))
            ids.extend(self.encoder[p] for p in self._bpe(mapped))
        return ids

    def decode(self, ids: Iterable[int]) -> str:
        # unknown ids (e.g. a model vocab larger than the tokenizer's)
        # become U+FFFD instead of crashing after generation completed
        text = "".join(self.decoder.get(int(i), "\ufffd") for i in ids)
        return bytes(
            self.byte_decoder.get(c, ord("?")) for c in text
        ).decode("utf-8", errors="replace")


class ByteTokenizer:
    """Offline fallback: raw UTF-8 bytes → ids 0..255 (lossless, stable)."""

    vocab_size = 256
    eot_id = 0
    pad_id = 0

    def text_ids(self, text: str) -> list[int]:
        return list(text.encode("utf-8"))

    def decode(self, ids: Iterable[int]) -> str:
        return bytes(int(i) % 256 for i in ids).decode("utf-8", errors="replace")


def encode_lm_rows(
    tokenizer,
    texts: list[str],
    max_length: int,
    *,
    append_eot: bool = True,
) -> dict[str, np.ndarray]:
    """Document-per-row causal-LM encoding: ids truncated/padded to
    ``max_length`` with an attention mask (the LM objective masks loss on
    pad positions via the mask — train/step.py ``_lm_shift_and_mask``)."""
    n = len(texts)
    input_ids = np.full((n, max_length), tokenizer.pad_id, np.int32)
    mask = np.zeros((n, max_length), np.int32)
    for i, t in enumerate(texts):
        ids = tokenizer.text_ids(t)
        if append_eot and getattr(tokenizer, "eot_id", None) is not None:
            ids = ids + [tokenizer.eot_id]
        ids = ids[:max_length]
        input_ids[i, : len(ids)] = ids
        mask[i, : len(ids)] = 1
    return {"input_ids": input_ids, "attention_mask": mask}
