"""Prefetching loader backed by the native C++ batch assembler.

Same iteration contract as ``pipeline.ShardedLoader``: train mode yields
[grad_accum, local_micro, ...] batches placed as global sharded arrays;
eval mode (``train=False``) yields [local_batch, ...] with a ``valid``
mask, every example exactly once, ragged tail padded. The difference is
WHO assembles: a C++ worker pool (native/src/batcher.cpp) gathers rows
into a ring of reusable buffers ahead of consumption, overlapping host
assembly with device compute — the role torch's DataLoader workers play
in the reference's stack (reference test_data_parallelism.py:102-107).

Cross-host consistency AND engine interchangeability: the train epoch
permutation is computed here with ``np.random.default_rng((seed,
epoch)).permutation`` — byte-identical to ``pipeline.ShardedLoader``'s
order — and handed to the C++ side. Every process assembles slices of the
SAME global batch (the property that keeps collectives from deadlocking,
SURVEY.md §7 hard parts), and a run may checkpoint under one engine and
resume under the other with the exact data trajectory preserved.

Eval rides the SAME C++ gather: the "permutation" is the identity padded
with row 0 up to a whole number of batches (the C++ side sizes epochs by
the row count given at create, so passing the padded count makes the
ragged tail a full step; pad gathers are in-bounds reads of row 0 whose
outputs are masked off), and the ``valid`` mask — position < n — is
attached host-side per step.

Slot lifetime: a yielded batch's host buffers live in a ring slot. The slot
is released two iterations later, after ``jax.block_until_ready`` on the
batch that lived there confirms its H2D transfer finished (normally a no-op
by then, keeping the release off the critical path). Integer datasets only
(the GLUE/LM contract).
"""

from __future__ import annotations

import ctypes
import time
from typing import Iterator

import jax
import numpy as np
from jax.sharding import Mesh

from pytorch_distributed_training_tpu.comms.ingest import make_global_batch
from pytorch_distributed_training_tpu.comms.mesh import TRAIN_BATCH_PSPEC
from pytorch_distributed_training_tpu.faults.inject import get_plan
from pytorch_distributed_training_tpu.native import load_batcher_lib
from pytorch_distributed_training_tpu.telemetry.registry import get_registry

_RING_SLOTS = 4
_WORKERS = 2


class NativeShardedLoader:
    """Drop-in for ``ShardedLoader`` (train or eval) with C++ prefetch."""

    def __init__(
        self,
        data: dict[str, np.ndarray],
        mesh: Mesh,
        *,
        global_batch_size: int,
        grad_accum_steps: int = 1,
        seed: int = 42,
        train: bool = True,
        process_index: int | None = None,
        process_count: int | None = None,
    ):
        lib = load_batcher_lib()
        if lib is None:
            raise RuntimeError(
                "native batcher unavailable (no C++ toolchain?) — use "
                "pipeline.ShardedLoader"
            )
        self._lib = lib
        self.mesh = mesh
        self.seed = seed
        self.global_batch = global_batch_size
        self.accum = grad_accum_steps if train else 1
        self.train = train

        from pytorch_distributed_training_tpu.data.pipeline import (
            resolve_batch_geometry,
        )

        self.pidx, self.pcount, micro_global, micro_local, _ = (
            resolve_batch_geometry(
                mesh,
                global_batch_size=global_batch_size,
                grad_accum_steps=grad_accum_steps,
                train=train,
                process_index=process_index,
                process_count=process_count,
            )
        )

        # int32, C-contiguous copies the C++ side can point at; keys sorted
        # for a deterministic array order across hosts.
        for k, v in data.items():
            if not np.issubdtype(np.asarray(v).dtype, np.integer):
                raise TypeError(
                    f"native loader serves integer datasets only; {k!r} is "
                    f"{np.asarray(v).dtype} — use pipeline.ShardedLoader"
                )
        self._keys = sorted(data)
        self._arrays = [
            np.ascontiguousarray(np.asarray(data[k], np.int32))
            for k in self._keys
        ]
        self.n = len(self._arrays[0])
        self._row_elems = [
            int(np.prod(a.shape[1:], dtype=np.int64)) for a in self._arrays
        ]
        self._row_shapes = [a.shape[1:] for a in self._arrays]

        # Eval pads the ragged tail into a full final step: the C++ side
        # sizes epochs by THIS row count, and the identity "permutation" we
        # hand it is padded with in-bounds row-0 entries (masked off via
        # ``valid``). Train keeps exact rows (ragged tail dropped, the
        # Python loader's train semantics).
        if train:
            self._n_epoch_rows = self.n
        else:
            gb = self.global_batch
            self._n_epoch_rows = ((self.n + gb - 1) // gb) * gb
        arr_ptrs = (ctypes.c_void_p * len(self._arrays))(
            *[a.ctypes.data_as(ctypes.c_void_p).value for a in self._arrays]
        )
        row_elems = (ctypes.c_int64 * len(self._arrays))(*self._row_elems)
        self._handle = lib.batcher_create(
            arr_ptrs,
            row_elems,
            len(self._arrays),
            self._n_epoch_rows,
            self.accum,
            micro_global,
            micro_local,
            self.pidx * micro_local,
            _RING_SLOTS,
            _WORKERS,
        )
        self._micro_local = micro_local

    @property
    def steps_per_epoch(self) -> int:
        return self._n_epoch_rows // self.global_batch

    def batch_spec(self) -> dict:
        """Abstract (global) shapes/dtypes of one yielded batch (all int32 —
        the C++ assembler's storage dtype); the AOT warm-start contract
        shared with ``ShardedLoader.batch_spec``."""
        micro_global = self.global_batch // self.accum
        if self.train:
            return {
                k: jax.ShapeDtypeStruct(
                    (self.accum, micro_global, *self._row_shapes[i]),
                    np.int32,
                )
                for i, k in enumerate(self._keys)
            }
        spec = {
            k: jax.ShapeDtypeStruct(
                (self.global_batch, *self._row_shapes[i]), np.int32
            )
            for i, k in enumerate(self._keys)
        }
        spec["valid"] = jax.ShapeDtypeStruct((self.global_batch,), np.int32)
        return spec

    def epoch(self, epoch_index: int = 0) -> Iterator[dict]:
        lib = self._lib
        if self.train:
            # SAME permutation as pipeline.ShardedLoader._train_epoch — the
            # two engines must be interchangeable mid-run (mid-epoch resume).
            perm = np.ascontiguousarray(
                np.random.default_rng(
                    (self.seed, epoch_index)
                ).permutation(self.n),
                dtype=np.int64,
            )
        else:
            # identity order; pad entries re-gather the LAST valid row (same
            # contract as pipeline.ShardedLoader._eval_epoch — masked off
            # via ``valid``, and a hot-in-cache read instead of row 0)
            perm = np.full(self._n_epoch_rows, self.n - 1, np.int64)
            perm[: self.n] = np.arange(self.n, dtype=np.int64)
        n_steps = lib.batcher_start_epoch(
            self._handle, perm.ctypes.data_as(ctypes.POINTER(ctypes.c_int64))
        )
        out_ptrs = (ctypes.c_void_p * len(self._arrays))()
        held: list[tuple[int, dict]] = []

        def release(slot, placed):
            if self._handle is None:  # close() already destroyed the batcher
                return
            # the slot's buffers may be overwritten once released: make sure
            # the device transfer that read them has completed
            jax.block_until_ready(placed)
            lib.batcher_release(self._handle, slot)

        reg = get_registry()
        try:
            for step in range(n_steps):
                # time the ring-slot wait: ~0 when the C++ workers are ahead
                # of the device, the prefetch-stall signal when they're not
                t0 = time.perf_counter()
                slot = lib.batcher_next(self._handle, out_ptrs)
                reg.observe("data/prefetch_wait_s", time.perf_counter() - t0)
                if slot < 0:
                    break
                batch = {}
                for i, k in enumerate(self._keys):
                    shape = (self.accum, self._micro_local, *self._row_shapes[i])
                    n_el = self.accum * self._micro_local * self._row_elems[i]
                    buf = (ctypes.c_int32 * n_el).from_address(out_ptrs[i])
                    batch[k] = np.frombuffer(buf, np.int32).reshape(shape)
                t_place = time.perf_counter()
                if self.train:
                    placed = make_global_batch(
                        self.mesh, batch, pspec=TRAIN_BATCH_PSPEC
                    )
                else:
                    # [local_batch, ...] + the per-step validity mask (pad
                    # rows of the final step masked off) — identical to
                    # pipeline.ShardedLoader._eval_epoch
                    batch = {k: v[0] for k, v in batch.items()}
                    valid_n = min(
                        self.n - step * self.global_batch, self.global_batch
                    )
                    valid_global = (
                        np.arange(self.global_batch) < valid_n
                    ).astype(np.int32)
                    lo = self.pidx * self._micro_local
                    batch["valid"] = valid_global[lo : lo + self._micro_local]
                    placed = make_global_batch(self.mesh, batch)
                reg.observe(
                    "data/h2d_place_s", time.perf_counter() - t_place
                )
                # fault injection (PDT_TPU_FAULT=slow_host:2x): stretch THIS
                # host's batch path so straggler detection has a straggler
                get_plan().slow_host_delay(time.perf_counter() - t0)
                yield placed
                held.append((slot, placed))
                if len(held) > 2:  # normally a no-op sync by now
                    release(*held.pop(0))
        finally:
            for slot, placed in held:
                release(slot, placed)

    def close(self) -> None:
        if getattr(self, "_handle", None):
            self._lib.batcher_destroy(self._handle)
            self._handle = None

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass
