"""Pallas (Mosaic) fused LayerNorm for TPU — fwd + custom-VJP bwd.

Why this kernel exists: on the bert-large MRPC recipe the xprof trace
(scripts/trace_step.py) shows XLA lowering every ``nn.LayerNorm`` to kLoop
reduce fusions costing ~0.2 ms per execution — ~37 ms of a ~167 ms step
across the 49 norms/microbatch (fwd ``convert_reduce_fusion`` ~19 ms + bwd
``multiply_reduce_fusion`` ~18 ms), an order of magnitude above the HBM
bandwidth bound for the tensors involved. A hand-fused row-block kernel
reads/writes each activation exactly once and keeps all statistics math in
VMEM/fp32. (The reference has no kernels of its own — it rides torch's
fused LN, reference test_data_parallelism.py:112; this is the TPU-native
equivalent of that fused native op.)

Contract (matches the ``nn.LayerNorm(dtype=fp32)`` + cast usage in
models/bert.py, models/gpt2.py):

- input x [..., H] bf16/f32; normalization over the last axis with fp32
  statistics regardless of input dtype; output = (x - mean) * rsqrt(var +
  eps) * scale + bias cast to ``out_dtype`` (the models always cast the
  fp32 LN output straight to bf16, so the kernel emits bf16 directly).
- ``var`` is the biased variance (ddof=0), eps added inside the rsqrt —
  identical formula to flax/torch LayerNorm.
- backward recomputes x_hat from the saved input + (mean, rstd) statistics
  (no [.., H] fp32 residual), returning dx in x.dtype and fp32 dscale/dbias.

Dispatch: Mosaic lowers on TPU only. Off-TPU (the CPU test mesh) the public
entry point runs a jnp reference with the exact same math unless the
caller is inside ``ops.flash_attention.tpu_interpret_mode`` (kernel parity
tests). Shapes that don't tile (H not a multiple of 128) also fall back.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pytorch_distributed_training_tpu.ops.dropout import (
    derive_kernel_seed,
    kernel_prng_seed as _prng_seed,
    kernel_keep_mask as _keep_mask,
    pow2_row_block,
    raw_dropout,
)

_LANES = 128  # stats outputs are lane-broadcast to the minor-dim tile width
_DEFAULT_BLOCK_R = 256


def reference_layer_norm(x, scale, bias, *, eps: float, out_dtype=None):
    """jnp twin of the kernel: fp32 stats, biased variance, cast at the end."""
    out_dtype = out_dtype or x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    c = xf - mean
    var = jnp.mean(c * c, axis=-1, keepdims=True)
    y = c * jax.lax.rsqrt(var + eps)
    y = y * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    return y.astype(out_dtype)


# ----------------------------------------------------- shared kernel math


def _ln_stats(xf, eps: float):
    """fp32 (mean, rstd, xhat) over the last axis — THE LayerNorm formula,
    shared by every kernel here so fwd and the bwd recompute can't drift."""
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    c = xf - mean
    var = jnp.mean(c * c, axis=-1, keepdims=True)
    rstd = jax.lax.rsqrt(var + eps)
    return mean, rstd, c * rstd


def _ln_dx(xhat, dy, scale_f32, rstd):
    """LayerNorm input gradient from fp32 xhat/dy."""
    wdy = dy * scale_f32
    h = xhat.shape[-1]
    c1 = jnp.sum(wdy * xhat, axis=-1, keepdims=True) / h
    c2 = jnp.sum(wdy, axis=-1, keepdims=True) / h
    return (wdy - xhat * c1 - c2) * rstd


def _write_param_partials(dscale_ref, dbias_ref, dy, xhat):
    """Per-block partial dscale/dbias, sublane-broadcast into [1, 8, H]
    blocks (Mosaic wants >= 8 sublanes; callers read row 0 and sum)."""
    dscale_ref[...] = jnp.broadcast_to(
        jnp.sum(dy * xhat, axis=0)[None, None, :], dscale_ref.shape
    )
    dbias_ref[...] = jnp.broadcast_to(
        jnp.sum(dy, axis=0)[None, None, :], dbias_ref.shape
    )


# --------------------------------------------------------------------- fwd


def _fwd_kernel(x_ref, scale_ref, bias_ref, y_ref, *, eps: float):
    xf = x_ref[...].astype(jnp.float32)  # [block_r, H]
    _, _, xhat = _ln_stats(xf, eps)
    y = xhat * scale_ref[...].astype(jnp.float32) + bias_ref[...].astype(
        jnp.float32
    )
    y_ref[...] = y.astype(y_ref.dtype)


def _fwd(x2d, scale, bias, *, eps: float, out_dtype, block_r: int):
    rows, h = x2d.shape
    grid = (rows // block_r,)
    return pl.pallas_call(
        functools.partial(_fwd_kernel, eps=eps),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_r, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((block_r, h), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((rows, h), out_dtype),
        interpret=interpret_active(),
    )(x2d, scale[None, :], bias[None, :])


# --------------------------------------------------------------------- bwd


def _bwd_kernel(x_ref, dy_ref, scale_ref,
                dx_ref, dscale_ref, dbias_ref, *, eps: float):
    xf = x_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    # stats recomputed from the (already loaded) input — cheaper than
    # round-tripping [rows, 128] lane-broadcast fp32 residuals through HBM
    _, rstd, xhat = _ln_stats(xf, eps)
    dx = _ln_dx(xhat, dy, scale_ref[...].astype(jnp.float32), rstd)
    dx_ref[...] = dx.astype(dx_ref.dtype)
    _write_param_partials(dscale_ref, dbias_ref, dy, xhat)


def _bwd(x2d, dy2d, scale, *, eps: float, block_r: int):
    rows, h = x2d.shape
    nblocks = rows // block_r
    dx, dscale_p, dbias_p = pl.pallas_call(
        functools.partial(_bwd_kernel, eps=eps),
        grid=(nblocks,),
        in_specs=[
            pl.BlockSpec((block_r, h), lambda i: (i, 0)),
            pl.BlockSpec((block_r, h), lambda i: (i, 0)),
            pl.BlockSpec((1, h), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_r, h), lambda i: (i, 0)),
            pl.BlockSpec((1, 8, h), lambda i: (i, 0, 0)),
            pl.BlockSpec((1, 8, h), lambda i: (i, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, h), x2d.dtype),
            jax.ShapeDtypeStruct((nblocks, 8, h), jnp.float32),
            jax.ShapeDtypeStruct((nblocks, 8, h), jnp.float32),
        ],
        interpret=interpret_active(),
    )(x2d, dy2d, scale[None, :])
    return dx, jnp.sum(dscale_p[:, 0], axis=0), jnp.sum(dbias_p[:, 0], axis=0)


# ------------------------------------------------------- public entry point


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _fused_layer_norm(x2d, scale, bias, eps, out_dtype, block_r):
    return _fwd(x2d, scale, bias, eps=eps, out_dtype=out_dtype,
                block_r=block_r)


def _fused_vjp_fwd(x2d, scale, bias, eps, out_dtype, block_r):
    y = _fwd(x2d, scale, bias, eps=eps, out_dtype=out_dtype, block_r=block_r)
    return y, (x2d, scale)


def _fused_vjp_bwd(eps, out_dtype, block_r, res, dy):
    x2d, scale = res
    dx, dscale, dbias = _bwd(
        x2d, dy.astype(x2d.dtype), scale, eps=eps, block_r=block_r
    )
    return dx, dscale.astype(scale.dtype), dbias.astype(scale.dtype)


_fused_layer_norm.defvjp(_fused_vjp_fwd, _fused_vjp_bwd)


from pytorch_distributed_training_tpu.ops.dispatch import (
    interpret_active,
    shard_map as _shard_map,
)


def _row_shard_plan(x, block_r: int):
    """shard_map plan for a row-wise kernel on ``x`` [..., H]: batch axes
    on dim 0, the seq axis on dim 1 when present (dispatch.plan_shards),
    plus the LOCAL row-block size — or None when the shape doesn't divide
    over the registered mesh (caller falls back to the XLA math)."""
    from pytorch_distributed_training_tpu.ops import dispatch

    ctx = dispatch.kernel_ctx()
    if ctx is None:
        return None
    seq_axis = ctx[2]
    plan = dispatch.plan_shards(
        x.shape, {1: seq_axis} if x.ndim >= 3 else {}
    )
    if plan is None:
        return None
    mesh, spec, axes_used, local_shape = plan
    rows_local = 1
    for d in local_shape[:-1]:
        rows_local *= d
    br = pow2_row_block(rows_local, block_r)
    if br < 16:
        return None
    return mesh, spec, axes_used, br


def layer_norm(
    x,
    scale,
    bias,
    *,
    eps: float = 1e-12,
    out_dtype=None,
    block_r: int = _DEFAULT_BLOCK_R,
    impl: str = "fused",
):
    """LayerNorm over the last axis; fp32 stats; output cast to out_dtype.

    ``impl``: "fused" uses the Pallas kernel when the backend supports it
    and shapes tile (falls back to the jnp reference otherwise);
    "reference" always uses the jnp math.
    """
    if impl not in ("fused", "reference"):
        raise ValueError(
            f"unknown layernorm impl {impl!r}; have ('fused', 'reference')"
        )
    from pytorch_distributed_training_tpu.ops import dispatch

    out_dtype = out_dtype or x.dtype
    h = x.shape[-1]
    rows = 1
    for d in x.shape[:-1]:
        rows *= d
    mode = dispatch.mode() if impl == "fused" and h % _LANES == 0 else "off"
    if mode == "shard_map":
        plan = _row_shard_plan(x, block_r)
        if plan is not None:
            mesh, spec, _, br = plan
            from jax.sharding import PartitionSpec as P

            def body(xl, sl, bl):
                with dispatch.manual_region():
                    y = _fused_layer_norm(
                        xl.reshape(-1, h), sl, bl, eps,
                        jnp.dtype(out_dtype), br,
                    )
                return y.reshape(xl.shape[:-1] + (h,))

            dispatch.KERNEL_DISPATCH_COUNTS["layer_norm"] += 1
            return _shard_map(
                body, mesh=mesh, in_specs=(spec, P(), P()),
                out_specs=spec, check_rep=False,
            )(x, scale, bias)
        mode = "off"
    # largest power-of-2 row block <= block_r dividing rows; Mosaic's bf16
    # tile needs >= 16 sublanes, so smaller row counts use the reference
    br = pow2_row_block(rows, block_r)
    if mode != "direct" or br < 16:
        return reference_layer_norm(x, scale, bias, eps=eps,
                                    out_dtype=out_dtype)
    x2d = x.reshape(rows, h)
    y = _fused_layer_norm(x2d, scale, bias, eps, jnp.dtype(out_dtype), br)
    return y.reshape(*x.shape[:-1], h)


# ------------------------------------------------- dropout + add + LN (v2)
#
# The post-LN block tail is Dropout(h) -> x + h -> LayerNorm. Materializing
# the u32 keep-mask words and running the select in whatever fusion XLA
# picks costs real HBM traffic and throttles neighboring matmul epilogues;
# this variant regenerates the mask from the per-core PRNG INSIDE the
# kernel (flash_attention.py's scheme: reseed per (site, block) so fwd and
# bwd reproduce bit-identical masks) and fuses mask, scale, residual add
# and the normalization into one read of h/x and one write of y.


def _dal_fwd_kernel(seed_ref, h_ref, x_ref, scale_ref, bias_ref,
                    y_ref, *s_out, eps: float, rate: float, site: int):
    i = pl.program_id(0)
    hf = h_ref[...].astype(jnp.float32)
    if rate > 0.0:
        _prng_seed(seed_ref[0], site * pl.num_programs(0) + i)
        keep = _keep_mask(hf.shape, rate)
        hf = jnp.where(keep, hf * (1.0 / (1.0 - rate)), 0.0)
    s = x_ref[...].astype(jnp.float32) + hf
    _, _, xhat = _ln_stats(s, eps)
    y = xhat * scale_ref[...].astype(jnp.float32) + bias_ref[...].astype(
        jnp.float32
    )
    y_ref[...] = y.astype(y_ref.dtype)
    if s_out:  # training: save the pre-norm sum for the backward
        s_out[0][...] = s.astype(s_out[0].dtype)


def _dal_fwd(h2d, x2d, scale, bias, seed, *, eps, rate, site, out_dtype,
             block_r, save_s=True):
    rows, hdim = h2d.shape
    grid = (rows // block_r,)
    row_block = lambda i, *_: (i, 0)  # noqa: E731
    one_block = lambda i, *_: (0, 0)  # noqa: E731
    out_specs = [pl.BlockSpec((block_r, hdim), row_block)]
    out_shape = [jax.ShapeDtypeStruct((rows, hdim), out_dtype)]
    if save_s:  # inference-only forwards skip the residual write entirely
        out_specs.append(pl.BlockSpec((block_r, hdim), row_block))
        out_shape.append(jax.ShapeDtypeStruct((rows, hdim), h2d.dtype))
    out = pl.pallas_call(
        functools.partial(_dal_fwd_kernel, eps=eps, rate=rate, site=site),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=grid,
            in_specs=[
                pl.BlockSpec((block_r, hdim), row_block),
                pl.BlockSpec((block_r, hdim), row_block),
                pl.BlockSpec((1, hdim), one_block),
                pl.BlockSpec((1, hdim), one_block),
            ],
            out_specs=out_specs,
        ),
        out_shape=out_shape,
        interpret=interpret_active(),
    )(seed, h2d, x2d, scale[None, :], bias[None, :])
    # pallas_call returns a list matching out_shape; normalize to (y, s)
    return (out[0], out[1]) if save_s else (out[0], None)


def _dal_bwd_kernel(seed_ref, s_ref, dy_ref, scale_ref,
                    dh_ref, dx_ref, dscale_ref, dbias_ref, *,
                    eps: float, rate: float, site: int):
    i = pl.program_id(0)
    sf = s_ref[...].astype(jnp.float32)
    dy = dy_ref[...].astype(jnp.float32)
    # stats recomputed in VMEM from the saved pre-norm sum (see _bwd_kernel)
    _, rstd, xhat = _ln_stats(sf, eps)
    ds = _ln_dx(xhat, dy, scale_ref[...].astype(jnp.float32), rstd)
    dx_ref[...] = ds.astype(dx_ref.dtype)
    if rate > 0.0:
        _prng_seed(seed_ref[0], site * pl.num_programs(0) + i)
        keep = _keep_mask(ds.shape, rate)
        dh = jnp.where(keep, ds * (1.0 / (1.0 - rate)), 0.0)
    else:
        dh = ds
    dh_ref[...] = dh.astype(dh_ref.dtype)
    _write_param_partials(dscale_ref, dbias_ref, dy, xhat)


def _dal_bwd(s2d, dy2d, scale, seed, *, eps, rate, site, h_dtype,
             block_r):
    rows, hdim = s2d.shape
    nblocks = rows // block_r
    row_block = lambda i, *_: (i, 0)  # noqa: E731
    one_block = lambda i, *_: (0, 0)  # noqa: E731
    dh, dx, dscale_p, dbias_p = pl.pallas_call(
        functools.partial(_dal_bwd_kernel, eps=eps, rate=rate, site=site),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(nblocks,),
            in_specs=[
                pl.BlockSpec((block_r, hdim), row_block),
                pl.BlockSpec((block_r, hdim), row_block),
                pl.BlockSpec((1, hdim), one_block),
            ],
            out_specs=[
                pl.BlockSpec((block_r, hdim), row_block),
                pl.BlockSpec((block_r, hdim), row_block),
                pl.BlockSpec((1, 8, hdim), lambda i, *_: (i, 0, 0)),
                pl.BlockSpec((1, 8, hdim), lambda i, *_: (i, 0, 0)),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct((rows, hdim), h_dtype),
            jax.ShapeDtypeStruct((rows, hdim), h_dtype),
            jax.ShapeDtypeStruct((nblocks, 8, hdim), jnp.float32),
            jax.ShapeDtypeStruct((nblocks, 8, hdim), jnp.float32),
        ],
        interpret=interpret_active(),
    )(seed, s2d, dy2d, scale[None, :])
    return dh, dx, jnp.sum(dscale_p[:, 0], 0), jnp.sum(dbias_p[:, 0], 0)


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9))
def _fused_dal(h2d, x2d, scale, bias, seed, eps, rate, site, out_dtype,
               block_r):
    y, _ = _dal_fwd(h2d, x2d, scale, bias, seed, eps=eps, rate=rate,
                    site=site, out_dtype=out_dtype, block_r=block_r,
                    save_s=False)
    return y


def _fused_dal_vjp_fwd(h2d, x2d, scale, bias, seed, eps, rate, site,
                       out_dtype, block_r):
    y, s = _dal_fwd(h2d, x2d, scale, bias, seed, eps=eps, rate=rate,
                    site=site, out_dtype=out_dtype, block_r=block_r)
    return y, (s, scale, seed)


def _fused_dal_vjp_bwd(eps, rate, site, out_dtype, block_r, res, dy):
    s, scale, seed = res
    dh, dx, dscale, dbias = _dal_bwd(
        s, dy.astype(s.dtype), scale, seed, eps=eps, rate=rate,
        site=site, h_dtype=s.dtype, block_r=block_r,
    )
    return dh, dx, dscale.astype(scale.dtype), dbias.astype(scale.dtype), None


_fused_dal.defvjp(_fused_dal_vjp_fwd, _fused_dal_vjp_bwd)


def dropout_add_layer_norm(
    h,
    x,
    scale,
    bias,
    *,
    rate: float,
    dropout_rng=None,
    deterministic: bool = True,
    eps: float = 1e-12,
    site: int = 0,
    out_dtype=None,
    block_r: int = _DEFAULT_BLOCK_R,
    impl: str = "fused",
    dropout_impl: str = "kernel",
):
    """LayerNorm(x + Dropout(h)) over the last axis.

    With ``impl="fused"`` AND ``dropout_impl="kernel"`` on TPU, the whole
    tail runs as one Pallas kernel with the keep-mask regenerated from the
    per-core PRNG (no mask bytes ever hit HBM; fwd and bwd reseed
    identically per (site, row-block), so ``site`` must differ between
    call sites sharing one ``dropout_rng``). Any other ``dropout_impl``
    keeps that generator's documented mask stream (ops/dropout.py — e.g.
    "exact" stays bit-identical to flax nn.Dropout) by applying dropout
    through ``raw_dropout`` and then the LN (still the LN kernel when
    usable). Off-TPU everything falls back to jax.random + reference LN.
    """
    from pytorch_distributed_training_tpu.ops import dispatch

    out_dtype = out_dtype or x.dtype
    hdim = x.shape[-1]
    rows = 1
    for d in x.shape[:-1]:
        rows *= d
    rate = 0.0 if deterministic else rate
    mode = (
        dispatch.mode() if impl == "fused" and hdim % _LANES == 0 else "off"
    )
    if rate > 0.0 and dropout_impl != "kernel":
        mode = "off"  # foreign mask streams can't regenerate in-kernel
    if mode == "shard_map":
        plan = _row_shard_plan(x, block_r)
        if plan is None:
            mode = "off"
        else:
            mesh, spec, axes_used, br = plan
            from jax.sharding import PartitionSpec as P

            if rate > 0.0:
                seed = derive_kernel_seed(dropout_rng)
            else:
                seed = jnp.zeros((1,), jnp.int32)

            def body(hl, xl, sl, bl, seedl):
                with dispatch.manual_region():
                    # distinct in-kernel PRNG stream per shard
                    seedl = seedl + dispatch.linear_device_index(
                        axes_used, mesh
                    )
                    y = _fused_dal(
                        hl.reshape(-1, hdim), xl.reshape(-1, hdim), sl, bl,
                        seedl, eps, float(rate), int(site),
                        jnp.dtype(out_dtype), br,
                    )
                return y.reshape(xl.shape[:-1] + (hdim,))

            dispatch.KERNEL_DISPATCH_COUNTS["dal"] += 1
            return _shard_map(
                body, mesh=mesh, in_specs=(spec, spec, P(), P(), P()),
                out_specs=spec, check_rep=False,
            )(h, x, scale, bias, seed)
    br = pow2_row_block(rows, block_r)
    if mode != "direct" or br < 16:
        if rate > 0.0:
            h = raw_dropout(h, rate, dropout_rng, dropout_impl)
        return layer_norm(x + h, scale, bias, eps=eps, out_dtype=out_dtype,
                          block_r=block_r, impl=impl)
    if rate > 0.0:
        # one int32 seed per call; the kernel folds in the block index.
        seed = derive_kernel_seed(dropout_rng)
    else:
        seed = jnp.zeros((1,), jnp.int32)
    y = _fused_dal(
        h.reshape(rows, hdim), x.reshape(rows, hdim), scale, bias, seed,
        eps, float(rate), int(site), jnp.dtype(out_dtype), br,
    )
    return y.reshape(x.shape[:-1] + (hdim,))


import flax.linen as nn  # noqa: E402


class FusedLayerNorm(nn.Module):
    """flax LayerNorm twin mirroring ``nn.LayerNorm``'s param names/init
    (``scale`` ones, ``bias`` zeros) so checkpoints and the HF weight
    mapper are layout-identical whichever impl a config selects. Output is
    cast to ``out_dtype`` (the models always cast the fp32 LN result to
    the compute dtype anyway — the kernel just does it in-register)."""

    epsilon: float
    param_dtype: jnp.dtype
    out_dtype: jnp.dtype
    impl: str = "fused"

    @nn.compact
    def __call__(self, x):
        h = x.shape[-1]
        scale = self.param(
            "scale", nn.initializers.ones, (h,), self.param_dtype
        )
        bias = self.param(
            "bias", nn.initializers.zeros, (h,), self.param_dtype
        )
        return layer_norm(
            x, scale, bias, eps=self.epsilon,
            out_dtype=self.out_dtype, impl=self.impl,
        )


class FusedDropoutAddLayerNorm(nn.Module):
    """``LayerNorm(x + Dropout(h))`` as one module — the post-LN block
    tail. Param names match ``nn.LayerNorm`` ("scale"/"bias") so the
    checkpoint/HF layouts are unchanged vs the unfused Dropout + LN pair.

    ``site`` disambiguates the in-kernel PRNG stream between the two tails
    of one transformer block (they share the layer's dropout key)."""

    epsilon: float
    rate: float
    param_dtype: jnp.dtype
    out_dtype: jnp.dtype
    impl: str = "fused"
    site: int = 0
    dropout_impl: str = "kernel"

    @nn.compact
    def __call__(self, h, x, deterministic: bool = True):
        hdim = x.shape[-1]
        scale = self.param(
            "scale", nn.initializers.ones, (hdim,), self.param_dtype
        )
        bias = self.param(
            "bias", nn.initializers.zeros, (hdim,), self.param_dtype
        )
        rng = None
        if not deterministic and self.rate > 0.0:
            rng = self.make_rng("dropout")
        return dropout_add_layer_norm(
            h, x, scale, bias, rate=self.rate, dropout_rng=rng,
            deterministic=deterministic, eps=self.epsilon, site=self.site,
            out_dtype=self.out_dtype, impl=self.impl,
            dropout_impl=self.dropout_impl,
        )
