"""Dropout as a first-class framework op with a selectable mask generator.

The reference inherits torch's dropout inside HF BERT (reference
test_data_parallelism.py:112) — mask generation there is a CUDA kernel. On
TPU the mask generator is a real throughput lever: profiling bert-large
(NOTES.md) showed mask bits competing with the matmuls for VPU cycles, so
the generator is configurable per model (``ModelConfig.dropout_impl``):

- ``"exact"``  — ``jax.random.bernoulli`` (uniform-fp32 compare), bit-exact
  with flax ``nn.Dropout`` under the same key. The numerically conventional
  default for parity runs.
- ``"bits32"`` — compares raw 32-bit PRNG words against ``rate * 2^32``:
  same 1/2^32 keep-probability granularity as a fp32-uniform compare (fp32
  uniforms only carry 24 random bits), but skips the int→float conversion
  so the mask fuses into its consumer as integer VPU ops.

- ``"bits8"``  — one random *byte* per element (a uint32 word drives four
  elements): quarter the PRNG volume of the fp32-uniform path. The keep
  probability quantizes to 1/256 granularity (rate 0.1 → actual drop rate
  26/256 ≈ 0.1016); the inverted-dropout scale uses the *actual* rate so
  E[output] == input exactly. Statistically equivalent regularization,
  cheapest masks — the throughput default would be this if the quantized
  rate mattered less than bits32's exact rate.

Both draw from the key's configured generator (rbg rides the TPU hardware
PRNG; threefry2x32 gives the portable stream — ``TrainConfig.prng_impl``).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

DROPOUT_IMPLS = ("exact", "bits32", "bits8")


def raw_dropout(x, rate: float, rng, impl: str = "exact"):
    """Apply inverted dropout (train mode) to ``x``. Scale is 1/(1-rate)."""
    if rate <= 0.0:
        return x
    if rate >= 1.0:  # nn.Dropout contract: everything dropped, no inf scale
        return jnp.zeros_like(x)
    if impl == "exact":
        keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
        return jnp.where(keep, x / (1.0 - rate), jnp.zeros_like(x))
    if impl == "bits32":
        thresh = jnp.uint32(min(round(rate * (1 << 32)), (1 << 32) - 1))
        bits = jax.random.bits(rng, x.shape, jnp.uint32)
        scale = jnp.asarray(1.0 / (1.0 - rate), x.dtype)
        # multiply-by-mask-scale (not where(bits, x, 0)): the multiply's
        # backward residual is the small x-dtype mask tensor, so XLA saves
        # that instead of the 4-byte random words (measured: the u32
        # residual copies were 3.6 ms/step on bert-large). IEEE note: a
        # non-finite x stays non-finite at dropped positions (NaN*0=NaN)
        # instead of being quenched to 0 like a select would — deliberate:
        # masking a NaN in 10% of positions only hides real numeric bugs
        # (--debug-nans is the detection tool), and finite inputs are
        # bit-identical to the select form.
        mask_scale = jnp.where(
            bits >= thresh, scale, jnp.zeros((), x.dtype)
        )
        return x * mask_scale
    if impl == "bits8":
        thresh_i = min(max(round(rate * 256), 1), 255)
        actual_rate = thresh_i / 256.0  # scale by the rate actually applied
        if x.shape[-1] % 4 == 0:
            words = jax.random.bits(
                rng, (*x.shape[:-1], x.shape[-1] // 4), jnp.uint32
            )
            bits = jax.lax.bitcast_convert_type(words, jnp.uint8).reshape(
                x.shape
            )
        else:
            bits = jax.random.bits(rng, x.shape, jnp.uint8)
        scale = jnp.asarray(1.0 / (1.0 - actual_rate), x.dtype)
        # same multiply form (and IEEE semantics) as bits32
        mask_scale = jnp.where(
            bits >= jnp.uint8(thresh_i), scale, jnp.zeros((), x.dtype)
        )
        return x * mask_scale
    raise ValueError(f"unknown dropout impl {impl!r}; have {DROPOUT_IMPLS}")


class Dropout(nn.Module):
    """Drop-in for ``nn.Dropout`` with the framework's mask generator.

    Same contract: rng collection ``"dropout"``, ``deterministic=True`` (or
    rate 0) is the identity.
    """

    rate: float
    impl: str = "exact"

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        if deterministic or self.rate <= 0.0:
            return x
        return raw_dropout(x, self.rate, self.make_rng("dropout"), self.impl)
