"""Dropout as a first-class framework op with a selectable mask generator.

The reference inherits torch's dropout inside HF BERT (reference
test_data_parallelism.py:112) — mask generation there is a CUDA kernel. On
TPU the mask generator is a real throughput lever: profiling bert-large
(NOTES.md) showed mask bits competing with the matmuls for VPU cycles, so
the generator is configurable per model (``ModelConfig.dropout_impl``):

- ``"exact"``  — ``jax.random.bernoulli`` (uniform-fp32 compare), bit-exact
  with flax ``nn.Dropout`` under the same key. The numerically conventional
  default for parity runs.
- ``"bits32"`` — compares raw 32-bit PRNG words against ``rate * 2^32``:
  same 1/2^32 keep-probability granularity as a fp32-uniform compare (fp32
  uniforms only carry 24 random bits), but skips the int→float conversion
  so the mask fuses into its consumer as integer VPU ops.

- ``"bits8"``  — one random *byte* per element (a uint32 word drives four
  elements): quarter the PRNG volume of the fp32-uniform path. The keep
  probability quantizes to 1/256 granularity (rate 0.1 → actual drop rate
  26/256 ≈ 0.1016); the inverted-dropout scale uses the *actual* rate so
  E[output] == input exactly. Statistically equivalent regularization,
  cheapest masks — the throughput default would be this if the quantized
  rate mattered less than bits32's exact rate.

Both draw from the key's configured generator (rbg rides the TPU hardware
PRNG; threefry2x32 gives the portable stream — ``TrainConfig.prng_impl``).
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

DROPOUT_IMPLS = ("exact", "bits32", "bits8", "kernel")


def mask_threshold(rate: float) -> "jnp.uint32":
    """Drop threshold for raw-PRNG-word masks: P(bits >= t) == 1 - rate.
    Single source of truth for every bits32-style generator (jax-stream
    and in-kernel alike) so the keep probability can't drift between
    implementations."""
    return jnp.uint32(min(round(rate * (1 << 32)), (1 << 32) - 1))


def derive_kernel_seed(rng):
    """One int32 scalar tying an in-kernel PRNG stream to a jax key."""
    return jax.lax.bitcast_convert_type(
        jax.random.bits(rng, (1,), jnp.uint32), jnp.int32
    )


def pow2_row_block(rows: int, block_r: int, floor: int = 16) -> int:
    """Largest power-of-2 row block <= block_r dividing rows (>= floor
    required by Mosaic's sublane tiling; returns a value < floor when no
    admissible block exists — callers fall back)."""
    br = block_r
    while br >= floor and rows % br != 0:
        br //= 2
    return br


def mask_scale_jax(rng, shape, rate: float, dtype):
    """jax-stream mask-scale tensor (0 or 1/(1-rate)) — the bits32 mask."""
    bits = jax.random.bits(rng, shape, jnp.uint32)
    scale = jnp.asarray(1.0 / (1.0 - rate), dtype)
    return jnp.where(bits >= mask_threshold(rate), scale, jnp.zeros((), dtype))


def kernel_prng_seed(*seeds) -> None:
    """``pltpu.prng_seed``, skipped in off-TPU interpret mode: the Mosaic
    PRNG primitives have no CPU lowering in this jax, and interpret-mode
    bits are all-zeros anyway (NOTES.md) — seeding a generator that will
    not be read would only crash the interpreter. Every kernel seeds
    through here so the gate can't drift per site."""
    from pytorch_distributed_training_tpu.ops.dispatch import (
        interpret_active,
    )

    if interpret_active():
        return
    from jax.experimental.pallas import tpu as pltpu

    pltpu.prng_seed(*seeds)


def kernel_keep_mask(shape, rate: float):
    """In-kernel Bernoulli(1-rate) keep mask from the ALREADY-SEEDED
    per-core TPU PRNG (call ``kernel_prng_seed`` first). Shared by every
    Pallas dropout site (flash attention, the LN tails, mask_scale) so the
    threshold semantics cannot drift. Off-TPU interpret mode emulates the
    documented all-zeros-bits contract (every position drops for rate>0)
    without touching the unlowerable Mosaic PRNG primitives."""
    from pytorch_distributed_training_tpu.ops.dispatch import (
        interpret_active,
    )

    if interpret_active():
        bits = jnp.zeros(shape, jnp.uint32)
    else:
        from jax.experimental.pallas import tpu as pltpu

        bits = pltpu.bitcast(pltpu.prng_random_bits(shape), jnp.uint32)
    return bits >= mask_threshold(rate)


def _mask_scale_kernel(seed_ref, o_ref, *, rate: float):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    kernel_prng_seed(seed_ref[0], pl.program_id(0))
    keep = kernel_keep_mask(o_ref.shape, rate)
    # select in fp32 (same 32-bit tiling as the predicate — a bf16 select
    # here trips a Mosaic i1 relayout), convert once at the store
    scale = jnp.float32(1.0 / (1.0 - rate))
    o_ref[...] = jnp.where(keep, scale, 0.0).astype(o_ref.dtype)


def _mask_scale_from_seed(seed, shape, rate: float, dtype,
                          *, block_r: int = 512):
    """Kernel core of ``mask_scale_pallas`` from an explicit [1] int32 seed
    (shard_map bodies offset the seed per device before calling). Returns
    None when the shape doesn't tile (caller picks its fallback)."""
    import functools

    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from pytorch_distributed_training_tpu.ops.dispatch import (
        interpret_active,
    )

    n = 1
    for d in shape:
        n *= d
    lanes = 128
    rows = n // lanes
    br = pow2_row_block(rows, block_r)
    if rows * lanes != n or br < 16:
        return None
    out = pl.pallas_call(
        functools.partial(_mask_scale_kernel, rate=rate),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(rows // br,),
            in_specs=[],
            out_specs=pl.BlockSpec((br, lanes), lambda i, *_: (i, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((rows, lanes), dtype),
        interpret=interpret_active(),
    )(seed)
    return out.reshape(shape)


def mask_scale_pallas(rng, shape, rate: float, dtype, *, block_r: int = 512):
    """[shape] tensor of 0 / 1/(1-rate) from the per-core TPU PRNG.

    The x-dtype mask-scale tensor is the ONLY thing that touches HBM —
    the 4-byte random words live and die in VMEM (the XLA path writes the
    u32 words, layout-copies them for the transposed consumer, then reads
    them back: ~3x the bytes on the bert-large probs dropout). The stream
    is seeded from the jax PRNG key, so it is deterministic per key (and
    per row-block) but is NOT the jax.random.bits stream; under
    ``jax.checkpoint`` the regeneration in the backward pass is
    bit-identical because the seed input is identical.
    """
    out = _mask_scale_from_seed(
        derive_kernel_seed(rng), shape, rate, dtype, block_r=block_r
    )
    if out is None:
        # ragged shape: fall back to the jax.random stream
        return mask_scale_jax(rng, shape, rate, dtype)
    return out


def _mask_scale_sharded(x, rate: float, rng):
    """shard_map-routed kernel mask-scale (ops/dispatch.py): dim 0 shards
    over the batch axes; dim 1 over the head axis for 4-D (attention
    probs [B, N, S, S] under tensor parallelism) or the seq axis for 3-D
    activations. Returns None when the registered mesh doesn't divide the
    shape (caller falls back to the jax-stream mask)."""
    from jax.sharding import PartitionSpec as P  # noqa: F401 (body spec)

    from pytorch_distributed_training_tpu.ops import dispatch
    from pytorch_distributed_training_tpu.ops.dispatch import shard_map

    ctx = dispatch.kernel_ctx()
    if ctx is None or x.ndim < 2:
        return None
    _, _, seq_axis, head_axis = ctx
    dim1_axis = head_axis if x.ndim == 4 else seq_axis
    plan = dispatch.plan_shards(
        x.shape, {1: dim1_axis} if x.ndim >= 3 else {}
    )
    if plan is None:
        return None
    mesh, spec, axes_used, local_shape = plan
    # decide tileability on the LOCAL shard shape, outside the body
    n = 1
    for d in local_shape:
        n *= d
    if (n // 128) * 128 != n or pow2_row_block(n // 128, 512) < 16:
        return None
    seed = derive_kernel_seed(rng)

    def body(xl, seedl):
        with dispatch.manual_region():
            seedl = seedl + dispatch.linear_device_index(axes_used, mesh)
            return xl * _mask_scale_from_seed(
                seedl, xl.shape, rate, xl.dtype
            )

    dispatch.KERNEL_DISPATCH_COUNTS["mask_scale"] += 1
    return shard_map(
        body, mesh=mesh, in_specs=(spec, P()), out_specs=spec,
        check_rep=False,
    )(x, seed)


def raw_dropout(x, rate: float, rng, impl: str = "exact"):
    """Apply inverted dropout (train mode) to ``x``. Scale is 1/(1-rate)."""
    if rate <= 0.0:
        return x
    if rate >= 1.0:  # nn.Dropout contract: everything dropped, no inf scale
        return jnp.zeros_like(x)
    if impl == "exact":
        keep = jax.random.bernoulli(rng, 1.0 - rate, x.shape)
        return jnp.where(keep, x / (1.0 - rate), jnp.zeros_like(x))
    if impl == "bits32":
        # multiply-by-mask-scale (not where(bits, x, 0)): the multiply's
        # backward residual is the small x-dtype mask tensor, so XLA saves
        # that instead of the 4-byte random words (measured: the u32
        # residual copies were 3.6 ms/step on bert-large). IEEE note: a
        # non-finite x stays non-finite at dropped positions (NaN*0=NaN)
        # instead of being quenched to 0 like a select would — deliberate:
        # masking a NaN in 10% of positions only hides real numeric bugs
        # (--debug-nans is the detection tool), and finite inputs are
        # bit-identical to the select form.
        return x * mask_scale_jax(rng, x.shape, rate, x.dtype)
    if impl == "kernel":
        from pytorch_distributed_training_tpu.ops import dispatch

        mode = dispatch.mode()
        if mode == "direct":  # single-device TPU or interpret ctx
            return x * mask_scale_pallas(rng, x.shape, rate, x.dtype)
        if mode == "shard_map":
            out = _mask_scale_sharded(x, rate, rng)
            if out is not None:
                return out
        # off-TPU / non-divisible shapes: same mask-scale form, jax stream
        return raw_dropout(x, rate, rng, "bits32")
    if impl == "bits8":
        thresh_i = min(max(round(rate * 256), 1), 255)
        actual_rate = thresh_i / 256.0  # scale by the rate actually applied
        if x.shape[-1] % 4 == 0:
            words = jax.random.bits(
                rng, (*x.shape[:-1], x.shape[-1] // 4), jnp.uint32
            )
            bits = jax.lax.bitcast_convert_type(words, jnp.uint8).reshape(
                x.shape
            )
        else:
            bits = jax.random.bits(rng, x.shape, jnp.uint8)
        scale = jnp.asarray(1.0 / (1.0 - actual_rate), x.dtype)
        # same multiply form (and IEEE semantics) as bits32
        mask_scale = jnp.where(
            bits >= jnp.uint8(thresh_i), scale, jnp.zeros((), x.dtype)
        )
        return x * mask_scale
    raise ValueError(f"unknown dropout impl {impl!r}; have {DROPOUT_IMPLS}")


class Dropout(nn.Module):
    """Drop-in for ``nn.Dropout`` with the framework's mask generator.

    Same contract: rng collection ``"dropout"``, ``deterministic=True`` (or
    rate 0) is the identity.
    """

    rate: float
    impl: str = "exact"

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        if deterministic or self.rate <= 0.0:
            return x
        return raw_dropout(x, self.rate, self.make_rng("dropout"), self.impl)
