"""int8 MXU matmul path (dynamic-quantized dense layers).

Why this exists: the v5e MXU executes int8×int8→int32 at twice its bf16
FLOP rate (≈394 vs ≈197 T/s). On the bert-large MRPC recipe the step time
is ~85% near-peak bf16 matmul (NOTES.md round-3 ledger), so once the
elementwise/optimizer tail is shaved there is structurally NOTHING left to
win in bf16 — the remaining lever the hardware offers is the int8 systolic
path. This module implements it as dynamic quantization around
``lax.dot_general``:

- weights: per-output-channel scales (absmax / 127), quantized once per
  step (loop-invariant across the accumulation microbatches — XLA CSEs the
  quantize of an unchanging operand in the unrolled accumulation graph);
- activations: one dynamic per-tensor scale per microbatch (absmax / 127).
  Per-tensor (not per-row) so the SAME quantized tensor stays valid for any
  contraction axis;
- products accumulate in int32 on the MXU, then one fused rescale
  ``* (sx * sw)`` lands the result back in the compute dtype.

The backward is a straight-through estimator: rounding is treated as
identity, and the two backward matmuls run against the QUANTIZED (then
dequantized) operands — the true gradient of the quantized forward, modulo
the STE step. ``QuantMode`` picks how the backward matmuls themselves
execute:

- ``"fwd"``  — backward runs bf16-input dots with f32 accumulation (the
  saved int8 operands are dequantized to the compute dtype first). ~⅓ of
  the dot FLOPs go 2×; the gradient dots keep the bf16 mantissa.
- ``"full"`` — dgrad and wgrad also int8, with fresh dynamic per-tensor
  scales for ``dy``. Fastest; gradient quantization noise is the price.

Dynamic per-tensor activation scales cost one absmax reduce-to-scalar pass
over every dense input per microbatch — a full HBM re-read that must
COMPLETE before the quantize pass can start (~9 ms/step on the bert-large
recipe, NOTES.md). ``delayed=True`` on :class:`QuantDenseGeneral` breaks
that serialization FP8-recipe style: each site quantizes with the amax
observed on the PREVIOUS microbatch (carried in the flax ``"quant"``
variable collection, threaded through the train step's scan carry and the
TrainState), while the CURRENT amax is computed concurrently with the
quantized dot for the next iteration. Values that outgrow the stale scale
saturate at ±127 for one microbatch — the same clipping semantics as any
int8 quantizer, one step late. Step 0 needs calibrated scales
(``train.step.calibrate_quant`` runs one forward on the first real batch).

This is an OPT-IN config (``ModelConfig.matmul_impl="int8"``), never a
silent default: convergence must be demonstrated per-recipe (see
NOTES.md int8 section for the on-chip A/B protocol) before any benchmark
reports it. The reference has no analogue (its AMP is fp16,
test_data_parallelism.py:55); this is TPU-hardware-first design, not
parity.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax

_INT8_MAX = 127.0


def _absmax(x, axes, keepdims=True):
    m = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=axes, keepdims=keepdims)
    # guard all-zero tensors: scale 0 would produce NaN on dequant
    return jnp.maximum(m, 1e-12)


def _quantize(x, scale):
    """THE quantization grid (symmetric, saturating at ±127) — every int8
    cast in this module goes through here so the dynamic, delayed, and
    per-channel paths cannot silently diverge."""
    return jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale), -127, 127
    ).astype(jnp.int8)


def quantize_per_tensor(x):
    """→ (int8 tensor, fp32 scalar scale). x ≈ q * scale."""
    scale = _absmax(x, axes=None, keepdims=False) / _INT8_MAX
    return _quantize(x, scale), scale


def quantize_per_channel(w, contract_axis):
    """→ (int8 weight, fp32 per-output-channel scale broadcastable against
    the matmul result). ``contract_axis`` is the axis being contracted away
    (reduced over when taking absmax)."""
    scale = _absmax(w, axes=contract_axis) / _INT8_MAX
    return _quantize(w, scale), jnp.squeeze(scale, axis=contract_axis)


def _fwd_dims(x_ndim: int, n_contract: int):
    """Forward dot dims: x's trailing ``n_contract`` axes against the
    kernel's leading ``n_contract`` axes (DenseGeneral contraction)."""
    nb = x_ndim - n_contract
    return (
        (tuple(range(nb, x_ndim)), tuple(range(n_contract))),
        ((), ()),
    )


def _quantized_dot(x, kernel, n_contract, x_scale=None):
    """Shared quantize → int8 dot → rescale body, on NATIVE shapes — no
    2-D reshape: an explicit reshape of an int8 (32,128)-tiled array is a
    materialized relayout copy on TPU (measured ~7 ms/step of pure copies
    on the bert-large recipe before this was dims-based). Returns the
    result in ``x``'s dtype plus the quantized operands/scales (the
    custom-VJP residuals; the primal drops them). ONE implementation so
    the primal and the VJP forward cannot diverge — the delayed path
    differs ONLY in passing a carried ``x_scale`` instead of computing a
    fresh per-tensor one."""
    if x_scale is None:
        xq, sx = quantize_per_tensor(x)
    else:
        sx = x_scale
        xq = _quantize(x, sx)
    wq, sw = quantize_per_channel(
        kernel, contract_axis=tuple(range(n_contract))
    )  # sw: [f1..fm]
    acc = lax.dot_general(
        xq, wq, _fwd_dims(x.ndim, n_contract),
        preferred_element_type=jnp.int32,
    )
    y = (acc.astype(jnp.float32) * (sx * sw)).astype(x.dtype)
    return y, (xq, sx, wq, sw)


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def int8_dense(x, kernel, n_contract: int = 1, mode: str = "fwd"):
    """Quantized DenseGeneral contraction with an STE backward; the result
    and the activation cotangent keep ``x``'s dtype.

    ``x``: [b1..bk, c1..cn]; ``kernel``: [c1..cn, f1..fm] → [b1..bk, f1..fm].

    ``mode="fwd"``: int8 forward, full-precision backward.
    ``mode="full"``: int8 forward AND int8 dgrad/wgrad.
    """
    return _quantized_dot(x, kernel, n_contract)[0]


def _int8_dense_fwd(x, kernel, n_contract, mode):
    y, (xq, sx, wq, sw) = _quantized_dot(x, kernel, n_contract)
    # save the QUANTIZED operands: the backward then differentiates the
    # function the forward actually computed (STE through the rounding),
    # and int8 residuals are 2-4x smaller in HBM than the bf16 inputs.
    # Zero-size sentinels carry the primal dtypes (dtype objects are not
    # pytree leaves; cotangents must come back in exactly these dtypes).
    sent = (jnp.zeros((0,), x.dtype), jnp.zeros((0,), kernel.dtype))
    return y, (xq, sx, wq, sw, sent)


def _full_mode_grad_dots(xq, sx, wq, dy_scaled, dy, s0, s1, n_contract,
                         x_dtype, w_dtype):
    """int8 dgrad/wgrad at given dy scales — THE "full"-mode backward
    layout, shared by the dynamic and delayed-dy paths so the two cannot
    diverge (only the scale SOURCE differs: fresh absmax vs carried).
    ``dy_scaled`` is dy with sw pre-folded (sw varies along dx's
    contracted f-dims; folding it before quantizing keeps one per-tensor
    scale exact). Per-tensor scales factor straight out of the batch
    contraction for dw."""
    nb = xq.ndim - n_contract  # batch rank
    nf = wq.ndim - n_contract  # feature rank
    # dx[b.., c..] = dy[b.., f..] · kernel[c.., f..]^T : contract f-dims
    dx_dims = (
        (tuple(range(nb, nb + nf)), tuple(range(n_contract, wq.ndim))),
        ((), ()),
    )
    # dw[c.., f..] = x[b.., c..]^T · dy[b.., f..] : contract batch dims
    dw_dims = ((tuple(range(nb)), tuple(range(nb))), ((), ()))
    dx = (
        lax.dot_general(
            _quantize(dy_scaled, s0), wq, dx_dims,
            preferred_element_type=jnp.int32,
        ).astype(jnp.float32) * s0
    ).astype(x_dtype)
    dw = (
        lax.dot_general(
            xq, _quantize(dy, s1), dw_dims,
            preferred_element_type=jnp.int32,
        ).astype(jnp.float32) * (sx * s1)
    ).astype(w_dtype)
    return dx, dw


def _int8_dense_bwd(n_contract, mode, res, dy):
    xq, sx, wq, sw, sent = res
    x_dtype, w_dtype = sent[0].dtype, sent[1].dtype
    nb = xq.ndim - n_contract  # batch rank
    nf = wq.ndim - n_contract  # feature rank
    dx_dims = (
        (tuple(range(nb, nb + nf)), tuple(range(n_contract, wq.ndim))),
        ((), ()),
    )
    dw_dims = ((tuple(range(nb)), tuple(range(nb))), ((), ()))
    if mode == "full":
        dy_scaled = dy.astype(jnp.float32) * sw  # broadcasts over [f..]
        return _full_mode_grad_dots(
            xq, sx, wq, dy_scaled, dy,
            _absmax(dy_scaled, axes=None, keepdims=False) / _INT8_MAX,
            _absmax(dy, axes=None, keepdims=False) / _INT8_MAX,
            n_contract, x_dtype, w_dtype,
        )
    xdq = (xq.astype(jnp.float32) * sx).astype(x_dtype)
    wdq = (wq.astype(jnp.float32) * sw).astype(x_dtype)
    dx = lax.dot_general(
        dy.astype(x_dtype), wdq, dx_dims,
        preferred_element_type=jnp.float32,
    ).astype(x_dtype)
    dw = lax.dot_general(
        xdq, dy.astype(x_dtype), dw_dims,
        preferred_element_type=jnp.float32,
    ).astype(w_dtype)
    return dx, dw


int8_dense.defvjp(_int8_dense_fwd, _int8_dense_bwd)


# ------------------------------------------------------- delayed scaling
def _delayed_quantized_dot(x, kernel, amax_prev, n_contract):
    """``_quantized_dot`` with a STALE (carried) activation scale.

    There is no data dependency between the quantize pass and any reduce
    over ``x``: ``scale`` is a carried scalar, so XLA can fuse the quantize
    into ``x``'s producer (the gelu epilogue, the LN output) and overlap
    the fresh-amax reduce with the dot. Returns (y, new_amax, residuals)."""
    scale = jnp.maximum(amax_prev, 1e-12) / _INT8_MAX
    new_amax = _absmax(x, axes=None, keepdims=False)
    y, res = _quantized_dot(x, kernel, n_contract, x_scale=scale)
    return y, new_amax, res


@partial(jax.custom_vjp, nondiff_argnums=(3, 4))
def int8_dense_delayed(x, kernel, amax_prev, n_contract: int = 1,
                       mode: str = "full"):
    """:func:`int8_dense` with delayed (previous-step) activation scaling.

    → ``(y, new_amax)``. ``amax_prev`` is the carried fp32 scalar amax of
    this site's input from the previous microbatch; ``new_amax`` is the
    current input's amax, to be carried forward. The backward is identical
    to :func:`int8_dense`'s (the saved residuals record the scale actually
    used); ``amax_prev`` gets a zero cotangent (scales are constants under
    the STE, exactly as the dynamic path treats its fresh scales).
    """
    return _delayed_quantized_dot(x, kernel, amax_prev, n_contract)[:2]


def _int8_dense_delayed_fwd(x, kernel, amax_prev, n_contract, mode):
    y, new_amax, (xq, scale, wq, sw) = _delayed_quantized_dot(
        x, kernel, amax_prev, n_contract
    )
    sent = (jnp.zeros((0,), x.dtype), jnp.zeros((0,), kernel.dtype))
    return (y, new_amax), (xq, scale, wq, sw, sent)


def _int8_dense_delayed_bwd(n_contract, mode, res, cts):
    dy, _d_amax = cts  # new_amax is an observation, not a differentiable path
    dx, dw = _int8_dense_bwd(n_contract, mode, res, dy)
    return dx, dw, jnp.zeros((), jnp.float32)


int8_dense_delayed.defvjp(_int8_dense_delayed_fwd, _int8_dense_delayed_bwd)


# ------------------------------------- delayed scaling for the BACKWARD
#
# "full" mode still quantizes dy DYNAMICALLY in the backward: two absmax
# reduce-to-scalar passes over dy per site per microbatch (one for the
# sw-folded dy that feeds dx, one for raw dy feeding dw) — the same
# serialization shape delayed activation scaling removed from the forward.
# Carrying dy amaxes needs a channel OUT of the backward, and gradients
# only leave a custom_vjp through cotangent slots: each site therefore
# takes a zero-valued ``dy_sink`` input (shape [2]) whose COTANGENT the
# backward sets to the observed [amax(dy_scaled), amax(dy)]. A caller
# that differentiates w.r.t. the sinks reads next-microbatch dy scales
# out of the sink gradients and carries them exactly like the forward
# amaxes. The forward result is bit-identical to int8_dense_delayed; only
# the backward's dy quantization scales differ (previous-microbatch
# observations, saturating at ±127 for one microbatch when dy outgrows
# them — the standard delayed-scaling contract).


import threading as _threading  # noqa: E402
import contextlib as _contextlib  # noqa: E402

_DY_CAL = _threading.local()


@_contextlib.contextmanager
def dy_calibration_mode():
    """Trace-time switch for :func:`int8_dense_delayed_grads`: inside this
    context the BACKWARD quantizes dy with fresh DYNAMIC scales (while
    still reporting observations through the sinks). Needed exactly once,
    for calibration: with zero carried dy amaxes every downstream site
    would otherwise differentiate through saturated garbage cotangents
    and record garbage observations (train/step.py::calibrate_quant)."""
    _DY_CAL.on = True
    try:
        yield
    finally:
        _DY_CAL.on = False


def _delayed_grads_core(x, kernel, amax_prev, dy_amaxes, dy_sink, n_contract):
    y, new_amax, res = _delayed_quantized_dot(
        x, kernel, amax_prev, n_contract
    )
    # 0.0 * sum(dy_sink) makes the sink a true input of the primal, so
    # its cotangent slot exists; XLA folds the zero away.
    y = y + (0.0 * jnp.sum(dy_sink)).astype(y.dtype)
    return y, new_amax, res


@partial(jax.custom_vjp, nondiff_argnums=(5, 6))
def int8_dense_delayed_grads(x, kernel, amax_prev, dy_amaxes, dy_sink,
                             n_contract: int = 1, calibrate: bool = False):
    """:func:`int8_dense_delayed` with DELAYED dy scales in the backward.

    ``dy_amaxes``: fp32 [2] — carried amaxes of (sw-folded dy, raw dy)
    from this site's previous microbatch. ``dy_sink``: fp32 [2] zeros;
    differentiate w.r.t. it and the gradient IS the current microbatch's
    observed [amax(dy_scaled), amax(dy)], to be carried forward.
    ``calibrate=True`` (bound from :func:`dy_calibration_mode` at trace
    time) switches the backward to fresh dynamic dy scales.
    Backward matmul layout matches ``mode="full"`` of :func:`int8_dense`.
    """
    return _delayed_grads_core(
        x, kernel, amax_prev, dy_amaxes, dy_sink, n_contract
    )[:2]


def _int8_dense_delayed_grads_fwd(x, kernel, amax_prev, dy_amaxes, dy_sink,
                                  n_contract, calibrate):
    y, new_amax, (xq, scale, wq, sw) = _delayed_grads_core(
        x, kernel, amax_prev, dy_amaxes, dy_sink, n_contract
    )
    sent = (jnp.zeros((0,), x.dtype), jnp.zeros((0,), kernel.dtype))
    return (y, new_amax), (xq, scale, wq, sw, dy_amaxes, sent)


def _int8_dense_delayed_grads_bwd(n_contract, calibrate, res, cts):
    dy, _d_amax = cts
    xq, sx, wq, sw, dy_amaxes, sent = res
    x_dtype, w_dtype = sent[0].dtype, sent[1].dtype
    dy_scaled = dy.astype(jnp.float32) * sw
    obs0 = _absmax(dy_scaled, axes=None, keepdims=False)
    obs1 = _absmax(dy, axes=None, keepdims=False)
    if calibrate:
        # dynamic scales: exact magnitudes even when every carried amax
        # is still zero — the one-pass calibration path
        s0, s1 = obs0 / _INT8_MAX, obs1 / _INT8_MAX
    else:
        # carried scales: no absmax dependency before the quantize pass
        # (the whole point — the reduce overlaps the dots)
        s0 = jnp.maximum(dy_amaxes[0], 1e-12) / _INT8_MAX
        s1 = jnp.maximum(dy_amaxes[1], 1e-12) / _INT8_MAX
    dx, dw = _full_mode_grad_dots(
        xq, sx, wq, dy_scaled, dy, s0, s1, n_contract, x_dtype, w_dtype
    )
    return (
        dx,
        dw,
        jnp.zeros((), jnp.float32),   # amax_prev: constant under STE
        jnp.zeros((2,), jnp.float32),  # dy_amaxes: constants too
        jnp.stack([obs0, obs1]),  # observations leave via the sink slot
    )


int8_dense_delayed_grads.defvjp(
    _int8_dense_delayed_grads_fwd, _int8_dense_delayed_grads_bwd
)


# ------------------------------------------- serving (weight-only int8)
#
# The serve engine stores matmul weights as int8 + fp32 per-output-channel
# scales and dequantizes INSIDE its jitted programs (a broadcast multiply
# that XLA fuses into the matmul's operand read) — resident weight bytes
# halve while every activation and accumulation stays in the compute
# dtype. Unlike the train path above there is no dynamic activation
# quantization: this is the LLM.int8/AWQ-style weight-only layout, chosen
# because serving batches are small enough that weights dominate HBM.
#
# Scales keep their contracted axes as size-1 dims (keepdims) so (a) the
# dequant is a plain broadcast multiply and (b) under tensor parallelism
# the scale shards with the SAME partition spec as its kernel wherever the
# kernel's sharded axis survives in the scale (parallel/sharding.py nulls
# the size-1 axes).

# serve modules whose kernels quantize -> number of leading contracted
# kernel axes (DenseGeneral layout: [*contracted, *features]); embeddings,
# layer norms, biases and the tied LM head stay in param dtype
_SERVE_QUANT_MODULES = {
    "query": 1, "key": 1, "value": 1, "out": 2,
    "mlp_up": 1, "mlp_down": 1,
}


def quantize_kernel(kernel, n_contract: int):
    """→ (int8 kernel, fp32 per-output-channel scale with the contracted
    axes kept as size-1 dims). ``kernel ≈ q.astype(f32) * scale``."""
    axes = tuple(range(n_contract))
    scale = _absmax(kernel, axes=axes, keepdims=True) / _INT8_MAX
    return _quantize(kernel, scale), scale


def quantize_serve_params(params):
    """Weight-only int8 variant of a serve params tree.

    Every attention/MLP projection kernel (``_SERVE_QUANT_MODULES``)
    becomes int8 with a sibling ``kernel_scale`` fp32 leaf; everything
    else passes through untouched. Idempotent: an already-quantized tree
    is returned as-is, so swap paths can call it unconditionally."""
    def walk(node, name):
        if not isinstance(node, dict):
            return node
        if name in _SERVE_QUANT_MODULES and "kernel" in node:
            out = dict(node)
            kernel = out["kernel"]
            if kernel.dtype == jnp.int8:
                return out
            q, scale = quantize_kernel(kernel, _SERVE_QUANT_MODULES[name])
            out["kernel"] = q
            out["kernel_scale"] = scale
            return out
        return {k: walk(v, k) for k, v in node.items()}

    return walk(dict(params), "")


def dequantize_serve_params(params):
    """Inverse of :func:`quantize_serve_params`: rebuild the fp32 tree by
    broadcasting each ``kernel_scale`` back over its int8 kernel (the
    scale leaf is dropped). A tree without scales passes through — the
    jitted programs call this unconditionally as their first op."""
    def walk(node):
        if not isinstance(node, dict):
            return node
        if "kernel_scale" in node:
            out = {k: walk(v) for k, v in node.items()
                   if k != "kernel_scale"}
            out["kernel"] = (
                node["kernel"].astype(jnp.float32) * node["kernel_scale"]
            )
            return out
        return {k: walk(v) for k, v in node.items()}

    return walk(params)


def serve_params_variant(params) -> str:
    """``"int8"`` when the tree carries quantized serve kernels (any
    ``kernel_scale`` leaf), else ``"fp32"`` — how swap/publish paths
    detect which precision variant a weight tree is."""
    found = []

    def walk(node):
        if isinstance(node, dict):
            if "kernel_scale" in node:
                found.append(True)
            for v in node.values():
                walk(v)

    walk(params)
    return "int8" if found else "fp32"


def quantize_kv(values, axis: int = -1):
    """Symmetric int8 quantization of K/V page writes: one fp32 scale per
    everything-but-``axis`` (the head_dim axis reduces away). → (int8
    values, fp32 scales with ``axis`` dropped)."""
    scale = _absmax(values, axes=axis, keepdims=True) / _INT8_MAX
    return _quantize(values, scale), jnp.squeeze(scale, axis=axis)


def int8_matmul(x2d, w2d, mode: str = "fwd"):
    """2-D convenience wrapper over :func:`int8_dense` ([T,K]·[K,N])."""
    return int8_dense(x2d, w2d, 1, mode)


def quant_dense_apply(x, kernel, bias, *, n_contract: int, mode: str,
                      out_dtype):
    """DenseGeneral-compatible apply through the int8 path.

    ``x``: [..., c1..cn] with the last ``n_contract`` axes contracted;
    ``kernel``: [c1..cn, f1..fm]; ``bias``: [f1..fm] or None. Contraction
    happens on the native shapes (see ``_quantized_dot`` on why there is
    deliberately no 2-D reshape here).
    """
    y = int8_dense(x, kernel, n_contract, mode).astype(out_dtype)
    if bias is not None:
        y = y + bias.astype(out_dtype)
    return y


# --------------------------------------------------------------------- flax
import flax.linen as nn  # noqa: E402  (module-level layer, keeps parity with
#                          ops/layer_norm.py's FusedDropoutAddLayerNorm home)


class QuantDenseGeneral(nn.Module):
    """Drop-in ``nn.DenseGeneral`` running its matmul on the int8 MXU path.

    Parameter names/shapes/init are IDENTICAL to ``nn.DenseGeneral``
    (kernel = [*contracted input dims, *features], bias = [*features]) so
    checkpoints and the HF weight loader are layout-agnostic: a model can
    be trained int8 and evaluated bf16 or vice versa by flipping
    ``ModelConfig.matmul_impl`` alone.

    ``delayed=True`` switches the activation scale to delayed (previous
    microbatch) amax carried in the ``"quant"`` variable collection — see
    the module docstring. Callers must apply with ``mutable=["quant"]``
    during training (the train step threads the collection through its
    accumulation scan) and calibrate once before step 0.
    """

    features: tuple  # output feature dims (tuple, possibly length 1)
    axis: tuple = (-1,)  # contracted input axes
    mode: str = "fwd"  # int8_matmul mode: "fwd" | "full"
    delayed: bool = False  # delayed activation scaling via "quant" collection
    delayed_grads: bool = False  # ...and delayed dy scaling in the backward
    use_bias: bool = True
    dtype: object = jnp.bfloat16
    param_dtype: object = jnp.float32
    kernel_init: object = nn.initializers.lecun_normal()
    bias_init: object = nn.initializers.zeros_init()

    @nn.compact
    def __call__(self, x):
        axis = tuple(a % x.ndim for a in self.axis)
        if axis != tuple(range(x.ndim - len(axis), x.ndim)):
            raise ValueError(
                f"QuantDenseGeneral contracts trailing axes only, got {self.axis}"
            )
        in_shape = tuple(x.shape[a] for a in axis)
        kernel = self.param(
            "kernel", self.kernel_init, (*in_shape, *self.features),
            self.param_dtype,
        )
        bias = (
            self.param("bias", self.bias_init, self.features, self.param_dtype)
            if self.use_bias
            else None
        )
        if self.delayed:
            amax = self.variable(
                "quant", "amax", lambda: jnp.zeros((), jnp.float32)
            )
            if self.delayed_grads:
                if self.mode != "full":
                    raise ValueError(
                        "delayed_grads implements the 'full' backward "
                        f"layout only (got mode={self.mode!r})"
                    )
                # carried dy amaxes live beside the fwd amax; the fresh
                # observations return through the SINK's gradient — the
                # train step differentiates w.r.t. the "quant_sink"
                # collection and merges them back (train/step.py)
                dy_amax = self.variable(
                    "quant", "dy_amax", lambda: jnp.zeros((2,), jnp.float32)
                )
                sink = self.variable(
                    "quant_sink", "sink",
                    lambda: jnp.zeros((2,), jnp.float32),
                )
                y, new_amax = int8_dense_delayed_grads(
                    x, kernel, amax.value, dy_amax.value, sink.value,
                    len(axis),
                    # trace-time bind: inside dy_calibration_mode() the
                    # backward uses fresh dynamic dy scales
                    getattr(_DY_CAL, "on", False),
                )
            else:
                y, new_amax = int8_dense_delayed(
                    x, kernel, amax.value, len(axis), self.mode
                )
            # init + every mutable apply observe the current amax; an
            # immutable apply (a caller that forgot mutable=["quant"]) keeps
            # the stale value rather than erroring — eval reuses training's
            # last scales that way.
            if self.is_mutable_collection("quant"):
                amax.value = new_amax
            y = y.astype(self.dtype)
            if bias is not None:
                y = y + bias.astype(self.dtype)
            return y
        return quant_dense_apply(
            x, kernel, bias, n_contract=len(axis), mode=self.mode,
            out_dtype=self.dtype,
        )
