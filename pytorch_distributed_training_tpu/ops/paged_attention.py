"""Paged decode attention: attention that gathers K/V through a block table
over fixed-size pages (vLLM PagedAttention layout).

Shapes
------
- ``q``:          [batch, heads, head_dim] — ONE query token per sequence
                  (the classic decode step), or
                  [batch, q_len, heads, head_dim] — a multi-token query block
                  (speculative verify / chunked prefill). The q_len tokens
                  are the LAST q_len positions of the sequence and attend
                  causally: query row ``j`` sees positions
                  ``< lengths - q_len + 1 + j``.
- ``k_pages``/``v_pages``: [num_pages, page_size, heads, head_dim] — the
                  engine-resident page pools. Page 0 is the reserved null
                  page (see serve/paged_cache.py); idle sequences park their
                  block table on it.
- ``block_table``: [batch, pages_per_seq] int32 — page ids per sequence, in
                  token order; entries past the live length point at page 0.
- ``lengths``:    [batch] int32 — valid tokens per sequence INCLUSIVE of all
                  query tokens (the engine writes the new K/V before
                  attending, so positions ``lengths-q_len .. lengths-1`` are
                  the query block itself). ``lengths >= q_len`` is an engine
                  contract: every query row has at least one visible token.

Two implementations behind one signature:

- ``impl="reference"``: XLA gather + the exact einsum/softmax formula of the
  dense flax cache path (models/bert.py ``_cached_attend``). Masked lanes go
  to ``finfo.min`` so their exp underflows to an exact 0.0 in fp32; paged
  output is therefore token-identical to the dense cache whatever the pool
  geometry (same argument that pins slotted serve to one-shot generate).
- ``impl="pallas"``: an online-softmax page-walk kernel — grid (batch,
  pages_per_seq), block table scalar-prefetched so each grid step's
  ``index_map`` streams exactly one page of K/V into VMEM, running
  max/denominator/accumulator rescaled per page, output written on the last
  page. ``interpret=`` falls back to the Pallas interpreter off-TPU (same
  ``tpu_interpret_mode()`` contract as ops/flash_attention.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pytorch_distributed_training_tpu.ops.flash_attention import _interpreting

_NEG_INF = jnp.finfo(jnp.float32).min


_SCALE_AXES = ("num_pages", "page_size", "heads")


def _check_scale_pool(pool_name, pool, scale_name, scales):
    """Trace-time contract between an int8 page pool and its scale pool,
    in the named-axis error style: int8 pools REQUIRE fp32 scales of shape
    [num_pages, page_size, heads]; float pools must not carry scales."""
    if pool.dtype == jnp.int8:
        want = pool.shape[:3]
        if scales is None:
            raise ValueError(
                f"{pool_name} is int8 but {scale_name} is missing: int8 "
                f"pools require fp32 per-page-per-head scales of shape "
                f"(num_pages, page_size, heads) = {want}"
            )
        if scales.ndim != 3:
            raise ValueError(
                f"{scale_name} must be [num_pages, page_size, heads]: got "
                f"shape {scales.shape} (rank {scales.ndim}, want 3)"
            )
        if tuple(scales.shape) != want:
            bad = ", ".join(
                f"{name} (axis {i}): got {g}, want {w}"
                for i, (name, g, w) in enumerate(
                    zip(_SCALE_AXES, scales.shape, want)
                )
                if g != w
            )
            raise ValueError(
                f"{scale_name} shape mismatch on {bad} (got {scales.shape},"
                f" want {want} from {pool_name})"
            )
        if scales.dtype != jnp.float32:
            raise ValueError(
                f"{scale_name} must be float32, got {scales.dtype}"
            )
    elif scales is not None:
        raise ValueError(
            f"{scale_name} provided but {pool_name} dtype is "
            f"{pool.dtype}: scale pools accompany int8 pages only"
        )


def paged_attention(
    q: jax.Array,
    k_pages: jax.Array,
    v_pages: jax.Array,
    block_table: jax.Array,
    lengths: jax.Array,
    *,
    scale: float,
    impl: str = "reference",
    k_scales: jax.Array | None = None,
    v_scales: jax.Array | None = None,
) -> jax.Array:
    """Attention through a page table. 3-D ``q`` is the single-token decode
    step (returns [batch, heads, head_dim]); 4-D ``q`` is a causal
    multi-token query block (returns [batch, q_len, heads, head_dim]).
    Output dtype is ``v_pages.dtype`` (the dense path's output dtype) —
    except for int8 pools, whose output is fp32 (the dequantized compute
    dtype). int8 pools carry fp32 ``k_scales``/``v_scales`` pools of shape
    [num_pages, page_size, heads]; both impls dequantize in-kernel
    (``page.astype(f32) * scale`` per head lane)."""
    if q.ndim not in (3, 4):
        raise ValueError(
            f"q must be [batch, heads, head_dim] or "
            f"[batch, q_len, heads, head_dim], got {q.shape}"
        )
    pool_axes = ("num_pages", "page_size", "heads", "head_dim")
    if k_pages.shape != v_pages.shape:
        bad = ", ".join(
            f"{name} (axis {i}): k_pages={ks} vs v_pages={vs}"
            for i, (name, ks, vs) in enumerate(
                zip(pool_axes, k_pages.shape, v_pages.shape)
            )
            if ks != vs
        ) or f"rank: k_pages={k_pages.ndim} vs v_pages={v_pages.ndim}"
        raise ValueError(
            f"k_pages/v_pages shapes differ on {bad} "
            f"(full shapes {k_pages.shape} vs {v_pages.shape})"
        )
    # q's trailing [heads, head_dim] must match the pools — the axis pair
    # that goes wrong first when heads shard over a tensor-parallel mesh
    # and one side of the call still sees the unsharded width
    for name, q_dim, pool_dim in (
        ("heads", q.shape[-2], k_pages.shape[2]),
        ("head_dim", q.shape[-1], k_pages.shape[3]),
    ):
        if q_dim != pool_dim:
            raise ValueError(
                f"q/pool mismatch on axis {name!r}: q has {q_dim}, "
                f"k_pages/v_pages have {pool_dim} (q {q.shape}, pools "
                f"{k_pages.shape})"
            )
    if block_table.ndim != 2 or block_table.shape[0] != q.shape[0]:
        raise ValueError(
            f"block_table must be [batch, pages_per_seq]: got shape "
            f"{block_table.shape} (rank {block_table.ndim}, want 2; axis "
            f"'batch' got {block_table.shape[0] if block_table.ndim else '-'}"
            f", want {q.shape[0]} from q)"
        )
    if lengths.shape != (q.shape[0],):
        raise ValueError(
            f"lengths must be [batch]: got shape {lengths.shape}, want "
            f"({q.shape[0]},) (axis 'batch' from q)"
        )
    if k_pages.dtype != v_pages.dtype:
        raise ValueError(
            f"k_pages/v_pages dtypes differ: {k_pages.dtype} vs "
            f"{v_pages.dtype} (pools quantize together or not at all)"
        )
    _check_scale_pool("k_pages", k_pages, "k_scales", k_scales)
    _check_scale_pool("v_pages", v_pages, "v_scales", v_scales)
    scales = (k_scales, v_scales)
    if q.ndim == 4:
        if impl == "reference":
            return _paged_reference_mq(
                q, k_pages, v_pages, block_table, lengths, scale, *scales
            )
        if impl == "pallas":
            return _paged_pallas_mq(
                q, k_pages, v_pages, block_table, lengths, scale, *scales
            )
        raise ValueError(f"unknown paged attention impl {impl!r}")
    if impl == "reference":
        return _paged_reference(
            q, k_pages, v_pages, block_table, lengths, scale, *scales
        )
    if impl == "pallas":
        return _paged_pallas(
            q, k_pages, v_pages, block_table, lengths, scale, *scales
        )
    raise ValueError(f"unknown paged attention impl {impl!r}")


# ---------------------------------------------------------------- reference


def _gather_dequant(pages, scales, block_table, batch, tokens, heads,
                    head_dim):
    """Gather pages through the block table ([B, W, P, H, D] → [B, T, H, D])
    and, for int8 pools, dequantize against the identically-gathered scale
    pool (one fp32 scale per token per head)."""
    x = pages[block_table].reshape(batch, tokens, heads, head_dim)
    if scales is None:
        return x
    s = scales[block_table].reshape(batch, tokens, heads)
    return x.astype(jnp.float32) * s[..., None]


def _paged_reference(q, k_pages, v_pages, block_table, lengths, scale,
                     k_scales=None, v_scales=None):
    batch, heads, head_dim = q.shape
    _, page_size, _, _ = k_pages.shape
    windows = block_table.shape[1]

    # Gather the full (padded) context per sequence: [B, W, P, H, D] →
    # [B, W*P, H, D]. Token order is page order × in-page offset, which is
    # exactly how serve/paged_cache.py lays tokens out.
    tokens = windows * page_size
    k = _gather_dequant(
        k_pages, k_scales, block_table, batch, tokens, heads, head_dim
    )
    v = _gather_dequant(
        v_pages, v_scales, block_table, batch, tokens, heads, head_dim
    )

    # Same contraction/softmax formula as the dense cache attend (fp32
    # scores, finfo.min mask, fp32 softmax, probs cast to V dtype) so the
    # two layouts stay bitwise-comparable on the valid lanes.
    scores = (
        jnp.einsum("bnd,btnd->bnt", q, k, preferred_element_type=jnp.float32)
        * scale
    )
    pos = jax.lax.broadcasted_iota(jnp.int32, (batch, windows * page_size), 1)
    valid = pos < lengths[:, None]
    scores = jnp.where(valid[:, None, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(v.dtype)
    return jnp.einsum("bnt,btnd->bnd", probs, v)


# ------------------------------------------------------------------- pallas


def _paged_kernel(
    bt_ref,  # scalar-prefetch: [B, W] int32
    len_ref,  # scalar-prefetch: [B] int32
    q_ref,  # [1, H, D]
    k_ref,  # [1, P, H, D] — the page selected by index_map for this step
    v_ref,  # [1, P, H, D]
    *refs,  # [ks_ref, vs_ref (int8 pools only)], o_ref, m/l/acc scratch
    scale: float,
    page_size: int,
    windows: int,
    quantized: bool,
):
    if quantized:
        # ks/vs: [1, P, H] fp32 — the scale page walked in lockstep with
        # its K/V page through the same block-table index_map
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = refs
    else:
        ks_ref = vs_ref = None
        o_ref, m_ref, l_ref, acc_ref = refs
    b = pl.program_id(0)
    w = pl.program_id(1)
    length = len_ref[b]

    @pl.when(w == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -jnp.inf)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # Pages wholly past the live length carry no valid tokens (their block
    # table entries are the null page): skip the whole online-softmax step.
    @pl.when(w * page_size < length)
    def _compute():
        q = q_ref[0].astype(jnp.float32)  # [H, D]
        k = k_ref[0].astype(jnp.float32)  # [P, H, D]
        v = v_ref[0].astype(jnp.float32)  # [P, H, D]
        if quantized:
            # in-kernel dequant: one fp32 scale per (token, head) lane
            k = k * ks_ref[0][..., None]
            v = v * vs_ref[0][..., None]

        # [H, P]: batch over heads (q dim 0 / k dim 1), contract head_dim.
        s = (
            jax.lax.dot_general(
                q, k, (((1,), (2,)), ((0,), (1,))),
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        pos = w * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(pos < length, s, _NEG_INF)

        m_prev = m_ref[...][:, :1]  # [H, 1]
        l_prev = l_ref[...][:, :1]
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)  # [H, P]
        l_new = alpha * l_prev + jnp.sum(p, axis=1, keepdims=True)
        # [H, D]: batch over heads (p dim 0 / v dim 1), contract page lanes.
        pv = jax.lax.dot_general(
            p, v, (((1,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(w == windows - 1)
    def _write():
        # length >= 1 by engine contract, so l > 0; the where only shields
        # the all-masked degenerate case from producing NaN.
        l = l_ref[...][:, :1]
        l = jnp.where(l > 0.0, l, 1.0)
        o_ref[0] = (acc_ref[...] / l).astype(o_ref.dtype)


_LANES = 128


def _page_walk_specs(page_size, heads, head_dim, quantized):
    """K/V (and, for int8 pools, scale-pool) BlockSpecs: one page per grid
    step, chosen through the prefetched block table — this is the whole
    point of the layout: the gather happens in the index_map, not in
    HBM-wasting XLA. Scale pages walk through the SAME index_map so a
    token's values and its scales always arrive together."""
    page = pl.BlockSpec(
        (1, page_size, heads, head_dim),
        lambda b, w, bt, ln: (bt[b, w], 0, 0, 0),
    )
    specs = [page, page]
    if quantized:
        scale_page = pl.BlockSpec(
            (1, page_size, heads),
            lambda b, w, bt, ln: (bt[b, w], 0, 0),
        )
        specs += [scale_page, scale_page]
    return specs


def _paged_pallas(q, k_pages, v_pages, block_table, lengths, scale,
                  k_scales=None, v_scales=None):
    batch, heads, head_dim = q.shape
    _, page_size, _, _ = k_pages.shape
    windows = block_table.shape[1]
    quantized = k_scales is not None

    operands = [block_table, lengths, q, k_pages, v_pages]
    if quantized:
        operands += [k_scales, v_scales]
    out = pl.pallas_call(
        functools.partial(
            _paged_kernel,
            scale=scale,
            page_size=page_size,
            windows=windows,
            quantized=quantized,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(batch, windows),
            in_specs=[
                pl.BlockSpec((1, heads, head_dim), lambda b, w, bt, ln: (b, 0, 0)),
                *_page_walk_specs(page_size, heads, head_dim, quantized),
            ],
            out_specs=pl.BlockSpec(
                (1, heads, head_dim), lambda b, w, bt, ln: (b, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((heads, _LANES), jnp.float32),
                pltpu.VMEM((heads, _LANES), jnp.float32),
                pltpu.VMEM((heads, head_dim), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct(
            q.shape, jnp.float32 if quantized else v_pages.dtype
        ),
        interpret=_interpreting(),
    )(*operands)
    return out


# ------------------------------------------------- multi-token query block
#
# Shared by speculative verify (q_len = k+1 candidate tokens) and chunked
# prefill (q_len = chunk tokens appended to an existing context). The query
# block occupies the LAST q_len positions of the sequence, so row j's causal
# horizon is ``pos < lengths - q_len + 1 + j``. With q_len == 1 this reduces
# to the single-query mask above; the 3-D paths are kept verbatim so the
# decode-step numerics (and their token-identity pins) cannot move.


def _paged_reference_mq(q, k_pages, v_pages, block_table, lengths, scale,
                        k_scales=None, v_scales=None):
    batch, q_len, heads, head_dim = q.shape
    _, page_size, _, _ = k_pages.shape
    windows = block_table.shape[1]

    tokens = windows * page_size
    k = _gather_dequant(
        k_pages, k_scales, block_table, batch, tokens, heads, head_dim
    )
    v = _gather_dequant(
        v_pages, v_scales, block_table, batch, tokens, heads, head_dim
    )

    scores = (
        jnp.einsum("bqnd,btnd->bnqt", q, k, preferred_element_type=jnp.float32)
        * scale
    )
    pos = jax.lax.broadcasted_iota(jnp.int32, (batch, q_len, windows * page_size), 2)
    row = jax.lax.broadcasted_iota(jnp.int32, (batch, q_len, windows * page_size), 1)
    limit = lengths[:, None, None] - (q_len - 1) + row
    valid = pos < limit
    scores = jnp.where(valid[:, None, :, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(v.dtype)
    return jnp.einsum("bnqt,btnd->bqnd", probs, v)


def _paged_kernel_mq(
    bt_ref,  # scalar-prefetch: [B, W] int32
    len_ref,  # scalar-prefetch: [B] int32
    q_ref,  # [1, Q, H, D]
    k_ref,  # [1, P, H, D]
    v_ref,  # [1, P, H, D]
    *refs,  # [ks_ref, vs_ref (int8 pools only)], o_ref, m/l/acc scratch
    scale: float,
    page_size: int,
    windows: int,
    q_len: int,
    quantized: bool,
):
    if quantized:
        ks_ref, vs_ref, o_ref, m_ref, l_ref, acc_ref = refs
    else:
        ks_ref = vs_ref = None
        o_ref, m_ref, l_ref, acc_ref = refs
    b = pl.program_id(0)
    w = pl.program_id(1)
    length = len_ref[b]

    @pl.when(w == 0)
    def _init():
        # finfo.min, NOT -inf: a computed page can be fully masked for the
        # earliest query rows (their causal horizon ends before the page),
        # and exp(-inf - -inf) would NaN-poison the rescale. With a finite
        # floor the masked-row algebra stays exact: p is where()-zeroed, so
        # l stays 0 until the first visible token.
        m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # The last valid token overall sits at length-1 (row q_len-1's horizon),
    # so pages at or past `length` carry nothing for any row.
    @pl.when(w * page_size < length)
    def _compute():
        q = q_ref[0].astype(jnp.float32)  # [Q, H, D]
        k = k_ref[0].astype(jnp.float32)  # [P, H, D]
        v = v_ref[0].astype(jnp.float32)  # [P, H, D]
        if quantized:
            k = k * ks_ref[0][..., None]
            v = v * vs_ref[0][..., None]

        # [H, Q, P]: batch over heads (q dim 1 / k dim 1), contract head_dim.
        s = (
            jax.lax.dot_general(
                q, k, (((2,), (2,)), ((1,), (1,))),
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        pos = w * page_size + jax.lax.broadcasted_iota(jnp.int32, s.shape, 2)
        row = jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        valid = pos < length - (q_len - 1) + row
        s = jnp.where(valid, s, _NEG_INF)

        m_prev = m_ref[...][:, :, :1]  # [H, Q, 1]
        l_prev = l_ref[...][:, :, :1]
        m_cur = jnp.max(s, axis=2, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        # where(), not bare exp: on an all-masked row m_new == _NEG_INF and
        # exp(s - m_new) would be exp(0) == 1 per lane.
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)  # [H, Q, P]
        l_new = alpha * l_prev + jnp.sum(p, axis=2, keepdims=True)
        # [H, Q, D]: batch over heads (p dim 0 / v dim 1), contract lanes.
        pv = jax.lax.dot_general(
            p, v, (((2,), (0,)), ((0,), (1,))),
            preferred_element_type=jnp.float32,
        )
        acc_ref[...] = acc_ref[...] * alpha + pv
        m_ref[...] = jnp.broadcast_to(m_new, m_ref.shape)
        l_ref[...] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(w == windows - 1)
    def _write():
        l = l_ref[...][:, :, :1]
        l = jnp.where(l > 0.0, l, 1.0)
        out = acc_ref[...] / l  # [H, Q, D]
        o_ref[0] = jnp.transpose(out, (1, 0, 2)).astype(o_ref.dtype)


def _paged_pallas_mq(q, k_pages, v_pages, block_table, lengths, scale,
                     k_scales=None, v_scales=None):
    batch, q_len, heads, head_dim = q.shape
    _, page_size, _, _ = k_pages.shape
    windows = block_table.shape[1]
    quantized = k_scales is not None

    operands = [block_table, lengths, q, k_pages, v_pages]
    if quantized:
        operands += [k_scales, v_scales]
    out = pl.pallas_call(
        functools.partial(
            _paged_kernel_mq,
            scale=scale,
            page_size=page_size,
            windows=windows,
            q_len=q_len,
            quantized=quantized,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(batch, windows),
            in_specs=[
                pl.BlockSpec(
                    (1, q_len, heads, head_dim),
                    lambda b, w, bt, ln: (b, 0, 0, 0),
                ),
                *_page_walk_specs(page_size, heads, head_dim, quantized),
            ],
            out_specs=pl.BlockSpec(
                (1, q_len, heads, head_dim), lambda b, w, bt, ln: (b, 0, 0, 0)
            ),
            scratch_shapes=[
                pltpu.VMEM((heads, q_len, _LANES), jnp.float32),
                pltpu.VMEM((heads, q_len, _LANES), jnp.float32),
                pltpu.VMEM((heads, q_len, head_dim), jnp.float32),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct(
            q.shape, jnp.float32 if quantized else v_pages.dtype
        ),
        interpret=_interpreting(),
    )(*operands)
    return out
