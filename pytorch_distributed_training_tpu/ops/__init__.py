from pytorch_distributed_training_tpu.ops.attention import (
    ATTENTION_IMPLS,
    dot_product_attention,
)
from pytorch_distributed_training_tpu.ops.quant import (
    QuantDenseGeneral,
    int8_dense,
    int8_matmul,
)

__all__ = [
    "ATTENTION_IMPLS",
    "QuantDenseGeneral",
    "dot_product_attention",
    "int8_dense",
    "int8_matmul",
]
