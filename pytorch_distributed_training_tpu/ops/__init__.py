from pytorch_distributed_training_tpu.ops.attention import (
    ATTENTION_IMPLS,
    dot_product_attention,
)

__all__ = ["ATTENTION_IMPLS", "dot_product_attention"]
