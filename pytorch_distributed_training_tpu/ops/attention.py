"""Attention implementations behind one swappable interface.

The reference has no attention code of its own — it rides HF BERT's
(reference test_data_parallelism.py:112). Here attention is a first-class,
swappable op (SURVEY.md §5 long-context: "keep attention swappable (Pallas
flash-attention kernel slot) so CP can be added later without core changes"):

- ``"reference"`` — plain XLA einsum attention. Scores/softmax accumulate in
  fp32 even under the bf16 policy (TPU MXU accumulates fp32 natively; this
  is the numerically-safe default).
- ``"flash"``     — Pallas (Mosaic) fused attention kernel, registered by
  ``ops.flash_attention``.
- ``"ring"``      — ring attention over a sequence-parallel mesh axis,
  registered by ``ops.ring_attention``.

All implementations share the signature
``impl(q, k, v, bias, *, dropout_rng, dropout_rate, deterministic, causal,
dropout_impl)`` with q/k/v shaped [batch, seq, heads, head_dim] and an
additive fp32 bias broadcastable to [batch, heads, q_len, kv_len].
``dropout_impl`` selects the probs-mask generator (ops/dropout.py) for the
impls that generate masks in XLA; the Pallas flash kernel's in-kernel
per-core PRNG is its own generator and ignores it.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from pytorch_distributed_training_tpu.ops.dropout import raw_dropout

ATTENTION_IMPLS: dict[str, Callable] = {}


def register_attention(name: str):
    def deco(fn):
        ATTENTION_IMPLS[name] = fn
        return fn

    return deco


def make_attention_bias(
    attention_mask: Optional[jnp.ndarray],
    *,
    dtype=jnp.float32,
) -> Optional[jnp.ndarray]:
    """[batch, kv_len] 1/0 mask → additive bias [batch, 1, 1, kv_len]."""
    if attention_mask is None:
        return None
    neg = jnp.finfo(dtype).min
    bias = jnp.where(attention_mask[:, None, None, :] > 0, 0.0, neg)
    return bias.astype(dtype)


def causal_bias(q_len: int, kv_len: int, dtype=jnp.float32) -> jnp.ndarray:
    i = jax.lax.broadcasted_iota(jnp.int32, (q_len, kv_len), 0)
    j = jax.lax.broadcasted_iota(jnp.int32, (q_len, kv_len), 1)
    neg = jnp.finfo(dtype).min
    return jnp.where(j <= i, 0.0, neg).astype(dtype)[None, None, :, :]


@register_attention("reference")
def reference_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: Optional[jnp.ndarray] = None,
    *,
    dropout_rng=None,
    dropout_rate: float = 0.0,
    deterministic: bool = True,
    causal: bool = False,
    dropout_impl: str = "exact",
):
    """Plain einsum attention; softmax in fp32 regardless of input dtype."""
    head_dim = q.shape[-1]
    scale = head_dim ** -0.5
    # [B, S, N, D] x [B, T, N, D] -> [B, N, S, T], accumulated in fp32
    scores = jnp.einsum(
        "bsnd,btnd->bnst", q, k, preferred_element_type=jnp.float32
    ) * scale
    if bias is not None:
        scores = scores + bias.astype(jnp.float32)
    if causal:
        scores = scores + causal_bias(q.shape[-3], k.shape[-3])
    probs = jax.nn.softmax(scores, axis=-1)
    if not deterministic and dropout_rate > 0.0:
        if dropout_impl == "exact":
            # flax-parity order: mask the fp32 probs, then cast
            probs = raw_dropout(probs, dropout_rate, dropout_rng, dropout_impl)
            probs = probs.astype(v.dtype)
        else:
            # bf16-policy order: cast first so the dropout mask residual is
            # half-width (a custom-vjp softmax that also rounds the probs
            # residual to bf16 measured SLOWER — XLA's own fused softmax
            # backward beats the hand-written ds formula; NOTES.md)
            probs = probs.astype(v.dtype)
            probs = raw_dropout(probs, dropout_rate, dropout_rng, dropout_impl)
    else:
        probs = probs.astype(v.dtype)
    return jnp.einsum("bnst,btnd->bsnd", probs, v)


def dot_product_attention(
    q,
    k,
    v,
    bias=None,
    *,
    impl: str = "reference",
    dropout_rng=None,
    dropout_rate: float = 0.0,
    deterministic: bool = True,
    causal: bool = False,
    dropout_impl: str = "exact",
):
    """Dispatch to the configured attention implementation."""
    if impl not in ATTENTION_IMPLS:
        # Lazily import optional kernels so plain use never pays the cost.
        try:
            if impl == "flash":
                from pytorch_distributed_training_tpu.ops import flash_attention  # noqa: F401
            elif impl == "ring":
                from pytorch_distributed_training_tpu.ops import ring_attention  # noqa: F401
        except ModuleNotFoundError as e:
            # Only swallow "the optional module itself is absent"; a broken
            # transitive import inside it must surface as the real error.
            if e.name is None or not e.name.endswith((impl + "_attention",)):
                raise
    fn = ATTENTION_IMPLS.get(impl)
    if fn is None:
        raise KeyError(
            f"unknown attention impl {impl!r}; registered: {sorted(ATTENTION_IMPLS)}"
        )
    return fn(
        q,
        k,
        v,
        bias,
        dropout_rng=dropout_rng,
        dropout_rate=dropout_rate,
        deterministic=deterministic,
        causal=causal,
        dropout_impl=dropout_impl,
    )
