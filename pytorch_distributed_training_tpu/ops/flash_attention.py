"""Pallas (Mosaic) fused flash attention for TPU.

Fills the framework's ``"flash"`` attention slot (ops/attention.py; SURVEY.md
§5 long-context — the reference rides HF BERT's materialized-scores attention,
reference test_data_parallelism.py:112, and has no kernels of its own).

Classic blockwise-softmax flash attention (online max/denominator), fwd +
custom-VJP bwd, designed for the TPU memory hierarchy:

- Never materializes the [batch, heads, S, S] score tensor in HBM — scores
  live blockwise in VMEM and the MXU consumes them immediately. HBM traffic
  drops from O(S^2) to O(S * D) per head.
- One program per (batch, head, q-block); K/V for the whole sequence stay
  resident in VMEM ([S, head_dim] bf16 — up to ~32k tokens at D=64 inside
  the ~16 MB budget) and are walked block-by-block with ``lax.fori_loop``.
- Softmax statistics accumulate in fp32 (the MXU accumulates fp32 natively);
  the saved per-row logsumexp makes the backward recomputation exact.
- Attention-probability dropout runs INSIDE the kernel via the per-core PRNG
  (``pltpu.prng_seed`` / ``prng_random_bits``), reseeded per
  (batch·head, q-block, k-block) so forward and both backward passes
  regenerate bit-identical keep masks in any block order.
- Supports the framework's two bias forms natively: key-padding bias
  [B, 1, 1, S] (ops.attention.make_attention_bias) and the causal flag
  (decoder family). Anything fancier falls back to the reference einsum
  implementation rather than silently mis-masking.

Backward: the default is a FUSED single pass gridded over k-blocks
(``_dqkv_kernel``) — probs recomputed ONCE per block from q, k and the
saved logsumexp, dk/dv formed locally and dq accumulated in a VMEM scratch
across the sequential grid (rematerialization instead of HBM round-trips,
and half the recompute of the classic scheme). The classic two-pass
backward (a dq pass over q-blocks + a dk/dv pass over k-blocks, each
recomputing probs) is kept behind ``FUSED_BWD = False`` for A/B runs.
"""

from __future__ import annotations

import contextlib
import functools
import os
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from pytorch_distributed_training_tpu.ops.attention import (
    reference_attention,
    register_attention,
)

# 512x512 blocks: measured 45% faster than 128x128 on gpt2-medium @ seq
# 1024 (30.8 -> 44.7 samples/s on v5e — fewer grid iterations, less
# per-block overhead, same VMEM headroom; 1024-wide blocks VMEM-OOM).
# Shorter sequences clamp to seq length in the adapter below.
DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
# Fused single-pass backward (dq+dk+dv from one probs recompute) vs the
# classic two-pass scheme — see _dqkv_kernel. Module-level so bench
# scripts can A/B it (same pattern as the block-size globals above);
# PDT_FLASH_TWO_PASS=1 flips the default from the environment so on-chip
# A/Bs need no code edit.
FUSED_BWD = os.environ.get("PDT_FLASH_TWO_PASS", "0") != "1"
_LANES = 128  # minor-dim tile width for fp32 stats outputs
_NEG_INF = -1e30  # large-negative instead of -inf: keeps exp/max NaN-free


from pytorch_distributed_training_tpu.ops.dropout import (  # noqa: E402
    kernel_keep_mask as _keep_mask,
    kernel_prng_seed as _prng_seed,
)


def _causal_block_mask(qi, kj, block_q, block_k):
    """fp32 additive mask for the (qi, kj) score block under causality."""
    q_pos = qi * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )
    k_pos = kj * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )
    return jnp.where(k_pos <= q_pos, 0.0, _NEG_INF).astype(jnp.float32)


def _num_visible_kv_blocks(qi, block_q, block_k, num_kb):
    """k-blocks a causal q-block can (partially) see: ceil((qi+1)*bq / bk)."""
    return jax.lax.min(num_kb, ((qi + 1) * block_q + block_k - 1) // block_k)


def _block_seed(bh, qi, kj, num_qb, num_kb):
    """One int per (batch·head, q-block, k-block) — Mosaic's prng_seed takes
    at most two values, so the block coordinates are mixed into a single id
    (identical in fwd/dq/dkv, making the keep mask block-order independent)."""
    return (bh * num_qb + qi) * num_kb + kj


# --------------------------------------------------------------------- fwd


def _fwd_kernel(
    seed_ref,  # [1] int32 (scalar prefetch, SMEM)
    q_ref,  # [1, 1, block_q, D]
    k_ref,  # [1, 1, S, D]
    v_ref,  # [1, 1, S, D]
    bias_ref,  # [1, 1, 1, S] fp32 key-padding bias
    o_ref,  # [1, 1, block_q, D]
    lse_ref,  # [1, 1, block_q, LANES]
    *,
    scale: float,
    block_k: int,
    causal: bool,
    dropout_rate: float,
):
    block_q, head_dim = q_ref.shape[2], q_ref.shape[3]
    kv_len = k_ref.shape[2]
    num_kb = kv_len // block_k
    num_qb = pl.num_programs(2)
    b, n, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    bh = b * pl.num_programs(1) + n

    q = q_ref[0, 0, :, :].astype(jnp.float32) * scale

    def body(kj, carry):
        m, l, acc = carry
        ks = pl.ds(kj * block_k, block_k)
        k = k_ref[0, 0, ks, :]
        s = jax.lax.dot_general(
            q.astype(k.dtype), k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [block_q, block_k]
        s = s + bias_ref[0, 0, :, ks]  # [1, block_k] broadcasts over rows
        if causal:
            s = s + _causal_block_mask(qi, kj, block_q, block_k)

        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new[:, None])  # un-normalized probs, fp32
        l = l * alpha + jnp.sum(p, axis=-1)

        if dropout_rate > 0.0:
            _prng_seed(
                seed_ref[0], _block_seed(bh, qi, kj, num_qb, num_kb)
            )
            keep = _keep_mask((block_q, block_k), dropout_rate)
            p = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)

        v = v_ref[0, 0, ks, :]
        pv = jax.lax.dot_general(
            p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        acc = acc * alpha[:, None] + pv
        return m_new, l, acc

    upper = (
        _num_visible_kv_blocks(qi, block_q, block_k, num_kb)
        if causal
        else num_kb
    )
    m, l, acc = jax.lax.fori_loop(
        0,
        upper,
        body,
        (
            jnp.full((block_q,), _NEG_INF, jnp.float32),
            jnp.zeros((block_q,), jnp.float32),
            jnp.zeros((block_q, head_dim), jnp.float32),
        ),
    )

    l_safe = jnp.maximum(l, 1e-30)  # fully-masked rows: zeros, not NaN
    o_ref[0, 0, :, :] = (acc / l_safe[:, None]).astype(o_ref.dtype)
    # TPU tiling wants a 128-lane minor dim: broadcast lse across lanes
    # (same convention as the in-tree TPU flash kernel's l/m outputs)
    lse_ref[0, 0, :, :] = jnp.broadcast_to(
        (m + jnp.log(l_safe))[:, None], lse_ref.shape[2:]
    )


# --------------------------------------------------------------------- bwd


def _dq_kernel(
    seed_ref,
    q_ref,  # [1, 1, block_q, D]
    k_ref,  # [1, 1, S, D]
    v_ref,  # [1, 1, S, D]
    bias_ref,  # [1, 1, 1, S]
    do_ref,  # [1, 1, block_q, D]
    lse_ref,  # [1, 1, block_q, LANES] (lane-broadcast)
    delta_ref,  # [1, 1, block_q, LANES]  rowsum(dO ⊙ O), lane-broadcast
    dq_ref,  # [1, 1, block_q, D]
    *,
    scale: float,
    block_k: int,
    causal: bool,
    dropout_rate: float,
):
    block_q, head_dim = q_ref.shape[2], q_ref.shape[3]
    kv_len = k_ref.shape[2]
    num_kb = kv_len // block_k
    num_qb = pl.num_programs(2)
    b, n, qi = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    bh = b * pl.num_programs(1) + n

    q = q_ref[0, 0, :, :].astype(jnp.float32) * scale
    do = do_ref[0, 0, :, :].astype(jnp.float32)
    lse = lse_ref[0, 0, :, :1]  # [block_q, 1]; all lanes hold the same value
    delta = delta_ref[0, 0, :, :1]

    def body(kj, dq):
        ks = pl.ds(kj * block_k, block_k)
        k = k_ref[0, 0, ks, :]
        v = v_ref[0, 0, ks, :]
        s = jax.lax.dot_general(
            q.astype(k.dtype), k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        s = s + bias_ref[0, 0, :, ks]
        if causal:
            s = s + _causal_block_mask(qi, kj, block_q, block_k)
        p = jnp.exp(s - lse)  # normalized probs

        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if dropout_rate > 0.0:
            _prng_seed(
                seed_ref[0], _block_seed(bh, qi, kj, num_qb, num_kb)
            )
            keep = _keep_mask((block_q, block_k), dropout_rate)
            dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
        ds = p * (dp - delta)  # [block_q, block_k]
        return dq + jax.lax.dot_general(
            ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    upper = (
        _num_visible_kv_blocks(qi, block_q, block_k, num_kb)
        if causal
        else num_kb
    )
    dq = jax.lax.fori_loop(
        0, upper, body, jnp.zeros((block_q, head_dim), jnp.float32)
    )
    dq_ref[0, 0, :, :] = (dq * scale).astype(dq_ref.dtype)


def _kblock_bwd_math(
    refs, k, v, bias, qi, kj, *,
    scale, block_q, block_k, causal, dropout_rate, bh, num_qb, num_kb,
):
    """ONE q-block's contribution at a fixed k-block: (dv_add, dk_add, ds).

    The shared body of the two k-gridded backward kernels — the classic
    ``_dkv_kernel`` and the fused ``_dqkv_kernel`` differ ONLY in what
    they do with ``ds`` (the fused one also accumulates dq), so the math
    lives once and the ``FUSED_BWD`` A/B compares the same algorithm.
    """
    seed_ref, q_ref, do_ref, lse_ref, delta_ref = refs
    qs = pl.ds(qi * block_q, block_q)
    q = q_ref[0, 0, qs, :].astype(jnp.float32) * scale
    do = do_ref[0, 0, qs, :].astype(jnp.float32)
    lse = lse_ref[0, 0, qs, :1]  # [block_q, 1]
    delta = delta_ref[0, 0, qs, :1]
    s = jax.lax.dot_general(
        q.astype(k.dtype), k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    s = s + bias
    if causal:
        s = s + _causal_block_mask(qi, kj, block_q, block_k)
    p = jnp.exp(s - lse)  # [block_q, block_k] — the one probs recompute

    if dropout_rate > 0.0:
        _prng_seed(
            seed_ref[0], _block_seed(bh, qi, kj, num_qb, num_kb)
        )
        keep = _keep_mask((block_q, block_k), dropout_rate)
        p_drop = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
    else:
        p_drop = p
    dv_add = jax.lax.dot_general(
        p_drop, do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    dp = jax.lax.dot_general(
        do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if dropout_rate > 0.0:
        dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
    ds = p * (dp - delta)
    dk_add = jax.lax.dot_general(
        ds, q, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    return dv_add, dk_add, ds


def _dkv_kernel(
    seed_ref,
    q_ref,  # [1, 1, S, D]   (full q per (b, n))
    k_ref,  # [1, 1, block_k, D]
    v_ref,  # [1, 1, block_k, D]
    bias_ref,  # [1, 1, 1, block_k]
    do_ref,  # [1, 1, S, D]
    lse_ref,  # [1, 1, S, LANES]
    delta_ref,  # [1, 1, S, LANES]
    dk_ref,  # [1, 1, block_k, D]
    dv_ref,  # [1, 1, block_k, D]
    *,
    scale: float,
    block_q: int,
    causal: bool,
    dropout_rate: float,
):
    block_k, head_dim = k_ref.shape[2], k_ref.shape[3]
    q_len = q_ref.shape[2]
    num_qb = q_len // block_q
    num_kb = pl.num_programs(2)
    b, n, kj = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    bh = b * pl.num_programs(1) + n

    k = k_ref[0, 0, :, :]
    v = v_ref[0, 0, :, :]
    bias = bias_ref[0, 0, :, :]  # [1, block_k]
    refs = (seed_ref, q_ref, do_ref, lse_ref, delta_ref)

    def body(qi, carry):
        dk, dv = carry
        dv_add, dk_add, _ = _kblock_bwd_math(
            refs, k, v, bias, qi, kj,
            scale=scale, block_q=block_q, block_k=block_k, causal=causal,
            dropout_rate=dropout_rate, bh=bh, num_qb=num_qb, num_kb=num_kb,
        )
        return dk + dk_add, dv + dv_add

    # under causality, q-blocks strictly before this k-block see nothing
    start_qb = (kj * block_k) // block_q if causal else 0
    dk, dv = jax.lax.fori_loop(
        start_qb,
        num_qb,
        body,
        (
            jnp.zeros((block_k, head_dim), jnp.float32),
            jnp.zeros((block_k, head_dim), jnp.float32),
        ),
    )
    # q was pre-scaled, so ds @ q already carries the 1/sqrt(d) factor
    dk_ref[0, 0, :, :] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0, :, :] = dv.astype(dv_ref.dtype)


def _dqkv_kernel(
    seed_ref,
    q_ref,  # [1, 1, S, D]   (full q per (b, n))
    k_ref,  # [1, 1, block_k, D]
    v_ref,  # [1, 1, block_k, D]
    bias_ref,  # [1, 1, 1, block_k]
    do_ref,  # [1, 1, S, D]
    lse_ref,  # [1, 1, S, LANES]
    delta_ref,  # [1, 1, S, LANES]
    dq_ref,  # [1, 1, S, D] (q dtype) — written once, on the LAST kj
    dk_ref,  # [1, 1, block_k, D]
    dv_ref,  # [1, 1, block_k, D]
    dq_acc,  # VMEM scratch [S, D] fp32 — persists across the kj grid
    *,
    scale: float,
    block_q: int,
    causal: bool,
    dropout_rate: float,
):
    """FUSED single-pass backward: dq, dk and dv from ONE probs recompute.

    The two-pass scheme (``_dq_kernel`` + ``_dkv_kernel``) recomputes the
    [block_q, block_k] probs twice — two QK^T matmuls and two exp passes
    per block, plus a full second pass of q/do/lse/delta HBM reads and a
    second grid's worth of per-program overhead. TPU grid iterations are
    SEQUENTIAL on a core, so gridding over k-blocks and accumulating dq
    in a VMEM scratch that persists across iterations gets dq for free
    while dk/dv form locally — halving the recompute; dq is cast and
    written to HBM once, on the last k-block. (Saving probs to HBM
    instead would cost ~S^2*2 bytes × 3 trips per head-layer — tens of
    GB/step at seq 1024 against a ~10 ms recompute; bandwidth arithmetic
    rules it out, so the fuse is the right probs-saving move.)
    """
    block_k, head_dim = k_ref.shape[2], k_ref.shape[3]
    q_len = q_ref.shape[2]
    num_qb = q_len // block_q
    num_kb = pl.num_programs(2)
    b, n, kj = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    bh = b * pl.num_programs(1) + n

    @pl.when(kj == 0)
    def _zero_dq():
        dq_acc[...] = jnp.zeros((q_len, head_dim), jnp.float32)

    k = k_ref[0, 0, :, :]
    v = v_ref[0, 0, :, :]
    bias = bias_ref[0, 0, :, :]  # [1, block_k]
    refs = (seed_ref, q_ref, do_ref, lse_ref, delta_ref)

    def body(qi, carry):
        dk, dv = carry
        dv_add, dk_add, ds = _kblock_bwd_math(
            refs, k, v, bias, qi, kj,
            scale=scale, block_q=block_q, block_k=block_k, causal=causal,
            dropout_rate=dropout_rate, bh=bh, num_qb=num_qb, num_kb=num_kb,
        )
        # dq[qs] += ds · k, accumulated across the SEQUENTIAL kj grid dim
        qs = pl.ds(qi * block_q, block_q)
        dq_acc[qs, :] += (
            jax.lax.dot_general(
                ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        )
        return dk + dk_add, dv + dv_add

    start_qb = (kj * block_k) // block_q if causal else 0
    dk, dv = jax.lax.fori_loop(
        start_qb,
        num_qb,
        body,
        (
            jnp.zeros((block_k, head_dim), jnp.float32),
            jnp.zeros((block_k, head_dim), jnp.float32),
        ),
    )
    # q was pre-scaled, so ds @ q already carries the 1/sqrt(d) factor
    dk_ref[0, 0, :, :] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0, :, :] = dv.astype(dv_ref.dtype)

    @pl.when(kj == num_kb - 1)
    def _write_dq():
        dq_ref[0, 0, :, :] = dq_acc[...].astype(dq_ref.dtype)


def _mh_softmax(q_ref, k_ref, bias_ref, h, *, scale: float, causal: bool):
    """Per-head normalized probs (fp32) for the whole-sequence path —
    shared verbatim by fwd and bwd so the backward's recompute is
    bit-identical to the forward (same inputs, same op order)."""
    q = q_ref[0, h, :, :].astype(jnp.float32) * scale
    k = k_ref[0, h, :, :]
    s = jax.lax.dot_general(
        q.astype(k.dtype), k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    s = s + bias_ref[0, 0, :, :]
    if causal:
        sq = q_ref.shape[2]
        s = s + _causal_block_mask(0, 0, sq, sq)
    # floor the row max so fully-masked rows give zeros, not exp(-inf+inf)
    m = jnp.maximum(jnp.max(s, axis=-1, keepdims=True), _NEG_INF)
    p = jnp.exp(s - m)
    l = jnp.maximum(jnp.sum(p, axis=-1, keepdims=True), 1e-30)
    return p / l


def _mh_fwd_kernel(
    seed_ref,
    q_ref,  # [1, H, S, D]
    k_ref,
    v_ref,
    bias_ref,  # [1, 1, 1, S]
    o_ref,  # [1, H, S, D]
    *,
    scale: float,
    causal: bool,
    dropout_rate: float,
):
    """Whole-sequence forward, ONE program per batch row (grid (B,)), all
    heads walked in-kernel. At short S the [S, S] score tile fits VMEM
    whole, so blockwise-softmax machinery (and its per-(b, n, block) grid
    overhead — 384 tiny programs at bert-large geometry, measured ~200 us
    per call against a ~40 us roofline) buys nothing. No residual is
    written at all: the backward recomputes probs exactly, so attention
    costs zero HBM beyond q/k/v/o — the flash trade taken to its seq-128
    extreme."""
    b = pl.program_id(0)
    heads = q_ref.shape[1]
    for h in range(heads):
        probs = _mh_softmax(q_ref, k_ref, bias_ref, h, scale=scale,
                            causal=causal)
        if dropout_rate > 0.0:
            # same (batch*heads + h) stream id as the multi-block path's
            # _block_seed(bh, 0, 0, 1, 1) so seed derivation stays uniform
            _prng_seed(seed_ref[0], b * heads + h)
            keep = _keep_mask(probs.shape, dropout_rate)
            probs = jnp.where(keep, probs / (1.0 - dropout_rate), 0.0)
        v = v_ref[0, h, :, :]
        o_ref[0, h, :, :] = jax.lax.dot_general(
            probs.astype(v.dtype), v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(o_ref.dtype)


def _mh_bwd_kernel(
    seed_ref,
    q_ref,  # [1, H, S, D]
    k_ref,
    v_ref,
    bias_ref,  # [1, 1, 1, S]
    o_ref,
    do_ref,
    dq_ref,
    dk_ref,
    dv_ref,
    *,
    scale: float,
    causal: bool,
    dropout_rate: float,
):
    """Whole-sequence backward (grid (B,)): recompute probs per head via
    the shared ``_mh_softmax`` (bit-identical to fwd), then dv/dp/ds/dq/dk
    — no lse/delta/probs residuals cross HBM."""
    b = pl.program_id(0)
    heads = q_ref.shape[1]
    for h in range(heads):
        p = _mh_softmax(q_ref, k_ref, bias_ref, h, scale=scale,
                        causal=causal)
        q = q_ref[0, h, :, :]
        k = k_ref[0, h, :, :]
        v = v_ref[0, h, :, :]
        do = do_ref[0, h, :, :].astype(jnp.float32)
        o = o_ref[0, h, :, :].astype(jnp.float32)
        delta = jnp.sum(do * o, axis=-1, keepdims=True)
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        if dropout_rate > 0.0:
            _prng_seed(seed_ref[0], b * heads + h)
            keep = _keep_mask(p.shape, dropout_rate)
            p_drop = jnp.where(keep, p / (1.0 - dropout_rate), 0.0)
            dp = jnp.where(keep, dp / (1.0 - dropout_rate), 0.0)
        else:
            p_drop = p
        dv_ref[0, h, :, :] = jax.lax.dot_general(
            p_drop, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ).astype(dv_ref.dtype)
        ds = p * (dp - delta)
        dq_ref[0, h, :, :] = (
            jax.lax.dot_general(
                ds.astype(k.dtype), k, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        ).astype(dq_ref.dtype)
        dk_ref[0, h, :, :] = (
            jax.lax.dot_general(
                ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * scale
        ).astype(dk_ref.dtype)


# ----------------------------------------------------------------- wrapper


def _flash_fwd(q, k, v, bias, seed, dropout_rate, causal, block_q, block_k):
    """q/k/v: [B, N, S, D]; bias: [B, 1, 1, S] fp32; seed: [1] int32."""
    batch, heads, q_len, head_dim = q.shape
    kv_len = k.shape[2]
    scale = head_dim**-0.5

    o, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel,
            scale=scale,
            block_k=block_k,
            causal=causal,
            dropout_rate=dropout_rate,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(batch, heads, q_len // block_q),
            in_specs=[
                pl.BlockSpec(
                    (1, 1, block_q, head_dim), lambda b, n, qi, *_: (b, n, qi, 0)
                ),
                pl.BlockSpec(
                    (1, 1, kv_len, head_dim), lambda b, n, qi, *_: (b, n, 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, kv_len, head_dim), lambda b, n, qi, *_: (b, n, 0, 0)
                ),
                pl.BlockSpec((1, 1, 1, kv_len), lambda b, n, qi, *_: (b, 0, 0, 0)),
            ],
            out_specs=[
                pl.BlockSpec(
                    (1, 1, block_q, head_dim), lambda b, n, qi, *_: (b, n, qi, 0)
                ),
                pl.BlockSpec(
                    (1, 1, block_q, _LANES), lambda b, n, qi, *_: (b, n, qi, 0)
                ),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct(
                (batch, heads, q_len, _LANES), jnp.float32
            ),
        ],
        interpret=_interpreting(),
    )(seed, q, k, v, bias)
    return o, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8))
def _flash(q, k, v, bias, seed, dropout_rate, causal, block_q, block_k):
    o, _ = _flash_fwd(
        q, k, v, bias, seed, dropout_rate, causal, block_q, block_k
    )
    return o


# Whole-seq ceiling: [S, S] fp32 score tiles per head must fit VMEM
# comfortably next to the [H, S, D] operand blocks; 256 keeps the per-
# program footprint ~2 MB at bert geometry.
_WHOLE_SEQ_MAX = 256


def _whole_seq(q, k, block_q, block_k):
    q_len, kv_len = q.shape[2], k.shape[2]
    return (
        q_len == block_q
        and kv_len == block_k
        and q_len == kv_len
        and q_len <= _WHOLE_SEQ_MAX
    )


def _mh_block_specs(q):
    batch, heads, q_len, head_dim = q.shape
    full = pl.BlockSpec(
        (1, heads, q_len, head_dim), lambda b, *_: (b, 0, 0, 0)
    )
    bias_spec = pl.BlockSpec((1, 1, 1, q_len), lambda b, *_: (b, 0, 0, 0))
    return full, bias_spec


def _flash_fwd_whole_seq(q, k, v, bias, seed, dropout_rate, causal):
    batch, heads, q_len, head_dim = q.shape
    full, bias_spec = _mh_block_specs(q)
    return pl.pallas_call(
        functools.partial(
            _mh_fwd_kernel,
            scale=head_dim**-0.5,
            causal=causal,
            dropout_rate=dropout_rate,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(batch,),
            in_specs=[full, full, full, bias_spec],
            out_specs=[full],
        ),
        out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype)],
        interpret=_interpreting(),
    )(seed, q, k, v, bias)[0]


def _vjp_fwd(q, k, v, bias, seed, dropout_rate, causal, block_q, block_k):
    if _whole_seq(q, k, block_q, block_k):
        o = _flash_fwd_whole_seq(
            q, k, v, bias, seed, dropout_rate, causal
        )
        return o, (q, k, v, bias, seed, o, None)
    o, lse = _flash_fwd(
        q, k, v, bias, seed, dropout_rate, causal, block_q, block_k
    )
    return o, (q, k, v, bias, seed, o, lse)


def _vjp_bwd(dropout_rate, causal, block_q, block_k, res, do):
    q, k, v, bias, seed, o, lse_or_none = res
    batch, heads, q_len, head_dim = q.shape
    kv_len = k.shape[2]
    scale = head_dim**-0.5

    if _whole_seq(q, k, block_q, block_k):
        full, bias_spec = _mh_block_specs(q)
        dq, dk, dv = pl.pallas_call(
            functools.partial(
                _mh_bwd_kernel,
                scale=scale,
                causal=causal,
                dropout_rate=dropout_rate,
            ),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(batch,),
                in_specs=[full, full, full, bias_spec, full, full],
                out_specs=[full, full, full],
            ),
            out_shape=[
                jax.ShapeDtypeStruct(q.shape, q.dtype),
                jax.ShapeDtypeStruct(k.shape, k.dtype),
                jax.ShapeDtypeStruct(v.shape, v.dtype),
            ],
            interpret=_interpreting(),
        )(seed, q, k, v, bias, o, do)
        dbias = jnp.zeros_like(bias)
        dseed = np.zeros(seed.shape, jax.dtypes.float0)
        return dq, dk, dv, dbias, dseed

    lse = lse_or_none
    delta = jnp.sum(
        do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
    )  # [B, N, S]
    delta = jnp.broadcast_to(
        delta[..., None], (*delta.shape, _LANES)
    )  # lane-broadcast to match lse's tiling

    if FUSED_BWD:
        dq, dk, dv = pl.pallas_call(
            functools.partial(
                _dqkv_kernel,
                scale=scale,
                block_q=block_q,
                causal=causal,
                dropout_rate=dropout_rate,
            ),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(batch, heads, kv_len // block_k),
                in_specs=[
                    pl.BlockSpec(
                        (1, 1, q_len, head_dim),
                        lambda b, n, kj, *_: (b, n, 0, 0),
                    ),
                    pl.BlockSpec(
                        (1, 1, block_k, head_dim),
                        lambda b, n, kj, *_: (b, n, kj, 0),
                    ),
                    pl.BlockSpec(
                        (1, 1, block_k, head_dim),
                        lambda b, n, kj, *_: (b, n, kj, 0),
                    ),
                    pl.BlockSpec(
                        (1, 1, 1, block_k), lambda b, n, kj, *_: (b, 0, 0, kj)
                    ),
                    pl.BlockSpec(
                        (1, 1, q_len, head_dim),
                        lambda b, n, kj, *_: (b, n, 0, 0),
                    ),
                    pl.BlockSpec(
                        (1, 1, q_len, _LANES), lambda b, n, kj, *_: (b, n, 0, 0)
                    ),
                    pl.BlockSpec(
                        (1, 1, q_len, _LANES), lambda b, n, kj, *_: (b, n, 0, 0)
                    ),
                ],
                out_specs=[
                    # dq: same block for every kj at fixed (b, n); the
                    # fp32 accumulator is a VMEM scratch persisting across
                    # the sequential grid, written back (cast) on last kj
                    pl.BlockSpec(
                        (1, 1, q_len, head_dim),
                        lambda b, n, kj, *_: (b, n, 0, 0),
                    ),
                    pl.BlockSpec(
                        (1, 1, block_k, head_dim),
                        lambda b, n, kj, *_: (b, n, kj, 0),
                    ),
                    pl.BlockSpec(
                        (1, 1, block_k, head_dim),
                        lambda b, n, kj, *_: (b, n, kj, 0),
                    ),
                ],
                scratch_shapes=[
                    pltpu.VMEM((q_len, head_dim), jnp.float32)
                ],
            ),
            out_shape=[
                jax.ShapeDtypeStruct(q.shape, q.dtype),
                jax.ShapeDtypeStruct(k.shape, k.dtype),
                jax.ShapeDtypeStruct(v.shape, v.dtype),
            ],
            interpret=_interpreting(),
        )(seed, q, k, v, bias, do, lse, delta)
        dbias = jnp.zeros_like(bias)
        dseed = np.zeros(seed.shape, jax.dtypes.float0)
        return dq, dk, dv, dbias, dseed

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel,
            scale=scale,
            block_k=block_k,
            causal=causal,
            dropout_rate=dropout_rate,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(batch, heads, q_len // block_q),
            in_specs=[
                pl.BlockSpec(
                    (1, 1, block_q, head_dim), lambda b, n, qi, *_: (b, n, qi, 0)
                ),
                pl.BlockSpec(
                    (1, 1, kv_len, head_dim), lambda b, n, qi, *_: (b, n, 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, kv_len, head_dim), lambda b, n, qi, *_: (b, n, 0, 0)
                ),
                pl.BlockSpec((1, 1, 1, kv_len), lambda b, n, qi, *_: (b, 0, 0, 0)),
                pl.BlockSpec(
                    (1, 1, block_q, head_dim), lambda b, n, qi, *_: (b, n, qi, 0)
                ),
                pl.BlockSpec(
                    (1, 1, block_q, _LANES), lambda b, n, qi, *_: (b, n, qi, 0)
                ),
                pl.BlockSpec(
                    (1, 1, block_q, _LANES), lambda b, n, qi, *_: (b, n, qi, 0)
                ),
            ],
            out_specs=pl.BlockSpec(
                (1, 1, block_q, head_dim), lambda b, n, qi, *_: (b, n, qi, 0)
            ),
        ),
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        interpret=_interpreting(),
    )(seed, q, k, v, bias, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel,
            scale=scale,
            block_q=block_q,
            causal=causal,
            dropout_rate=dropout_rate,
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(batch, heads, kv_len // block_k),
            in_specs=[
                pl.BlockSpec(
                    (1, 1, q_len, head_dim), lambda b, n, kj, *_: (b, n, 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, block_k, head_dim), lambda b, n, kj, *_: (b, n, kj, 0)
                ),
                pl.BlockSpec(
                    (1, 1, block_k, head_dim), lambda b, n, kj, *_: (b, n, kj, 0)
                ),
                pl.BlockSpec(
                    (1, 1, 1, block_k), lambda b, n, kj, *_: (b, 0, 0, kj)
                ),
                pl.BlockSpec(
                    (1, 1, q_len, head_dim), lambda b, n, kj, *_: (b, n, 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, q_len, _LANES), lambda b, n, kj, *_: (b, n, 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, q_len, _LANES), lambda b, n, kj, *_: (b, n, 0, 0)
                ),
            ],
            out_specs=[
                pl.BlockSpec(
                    (1, 1, block_k, head_dim), lambda b, n, kj, *_: (b, n, kj, 0)
                ),
                pl.BlockSpec(
                    (1, 1, block_k, head_dim), lambda b, n, kj, *_: (b, n, kj, 0)
                ),
            ],
        ),
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        interpret=_interpreting(),
    )(seed, q, k, v, bias, do, lse, delta)

    # bias is a mask (non-differentiable by contract); seed is integer
    dbias = jnp.zeros_like(bias)
    dseed = np.zeros(seed.shape, jax.dtypes.float0)
    return dq, dk, dv, dbias, dseed


_flash.defvjp(_vjp_fwd, _vjp_bwd)


def flash_attention_base(
    q, k, v, bias, seed,
    *,
    dropout_rate: float = 0.0,
    causal: bool = False,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
):
    """Differentiable flash attention on [B, N, S, D] inputs."""
    return _flash(
        q, k, v, bias, seed, dropout_rate, causal, block_q, block_k
    )


# Owned signal; no jax private-API probing. Thread-local to mirror jax's
# interpret-mode config scoping (a global would let one thread's context
# flip another thread's dispatch).
_INTERPRET = threading.local()


@contextlib.contextmanager
def tpu_interpret_mode():
    """Run Pallas TPU kernels in interpret mode off-TPU AND tell the flash
    dispatch guard the kernel path is live.

    This is the framework-owned replacement for jax's global force-interpret
    context (``pltpu.force_tpu_interpret_mode`` — removed in the jax this
    image ships): every ``pl.pallas_call`` in ops/ passes
    ``interpret=_interpreting()``, so entering this context before the
    kernel's first trace routes it through the Pallas interpreter. Tests
    (and any CPU-host user who wants the kernel semantics) enter this
    context; the dispatch gate (``ops.dispatch.mode``) reads the same
    thread-local and needs no ``jax._src`` imports.
    """
    _INTERPRET.depth = getattr(_INTERPRET, "depth", 0) + 1
    try:
        yield
    finally:
        _INTERPRET.depth -= 1


def _interpreting() -> bool:
    """Trace-time value of the ``interpret=`` kwarg for every Pallas call
    in ops/: True inside ``tpu_interpret_mode()`` (the context must wrap
    the kernel's FIRST trace — jit caches bake the flag in, same scoping
    contract the removed jax global had)."""
    return getattr(_INTERPRET, "depth", 0) > 0


# ------------------------------------------------------------ registration


@register_attention("flash")
def flash_attention(
    q: jnp.ndarray,  # [B, S, N, D]
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: Optional[jnp.ndarray] = None,
    *,
    dropout_rng=None,
    dropout_rate: float = 0.0,
    deterministic: bool = True,
    causal: bool = False,
    dropout_impl: str = "exact",  # in-kernel per-core PRNG; generator n/a
):
    """Adapter matching the swappable-attention signature (ops/attention.py).

    Handles the key-padding bias produced by ``make_attention_bias``
    ([B, 1, 1, S]) and the causal flag natively; any other bias shape (e.g.
    per-head or per-query additive biases) falls back to the reference einsum
    implementation so masking is never silently wrong.
    """
    batch, q_len, heads, head_dim = q.shape
    kv_len = k.shape[1]

    def pick_block(n, cap):
        # largest multiple of 128 <= cap that divides n (so e.g. seq 768
        # gets 256-wide blocks instead of silently losing the kernel to
        # the 768 % 512 != 0 fallback); short sequences use one block.
        if n <= cap:
            return n
        for b in range(cap, 127, -128):
            if n % b == 0:
                return b
        return cap  # no divisor: the divisibility check below falls back

    from pytorch_distributed_training_tpu.ops import dispatch

    block_q = pick_block(q_len, DEFAULT_BLOCK_Q)
    block_k = pick_block(kv_len, DEFAULT_BLOCK_K)
    bias_ok = bias is None or (
        bias.ndim == 4 and bias.shape[1] == 1 and bias.shape[2] == 1
    )
    # Same dispatch policy as every kernel (ops/dispatch.py): direct on a
    # single device / interpret, shard_map on a registered sharded mesh,
    # reference fallback otherwise — fixing the round-2 inconsistency where
    # flash dispatched bare on any TPU (the SPMD partitioner would have
    # all-gathered the sharded activations per call; VERDICT r2 #3).
    mode = dispatch.mode()
    if (
        mode == "off"
        or not bias_ok
        or q_len % block_q
        or kv_len % block_k
        or head_dim > 256
    ):
        return reference_attention(
            q, k, v, bias,
            dropout_rng=dropout_rng, dropout_rate=dropout_rate,
            deterministic=deterministic, causal=causal,
            dropout_impl=dropout_impl,
        )

    rate = 0.0 if deterministic or dropout_rng is None else dropout_rate
    if rate > 0.0:
        seed = jax.random.randint(
            dropout_rng, (1,), 0, jnp.iinfo(jnp.int32).max, jnp.int32
        )
    else:
        seed = jnp.zeros((1,), jnp.int32)

    if bias is None:
        bias_f = jnp.zeros((batch, 1, 1, kv_len), jnp.float32)
    else:
        bias_f = bias.astype(jnp.float32)

    def call_base(qh, kh, vh, bf, sd):
        # [B, S, N, D] -> [B, N, S, D]
        o = flash_attention_base(
            qh.transpose(0, 2, 1, 3),
            kh.transpose(0, 2, 1, 3),
            vh.transpose(0, 2, 1, 3),
            bf,
            sd,
            dropout_rate=rate,
            causal=causal,
            block_q=block_q,
            block_k=block_k,
        )
        return o.transpose(0, 2, 1, 3)

    if mode == "shard_map":
        plan = _flash_shard_plan(q)
        if plan is None:
            return reference_attention(
                q, k, v, bias,
                dropout_rng=dropout_rng, dropout_rate=dropout_rate,
                deterministic=deterministic, causal=causal,
                dropout_impl=dropout_impl,
            )
        mesh, spec, bias_spec, axes_used = plan

        def body(qh, kh, vh, bf, sd):
            with dispatch.manual_region():
                sd = sd + dispatch.linear_device_index(axes_used, mesh)
                return call_base(qh, kh, vh, bf, sd)

        dispatch.KERNEL_DISPATCH_COUNTS["flash"] += 1
        from jax.sharding import PartitionSpec as P

        from pytorch_distributed_training_tpu.ops.dispatch import shard_map

        return shard_map(
            body, mesh=mesh,
            in_specs=(spec, spec, spec, bias_spec, P()),
            out_specs=spec, check_rep=False,
        )(q, k, v, bias_f, seed)

    return call_base(q, k, v, bias_f, seed)


def _flash_shard_plan(q):
    """shard_map plan for [B, S, N, D] attention inputs: batch axes on
    dim 0, the head axis (tensor parallelism) on dim 2
    (dispatch.plan_shards). None when the registered mesh doesn't divide
    the shape, or when a seq axis is active (context parallelism routes
    through ops/ring_attention instead)."""
    from jax.sharding import PartitionSpec as P

    from pytorch_distributed_training_tpu.ops import dispatch

    ctx = dispatch.kernel_ctx()
    if ctx is None:
        return None
    mesh, batch_axes, seq_axis, head_axis = ctx
    if mesh.shape.get(seq_axis, 1) > 1:
        return None
    plan = dispatch.plan_shards(q.shape, {2: head_axis})
    if plan is None:
        return None
    mesh, spec, axes_used, _ = plan
    bias_spec = P(tuple(batch_axes), None, None, None)
    return mesh, spec, bias_spec, axes_used
