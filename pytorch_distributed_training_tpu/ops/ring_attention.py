"""Ring attention: sequence/context parallelism over the mesh ``seq`` axis.

The reference has NO long-context machinery — sequences are truncated to the
model max (reference test_data_parallelism.py:75) and padded to 128 on TPU
(:96-98). This framework makes sequence scaling first-class: activations
shard on the sequence dimension over the mesh's ``seq`` axis, and attention
— the one op that needs every key/value — runs as a ring (Liu et al., Ring
Attention with Blockwise Transformers): each device holds its local Q block
for the whole pass while K/V (+ the key-padding bias) blocks hop around the
ring via ``jax.lax.ppermute`` (XLA collective-permute over adjacent-chip ICI
links), combined with the same online-softmax accumulation the flash kernel
uses. Peak memory per device is O(S/P · S/P) scores instead of O(S²), and
each hop's communication overlaps the previous block's compute under XLA's
latency-hiding scheduler.

Implementation notes:
- Entered via ``jax.shard_map`` over the enclosing jit's GSPMD program:
  the op takes GLOBAL [B, S, N, D] arrays (sharded however the trainer laid
  them out), forces the seq-sharded layout at the shard_map boundary, and
  returns the same layout. The concrete Mesh comes from
  ``comms.mesh.current_mesh()`` because flax module calls can't thread a
  Mesh through ``dot_product_attention``'s signature.
- The ring loop is a static python loop (mesh sizes are static): fully
  unrolled, differentiable (reverse-mode AD transposes each ppermute into
  the inverse rotation), and schedulable — XLA overlaps hop j+1's
  collective-permute with hop j's matmuls.
- Causality is enforced with GLOBAL positions (shard offset + local index),
  so a causal model sharded over ``seq`` matches the single-device result;
  whole ring hops that are entirely above the diagonal still pay the
  permute (pipelined away) but skip nothing numerically — their
  contribution is exactly masked.
- Attention-probability dropout folds (ring step, my shard index) into the
  key so every (q-block, kv-block) pair gets an independent keep mask.
- With ``seq`` axis size 1 (or no mesh recorded) this degrades to the plain
  reference implementation — same math, no shard_map.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from pytorch_distributed_training_tpu.comms.mesh import (
    AXIS_SEQ,
    BATCH_AXES,
    current_mesh,
)
from pytorch_distributed_training_tpu.ops.attention import (
    reference_attention,
    register_attention,
)
from pytorch_distributed_training_tpu.ops.dropout import raw_dropout

_NEG_INF = -1e30


def _local_block(q, k, v, bias, *, scale, q_offset, kv_offset, causal,
                 dropout_rng, dropout_rate, dropout_impl):
    """One (local Q) x (one ring hop's K/V) block: scores + online-softmax
    partials. Shapes: q [B, Sq, N, D]; k/v [B, Skv, N, D];
    bias [B, 1, 1, Skv]. Returns (m, l, pv): running-max [B, N, Sq],
    denominator partial [B, N, Sq], weighted values [B, Sq, N, D]."""
    s = jnp.einsum(
        "bsnd,btnd->bnst", q, k, preferred_element_type=jnp.float32
    ) * scale
    if bias is not None:
        s = s + bias.astype(jnp.float32)
    if causal:
        sq, skv = q.shape[1], k.shape[1]
        q_pos = q_offset + jax.lax.broadcasted_iota(jnp.int32, (sq, skv), 0)
        k_pos = kv_offset + jax.lax.broadcasted_iota(jnp.int32, (sq, skv), 1)
        s = s + jnp.where(k_pos <= q_pos, 0.0, _NEG_INF)[None, None]
    m = jnp.max(s, axis=-1)  # [B, N, Sq]
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)  # denominator from the UNDROPPED fp32 p
    p = p.astype(v.dtype)  # cast before dropout: half-width mask residual,
    # same ordering as reference_attention's bf16-policy path
    if dropout_rate > 0.0:
        p = raw_dropout(p, dropout_rate, dropout_rng, dropout_impl)
    pv = jnp.einsum(
        "bnst,btnd->bsnd", p, v,
        preferred_element_type=jnp.float32,
    )
    return m, l, pv


def _ring_shard(q, k, v, bias, *, scale, n_shards, causal, dropout_rng,
                dropout_rate, dropout_impl, axis_name):
    """Per-shard body under shard_map: local Q stays, K/V/bias ring-hop."""
    from pytorch_distributed_training_tpu.ops import dispatch

    with dispatch.manual_region():
        return _ring_shard_body(
            q, k, v, bias, scale=scale, n_shards=n_shards, causal=causal,
            dropout_rng=dropout_rng, dropout_rate=dropout_rate,
            dropout_impl=dropout_impl, axis_name=axis_name,
        )


def _ring_shard_body(q, k, v, bias, *, scale, n_shards, causal, dropout_rng,
                     dropout_rate, dropout_impl, axis_name):
    my = jax.lax.axis_index(axis_name)
    seq_local = q.shape[1]
    perm = [(i, (i - 1) % n_shards) for i in range(n_shards)]  # blocks move left

    m_run = jnp.full(q.shape[:1] + (q.shape[2], seq_local), _NEG_INF,
                     jnp.float32)  # [B, N, Sq]
    l_run = jnp.zeros_like(m_run)
    acc = jnp.zeros(q.shape, jnp.float32)

    k_cur, v_cur, bias_cur = k, v, bias
    for j in range(n_shards):
        src = (my + j) % n_shards  # origin shard of the block now held
        step_rng = (
            jax.random.fold_in(jax.random.fold_in(dropout_rng, j), my)
            if dropout_rate > 0.0
            else None
        )
        m_j, l_j, pv_j = _local_block(
            q, k_cur, v_cur, bias_cur,
            scale=scale,
            q_offset=my * seq_local,
            kv_offset=src * seq_local,
            causal=causal,
            dropout_rng=step_rng,
            dropout_rate=dropout_rate,
            dropout_impl=dropout_impl,
        )
        m_new = jnp.maximum(m_run, m_j)
        alpha = jnp.exp(m_run - m_new)
        beta = jnp.exp(m_j - m_new)
        l_run = l_run * alpha + l_j * beta
        # acc is [B, Sq, N, D]; stats are [B, N, Sq] -> move Sq next to B
        acc = (
            acc * alpha.transpose(0, 2, 1)[..., None]
            + pv_j * beta.transpose(0, 2, 1)[..., None]
        )
        m_run = m_new
        if j + 1 < n_shards:
            k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
            v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
            if bias_cur is not None:
                bias_cur = jax.lax.ppermute(bias_cur, axis_name, perm)

    l_safe = jnp.maximum(l_run, 1e-30)
    out = acc / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


@register_attention("ring")
def ring_attention(
    q: jnp.ndarray,  # [B, S, N, D] (global)
    k: jnp.ndarray,
    v: jnp.ndarray,
    bias: Optional[jnp.ndarray] = None,
    *,
    dropout_rng=None,
    dropout_rate: float = 0.0,
    deterministic: bool = True,
    causal: bool = False,
    dropout_impl: str = "exact",
):
    """Sequence-parallel attention over the mesh ``seq`` axis.

    Matches the swappable-attention signature (ops/attention.py). Requires
    the key-padding bias form [B, 1, 1, S] (or none); any other bias shape
    falls back to the reference implementation, as does a missing/size-1
    ``seq`` axis.
    """
    mesh = current_mesh()
    rate = 0.0 if deterministic or dropout_rng is None else dropout_rate
    bias_ok = bias is None or (
        bias.ndim == 4 and bias.shape[1] == 1 and bias.shape[2] == 1
    )
    n_shards = mesh.shape[AXIS_SEQ] if mesh is not None else 1
    if n_shards == 1 or not bias_ok or q.shape[1] % n_shards:
        return reference_attention(
            q, k, v, bias,
            dropout_rng=dropout_rng, dropout_rate=dropout_rate,
            deterministic=deterministic, causal=causal,
            dropout_impl=dropout_impl,
        )

    scale = q.shape[-1] ** -0.5
    # batch rows shard over the data axes only when they divide — a batch
    # smaller than data×fsdp (e.g. the 2-row model-init example) computes
    # replicated instead of failing shard_map's divisibility check; the
    # seq axis (the op's whole point) is already guarded above
    from pytorch_distributed_training_tpu.comms.mesh import dp_degree

    batch_axes = BATCH_AXES if q.shape[0] % dp_degree(mesh) == 0 else None
    qkv_spec = P(batch_axes, AXIS_SEQ, None, None)
    bias_spec = P(batch_axes, None, None, AXIS_SEQ)

    import functools

    # Uniform signature for ONE shard_map: a zeros bias (folded away by XLA)
    # stands in for None, and a dummy key rides along when dropout is off
    # (rate is static, so the body traces no dropout ops from it).
    if bias is None:
        bias = jnp.zeros((q.shape[0], 1, 1, q.shape[1]), jnp.float32)
    rng = dropout_rng if rate > 0.0 else jax.random.key(0)

    body = functools.partial(
        _ring_shard,
        scale=scale,
        n_shards=n_shards,
        causal=causal,
        dropout_rate=rate,
        dropout_impl=dropout_impl,
        axis_name=AXIS_SEQ,
    )
    # dispatch.shard_map owns the jax.shard_map-vs-experimental import and
    # the check_vma/check_rep kwarg rename across the jax versions in play
    from pytorch_distributed_training_tpu.ops.dispatch import shard_map

    fn = shard_map(
        lambda q, k, v, b, r: body(q, k, v, b, dropout_rng=r),
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, bias_spec, P()),
        out_specs=qkv_spec,
        check_rep=False,
    )
    return fn(q, k, v, bias, rng)
