"""Pallas kernel dispatch under sharded meshes (shard_map routing).

Problem (NOTES.md round-2; VERDICT r2 #3): a Pallas custom call inside a
GSPMD-partitioned program is treated as REPLICATED by the SPMD partitioner
— XLA all-gathers the sharded operands before every call, silently turning
the kernels' wins into catastrophic collective traffic. Round 2 therefore
gated every kernel to ``jax.device_count() == 1`` and sharded meshes fell
back to identical-math XLA ops (correct, but the fused-kernel throughput
evaporated exactly on the multi-chip configs that need it most).

The fix is the standard one: run the kernel INSIDE ``shard_map`` over the
axes its math is embarrassingly parallel in (batch/seq rows for LayerNorm
and dropout-add-LN tails, batch x heads for attention-probs mask-scale and
flash attention). Each device then invokes the kernel on its LOCAL shard
and no collective is emitted — GSPMD sees a manually-partitioned region.

The ops can't guess the mesh from inside a traced function, so the Trainer
(or any harness) registers the mesh + axis convention here before tracing:

    set_kernel_mesh(mesh)            # Trainer.__init__ / bench setup
    with use_kernel_mesh(mesh): ...  # tests

Dispatch contract per op (see each op's wrapper):
- ``mode() == "direct"``   — single-device TPU or the interpret context:
  call the kernel directly (round-2 behavior, unchanged).
- ``mode() == "shard_map"``— TPU backend, >1 device, mesh registered:
  wrap the kernel in shard_map with the op's specs; the per-device seed is
  offset by the linearized device index so dropout streams stay distinct.
- ``mode() == "off"``      — anything else: the op falls back to its
  XLA/jnp reference math (identical numerics), as before.

The reference delegates all of this to torch/NCCL (its kernels arrive
pre-sharded per GPU, reference test_data_parallelism.py:125-127); owning
the kernels means owning their partitioning story too.
"""

from __future__ import annotations

import contextlib
import threading
from collections import Counter
from typing import Optional, Sequence

import jax
from jax.sharding import Mesh

_CTX = threading.local()

# trace-time counters keyed by op name ("layer_norm", "dal", "mask_scale",
# "flash") — tests assert the shard_map kernel path was actually taken
# (the compiled HLO hides the kernel under interpret mode, so a counter at
# trace time is the observable).
KERNEL_DISPATCH_COUNTS: Counter = Counter()


def set_kernel_mesh(
    mesh: Optional[Mesh],
    *,
    batch_axes: Sequence[str] = ("data", "fsdp"),
    seq_axis: str = "seq",
    head_axis: str = "model",
) -> None:
    """Register (or clear, with None) the mesh the kernels shard over."""
    _CTX.mesh = mesh
    _CTX.batch_axes = tuple(batch_axes)
    _CTX.seq_axis = seq_axis
    _CTX.head_axis = head_axis


@contextlib.contextmanager
def use_kernel_mesh(mesh: Mesh, **kwargs):
    prev = kernel_ctx()
    set_kernel_mesh(mesh, **kwargs)
    try:
        yield
    finally:
        if prev is None:
            set_kernel_mesh(None)
        else:
            set_kernel_mesh(
                prev[0], batch_axes=prev[1], seq_axis=prev[2],
                head_axis=prev[3],
            )


def kernel_ctx():
    """(mesh, batch_axes, seq_axis, head_axis) or None."""
    mesh = getattr(_CTX, "mesh", None)
    if mesh is None:
        return None
    return (mesh, _CTX.batch_axes, _CTX.seq_axis, _CTX.head_axis)


def interpret_active() -> bool:
    from pytorch_distributed_training_tpu.ops.flash_attention import (
        _INTERPRET,
    )

    return getattr(_INTERPRET, "depth", 0) > 0


try:  # single home for the shard_map import (new API first)
    from jax import shard_map as _shard_map_impl  # type: ignore[attr-defined]
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map_impl

# The check_rep -> check_vma rename is independent of WHERE shard_map is
# importable from (jax versions exist with the top-level export and the old
# kwarg), so gate on the actual signature, not the import location.
import inspect as _inspect

_CHECK_KW = (
    "check_vma"
    if "check_vma" in _inspect.signature(_shard_map_impl).parameters
    else "check_rep"
)


def shard_map(f, *, mesh, in_specs, out_specs, check_rep=True):
    """API-normalized shard_map (``check_rep`` name regardless of jax
    version) — the single import site for every kernel/pipeline wrapper."""
    return _shard_map_impl(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        **{_CHECK_KW: check_rep},
    )


@contextlib.contextmanager
def manual_region():
    """Mark 'we are inside a shard_map body' (trace-time flag).

    Inside a manual region every mesh axis is already manually partitioned,
    so a kernel must be called DIRECTLY on the local shard — opening a
    second shard_map over the same mesh is a trace error ("context mesh
    Manual should match mesh passed to shard_map"), hit e.g. when
    GPipeClassifier's pipelined BertLayers (already inside gpipe_apply's
    shard_map) reach dropout_add_layer_norm with a registered kernel mesh.
    Every shard_map body this framework creates enters this context."""
    _CTX.manual_depth = getattr(_CTX, "manual_depth", 0) + 1
    try:
        yield
    finally:
        _CTX.manual_depth -= 1


@contextlib.contextmanager
def force_shard_map():
    """Test hook: make ``mode()`` report "shard_map" regardless of device
    count (requires a registered mesh). Lets the on-TPU tier execute the
    real Mosaic kernels through the shard_map routing on the single
    available chip — the 1-device mesh is trivial, the code path is not."""
    _CTX.force = "shard_map"
    try:
        yield
    finally:
        _CTX.force = None


def mode() -> str:
    """Kernel dispatch mode for the calling op (see module docstring)."""
    if getattr(_CTX, "manual_depth", 0) > 0:
        # already inside a shard_map body: operands are local shards,
        # call the kernel directly (nesting another shard_map would crash)
        if interpret_active() or jax.default_backend() == "tpu":
            return "direct"
        return "off"
    forced = getattr(_CTX, "force", None)
    if forced is not None and kernel_ctx() is not None:
        return forced
    if interpret_active():
        # the interpret context emulates kernels anywhere; with a mesh
        # registered it exercises the exact shard_map routing real chips use
        return "shard_map" if kernel_ctx() is not None else "direct"
    if jax.default_backend() != "tpu":
        return "off"
    if jax.device_count() == 1:
        return "direct"
    return "shard_map" if kernel_ctx() is not None else "off"


def linear_device_index(axes: Sequence[str], mesh: Mesh):
    """Linearized index over ``axes`` inside a shard_map body — offsets the
    per-device kernel PRNG seed so no two shards reuse a mask stream."""
    idx = None
    for a in axes:
        comp = jax.lax.axis_index(a)
        idx = comp if idx is None else idx * mesh.shape[a] + comp
    if idx is None:
        import jax.numpy as jnp

        return jnp.int32(0)
    return idx


def axes_size(mesh: Mesh, axes: Sequence[str]) -> int:
    """Product of the mesh axes' sizes — the shard count a dim divides by."""
    n = 1
    for a in axes:
        n *= mesh.shape.get(a, 1)
    return n


def plan_shards(shape: Sequence[int], extra_axes: dict):
    """Common shard-plan core for every kernel's shard_map wrapper.

    Dim 0 always shards over the registered batch axes; each
    ``{dim: mesh_axis}`` in ``extra_axes`` additionally shards that dim
    when the axis is >1 in the mesh. Returns
    ``(mesh, PartitionSpec, axes_used, local_shape)`` — ``axes_used`` is
    the ordered axis list for :func:`linear_device_index` seed offsets,
    ``local_shape`` the per-shard shape for the caller's own tileability
    checks — or None when no mesh is registered or a sharded dim doesn't
    divide (caller falls back to its XLA math). ONE implementation so the
    axis convention and divisibility rule can't drift between the ops
    (layer_norm row kernels, mask-scale, flash)."""
    from jax.sharding import PartitionSpec as P

    ctx = kernel_ctx()
    if ctx is None:
        return None
    mesh, batch_axes, _, _ = ctx
    entries: list = [None] * len(shape)
    entries[0] = tuple(batch_axes)
    axes_used = list(batch_axes)
    local = list(shape)
    f0 = axes_size(mesh, batch_axes)
    if shape[0] % f0:
        return None
    local[0] //= f0
    for dim, axis_name in extra_axes.items():
        f = mesh.shape.get(axis_name, 1)
        if f > 1:
            if shape[dim] % f:
                return None
            entries[dim] = axis_name
            axes_used.append(axis_name)
            local[dim] //= f
    return mesh, P(*entries), axes_used, tuple(local)
