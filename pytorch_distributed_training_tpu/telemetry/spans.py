"""Request-span tracing: one causal tree per request over the JSONL sink.

The serving stack's telemetry was flat per-record events (``serve_request``,
``router_request``, ...) — enough for rates and percentiles, useless for the
question "where did THIS slow request spend its time?". Spans answer it:

- **trace id** = the existing ``X-Request-Id``. The router, every replica a
  hedged/retried attempt lands on, and the engine all emit spans keyed by
  the same id, so ``scripts/trace_view.py`` can merge a fleet's metrics
  streams into one waterfall per request.
- **span** = one named phase with a parent span id, ``time.monotonic()``
  start/end stamps (durations are exact within a process) and wall-clock
  stamps derived at emit time (cross-process alignment is approximate —
  good enough for a waterfall, never used for arithmetic).
- **phase taxonomy** (replica side): ``serve`` is the replica root
  (child of the router's ``attempt`` span when the request came through a
  router), and its children ``queue`` / ``prefill`` / ``decode`` TILE the
  request's lifetime exactly — queue is submit→admit, prefill is
  admit→first-token, decode is first-token→finish — so the per-phase sums
  reconcile against the request's measured total (the bench gate).
  ``admission`` (page reservation) nests under prefill; ``swap_overlap``
  and ``brownout_clamp`` annotate requests a weight swap or overload clamp
  touched. Router side: ``request`` (root) → ``attempt`` → ``hedge``.

``Tracer`` is thread-safe (front-end threads begin what the engine thread
ends); its one mutable counter sits behind the PR-8 named-lock registry
(``concurrency.lock``), never a raw ``threading.Lock``. The module is
deliberately jax-free: routers and fleet coordinators import it in
processes that never touch an accelerator.
"""

from __future__ import annotations

import contextlib
import dataclasses
import time
import uuid
from typing import Optional

from pytorch_distributed_training_tpu.analysis import concurrency

#: replica-side phases that tile a request's submit->finish interval; the
#: summarize/bench reconciliation sums exactly these against the root span
REQUEST_PHASES = ("queue", "prefill", "decode")

#: every span name any instrumentation site emits (trace_view legend)
SPAN_NAMES = (
    "request", "attempt", "hedge",              # router side
    "serve", "queue", "admission", "prefill",   # replica side
    "decode", "swap_overlap", "brownout_clamp",
)


@dataclasses.dataclass
class Span:
    """One live (or retroactively constructed) span; ``Tracer.end`` emits
    it as a ``span`` record and returns it closed."""

    trace: str
    span: str
    name: str
    parent: Optional[str] = None
    t0: float = 0.0                 # time.monotonic()
    t1: Optional[float] = None
    attrs: dict = dataclasses.field(default_factory=dict)

    @property
    def dur_s(self) -> Optional[float]:
        return None if self.t1 is None else max(0.0, self.t1 - self.t0)


class Tracer:
    """Span factory + emitter bound to one MetricsRegistry.

    ``begin``/``end`` take explicit ``t0``/``t1`` overrides so loop-
    structured phases (the engine's tick loop stamps phase boundaries on
    the request as it goes) can emit their spans retroactively with exact
    monotonic bounds; ``span()`` is the context-manager form for linear
    code (the router). Span ids are unique across processes (random
    per-tracer prefix + a counter), which is what lets a replica parent
    its ``serve`` span under a router-generated ``attempt`` span id
    carried over HTTP.
    """

    def __init__(self, *, registry=None, component: str = "",
                 now_fn=None, wall_fn=None):
        if registry is None:
            from pytorch_distributed_training_tpu.telemetry.registry import (
                get_registry,
            )

            registry = get_registry()
        self._registry = registry
        self.component = component
        self._now = now_fn if now_fn is not None else time.monotonic
        self._wall = wall_fn if wall_fn is not None else time.time
        # begin() is called from front-end threads while end() runs on the
        # engine thread: the id counter is the shared state (named lock —
        # the concurrency linter's thread-shared rule)
        self._lock = concurrency.lock("telemetry.spans")
        self._prefix = uuid.uuid4().hex[:6]
        self._seq = 0
        self.emitted = 0

    def _span_id(self) -> str:
        with self._lock:
            self._seq += 1
            n = self._seq
        head = self.component or "span"
        return f"{head}-{self._prefix}-{n}"

    def begin(self, trace: str, name: str, *, parent: Optional[str] = None,
              t0: Optional[float] = None, attrs: Optional[dict] = None,
              ) -> Span:
        return Span(
            trace=str(trace), span=self._span_id(), name=name,
            parent=parent, t0=self._now() if t0 is None else float(t0),
            attrs=dict(attrs or {}),
        )

    def end(self, span: Span, *, t1: Optional[float] = None,
            attrs: Optional[dict] = None) -> Span:
        span.t1 = self._now() if t1 is None else float(t1)
        if attrs:
            span.attrs.update(attrs)
        # wall-clock bounds derived from the monotonic offsets at emit
        # time: cross-process waterfall alignment, never duration math
        mono, wall = self._now(), self._wall()
        with self._lock:
            self.emitted += 1
        self._registry.emit({
            "record": "span",
            "trace": span.trace,
            "span": span.span,
            "parent": span.parent,
            "name": span.name,
            "component": self.component or None,
            "t0_s": span.t0,
            "t1_s": span.t1,
            "dur_s": span.dur_s,
            "wall_t0": wall - (mono - span.t0),
            "wall_t1": wall - (mono - span.t1),
            "attrs": span.attrs,
        })
        return span

    def event(self, trace: str, name: str, *, parent: Optional[str] = None,
              t: Optional[float] = None, attrs: Optional[dict] = None,
              ) -> Span:
        """A zero-duration marker span (e.g. a brownout clamp applied at
        admission)."""
        s = self.begin(trace, name, parent=parent, t0=t, attrs=attrs)
        return self.end(s, t1=s.t0)

    @contextlib.contextmanager
    def span(self, trace: str, name: str, *, parent: Optional[str] = None,
             attrs: Optional[dict] = None):
        s = self.begin(trace, name, parent=parent, attrs=attrs)
        try:
            yield s
        finally:
            self.end(s)


# --------------------------------------------------------- trace analysis


def spans_by_trace(records) -> dict:
    """Group ``span`` records (any iterable of record dicts) by trace id,
    preserving emission order — the merge step for fleet-side analysis."""
    out: dict[str, list] = {}
    for rec in records:
        if rec.get("record") == "span" and rec.get("trace"):
            out.setdefault(str(rec["trace"]), []).append(rec)
    return out


def trace_summary(spans: list) -> dict:
    """Structural verdict for ONE trace's span list.

    A trace is **complete** when it has exactly one root (a span with no
    parent), the root is closed, every span is closed, and every parent id
    resolves to a span within the trace (unresolved parents are orphans —
    the signature of a replica stream that wasn't merged, or a dropped
    root). ``phase_sum_s``/``root_dur_s`` carry the tiling reconciliation
    for the replica phases (summed across replicas for hedged traces;
    compared per-serve-span by callers that need the 5% gate)."""
    roots = [s for s in spans if not s.get("parent")]
    ids = {s.get("span") for s in spans}
    orphans = [
        s for s in spans
        if s.get("parent") and s.get("parent") not in ids
    ]
    open_spans = [s for s in spans if s.get("t1_s") is None]
    serve = [s for s in spans if s.get("name") == "serve"]
    phase_sum = sum(
        s.get("dur_s") or 0.0 for s in spans
        if s.get("name") in REQUEST_PHASES
    )
    serve_dur = sum(s.get("dur_s") or 0.0 for s in serve)
    return {
        "spans": len(spans),
        "roots": len(roots),
        "orphans": len(orphans),
        "open": len(open_spans),
        "complete": (
            len(roots) == 1 and not orphans and not open_spans
        ),
        "root_name": roots[0].get("name") if len(roots) == 1 else None,
        "root_dur_s": roots[0].get("dur_s") if len(roots) == 1 else None,
        "serve_spans": len(serve),
        "serve_dur_s": serve_dur or None,
        "phase_sum_s": phase_sum or None,
        "phase_sum_ok": (
            abs(phase_sum - serve_dur) <= 0.05 * serve_dur
            if serve_dur else None
        ),
    }


def trace_coverage(records, *, accepted_ids=None) -> dict:
    """Fleet-level span coverage over an iterable of records.

    ``accepted_ids`` (when given) restricts the verdict to those trace ids
    — the bench gate: every ACCEPTED request must yield a complete,
    root-closed tree with zero orphans and phase sums reconciling within
    5% of the serve span total. Returns counts plus the offending trace
    ids so a failing gate names its evidence."""
    traces = spans_by_trace(records)
    if accepted_ids is not None:
        wanted = {str(i) for i in accepted_ids}
        traces = {t: s for t, s in traces.items() if t in wanted}
        missing = sorted(wanted - set(traces))
    else:
        missing = []
    complete = 0
    orphan_spans = 0
    incomplete: list[str] = []
    phase_sum_bad: list[str] = []
    for trace, spans in sorted(traces.items()):
        v = trace_summary(spans)
        orphan_spans += v["orphans"]
        if v["complete"]:
            complete += 1
        else:
            incomplete.append(trace)
        if v["phase_sum_ok"] is False:
            phase_sum_bad.append(trace)
    total = len(traces) + len(missing)
    return {
        "traces": total,
        "complete": complete,
        "incomplete": incomplete + missing,
        "orphan_spans": orphan_spans,
        "phase_sum_bad": phase_sum_bad,
        "coverage": (complete / total) if total else 1.0,
    }
