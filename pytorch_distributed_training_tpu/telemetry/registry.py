"""Metric primitives: counters, gauges, timer histograms, one registry.

The reference repo's observability is rank-0 ``print`` (SURVEY.md §5); every
BENCH_*/HISTORY_* artifact in this repo was hand-assembled from it. The
registry is the in-process half of the replacement: instrumentation sites
(loaders, checkpointer, supervisor, the train loop) record into whatever
registry is installed — cheap enough to stay on unconditionally — and the
Trainer snapshots it per epoch. The persistence half is ``sink.JsonlSink``;
when one is attached, ``emit`` forwards event records through it
(process-0-gated inside the sink, so call sites never branch on rank).

A module-level default registry exists so layers with no Trainer handle
(data loaders, the checkpointer, the supervisor) can instrument without
threading a registry through every constructor; the Trainer installs its
own registry as the default for the duration of its run.
"""

from __future__ import annotations

import contextlib
import threading
import time

import numpy as np


class TimerStat:
    """Observations of one timed quantity (seconds); summarizes on demand."""

    __slots__ = ("values",)

    def __init__(self):
        self.values: list[float] = []

    def observe(self, seconds: float) -> None:
        self.values.append(float(seconds))

    def summary(self) -> dict:
        v = np.asarray(self.values, np.float64)
        if v.size == 0:
            return {"count": 0, "total_s": 0.0}
        return {
            "count": int(v.size),
            "total_s": float(v.sum()),
            "mean_s": float(v.mean()),
            "min_s": float(v.min()),
            "max_s": float(v.max()),
            "p50_s": float(np.percentile(v, 50)),
            "p95_s": float(np.percentile(v, 95)),
        }


class MetricsRegistry:
    """Counters + gauges + timer histograms, with an optional JSONL sink.

    - counters are monotonic per snapshot window (``inc``);
    - gauges hold the last value set (``gauge``);
    - timers accumulate observations in seconds (``observe`` or the
      ``timer(name)`` context manager) and summarize to
      count/total/mean/min/max/p50/p95.

    ``snapshot(reset=True)`` returns the current window and optionally
    clears it (the Trainer resets per epoch so epoch records don't smear).
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = {}
        self._gauges: dict[str, float] = {}
        self._timers: dict[str, TimerStat] = {}
        self._sink = None

    # ------------------------------------------------------------- recording

    def inc(self, name: str, n: float = 1) -> None:
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = float(value)

    def observe(self, name: str, seconds: float) -> None:
        with self._lock:
            stat = self._timers.get(name)
            if stat is None:
                stat = self._timers[name] = TimerStat()
            stat.observe(seconds)

    @contextlib.contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.observe(name, time.perf_counter() - t0)

    # ----------------------------------------------------------------- sink

    def attach_sink(self, sink) -> None:
        self._sink = sink

    @property
    def sink(self):
        return self._sink

    def emit(self, record: dict) -> None:
        """Forward an event record to the attached sink (no-op without one;
        the sink itself gates on process 0)."""
        if self._sink is not None:
            self._sink.emit(record)

    # ------------------------------------------------------------- snapshot

    def snapshot(self, *, reset: bool = False) -> dict:
        with self._lock:
            out = {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "timers": {k: s.summary() for k, s in self._timers.items()},
            }
            if reset:
                self._counters.clear()
                self._gauges.clear()
                self._timers.clear()
        return out


_DEFAULT: MetricsRegistry | None = None
_DEFAULT_LOCK = threading.Lock()


def get_registry() -> MetricsRegistry:
    """The process-wide default registry (created lazily)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        if _DEFAULT is None:
            _DEFAULT = MetricsRegistry()
        return _DEFAULT


def set_registry(registry: MetricsRegistry | None) -> MetricsRegistry | None:
    """Install ``registry`` as the process default; returns the previous one
    (pass it back to restore — tests and nested Trainers)."""
    global _DEFAULT
    with _DEFAULT_LOCK:
        prev = _DEFAULT
        _DEFAULT = registry
        return prev
