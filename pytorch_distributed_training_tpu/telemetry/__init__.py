"""Structured telemetry: metric registry, JSONL sink, straggler detection.

The three pieces, wired together by the Trainer (train/loop.py):

- ``MetricsRegistry`` (registry.py) — counters/gauges/timer histograms that
  instrumentation sites record into; a process-wide default registry lets
  loaders, the checkpointer and the supervisor instrument without plumbing;
- ``JsonlSink`` (sink.py) — process-0-gated append-only JSONL stream
  (``--metrics-dir``): run-metadata header, per-step timing breakdown,
  per-epoch records, checkpoint/restart events;
- ``epoch_straggler_stats`` (straggler.py) — cross-host step-time gather so
  process 0 can name the slowest host instead of just a slow fleet.

The serving observability plane layers on top of the same sink:

- ``Tracer``/``Span`` (spans.py) — request-span tracing keyed by
  ``X-Request-Id``; ``trace_coverage`` is the bench/test completeness
  verdict;
- ``FlightRecorder`` (flight.py) — ring-buffer of engine tick summaries
  dumped as ``flight_dump`` records on watchdog stall, fatal tick,
  SIGTERM and ``/debug/flight``;
- ``BurnRateMonitor`` (slo.py) — per-tier multi-window SLO burn rates
  (``slo_burn`` records + the optional autoscaler/brownout signal).

``scripts/summarize_metrics.py`` folds a stream back into a per-epoch table;
``scripts/trace_view.py`` renders one trace's waterfall + a fleet timeline.
"""

from pytorch_distributed_training_tpu.telemetry.flight import (
    FlightRecorder,
)
from pytorch_distributed_training_tpu.telemetry.registry import (
    MetricsRegistry,
    TimerStat,
    get_registry,
    set_registry,
)
from pytorch_distributed_training_tpu.telemetry.sink import (
    JsonlSink,
    run_metadata,
)
from pytorch_distributed_training_tpu.telemetry.slo import (
    BurnRateMonitor,
    SloConfig,
)
from pytorch_distributed_training_tpu.telemetry.spans import (
    Span,
    Tracer,
    trace_coverage,
)
from pytorch_distributed_training_tpu.telemetry.straggler import (
    epoch_straggler_stats,
)

__all__ = [
    "MetricsRegistry",
    "TimerStat",
    "JsonlSink",
    "run_metadata",
    "epoch_straggler_stats",
    "get_registry",
    "set_registry",
    "Tracer",
    "Span",
    "trace_coverage",
    "FlightRecorder",
    "BurnRateMonitor",
    "SloConfig",
]
