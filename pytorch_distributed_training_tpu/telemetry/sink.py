"""Process-0-gated JSONL metrics sink + the run-metadata header.

One record per line, appended and flushed as they happen, so a crashed or
preempted run leaves a readable stream up to its last completed step — the
machine-readable replacement for hand-assembling BENCH_*/HISTORY_* artifacts
from rank-0 prints. Record types written by the framework:

- ``run_meta``   — one header per (re)started run: mesh shape, chip/process
                   counts, jax version, the fully-resolved model/train config;
- ``step``       — per-step timing breakdown (data wait, dispatch, device
                   block) + loss; ``compile_inclusive`` marks the first step;
- ``epoch``      — the Trainer's history record + straggler stats + the
                   epoch's timer summaries (checkpoint/loader/eval timings);
- ``checkpoint_save`` / ``checkpoint_restore`` / ``restart`` — events.

Every record gains a ``ts`` wall-clock field at write time. The file opens
in append mode: a supervised restart (utils/supervisor.py) continues the
same stream, with a fresh ``run_meta`` header marking the attempt boundary.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from typing import Any

import jax

from pytorch_distributed_training_tpu.analysis import concurrency


def _jsonable(x: Any):
    """Best-effort coercion for config values (paths, numpy scalars)."""
    if isinstance(x, (str, int, float, bool)) or x is None:
        return x
    if isinstance(x, dict):
        return {str(k): _jsonable(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_jsonable(v) for v in x]
    try:
        return float(x)
    except (TypeError, ValueError):
        return str(x)


class JsonlSink:
    """Append-mode JSONL writer, active on process 0 only.

    Construct it on every process — non-0 processes get an inert sink, so
    call sites (checkpointer, supervisor, loaders) never branch on rank.
    """

    def __init__(
        self,
        metrics_dir: str,
        *,
        filename: str = "metrics.jsonl",
        process_index: int | None = None,
    ):
        pidx = jax.process_index() if process_index is None else process_index
        self._file = None
        # serving emits from many threads at once (router request handlers,
        # the health loop, fleet monitors); a lock keeps each JSONL line
        # atomic — interleaved torn lines would poison the whole stream.
        # Instrumented: sink contention is the first suspect when every
        # thread funnels telemetry through one file (per-acquire stats are
        # in-memory only, so instrumenting the sink's own lock can't
        # recurse into emit)
        self._lock = concurrency.lock("telemetry.sink")
        self.path = os.path.join(os.path.abspath(metrics_dir), filename)
        if pidx == 0:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            self._file = open(self.path, "a")

    @property
    def active(self) -> bool:
        return self._file is not None

    def emit(self, record: dict) -> None:
        if self._file is None:
            return
        rec = dict(record)
        rec.setdefault("ts", time.time())
        line = json.dumps(_jsonable(rec)) + "\n"
        with self._lock:
            if self._file is None:      # closed while we serialized
                return
            self._file.write(line)
            self._file.flush()

    def flush(self, *, fsync: bool = False) -> None:
        """Push buffered records to the OS — and with ``fsync``, to disk.
        The crash/preemption/watchdog exits call this so the last records
        (the ones explaining the exit) survive the process."""
        with self._lock:
            if self._file is None:
                return
            self._file.flush()
            if fsync:
                os.fsync(self._file.fileno())

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


def run_metadata(mesh, model_config=None, train_config=None, **extra) -> dict:
    """The ``run_meta`` header record: everything needed to interpret the
    stream without the launching shell — mesh shape, chip count, resolved
    configs, jax version."""
    rec = {
        "record": "run_meta",
        "mesh_shape": {k: int(v) for k, v in dict(mesh.shape).items()},
        "chip_count": len(mesh.devices.flat),
        "process_count": jax.process_count(),
        "jax_version": jax.__version__,
        "backend": jax.default_backend(),
        "config": {},
    }
    for key, cfg in (("model", model_config), ("train", train_config)):
        if cfg is not None:
            rec["config"][key] = _jsonable(dataclasses.asdict(cfg))
    rec.update(extra)
    return rec
