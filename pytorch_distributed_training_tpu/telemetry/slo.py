"""Per-tier multi-window SLO burn-rate monitors.

The autoscaler and brownout controller act on INSTANTANEOUS pressure
(queue depth, page occupancy). Burn rate is the budget view: over each
window, what fraction of the tier's error budget is being consumed?

    burn = (1 - good_ratio) / (1 - objective)

burn 1.0 means failures arrive exactly at the rate the objective budgets
for; burn 10 over a short window plus burn >1 over a long one is the
classic page-worthy condition. Two good-ratios are tracked per tier:

- **deadline-met**: of finished requests that CARRIED a deadline, the
  fraction that completed instead of expiring (the engine feeds this from
  its finish path);
- **availability**: the fraction of requests that got served at all —
  errors, fail-fast sheds and router-level rejections count against it
  (the engine, HTTP front-end and router all feed it).

``BurnRateMonitor`` emits an ``slo_burn`` record (throttled to
``emit_interval_s``) and a ``slo/max_burn`` gauge. The autoscaler and the
brownout controller accept the monitor as an OPTIONAL input signal —
plumbed but default-off (``slo_burn_high=0``), so existing policy and the
storm bench's semantics are unchanged until a deployment opts in.

Clocks are injectable (``now_fn``) so the window math is testable without
sleeps. Events sit behind a named lock from the PR-8 registry — observe()
is called from the engine thread, HTTP threads and the router at once.
Jax-free.
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque
from typing import Optional

from pytorch_distributed_training_tpu.analysis import concurrency

#: default burn windows: the fast window catches an active incident, the
#: slow one keeps a lingering simmer visible after the spike passes
DEFAULT_WINDOWS_S = (300.0, 3600.0)


@dataclasses.dataclass(frozen=True)
class SloConfig:
    """Objectives + windows + emission cadence."""

    windows_s: tuple = DEFAULT_WINDOWS_S
    #: objective on the deadline-met ratio of deadline-carrying requests
    deadline_objective: float = 0.99
    #: objective on the served-at-all ratio
    availability_objective: float = 0.999
    #: min seconds between ``slo_burn`` records (0 = every observe)
    emit_interval_s: float = 5.0

    def __post_init__(self):
        if not self.windows_s or list(self.windows_s) != sorted(
            float(w) for w in self.windows_s
        ):
            raise ValueError(
                f"windows_s must be sorted positive seconds, got "
                f"{self.windows_s!r}"
            )
        for obj in (self.deadline_objective, self.availability_objective):
            if not 0.0 < obj < 1.0:
                raise ValueError(
                    f"objectives must sit in (0, 1), got {obj}"
                )


def burn_rate(good: int, total: int, objective: float) -> float:
    """Error-budget burn for one window (0.0 when the window is empty —
    no traffic burns no budget)."""
    if total <= 0:
        return 0.0
    bad_ratio = 1.0 - good / total
    return bad_ratio / (1.0 - objective)


class BurnRateMonitor:
    """Sliding-window burn accounting per tier."""

    def __init__(self, config: Optional[SloConfig] = None, *,
                 tiers=("interactive", "batch"), registry=None,
                 now_fn=None):
        self.config = config or SloConfig()
        self.tiers = tuple(tiers)
        if registry is None:
            from pytorch_distributed_training_tpu.telemetry.registry import (
                get_registry,
            )

            registry = get_registry()
        self._registry = registry
        self._now = now_fn if now_fn is not None else time.monotonic
        # events arrive from the engine thread, HTTP handler threads and
        # the router's request path at once
        self._lock = concurrency.lock("telemetry.slo")
        # per tier: deque of (t, deadline_met: bool|None, available: bool)
        self._events: dict[str, deque] = {t: deque() for t in self.tiers}
        self._last_emit_t: Optional[float] = None
        self.observed = 0

    # -------------------------------------------------------------- feeding

    def observe(self, tier: str, *, available: bool,
                deadline_met: Optional[bool] = None,
                now: Optional[float] = None) -> None:
        """One request outcome. ``deadline_met=None`` means the request
        carried no deadline (it never touches the deadline ratio)."""
        if tier not in self._events:
            tier = self.tiers[0]
        now = self._now() if now is None else now
        horizon = now - self.config.windows_s[-1]
        with self._lock:
            dq = self._events[tier]
            dq.append((now, deadline_met, bool(available)))
            while dq and dq[0][0] < horizon:
                dq.popleft()
            self.observed += 1
            emit = (
                self._last_emit_t is None
                or now - self._last_emit_t >= self.config.emit_interval_s
            )
            if emit:
                self._last_emit_t = now
        if emit:
            self.emit_now(now=now)

    # ------------------------------------------------------------- queries

    def burn_rates(self, now: Optional[float] = None) -> dict:
        """``{tier: {window_label: {requests, deadline_met,
        availability, deadline_burn, availability_burn}}}``."""
        cfg = self.config
        now = self._now() if now is None else now
        with self._lock:
            events = {t: list(dq) for t, dq in self._events.items()}
        out: dict[str, dict] = {}
        for tier, evs in events.items():
            tier_out: dict[str, dict] = {}
            for window in cfg.windows_s:
                cut = now - window
                in_win = [e for e in evs if e[0] >= cut]
                dl = [e for e in in_win if e[1] is not None]
                dl_good = sum(1 for e in dl if e[1])
                av_good = sum(1 for e in in_win if e[2])
                label = f"{int(window)}s"
                tier_out[label] = {
                    "requests": len(in_win),
                    "deadline_requests": len(dl),
                    "deadline_met": (
                        dl_good / len(dl) if dl else None
                    ),
                    "availability": (
                        av_good / len(in_win) if in_win else None
                    ),
                    "deadline_burn": burn_rate(
                        dl_good, len(dl), cfg.deadline_objective
                    ),
                    "availability_burn": burn_rate(
                        av_good, len(in_win), cfg.availability_objective
                    ),
                }
            out[tier] = tier_out
        return out

    def max_burn(self, now: Optional[float] = None) -> float:
        """Worst burn across tiers, windows and both ratios — the single
        gauge the autoscaler/brownout coupling keys on."""
        worst = 0.0
        for windows in self.burn_rates(now).values():
            for w in windows.values():
                worst = max(
                    worst, w["deadline_burn"], w["availability_burn"]
                )
        return worst

    # ------------------------------------------------------------- emission

    def emit_now(self, now: Optional[float] = None) -> dict:
        """Emit one ``slo_burn`` record + the ``slo/max_burn`` gauge."""
        now = self._now() if now is None else now
        tiers = self.burn_rates(now)
        worst = 0.0
        for windows in tiers.values():
            for w in windows.values():
                worst = max(
                    worst, w["deadline_burn"], w["availability_burn"]
                )
        record = {
            "record": "slo_burn",
            "windows_s": [float(w) for w in self.config.windows_s],
            "deadline_objective": self.config.deadline_objective,
            "availability_objective": self.config.availability_objective,
            "tiers": tiers,
            "max_burn": worst,
        }
        self._registry.gauge("slo/max_burn", worst)
        self._registry.emit(record)
        return record

    def stats(self) -> dict:
        with self._lock:
            return {
                "slo_observed": self.observed,
                "slo_windows_s": list(self.config.windows_s),
            }
