"""Engine flight recorder: the last N tick summaries, dumped post-mortem.

The serve failure drills (crash, hang, preemption) used to die with a
stack trace and flat counters — the stack says WHERE the loop wedged, the
counters say nothing about the ticks leading up to it. The flight recorder
is the black box in between: the engine appends one bounded summary per
interesting tick (phase mix, slots, pages, dispatch ms, swap/brownout
events) into a fixed-size ring, and the ring is dumped as one
``flight_dump`` telemetry record when something goes wrong:

- **watchdog stall/abort** (faults/watchdog.py calls ``dump_all``) — the
  dump's last entries ARE the stalled tick's run-up;
- **fatal tick** (serve/server.py's loop failure path);
- **SIGTERM drain** (cli/serve_lm.py) — what the replica was doing when
  the preemption landed;
- **on demand** via ``GET /debug/flight`` on a live replica.

Writers are the engine thread; dumpers are the watchdog monitor, HTTP
handler threads and signal-drain threads — the ring sits behind a named
lock from the PR-8 registry (``concurrency.lock``), never a raw
``threading.Lock``. Jax-free by design.
"""

from __future__ import annotations

import time
from collections import deque

from pytorch_distributed_training_tpu.analysis import concurrency

#: default ring capacity — enough run-up to see a stall pattern, small
#: enough that a dump record stays one readable JSONL line
DEFAULT_CAPACITY = 256

#: entries included verbatim in a ``flight_dump`` record (the full ring is
#: available via ``snapshot()``/``/debug/flight``; the emitted record keeps
#: the tail, which is where the evidence lives)
DUMP_TAIL = 64


class FlightRecorder:
    """Bounded ring of tick summaries with one-call post-mortem dumps."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY, *,
                 component: str = "engine", registry=None):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        if registry is None:
            from pytorch_distributed_training_tpu.telemetry.registry import (
                get_registry,
            )

            registry = get_registry()
        self._registry = registry
        self.component = component
        self.capacity = capacity
        # engine thread records; watchdog/HTTP/drain threads dump
        self._lock = concurrency.lock("telemetry.flight")
        self._ring: deque = deque(maxlen=capacity)
        self._seq = 0
        self.recorded = 0
        self.dumps = 0
        self.last_dump_reason = None

    def record(self, **entry) -> None:
        """Append one tick summary (engine thread, once per busy/eventful
        tick). Entries get a monotonic sequence number so a dump shows
        gaps (idle stretches) honestly."""
        with self._lock:
            self._seq += 1
            self._ring.append({"seq": self._seq, **entry})
            self.recorded += 1

    def snapshot(self) -> list:
        """The current ring contents, oldest first (any thread)."""
        with self._lock:
            return [dict(e) for e in self._ring]

    def dump(self, reason: str, *, attrs: dict = None) -> dict:
        """Emit the ring as one ``flight_dump`` record and return it."""
        with self._lock:
            entries = [dict(e) for e in self._ring]
            self.dumps += 1
            self.last_dump_reason = reason
            dumps = self.dumps
        record = {
            "record": "flight_dump",
            "component": self.component,
            "reason": reason,
            "capacity": self.capacity,
            "depth": len(entries),
            "dropped": max(0, self._seq - len(entries)),
            "dumps": dumps,
            "dumped_at": time.time(),
            "entries": entries[-DUMP_TAIL:],
            **(attrs or {}),
        }
        self._registry.emit(record)
        return record

    def stats(self) -> dict:
        with self._lock:
            return {
                "flight_capacity": self.capacity,
                "flight_depth": len(self._ring),
                "flight_recorded": self.recorded,
                "flight_dumps": self.dumps,
                "flight_last_dump": self.last_dump_reason,
            }


# ----------------------------------------------------- process-wide hookup
#
# The watchdog monitor (faults/watchdog.py) fires in layers that hold no
# engine handle; recorders register here so ``dump_all`` can reach every
# live ring in the process without plumbing.

_registered: list = []
_reg_lock = concurrency.lock("telemetry.flight.registry")


def register(recorder: FlightRecorder) -> FlightRecorder:
    with _reg_lock:
        if recorder not in _registered:
            _registered.append(recorder)
    return recorder


def unregister(recorder: FlightRecorder) -> None:
    with _reg_lock:
        if recorder in _registered:
            _registered.remove(recorder)


def registered() -> list:
    with _reg_lock:
        return list(_registered)


def dump_all(reason: str) -> int:
    """Dump every registered recorder (watchdog stall/abort path); returns
    how many dumps were emitted. Never raises — this runs on failure paths
    that must keep making progress."""
    n = 0
    for recorder in registered():
        try:
            recorder.dump(reason)
            n += 1
        except Exception:  # pragma: no cover - failure-path best effort
            pass
    return n
