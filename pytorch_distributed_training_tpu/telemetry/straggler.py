"""Cross-host straggler detection from per-host step-time samples.

On a multi-host run every step is a barrier: the global batch ships as one
sharded array and the gradient psum can't complete until the slowest host
has dispatched. A host that assembles batches slowly (cold page cache, a
noisy neighbor, a dying NIC) therefore taxes EVERY host's step time, and
rank-0's own wall clock can't tell which host it was. At each epoch
boundary the Trainer all-gathers per-host step-time stats over the existing
host collectives (comms/collectives.py — the same ``process_allgather``
path the eval metrics ride) and process 0 reports the slowest host and the
skew: ``wait_skew_s`` is how much mean step time the fleet would shed if
the slowest host matched the fastest — the number that says "fix host k"
instead of "the run is slow".

Single-process runs degrade to a report over host 0 alone (skew 0), so the
epoch record schema is identical everywhere.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

from pytorch_distributed_training_tpu.comms.collectives import host_allgather

# per-host stat vector layout: [mean, max, min, count, data_wait_mean]
_STAT_WIDTH = 5


def epoch_straggler_stats(
    step_times: Sequence[float],
    data_waits: Sequence[float] | None = None,
) -> dict:
    """All-gather this host's step-time stats; return the fleet summary.

    Collective: every process must call this the same number of times per
    epoch (the Trainer calls it exactly once, at the epoch boundary —
    the same cadence contract the eval metric gather already obeys).
    """
    st = np.asarray(step_times, np.float64)
    dw = np.asarray(
        data_waits if data_waits is not None else [], np.float64
    )
    local = np.array(
        [
            st.mean() if st.size else 0.0,
            st.max() if st.size else 0.0,
            st.min() if st.size else 0.0,
            float(st.size),
            dw.mean() if dw.size else 0.0,
        ],
        np.float64,
    )
    gathered = host_allgather(local).reshape(-1, _STAT_WIDTH)
    means = gathered[:, 0]
    slowest = int(np.argmax(means))
    fastest = int(np.argmin(means))
    return {
        "hosts": int(gathered.shape[0]),
        "slowest_host": slowest,
        "slowest_host_mean_step_s": float(means[slowest]),
        "fastest_host": fastest,
        "fastest_host_mean_step_s": float(means[fastest]),
        "wait_skew_s": float(means[slowest] - means[fastest]),
        "slowest_host_max_step_s": float(gathered[slowest, 1]),
        "slowest_host_data_wait_mean_s": float(gathered[slowest, 4]),
        "per_host_mean_step_s": [float(m) for m in means],
    }
