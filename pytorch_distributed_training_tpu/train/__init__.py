from pytorch_distributed_training_tpu.train.optim import (
    adamw_with_schedule,
    linear_warmup_schedule,
)
from pytorch_distributed_training_tpu.train.state import TrainState, create_train_state
from pytorch_distributed_training_tpu.train.step import (
    calibrate_quant,
    make_eval_step,
    make_train_step,
)
from pytorch_distributed_training_tpu.train.metrics import MetricAccumulator

__all__ = [
    "adamw_with_schedule",
    "linear_warmup_schedule",
    "TrainState",
    "create_train_state",
    "make_train_step",
    "make_eval_step",
    "calibrate_quant",
    "MetricAccumulator",
]
