"""In-repo eval metrics: GLUE accuracy + F1 from confusion counts.

The reference computes metrics with HF ``load_metric("glue", "mrpc")``
(reference test_data_parallelism.py:71,159-164), gathering full prediction
tensors across ranks first (``accelerator.gather`` :160-161; hand-rolled
allgather, test_model_parallelism.py:302-310). Network-free and
gather-free here: the eval step reduces each batch to five masked counts
(correct/total/tp/fp/fn) on device; hosts only ever fold scalars. Identical
results to sklearn/HF definitions — accuracy = correct/total, binary F1 =
2tp / (2tp + fp + fn) — verified in tests against sklearn-style closed forms.
"""

from __future__ import annotations

import numpy as np


class MetricAccumulator:
    """Folds per-batch count dicts; computes accuracy (+ F1 when binary)."""

    FIELDS = ("correct", "total", "tp", "fp", "fn")

    def __init__(self, num_labels: int = 2):
        self.num_labels = num_labels
        self.reset()

    def reset(self) -> None:
        self._c = {k: 0.0 for k in self.FIELDS}

    def update(self, counts: dict) -> None:
        for k in self.FIELDS:
            if k in counts:
                self._c[k] += float(np.asarray(counts[k]))

    def compute(self) -> dict:
        total = self._c["total"]
        out = {"accuracy": self._c["correct"] / total if total else 0.0}
        if self.num_labels == 2:
            denom = 2 * self._c["tp"] + self._c["fp"] + self._c["fn"]
            out["f1"] = 2 * self._c["tp"] / denom if denom else 0.0
        return out


class LMMetricAccumulator:
    """Folds causal-LM eval counts → eval loss, perplexity, token accuracy."""

    FIELDS = ("nll_sum", "token_count", "token_correct")

    def __init__(self, num_labels: int = 0):  # signature-compatible
        self.reset()

    def reset(self) -> None:
        self._c = {k: 0.0 for k in self.FIELDS}

    def update(self, counts: dict) -> None:
        for k in self.FIELDS:
            if k in counts:
                self._c[k] += float(np.asarray(counts[k]))

    def compute(self) -> dict:
        n = self._c["token_count"]
        nll = self._c["nll_sum"] / n if n else 0.0
        return {
            "eval_loss": nll,
            "perplexity": float(np.exp(min(nll, 30.0))),
            "token_accuracy": self._c["token_correct"] / n if n else 0.0,
        }
