"""AdamW with configurable moment dtypes — optax-compatible, HBM-lean.

The optimizer tail of the bert-large step is pure HBM traffic: ~335M params
x (param read/write + mu read/write + nu read/write + grad read) once per
global batch. optax.adamw exposes ``mu_dtype`` but always stores ``nu`` in
the param dtype; storing nu in bf16 as well cuts another 8 bytes/param of
traffic (~1.6 ms/step on v5e). This transformation replicates
``optax.adamw`` exactly (same state layout per-leaf, same bias-correction
and decay math, all arithmetic in fp32) with both moment dtypes settable.

Numerical contract:
- ``mu_dtype=nu_dtype=float32`` matches ``optax.adamw`` to within 1 ulp
  per step (moments are bit-identical; the update differs only in XLA's
  fusion ordering of the two bias-correction divisions). Pinned by
  tests/test_train.py::test_fused_adamw_matches_optax at rtol 1e-6 over
  5 steps, plus the closed-form AdamW test.
- bf16 nu adds ~0.4% relative error to sqrt(nu_hat); with eps=1e-8 the
  update direction error is ~2^-9 per step. Convergence-checked on the
  MRPC recipe (loss trajectory within float noise, eval metrics identical
  — see NOTES.md r2 ledger) before becoming the bench default.

The reference relies on transformers' ``AdamW(correct_bias=True)``
(reference test_data_parallelism.py:120); this keeps that math.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import chex
import jax
import jax.numpy as jnp
import optax


class ScaleByAdamFusedState(NamedTuple):
    count: chex.Array  # int32 scalar
    mu: optax.Updates
    nu: optax.Updates


def scale_by_adam_fused(
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    mu_dtype: Optional[str] = None,
    nu_dtype: Optional[str] = None,
) -> optax.GradientTransformation:
    """optax.scale_by_adam twin with a ``nu_dtype`` knob.

    Moments are STORED in the given dtypes but all update math runs in
    fp32 (moments are upcast before use, like optax's mu_dtype handling).
    """
    mu_dt = jnp.dtype(mu_dtype) if mu_dtype else None
    nu_dt = jnp.dtype(nu_dtype) if nu_dtype else None

    def init(params):
        mu = jax.tree.map(
            lambda p: jnp.zeros_like(p, dtype=mu_dt or p.dtype), params
        )
        nu = jax.tree.map(
            lambda p: jnp.zeros_like(p, dtype=nu_dt or p.dtype), params
        )
        return ScaleByAdamFusedState(
            count=jnp.zeros([], jnp.int32), mu=mu, nu=nu
        )

    def update(updates, state, params=None):
        del params
        # optax renamed safe_int32_increment -> safe_increment; accept both
        # so the optimizer works across the versions this image may carry
        _safe_inc = getattr(
            optax, "safe_increment", None
        ) or optax.safe_int32_increment
        count_inc = _safe_inc(state.count)
        # integer-exponent pow, exactly as optax's bias_correction computes
        # it (an explicit float cast here costs a ulp vs optax)
        b1c = 1 - b1 ** count_inc
        b2c = 1 - b2 ** count_inc

        def one(g, mu, nu):
            # upcast in-register: callers may hand over bf16 grads (the
            # accumulation-carry dtype) without materializing fp32 copies
            gf = g.astype(jnp.float32)
            mu_new = b1 * mu.astype(jnp.float32) + (1 - b1) * gf
            # (1-b2)*(g*g), NOT ((1-b2)*g)*g: the grouping must match
            # optax's update_moment_per_elem_norm for bit-equality
            nu_new = b2 * nu.astype(jnp.float32) + (1 - b2) * (gf * gf)
            upd = (mu_new / b1c) / (jnp.sqrt(nu_new / b2c) + eps)
            return (
                upd,  # fp32 always: downstream lr-scale/apply are fp32
                mu_new.astype(mu_dt or mu.dtype),
                nu_new.astype(nu_dt or nu.dtype),
            )

        flat = jax.tree.map(one, updates, state.mu, state.nu)
        upd = jax.tree.map(lambda t: t[0], flat, is_leaf=lambda t: isinstance(t, tuple))
        mu = jax.tree.map(lambda t: t[1], flat, is_leaf=lambda t: isinstance(t, tuple))
        nu = jax.tree.map(lambda t: t[2], flat, is_leaf=lambda t: isinstance(t, tuple))
        return upd, ScaleByAdamFusedState(count=count_inc, mu=mu, nu=nu)

    return optax.GradientTransformation(init, update)


def adamw_fused(
    learning_rate,
    *,
    b1: float = 0.9,
    b2: float = 0.999,
    eps: float = 1e-8,
    weight_decay: float = 0.0,
    mu_dtype: Optional[str] = None,
    nu_dtype: Optional[str] = None,
) -> optax.GradientTransformation:
    """``optax.adamw`` twin: bias-corrected Adam + decoupled weight decay +
    schedule, with both moment dtypes settable."""
    return optax.chain(
        scale_by_adam_fused(
            b1=b1, b2=b2, eps=eps, mu_dtype=mu_dtype, nu_dtype=nu_dtype
        ),
        # unconditional (a no-op at 0.0) so the opt-state TREE STRUCTURE
        # does not depend on the hyperparameter — checkpoints restore
        # across weight_decay changes, matching optax.adamw's layout
        optax.add_decayed_weights(weight_decay),
        optax.scale_by_learning_rate(learning_rate),
    )
