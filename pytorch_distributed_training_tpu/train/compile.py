"""Warm-start compilation: persistent XLA cache + AOT step compiles.

Two independent levers against cold-start latency, both wired through the
Trainer (train/loop.py) and all three train CLIs via ``TrainConfig``:

- ``enable_persistent_cache(dir)`` (``--compile-cache-dir``) points JAX's
  persistent compilation cache at ``dir`` so a second run of the same
  recipe loads compiled executables instead of re-invoking XLA. The
  thresholds are dropped to zero so even sub-second CPU smoke compiles
  persist — warm start must cover the tiny configs tests exercise, not
  just hour-long TPU compiles.
- ``aot_warm_start(...)`` lowers and compiles the train/eval steps against
  the loaders' ``batch_spec()`` BEFORE epoch 0, so the first step of the
  run is a normal steady-state step: compile wall time moves out of the
  step stream into its own ``compile`` telemetry record (with a cache-hit
  flag when a cache dir is configured), the per-step ``compile_inclusive``
  flag disappears, and the watchdog can arm from step 1.

The compiled executables keep the jitted functions' donation and sharding
contracts (AOT lowering carries ``donate_argnums``/``in_shardings``), so
the Trainer swaps them in place of the jit wrappers and the step loop is
unchanged.
"""

from __future__ import annotations

import os
import time

import jax
from jax.sharding import NamedSharding


def enable_persistent_cache(cache_dir: str | None) -> str | None:
    """Enable JAX's persistent compilation cache rooted at ``cache_dir``.

    Returns the absolute cache path (None when disabled). Process-global:
    every jit compile from here on — state init, calibration, train/eval
    steps — reads/writes the cache.
    """
    if not cache_dir:
        return None
    path = os.path.abspath(cache_dir)
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    return path


def cache_entry_count(cache_dir: str | None) -> int | None:
    """Number of cache entries currently on disk (None when no dir)."""
    if not cache_dir or not os.path.isdir(cache_dir):
        return None
    n = 0
    for _, _, files in os.walk(cache_dir):
        n += sum(1 for f in files if not f.startswith("."))
    return n


def _attach_shardings(spec_tree, mesh, pspec):
    """ShapeDtypeStructs -> sharded ShapeDtypeStructs under ``pspec`` (the
    exact placement ``make_global_batch`` commits real batches to)."""
    sharding = NamedSharding(mesh, pspec)
    return jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sharding),
        spec_tree,
    )


def aot_warm_start(
    *,
    train_step,
    eval_step,
    state,
    train_spec,
    eval_spec,
    mesh,
    train_pspec,
    eval_pspec,
    cache_dir: str | None = None,
    registry=None,
    guard_mode: str = "off",
    comm_manifest=None,
):
    """AOT-compile the steps against abstract batches; returns
    ``(compiled_train, compiled_eval, record)``.

    ``train_spec``/``eval_spec`` are the loaders' ``batch_spec()`` pytrees;
    ``state`` is the concrete (already sharded) TrainState, which pins the
    state avals exactly. Raises on lowering/compile failure — the caller
    decides whether to fall back to the lazy jit path.

    With ``guard_mode`` != "off" the compiled train step gets the
    post-lower donation audit (analysis/guards.py): the step donates its
    state, and an executable that aliases nothing means XLA dropped the
    donation — optimizer state would sit double-resident in HBM. The
    audit emits a ``donation_audit`` record through ``registry`` (strict:
    raises).

    With a ``comm_manifest`` (``analysis/spmd/manifest.CommManifest``,
    typically ``train_manifest(mesh)``) the compiled train step's
    collective footprint is also audited — the compiled object is already
    in hand here, so the comm audit costs one ``as_text()`` parse, not an
    extra compile.
    """
    entries_before = cache_entry_count(cache_dir)
    t0 = time.perf_counter()
    compiled_train = train_step.lower(
        state, _attach_shardings(train_spec, mesh, train_pspec)
    ).compile()
    train_s = time.perf_counter() - t0
    if guard_mode != "off":
        from pytorch_distributed_training_tpu.analysis.guards import (
            donation_audit,
        )

        donation_audit(
            "train_step", compiled_train,
            registry=registry, mode=guard_mode,
        )
        if comm_manifest is not None:
            from pytorch_distributed_training_tpu.analysis.spmd.manifest import (
                comm_audit,
            )

            comm_audit(
                "train_step", compiled_train, comm_manifest,
                registry=registry, mode=guard_mode,
            )
    t0 = time.perf_counter()
    compiled_eval = eval_step.lower(
        state, _attach_shardings(eval_spec, mesh, eval_pspec)
    ).compile()
    eval_s = time.perf_counter() - t0
    entries_after = cache_entry_count(cache_dir)
    cache_hit = None
    if entries_before is not None:
        # no new entries appeared and the cache wasn't empty -> every
        # compile was served from disk
        cache_hit = entries_before > 0 and entries_after == entries_before
    record = {
        "record": "compile",
        "aot": True,
        "train_compile_s": train_s,
        "eval_compile_s": eval_s,
        "compile_s": train_s + eval_s,
        "cache_dir": cache_dir,
        "cache_hit": cache_hit,
        "cache_entries": entries_after,
        "backend": jax.default_backend(),
    }
    return compiled_train, compiled_eval, record
