"""Per-checkpoint integrity manifests: commit markers that can prove it.

Orbax commits a step atomically on a POSIX filesystem, but "the directory
exists" is not "the bytes are right": a torn GCS upload, a disk error, an
operator's stray ``rm``, or a truncated copy between machines all leave a
step that ``latest_step()`` happily returns and restore then dies (or
worse, silently half-loads) on. The manifest is written AFTER orbax
finishes committing a step, next to it, and records:

- the saved pytree's structure (per-leaf path, shape, dtype) — catches a
  checkpoint written by an incompatible config before orbax's opaque
  tree-mismatch error does;
- a file inventory of the committed step directory (per-file byte size +
  sha256) — catches truncation and partial writes by size, bit rot and
  overwrites by digest;
- framework versions and a wall-clock stamp — the provenance a post-mortem
  needs.

The manifest doubles as the PUBLISH SIGNAL for live consumers — the
serve-side hot-swap watcher (serve/hotswap.py) admits a step the moment
its manifest verifies — so ``write_manifest`` enforces durability order:
every file the manifest names (and its directory) is fsynced before the
seal rename, and the rename itself is fsynced after; a host crash
mid-publish can leave an unsealed step, never a seal over torn bytes.

``verify_step`` is the single checker behind ``Checkpointer.restore``'s
fall-back-to-newest-verified-step walk and the offline
``scripts/verify_checkpoint.py`` validator. Verification levels: ``"size"``
(cheap: existence + byte sizes; catches truncation/partial commits) and
``"digest"`` (full sha256 re-hash; catches same-size corruption — what
``--strict`` uses). A step with no manifest at all verifies only in
``legacy_ok`` mode (checkpoints written before manifests existed).
"""

from __future__ import annotations

import hashlib
import json
import os
import time

MANIFEST_NAME = "pdt_manifest.json"
MANIFEST_FORMAT = 1

VERIFY_LEVELS = ("off", "size", "digest")


def _sha256(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def _walk_files(step_path: str):
    for root, _dirs, files in os.walk(step_path):
        for name in sorted(files):
            if name == MANIFEST_NAME:
                continue
            full = os.path.join(root, name)
            yield os.path.relpath(full, step_path), full


def tree_summary(tree) -> dict[str, dict]:
    """{leaf path: {shape, dtype}} for the saved pytree — shape/dtype only,
    so the summary is identical across hosts and shardings."""
    import jax

    out = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        out[jax.tree_util.keystr(path)] = {
            "shape": list(getattr(leaf, "shape", ())),
            "dtype": str(getattr(leaf, "dtype", type(leaf).__name__)),
        }
    return out


def build_manifest(step_path: str, step: int, tree: dict | None = None) -> dict:
    """Inventory a COMMITTED step directory (call only after orbax's
    ``wait_until_finished``). ``tree`` is a prebuilt ``tree_summary`` —
    captured at save time, when the caller still holds the pytree."""
    import jax

    files = {}
    for rel, full in _walk_files(step_path):
        files[rel] = {
            "bytes": os.path.getsize(full),
            "sha256": _sha256(full),
        }
    manifest = {
        "format": MANIFEST_FORMAT,
        "step": int(step),
        "files": files,
        "versions": {
            "jax": jax.__version__,
            "orbax": __import__("orbax.checkpoint", fromlist=["_"]).__version__,
        },
        "written_at": time.time(),
    }
    if tree is not None:
        manifest["tree"] = tree
    return manifest


def _fsync_path(path: str) -> None:
    """fsync a file or directory by path (directory fsync is how POSIX
    makes a rename/creation durable, not just the bytes inside it)."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def write_manifest(step_path: str, manifest: dict) -> str:
    """Seal a committed step: atomic write (tmp + fsync + rename), with
    full durability ordering. The manifest is the publish signal live
    consumers (the serve-side hot-swap watcher) act on, so before the seal
    rename lands, every data file it NAMES — and the directories holding
    them — is fsynced; after the rename the step directory is fsynced too.
    A host crash at any point therefore leaves either no manifest (the
    step stays unverified/in-flight) or a manifest whose named bytes are
    durably on disk — never a seal over data still sitting in the page
    cache. A crash mid-write leaves at most a ``.tmp`` the reader
    ignores."""
    dirs = {step_path}
    for rel in manifest.get("files", {}):
        full = os.path.join(step_path, rel)
        _fsync_path(full)
        dirs.add(os.path.dirname(full))
    for d in dirs:
        _fsync_path(d)
    path = os.path.join(step_path, MANIFEST_NAME)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    _fsync_path(step_path)  # make the rename itself durable
    return path


def read_manifest(step_path: str) -> dict | None:
    path = os.path.join(step_path, MANIFEST_NAME)
    try:
        with open(path) as f:
            manifest = json.load(f)
    except (FileNotFoundError, NotADirectoryError):
        return None
    except (json.JSONDecodeError, OSError):
        return {}  # present but unreadable: corrupt, not legacy
    if not isinstance(manifest, dict) or "files" not in manifest:
        return {}
    return manifest


def verify_step(
    step_path: str, *, level: str = "size", legacy_ok: bool = False
) -> tuple[bool, str]:
    """Check a committed step against its manifest.

    Returns ``(ok, reason)``; ``reason`` is ``"ok"`` on success, else the
    first failure found (one is enough to disqualify the step).
    """
    if level not in VERIFY_LEVELS:
        raise ValueError(
            f"verify level must be one of {VERIFY_LEVELS}, got {level!r}"
        )
    if level == "off":
        return True, "ok"
    if not os.path.isdir(step_path):
        return False, "step directory missing"
    manifest = read_manifest(step_path)
    if manifest is None:
        if legacy_ok:
            return True, "no manifest (legacy checkpoint, accepted)"
        return False, "no manifest"
    if not manifest:
        return False, "manifest unreadable"
    files = manifest["files"]
    if not files:
        return False, "manifest lists no files"
    for rel, want in files.items():
        full = os.path.join(step_path, rel)
        try:
            size = os.path.getsize(full)
        except OSError:
            return False, f"file missing: {rel}"
        if size != want["bytes"]:
            return False, (
                f"size mismatch: {rel} has {size} bytes, "
                f"manifest says {want['bytes']}"
            )
        if level == "digest" and _sha256(full) != want["sha256"]:
            return False, f"digest mismatch: {rel}"
    return True, "ok"
