"""The Trainer: epochs → jitted steps → eval → metrics, on any mesh/policy.

Capability twin of both reference training functions (reference
test_data_parallelism.py:53-166; test_model_parallelism.py:174-315) as ONE
engine: the parallelism regime is entirely a (mesh shape, sharding policy,
model) choice, so the DP entry point and the hybrid DP×MP entry point differ
only in configuration — where the reference needed two divergent scripts
(Accelerate-managed vs hand-rolled process groups).

Per epoch: train over all global batches (each step is one compiled call
consuming an [accum, micro, ...] sharded batch), then a masked eval pass and
a process-0 metrics print (the reference's per-epoch ``accelerator.print``/
rank-0 print, :164-166/:312-315) — plus samples/sec/chip, the driver's
north-star metric (BASELINE.md).
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_training_tpu.analysis.guards import (
    GuardSet,
    GuardViolation,
    guard_mode_from_env,
    sharding_audit,
)
from pytorch_distributed_training_tpu.comms import initialize
from pytorch_distributed_training_tpu.comms.mesh import build_mesh
from pytorch_distributed_training_tpu.faults.inject import get_plan
from pytorch_distributed_training_tpu.faults.preemption import (
    GracefulShutdown,
    Preempted,
)
from pytorch_distributed_training_tpu.faults.watchdog import (
    Watchdog,
    set_watchdog,
)
from pytorch_distributed_training_tpu.data import ShardedLoader, load_task_arrays
from pytorch_distributed_training_tpu.models import BertForSequenceClassification
from pytorch_distributed_training_tpu.parallel import ShardingPolicy, state_shardings
from pytorch_distributed_training_tpu.parallel.sharding import shard_state
from pytorch_distributed_training_tpu.train import checkpoint as ckpt
from pytorch_distributed_training_tpu.train.metrics import MetricAccumulator
from pytorch_distributed_training_tpu.train.optim import adamw_with_schedule
from pytorch_distributed_training_tpu.train.state import create_train_state
from pytorch_distributed_training_tpu.train.step import make_eval_step, make_train_step
from pytorch_distributed_training_tpu.telemetry import (
    JsonlSink,
    MetricsRegistry,
    epoch_straggler_stats,
    run_metadata,
    set_registry,
)
from pytorch_distributed_training_tpu.utils.config import (
    MeshConfig,
    ModelConfig,
    TrainConfig,
)
from pytorch_distributed_training_tpu.utils.logging import log0, set_log_format
from pytorch_distributed_training_tpu.utils.profiling import (
    annotate,
    maybe_profile,
    set_debug_nans,
)


class Trainer:
    def __init__(
        self,
        model_config: ModelConfig,
        train_config: TrainConfig,
        mesh_config: MeshConfig | None = None,
        policy: ShardingPolicy | None = None,
        *,
        task: str = "auto",
        model=None,
        model_factory=None,
        hf_checkpoint=None,
        train_step_factory=None,
    ):
        self.mcfg = model_config
        self.tcfg = train_config
        self.info = initialize()
        self.mesh = build_mesh(mesh_config)
        # kernels (fused LN / dal / mask-scale / flash) shard over this
        # mesh via shard_map instead of falling back to XLA math on
        # multi-chip runs (ops/dispatch.py; VERDICT r2 #3)
        from pytorch_distributed_training_tpu.ops.dispatch import (
            set_kernel_mesh,
        )

        set_kernel_mesh(self.mesh)

        # ------------------------------------------------------- telemetry
        # Installed before data/checkpoint construction so every layer that
        # records through the default registry (loaders, checkpointer,
        # supervisor) lands in THIS run's window. The JSONL sink is built on
        # every process but writes on process 0 only (telemetry/sink.py);
        # the run-metadata header is emitted at the end of __init__, once
        # the resolved geometry (steps_per_epoch) is known.
        set_log_format(train_config.log_format)
        # Persistent compilation cache FIRST: every compile below (state
        # init, quant calibration, the AOT warm start) should read/write it
        from pytorch_distributed_training_tpu.train.compile import (
            enable_persistent_cache,
        )

        self.compile_cache_dir = enable_persistent_cache(
            train_config.compile_cache_dir
        )
        self.registry = MetricsRegistry()
        set_registry(self.registry)
        # Runtime correctness guards (analysis/guards.py): recompile
        # detection around the jitted steps, transfer-guard arming (strict),
        # donation/sharding audits. PDT_TPU_GUARDS overrides the config.
        self.guards = GuardSet(
            mode=guard_mode_from_env(default=train_config.guards),
            registry=self.registry,
        )
        self.metrics_sink = None
        self._first_step_done = False
        self._log_pending = None  # (step, device loss) awaiting a non-blocking fetch
        if train_config.metrics_dir:
            self.metrics_sink = JsonlSink(train_config.metrics_dir)
            self.registry.attach_sink(self.metrics_sink)

        self.policy = policy or ShardingPolicy()
        if model is None and model_factory is not None:
            # mesh-dependent models (e.g. the GPipe pipeline classifier,
            # parallel/pipeline.py) are built here, after bootstrap + mesh
            model = model_factory(self.mesh)
        if train_config.debug_nans:
            set_debug_nans(True)

        # ------------------------------------------------------------ data
        from pytorch_distributed_training_tpu.data.glue import resolve_task

        task = resolve_task(task)  # once, so both splits agree
        self.objective = "causal_lm" if task == "lm" else "classification"
        if (self.objective == "causal_lm") != bool(model_config.causal):
            raise ValueError(
                f"task {task!r} implies objective {self.objective!r} but the "
                f"model config has causal={model_config.causal} — use a "
                f"decoder preset (gpt2-*) with --task lm, an encoder preset "
                f"with classification tasks"
            )
        from pytorch_distributed_training_tpu.data import synthetic

        # Synthetic tasks generate rows at requested size directly (hub tasks
        # still load the full split and get truncated below).
        sizes = (
            train_config.train_size or synthetic.MRPC_TRAIN_SIZE,
            train_config.eval_size or synthetic.MRPC_EVAL_SIZE,
        )
        train_data, num_labels = load_task_arrays(
            task, "train",
            max_length=train_config.max_seq_length,
            vocab_path=train_config.vocab_path,
            vocab_size=model_config.vocab_size,
            seed=train_config.seed,
            synthetic_sizes=sizes,
        )
        from pytorch_distributed_training_tpu.data.glue import eval_splits

        eval_datas = {}  # suffix -> arrays (MNLI evaluates both val splits)
        for suffix, split in eval_splits(task):
            eval_datas[suffix], _ = load_task_arrays(
                task, split,
                max_length=train_config.max_seq_length,
                vocab_path=train_config.vocab_path,
                vocab_size=model_config.vocab_size,
                seed=train_config.seed,
                synthetic_sizes=sizes,
            )
        if train_config.train_size:
            train_data = {
                k: v[: train_config.train_size] for k, v in train_data.items()
            }
        if train_config.eval_size:
            eval_datas = {
                s: {k: v[: train_config.eval_size] for k, v in d.items()}
                for s, d in eval_datas.items()
            }
        if num_labels:
            self.mcfg.num_labels = num_labels
        self.train_loader = self._make_loader(
            train_data, train_config, train=True
        )
        self.eval_loaders = {
            suffix: self._make_loader(d, train_config, train=False)
            for suffix, d in eval_datas.items()
        }

        # ----------------------------------------------------------- model
        if model is None:
            if self.mcfg.causal:
                from pytorch_distributed_training_tpu.models.gpt2 import (
                    GPT2LMModel,
                )

                model = GPT2LMModel(self.mcfg)
            else:
                model = BertForSequenceClassification(self.mcfg)
        self.model = model
        total_updates = self.train_loader.steps_per_epoch * train_config.num_epochs
        tx, self.schedule = adamw_with_schedule(train_config, total_updates)
        example = {
            "input_ids": jnp.ones(
                (2, train_config.max_seq_length), jnp.int32
            ),
            "attention_mask": jnp.ones(
                (2, train_config.max_seq_length), jnp.int32
            ),
            "token_type_ids": jnp.zeros(
                (2, train_config.max_seq_length), jnp.int32
            ),
        }
        state = create_train_state(
            self.model,
            tx,
            jax.random.key(train_config.seed, impl=train_config.prng_impl),
            example
        )
        if hf_checkpoint is not None:
            from pytorch_distributed_training_tpu.models import hf_loader

            load = (
                hf_loader.load_gpt2_lm
                if self.mcfg.causal
                else hf_loader.load_bert_classifier
            )
            state = state.replace(params=load(hf_checkpoint, self.mcfg))
        self.shardings = state_shardings(state, self.policy, self.mesh)
        self.state = shard_state(state, self.shardings)

        self.checkpointer = (
            ckpt.Checkpointer(
                train_config.checkpoint_dir,
                verify=train_config.checkpoint_verify,
            )
            if train_config.checkpoint_dir
            else None
        )
        restored = False
        if train_config.resume and self.checkpointer:
            if self.checkpointer.latest_step() is not None:
                self.state = self.checkpointer.restore(self.state)
                restored = True
        if self.state.quant is not None and not restored:
            # delayed int8 scaling: observe step-0 amaxes on one microbatch
            # of real rows (a restored run already carries its scales — no
            # point compiling a forward just to overwrite it). Built straight
            # from the dataset arrays — NOT by peeking the train loader:
            # abandoning a native-loader generator mid-epoch leaks its
            # prefetch slot and races the calibration batch's async H2D
            # against the next epoch's slot reuse.
            from pytorch_distributed_training_tpu.comms.ingest import (
                make_global_batch,
            )
            from pytorch_distributed_training_tpu.comms.mesh import BATCH_AXES
            from pytorch_distributed_training_tpu.train.step import (
                calibrate_quant,
            )
            from jax.sharding import PartitionSpec as P

            from pytorch_distributed_training_tpu.data.pipeline import (
                resolve_batch_geometry,
            )

            # per-host slice of the first global microbatch (the same
            # contract both loaders use) — so the calibration forward runs
            # at exactly the training microbatch geometry: no duplicated
            # rows across hosts, no extra compile at a different shape
            pidx, _, micro_global, micro_local, _ = resolve_batch_geometry(
                self.mesh,
                global_batch_size=train_config.global_batch_size,
                grad_accum_steps=train_config.grad_accum_steps,
                train=True,
            )
            take = np.arange(micro_global) % len(
                next(iter(train_data.values()))
            )  # wrap tiny datasets
            local = take[pidx * micro_local : (pidx + 1) * micro_local]
            rows = {k: np.asarray(v)[local] for k, v in train_data.items()}
            micro0 = make_global_batch(self.mesh, rows, pspec=P(BATCH_AXES))
            self.state = calibrate_quant(
                self.state, micro0,
                objective=self.objective,
                loss_scale=1.0 / train_config.grad_accum_steps,
            )

        chain = train_config.chain_steps
        if chain > 1:
            # chained dispatch must tile every step-indexed cadence: a chain
            # crossing an epoch (or checkpoint/crash point) would tear the
            # per-epoch eval/resume contract
            spe = self.train_loader.steps_per_epoch
            bad = next(
                (
                    (what, n)
                    for what, n in (
                        ("steps_per_epoch", spe),
                        ("checkpoint_every_steps",
                         train_config.checkpoint_every_steps),
                        ("crash_at_step", train_config.crash_at_step),
                    )
                    if n and n % chain
                ),
                None,
            )
            if bad:
                raise ValueError(
                    f"chain_steps={chain} must divide {bad[0]}={bad[1]}"
                )
        if train_config.unroll_accum not in ("auto", "on", "off"):
            raise ValueError(
                f"unroll_accum must be auto/on/off, got "
                f"{train_config.unroll_accum!r}"
            )
        if train_step_factory is not None:
            # custom schedules (the 1F1B pipeline step,
            # parallel/pipeline.py) replace the standard step wholesale;
            # they own their accumulation/loss contract — reject knobs they
            # would silently ignore rather than let an OOM-motivated
            # unroll_accum="off" change nothing
            if chain > 1:
                raise ValueError(
                    "chain_steps > 1 is not supported with a custom "
                    "train_step_factory"
                )
            if train_config.unroll_accum != "auto":
                raise ValueError(
                    "unroll_accum is not supported with a custom "
                    "train_step_factory (the schedule owns its scan policy)"
                )
            self.train_step = train_step_factory(self.mesh, self.shardings)
            self._custom_train_step = True
        else:
            self._custom_train_step = False
            self.train_step = make_train_step(
                grad_accum_steps=train_config.grad_accum_steps,
                mesh=self.mesh,
                state_shardings=self.shardings,
                objective=self.objective,
                accum_dtype=train_config.grad_accum_dtype,
                chain_steps=chain,
                unroll_accum={"auto": None, "on": True, "off": False}[
                    train_config.unroll_accum
                ],
            )
        self.eval_step = make_eval_step(
            mesh=self.mesh, state_shardings=self.shardings,
            objective=self.objective,
            # pipeline models evaluate through their serial trunk (same
            # params, no schedule) — see GPipeClassifier.serial_apply
            apply_fn=getattr(self.model, "serial_apply", None),
        )
        self.history: list[dict] = []
        if self.metrics_sink is not None:
            self.metrics_sink.emit(
                run_metadata(
                    self.mesh, self.mcfg, train_config,
                    steps_per_epoch=self.train_loader.steps_per_epoch,
                    objective=self.objective,
                )
            )
        if self.guards.mode != "off":
            # committed placement is final: large params still fully
            # replicated on a sharded (fsdp/model/stage) mesh mean the
            # policy silently didn't apply — record it (strict: raise).
            # After the run-metadata emit so the stream keeps its
            # header-first contract.
            sharding_audit(
                self.state.params, self.mesh,
                registry=self.registry, mode=self.guards.mode,
            )

    def _make_loader(self, data, train_config, *, train: bool):
        """ONE loader factory for both splits: the native C++ prefetching
        batcher when configured/available (train batches AND eval batches —
        identity order + padded tail + valid mask, VERDICT r3 weak-#6),
        else the Python ShardedLoader. Same iteration contract either way.
        The TRAIN loader additionally gets the depth-k latency-hiding
        pipeline (data/prefetch.py, ``--prefetch-depth``): batch i+1..i+k
        assemble and ship H2D while step i computes, for either engine."""
        mode = train_config.native_loader
        if mode not in ("auto", "on", "off"):
            raise ValueError(f"native_loader must be auto/on/off, got {mode!r}")
        if train_config.prefetch_depth < 0:
            raise ValueError(
                f"prefetch_depth must be >= 0, got "
                f"{train_config.prefetch_depth}"
            )
        what = "train" if train else "eval"
        batch = (
            train_config.global_batch_size
            if train
            else train_config.eval_batch_size
        )
        accum = train_config.grad_accum_steps if train else 1
        loader = None
        if mode != "off":
            from pytorch_distributed_training_tpu.native import native_available

            if native_available():
                from pytorch_distributed_training_tpu.data.native_loader import (
                    NativeShardedLoader,
                )

                try:
                    loader = NativeShardedLoader(
                        data, self.mesh,
                        global_batch_size=batch, grad_accum_steps=accum,
                        train=train, seed=train_config.seed,
                    )
                except TypeError as e:  # non-integer dataset arrays
                    if mode == "on":
                        raise
                    log0(
                        f"native {what} loader declined ({e}); using the "
                        f"Python loader"
                    )
                else:
                    log0(f"{what} loader: native C++ prefetching batcher")
            elif mode == "on":
                raise RuntimeError(
                    "native_loader='on' but the C++ batcher is unavailable "
                    "(no toolchain?)"
                )
        if loader is None:
            loader = ShardedLoader(
                data, self.mesh,
                global_batch_size=batch, grad_accum_steps=accum,
                train=train, seed=train_config.seed,
            )
        if train and train_config.prefetch_depth > 0:
            from pytorch_distributed_training_tpu.data.prefetch import (
                PrefetchingLoader,
            )

            loader = PrefetchingLoader(
                loader, depth=train_config.prefetch_depth
            )
        return loader

    # ------------------------------------------------------------------ run

    def run(self) -> list[dict]:
        from pytorch_distributed_training_tpu.comms.mesh import set_current_mesh

        set_current_mesh(self.mesh)  # ring attention retraces resolve to OUR mesh
        set_registry(self.registry)  # layers record into OUR window/sink
        cfg = self.tcfg
        n_chips = self.info.global_device_count
        spe = max(self.train_loader.steps_per_epoch, 1)
        done_steps = int(jax.device_get(self.state.step))
        start_epoch = done_steps // spe
        # Mid-epoch resume: the loader's per-epoch order is deterministic
        # (seeded by epoch index), so skipping the first `step % spe` batches
        # of the resumed epoch continues the exact optimizer/data trajectory —
        # no sample is trained twice and the LR schedule stays on its course.
        skip_in_first_epoch = done_steps % spe
        log0(
            f"training: {cfg.num_epochs} epochs × "
            f"{self.train_loader.steps_per_epoch} updates "
            f"(global batch {cfg.global_batch_size} = "
            f"{cfg.grad_accum_steps} × {cfg.global_batch_size // cfg.grad_accum_steps}), "
            f"mesh {dict(self.mesh.shape)}, {n_chips} chip(s)"
        )
        if start_epoch < cfg.num_epochs:
            # AOT warm start: compile the steps NOW, against the loaders'
            # abstract batch specs, so epoch 0's first step is a normal
            # steady-state step and compile wall time gets its own record
            self._warm_start()
        if self.guards.mode != "off":
            # guard the compiled entry points: a retrace after warm-up (or,
            # strict, an implicit transfer inside a warm call) is a recorded
            # violation. Wrapped AFTER the warm start so .lower() above saw
            # the raw jit objects; the wrapper forwards everything else.
            self.train_step = self.guards.wrap_jit("train_step", self.train_step)
            self.eval_step = self.guards.wrap_jit("eval_step", self.eval_step)
        # Hung-step watchdog: armed around device-blocking sections here and
        # (via the module install) around checkpoint joins + host collectives
        self.watchdog = (
            Watchdog(
                stall_factor=cfg.watchdog_stall_factor,
                min_stall_s=cfg.watchdog_min_stall_s,
                hard_timeout_s=cfg.watchdog_hard_timeout_s,
            )
            if cfg.watchdog
            else None
        )
        prev_watchdog = set_watchdog(self.watchdog)
        # Preemption-safe shutdown: handlers only set a flag; the step loop
        # notices at the next boundary and exits through _preempt_exit
        self._shutdown = (
            GracefulShutdown().install() if cfg.handle_preemption else None
        )
        try:
            self._run_epochs(cfg, n_chips, start_epoch, skip_in_first_epoch)
        finally:
            if self._shutdown is not None:
                self._shutdown.uninstall()
            set_watchdog(prev_watchdog)
            if self.watchdog is not None:
                self.watchdog.close()
            # release native-loader worker threads / checkpoint threadpools
            # even when a train step raises (NaN abort, OOM, interrupt)
            if self.checkpointer:
                self.checkpointer.close()
            for loader in (self.train_loader, *self.eval_loaders.values()):
                close = getattr(loader, "close", None)
                if close:
                    close()
            # crash path: the stream stays OPEN (the supervisor's restart
            # event and the next attempt append to it) but is pushed to disk
            # — restart/preemption/stall records must survive the process
            if self.metrics_sink is not None:
                self.metrics_sink.flush(fsync=True)
        # Closed on the CLEAN path only: after a crash the stream stays open
        # (every record is already flushed) so the supervisor's restart event
        # and the next attempt's header append to the same file.
        if self.metrics_sink is not None:
            self.metrics_sink.close()
        return self.history

    def _warm_start(self) -> None:
        """AOT ``.lower().compile()`` of the train/eval steps (train/
        compile.py) before the first step. Skipped — falling back to lazy
        jit compilation on first call — for configurations whose batch
        layout this method can't reproduce: custom ``train_step_factory``
        schedules (they own their batch contract), ``chain_steps > 1``
        (the chain stack's device-side layout is XLA's choice), and
        seq-sharded meshes (batch shardings are inherited per-leaf from
        the loader). Failure is non-fatal: the lazy path still works."""
        cfg = self.tcfg
        if not cfg.aot_warmup or self._first_step_done:
            return
        if (
            self._custom_train_step
            or cfg.chain_steps > 1
            or self.mesh.shape.get("seq", 1) > 1
        ):
            log0(
                "AOT warm start skipped (custom step/chained dispatch/"
                "seq-sharded batches); first step compiles lazily"
            )
            return
        from jax.sharding import PartitionSpec as P

        from pytorch_distributed_training_tpu.comms.mesh import (
            BATCH_AXES,
            TRAIN_BATCH_PSPEC,
        )
        from pytorch_distributed_training_tpu.analysis.spmd.manifest import (
            train_manifest,
        )
        from pytorch_distributed_training_tpu.train.compile import (
            aot_warm_start,
        )

        # the manifest REQUIRES an all-gather only when some param is
        # actually laid out over the fsdp axis — a policy that's on but
        # never applied (all leaves under fsdp_min_size) legally gathers
        # nothing
        fsdp_sharded = any(
            any(
                "fsdp" in (ax if isinstance(ax, tuple) else (ax,))
                for ax in s.spec
                if ax is not None
            )
            for s in jax.tree.leaves(self.shardings)
        )
        try:
            compiled_train, compiled_eval, record = aot_warm_start(
                train_step=self.train_step,
                eval_step=self.eval_step,
                state=self.state,
                train_spec=self.train_loader.batch_spec(),
                eval_spec=self.eval_loader.batch_spec(),
                mesh=self.mesh,
                train_pspec=TRAIN_BATCH_PSPEC,
                eval_pspec=P(BATCH_AXES),
                cache_dir=self.compile_cache_dir,
                registry=self.registry,
                guard_mode=self.guards.mode,
                comm_manifest=train_manifest(
                    self.mesh, fsdp_sharded=fsdp_sharded
                ),
            )
        except GuardViolation:
            # a strict donation-audit failure is a finding, not a compile
            # hiccup — don't swallow it into the lazy-jit fallback
            raise
        except Exception as e:  # noqa: BLE001 — warm start is best-effort
            log0(f"AOT warm start failed ({e!r}); first step compiles lazily")
            return
        self.train_step = compiled_train
        self.eval_step = compiled_eval
        self._first_step_done = True  # step 0 is no longer compile-inclusive
        self.registry.emit(record)
        hit = record["cache_hit"]
        log0(
            f"AOT warm start: train {record['train_compile_s']:.2f}s + eval "
            f"{record['eval_compile_s']:.2f}s"
            + (f" (persistent cache {'hit' if hit else 'miss'})"
               if hit is not None else "")
        )

    def _preempt_exit(self, signum: int, step_no: int) -> None:
        """SIGTERM/SIGINT arrived: emergency-save inside the grace window,
        record the preemption, and exit RESUMABLE (code 75) — the supervisor
        must not burn a restart on a host that is being taken away."""
        cfg = self.tcfg
        t0 = time.perf_counter()
        saved_step = None
        if self.checkpointer is not None:
            # duplicate-step saves (preempted right after a periodic save)
            # are skipped by the Checkpointer, not errors
            self.checkpointer.save(self.state)
            self.checkpointer.wait()
            saved_step = int(jax.device_get(self.state.step))
        save_wall_s = time.perf_counter() - t0
        if save_wall_s > cfg.preempt_grace_s:
            log0(
                f"emergency checkpoint took {save_wall_s:.1f}s, over the "
                f"{cfg.preempt_grace_s:.0f}s grace window — the checkpoint "
                f"landed but the infra may have SIGKILLed peers; consider "
                f"more frequent periodic saves"
            )
        self.registry.inc("preemptions")
        self.registry.emit({
            "record": "preemption",
            "signal": signum,
            "step": step_no,
            "saved_step": saved_step,
            "save_wall_s": save_wall_s,
            "grace_s": cfg.preempt_grace_s,
        })
        if self.metrics_sink is not None:
            self.metrics_sink.flush(fsync=True)
        log0(
            f"preempted at step {step_no}: emergency checkpoint "
            f"{'at step ' + str(saved_step) if saved_step is not None else 'skipped (no checkpoint_dir)'}, "
            f"exiting resumable"
        )
        raise Preempted(signum, step=step_no)

    def _run_epochs(self, cfg, n_chips, start_epoch, skip_in_first_epoch):
        # Per-step telemetry (metrics_dir set) synchronizes on each step's
        # loss so data-wait / dispatch / device-block attribution is honest;
        # without it the loop keeps today's fully-async dispatch and only
        # wall-clock step times (backpressure-accurate in steady state) are
        # collected for the epoch-boundary straggler gather.
        per_step = bool(cfg.metrics_dir)
        reg = self.registry
        with maybe_profile(cfg.profile_dir):
            for epoch in range(start_epoch, cfg.num_epochs):
                epoch_t0 = time.perf_counter()
                samples = 0
                losses = []
                step_times: list[float] = []
                data_waits: list[float] = []
                # plain host-side counter mirrors state.step (one increment
                # per train_step) — reading state.step back would force a
                # host-device sync every step and serialize dispatch
                step_no = epoch * self.train_loader.steps_per_epoch
                skip = skip_in_first_epoch if epoch == start_epoch else 0
                chain = cfg.chain_steps
                if chain > 1 and skip % chain:
                    # cadence validation (__init__) keeps every checkpoint
                    # on a chain boundary, so a legal resume never lands here
                    raise RuntimeError(
                        f"resume step {skip} is mid-chain (chain_steps="
                        f"{chain}) — checkpoint written by a different "
                        f"chain configuration?"
                    )
                buf = []
                t_prev = time.perf_counter()
                for i, batch in enumerate(self.train_loader.epoch(epoch)):
                    if (
                        self._shutdown is not None
                        and self._shutdown.requested is not None
                    ):
                        self._preempt_exit(self._shutdown.requested, step_no)
                    t_batch = time.perf_counter()
                    data_wait = t_batch - t_prev
                    if i < skip:
                        step_no += 1
                        t_prev = time.perf_counter()
                        continue
                    if chain > 1:
                        # ONE dispatch per chain_steps updates: stack the
                        # placed batches on a leading chain dim (device-side
                        # concat; the extra copy is batch-sized, ~negligible
                        # next to a step) and let the scan-chained step
                        # (train/step.py) run them back-to-back
                        buf.append(batch)
                        if len(buf) < chain:
                            continue
                        batch = jax.tree.map(
                            lambda *xs: jnp.stack(xs), *buf
                        )
                        buf.clear()
                    compile_inclusive = not self._first_step_done
                    # watchdog arms over dispatch + (per_step) device block:
                    # a hung collective inside the step surfaces here. The
                    # compile-inclusive first step is exempt — tracing+XLA
                    # time is unbounded-ish and is not a hang
                    guard = (
                        self.watchdog.guard("train_step", step=step_no + chain)
                        if self.watchdog is not None and not compile_inclusive
                        else contextlib.nullcontext()
                    )
                    with annotate("train_step"), guard:
                        self.state, metrics = self.train_step(self.state, batch)
                        self._first_step_done = True
                        t_dispatched = time.perf_counter()
                        if per_step:
                            # join this step so device_block_s is real device
                            # time, not queue depth
                            jax.block_until_ready(metrics["loss"])
                    t_done = time.perf_counter()
                    samples += cfg.global_batch_size * chain
                    losses.append(metrics["loss"])
                    step_no += chain
                    step_times.append(t_done - t_prev)
                    data_waits.append(data_wait)
                    reg.observe("train/data_wait_s", data_wait)
                    loss_host = None  # fetched at most once per step
                    if per_step:
                        reg.observe("train/dispatch_s", t_dispatched - t_batch)
                        reg.observe("train/device_block_s", t_done - t_dispatched)
                        reg.observe("train/step_s", t_done - t_prev)
                        loss_host = float(jax.device_get(metrics["loss"]))
                        step_rec = {
                            "record": "step",
                            "epoch": epoch,
                            "step": step_no,
                            "data_wait_s": data_wait,
                            "dispatch_s": t_dispatched - t_batch,
                            "device_block_s": t_done - t_dispatched,
                            "step_s": t_done - t_prev,
                            "loss": loss_host,
                            "compile_inclusive": compile_inclusive,
                        }
                        occ = getattr(
                            self.train_loader, "last_occupancy", None
                        )
                        if occ is not None:  # prefetch pipeline active
                            step_rec["prefetch_occupancy"] = occ
                        reg.emit(step_rec)
                    if cfg.log_every and (
                        step_no // cfg.log_every
                        > (step_no - chain) // cfg.log_every
                    ):
                        if loss_host is not None:
                            # reuse the loss already synced for the step
                            # record — no second host round-trip
                            log0(
                                f"step {step_no}: loss={loss_host:.4f} "
                                f"lr={float(self.schedule(step_no)):.2e}"
                            )
                        else:
                            # non-blocking: fetch the PREVIOUS logged step's
                            # loss (long since computed) and queue this one —
                            # a device_get of the current step's loss here
                            # would stall the async dispatch stream
                            self._flush_pending_log()
                            self._log_pending = (step_no, metrics["loss"])
                    if (
                        self.checkpointer
                        and cfg.checkpoint_every_steps
                        and step_no % cfg.checkpoint_every_steps == 0
                    ):
                        self.checkpointer.save(self.state)
                    if (
                        cfg.crash_at_step
                        and step_no == cfg.crash_at_step
                        and jax.process_index() == cfg.crash_rank
                    ):
                        # fault injection: die like a preempted/killed host
                        # (no python cleanup, no checkpoint flush)
                        import os as _os

                        jax.block_until_ready(self.state.params)
                        if self.checkpointer:
                            # join async saves: the injected fault models a
                            # crash AFTER the last periodic checkpoint
                            # committed, not a torn write race
                            self.checkpointer.wait()
                        # plain print: log0 is process-0-gated and the
                        # crashing rank is usually not 0
                        print(
                            f"injected crash at step {step_no} "
                            f"(rank {jax.process_index()})",
                            flush=True,
                        )
                        _os._exit(13)
                    # PDT_TPU_FAULT step faults (faults/inject.py): raise an
                    # InjectedCrash (supervisor-retryable), self-SIGTERM
                    # (preemption path) or hang (watchdog path) right after
                    # completing this update
                    get_plan().fire_step_fault(step_no)
                    t_prev = time.perf_counter()
                with (
                    self.watchdog.guard("epoch_block", step=step_no)
                    if self.watchdog is not None
                    else contextlib.nullcontext()
                ):
                    # with per-step sync off this join is where a wedged
                    # device/collective actually surfaces
                    jax.block_until_ready(self.state.params)
                # the last queued log line (everything is ready post-join)
                self._flush_pending_log()
                train_time = time.perf_counter() - epoch_t0
                # every host contributes its step-time stats; process 0's
                # epoch record then names the slowest host (telemetry/
                # straggler.py) — a collective, same cadence as eval
                straggler = epoch_straggler_stats(step_times, data_waits)
                eval_metrics = self.evaluate()
                record = {
                    "epoch": epoch,
                    # ONE transfer for the whole epoch's losses (not one
                    # device_get per step)
                    "train_loss": float(np.mean(jax.device_get(losses)))
                    if losses
                    else float("nan"),
                    "samples_per_sec": samples / train_time,
                    "samples_per_sec_per_chip": samples / train_time / n_chips,
                    **eval_metrics,
                }
                self.history.append(record)
                log0(f"epoch {epoch}: {record}")
                if self.checkpointer:
                    self.checkpointer.save(self.state)
                # epoch record last, so the checkpoint-save submit and eval
                # wall time land inside this epoch's telemetry window
                reg.emit({
                    "record": "epoch",
                    **record,
                    "train_wall_s": train_time,
                    "straggler": straggler,
                    "telemetry": reg.snapshot(reset=True),
                })

    def _flush_pending_log(self) -> None:
        """Emit the queued --log-every line (its loss is ready by now)."""
        if self._log_pending is None:
            return
        p_step, p_loss = self._log_pending
        self._log_pending = None
        log0(
            f"step {p_step}: loss={float(jax.device_get(p_loss)):.4f} "
            f"lr={float(self.schedule(p_step)):.2e}"
        )

    @property
    def eval_loader(self):
        """The primary eval split's loader (the only one for every task but
        MNLI, whose loaders are keyed "matched"/"mismatched")."""
        return next(iter(self.eval_loaders.values()))

    def evaluate(self) -> dict:
        eval_t0 = time.perf_counter()
        out = {}
        for suffix, loader in self.eval_loaders.items():
            if self.objective == "causal_lm":
                from pytorch_distributed_training_tpu.train.metrics import (
                    LMMetricAccumulator,
                )

                acc = LMMetricAccumulator()
            else:
                acc = MetricAccumulator(self.mcfg.num_labels)
            # accumulate the per-batch counts ON DEVICE: one host transfer
            # per split at the end, instead of a device_get sync per eval
            # batch tearing the dispatch stream
            totals = None
            for batch in loader.epoch():
                with annotate("eval_step"):
                    counts = self.eval_step(self.state, batch)
                totals = (
                    counts
                    if totals is None
                    else jax.tree.map(jnp.add, totals, counts)
                )
            if totals is not None:
                acc.update(jax.device_get(totals))
            raw = acc.compute()
            # first (primary) split also keeps unprefixed keys so existing
            # consumers (tests, HISTORY artifacts) read the same fields
            if not out and suffix:
                out.update(raw)
            out.update(
                {f"{k}_{suffix}": v for k, v in raw.items()} if suffix else raw
            )
        self.registry.observe("eval/wall_s", time.perf_counter() - eval_t0)
        return out
