"""Sharded checkpoint save/restore/resume (orbax-backed).

The reference persists NOTHING — models always load from the HF hub and no
state is ever saved (SURVEY.md §5 checkpoint/resume: ABSENT) — yet its only
failure story is "crash and start over" (``mp.spawn(join=True)``, reference
test_model_parallelism.py:333-335). This framework's recovery story is
restart-from-checkpoint: each save captures params + optimizer state + step +
the dropout RNG key, written shard-by-shard from every host (orbax OCDBT),
and restore re-places each leaf on its mesh sharding — so a resumed run
continues the exact optimizer trajectory on any compatible mesh.

The dropout key is stored as raw ``jax.random.key_data`` words in a
fixed-size uint32 buffer ``[n_words, *words, pad...]``: the container shape
is then independent of both jax's extended-dtype plumbing and the PRNG impl,
so a checkpoint written under one impl restores under another — the key
stream itself can't carry across impls (different word sizes), so on an impl
mismatch restore keeps the fresh state's key and logs a warning instead of
crashing mid-resume.
"""

from __future__ import annotations

import os
import time
from typing import Optional

import jax
import orbax.checkpoint as ocp

from pytorch_distributed_training_tpu.telemetry.registry import get_registry
from pytorch_distributed_training_tpu.train.state import TrainState
from pytorch_distributed_training_tpu.utils.logging import log0

_SAVEABLE = ("step", "params", "opt_state", "dropout_rng")
_RNG_BUF_WORDS = 8  # fits every jax key impl (threefry 2, rbg/unsafe_rbg 4)


def _saveable(state: TrainState) -> dict:
    import jax.numpy as jnp

    d = {k: getattr(state, k) for k in _SAVEABLE}
    if state.quant is not None:
        # delayed-int8 amaxes: step N quantizes with step N-1's scales, so
        # bitwise-exact resume requires restoring them (both sides build
        # their abstract tree from the same state, so save/restore agree on
        # whether the key exists)
        d["quant"] = state.quant
    words = jax.random.key_data(state.dropout_rng).ravel().astype(jnp.uint32)
    buf = jnp.zeros((_RNG_BUF_WORDS + 1,), jnp.uint32)
    buf = buf.at[0].set(words.size).at[1 : 1 + words.size].set(words)
    d["dropout_rng"] = buf
    return d


def _saved_top_keys(mngr, step: int):
    """Top-level keys of the saved tree, read from checkpoint METADATA only
    (no tensor bytes); None when the metadata shape is unrecognized."""
    try:
        meta = mngr.item_metadata(step)
        tree = getattr(meta, "tree", meta)
        if isinstance(tree, dict):
            return set(tree.keys())
    except Exception:
        pass
    return None


def _restore_standard(mngr, step: int, state: TrainState) -> dict:
    """StandardRestore into ``state``'s abstract tree, with a clear message
    for the one structural mismatch a user can cause: the ``quant`` subtree
    exists iff the run used quant_delayed, so saving and resuming runs must
    agree on the flag (orbax's raw tree-mismatch error doesn't say that)."""
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, _saveable(state))
    try:
        return mngr.restore(step, args=ocp.args.StandardRestore(abstract))
    except Exception as e:
        # relabel ONLY the structural mismatch this flag can cause —
        # verified against the saved tree's metadata, not the error text
        # (a dtype/sharding error on the quant leaf itself must propagate
        # untouched, and its message also says "quant")
        saved = _saved_top_keys(mngr, step)
        if saved is None or ("quant" in saved) == ("quant" in abstract):
            raise
        on = state.quant is not None
        raise ValueError(
            f"checkpoint restore failed (step {step}) on the 'quant' "
            f"subtree: this run has quant_delayed {'ON' if on else 'OFF'}, "
            f"and checkpoints carry the delayed-int8 amaxes only when the "
            f"saving run had it ON — save and resume must agree on "
            f"--quant-delayed"
        ) from e


def _merge_restored(state: TrainState, restored: dict) -> TrainState:
    """Rebuild the typed dropout key from the restored word buffer; on an
    impl (word-count) mismatch keep the fresh key — the optimizer trajectory
    lives in params/opt_state/step, the dropout stream is not worth a failed
    resume."""
    cur_data = jax.random.key_data(state.dropout_rng)
    buf = jax.device_get(restored.pop("dropout_rng"))
    n = int(buf[0])
    if n == cur_data.size:
        restored["dropout_rng"] = jax.random.wrap_key_data(
            buf[1 : 1 + n].reshape(cur_data.shape).astype(cur_data.dtype),
            impl=jax.random.key_impl(state.dropout_rng),
        )
    else:
        log0(
            f"checkpoint dropout_rng has {n} key words but the configured"
            f" prng_impl uses {cur_data.size}; keeping the fresh key"
        )
    return state.replace(**restored)


class Checkpointer:
    """Long-lived checkpoint manager for a training run.

    Holds ONE ``ocp.CheckpointManager`` for the run so periodic saves reuse
    its threadpools and directory state instead of paying full setup +
    ``wait_until_finished`` teardown per save; saves are async (orbax
    serializes in the background while training continues) and only joined at
    ``close()`` or when a newer save supersedes them.
    """

    def __init__(self, directory: str, *, keep: int = 3):
        self.directory = os.path.abspath(directory)
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, enable_async_checkpointing=True
            ),
        )

    def save(self, state: TrainState) -> str:
        step = int(jax.device_get(state.step))
        t0 = time.perf_counter()
        self._mngr.save(step, args=ocp.args.StandardSave(_saveable(state)))
        submit_s = time.perf_counter() - t0
        reg = get_registry()
        reg.inc("checkpoint/saves")
        # submit time = what the training loop actually pays (orbax
        # serializes asynchronously; the join is timed at wait/close)
        reg.observe("checkpoint/save_submit_s", submit_s)
        reg.emit({
            "record": "checkpoint_save",
            "step": step,
            "submit_s": submit_s,
            "path": os.path.join(self.directory, str(step)),
        })
        log0(f"checkpoint saving: {self.directory}/{step}")
        return os.path.join(self.directory, str(step))

    def wait(self) -> None:
        """Join any in-flight async save (fault-injection and tests; a
        normal run only joins at ``close()``)."""
        with get_registry().timer("checkpoint/join_s"):
            self._mngr.wait_until_finished()

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def restore(self, state: TrainState, *, step: Optional[int] = None) -> TrainState:
        step = self._mngr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        t0 = time.perf_counter()
        restored = _restore_standard(self._mngr, step, state)
        restore_s = time.perf_counter() - t0
        reg = get_registry()
        reg.observe("checkpoint/restore_s", restore_s)
        reg.emit({
            "record": "checkpoint_restore",
            "step": step,
            "restore_s": restore_s,
            "path": os.path.join(self.directory, str(step)),
        })
        log0(f"checkpoint restored: {self.directory}/{step}")
        return _merge_restored(state, dict(restored))

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()


def save_checkpoint(directory: str, state: TrainState, *, keep: int = 3) -> str:
    """One-shot sharded checkpoint save (opens/closes its own manager; use
    ``Checkpointer`` inside training loops)."""
    directory = os.path.abspath(directory)
    step = int(jax.device_get(state.step))
    t0 = time.perf_counter()
    with ocp.CheckpointManager(
        directory, options=ocp.CheckpointManagerOptions(max_to_keep=keep)
    ) as mngr:
        mngr.save(step, args=ocp.args.StandardSave(_saveable(state)))
        mngr.wait_until_finished()
    get_registry().observe("checkpoint/save_s", time.perf_counter() - t0)
    log0(f"checkpoint saved: {directory}/{step}")
    return os.path.join(directory, str(step))


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    with ocp.CheckpointManager(directory) as mngr:
        return mngr.latest_step()


def restore_params(directory: str, *, params_like=None, step: Optional[int] = None):
    """Restore ONLY the parameter pytree from a training checkpoint.

    The inference-side loader (cli/generate_lm.py): no optimizer state or
    step counter is reconstructed. With ``params_like`` (a pytree of arrays
    or ShapeDtypeStructs matching the saved params) the read is a true
    partial restore — the Adam moments (2x the param bytes) are never
    touched on disk; without it the full checkpoint is read and the extras
    dropped. Leaves come back as host arrays for the caller to place."""
    directory = os.path.abspath(directory)
    with ocp.CheckpointManager(directory) as mngr:
        step = mngr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {directory}")
        if params_like is not None:
            def _sds(x):
                # keep an explicit sharding if the caller attached one —
                # required when restoring a checkpoint written on a
                # DIFFERENT topology (orbax can't rebuild the saved mesh)
                sh = getattr(x, "sharding", None)
                if isinstance(sh, jax.sharding.Sharding):
                    return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)
                return ocp.utils.to_shape_dtype_struct(x)

            abstract = {"params": jax.tree.map(_sds, params_like)}
            restore_args = ocp.checkpoint_utils.construct_restore_args(
                abstract
            )
            restored = mngr.restore(
                step,
                args=ocp.args.PyTreeRestore(
                    item=abstract, restore_args=restore_args,
                    partial_restore=True,
                ),
            )
        else:
            restored = mngr.restore(step)
    log0(f"params restored: {directory}/{step}")
    return dict(restored)["params"]


def saved_params_scanned(directory: str, *, step: Optional[int] = None) -> bool:
    """True if the checkpoint's params use the stacked ``layers_scan`` trunk.

    Reads only checkpoint METADATA (tree structure), no tensor bytes —
    lets inference entry points (cli/generate_lm.py) construct a model
    whose layout matches whatever the training run saved, instead of
    requiring the user to know how the checkpoint was trained.
    """
    from pytorch_distributed_training_tpu.models.relayout import (
        has_scanned_trunk,
    )

    directory = os.path.abspath(directory)
    if step is None:
        with ocp.CheckpointManager(directory) as mngr:
            step = mngr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {directory}")
    # Resolve the step path through orbax's own name format (not a
    # hand-built join) so a future step-naming change on the save side
    # can't silently diverge from this reader.
    step_path = ocp.step.find_step_path(
        directory, ocp.step.standard_name_format(), step=step
    )
    ckptr = ocp.PyTreeCheckpointer()
    try:
        meta = ckptr.metadata(step_path / "default")
    finally:
        ckptr.close()
    # StepMetadata.item_metadata.tree is the saved pytree structure with
    # ArrayMetadata leaves (no tensor reads)
    tree = getattr(getattr(meta, "item_metadata", meta), "tree", None)
    if not isinstance(tree, dict) or "params" not in tree:
        raise ValueError(f"unrecognized checkpoint metadata under {directory}")
    return has_scanned_trunk(tree["params"])


def restore_checkpoint(
    directory: str, state: TrainState, *, step: Optional[int] = None
) -> TrainState:
    """Restore into the structure/shardings of ``state`` (pass a freshly
    created — possibly abstract — state already placed on the mesh)."""
    directory = os.path.abspath(directory)
    with ocp.CheckpointManager(directory) as mngr:
        step = mngr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {directory}")
        restored = _restore_standard(mngr, step, state)
    log0(f"checkpoint restored: {directory}/{step}")
    return _merge_restored(state, dict(restored))
