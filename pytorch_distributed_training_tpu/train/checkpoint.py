"""Sharded checkpoint save/restore/resume (orbax-backed).

The reference persists NOTHING — models always load from the HF hub and no
state is ever saved (SURVEY.md §5 checkpoint/resume: ABSENT) — yet its only
failure story is "crash and start over" (``mp.spawn(join=True)``, reference
test_model_parallelism.py:333-335). This framework's recovery story is
restart-from-checkpoint: each save captures params + optimizer state + step +
the dropout RNG key, written shard-by-shard from every host (orbax OCDBT),
and restore re-places each leaf on its mesh sharding — so a resumed run
continues the exact optimizer trajectory on any compatible mesh.

The dropout key is stored as raw ``jax.random.key_data`` words in a
fixed-size uint32 buffer ``[n_words, *words, pad...]``: the container shape
is then independent of both jax's extended-dtype plumbing and the PRNG impl,
so a checkpoint written under one impl restores under another — the key
stream itself can't carry across impls (different word sizes), so on an impl
mismatch restore keeps the fresh state's key and logs a warning instead of
crashing mid-resume.
"""

from __future__ import annotations

import os
import time
from typing import Optional

import jax
import orbax.checkpoint as ocp

from pytorch_distributed_training_tpu.faults.watchdog import watchdog_guard
from pytorch_distributed_training_tpu.telemetry.registry import get_registry
from pytorch_distributed_training_tpu.train import manifest as ckpt_manifest
from pytorch_distributed_training_tpu.train.state import TrainState
from pytorch_distributed_training_tpu.utils.logging import log0


class CheckpointCorruptError(RuntimeError):
    """No step under the directory passed integrity verification (and the
    directory is not a pre-manifest legacy one)."""

_SAVEABLE = ("step", "params", "opt_state", "dropout_rng")
_RNG_BUF_WORDS = 8  # fits every jax key impl (threefry 2, rbg/unsafe_rbg 4)


def _saveable(state: TrainState) -> dict:
    import jax.numpy as jnp

    d = {k: getattr(state, k) for k in _SAVEABLE}
    if state.quant is not None:
        # delayed-int8 amaxes: step N quantizes with step N-1's scales, so
        # bitwise-exact resume requires restoring them (both sides build
        # their abstract tree from the same state, so save/restore agree on
        # whether the key exists)
        d["quant"] = state.quant
    words = jax.random.key_data(state.dropout_rng).ravel().astype(jnp.uint32)
    buf = jnp.zeros((_RNG_BUF_WORDS + 1,), jnp.uint32)
    buf = buf.at[0].set(words.size).at[1 : 1 + words.size].set(words)
    d["dropout_rng"] = buf
    return d


def _saved_top_keys(mngr, step: int):
    """Top-level keys of the saved tree, read from checkpoint METADATA only
    (no tensor bytes); None when the metadata shape is unrecognized."""
    try:
        meta = mngr.item_metadata(step)
        tree = getattr(meta, "tree", meta)
        if isinstance(tree, dict):
            return set(tree.keys())
    except Exception:
        pass
    return None


def _restore_standard(mngr, step: int, state: TrainState) -> dict:
    """StandardRestore into ``state``'s abstract tree, with a clear message
    for the one structural mismatch a user can cause: the ``quant`` subtree
    exists iff the run used quant_delayed, so saving and resuming runs must
    agree on the flag (orbax's raw tree-mismatch error doesn't say that)."""
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, _saveable(state))
    try:
        return mngr.restore(step, args=ocp.args.StandardRestore(abstract))
    except Exception as e:
        # relabel ONLY the structural mismatch this flag can cause —
        # verified against the saved tree's metadata, not the error text
        # (a dtype/sharding error on the quant leaf itself must propagate
        # untouched, and its message also says "quant")
        saved = _saved_top_keys(mngr, step)
        if saved is None or ("quant" in saved) == ("quant" in abstract):
            raise
        on = state.quant is not None
        raise ValueError(
            f"checkpoint restore failed (step {step}) on the 'quant' "
            f"subtree: this run has quant_delayed {'ON' if on else 'OFF'}, "
            f"and checkpoints carry the delayed-int8 amaxes only when the "
            f"saving run had it ON — save and resume must agree on "
            f"--quant-delayed"
        ) from e


def _merge_restored(state: TrainState, restored: dict) -> TrainState:
    """Rebuild the typed dropout key from the restored word buffer; on an
    impl (word-count) mismatch keep the fresh key — the optimizer trajectory
    lives in params/opt_state/step, the dropout stream is not worth a failed
    resume."""
    cur_data = jax.random.key_data(state.dropout_rng)
    buf = jax.device_get(restored.pop("dropout_rng"))
    n = int(buf[0])
    if n == cur_data.size:
        restored["dropout_rng"] = jax.random.wrap_key_data(
            buf[1 : 1 + n].reshape(cur_data.shape).astype(cur_data.dtype),
            impl=jax.random.key_impl(state.dropout_rng),
        )
    else:
        log0(
            f"checkpoint dropout_rng has {n} key words but the configured"
            f" prng_impl uses {cur_data.size}; keeping the fresh key"
        )
    return state.replace(**restored)


class Checkpointer:
    """Long-lived checkpoint manager for a training run.

    Holds ONE ``ocp.CheckpointManager`` for the run so periodic saves reuse
    its threadpools and directory state instead of paying full setup +
    ``wait_until_finished`` teardown per save; saves are async (orbax
    serializes in the background while training continues) and only joined at
    ``close()`` or when a newer save supersedes them.
    """

    def __init__(self, directory: str, *, keep: int = 3, verify: str = "size"):
        if verify not in ckpt_manifest.VERIFY_LEVELS:
            raise ValueError(
                f"checkpoint verify level must be one of "
                f"{ckpt_manifest.VERIFY_LEVELS}, got {verify!r}"
            )
        self.directory = os.path.abspath(directory)
        self.verify = verify
        # steps submitted but whose integrity manifest is not yet written —
        # flushed once orbax commits (next save / wait / close)
        self._pending_manifest: dict[int, dict] = {}
        self._mngr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=keep, enable_async_checkpointing=True
            ),
        )

    def _step_path(self, step: int) -> str:
        return str(
            ocp.step.find_step_path(
                self.directory, ocp.step.standard_name_format(), step=step
            )
        )

    def _flush_manifests(self) -> None:
        """Write manifests for every committed pending step. Callers
        guarantee orbax has finished (manifest = the post-commit seal;
        writing earlier would certify bytes that aren't on disk yet)."""
        if not self._pending_manifest:
            return
        committed = set(self._mngr.all_steps())
        for step in sorted(self._pending_manifest):
            tree = self._pending_manifest.pop(step)
            if step not in committed:  # save failed/aborted: no seal
                continue
            if jax.process_index() == 0:
                ckpt_manifest.write_manifest(
                    self._step_path(step),
                    ckpt_manifest.build_manifest(
                        self._step_path(step), step, tree=tree
                    ),
                )

    def save(self, state: TrainState, *, force: bool = False) -> str:
        step = int(jax.device_get(state.step))
        reg = get_registry()
        if not force and step in set(self._mngr.all_steps()):
            # a resume immediately followed by a periodic/emergency save
            # lands on an already-saved step — skip instead of hitting
            # orbax's step-exists error mid-run. But only trust the existing
            # copy if it verifies (or its manifest is still pending from
            # THIS process): a run that fell back past a corrupt latest step
            # must replace it when training reaches that step again, not
            # leave the damage on disk for the next resume to dodge.
            ok, reason = True, "ok"
            if step not in self._pending_manifest and self.verify != "off":
                ok, reason = ckpt_manifest.verify_step(
                    self._step_path(step), level=self.verify
                )
            if ok:
                reg.inc("checkpoint/duplicate_skips")
                reg.emit({"record": "checkpoint_skip_duplicate", "step": step})
                log0(f"checkpoint skip: step {step} already saved")
                return os.path.join(self.directory, str(step))
            reg.inc("checkpoint/resaves")
            reg.emit({
                "record": "checkpoint_resave",
                "step": step,
                "reason": reason,
            })
            log0(
                f"checkpoint step {step} exists but fails verification "
                f"({reason}); deleting and re-saving"
            )
            self._mngr.delete(step)
        t0 = time.perf_counter()
        if self._pending_manifest:
            # join the in-flight save (orbax serializes saves anyway) so its
            # manifest commits before a newer step supersedes it
            with watchdog_guard("checkpoint_join"):
                self._mngr.wait_until_finished()
            self._flush_manifests()
        self._mngr.save(step, args=ocp.args.StandardSave(_saveable(state)))
        self._pending_manifest[step] = ckpt_manifest.tree_summary(
            _saveable(state)
        )
        submit_s = time.perf_counter() - t0
        reg.inc("checkpoint/saves")
        # submit time = what the training loop actually pays (orbax
        # serializes asynchronously; the join is timed at wait/close)
        reg.observe("checkpoint/save_submit_s", submit_s)
        reg.emit({
            "record": "checkpoint_save",
            "step": step,
            "submit_s": submit_s,
            "path": os.path.join(self.directory, str(step)),
        })
        log0(f"checkpoint saving: {self.directory}/{step}")
        return os.path.join(self.directory, str(step))

    def wait(self) -> None:
        """Join any in-flight async save (fault-injection and tests; a
        normal run only joins at ``close()``)."""
        with get_registry().timer("checkpoint/join_s"), watchdog_guard(
            "checkpoint_join"
        ):
            self._mngr.wait_until_finished()
        self._flush_manifests()

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def verified_latest_step(self) -> Optional[int]:
        """The newest step that passes integrity verification — what a
        restore with no explicit step will actually use. None when no step
        verifies (including manifest-less legacy steps)."""
        for step in sorted(self._mngr.all_steps(), reverse=True):
            ok, _ = ckpt_manifest.verify_step(
                self._step_path(step), level=self.verify or "size"
            )
            if ok:
                return step
        return None

    def _restore_candidates(self) -> list[int]:
        """Steps to try restoring, best first: verified steps newest-first;
        if NONE verifies and none has a manifest (a pre-manifest legacy
        directory) every step newest-first; else the corrupt steps are
        excluded and an empty tail means CheckpointCorruptError."""
        steps = sorted(self._mngr.all_steps(), reverse=True)
        if not steps:
            raise FileNotFoundError(f"no checkpoint under {self.directory}")
        if self.verify == "off":
            return steps
        verified, reasons, any_manifest = [], {}, False
        for step in steps:
            path = self._step_path(step)
            if ckpt_manifest.read_manifest(path) is not None:
                any_manifest = True
            ok, reason = ckpt_manifest.verify_step(path, level=self.verify)
            if ok:
                verified.append(step)
            else:
                reasons[step] = reason
        if verified:
            if steps[0] not in verified:
                reg = get_registry()
                reg.inc("checkpoint/fallbacks")
                reg.emit({
                    "record": "checkpoint_fallback",
                    "latest_step": steps[0],
                    "fallback_step": verified[0],
                    "reason": reasons.get(steps[0], "unverified"),
                })
                log0(
                    f"checkpoint step {steps[0]} failed verification "
                    f"({reasons.get(steps[0])}); falling back to verified "
                    f"step {verified[0]}"
                )
            return verified
        if not any_manifest:
            log0(
                f"no checkpoint under {self.directory} carries an integrity "
                f"manifest (legacy save?); restoring latest unverified"
            )
            return steps
        raise CheckpointCorruptError(
            f"no verified checkpoint under {self.directory}: "
            + "; ".join(f"step {s}: {r}" for s, r in reasons.items())
        )

    def restore(self, state: TrainState, *, step: Optional[int] = None) -> TrainState:
        candidates = [step] if step is not None else self._restore_candidates()
        reg = get_registry()
        last_exc: Exception | None = None
        for i, cand in enumerate(candidates):
            t0 = time.perf_counter()
            try:
                restored = _restore_standard(self._mngr, cand, state)
            except Exception as e:
                # verification passed but orbax couldn't read it (damage a
                # size check can't see): fall through to the next verified
                # step rather than kill a resumable run
                last_exc = e
                if i + 1 < len(candidates):
                    reg.inc("checkpoint/fallbacks")
                    reg.emit({
                        "record": "checkpoint_fallback",
                        "latest_step": cand,
                        "fallback_step": candidates[i + 1],
                        "reason": f"restore failed: {type(e).__name__}",
                    })
                    log0(
                        f"checkpoint restore of step {cand} failed "
                        f"({type(e).__name__}: {e}); trying step "
                        f"{candidates[i + 1]}"
                    )
                continue
            restore_s = time.perf_counter() - t0
            reg.observe("checkpoint/restore_s", restore_s)
            reg.emit({
                "record": "checkpoint_restore",
                "step": cand,
                "restore_s": restore_s,
                "path": os.path.join(self.directory, str(cand)),
            })
            log0(f"checkpoint restored: {self.directory}/{cand}")
            return _merge_restored(state, dict(restored))
        assert last_exc is not None
        raise last_exc

    def close(self) -> None:
        with watchdog_guard("checkpoint_join"):
            self._mngr.wait_until_finished()
        self._flush_manifests()
        # fault injection (PDT_TPU_FAULT=corrupt_ckpt:...): damage a
        # COMMITTED, manifest-sealed step so the next restore must detect
        # it and fall back — exercised after the manifests above land
        from pytorch_distributed_training_tpu.faults.inject import (
            corrupt_step_dir,
            get_plan,
        )

        target = get_plan().corrupt_checkpoint_target()
        if target is not None and jax.process_index() == 0:
            step = (
                self._mngr.latest_step() if target == "latest" else int(target)
            )
            if step is not None:
                corrupt_step_dir(self._step_path(step))
                get_registry().emit({
                    "record": "fault_injected",
                    "fault": "corrupt_ckpt",
                    "step": step,
                })
        self._mngr.close()


def save_checkpoint(directory: str, state: TrainState, *, keep: int = 3) -> str:
    """One-shot sharded checkpoint save (opens/closes its own manager; use
    ``Checkpointer`` inside training loops)."""
    directory = os.path.abspath(directory)
    step = int(jax.device_get(state.step))
    t0 = time.perf_counter()
    with ocp.CheckpointManager(
        directory, options=ocp.CheckpointManagerOptions(max_to_keep=keep)
    ) as mngr:
        mngr.save(step, args=ocp.args.StandardSave(_saveable(state)))
        mngr.wait_until_finished()
        if jax.process_index() == 0:
            step_path = str(
                ocp.step.find_step_path(
                    directory, ocp.step.standard_name_format(), step=step
                )
            )
            ckpt_manifest.write_manifest(
                step_path,
                ckpt_manifest.build_manifest(
                    step_path, step, tree=ckpt_manifest.tree_summary(
                        _saveable(state)
                    )
                ),
            )
    get_registry().observe("checkpoint/save_s", time.perf_counter() - t0)
    log0(f"checkpoint saved: {directory}/{step}")
    return os.path.join(directory, str(step))


def latest_step(directory: str) -> Optional[int]:
    if not os.path.isdir(directory):
        return None
    with ocp.CheckpointManager(directory) as mngr:
        return mngr.latest_step()


def verified_latest_step(
    directory: str, *, level: str = "size"
) -> Optional[int]:
    """The newest step under ``directory`` passing integrity verification —
    what a no-explicit-step restore will use; the supervisor logs it before
    each retry and ``scripts/verify_checkpoint.py`` reports it offline."""
    if not os.path.isdir(directory):
        return None
    directory = os.path.abspath(directory)
    with ocp.CheckpointManager(directory) as mngr:
        for step in sorted(mngr.all_steps(), reverse=True):
            step_path = str(
                ocp.step.find_step_path(
                    directory, ocp.step.standard_name_format(), step=step
                )
            )
            ok, _ = ckpt_manifest.verify_step(step_path, level=level)
            if ok:
                return step
    return None


def restore_params(directory: str, *, params_like=None, step: Optional[int] = None):
    """Restore ONLY the parameter pytree from a training checkpoint.

    The inference-side loader (cli/generate_lm.py): no optimizer state or
    step counter is reconstructed. With ``params_like`` (a pytree of arrays
    or ShapeDtypeStructs matching the saved params) the read is a true
    partial restore — the Adam moments (2x the param bytes) are never
    touched on disk; without it the full checkpoint is read and the extras
    dropped. Leaves come back as host arrays for the caller to place."""
    directory = os.path.abspath(directory)
    with ocp.CheckpointManager(directory) as mngr:
        step = mngr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {directory}")
        if params_like is not None:
            def _sds(x):
                # keep an explicit sharding if the caller attached one —
                # required when restoring a checkpoint written on a
                # DIFFERENT topology (orbax can't rebuild the saved mesh)
                sh = getattr(x, "sharding", None)
                if isinstance(sh, jax.sharding.Sharding):
                    return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=sh)
                # build the abstract leaf directly (older orbax's
                # to_shape_dtype_struct chokes on sharding-less structs)
                return jax.ShapeDtypeStruct(x.shape, x.dtype)

            abstract = {"params": jax.tree.map(_sds, params_like)}
            restore_args = ocp.checkpoint_utils.construct_restore_args(
                abstract
            )
            try:
                args = ocp.args.PyTreeRestore(
                    item=abstract, restore_args=restore_args,
                    partial_restore=True,
                )
            except TypeError:
                # older orbax (no partial_restore kwarg): an empty
                # transforms dict is its partial-restore spelling — only
                # the keys present in ``item`` are read
                args = ocp.args.PyTreeRestore(
                    item=abstract, restore_args=restore_args, transforms={},
                )
            restored = mngr.restore(step, args=args)
        else:
            try:
                restored = mngr.restore(step)
            except KeyError:
                # older orbax can't infer the handler for a bare restore;
                # name the PyTree handler explicitly
                restored = mngr.restore(step, args=ocp.args.PyTreeRestore())
    log0(f"params restored: {directory}/{step}")
    return dict(restored)["params"]


def saved_params_scanned(directory: str, *, step: Optional[int] = None) -> bool:
    """True if the checkpoint's params use the stacked ``layers_scan`` trunk.

    Reads only checkpoint METADATA (tree structure), no tensor bytes —
    lets inference entry points (cli/generate_lm.py) construct a model
    whose layout matches whatever the training run saved, instead of
    requiring the user to know how the checkpoint was trained.
    """
    from pytorch_distributed_training_tpu.models.relayout import (
        has_scanned_trunk,
    )

    directory = os.path.abspath(directory)
    if step is None:
        with ocp.CheckpointManager(directory) as mngr:
            step = mngr.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {directory}")
    # Resolve the step path through orbax's own name format (not a
    # hand-built join) so a future step-naming change on the save side
    # can't silently diverge from this reader.
    step_path = ocp.step.find_step_path(
        directory, ocp.step.standard_name_format(), step=step
    )
    ckptr = ocp.PyTreeCheckpointer()
    try:
        meta = ckptr.metadata(step_path / "default")
    finally:
        ckptr.close()
    # StepMetadata.item_metadata.tree is the saved pytree structure with
    # ArrayMetadata leaves (no tensor reads); older orbax returns the tree
    # itself as a plain dict
    tree = getattr(getattr(meta, "item_metadata", meta), "tree", None)
    if tree is None and isinstance(meta, dict):
        tree = meta
    if not isinstance(tree, dict) or "params" not in tree:
        raise ValueError(f"unrecognized checkpoint metadata under {directory}")
    return has_scanned_trunk(tree["params"])


def restore_checkpoint(
    directory: str, state: TrainState, *, step: Optional[int] = None
) -> TrainState:
    """Restore into the structure/shardings of ``state`` (pass a freshly
    created — possibly abstract — state already placed on the mesh)."""
    directory = os.path.abspath(directory)
    with ocp.CheckpointManager(directory) as mngr:
        step = mngr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoint under {directory}")
        restored = _restore_standard(mngr, step, state)
    log0(f"checkpoint restored: {directory}/{step}")
    return _merge_restored(state, dict(restored))
