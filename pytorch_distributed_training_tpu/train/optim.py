"""Optimizer + LR schedule, matching the reference's training recipe.

The reference uses transformers ``AdamW(lr=2e-5, correct_bias=True)``
(reference test_data_parallelism.py:120,174) — i.e. Adam *with* bias
correction plus decoupled weight decay — and
``get_linear_schedule_with_warmup(num_warmup_steps=100, num_training_steps=
len(train_dataloader) * num_epochs)`` (test_data_parallelism.py:131-135).
``optax.adamw`` implements exactly the bias-corrected update, so the recipe
maps 1:1. Bias-correction equivalence is unit-tested against the closed-form
update (tests/test_train.py), per SURVEY.md §4.

Note the reference computes ``num_training_steps`` from the *post-prepare,
per-process* dataloader length (SURVEY.md §2 row 6); here total steps are
counted in optimizer updates (global-batch boundaries), the correct
denominator under any data-parallel degree.
"""

from __future__ import annotations

import optax

from pytorch_distributed_training_tpu.utils.config import TrainConfig


def linear_warmup_schedule(
    peak_lr: float, warmup_steps: int, total_steps: int
) -> optax.Schedule:
    """0 → peak over ``warmup_steps``, then linear decay → 0 at ``total_steps``
    (transformers ``get_linear_schedule_with_warmup`` semantics)."""
    warmup_steps = max(warmup_steps, 1)
    decay_steps = max(total_steps - warmup_steps, 1)
    return optax.join_schedules(
        [
            optax.linear_schedule(0.0, peak_lr, warmup_steps),
            optax.linear_schedule(peak_lr, 0.0, decay_steps),
        ],
        boundaries=[warmup_steps],
    )


def adamw_with_schedule(
    config: TrainConfig, total_steps: int
) -> tuple[optax.GradientTransformation, optax.Schedule]:
    """Build the optimizer chain: [global-norm clip →] bias-corrected AdamW
    with the linear-warmup schedule. Returns (tx, schedule) — the schedule is
    exposed separately for logging the current LR."""
    schedule = linear_warmup_schedule(
        config.learning_rate, config.warmup_steps, total_steps
    )
    components = []
    if config.max_grad_norm and config.max_grad_norm > 0:
        components.append(optax.clip_by_global_norm(config.max_grad_norm))
    components.append(
        optax.adamw(
            learning_rate=schedule,
            b1=config.adam_b1,
            b2=config.adam_b2,
            eps=config.adam_eps,
            weight_decay=config.weight_decay,
            # first-moment dtype: bf16 halves the m read+write traffic in
            # the fused update (optax upcasts for the math); fp32 default.
            # The second moment stays fp32 always — sqrt(v)+eps is the
            # precision-critical denominator.
            mu_dtype=config.adam_mu_dtype,
        )
    )
    return optax.chain(*components), schedule
