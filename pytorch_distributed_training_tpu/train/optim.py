"""Optimizer + LR schedule, matching the reference's training recipe.

The reference uses transformers ``AdamW(lr=2e-5, correct_bias=True)``
(reference test_data_parallelism.py:120,174) — i.e. Adam *with* bias
correction plus decoupled weight decay — and
``get_linear_schedule_with_warmup(num_warmup_steps=100, num_training_steps=
len(train_dataloader) * num_epochs)`` (test_data_parallelism.py:131-135).
``optax.adamw`` implements exactly the bias-corrected update, so the recipe
maps 1:1. Bias-correction equivalence is unit-tested against the closed-form
update (tests/test_train.py), per SURVEY.md §4.

Note the reference computes ``num_training_steps`` from the *post-prepare,
per-process* dataloader length (SURVEY.md §2 row 6); here total steps are
counted in optimizer updates (global-batch boundaries), the correct
denominator under any data-parallel degree.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax

from pytorch_distributed_training_tpu.utils.config import TrainConfig


def linear_warmup_schedule(
    peak_lr: float, warmup_steps: int, total_steps: int
) -> optax.Schedule:
    """0 → peak over ``warmup_steps``, then linear decay → 0 at ``total_steps``
    (transformers ``get_linear_schedule_with_warmup`` semantics)."""
    warmup_steps = max(warmup_steps, 1)
    decay_steps = max(total_steps - warmup_steps, 1)
    return optax.join_schedules(
        [
            optax.linear_schedule(0.0, peak_lr, warmup_steps),
            optax.linear_schedule(peak_lr, 0.0, decay_steps),
        ],
        boundaries=[warmup_steps],
    )


def adamw_with_schedule(
    config: TrainConfig, total_steps: int
) -> tuple[optax.GradientTransformation, optax.Schedule]:
    """Build the optimizer chain: [global-norm clip →] bias-corrected AdamW
    with the linear-warmup schedule. Returns (tx, schedule) — the schedule is
    exposed separately for logging the current LR."""
    schedule = linear_warmup_schedule(
        config.learning_rate, config.warmup_steps, total_steps
    )
    from pytorch_distributed_training_tpu.train.fused_adamw import adamw_fused

    components = []
    if config.max_grad_norm and config.max_grad_norm > 0:
        # The train step hands the optimizer CARRY-dtype gradients (may be
        # bf16); global-norm accumulation in bf16 drops small terms, so
        # clipping upcasts first. Costs one fp32 materialization of the
        # grads — only when clipping is actually enabled (default off,
        # like the reference, which never clips).
        components.append(
            optax.GradientTransformation(
                lambda params: optax.EmptyState(),
                lambda updates, state, params=None: (
                    jax.tree.map(
                        lambda g: g.astype(jnp.float32), updates
                    ),
                    state,
                ),
            )
        )
        components.append(optax.clip_by_global_norm(config.max_grad_norm))
    components.append(
        # optax.adamw twin with BOTH moment dtypes settable (optax only
        # exposes mu_dtype). Moment dtype = bf16 halves that moment's HBM
        # read+write traffic in the update; math stays fp32 either way.
        # fp32/fp32 matches optax.adamw to ~1 ulp/step (unit-tested).
        adamw_fused(
            schedule,
            b1=config.adam_b1,
            b2=config.adam_b2,
            eps=config.adam_eps,
            weight_decay=config.weight_decay,
            mu_dtype=config.adam_mu_dtype,
            nu_dtype=config.adam_nu_dtype,
        )
    )
    return optax.chain(*components), schedule
