"""Training state: one donated pytree carrying everything a step mutates.

The reference's mutable training state is spread across the DDP module, the
torch optimizer, the LR scheduler, and the AMP scaler, glued by
``accelerator.prepare`` (reference test_data_parallelism.py:125-135). Here it
is a single immutable pytree — params + optimizer state + step + the base
dropout RNG key — threaded through a jitted step with donated buffers, so
XLA updates it in place in HBM.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
import optax
from flax import struct


@struct.dataclass
class TrainState:
    step: jnp.ndarray  # int32 scalar, counts optimizer updates
    params: Any
    opt_state: Any
    dropout_rng: jax.Array
    apply_fn: Callable = struct.field(pytree_node=False)
    tx: optax.GradientTransformation = struct.field(pytree_node=False)
    # Non-parameter model state mutated by the forward pass: today the
    # "quant" collection of delayed int8 activation amaxes (ops/quant.py).
    # None for models without such state (None is an empty pytree, so every
    # existing step/sharding/checkpoint path is unchanged); otherwise the
    # step threads it through its accumulation scan and writes it back.
    quant: Any = None

    def apply_gradients(self, grads) -> "TrainState":
        updates, new_opt_state = self.tx.update(
            grads, self.opt_state, self.params
        )
        return self.replace(
            step=self.step + 1,
            params=optax.apply_updates(self.params, updates),
            opt_state=new_opt_state,
        )


def create_train_state(
    model,
    tx: optax.GradientTransformation,
    rng: jax.Array,
    example_batch: dict,
) -> TrainState:
    """Initialize params (jitted — eager init is pathologically slow through
    the axon TPU tunnel) and optimizer state."""
    init_rng, dropout_rng = jax.random.split(rng)

    def _init(r, batch):
        return model.init(
            r,
            batch["input_ids"],
            batch.get("attention_mask"),
            batch.get("token_type_ids"),
        )

    variables = jax.jit(_init)(init_rng, example_batch)
    params = variables["params"]
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        opt_state=tx.init(params),
        dropout_rng=dropout_rng,
        apply_fn=model.apply,
        tx=tx,
        # delayed-quant amaxes observed on the init dummy batch; real
        # calibration (train.step.calibrate_quant) overwrites before step 0
        quant=variables.get("quant"),
    )
