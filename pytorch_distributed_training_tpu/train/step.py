"""Jitted train/eval steps with structural gradient accumulation.

This file replaces the reference's entire hot loop (reference
test_data_parallelism.py:140-150; test_model_parallelism.py:283-299) with two
compiled functions:

- ``train_step(state, batch)`` — batch leaves are [accum, micro_batch, ...];
  a ``lax.scan`` over the accumulation axis computes fp32 gradients per
  microbatch and accumulates them in the carry, then ONE optimizer update
  fires at the end. This is the TPU-structural equivalent of the reference's
  ``model.no_sync()`` allreduce suppression (test_model_parallelism.py:
  292-294): the cross-replica psum happens once per global batch because the
  accumulated gradient is only materialized once — no flags, no off-by-one.
  (The reference steps on ``step % accum == 0``, which fires on the very
  first microbatch — SURVEY.md §2c-1. Here every update sees exactly
  ``accum`` microbatches by construction.)
- ``eval_step(state, batch)`` — forward + argmax, returning the confusion
  counts needed for accuracy/F1 under a validity mask. Static shapes force
  padding the last eval batch; masked counts keep the metric bit-honest
  (fixing the reference's uneven-last-batch gather skew, SURVEY.md §2c-6)
  and nothing bigger than a handful of scalars crosses device→host.

Loss is computed in fp32 off bf16 activations; gradients accumulate in fp32
by default (``accum_dtype`` — TrainConfig.grad_accum_dtype — can trade carry
bandwidth for bf16 rounding in the microbatch sum; the optimizer update is
fp32 either way). Jit donates ``state`` so params/optimizer state update in
place in HBM.
"""

from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from pytorch_distributed_training_tpu.comms.mesh import BATCH_AXES, TRAIN_BATCH_PSPEC
from pytorch_distributed_training_tpu.train.state import TrainState


def _sink_zeros(quant):
    """Zero-valued "quant_sink" collection matching ``quant``'s delayed-
    gradient sites (the leaves named ``dy_amax``) — None when the model
    has none. The sinks are the cotangent channel that carries the
    backward's observed dy amaxes out (ops/quant.py
    ``int8_dense_delayed_grads``); their STRUCTURE is static, so this
    also serves as the trace-time "is delayed_grads on?" predicate."""
    if quant is None:
        return None
    from flax import traverse_util

    flat = traverse_util.flatten_dict(quant)
    sinks = {
        k[:-1] + ("sink",): jnp.zeros_like(v)
        for k, v in flat.items()
        if k[-1] == "dy_amax"
    }
    return traverse_util.unflatten_dict(sinks) if sinks else None


def _merge_dy_amaxes(quant, sink_grads):
    """Write the backward's observed dy amaxes (the sink gradients) into
    the ``dy_amax`` leaves of the carried quant collection."""
    from flax import traverse_util

    q = traverse_util.flatten_dict(quant)
    s = traverse_util.flatten_dict(sink_grads)
    merged = {
        k: (s[k[:-1] + ("sink",)] if k[-1] == "dy_amax" else v)
        for k, v in q.items()
    }
    return traverse_util.unflatten_dict(merged)


def _apply(state: TrainState, params, micro, dropout_rng, quant=None,
           apply_fn=None, sinks=None):
    """Model forward → (output, new_quant). ``quant`` is the delayed-int8
    amax collection (ops/quant.py); when present the apply is mutable over
    it and the updated collection comes back for the caller to carry. None
    (every non-delayed model) leaves the apply exactly as before.
    ``apply_fn`` overrides ``state.apply_fn`` (the pipeline trainer
    evaluates through the serial trunk — same params, no schedule).
    ``sinks`` feeds the "quant_sink" collection for delayed-gradient
    models (built as zeros here when not supplied — callers pass their
    own only to differentiate w.r.t. it)."""
    fn = state.apply_fn if apply_fn is None else apply_fn
    rngs = {"dropout": dropout_rng} if dropout_rng is not None else None
    kwargs = dict(deterministic=dropout_rng is None, rngs=rngs)
    if quant is not None:
        variables = {"params": params, "quant": quant}
        if sinks is None:
            sinks = _sink_zeros(quant)
        if sinks is not None:
            variables["quant_sink"] = sinks
        out, updated = fn(
            variables,
            micro["input_ids"],
            micro.get("attention_mask"),
            micro.get("token_type_ids"),
            mutable=["quant"],
            **kwargs,
        )
        return out, updated["quant"]
    return (
        fn(
            {"params": params},
            micro["input_ids"],
            micro.get("attention_mask"),
            micro.get("token_type_ids"),
            **kwargs,
        ),
        None,
    )


def calibrate_quant(state: TrainState, micro, *,
                    objective: str = "classification",
                    loss_scale: float = 1.0) -> TrainState:
    """Populate delayed-int8 amaxes from ONE real microbatch (step-0 scales).

    Delayed scaling quantizes with the previous microbatch's amax; before
    the first step there is none (init observed a dummy batch of ones), so
    run one deterministic forward with the quant collection mutable and keep
    the observed amaxes. With delayed GRADIENT scaling
    (``quant_delayed_grads``) one backward also runs, reading the dy
    amaxes out of the sink gradients; ``loss_scale`` should match the
    training step's per-microbatch loss scaling (1/grad_accum_steps) so
    the calibrated dy magnitudes match what training's backward sees.
    No-op for models without delayed quant."""
    if state.quant is None:
        return state

    def _cal(st, m):
        q = _apply(st, st.params, m, None, st.quant)[1]
        sinks0 = _sink_zeros(q)
        if sinks0 is not None:
            forward_loss = _LOSS_FNS[objective]

            def f(sinks):
                loss, _ = forward_loss(st, st.params, m, None, q,
                                       sinks=sinks)
                return loss * loss_scale

            q = _merge_dy_amaxes(q, jax.grad(f)(sinks0))
        return q

    from pytorch_distributed_training_tpu.ops.quant import dy_calibration_mode

    with dy_calibration_mode():
        # trace-time switch: the calibration backward quantizes dy with
        # fresh DYNAMIC scales — with zero carried amaxes every
        # downstream site would otherwise differentiate through saturated
        # garbage cotangents and record garbage observations
        new_q = jax.jit(_cal)(state, micro)
    # keep every amax leaf on its ORIGINAL sharding: under the pipeline
    # policies the [num_layers] dim is stage-sharded, and the train step's
    # in_shardings reject the jit default (replicated) placement
    new_q = jax.tree.map(
        lambda new, old: (
            jax.device_put(new, old.sharding)
            if isinstance(getattr(old, "sharding", None), jax.sharding.Sharding)
            else new
        ),
        new_q,
        state.quant,
    )
    return state.replace(quant=new_q)


def _classification_loss(state: TrainState, params, micro, dropout_rng,
                         quant=None, sinks=None):
    """Mean masked softmax-CE over one microbatch, in fp32."""
    logits, new_quant = _apply(
        state, params, micro, dropout_rng, quant, sinks=sinks
    )
    labels = micro["labels"]
    valid = micro.get("valid")
    if valid is None:
        valid = jnp.ones_like(labels, jnp.float32)
    valid = valid.astype(jnp.float32)
    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), labels
    )
    denom = jnp.maximum(valid.sum(), 1.0)
    loss = (ce * valid).sum() / denom
    return loss, (logits, new_quant)


def _lm_shift_and_mask(micro):
    """Next-token targets + per-position validity for causal LM batches.

    Position t predicts token t+1. Shift via ``roll`` (not slicing) so every
    tensor keeps the full [B, S] shape — slicing the sharded sequence dim
    makes the SPMD partitioner fully rematerialize the logits grad on the
    pad. The rolled-in last position is masked out, as are pad targets
    (attention_mask) and padded eval rows (valid).
    """
    ids = micro["input_ids"]
    targets = jnp.roll(ids, -1, axis=1)
    mask = micro.get("attention_mask")
    mask = (
        jnp.ones_like(ids, jnp.float32)
        if mask is None
        else jnp.roll(mask, -1, axis=1).astype(jnp.float32)
    )
    mask = mask.at[:, -1].set(0.0)
    valid = micro.get("valid")
    if valid is not None:
        mask = mask * valid.astype(jnp.float32)[:, None]
    return targets, mask


def _causal_lm_loss(state: TrainState, params, micro, dropout_rng,
                    quant=None, sinks=None):
    """Mean next-token CE per valid target position, in fp32."""
    logits, new_quant = _apply(
        state, params, micro, dropout_rng, quant, sinks=sinks
    )
    targets, mask = _lm_shift_and_mask(micro)
    ce = optax.softmax_cross_entropy_with_integer_labels(
        logits.astype(jnp.float32), targets
    )
    loss = (ce * mask).sum() / jnp.maximum(mask.sum(), 1.0)
    return loss, (logits, new_quant)


_LOSS_FNS = {
    "classification": _classification_loss,
    "causal_lm": _causal_lm_loss,
}


def make_train_step(
    *,
    grad_accum_steps: int,
    mesh: Optional[Mesh] = None,
    state_shardings=None,
    objective: str = "classification",
    accum_dtype: str = "float32",
    chain_steps: int = 1,
    log_grad_norm: bool = True,
    unroll_accum: Optional[bool] = None,
) -> Callable:
    """Build the jitted train step.

    ``batch`` leaves: [grad_accum_steps, micro_batch, ...] (microbatch axis
    first so ``lax.scan`` walks it). With ``mesh`` given, inputs are
    constrained so the micro-batch dim shards over (data, fsdp) and the
    optimizer update runs under the provided state shardings — XLA inserts
    the per-boundary gradient AllReduce over ICI.

    ``chain_steps > 1`` returns a driver over PRE-PLACED batches with an
    extra leading [chain_steps] dim: ONE dispatch executes that many
    optimizer steps back-to-back on device (lax.scan over the per-step
    body). Host dispatch latency — a few ms per call through remote/tunnel
    runtimes — amortizes across the chain; ``loss`` comes back as the MEAN
    over the chain (so epoch averages weight every step equally, matching
    chain_steps=1 artifacts) while other metrics report the LAST step
    (per-step metrics would force device->host syncs, defeating the
    point). The per-step numerics are identical to chain_steps=1.
    """

    forward_loss = _LOSS_FNS[objective]
    acc_dtype = jnp.dtype(accum_dtype)

    # The 1/accum scale is folded into the microbatch loss, so the summed
    # carry IS the mean gradient — no separate full-gradient scaling pass
    # after the scan (one read+write of every gradient, ~3 ms/step on
    # bert-large). Backward scales d(loss)/d(logits) by 1/accum at the
    # top, identical math to scaling the summed gradient.
    inv_accum = 1.0 / grad_accum_steps

    def train_step(state: TrainState, batch):
        base_rng = jax.random.fold_in(state.dropout_rng, state.step)

        def micro_grads(carry, micro):
            grads_acc, loss_acc, quant = carry
            step_rng = jax.random.fold_in(base_rng, loss_acc[1].astype(jnp.int32))
            sinks0 = _sink_zeros(quant)

            if sinks0 is not None:
                # delayed dy scaling: the sinks' GRADIENTS are the
                # backward's observed dy amaxes (ops/quant.py) — read
                # them out and carry them with the fwd amaxes. The dy
                # observed here includes the 1/accum loss scaling, which
                # is exactly the magnitude next microbatch's backward
                # sees, so the carried scale is self-consistent.
                def loss_fn(p, sinks):
                    loss, (_, new_quant) = forward_loss(
                        state, p, micro, step_rng, quant, sinks=sinks
                    )
                    return loss * inv_accum, new_quant

                (loss, new_quant), (grads, sink_grads) = jax.value_and_grad(
                    loss_fn, argnums=(0, 1), has_aux=True
                )(state.params, sinks0)
                new_quant = _merge_dy_amaxes(new_quant, sink_grads)
            else:

                def loss_fn(p):
                    loss, (_, new_quant) = forward_loss(
                        state, p, micro, step_rng, quant
                    )
                    return loss * inv_accum, new_quant

                (loss, new_quant), grads = jax.value_and_grad(
                    loss_fn, has_aux=True
                )(state.params)
            grads = jax.tree.map(
                lambda a, g: a + g.astype(acc_dtype), grads_acc, grads
            )
            return (
                (grads, (loss_acc[0] + loss, loss_acc[1] + 1.0), new_quant),
                None,
            )

        zero_grads = jax.tree.map(
            lambda p: jnp.zeros(p.shape, acc_dtype), state.params
        )
        # Small accumulation counts unroll fully by default: XLA folds the
        # zeros init into the first microbatch's gradients and schedules
        # across iterations (~3 ms/step on the 3-step bert-large recipe);
        # large counts keep the rolled loop for compile-time/code-size
        # sanity. ``unroll_accum`` overrides — unrolling lets XLA overlap
        # microbatch LIFETIMES, which raises peak activation memory
        # (gpt2-medium at micro 8 OOMs unrolled, fits rolled).
        # The delayed-quant amax collection rides the same carry (each
        # microbatch quantizes with the previous one's scales); None for
        # every other model — an empty pytree in the carry.
        unroll = (
            grad_accum_steps <= 4 if unroll_accum is None else unroll_accum
        )
        (grads, (loss_sum, _), final_quant), _ = jax.lax.scan(
            micro_grads,
            (
                zero_grads,
                (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
                state.quant,
            ),
            batch,
            unroll=unroll,
        )
        # Gradients go to the optimizer in the CARRY dtype — fused_adamw
        # upcasts per-element in-register, so a tree-wide astype here would
        # only materialize a full fp32 copy of every gradient (~3 ms/step
        # on bert-large with a bf16 carry). Optimizer math is fp32 either
        # way (train/fused_adamw.py).
        new_state = state.apply_gradients(grads).replace(quant=final_quant)
        metrics = {
            "loss": loss_sum,  # sum of 1/accum-scaled losses == mean loss
        }
        if log_grad_norm:
            # one extra read of every gradient leaf (~0.7 GB on bert-large)
            metrics["grad_norm"] = jnp.sqrt(
                sum(
                    jnp.sum(jnp.square(g.astype(jnp.float32)))
                    for g in jax.tree.leaves(grads)
                )
            )
        return new_state, metrics

    if chain_steps > 1:
        single_step = train_step

        def train_step(state: TrainState, batches):  # noqa: F811
            # scan carries the metrics DICT as a pytree — no parallel key
            # list to keep in sync with whatever single_step emits
            state, stacked = jax.lax.scan(single_step, state, batches)
            out = {k: v[-1] for k, v in stacked.items()}
            # chain-mean loss: an epoch average built from these then
            # weights every optimizer step equally, not just chain tails
            out["loss"] = stacked["loss"].mean()
            return state, out

    donate = (0,)
    if mesh is None:
        return jax.jit(train_step, donate_argnums=donate)
    # With context parallelism the loader shards sequence dims per-leaf
    # (comms.ingest._leaf_spec); None lets jit inherit that committed layout
    # instead of forcing a replicated-on-seq reshard.
    if mesh.shape.get("seq", 1) > 1:
        batch_sharding = None
    else:
        pspec = TRAIN_BATCH_PSPEC
        if chain_steps > 1:  # extra leading [chain_steps] dim, unsharded
            pspec = P(None, *pspec)
        batch_sharding = NamedSharding(mesh, pspec)
    return jax.jit(
        train_step,
        donate_argnums=donate,
        in_shardings=(state_shardings, batch_sharding),
        out_shardings=(state_shardings, NamedSharding(mesh, P())),
    )


def make_eval_step(
    *,
    mesh: Optional[Mesh] = None,
    state_shardings=None,
    objective: str = "classification",
    apply_fn=None,
) -> Callable:
    """Build the jitted eval step → replicated scalar counts.

    classification: {"correct", "total", "tp", "fp", "fn"} summed over the
    (masked) batch — host-side ``MetricAccumulator`` folds batches; positive
    class for binary F1 is label 1 (GLUE/MRPC convention).
    causal_lm: {"nll_sum", "token_count", "token_correct"} — folds into
    ``LMMetricAccumulator`` (eval loss / perplexity / token accuracy).

    ``apply_fn`` evaluates through a DIFFERENT apply than training's over
    the same params — the pipeline trainer's serial-trunk eval (the GPipe
    param tree is identical to the serial scan model's by design), which
    frees eval batches from the n_micro × data-shard divisibility the
    schedule needs and skips the fill/drain bubble per eval batch.
    """

    def lm_eval_step(state: TrainState, batch):
        # eval quantizes with training's latest amaxes, unmutated (the
        # updated collection from this forward is discarded)
        logits = _apply(
            state, state.params, batch, None, state.quant, apply_fn
        )[0].astype(jnp.float32)
        targets, mask = _lm_shift_and_mask(batch)
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
        preds = jnp.argmax(logits, axis=-1)
        return {
            "nll_sum": (ce * mask).sum(),
            "token_count": mask.sum(),
            "token_correct": ((preds == targets) * mask).sum(),
        }

    def eval_step(state: TrainState, batch):
        logits, _ = _apply(
            state, state.params, batch, None, state.quant, apply_fn
        )
        preds = jnp.argmax(logits.astype(jnp.float32), axis=-1)
        labels = batch["labels"]
        valid = batch.get("valid")
        if valid is None:
            valid = jnp.ones_like(labels)
        valid = valid.astype(jnp.float32)
        correct = ((preds == labels) * valid).sum()
        pos_pred = (preds == 1) * valid
        pos_label = (labels == 1) * valid
        return {
            "correct": correct,
            "total": valid.sum(),
            "tp": (pos_pred * pos_label).sum(),
            "fp": (pos_pred * (1.0 - pos_label)).sum(),
            "fn": ((1.0 - pos_pred) * pos_label).sum(),
        }

    from pytorch_distributed_training_tpu.train.metrics import (
        LMMetricAccumulator,
        MetricAccumulator,
    )

    if objective == "causal_lm":
        fn, keys = lm_eval_step, LMMetricAccumulator.FIELDS
    else:
        fn, keys = eval_step, MetricAccumulator.FIELDS
    if mesh is None:
        return jax.jit(fn)
    if mesh.shape.get("seq", 1) > 1:
        batch_sharding = None  # inherit the loader's seq-sharded layout
    else:
        batch_sharding = NamedSharding(mesh, P(BATCH_AXES))
    replicated = NamedSharding(mesh, P())
    return jax.jit(
        fn,
        in_shardings=(state_shardings, batch_sharding),
        out_shardings={k: replicated for k in keys},
    )
