"""Parameter re-layout between the scanned and unscanned trunk forms.

``config.scan_layers`` stacks every transformer block's params on a leading
[num_layers] axis under ``layers_scan/<inner>/...`` (bert.py/gpt2.py nn.scan
trunks); the unscanned trunk names each block ``layer_i``/``block_i`` with
the same inner tree minus the leading axis. The two layouts hold identical
weights, so converting is a pure pytree reshape — this module provides both
directions, letting a checkpoint trained with the scanned trunk (the
``train_lm`` default) drive KV-cache generation (models/generate.py), which
runs the unscanned trunk.

Both transforms walk the whole (possibly nested) param dict — the LM's
trunk sits at top level, the classifier's under ``bert`` — and convert
every trunk they find.

The reference repo has no trunk-layout concept at all (eager torch modules,
reference test_model_parallelism.py:92-163); this is the price/benefit of
the lax.scan compile-time optimization and is framework-owned machinery.
"""

from __future__ import annotations

from collections.abc import Mapping
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

# scanned inner-module name -> unscanned per-layer name prefix
_SCAN_INNER_TO_PREFIX = {"block": "block_", "layer": "layer_"}


def _is_layer_key(key: str, prefix: str) -> bool:
    return key.startswith(prefix) and key[len(prefix):].isdigit()


def has_scanned_trunk(params) -> bool:
    """True if ``params`` carries a stacked ``layers_scan`` trunk anywhere."""
    if not isinstance(params, Mapping):
        return False
    if "layers_scan" in params:
        return True
    return any(has_scanned_trunk(v) for v in params.values())


def _scan_inner(trunk: dict) -> str:
    if len(trunk) != 1:
        raise ValueError(
            f"unrecognized layers_scan contents: {sorted(trunk)} "
            "(expected exactly one inner module)"
        )
    (inner,) = trunk
    if inner not in _SCAN_INNER_TO_PREFIX:
        raise ValueError(
            f"unrecognized scanned trunk inner module {inner!r} "
            f"(known: {sorted(_SCAN_INNER_TO_PREFIX)})"
        )
    return inner


def unstack_scanned_params(params) -> dict[str, Any]:
    """[L]-stacked ``layers_scan`` trunks -> per-layer ``block_i``/``layer_i``.

    Returns a NEW dict (leaves are slices of the originals; nothing is
    copied beyond what ``a[i]`` materializes under jit/np).
    """
    if not isinstance(params, Mapping):
        return params
    out: dict[str, Any] = {}
    for k, v in params.items():
        if k == "layers_scan":
            inner = _scan_inner(v)
            prefix = _SCAN_INNER_TO_PREFIX[inner]
            stacked = v[inner]
            dims = {int(np.shape(a)[0]) for a in jax.tree.leaves(stacked)}
            if len(dims) != 1:
                raise ValueError(
                    f"inconsistent leading layer dims in layers_scan: {dims}"
                )
            (n,) = dims
            for i in range(n):
                out[f"{prefix}{i}"] = jax.tree.map(lambda a, i=i: a[i], stacked)
        else:
            out[k] = unstack_scanned_params(v)
    return out


def stack_layer_params(params) -> dict[str, Any]:
    """Per-layer ``block_i``/``layer_i`` params -> [L]-stacked trunks."""
    if not isinstance(params, Mapping):
        return params
    inner = prefix = None
    for cand_inner, cand_prefix in _SCAN_INNER_TO_PREFIX.items():
        if any(_is_layer_key(k, cand_prefix) for k in params):
            inner, prefix = cand_inner, cand_prefix
            break
    out: dict[str, Any] = {}
    if inner is not None:
        idxs = sorted(
            int(k[len(prefix):]) for k in params if _is_layer_key(k, prefix)
        )
        if idxs != list(range(len(idxs))):
            raise ValueError(f"non-contiguous layer indices: {idxs}")
        out["layers_scan"] = {
            inner: jax.tree.map(
                lambda *xs: jnp.stack(xs),
                *[params[f"{prefix}{i}"] for i in idxs],
            )
        }
    for k, v in params.items():
        if prefix is not None and _is_layer_key(k, prefix):
            continue
        out[k] = stack_layer_params(v)
    return out
