"""HuggingFace checkpoint → framework pytree weight loader.

The reference always starts from HF pretrained weights
(``from_pretrained("bert-large-cased")``, reference
test_data_parallelism.py:112; test_model_parallelism.py:230-238). This module
maps a torch BERT/RoBERTa ``state_dict`` (or an in-memory ``transformers``
model, or a local checkpoint directory) onto this framework's flax parameter
pytree. Torch ``nn.Linear`` stores weights [out, in]; flax kernels are
[in, out] — every dense weight transposes, and Q/K/V/O reshape to/from the
[heads, head_dim] DenseGeneral layout (SURVEY.md §7 hard parts: "transpose
conventions for dense kernels").

Network-free by design: nothing here downloads. In this zero-egress image the
loader is exercised against randomly-initialized ``transformers`` models
built from configs (see tests/test_models.py), which also serves as the
numerical parity check of the whole model.
"""

from __future__ import annotations

from typing import Any, Mapping

import numpy as np

from pytorch_distributed_training_tpu.utils.config import ModelConfig


def _np(t) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor
        t = t.detach().cpu()
        if t.is_floating_point():
            # bf16 has no numpy equivalent; fp16 would silently violate the
            # fp32-param policy. Promote all float weights before conversion.
            t = t.float()
        t = t.numpy()
    return np.asarray(t)


def state_dict_from(source: Any) -> dict[str, np.ndarray]:
    """Accept a transformers model, a torch state_dict, a mapping of numpy
    arrays, or a local directory containing ``model.safetensors`` /
    ``pytorch_model.bin``."""
    if isinstance(source, (str,)):
        import os

        st_path = os.path.join(source, "model.safetensors")
        pt_path = os.path.join(source, "pytorch_model.bin")
        if os.path.exists(st_path):
            from safetensors.numpy import load_file

            return dict(load_file(st_path))
        if os.path.exists(pt_path):
            import torch

            return {
                k: _np(v)
                for k, v in torch.load(pt_path, map_location="cpu").items()
            }
        raise FileNotFoundError(f"no checkpoint found under {source!r}")
    if hasattr(source, "state_dict"):
        source = source.state_dict()
    if isinstance(source, Mapping):
        return {k: _np(v) for k, v in source.items()}
    raise TypeError(f"unsupported checkpoint source {type(source)!r}")


def load_bert_classifier(source: Any, config: ModelConfig) -> dict:
    """Build the flax params pytree for ``BertForSequenceClassification``
    from an HF BERT/RoBERTa sequence-classification checkpoint."""
    sd = state_dict_from(source)
    n, d, h = config.num_heads, config.head_dim, config.hidden_size

    # HF prefixes: bert.* (BertForSequenceClassification) or roberta.*
    prefix = "bert." if any(k.startswith("bert.") for k in sd) else (
        "roberta." if any(k.startswith("roberta.") for k in sd) else ""
    )

    def W(key):  # torch Linear weight -> flax kernel
        return _np(sd[key]).T

    def arr(key):
        return _np(sd[key])

    def dense(key):
        return {"kernel": W(key + ".weight"), "bias": arr(key + ".bias")}

    def norm(key):
        return {"scale": arr(key + ".weight"), "bias": arr(key + ".bias")}

    def qkv(key):  # [out,in] -> [in, heads, head_dim]
        return {
            "kernel": W(key + ".weight").reshape(h, n, d),
            "bias": arr(key + ".bias").reshape(n, d),
        }

    emb = prefix + "embeddings."
    embeddings = {
        "word_embeddings": {"embedding": arr(emb + "word_embeddings.weight")},
        "position_embeddings": {
            "embedding": arr(emb + "position_embeddings.weight")
        },
        "norm": norm(emb + "LayerNorm"),
    }
    if config.type_vocab_size:
        embeddings["token_type_embeddings"] = {
            "embedding": arr(emb + "token_type_embeddings.weight")
        }

    trunk: dict[str, Any] = {"embeddings": embeddings}
    for i in range(config.num_layers):
        lp = f"{prefix}encoder.layer.{i}."
        trunk[f"layer_{i}"] = {
            "attention": {
                "query": qkv(lp + "attention.self.query"),
                "key": qkv(lp + "attention.self.key"),
                "value": qkv(lp + "attention.self.value"),
                "out": {
                    # [out,in] -> [heads, head_dim, out]
                    "kernel": W(lp + "attention.output.dense.weight").reshape(
                        n, d, h
                    ),
                    "bias": arr(lp + "attention.output.dense.bias"),
                },
            },
            "attention_norm": norm(lp + "attention.output.LayerNorm"),
            "mlp_up": dense(lp + "intermediate.dense"),
            "mlp_down": dense(lp + "output.dense"),
            "mlp_norm": norm(lp + "output.LayerNorm"),
        }

    if prefix + "pooler.dense.weight" in sd:
        trunk["pooler"] = dense(prefix + "pooler.dense")
    elif "classifier.dense.weight" in sd:
        # RoBERTa classification heads carry their own dense; map it to the
        # pooler slot (tanh pooling matches RobertaClassificationHead).
        trunk["pooler"] = dense("classifier.dense")

    params: dict[str, Any] = {"bert": trunk}
    if "classifier.weight" in sd:
        params["classifier"] = dense("classifier")
    elif "classifier.out_proj.weight" in sd:
        params["classifier"] = dense("classifier.out_proj")

    # Enforce the parameter-dtype policy (fp32 by default) on every float
    # leaf, whatever precision the checkpoint was saved in.
    pdtype = np.dtype(config.param_dtype)
    import jax

    return jax.tree.map(
        lambda x: x.astype(pdtype) if np.issubdtype(x.dtype, np.floating) else x,
        params,
    )


def load_gpt2_lm(source: Any, config: ModelConfig) -> dict:
    """Build the flax params pytree for ``GPT2LMModel`` from an HF
    ``GPT2LMHeadModel`` checkpoint.

    HF GPT-2 uses ``Conv1D`` modules whose weights are stored [in, out] —
    already the flax kernel orientation, so unlike the BERT path nothing
    transposes. The fused ``c_attn`` [h, 3h] splits into the framework's
    separate q/k/v DenseGeneral kernels ([h, heads, head_dim]); the LM head
    is weight-tied to ``wte`` (both here and in HF), so only the embedding
    loads. With ``config.scan_layers`` the per-layer trees stack on a
    leading [num_layers] axis (the lax.scan trunk layout).
    """
    sd = state_dict_from(source)
    n, d, h = config.num_heads, config.head_dim, config.hidden_size
    prefix = (
        "transformer."
        if any(k.startswith("transformer.") for k in sd)
        else ""
    )

    def arr(key):
        return _np(sd[key])

    def norm(key):
        return {"scale": arr(key + ".weight"), "bias": arr(key + ".bias")}

    def layer(i):
        lp = f"{prefix}h.{i}."
        ck, cb = arr(lp + "attn.c_attn.weight"), arr(lp + "attn.c_attn.bias")
        q_k, k_k, v_k = np.split(ck, 3, axis=1)  # [h, h] each
        q_b, k_b, v_b = np.split(cb, 3)
        return {
            "ln_1": norm(lp + "ln_1"),
            "attention": {
                "query": {
                    "kernel": q_k.reshape(h, n, d),
                    "bias": q_b.reshape(n, d),
                },
                "key": {
                    "kernel": k_k.reshape(h, n, d),
                    "bias": k_b.reshape(n, d),
                },
                "value": {
                    "kernel": v_k.reshape(h, n, d),
                    "bias": v_b.reshape(n, d),
                },
                "out": {
                    "kernel": arr(lp + "attn.c_proj.weight").reshape(n, d, h),
                    "bias": arr(lp + "attn.c_proj.bias"),
                },
            },
            "ln_2": norm(lp + "ln_2"),
            "mlp_up": {
                "kernel": arr(lp + "mlp.c_fc.weight"),
                "bias": arr(lp + "mlp.c_fc.bias"),
            },
            "mlp_down": {
                "kernel": arr(lp + "mlp.c_proj.weight"),
                "bias": arr(lp + "mlp.c_proj.bias"),
            },
        }

    layers = [layer(i) for i in range(config.num_layers)]
    params: dict[str, Any] = {
        "wte": {"embedding": arr(prefix + "wte.weight")},
        "wpe": {"embedding": arr(prefix + "wpe.weight")},
        "ln_f": norm(prefix + "ln_f"),
    }
    if config.scan_layers:
        import jax

        params["layers_scan"] = {
            "block": jax.tree.map(lambda *xs: np.stack(xs), *layers)
        }
    else:
        for i, lyr in enumerate(layers):
            params[f"block_{i}"] = lyr

    pdtype = np.dtype(config.param_dtype)
    import jax

    return jax.tree.map(
        lambda x: x.astype(pdtype) if np.issubdtype(x.dtype, np.floating) else x,
        params,
    )
