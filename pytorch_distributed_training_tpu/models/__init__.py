from pytorch_distributed_training_tpu.models.bert import (
    BertEncoderModel,
    BertForSequenceClassification,
)
from pytorch_distributed_training_tpu.models.branch import (
    BranchEnsembleClassifier,
)

__all__ = [
    "BertEncoderModel",
    "BertForSequenceClassification",
    "BranchEnsembleClassifier",
]
