from pytorch_distributed_training_tpu.models.bert import (
    BertEncoderModel,
    BertForSequenceClassification,
)

__all__ = ["BertEncoderModel", "BertForSequenceClassification"]
