from pytorch_distributed_training_tpu.models.bert import (
    BertEncoderModel,
    BertForSequenceClassification,
)
from pytorch_distributed_training_tpu.models.branch import (
    BranchEnsembleClassifier,
)
from pytorch_distributed_training_tpu.models.generate import generate

__all__ = [
    "BertEncoderModel",
    "BertForSequenceClassification",
    "BranchEnsembleClassifier",
    "generate",
]
