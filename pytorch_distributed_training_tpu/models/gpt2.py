"""GPT-2 causal language model (flax.linen), TPU-first.

The reference repo has no decoder models — this family exists for the
driver's extra config "GPT-2-medium causal-LM fine-tune, FSDP-style param
sharding" (/root/repo/BASELINE.json configs[4]). Architecture follows GPT-2:
pre-LN transformer blocks, learned absolute positions, tanh-approximate GELU,
final LayerNorm, and a weight-tied LM head (logits = h @ wte.T).

Reuses this framework's attention stack (``BertSelfAttention`` with
``config.causal=True`` → causal masking inside the swappable attention op)
and the same dtype policy (params fp32, compute bf16, LayerNorm/softmax
fp32). ``config.scan_layers`` stacks blocks on a leading [num_layers] dim
(lax.scan trunk) exactly like the encoder, so the stage/FSDP sharding rules
apply unchanged.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp

from pytorch_distributed_training_tpu.models.bert import (
    BertSelfAttention,
    _dtype,
    _ln,
    _pdtype,
    dense_general,
)
from pytorch_distributed_training_tpu.ops.attention import make_attention_bias
from pytorch_distributed_training_tpu.ops.dropout import Dropout
from pytorch_distributed_training_tpu.utils.config import ModelConfig


def _mlp_body(mdl: "GPT2Block", h, deterministic):
    """The block's MLP tail (mlp_up → gelu → mlp_down → dropout) as a
    module-first function so ``remat_mlp`` can wrap it in a LIFTED
    ``nn.remat`` without changing parameter names/paths: children created
    here register in the block's own scope. Structural (plain
    jax.checkpoint, no saveable policies) — the tunnel's TPU compiler
    crashes on checkpoint POLICIES at gpt2-medium scale (NOTES.md), while
    plain-remat subgraphs compile fine; rematerializing ONLY the MLP drops
    the [B,S,4·hidden] gelu residuals (the biggest per-layer activations)
    for one extra mlp_up matmul in the backward."""
    cfg = mdl.config
    kw = dict(dtype=_dtype(cfg), param_dtype=_pdtype(cfg),
              kernel_init=nn.initializers.normal(stddev=0.02))
    h = dense_general(cfg, cfg.intermediate_size, -1, "mlp_up", kw)(h)
    h = nn.gelu(h, approximate=True)  # GPT-2 uses the tanh approximation
    h = dense_general(cfg, cfg.hidden_size, -1, "mlp_down", kw)(h)
    return Dropout(cfg.hidden_dropout, cfg.dropout_impl)(
        h, deterministic=deterministic
    )


class GPT2Block(nn.Module):
    """Pre-LN transformer block (GPT-2 convention — LN before each sublayer,
    unlike BERT's post-LN ``BertLayer``)."""

    config: ModelConfig

    @nn.compact
    def __call__(self, x, attention_bias, deterministic):
        cfg = self.config
        h = _ln(cfg, "ln_1")(x)
        h = BertSelfAttention(cfg, name="attention")(
            h, attention_bias, deterministic
        )
        h = Dropout(cfg.hidden_dropout, cfg.dropout_impl)(h, deterministic=deterministic)
        x = x + h

        h = _ln(cfg, "ln_2")(x)
        mlp = (
            nn.remat(_mlp_body, static_argnums=(2,))
            if cfg.remat_mlp
            else _mlp_body
        )
        h = mlp(self, h, deterministic)
        return x + h


def _gpt2_layer_cls(cfg: ModelConfig):
    """GPT2Block, remat-wrapped when configured — same nn.remat/static_argnums
    contract as bert._layer_cls (GPT2Block.__call__ shares BertLayer's
    signature, with ``deterministic`` at position 3)."""
    if cfg.remat:
        from pytorch_distributed_training_tpu.models.bert import remat_policy

        return nn.remat(
            GPT2Block, static_argnums=(3,), policy=remat_policy(cfg)
        )
    return GPT2Block


class _GPT2ScanBlock(nn.Module):
    config: ModelConfig
    deterministic: bool

    @nn.compact
    def __call__(self, x, attention_bias):
        x = _gpt2_layer_cls(self.config)(self.config, name="block")(
            x, attention_bias, self.deterministic
        )
        return x, None


class GPT2LMModel(nn.Module):
    """wte+wpe embeddings → N pre-LN blocks → ln_f → tied-head logits.

    Signature matches the encoder classifiers (token_type_ids accepted and
    ignored) so train/eval steps and the Trainer drive either family
    unchanged.
    """

    config: ModelConfig

    @nn.compact
    def __call__(
        self,
        input_ids,
        attention_mask=None,
        token_type_ids=None,  # unused; uniform model signature
        position_ids=None,
        deterministic: bool = True,
    ):
        cfg = self.config
        batch, seq = input_ids.shape
        if seq > cfg.max_position_embeddings:
            raise ValueError(
                f"sequence length {seq} exceeds max_position_embeddings "
                f"{cfg.max_position_embeddings} — the position-embedding "
                f"gather would silently clamp (NaN/garbage logits); raise "
                f"max_position_embeddings for long-context runs"
            )
        if position_ids is None:
            if cfg.decode:
                # generation: positions continue from the cached index
                # (same flax "cache" pattern as the attention KV buffers)
                is_init = not self.has_variable("cache", "pos_index")
                pi = self.variable(
                    "cache", "pos_index", lambda: jnp.zeros((), jnp.int32)
                )
                offset = jnp.zeros((), jnp.int32) if is_init else pi.value
                if not is_init:
                    pi.value = offset + seq
                position_ids = offset + jnp.broadcast_to(
                    jnp.arange(seq, dtype=jnp.int32)[None, :], (batch, seq)
                )
            else:
                position_ids = jnp.broadcast_to(
                    jnp.arange(seq, dtype=jnp.int32)[None, :], (batch, seq)
                )
        embed_init = nn.initializers.normal(stddev=0.02)
        wte = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, embedding_init=embed_init,
            dtype=_dtype(cfg), param_dtype=_pdtype(cfg), name="wte",
        )
        wpe = nn.Embed(
            cfg.max_position_embeddings, cfg.hidden_size,
            embedding_init=embed_init, dtype=_dtype(cfg),
            param_dtype=_pdtype(cfg), name="wpe",
        )
        x = wte(input_ids) + wpe(position_ids)
        x = Dropout(cfg.hidden_dropout, cfg.dropout_impl)(x, deterministic=deterministic)

        # padding bias (causal masking is applied inside attention via
        # cfg.causal; GPT-2 training batches are usually dense so
        # attention_mask may be None)
        bias = make_attention_bias(attention_mask)

        if cfg.scan_layers:
            scan = nn.scan(
                _GPT2ScanBlock,
                # "quant": per-layer delayed-int8 amaxes (ops/quant.py)
                variable_axes={"params": 0, "quant": 0, "quant_sink": 0},
                split_rngs={"params": True, "dropout": True},
                in_axes=(nn.broadcast,),
                length=cfg.num_layers,
            )
            x, _ = scan(cfg, deterministic, name="layers_scan")(x, bias)
        else:
            for i in range(cfg.num_layers):
                x = _gpt2_layer_cls(cfg)(cfg, name=f"block_{i}")(
                    x, bias, deterministic
                )

        x = _ln(cfg, "ln_f")(x)
        # Tied LM head: logits share the input embedding matrix (GPT-2
        # convention). bf16 operands with fp32 MXU accumulation — the same
        # policy as every other matmul; a full-fp32 vocab matmul runs at
        # half MXU rate and the [B,S,V] logits dominate the LM step.
        logits = jax.lax.dot_general(
            x,
            wte.embedding.astype(_dtype(cfg)),
            (((2,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return logits
