"""In-repo BERT encoder family (flax.linen), TPU-first.

The reference always rides HuggingFace's torch BERT
(``AutoModelForSequenceClassification("bert-large-cased")``, reference
test_data_parallelism.py:112; three ``bert-base-cased`` instances,
test_model_parallelism.py:230-238). This framework owns the model: a pure
functional flax implementation whose parameter layout is deliberately
HF-mappable (see ``models.hf_loader``) so pretrained checkpoints load when a
hub cache is available, while everything else — dtype policy, attention
implementation, remat, sharding — is native to this framework.

TPU design notes:
- bf16 compute / fp32 params policy (the fp16-AMP replacement, SURVEY.md §2b):
  every Dense/Embed takes ``dtype=compute_dtype, param_dtype=param_dtype``;
  softmax and LayerNorm statistics stay fp32.
- Q/K/V/O projections are ``DenseGeneral`` straight to/from
  [heads, head_dim] — one reshape-free matmul each, MXU-friendly.
- ``config.remat`` wraps each layer in ``jax.checkpoint`` to trade FLOPs for
  HBM on long sequences / big batches.
- RoBERTa is the same trunk with pad-offset learned positions and no token
  types (``config.roberta_style``); GPT-2 reuses the attention stack with
  ``causal=True`` (see ``models.gpt2``).
"""

from __future__ import annotations

from typing import Optional

import flax.linen as nn
import jax
import jax.numpy as jnp

from pytorch_distributed_training_tpu.ops.layer_norm import (
    FusedDropoutAddLayerNorm,
    FusedLayerNorm,
)
from pytorch_distributed_training_tpu.ops.attention import (
    dot_product_attention,
    make_attention_bias,
)
from pytorch_distributed_training_tpu.ops.dropout import Dropout
from pytorch_distributed_training_tpu.ops.paged_attention import paged_attention
from pytorch_distributed_training_tpu.ops.quant import quantize_kv
from pytorch_distributed_training_tpu.utils.config import ModelConfig


def _dtype(cfg: ModelConfig):
    return jnp.dtype(cfg.compute_dtype)


def _pdtype(cfg: ModelConfig):
    return jnp.dtype(cfg.param_dtype)

def _ln(cfg: "ModelConfig", name: str) -> FusedLayerNorm:
    """LayerNorm with fp32 stats emitting the compute dtype directly (the
    fused Pallas kernel on TPU; identical jnp math elsewhere)."""
    return FusedLayerNorm(
        epsilon=cfg.layer_norm_eps, param_dtype=_pdtype(cfg),
        out_dtype=_dtype(cfg), impl=cfg.layernorm_impl, name=name,
    )



class BertEmbeddings(nn.Module):
    config: ModelConfig

    @nn.compact
    def __call__(self, input_ids, token_type_ids, position_ids, deterministic):
        cfg = self.config
        kw = dict(dtype=_dtype(cfg), param_dtype=_pdtype(cfg))
        embed_init = nn.initializers.normal(stddev=0.02)
        words = nn.Embed(
            cfg.vocab_size, cfg.hidden_size, embedding_init=embed_init,
            name="word_embeddings", **kw,
        )(input_ids)
        positions = nn.Embed(
            cfg.max_position_embeddings, cfg.hidden_size,
            embedding_init=embed_init, name="position_embeddings", **kw,
        )(position_ids)
        x = words + positions
        if cfg.type_vocab_size:
            # RoBERTa has a SIZE-1 type table (HF parity) while pair tasks
            # feed segment ids {0,1}: clamp explicitly instead of relying
            # on XLA's silent OOB-gather clamp. The constant embedding adds
            # no segment signal — random-init RoBERTa therefore learns
            # pair tasks noticeably slower than BERT (measured on the
            # synthetic recipe: the segment cue is the easiest feature,
            # NOTES.md round-4 RoBERTa section).
            types = jnp.clip(token_type_ids, 0, cfg.type_vocab_size - 1)
            x = x + nn.Embed(
                cfg.type_vocab_size, cfg.hidden_size, embedding_init=embed_init,
                name="token_type_embeddings", **kw,
            )(types)
        x = _ln(cfg, "norm")(x)
        return Dropout(cfg.hidden_dropout, cfg.dropout_impl)(
            x, deterministic=deterministic
        )


def dense_general(cfg: ModelConfig, features, axis, name, kw):
    """nn.DenseGeneral or its int8-MXU twin (ops/quant.py), switched by
    ``cfg.matmul_impl``. Parameter layout is identical either way, so the
    switch never touches checkpoints or the HF loader."""
    if cfg.matmul_impl == "native":
        return nn.DenseGeneral(features, axis=axis, name=name, **kw)
    if cfg.matmul_impl not in ("int8", "int8_full"):
        raise ValueError(
            f"matmul_impl must be native/int8/int8_full, got "
            f"{cfg.matmul_impl!r}"
        )
    from pytorch_distributed_training_tpu.ops.quant import QuantDenseGeneral

    feats = features if isinstance(features, tuple) else (features,)
    ax = axis if isinstance(axis, tuple) else (axis,)
    return QuantDenseGeneral(
        features=feats, axis=ax,
        mode="full" if cfg.matmul_impl == "int8_full" else "fwd",
        delayed=cfg.quant_delayed,
        delayed_grads=cfg.quant_delayed_grads,
        dtype=kw["dtype"], param_dtype=kw["param_dtype"],
        kernel_init=kw["kernel_init"], name=name,
    )


class BertSelfAttention(nn.Module):
    config: ModelConfig

    @nn.compact
    def __call__(self, x, attention_bias, deterministic):
        cfg = self.config
        kw = dict(dtype=_dtype(cfg), param_dtype=_pdtype(cfg),
                  kernel_init=nn.initializers.normal(stddev=0.02))
        # Three separate projections, NOT a fused [h, 3h] qkv matmul: the
        # fused form measured ~2 ms/step SLOWER on v5e (XLA pipelines the
        # three column matmuls + their consumers better than one wide one
        # followed by slices; tried 2026-07, see NOTES.md).
        heads_shape = (cfg.num_heads, cfg.head_dim)
        q = dense_general(cfg, heads_shape, -1, "query", kw)(x)
        k = dense_general(cfg, heads_shape, -1, "key", kw)(x)
        v = dense_general(cfg, heads_shape, -1, "value", kw)(x)
        if cfg.decode:
            if cfg.kv_layout == "paged":
                out = self._paged_attend(q, k, v, attention_bias)
            else:
                out = self._cached_attend(q, k, v, attention_bias)
        else:
            dropout_rng = None
            if not deterministic and cfg.attention_dropout > 0.0:
                dropout_rng = self.make_rng("dropout")

            def core(q, k, v, bias, rng):
                return dot_product_attention(
                    q, k, v, bias,
                    impl=cfg.attention_impl,
                    dropout_rng=rng,
                    dropout_rate=cfg.attention_dropout,
                    deterministic=deterministic,
                    causal=cfg.causal,
                    dropout_impl=cfg.dropout_impl,
                )

            if cfg.attention_remat and cfg.attention_impl == "reference":
                # recompute scores/probs in the backward instead of storing
                # [B, N, S, S] probs residuals: the recompute is one small
                # einsum+softmax while the saved-probs path paid fp32
                # residual copies (measured +1.9 ms/step on bert-large;
                # bit-identical numerics — the dropout mask regenerates
                # from the same rng). Pallas flash / ring bring their own
                # backward structure, so only the XLA einsum impl opts in.
                core = jax.checkpoint(core)
            out = core(q, k, v, attention_bias, dropout_rng)
        return dense_general(cfg, cfg.hidden_size, (-2, -1), "out", kw)(out)

    def _cached_attend(self, q, k, v, attention_bias):
        """Autoregressive attention over the KV cache (generation path).

        Flax "cache" collection pattern: the cache buffers are created at
        their FULL [batch, max_len, heads, head_dim] size during ``init``
        (call the model once with a max_len-shaped dummy input), and every
        subsequent ``apply(..., mutable=["cache"])`` writes the current
        chunk at ``cache_index`` and attends causally over the filled
        prefix. Works for multi-token prefill chunks and 1-token decode
        steps alike. Deterministic (no dropout) — generation never trains.
        """
        cfg = self.config
        if not cfg.causal:
            raise ValueError("decode=True requires a causal model")
        batch, chunk, heads, head_dim = q.shape
        is_init = not self.has_variable("cache", "cached_key")
        ck = self.variable(
            "cache", "cached_key",
            lambda: jnp.zeros(k.shape, k.dtype),
        )
        cv = self.variable(
            "cache", "cached_value",
            lambda: jnp.zeros(v.shape, v.dtype),
        )
        ci = self.variable(
            "cache", "cache_index", lambda: jnp.zeros((), jnp.int32)
        )
        if is_init:
            # init trace: buffers take the dummy input's (max_len) shape;
            # attend output only fixes parameter shapes, values unused
            return q
        idx = ci.value
        max_len = ck.value.shape[1]
        ck.value = jax.lax.dynamic_update_slice(
            ck.value, k.astype(ck.value.dtype), (0, idx, 0, 0)
        )
        cv.value = jax.lax.dynamic_update_slice(
            cv.value, v.astype(cv.value.dtype), (0, idx, 0, 0)
        )
        ci.value = idx + chunk
        scale = head_dim ** -0.5
        scores = jnp.einsum(
            "bsnd,btnd->bnst", q, ck.value,
            preferred_element_type=jnp.float32,
        ) * scale
        # causal-over-cache mask: key position t visible to chunk row i iff
        # t <= idx + i (rows are global positions idx..idx+chunk-1)
        q_pos = idx + jax.lax.broadcasted_iota(jnp.int32, (chunk, max_len), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (chunk, max_len), 1)
        neg = jnp.finfo(jnp.float32).min
        scores = jnp.where((k_pos <= q_pos)[None, None], scores, neg)
        if attention_bias is not None:
            scores = scores + attention_bias.astype(jnp.float32)
        probs = jax.nn.softmax(scores, axis=-1).astype(cv.value.dtype)
        return jnp.einsum("bnst,btnd->bsnd", probs, cv.value)

    def _paged_attend(self, q, k, v, attention_bias):
        """Autoregressive attention over PAGED KV (cfg.kv_layout="paged").

        Same flax "cache" collection pattern as ``_cached_attend``, but the
        K/V buffers are page POOLS shared by every sequence in the batch:
        ``k_pages``/``v_pages`` [num_pages, page_size, heads, head_dim],
        addressed through a per-sequence ``block_table`` [batch, W] and
        ``context_len`` [batch]. The serving engine owns page placement
        (serve/paged_cache.py) and injects block_table/context_len as traced
        operands per call; only the pools are engine-resident state.

        Contract with the engine:
        - prefill (chunk > 1, paged_multiquery=False): the sequence is
          FRESH (context_len == 0) and its block table row covers the
          chunk; K/V is scattered into its pages and attention is
          intra-chunk causal — bitwise the dense cache formula at idx == 0.
        - decode (chunk == 1): one token appended at ``context_len``, then
          ops/paged_attention gathers the whole context through the block
          table. Idle batch rows park on the reserved null page 0: their
          writes land there and their outputs are garbage the host ignores
          (no lax.select freeze needed — page structure isolates them).
        - multi-token query (paged_multiquery=True): the chunk is appended
          at ``context_len`` of an EXISTING context (speculative verify /
          chunked-prefill continuation) and attends causally over prior
          pages plus itself through the 4-D-query paged_attention path.
        """
        cfg = self.config
        if not cfg.causal:
            raise ValueError("decode=True requires a causal model")
        if cfg.kv_num_pages < 2:
            raise ValueError(
                "kv_layout='paged' needs kv_num_pages >= 2 (page 0 is the "
                f"reserved null page), got {cfg.kv_num_pages}"
            )
        batch, chunk, heads, head_dim = q.shape
        page_size = cfg.kv_page_size
        is_init = not self.has_variable("cache", "k_pages")
        # int8 pool storage: pages quantize on write (symmetric absmax over
        # head_dim) against fp32 scale pools [num_pages, page_size, heads]
        # that live beside the block tables in the same cache node, so the
        # engine's with_tables/strip_tables walk, donation and sharding all
        # carry them automatically. Reads dequantize in-kernel
        # (ops/paged_attention.py); the allocator never sees dtypes.
        quant_kv = cfg.kv_cache_dtype == "int8"
        pool_dtype = jnp.int8 if quant_kv else k.dtype
        kp = self.variable(
            "cache", "k_pages",
            lambda: jnp.zeros(
                (cfg.kv_num_pages, page_size, heads, head_dim), pool_dtype
            ),
        )
        vp = self.variable(
            "cache", "v_pages",
            lambda: jnp.zeros(
                (cfg.kv_num_pages, page_size, heads, head_dim), pool_dtype
            ),
        )
        if quant_kv:
            ks = self.variable(
                "cache", "k_scales",
                lambda: jnp.zeros(
                    (cfg.kv_num_pages, page_size, heads), jnp.float32
                ),
            )
            vs = self.variable(
                "cache", "v_scales",
                lambda: jnp.zeros(
                    (cfg.kv_num_pages, page_size, heads), jnp.float32
                ),
            )
        # Placeholder shapes only: the engine always supplies real
        # block_table/context_len values per call (serve/paged_cache.py
        # with_tables); they are never engine-resident.
        bt = self.variable(
            "cache", "block_table",
            lambda: jnp.zeros((batch, 1), jnp.int32),
        )
        cl = self.variable(
            "cache", "context_len", lambda: jnp.zeros((batch,), jnp.int32)
        )
        if is_init:
            return q
        idx = cl.value  # [batch]
        # Scatter this chunk's K/V through the block table: token position
        # idx+j lives at page bt[b, (idx+j)//P], offset (idx+j)%P.
        pos = idx[:, None] + jax.lax.broadcasted_iota(
            jnp.int32, (batch, chunk), 1
        )
        page_ids = jnp.take_along_axis(bt.value, pos // page_size, axis=1)
        offs = pos % page_size
        if quant_kv:
            # quantize-on-write: the scale entries scatter through the SAME
            # (page, offset) indices as their values, so a token's int8
            # lanes and its fp32 scales can never drift apart
            kq, ksc = quantize_kv(k)
            vq, vsc = quantize_kv(v)
            kp.value = kp.value.at[page_ids, offs].set(kq)
            vp.value = vp.value.at[page_ids, offs].set(vq)
            ks.value = ks.value.at[page_ids, offs].set(ksc)
            vs.value = vs.value.at[page_ids, offs].set(vsc)
            pool_kw = dict(k_scales=ks.value, v_scales=vs.value)
        else:
            kp.value = kp.value.at[page_ids, offs].set(
                k.astype(kp.value.dtype)
            )
            vp.value = vp.value.at[page_ids, offs].set(
                v.astype(vp.value.dtype)
            )
            pool_kw = {}
        cl.value = idx + chunk
        scale = head_dim ** -0.5
        if chunk == 1:
            if attention_bias is not None:
                raise ValueError(
                    "paged decode steps take no attention bias (padding is "
                    "expressed through context_len)"
                )
            out = paged_attention(
                q[:, 0], kp.value, vp.value, bt.value, idx + 1,
                scale=scale, impl=cfg.paged_attention_impl, **pool_kw,
            )
            return out[:, None]
        if cfg.paged_multiquery:
            # Multi-token query over an existing context: the chunk's rows
            # sit at positions idx..idx+chunk-1 and see everything written
            # up to themselves (lengths inclusive of the chunk). Used by
            # the engine's speculative-verify and chunked-prefill programs.
            if attention_bias is not None:
                raise ValueError(
                    "paged multiquery attention takes no attention bias "
                    "(padding is expressed through context_len)"
                )
            return paged_attention(
                q, kp.value, vp.value, bt.value, idx + chunk,
                scale=scale, impl=cfg.paged_attention_impl, **pool_kw,
            )
        # Prefill: fresh sequence (idx == 0 by engine contract), so the
        # visible context IS this chunk — attend intra-chunk with the exact
        # dense-cache formula (fp32 scores, finfo.min mask, fp32 softmax)
        # so paged prefill stays bitwise against the dense path. Under int8
        # pools the fresh K/V stays in compute dtype here (only the STORED
        # pages quantize), so prefill logits — and the first sampled token —
        # are exact whatever the pool dtype.
        kc = k if quant_kv else k.astype(kp.value.dtype)
        vc = v if quant_kv else v.astype(vp.value.dtype)
        scores = jnp.einsum(
            "bsnd,btnd->bnst", q, kc, preferred_element_type=jnp.float32
        ) * scale
        q_pos = jax.lax.broadcasted_iota(jnp.int32, (chunk, chunk), 0)
        k_pos = jax.lax.broadcasted_iota(jnp.int32, (chunk, chunk), 1)
        neg = jnp.finfo(jnp.float32).min
        scores = jnp.where((k_pos <= q_pos)[None, None], scores, neg)
        if attention_bias is not None:
            scores = scores + attention_bias.astype(jnp.float32)
        probs = jax.nn.softmax(scores, axis=-1).astype(vc.dtype)
        return jnp.einsum("bnst,btnd->bsnd", probs, vc)


class BertLayer(nn.Module):
    """Post-LN transformer block (BERT convention)."""

    config: ModelConfig

    @nn.compact
    def __call__(self, x, attention_bias, deterministic):
        cfg = self.config
        kw = dict(dtype=_dtype(cfg), param_dtype=_pdtype(cfg),
                  kernel_init=nn.initializers.normal(stddev=0.02))
        def tail(name, site):
            # Dropout -> residual add -> LN as ONE fused op (Pallas kernel
            # on TPU with the keep-mask regenerated in-kernel; jax.random
            # dropout + reference LN elsewhere). site splits the PRNG
            # stream between the block's two tails.
            return FusedDropoutAddLayerNorm(
                epsilon=cfg.layer_norm_eps, rate=cfg.hidden_dropout,
                param_dtype=_pdtype(cfg), out_dtype=_dtype(cfg),
                impl=cfg.layernorm_impl, site=site,
                dropout_impl=cfg.dropout_impl, name=name,
            )

        attn_out = BertSelfAttention(cfg, name="attention")(
            x, attention_bias, deterministic
        )
        x = tail("attention_norm", 0)(attn_out, x, deterministic)

        h = dense_general(cfg, cfg.intermediate_size, -1, "mlp_up", kw)(x)
        h = nn.gelu(h, approximate=cfg.gelu_approximate)
        h = dense_general(cfg, cfg.hidden_size, -1, "mlp_down", kw)(h)
        return tail("mlp_norm", 1)(h, x, deterministic)


def default_position_ids(cfg: ModelConfig, input_ids):
    """Position ids per model family: RoBERTa counts non-pad tokens offset
    past the pad id; BERT uses plain arange. Shared by every trunk (single
    encoder AND the branch ensemble) so family semantics can't drift."""
    batch, seq = input_ids.shape
    # roberta positions run pad_token_id+1 .. seq+pad_token_id (HF offset)
    max_pos = seq + cfg.pad_token_id + 1 if cfg.roberta_style else seq
    if max_pos > cfg.max_position_embeddings:
        raise ValueError(
            f"sequence length {seq} needs position ids up to {max_pos - 1} "
            f"but max_position_embeddings is {cfg.max_position_embeddings}"
        )
    if cfg.roberta_style:
        mask = (input_ids != cfg.pad_token_id).astype(jnp.int32)
        return jnp.cumsum(mask, axis=-1) * mask + cfg.pad_token_id
    return jnp.broadcast_to(
        jnp.arange(seq, dtype=jnp.int32)[None, :], (batch, seq)
    )


def remat_policy(cfg: ModelConfig):
    """Map ``cfg.remat_policy`` to a ``jax.checkpoint`` policy (None =
    save nothing = classic full remat). Shared by both model families."""
    name = getattr(cfg, "remat_policy", "nothing")
    if name == "nothing":
        return None
    import jax

    # name validity is enforced once, in ModelConfig.__post_init__ — a
    # KeyError here means a config bypassed the dataclass constructor
    return {
        "dots": jax.checkpoint_policies.dots_saveable,
        "weight_dots": jax.checkpoint_policies.dots_with_no_batch_dims_saveable,
    }[name]


def _layer_cls(cfg: ModelConfig):
    """BertLayer, remat-wrapped when configured — the ONE place the
    nn.remat/static_argnums contract with BertLayer.__call__ is encoded."""
    if cfg.remat:
        return nn.remat(
            BertLayer, static_argnums=(3,), policy=remat_policy(cfg)
        )
    return BertLayer


def run_layers(cfg: ModelConfig, x, attention_bias, deterministic):
    """The python-loop trunk body (layer_0..layer_{N-1}), shared by
    BertEncoderModel's non-scan path and each ensemble branch. Must be called
    from inside an ``@nn.compact`` ``__call__`` (submodules register in the
    caller's scope, keeping the flat ``layer_i`` param names)."""
    for i in range(cfg.num_layers):
        x = _layer_cls(cfg)(cfg, name=f"layer_{i}")(
            x, attention_bias, deterministic
        )
    return x


def pool_cls(cfg: ModelConfig, x, deterministic):
    """CLS pooling head: [roberta pre-dropout →] dense('pooler') → tanh.

    RobertaClassificationHead applies dropout BEFORE its dense (dropout →
    dense → tanh → dropout → out_proj); BERT's pooler does not. Keeping the
    distinction here — shared by all classifiers — regularizes fine-tuning
    identically to the respective HF heads."""
    cls = x[:, 0]
    if cfg.roberta_style:
        cls = Dropout(cfg.hidden_dropout, cfg.dropout_impl)(
            cls, deterministic=deterministic
        )
    pooled = nn.Dense(
        cfg.hidden_size, dtype=x.dtype, param_dtype=_pdtype(cfg),
        kernel_init=nn.initializers.normal(stddev=0.02), name="pooler",
    )(cls)
    return jnp.tanh(pooled)


def classify(cfg: ModelConfig, pooled, deterministic):
    """dropout → fp32 dense('classifier') → logits, shared by all heads."""
    pooled = Dropout(cfg.hidden_dropout, cfg.dropout_impl)(
        pooled, deterministic=deterministic
    )
    return nn.Dense(
        cfg.num_labels, dtype=jnp.float32, param_dtype=_pdtype(cfg),
        kernel_init=nn.initializers.normal(stddev=0.02), name="classifier",
    )(pooled.astype(jnp.float32))


class _ScanBlock(nn.Module):
    """One layer in (carry, x) scan form for ``nn.scan`` stacking."""

    config: ModelConfig
    deterministic: bool

    @nn.compact
    def __call__(self, x, attention_bias):
        cfg = self.config
        x = _layer_cls(cfg)(cfg, name="layer")(
            x, attention_bias, self.deterministic
        )
        return x, None


class BertEncoderModel(nn.Module):
    """Embeddings + N layers + pooler → (sequence_output, pooled_output)."""

    config: ModelConfig

    @nn.compact
    def __call__(
        self,
        input_ids,
        attention_mask=None,
        token_type_ids=None,
        position_ids=None,
        deterministic: bool = True,
    ):
        cfg = self.config
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        if position_ids is None:
            position_ids = default_position_ids(cfg, input_ids)

        x = BertEmbeddings(cfg, name="embeddings")(
            input_ids, token_type_ids, position_ids, deterministic
        )
        bias = make_attention_bias(attention_mask)

        if cfg.scan_layers:
            # Layers stacked on a leading [num_layers] param dim and walked
            # with ONE traced body (lax.scan): near-constant compile time in
            # depth, and the layer dim becomes shardable — the mesh ``stage``
            # axis splits it into contiguous layer blocks per stage slice,
            # the GSPMD generalization of the reference ConcatBert's 2-stage
            # layer split (test_model_parallelism.py:40-89, where stage
            # transfer was a hand-written ``.to(second_device)`` at :62-63).
            scan = nn.scan(
                _ScanBlock,
                # "quant": per-layer delayed-int8 amaxes stack on the same
                # leading [num_layers] dim as the params (no-op otherwise)
                variable_axes={"params": 0, "quant": 0, "quant_sink": 0},
                split_rngs={"params": True, "dropout": True},
                in_axes=(nn.broadcast,),
                length=cfg.num_layers,
            )
            x, _ = scan(cfg, deterministic, name="layers_scan")(x, bias)
        else:
            x = run_layers(cfg, x, bias, deterministic)

        return x, pool_cls(cfg, x, deterministic)


class BertForSequenceClassification(nn.Module):
    """Trunk + dropout + classifier head → logits [batch, num_labels].

    Loss lives in the train step (functional style), not the module — unlike
    the reference where CE loss is computed inside ``forward``
    (test_model_parallelism.py:153-156).
    """

    config: ModelConfig

    @nn.compact
    def __call__(
        self,
        input_ids,
        attention_mask=None,
        token_type_ids=None,
        position_ids=None,
        deterministic: bool = True,
    ):
        cfg = self.config
        _, pooled = BertEncoderModel(cfg, name="bert")(
            input_ids, attention_mask, token_type_ids, position_ids,
            deterministic,
        )
        return classify(cfg, pooled, deterministic)
