"""Autoregressive generation for the causal-LM family (KV-cache decode).

The reference repo is fine-tuning-only — it never samples from a model. A
complete framework needs the inference side of its decoder family, so this
module provides jitted prefill+decode generation over the KV cache that
``BertSelfAttention._cached_attend`` maintains (flax "cache" collection):

- ONE forward over the whole prompt fills the cache (prefill), then a
  ``lax.scan`` emits one token per step attending over the cache — O(L) per
  new token instead of the O(L^2) full-recompute loop.
- Greedy (temperature=0) or temperature/top-k sampling via
  ``jax.random.categorical``.
- Static shapes throughout (prompt length and max_new_tokens fix the cache
  size), so the whole generate call is one compiled program — XLA-friendly
  exactly like the train step.

Prompt batches are right-padded. Each row's next-token distribution starts
from its own last REAL prompt token (``prompt_lengths``), and pad positions
are masked out of attention; continuations for every row are written at
columns [prompt_len, prompt_len + max_new_tokens). Decode steps pass
per-row position ids (``prompt_lengths + t``) explicitly, so a generated
token's GPT-2 absolute position continues from the row's REAL length, not
the padded column index — ragged batches attend with correct positions
(each row's continuation is identical to running it alone unpadded;
pinned by tests/test_generate.py::test_padded_matches_exact_per_row).
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


# LRU-bounded: long-running servers cycling many request shapes would
# otherwise retain one jitted executable (plus closed-over constants) per
# distinct (config, shapes, sampling params) key forever.
_RUN_CACHE: "OrderedDict" = OrderedDict()
_RUN_CACHE_MAX = 32
def _sample(logits, rng, temperature: float, top_k: int):
    """logits [B, V] -> token ids [B]."""
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k > 0:
        kth = jax.lax.top_k(logits, top_k)[0][:, -1:]
        logits = jnp.where(logits < kth, jnp.finfo(logits.dtype).min, logits)
    return jax.random.categorical(rng, logits).astype(jnp.int32)


def generate(
    model,
    params,
    prompt_ids: np.ndarray,
    *,
    max_new_tokens: int,
    prompt_lengths: Optional[np.ndarray] = None,
    temperature: float = 0.0,
    top_k: int = 0,
    rng=None,
    eot_id: Optional[int] = None,
):
    """Generate continuations for a batch of right-padded prompts.

    Args:
        model: a ``GPT2LMModel`` (or config-compatible causal LM). A
            ``scan_layers=True`` model is accepted: its stacked params are
            re-laid-out to the per-layer form (models/relayout.py) and
            decode runs the unscanned trunk.
        params: trained parameter pytree for ``model``.
        prompt_ids: [batch, prompt_len] int32, right-padded.
        max_new_tokens: tokens to append per row.
        prompt_lengths: [batch] real prompt lengths; defaults to full rows.
        temperature: 0 → greedy argmax; >0 → categorical sampling.
        top_k: keep only the k highest logits before sampling (0 = all).
        rng: jax PRNG key (required when temperature > 0).
        eot_id: when set, a row that emits this token keeps emitting it
            (frozen) for the rest of the scan.

    Returns:
        [batch, prompt_len + max_new_tokens] int32 — the padded prompts
        with continuations in the trailing ``max_new_tokens`` columns.
    """
    cfg = model.config
    if not cfg.causal:
        raise ValueError("generate() needs a causal model")
    if cfg.scan_layers:
        # The decode path runs the unscanned trunk (per-layer KV caches);
        # a scan-trained checkpoint is the same weights in stacked form —
        # re-layout and decode with scan_layers=False. The re-layout is
        # per-call (cheap next to decode); hot serving loops can pre-apply
        # models/relayout.unstack_scanned_params once and pass an
        # unscanned model+params instead.
        from pytorch_distributed_training_tpu.models.relayout import (
            unstack_scanned_params,
        )

        cfg = dataclasses.replace(cfg, scan_layers=False)
        model = type(model)(cfg)
        params = unstack_scanned_params(params)
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature sampling needs an rng key")
    prompt_ids = jnp.asarray(prompt_ids, jnp.int32)
    batch, prompt_len = prompt_ids.shape
    total_len = prompt_len + max_new_tokens
    if total_len > cfg.max_position_embeddings:
        raise ValueError(
            f"prompt_len + max_new_tokens = {total_len} exceeds "
            f"max_position_embeddings {cfg.max_position_embeddings}"
        )
    if prompt_lengths is None:
        prompt_lengths = jnp.full((batch,), prompt_len, jnp.int32)
    else:
        prompt_lengths = jnp.asarray(prompt_lengths, jnp.int32)
    if rng is None:
        rng = jax.random.key(0)

    decode_model = type(model)(dataclasses.replace(cfg, decode=True))
    run_key = (
        type(model).__name__, dataclasses.astuple(cfg), batch, prompt_len,
        max_new_tokens, temperature, top_k, eot_id,
    )

    # Cache buffers are sized by the init input: shape-infer the "cache"
    # collection from an abstract init at total_len (eval_shape — no params
    # are materialized) and allocate zeros per leaf.
    cache_shapes = jax.eval_shape(
        lambda: decode_model.init(
            jax.random.key(0), jnp.ones((batch, total_len), jnp.int32)
        )
    )["cache"]
    cache = jax.tree.map(
        lambda s: jnp.zeros(s.shape, s.dtype), cache_shapes
    )

    def make_run():
      def run(params, cache, prompt_ids, prompt_lengths, rng):
        out = jnp.zeros((batch, total_len), jnp.int32)
        out = jax.lax.dynamic_update_slice(out, prompt_ids, (0, 0))
        positions = jnp.arange(total_len, dtype=jnp.int32)[None, :]

        def mask_upto(n_generated):
            """Visibility over the full buffer: each row's real prompt
            prefix plus the first ``n_generated`` generated positions."""
            return (
                (positions < prompt_lengths[:, None])
                | (
                    (positions >= prompt_len)
                    & (positions < prompt_len + n_generated)
                )
            ).astype(jnp.int32)

        # ---- prefill: one forward over the whole (padded) prompt
        logits, vars_ = decode_model.apply(
            {"params": params, "cache": cache},
            prompt_ids,
            mask_upto(0),
            mutable=["cache"],
        )
        cache = vars_["cache"]
        # next token comes from each row's LAST REAL prompt position
        last = jnp.take_along_axis(
            logits, (prompt_lengths - 1)[:, None, None], axis=1
        )[:, 0, :].astype(jnp.float32)

        def step(carry, t):
            cache, out, prev_logits, done, rng = carry
            rng, sub = jax.random.split(rng)
            nxt = _sample(prev_logits, sub, temperature, top_k)
            if eot_id is not None:
                nxt = jnp.where(done, eot_id, nxt)
                done = done | (nxt == eot_id)
            out = out.at[:, prompt_len + t].set(nxt)
            logits, vars_ = decode_model.apply(
                {"params": params, "cache": cache},
                nxt[:, None],
                mask_upto(t + 1),
                # per-row positions: the generated token's absolute position
                # continues from the row's REAL prompt length, not from the
                # padded column it is stored at (right-padding positional
                # gap fix) — for full-length rows this is exactly the value
                # the cached pos_index would have supplied
                position_ids=(prompt_lengths + t)[:, None],
                mutable=["cache"],
            )
            return (
                vars_["cache"], out, logits[:, 0, :].astype(jnp.float32),
                done, rng,
            ), None

        done0 = jnp.zeros((batch,), bool)
        (cache, out, _, _, _), _ = jax.lax.scan(
            step,
            (cache, out, last, done0, rng),
            jnp.arange(max_new_tokens, dtype=jnp.int32),
        )
        return out

      return jax.jit(run)

    # one compiled program per (model config, shapes, sampling params):
    # repeated generate() calls reuse the executable instead of retracing
    run = _RUN_CACHE.get(run_key)
    if run is None:
        run = _RUN_CACHE[run_key] = make_run()
        if len(_RUN_CACHE) > _RUN_CACHE_MAX:
            _RUN_CACHE.popitem(last=False)
    else:
        _RUN_CACHE.move_to_end(run_key)
    return run(params, cache, prompt_ids, prompt_lengths, rng)
