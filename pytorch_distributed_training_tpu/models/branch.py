"""Branch-ensemble classifier: the TriBert capability, TPU-first.

The reference's ``TriBert`` (reference test_model_parallelism.py:92-163) is a
3-branch ensemble: shared input embeddings from bert_1 (:114,118), each branch
a full BERT encoder on its own device (:98-103,120-137), branch outputs moved
back to one device and ``stack(dim=1).mean(dim=1)``-fused (:139-148), then
bert_1's pooler/dropout/classifier produce logits (:149-151).

TPU-first redesign — no ``.to(device)`` shuttling, no serialized branches:

- the branch dimension is a *parameter axis*: ``nn.vmap`` stacks the three
  encoders' weights with a leading [n_branches, ...] dim, and the sharding
  policy maps that dim onto the mesh's ``model`` axis — so each mesh slice
  holds exactly one branch's weights and all branches run CONCURRENTLY
  (the reference executes them sequentially, :120-137; SURVEY.md §7 calls
  out doing better);
- the embedded input is broadcast to branches (``in_axes=None``) — the
  shared-embedding semantics of :114,118;
- the mean over the branch axis is the fuse (:148); under branch sharding
  XLA lowers it to one small all-reduce over ``model`` — the only
  cross-branch communication in the whole forward.
"""

from __future__ import annotations

import flax.linen as nn
import jax.numpy as jnp

from pytorch_distributed_training_tpu.models.bert import (
    BertEmbeddings,
    classify,
    default_position_ids,
    pool_cls,
    run_layers,
)
from pytorch_distributed_training_tpu.ops.attention import make_attention_bias
from pytorch_distributed_training_tpu.utils.config import ModelConfig

BRANCH_MODULE = "branches"  # param-tree key the sharding policy matches on


class _EncoderStack(nn.Module):
    """N transformer layers — one ensemble branch (no embeddings/pooler)."""

    config: ModelConfig

    @nn.compact
    def __call__(self, x, attention_bias, deterministic):
        return run_layers(self.config, x, attention_bias, deterministic)


class BranchEnsembleClassifier(nn.Module):
    """n_branches parallel encoders over shared embeddings → mean-fused CLS.

    Semantics of reference TriBert.forward (test_model_parallelism.py:
    105-163): shared embeddings → per-branch encoders → stack+mean fuse →
    pooler → dropout → classifier. Loss lives in the train step.
    """

    config: ModelConfig
    n_branches: int = 3

    @nn.compact
    def __call__(
        self,
        input_ids,
        attention_mask=None,
        token_type_ids=None,
        position_ids=None,
        deterministic: bool = True,
    ):
        cfg = self.config
        if token_type_ids is None:
            token_type_ids = jnp.zeros_like(input_ids)
        if position_ids is None:
            position_ids = default_position_ids(cfg, input_ids)

        # Shared embeddings (the reference reuses bert_1's embedding table
        # for every branch, :114,118) — computed ONCE, broadcast to branches.
        x = BertEmbeddings(cfg, name="embeddings")(
            input_ids, token_type_ids, position_ids, deterministic
        )
        bias = make_attention_bias(attention_mask)

        # vmap over the branch axis: params gain a leading [n_branches] dim
        # (sharded over the mesh "model" axis by ShardingPolicy(branch=True)),
        # inputs broadcast, outputs stack on axis 0.
        branches = nn.vmap(
            _EncoderStack,
            # "quant": per-branch delayed-int8 amaxes (ops/quant.py)
            variable_axes={"params": 0, "quant": 0, "quant_sink": 0},
            split_rngs={"params": True, "dropout": True},
            in_axes=(None, None, None),
            out_axes=0,
            axis_size=self.n_branches,
            methods=["__call__"],
        )(cfg, name=BRANCH_MODULE)
        hidden = branches(x, bias, deterministic)  # [n_branches, B, S, H]

        # stack+mean fuse (reference :139-148); in fp32 like the reference's
        # fp32 path, then back to the compute dtype.
        fused = jnp.mean(hidden.astype(jnp.float32), axis=0)
        fused = fused.astype(x.dtype)

        pooled = pool_cls(cfg, fused, deterministic)
        return classify(cfg, pooled, deterministic)
