"""Capture an xprof trace of the production bert-large train step and print
a per-op-category time breakdown (device ops only).

Usage: python scripts/trace_step.py [micro] [steps]
Writes the raw trace under /tmp/xprof_step and prints the bucketed ledger
(dot/fusion/copy/rng/... in ms per step) — the data source for NOTES.md's
perf ledger entries.
"""

import collections
import glob
import gzip
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from pytorch_distributed_training_tpu.comms.mesh import build_mesh
from pytorch_distributed_training_tpu.models import BertForSequenceClassification
from pytorch_distributed_training_tpu.parallel import ShardingPolicy, state_shardings
from pytorch_distributed_training_tpu.parallel.sharding import shard_state
from pytorch_distributed_training_tpu.train.optim import adamw_with_schedule
from pytorch_distributed_training_tpu.train.state import create_train_state
from pytorch_distributed_training_tpu.train.step import make_train_step
from pytorch_distributed_training_tpu.utils.config import TrainConfig, model_preset

GLOBAL, SEQ = 96, 128


def build_step(micro, model_name="bert-large-cased", seq=None, global_batch=None):
    import os as _os
    _attn = {"attention_impl": _os.environ["ATTN"]} if _os.environ.get("ATTN") else {}
    if _os.environ.get("MATMUL"):
        _attn["matmul_impl"] = _os.environ["MATMUL"]
    if _os.environ.get("QUANT_DELAYED") == "1":
        # the shipping bench config: delayed int8 activation scaling
        if not str(_attn.get("matmul_impl", "")).startswith("int8"):
            # same contract as train_dp's CLI guard: a silently-bf16 trace
            # labeled "delayed int8" is worse than an error
            raise SystemExit("QUANT_DELAYED=1 requires MATMUL=int8|int8_full")
        _attn["quant_delayed"] = True
    global_batch = global_batch or GLOBAL
    seq = seq or SEQ
    mesh = build_mesh()
    from pytorch_distributed_training_tpu.ops.dispatch import set_kernel_mesh

    set_kernel_mesh(mesh)  # multi-chip: keep the Pallas kernel path active
    mcfg = model_preset(model_name, dropout_impl="kernel", **_attn)
    if mcfg.causal:
        from pytorch_distributed_training_tpu.models.gpt2 import GPT2LMModel

        model = GPT2LMModel(mcfg)
        objective = "causal_lm"
    else:
        model = BertForSequenceClassification(mcfg)
        objective = "classification"
    tcfg = TrainConfig(
        global_batch_size=global_batch, micro_batch_size=micro,
        max_seq_length=seq,
        grad_accum_dtype="bfloat16", adam_mu_dtype="bfloat16",
        adam_nu_dtype="bfloat16",
    )
    tx, _ = adamw_with_schedule(tcfg, total_steps=1000)
    example = {
        "input_ids": jnp.ones((2, seq), jnp.int32),
        "attention_mask": jnp.ones((2, seq), jnp.int32),
        "token_type_ids": jnp.zeros((2, seq), jnp.int32),
    }
    state = create_train_state(model, tx, jax.random.key(42, impl="rbg"), example)
    shardings = state_shardings(state, ShardingPolicy(), mesh)
    state = shard_state(state, shardings)
    step = make_train_step(
        grad_accum_steps=tcfg.grad_accum_steps, mesh=mesh,
        state_shardings=shardings, objective=objective,
        accum_dtype=tcfg.grad_accum_dtype,
    )
    import numpy as np
    from pytorch_distributed_training_tpu.comms.ingest import make_global_batch
    from pytorch_distributed_training_tpu.comms.mesh import TRAIN_BATCH_PSPEC

    rng = np.random.default_rng(0)
    accum = tcfg.grad_accum_steps
    b = {
        "input_ids": rng.integers(
            0, mcfg.vocab_size, (accum, micro, seq)
        ).astype(np.int32),
        "attention_mask": np.ones((accum, micro, seq), np.int32),
        "token_type_ids": np.zeros((accum, micro, seq), np.int32),
        "labels": rng.integers(0, 2, (accum, micro)).astype(np.int32),
    }
    batch = make_global_batch(mesh, b, pspec=TRAIN_BATCH_PSPEC)
    from pytorch_distributed_training_tpu.train.step import calibrate_quant

    # no-op unless the config carries delayed-quant state
    state = calibrate_quant(state, jax.tree.map(lambda x: x[0], batch))
    return step, state, batch


def bucket(name: str) -> str:
    n = name.lower()
    if n.startswith("fusion") or ".fusion" in n:
        return "fusion(loop/other)"
    for key, b in (
        ("dot", "dot"), ("conv", "dot"), ("copy", "copy"),
        ("rng", "rng"), ("all-reduce", "collective"),
        ("dynamic-update", "dus"), ("transpose", "transpose"),
        ("reduce", "reduce"), ("scatter", "scatter"), ("iota", "misc"),
    ):
        if key in n:
            return b
    return "misc"


def main():
    micro = int(sys.argv[1]) if len(sys.argv) > 1 else 32
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    model = sys.argv[3] if len(sys.argv) > 3 else "bert-large-cased"
    seq = int(sys.argv[4]) if len(sys.argv) > 4 else None
    glob_b = int(sys.argv[5]) if len(sys.argv) > 5 else None
    step, state, batch = build_step(
        micro, model_name=model, seq=seq, global_batch=glob_b
    )
    state, m = step(state, batch)  # compile
    jax.block_until_ready(state.params)

    tracedir = "/tmp/xprof_step"
    import shutil

    shutil.rmtree(tracedir, ignore_errors=True)
    with jax.profiler.trace(tracedir):
        for _ in range(steps):
            state, m = step(state, batch)
        float(jax.device_get(m["loss"]))

    # parse the perfetto trace: device-lane complete events
    paths = glob.glob(tracedir + "/**/*.trace.json.gz", recursive=True)
    assert paths, "no trace written"
    with gzip.open(paths[0], "rt") as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    # find device process ids (TPU core lanes)
    device_pids = {
        e["pid"]
        for e in events
        if e.get("ph") == "M"
        and e.get("name") == "process_name"
        and "TPU" in str(e.get("args", {}).get("name", ""))
    }
    # leaf XLA ops live on the "XLA Ops" thread lanes; module/step lanes
    # hold container events that would double-count
    op_tids = {
        (e["pid"], e["tid"])
        for e in events
        if e.get("ph") == "M"
        and e.get("name") == "thread_name"
        and e["pid"] in device_pids
        and "XLA Ops" in str(e.get("args", {}).get("name", ""))
    }
    # exclusive time per event: subtract children (events nest on a lane)
    lanes = collections.defaultdict(list)
    for e in events:
        if e.get("ph") != "X" or (e.get("pid"), e.get("tid")) not in op_tids:
            continue
        lanes[(e["pid"], e.get("tid"))].append(e)
    per_op = collections.Counter()
    per_bucket = collections.Counter()
    for lane in lanes.values():
        lane.sort(key=lambda e: (e["ts"], -e.get("dur", 0)))
        stack = []  # (end_ts, name, child_time_accum index)
        child_time = []
        for e in lane:
            ts, dur = e["ts"], e.get("dur", 0)
            while stack and ts >= stack[-1][0] - 1e-9:
                end, name, idx = stack.pop()
                excl = child_time[idx][0] - child_time[idx][1]
                per_op[name] += excl / 1e3 / steps
                per_bucket[bucket(name)] += excl / 1e3 / steps
                if stack:
                    child_time[stack[-1][2]][1] += child_time[idx][0]
            stack.append((ts + dur, e.get("name", "?"), len(child_time)))
            child_time.append([dur, 0.0])
        while stack:
            end, name, idx = stack.pop()
            excl = child_time[idx][0] - child_time[idx][1]
            per_op[name] += excl / 1e3 / steps
            per_bucket[bucket(name)] += excl / 1e3 / steps
            if stack:
                child_time[stack[-1][2]][1] += child_time[idx][0]
    total = sum(per_bucket.values())
    print(f"\n== micro {micro}: device time {total:.1f} ms/step ==")
    for b, ms in per_bucket.most_common():
        print(f"  {b:22s} {ms:8.2f} ms")
    # group ops by name family (trailing .N stripped) to see where time goes
    fam = collections.Counter()
    fam_n = collections.Counter()
    import re

    for name, ms in per_op.items():
        f = re.sub(r"[.\d]+$", "", name)
        fam[f] += ms
        fam_n[f] += 1
    print("\nop families (exclusive ms/step, count):")
    for f, ms in fam.most_common(30):
        print(f"  {ms:8.2f} ms  x{fam_n[f]:<5d} {f[:100]}")


if __name__ == "__main__":
    main()
