"""gpt2-medium @ seq 1024 step-time sweep: attention x remat x scan x micro.

Same chained-timing discipline as bench_combo.py. Edit the combos at the
bottom; each run() times the production train step on the real chip.
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_training_tpu.comms.ingest import make_global_batch
from pytorch_distributed_training_tpu.comms.mesh import (
    TRAIN_BATCH_PSPEC,
    build_mesh,
)
from pytorch_distributed_training_tpu.models.gpt2 import GPT2LMModel
from pytorch_distributed_training_tpu.parallel import (
    ShardingPolicy,
    state_shardings,
)
from pytorch_distributed_training_tpu.parallel.sharding import shard_state
from pytorch_distributed_training_tpu.train.optim import adamw_with_schedule
from pytorch_distributed_training_tpu.train.state import create_train_state
from pytorch_distributed_training_tpu.train.step import make_train_step
from pytorch_distributed_training_tpu.utils.config import (
    TrainConfig,
    model_preset,
)

GLOBAL, SEQ, ITERS = 32, 1024, 8


def run(micro=4, block_q=None, block_k=None, unroll=None, **mkw):
    if block_q or block_k:
        import pytorch_distributed_training_tpu.ops.flash_attention as fa
        fa.DEFAULT_BLOCK_Q = block_q or fa.DEFAULT_BLOCK_Q
        fa.DEFAULT_BLOCK_K = block_k or fa.DEFAULT_BLOCK_K
    mesh = build_mesh()
    mcfg = model_preset("gpt2-medium", **mkw)
    model = GPT2LMModel(mcfg)
    tcfg = TrainConfig(
        global_batch_size=GLOBAL, micro_batch_size=micro,
        max_seq_length=SEQ, grad_accum_dtype="bfloat16",
        adam_mu_dtype="bfloat16", adam_nu_dtype="bfloat16",
    )
    tx, _ = adamw_with_schedule(tcfg, total_steps=1000)
    example = {
        "input_ids": jnp.ones((2, SEQ), jnp.int32),
        "attention_mask": jnp.ones((2, SEQ), jnp.int32),
    }
    state = create_train_state(model, tx, jax.random.key(42, impl="rbg"), example)
    shardings = state_shardings(state, ShardingPolicy(), mesh)
    state = shard_state(state, shardings)
    accum = tcfg.grad_accum_steps
    step = make_train_step(
        grad_accum_steps=accum, mesh=mesh, state_shardings=shardings,
        objective="causal_lm", accum_dtype=tcfg.grad_accum_dtype,
        unroll_accum=unroll,
    )
    rng = np.random.default_rng(0)
    b = {
        "input_ids": rng.integers(0, 50257, (accum, micro, SEQ)).astype(np.int32),
        "attention_mask": np.ones((accum, micro, SEQ), np.int32),
    }
    batch = make_global_batch(mesh, b, pspec=TRAIN_BATCH_PSPEC)
    state, m = step(state, batch)
    jax.block_until_ready(state.params)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            state, m = step(state, batch)
        _ = float(jax.device_get(m["loss"]))
        best = min(best, (time.perf_counter() - t0) / ITERS)
    flags = " ".join(f"{k}={v}" for k, v in mkw.items())
    if block_q or block_k:
        flags += f" bq={block_q} bk={block_k}"
    if unroll is not None:
        flags += f" unroll={unroll}"
    sps = GLOBAL / best
    toks = sps * SEQ
    print(
        f"micro={micro} {flags:55s} {best*1e3:8.1f} ms/step "
        f"{sps:6.2f} samples/s  {toks/1e3:6.1f}k tok/s",
        flush=True,
    )


def parse_combo(spec):
    """``micro=8,remat_mlp=True,block_q=512`` -> kwargs dict (literals only)."""
    import ast

    out = {}
    for part in spec.split(","):
        key, _, val = part.partition("=")
        if not _:
            raise SystemExit(f"combo item {part!r} is not key=value")
        out[key.strip()] = ast.literal_eval(val.strip())
    return out


if __name__ == "__main__":
    # combos picked per round; pass key=value lists as argv to override,
    # e.g. scripts/bench_gpt2.py "micro=8,remat_mlp=True"
    default = (
        dict(micro=4),
        dict(micro=6, remat_mlp=True),
        dict(micro=8, remat_mlp=True),
        dict(micro=4, remat_mlp=True),
        dict(micro=16, remat_mlp=True),
    )
    combos = (
        [parse_combo(a) for a in sys.argv[1:]]
        if len(sys.argv) > 1
        else default
    )
    for kw in combos:
        run(**kw)
