"""Audit the collective footprint (and fusions) of a compiled train step.

Subsumes the old ``dump_hlo.py``: compiles the production train step (or
reads an existing HLO dump with ``--hlo-file``), writes the full text to
``--out``, and reports every collective the SPMD partitioner inserted —
kind, payload/moved bytes, group sizes, ICI vs DCN split — through
``analysis/spmd/hlo.py``'s extractor and cost model.

Usage:
  python scripts/audit_hlo.py [micro] [--model NAME] [--seq N]
      [--global-batch N]      # compile the production step (trace_step)
  python scripts/audit_hlo.py --hlo-file /tmp/step_hlo.txt
      [--world-size N]        # audit an existing dump, jax-free
  --json                      # machine-readable summary on stdout
  --check                     # exit 1 unless the footprint conforms to
                              # the mesh-derived train manifest
  --expect KINDS              # comma-separated allowed kinds overriding
                              # the mesh-derived manifest (e.g.
                              # --expect all-gather,reduce-scatter)
  --max-bytes N               # payload-bytes ceiling for --check
  --fusions                   # also print one representative instruction
                              # per named-fusion family (dump_hlo's job)
"""

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_distributed_training_tpu.analysis.spmd.hlo import (  # noqa: E402
    COLLECTIVE_KINDS,
    extract_collectives,
    summarize_collectives,
)
from pytorch_distributed_training_tpu.analysis.spmd.manifest import (  # noqa: E402
    CommManifest,
    train_manifest,
)


def _parse_args(argv):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("micro", nargs="?", type=int, default=32)
    p.add_argument("--model", default="bert-large-cased")
    p.add_argument("--seq", type=int, default=None)
    p.add_argument("--global-batch", type=int, default=None)
    p.add_argument("--hlo-file", default=None,
                   help="audit this HLO text instead of compiling")
    p.add_argument("--out", default="/tmp/step_hlo.txt",
                   help="where the full HLO text is written when compiling")
    p.add_argument("--world-size", type=int, default=None,
                   help="device count for iota replica groups "
                        "(default: jax.device_count() when compiling)")
    p.add_argument("--json", action="store_true")
    p.add_argument("--check", action="store_true")
    p.add_argument("--expect", default=None,
                   help="comma-separated allowed collective kinds")
    p.add_argument("--max-bytes", type=int, default=None)
    p.add_argument("--fusions", action="store_true")
    return p.parse_args(argv)


def _fusion_families(txt):
    """One representative instruction per named-fusion family."""
    fams = {}
    for m in re.finditer(
        r"^\s*%?((?:[a-z_]+)fusion)\.(\d+)\s.*?(?=^\s*%|\Z)",
        txt,
        re.M | re.S,
    ):
        fams.setdefault(m.group(1), m.group(0)[:1500])
    return fams


def main(argv=None):
    args = _parse_args(argv)
    manifest = None
    if args.hlo_file:
        with open(args.hlo_file) as f:
            txt = f.read()
        world_size = args.world_size
    else:
        from trace_step import build_step  # noqa: E402  (same dir)

        import jax

        step, state, batch = build_step(
            args.micro, model_name=args.model,
            seq=args.seq, global_batch=args.global_batch,
        )
        txt = step.lower(state, batch).compile().as_text()
        with open(args.out, "w") as f:
            f.write(txt)
        print(f"HLO written: {args.out} ({len(txt)} bytes)", file=sys.stderr)
        world_size = args.world_size or jax.device_count()
        from pytorch_distributed_training_tpu.comms.mesh import build_mesh

        manifest = train_manifest(build_mesh(), max_bytes=args.max_bytes)
    if args.expect is not None:
        allowed = tuple(k for k in args.expect.split(",") if k)
        for k in allowed:
            if k not in COLLECTIVE_KINDS:
                raise SystemExit(
                    f"--expect: unknown kind {k!r} "
                    f"(must be among {COLLECTIVE_KINDS})"
                )
        manifest = CommManifest(
            "cli-expect", allowed=allowed, max_bytes=args.max_bytes
        )

    collectives = extract_collectives(txt, world_size=world_size)
    summary = summarize_collectives(collectives)
    deviations = manifest.check(summary) if manifest is not None else []

    if args.json:
        print(json.dumps({
            "summary": summary,
            "manifest": manifest.to_record() if manifest else None,
            "deviations": deviations,
            "collectives": [
                {"name": c.name, "kind": c.kind, "dtype": c.dtype,
                 "bytes": c.bytes, "group_size": c.group_size,
                 "line": c.line, "asynchronous": c.asynchronous}
                for c in collectives
            ],
        }, indent=2))
    else:
        print(f"collectives: {summary['count']} "
              f"({summary['total_bytes']} payload B, "
              f"{summary['total_moved_bytes']} moved B, "
              f"~{summary['est_time_s'] * 1e3:.3f} ms)")
        for kind, slot in sorted(summary["by_kind"].items()):
            print(f"  {kind:20s} x{slot['count']:<4d} "
                  f"{slot['bytes']:>12d} B payload  "
                  f"{slot['moved_bytes']:>12d} B moved")
        if manifest is not None:
            verdict = "CONFORMS" if not deviations else "DEVIATES"
            print(f"manifest {manifest.name!r}: {verdict}")
            for d in deviations:
                print(f"  - {d}")
    if args.fusions:
        for fam, body in _fusion_families(txt).items():
            print(f"\n===== {fam} =====\n{body}\n")
    if args.check and deviations:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
