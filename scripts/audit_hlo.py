"""Audit the collective footprint (and fusions) of a compiled train step.

Subsumes the old ``dump_hlo.py``: compiles the production train step (or
reads an existing HLO dump with ``--hlo-file``), writes the full text to
``--out``, and reports every collective the SPMD partitioner inserted —
kind, payload/moved bytes, group sizes, ICI vs DCN split — through
``analysis/spmd/hlo.py``'s extractor and cost model.

Usage:
  python scripts/audit_hlo.py [micro] [--model NAME] [--seq N]
      [--global-batch N]      # compile the production step (trace_step)
  python scripts/audit_hlo.py --hlo-file /tmp/step_hlo.txt
      [--world-size N]        # audit an existing dump, jax-free
  --json                      # machine-readable summary on stdout
  --check                     # exit 1 unless the footprint conforms to
                              # the mesh-derived train manifest
  --expect KINDS              # comma-separated allowed kinds overriding
                              # the mesh-derived manifest (e.g.
                              # --expect all-gather,reduce-scatter)
  --max-bytes N               # payload-bytes ceiling for --check
  --fusions                   # also print one representative instruction
                              # per named-fusion family (dump_hlo's job)
  --serve-tp N                # compile the tensor-parallel serve programs
                              # (paged decode + spec verify, tiny LM) over
                              # an N-way model-axis mesh and audit each
                              # against serve_tp_manifest; same --json /
                              # --check contract as the train-step audit
  --int8                      # with --serve-tp: build the int8 variant
                              # (weight-only int8 matmuls + int8 KV pages)
                              # so the audit checks the sharded quantized
                              # programs against the dtype-aware manifest
                              # (weight-bytes floor priced at 1 B/elem)
"""

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_distributed_training_tpu.analysis.spmd.hlo import (  # noqa: E402
    COLLECTIVE_KINDS,
    extract_collectives,
    summarize_collectives,
)
from pytorch_distributed_training_tpu.analysis.spmd.manifest import (  # noqa: E402
    CommManifest,
    train_manifest,
)


def _parse_args(argv):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("micro", nargs="?", type=int, default=32)
    p.add_argument("--model", default="bert-large-cased")
    p.add_argument("--seq", type=int, default=None)
    p.add_argument("--global-batch", type=int, default=None)
    p.add_argument("--hlo-file", default=None,
                   help="audit this HLO text instead of compiling")
    p.add_argument("--out", default="/tmp/step_hlo.txt",
                   help="where the full HLO text is written when compiling")
    p.add_argument("--world-size", type=int, default=None,
                   help="device count for iota replica groups "
                        "(default: jax.device_count() when compiling)")
    p.add_argument("--json", action="store_true")
    p.add_argument("--check", action="store_true")
    p.add_argument("--expect", default=None,
                   help="comma-separated allowed collective kinds")
    p.add_argument("--max-bytes", type=int, default=None)
    p.add_argument("--fusions", action="store_true")
    p.add_argument("--serve-tp", type=int, default=None,
                   help="audit the tensor-parallel serve programs over an "
                        "N-way model-axis mesh instead of the train step")
    p.add_argument("--int8", action="store_true",
                   help="with --serve-tp: audit the int8 serve variant "
                        "(weight-only int8 + int8 KV pages)")
    return p.parse_args(argv)


def _fusion_families(txt):
    """One representative instruction per named-fusion family."""
    fams = {}
    for m in re.finditer(
        r"^\s*%?((?:[a-z_]+)fusion)\.(\d+)\s.*?(?=^\s*%|\Z)",
        txt,
        re.M | re.S,
    ):
        fams.setdefault(m.group(1), m.group(0)[:1500])
    return fams


def _serve_tp_audit(args):
    """Compile-and-audit the sharded serve programs standalone.

    Builds the tiny-LM paged serve engine twice (spec off -> hot program
    is ``serve_decode``; spec on -> ``serve_verify``) at ``--serve-tp N``
    with warmup on, which compiles each hot program under the tensor-
    parallel mesh and runs the production compile-time comm audit against
    ``serve_tp_manifest``. The audit records ARE the report — the same
    code path a serving replica runs, not a re-implementation."""
    tp = args.serve_tp
    # the audit must REPORT deviations (and let --check set the exit
    # code), not die on the strict guard's first violation
    os.environ["PDT_TPU_GUARDS"] = "record"
    if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={tp}"
            ).strip()

    import jax
    import jax.numpy as jnp

    from pytorch_distributed_training_tpu.models.gpt2 import GPT2LMModel
    from pytorch_distributed_training_tpu.serve import (
        EngineConfig,
        InferenceServer,
    )
    from pytorch_distributed_training_tpu.telemetry.registry import (
        MetricsRegistry,
    )
    from pytorch_distributed_training_tpu.utils.config import model_preset

    if jax.device_count() < tp:
        raise SystemExit(
            f"--serve-tp {tp} needs {tp} devices, have "
            f"{jax.device_count()} (on CPU set JAX_PLATFORMS=cpu so the "
            f"script can force virtual devices)"
        )

    class _Sink:
        def __init__(self):
            self.records = []

        def emit(self, record):
            self.records.append(record)

    mcfg = model_preset(
        "gpt2-tiny", compute_dtype="float32", attention_impl="reference",
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    model = GPT2LMModel(mcfg)
    params = model.init(
        jax.random.key(0), jnp.ones((1, 8), jnp.int32)
    )["params"]

    dtype_kw = (
        {"weights_dtype": "int8", "kv_dtype": "int8"} if args.int8 else {}
    )
    audits = []
    for spec_k in (0, 3):
        registry = MetricsRegistry()
        sink = _Sink()
        registry.attach_sink(sink)
        # construction alone compiles + audits: warmup=True runs every
        # bucket and the hot decode/verify program before any request
        InferenceServer(
            model, params,
            EngineConfig(
                num_slots=2, prompt_buckets=(8,), max_new_tokens=8,
                kv_layout="paged", sampling="device", page_size=4,
                spec_k=spec_k, warmup=True, tp=tp, **dtype_kw,
            ),
            queue_depth=2, registry=registry,
        )
        audits += [
            r for r in sink.records if r.get("record") == "comm_audit"
        ]

    ok = bool(audits) and all(a["ok"] for a in audits)
    if args.json:
        print(json.dumps({"serve_tp": tp, "int8": bool(args.int8),
                          "ok": ok, "audits": audits},
                         indent=2, default=str))
    else:
        for a in audits:
            print(f"{a['name']}: "
                  f"{sum(s['count'] for s in a['by_kind'].values())} "
                  f"collectives ({a['total_bytes']} payload B, "
                  f"{a['total_moved_bytes']} moved B)")
            for kind, slot in sorted(a["by_kind"].items()):
                print(f"  {kind:20s} x{slot['count']:<4d} "
                      f"{slot['bytes']:>12d} B payload  "
                      f"{slot['moved_bytes']:>12d} B moved")
            verdict = "CONFORMS" if a["ok"] else "DEVIATES"
            print(f"manifest {a['manifest']!r}: {verdict}")
            for d in a.get("deviations", ()):
                print(f"  - {d}")
        if not audits:
            print("no comm_audit records emitted (unexpected)")
    if args.check and not ok:
        return 1
    return 0


def main(argv=None):
    args = _parse_args(argv)
    if args.serve_tp:
        return _serve_tp_audit(args)
    manifest = None
    if args.hlo_file:
        with open(args.hlo_file) as f:
            txt = f.read()
        world_size = args.world_size
    else:
        from trace_step import build_step  # noqa: E402  (same dir)

        import jax

        step, state, batch = build_step(
            args.micro, model_name=args.model,
            seq=args.seq, global_batch=args.global_batch,
        )
        txt = step.lower(state, batch).compile().as_text()
        with open(args.out, "w") as f:
            f.write(txt)
        print(f"HLO written: {args.out} ({len(txt)} bytes)", file=sys.stderr)
        world_size = args.world_size or jax.device_count()
        from pytorch_distributed_training_tpu.comms.mesh import build_mesh

        manifest = train_manifest(build_mesh(), max_bytes=args.max_bytes)
    if args.expect is not None:
        allowed = tuple(k for k in args.expect.split(",") if k)
        for k in allowed:
            if k not in COLLECTIVE_KINDS:
                raise SystemExit(
                    f"--expect: unknown kind {k!r} "
                    f"(must be among {COLLECTIVE_KINDS})"
                )
        manifest = CommManifest(
            "cli-expect", allowed=allowed, max_bytes=args.max_bytes
        )

    collectives = extract_collectives(txt, world_size=world_size)
    summary = summarize_collectives(collectives)
    deviations = manifest.check(summary) if manifest is not None else []

    if args.json:
        print(json.dumps({
            "summary": summary,
            "manifest": manifest.to_record() if manifest else None,
            "deviations": deviations,
            "collectives": [
                {"name": c.name, "kind": c.kind, "dtype": c.dtype,
                 "bytes": c.bytes, "group_size": c.group_size,
                 "line": c.line, "asynchronous": c.asynchronous}
                for c in collectives
            ],
        }, indent=2))
    else:
        print(f"collectives: {summary['count']} "
              f"({summary['total_bytes']} payload B, "
              f"{summary['total_moved_bytes']} moved B, "
              f"~{summary['est_time_s'] * 1e3:.3f} ms)")
        for kind, slot in sorted(summary["by_kind"].items()):
            print(f"  {kind:20s} x{slot['count']:<4d} "
                  f"{slot['bytes']:>12d} B payload  "
                  f"{slot['moved_bytes']:>12d} B moved")
        if manifest is not None:
            verdict = "CONFORMS" if not deviations else "DEVIATES"
            print(f"manifest {manifest.name!r}: {verdict}")
            for d in deviations:
                print(f"  - {d}")
    if args.fusions:
        for fam, body in _fusion_families(txt).items():
            print(f"\n===== {fam} =====\n{body}\n")
    if args.check and deviations:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
