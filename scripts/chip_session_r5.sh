#!/bin/bash
# Round-5 serialized chip session — run ONLY when nothing else is using
# the CPU or the chip (NOTES.md pitfalls: never overlap chip work).
#
# Wedge discipline: a SIGTERM/SIGKILL to a chip-attached process — from
# pkill OR from `timeout` at expiry — is what wedges the tunnel for
# hours. So every stage is gated on a PROBE first (bench.probe_backend:
# throwaway subprocess, never killed): if the tunnel is wedged, the
# stage is SKIPPED and no doomed chip process is ever spawned. The
# per-stage `timeout -k` bounds that remain are last-resort liveness
# backstops at ~2-4x the expected stage time — if one ever fires, the
# tunnel is already sick and the priority is finishing the session log,
# not protecting an already-lost claim.
#
# Stages (each independent; a failure logs and continues):
#   1. driver-style bench (delayed int8) -> the round's headline number
#   2. missing bf16 seed-43 default-schedule gate cell (VERDICT #7)
#   3. RoBERTa/MNLI recipe artifacts with the learnable task (VERDICT #3)
#   4. on-TPU test tier (the r4/r5 kernel set has never run on the chip)
#   5. gpt2-medium flash fused-vs-two-pass backward A/B (VERDICT #5)
#   6. xprof trace of the delayed-int8 step (VERDICT #2)
#   7. 6-epoch tuned MNLI artifact (longest, lowest priority)
set -u
cd /root/repo
LOG=/tmp/chip_session_r5.log
exec > >(tee -a "$LOG") 2>&1
echo "=== chip session start: $(date -u +%FT%TZ)"

probe_ok() {
  python - <<'EOF'
import sys, bench
r = bench.probe_backend(budget_s=180, poll_s=5)
print("probe:", r.get("ok"), r.get("cause", ""))
sys.exit(0 if r.get("ok") else 1)
EOF
}

# The driver runs its own bench.py + dryrun at round end (~12:24Z for
# this round) and MUST find the chip free — a stage still holding the
# claim then would cost the round its driver-verified number exactly the
# way round 4 lost it. No stage starts unless its full bound fits before
# the deadline.
DEADLINE=${CHIP_DEADLINE_EPOCH:-1785584700}  # 2026-08-01T11:45Z

run() {
  local name=$1 tmo=$2; shift 2
  if [ $(( $(date +%s) + tmo )) -gt "$DEADLINE" ]; then
    echo "--- [$name] SKIPPED: bound ${tmo}s does not fit before the"\
         "driver-bench deadline ($(date -u +%T) now)"
    return 1
  fi
  if ! probe_ok; then
    echo "--- [$name] SKIPPED: tunnel probe failed at $(date -u +%T)"
    return 1
  fi
  echo "--- [$name] $(date -u +%T) bound=${tmo}s: $*"
  timeout -k 60 "$tmo" "$@"
  echo "--- [$name] rc=$? $(date -u +%T)"
}

# Stage order is unique-value-per-minute: if the tunnel recovers late in
# the round, the artifacts only this session can produce must land first
# (the driver re-runs bench.py itself at round end either way, but a
# builder-verified number + HISTORY files + the tier note have no other
# source).

# 1. headline bench (~10 min; also validates the whole int8 path quickly)
run bench 2400 python bench.py

# 2. bf16 seed-43 default-schedule cell (completes the 6v6 gate matrix)
run gate-cell 3600 python -m pytorch_distributed_training_tpu.cli.train_dp \
  --model bert-large-cased --task synthetic --seed 43 \
  --history-out HISTORY_bert_large_recipe_seed43.json

# 3. MNLI recipe artifacts (type-id-free cue; replaces the at-chance ones)
run mnli 5400 python -m pytorch_distributed_training_tpu.cli.train_dp \
  --model roberta-large --task mnli \
  --history-out HISTORY_roberta_mnli.json
run mnli-w10 5400 python -m pytorch_distributed_training_tpu.cli.train_dp \
  --model roberta-large --task mnli --warmup-steps 10 \
  --history-out HISTORY_roberta_mnli_warmup10.json

# 4. on-TPU test tier (serialized, generous bound, probe-gated)
run tpu-tier 5400 env PDT_TPU_TESTS=1 python -m pytest tests/ -m tpu -q

# 5. gpt2-medium flash backward A/B (fused default vs two-pass)
run gpt2-fused 3600 python scripts/bench_gpt2.py "micro=4"
run gpt2-twopass 3600 env PDT_FLASH_TWO_PASS=1 python scripts/bench_gpt2.py "micro=4"

# 6. delayed-int8 step trace (the shipping bench config)
run trace 2400 env MATMUL=int8_full QUANT_DELAYED=1 python scripts/trace_step.py 24 4

# 7. (lowest priority, longest run — LAST so a slow pass can't starve the
# stages above) 6-epoch tuned MNLI artifact; 10800s keeps the 2-4x margin
run mnli-tuned 10800 python -m pytorch_distributed_training_tpu.cli.train_dp \
  --model roberta-large --task mnli --learning-rate 5e-5 --num-epochs 6 \
  --warmup-steps 10 \
  --history-out HISTORY_roberta_mnli_tuned.json

echo "=== chip session end: $(date -u +%FT%TZ)"
