"""Render request-span traces and the fleet event timeline from a
metrics dir.

The span plane (telemetry/spans.py) writes ``span`` records into the same
JSONL streams everything else uses: the coordinator's ``metrics.jsonl``
holds the router's ``request``/``attempt``/``hedge`` spans, each
``replica-*/metrics.jsonl`` holds that replica's ``serve`` trees. This
tool merges them fleet-side (by trace id — the ``X-Request-Id``) and
prints either:

- **a waterfall** for one trace (``--trace <id>``): the span tree indented
  by parentage, each row with its start offset from the root and its
  duration, plus the span's salient attributes — the "where did THIS
  request spend its time" view; or
- **the fleet timeline** (default): every operational event in the merged
  streams — scale actions, swap rollouts, brownout transitions, flight
  dumps, watchdog stalls, SLO burn emissions — in wall-clock order, with
  a trace inventory footer.

    python scripts/trace_view.py /path/to/metrics_dir
    python scripts/trace_view.py /path/to/metrics_dir --trace <request-id>
    python scripts/trace_view.py /path/to/metrics_dir --traces   # list ids

Offsets in the waterfall use the emit-time wall stamps (``wall_t0``):
within one process they are exact, across processes they are aligned only
as well as the hosts' clocks — good enough to SEE a hedge race, never
used for duration arithmetic (durations come from monotonic bounds).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_distributed_training_tpu.telemetry.spans import (
    spans_by_trace,
    trace_summary,
)

#: fleet-timeline record types worth a row, with the fields shown per type
TIMELINE_RECORDS = {
    "fleet_scale": ("action", "replica", "size", "drain_s"),
    "autoscale_event": ("action", "replica", "mean_queue_depth", "slo_burn"),
    "autoscale_ready": ("replica", "ready_s"),
    "swap_admitted": ("step",),
    "swap_ok": ("step", "load_s"),
    "swap_failed": ("step", "error"),
    "swap_rollback": ("step",),
    "fleet_swap": ("step", "converged", "duration_s"),
    "brownout_transition": ("from", "to", "level"),
    "flight_dump": ("component", "reason", "depth", "dropped"),
    "watchdog_stall": ("name", "stalled_s"),
    "watchdog_abort": ("name",),
    "slo_burn": ("max_burn",),
    "serve_shed": ("tier", "reason"),
}


def load_file(path: str) -> list[dict]:
    """Parse one metrics JSONL file, skipping torn lines (a crashed
    writer's final partial record) rather than failing the view."""
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                print(f"warning: skipping unparseable line: {line[:80]}",
                      file=sys.stderr)
    return records


def load_dir(path: str) -> list[dict]:
    """Merge a metrics dir's streams: the coordinator's ``metrics.jsonl``
    plus every ``replica-*/metrics.jsonl`` under it (the fleet layout
    cli/fleet_lm.py writes). A plain file path loads just that file."""
    if os.path.isfile(path):
        return load_file(path)
    paths = []
    top = os.path.join(path, "metrics.jsonl")
    if os.path.isfile(top):
        paths.append(top)
    paths += sorted(glob.glob(os.path.join(path, "replica-*",
                                           "metrics.jsonl")))
    if not paths:
        raise FileNotFoundError(f"no metrics.jsonl under {path}")
    records = []
    for p in paths:
        records += load_file(p)
    return records


# ------------------------------------------------------------- waterfall


def _fmt_ms(v) -> str:
    return "-" if v is None else f"{v * 1e3:.1f}ms"


def _attr_line(span: dict) -> str:
    attrs = span.get("attrs") or {}
    keep = [
        (k, attrs[k]) for k in sorted(attrs)
        if attrs[k] is not None and attrs[k] != ""
    ]
    return " ".join(f"{k}={v}" for k, v in keep)


def render_waterfall(records, trace_id: str) -> str:
    """The span tree for one trace, children indented under parents and
    ordered by wall start; orphans (parents outside the merged streams)
    surface under their own heading instead of vanishing."""
    spans = spans_by_trace(records).get(str(trace_id))
    if not spans:
        return f"trace {trace_id}: no spans found"
    verdict = trace_summary(spans)
    by_id = {s.get("span"): s for s in spans}
    children: dict = {}
    roots, orphans = [], []
    for s in spans:
        parent = s.get("parent")
        if not parent:
            roots.append(s)
        elif parent in by_id:
            children.setdefault(parent, []).append(s)
        else:
            orphans.append(s)

    def start_key(s: dict):
        return (s.get("wall_t0") or 0.0, s.get("t0_s") or 0.0)

    base = min((start_key(s)[0] for s in spans), default=0.0)
    lines = [
        f"trace {trace_id}: {verdict['spans']} span(s), "
        + ("complete" if verdict["complete"] else
           f"INCOMPLETE (roots={verdict['roots']} "
           f"orphans={verdict['orphans']} open={verdict['open']})")
        + (f", phases {'ok' if verdict['phase_sum_ok'] else 'DIVERGE'}"
           f" ({_fmt_ms(verdict['phase_sum_s'])}"
           f" of {_fmt_ms(verdict['serve_dur_s'])} serve)"
           if verdict["phase_sum_ok"] is not None else ""),
    ]

    def walk(span: dict, depth: int) -> None:
        offset = (span.get("wall_t0") or base) - base
        name = "  " * depth + span.get("name", "?")
        attrs = _attr_line(span)
        lines.append(
            f"  {name:<24} {span.get('component') or '-':<12} "
            f"+{offset * 1e3:8.1f}ms  {_fmt_ms(span.get('dur_s')):>10}"
            + (f"  {attrs}" if attrs else "")
        )
        for child in sorted(children.get(span.get("span"), []),
                            key=start_key):
            walk(child, depth + 1)

    for root in sorted(roots, key=start_key):
        walk(root, 0)
    if orphans:
        lines.append("  orphans (parent span not in merged streams):")
        for s in sorted(orphans, key=start_key):
            attrs = _attr_line(s)
            lines.append(
                f"    {s.get('name', '?'):<22} "
                f"{s.get('component') or '-':<12} "
                f"parent={s.get('parent')}  {_fmt_ms(s.get('dur_s')):>10}"
                + (f"  {attrs}" if attrs else "")
            )
    return "\n".join(lines)


# -------------------------------------------------------- fleet timeline


def render_timeline(records) -> str:
    """Operational events across the merged streams in sink-timestamp
    order, plus a trace inventory footer (how many traces the streams
    hold and how many merge into complete trees)."""
    events = [
        r for r in records if r.get("record") in TIMELINE_RECORDS
    ]
    events.sort(key=lambda r: r.get("ts") or 0.0)
    t0 = next((r["ts"] for r in events if r.get("ts") is not None), None)
    lines = ["fleet timeline:"]
    if not events:
        lines.append("  (no operational events in stream)")
    for r in events:
        at = (f"+{r['ts'] - t0:8.1f}s"
              if t0 is not None and r.get("ts") is not None else "        ?")
        detail = " ".join(
            f"{k}={r[k]}" for k in TIMELINE_RECORDS[r["record"]]
            if r.get(k) is not None
        )
        lines.append(f"  {at}  {r['record']:<19} {detail}")

    traces = spans_by_trace(records)
    if traces:
        complete = sum(
            1 for s in traces.values() if trace_summary(s)["complete"]
        )
        lines.append(
            f"traces: {len(traces)} ({complete} complete) — "
            f"re-run with --trace <id> for a waterfall"
        )
    return "\n".join(lines)


def render_trace_list(records) -> str:
    """One row per trace: id, span count, completeness, root duration."""
    traces = spans_by_trace(records)
    if not traces:
        return "no span records in stream"
    lines = ["traces:"]
    for trace in sorted(traces):
        v = trace_summary(traces[trace])
        lines.append(
            f"  {trace:<36} spans={v['spans']:<3} "
            f"{'complete' if v['complete'] else 'INCOMPLETE':<10} "
            f"root={v['root_name'] or '?'} "
            f"dur={_fmt_ms(v['root_dur_s'])}"
        )
    return "\n".join(lines)


def main(argv=None) -> str:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("path", help="metrics dir (fleet layout) or one "
                               "metrics.jsonl")
    p.add_argument("--trace", help="render the waterfall for this trace "
                                   "id (the request's X-Request-Id)")
    p.add_argument("--traces", action="store_true",
                   help="list every trace id in the merged streams")
    args = p.parse_args(argv)
    records = load_dir(args.path)
    if args.trace:
        out = render_waterfall(records, args.trace)
    elif args.traces:
        out = render_trace_list(records)
    else:
        out = render_timeline(records)
    print(out)
    return out


if __name__ == "__main__":
    main()
