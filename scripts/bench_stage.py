"""Stage-axis measurement (VERDICT r1 #3): does GSPMD layer-sharding over
the `stage` axis pipeline, or serialize?

Runs the scan-stacked trunk on the 8-device CPU mesh in two shapes with
the SAME chip count: pure DP (data=8) vs DP x stage (data=4, stage=2).
Equal per-sample math => equal step time IF stages overlapped; stage time
~2x DP time means devices holding other stages idle (no schedule).

CPU-mesh wall clock is noisy but the serialization signal is ~2x.
"""

import os
import sys
import time

os.environ.pop("PALLAS_AXON_POOL_IPS", None)
os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

jax.config.update("jax_platforms", "cpu")
import jax._src.xla_bridge as _xb

_xb._clear_backends()

import jax.numpy as jnp
import numpy as np

from pytorch_distributed_training_tpu.comms.ingest import make_global_batch
from pytorch_distributed_training_tpu.comms.mesh import (
    TRAIN_BATCH_PSPEC,
    build_mesh,
)
from pytorch_distributed_training_tpu.models import BertForSequenceClassification
from pytorch_distributed_training_tpu.parallel import (
    ShardingPolicy,
    state_shardings,
)
from pytorch_distributed_training_tpu.parallel.sharding import shard_state
from pytorch_distributed_training_tpu.train.optim import adamw_with_schedule
from pytorch_distributed_training_tpu.train.state import create_train_state
from pytorch_distributed_training_tpu.train.step import make_train_step
from pytorch_distributed_training_tpu.utils.config import (
    MeshConfig,
    TrainConfig,
    model_preset,
)

GLOBAL, MICRO, SEQ, ITERS = 64, 16, 128, 2


def run(name, mesh_cfg, policy):
    mesh = build_mesh(mesh_cfg)
    mcfg = model_preset(
        "tiny", compute_dtype="float32", scan_layers=True,
        hidden_dropout=0.0, attention_dropout=0.0,
        hidden_size=256, num_layers=8, num_heads=4, intermediate_size=1024,
        vocab_size=8192,
    )
    model = BertForSequenceClassification(mcfg)
    tcfg = TrainConfig(global_batch_size=GLOBAL, micro_batch_size=MICRO)
    tx, _ = adamw_with_schedule(tcfg, 100)
    example = {
        "input_ids": jnp.ones((2, SEQ), jnp.int32),
        "attention_mask": jnp.ones((2, SEQ), jnp.int32),
        "token_type_ids": jnp.zeros((2, SEQ), jnp.int32),
    }
    state = create_train_state(model, tx, jax.random.key(0), example)
    shardings = state_shardings(state, policy, mesh)
    state = shard_state(state, shardings)
    step = make_train_step(
        grad_accum_steps=tcfg.grad_accum_steps, mesh=mesh,
        state_shardings=shardings,
    )
    rng = np.random.default_rng(0)
    accum = tcfg.grad_accum_steps
    b = {
        "input_ids": rng.integers(0, 8192, (accum, MICRO, SEQ)).astype(np.int32),
        "attention_mask": np.ones((accum, MICRO, SEQ), np.int32),
        "token_type_ids": np.zeros((accum, MICRO, SEQ), np.int32),
        "labels": rng.integers(0, 2, (accum, MICRO)).astype(np.int32),
    }
    batch = make_global_batch(mesh, b, pspec=TRAIN_BATCH_PSPEC)
    state, m = step(state, batch)
    jax.block_until_ready(state.params)
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            state, m = step(state, batch)
        float(jax.device_get(m["loss"]))
        best = min(best, (time.perf_counter() - t0) / ITERS)
    print(f"{name:32s} {best*1e3:9.1f} ms/step", flush=True)
    return best





def run_gpipe(name, mesh_cfg, n_micro=8):
    """Trunk-only fwd+bwd: GPipe schedule vs the same-chip DP trunk."""
    import dataclasses

    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytorch_distributed_training_tpu.ops.attention import (
        make_attention_bias,
    )
    from pytorch_distributed_training_tpu.parallel.pipeline import (
        gpipe_apply,
        gpipe_trunk_fn,
    )

    mesh = build_mesh(mesh_cfg)
    mcfg = model_preset(
        "tiny", compute_dtype="float32", scan_layers=True,
        hidden_dropout=0.0, attention_dropout=0.0,
        hidden_size=256, num_layers=8, num_heads=4, intermediate_size=1024,
        vocab_size=8192,
    )
    model = BertForSequenceClassification(mcfg)
    ids = jnp.ones((4, SEQ), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    stacked = params["bert"]["layers_scan"]["layer"]
    rng = np.random.default_rng(0)
    mb = GLOBAL // n_micro
    xs = jnp.asarray(
        rng.normal(size=(n_micro, mb, SEQ, mcfg.hidden_size)), jnp.float32
    )
    biases = jnp.zeros((n_micro, mb, 1, 1, SEQ), jnp.float32)
    layer_fn = gpipe_trunk_fn(mcfg)
    n_stages = mesh.shape["stage"]
    stream = P(None, ("data", "fsdp"))

    if n_stages > 1:
        def loss(p, x):
            return jnp.sum(
                gpipe_apply(mesh, layer_fn, p, x, biases,
                            stream_spec=stream)
            )
    else:
        # DP baseline: the same total work as one flat batch, rows
        # sharded over all 8 devices (no microbatch split needed)
        xs = xs.reshape(GLOBAL, SEQ, mcfg.hidden_size)
        biases = jnp.zeros((GLOBAL, 1, 1, SEQ), jnp.float32)
        stream = P(("data", "fsdp"))

        def loss(p, x):
            def body(h, lp):
                return layer_fn(lp, h, biases), None

            out, _ = jax.lax.scan(body, x, p)
            return jnp.sum(out)

    stacked_sh = jax.device_put(
        stacked,
        jax.tree.map(
            lambda _: NamedSharding(
                mesh, P("stage") if n_stages > 1 else P()
            ),
            stacked,
        ),
    )
    xs_sh = jax.device_put(xs, NamedSharding(mesh, stream))
    g = jax.jit(jax.grad(loss, argnums=(0, 1)))
    o = g(stacked_sh, xs_sh)
    jax.block_until_ready(o)
    best = float("inf")
    for _ in range(2):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            o = g(stacked_sh, xs_sh)
        jax.block_until_ready(o)
        best = min(best, (time.perf_counter() - t0) / ITERS)
    print(f"{name:32s} {best*1e3:9.1f} ms/step", flush=True)
    return best


if __name__ == "__main__":
    import sys as _sys

    if "--gpipe" in _sys.argv:
        t_dp = run_gpipe("trunk dp8 (data=8)", MeshConfig(data=8))
        t_g2 = run_gpipe("gpipe stage2 (data=4, stage=2)",
                         MeshConfig(data=4, stage=2))
        t_g4 = run_gpipe("gpipe stage4 (data=2, stage=4)",
                         MeshConfig(data=2, stage=4))
        print(f"gpipe2/dp8 = {t_g2 / t_dp:.2f}x   "
              f"gpipe4/dp8 = {t_g4 / t_dp:.2f}x")
    else:
        t_dp = run("dp8 (data=8)", MeshConfig(data=8), ShardingPolicy())
        t_s2 = run("stage2 (data=4, stage=2)", MeshConfig(data=4, stage=2),
                   ShardingPolicy(stage=True))
        t_s4 = run("stage4 (data=2, stage=4)", MeshConfig(data=2, stage=4),
                   ShardingPolicy(stage=True))
        print(f"stage2/dp8 = {t_s2 / t_dp:.2f}x   stage4/dp8 = {t_s4 / t_dp:.2f}x")
