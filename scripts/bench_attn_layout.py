"""Microbench: attention-block layouts on the real chip.

Compares, for one attention block (q/k/v proj -> attention -> out proj)
under grad, bert-large geometry:

  A. baseline:  DenseGeneral [B,S,N,D] + reference einsum attention
  B. flash-cur: DenseGeneral [B,S,N,D] + flash adapter (boundary transposes)
  C. flash-hm:  head-major einsum projections [B,N,S,D] + flash (no
                adapter transposes); out-proj consumes [B,N,S,D]

Timing per NOTES.md axon rules: chain iterations (x = f(x)) and end with a
device_get of a scalar.
"""

import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_training_tpu.ops.attention import reference_attention
from pytorch_distributed_training_tpu.ops.flash_attention import (
    flash_attention_base,
)

B, S, H, N, D = 32, 128, 1024, 16, 64
DROPOUT = 0.1
ITERS = 50


def init_params(key):
    k1, k2, k3, k4 = jax.random.split(key, 4)
    scale = 0.02
    return {
        "wq": (jax.random.normal(k1, (H, N, D), jnp.float32) * scale).astype(jnp.bfloat16),
        "wk": (jax.random.normal(k2, (H, N, D), jnp.float32) * scale).astype(jnp.bfloat16),
        "wv": (jax.random.normal(k3, (H, N, D), jnp.float32) * scale).astype(jnp.bfloat16),
        "wo": (jax.random.normal(k4, (N, D, H), jnp.float32) * scale).astype(jnp.bfloat16),
    }


def block_bsnd(params, x, bias, seed, impl, dropout):
    q = jnp.einsum("bsh,hnd->bsnd", x, params["wq"])
    k = jnp.einsum("bsh,hnd->bsnd", x, params["wk"])
    v = jnp.einsum("bsh,hnd->bsnd", x, params["wv"])
    if impl == "reference":
        rng = jax.random.wrap_key_data(
            jnp.array([[seed[0].astype(jnp.uint32), 0, 0, 0]], jnp.uint32)[0],
            impl="rbg",
        )
        o = reference_attention(
            q, k, v, bias, dropout_rng=rng, dropout_rate=dropout,
            deterministic=dropout == 0.0,
        )
    else:
        o = flash_attention_base(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), bias, seed, dropout_rate=dropout,
        ).transpose(0, 2, 1, 3)
    return jnp.einsum("bsnd,ndh->bsh", o, params["wo"])


def block_bnsd(params, x, bias, seed, dropout):
    q = jnp.einsum("bsh,hnd->bnsd", x, params["wq"])
    k = jnp.einsum("bsh,hnd->bnsd", x, params["wk"])
    v = jnp.einsum("bsh,hnd->bnsd", x, params["wv"])
    o = flash_attention_base(q, k, v, bias, seed, dropout_rate=dropout)
    return jnp.einsum("bnsd,ndh->bsh", o, params["wo"])


def make_step(fn):
    def loss_fn(params, x, bias, seed):
        out = fn(params, x, bias, seed)
        return jnp.sum(out.astype(jnp.float32) ** 2), out

    @jax.jit
    def step(params, x, bias, seed):
        (l, out), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            params, x, bias, seed
        )
        # chain: feed the block output back in (keeps the device busy)
        nxt = (x + out * 1e-6).astype(x.dtype)
        return nxt, l, grads

    return step


def bench(name, fn, batch):
    step = make_step(fn)
    key = jax.random.key(0)
    params = init_params(key)
    x = jax.random.normal(key, (batch, S, H), jnp.bfloat16)
    bias = jnp.zeros((batch, 1, 1, S), jnp.float32)
    seed = jnp.array([123], jnp.int32)
    x, l, g = step(params, x, bias, seed)  # compile
    jax.block_until_ready(l)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            x, l, g = step(params, x, bias, seed)
        _ = float(jax.device_get(l))
        best = min(best, (time.perf_counter() - t0) / ITERS * 1e3)
    print(f"{name:32s} {best:7.3f} ms/iter", flush=True)
    return best


if __name__ == "__main__":
    print(f"backend={jax.default_backend()} S={S} N={N} D={D}")
    for batch in (32, 96):
        for dropout in (0.0, DROPOUT):
            print(f"--- batch={batch} dropout={dropout}")
            bench("A reference bsnd", functools.partial(
                block_bsnd, impl="reference", dropout=dropout), batch)
            bench("B flash adapter (transposes)", functools.partial(
                block_bsnd, impl="flash", dropout=dropout), batch)
            bench("C flash head-major", functools.partial(
                block_bnsd, dropout=dropout), batch)
