"""Dump the optimized HLO of the production train step and summarize the
named fusions the trace flags as hot (convert_reduce / multiply_reduce /
bitcast_add families), so trace time can be attributed to actual HLO.

Usage: python scripts/dump_hlo.py [micro]
Writes full text to /tmp/step_hlo.txt.
"""

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from trace_step import build_step  # noqa: E402  (same dir)


def main():
    micro = int(sys.argv[1]) if len(sys.argv) > 1 else 32
    step, state, batch = build_step(micro)
    txt = step.lower(state, batch).compile().as_text()
    with open("/tmp/step_hlo.txt", "w") as f:
        f.write(txt)
    print(f"HLO written: /tmp/step_hlo.txt ({len(txt)} bytes)")
    # print ONE representative instruction of each fusion family
    fams = {}
    for m in re.finditer(
        r"^\s*%?((?:[a-z_]+)fusion)\.(\d+)\s.*?(?=^\s*%|\Z)",
        txt,
        re.M | re.S,
    ):
        fam = m.group(1)
        if fam not in fams:
            fams[fam] = m.group(0)[:1500]
    for fam, body in fams.items():
        print(f"\n===== {fam} =====\n{body}\n")


if __name__ == "__main__":
    main()
