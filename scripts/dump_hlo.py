"""Dump the optimized HLO of the production train step and summarize the
named fusions the trace flags as hot (convert_reduce / multiply_reduce /
bitcast_add families), so trace time can be attributed to actual HLO.

Usage: python scripts/dump_hlo.py [micro] [family_regex]
Writes full text to /tmp/step_hlo.txt.
"""

import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from trace_step import build_step  # noqa: E402  (same dir)


def main():
    micro = int(sys.argv[1]) if len(sys.argv) > 1 else 32
    pat = sys.argv[2] if len(sys.argv) > 2 else r"(convert_reduce_fusion|multiply_reduce_fusion|bitcast_add_fusion|convolution_add_fusion)\.\d+"
    step, state, batch = build_step(micro)
    txt = step.lower(state, batch).compile().as_text()
    with open("/tmp/step_hlo.txt", "w") as f:
        f.write(txt)
    print(f"HLO written: /tmp/step_hlo.txt ({len(txt)} bytes)")
    # print the computation body for ONE representative of each family
    seen = set()
    for m in re.finditer(r"%?([a-z_]+fusion)[.\d]*", txt):
        pass
    # find fusion definitions: lines like "%convert_reduce_fusion.293 (...) -> ... {"
    fams = {}
    for m in re.finditer(
        r"^\s*%?((?:[a-z_]+)fusion)\.(\d+)\s.*?(?=^\s*%|\Z)",
        txt,
        re.M | re.S,
    ):
        fam = m.group(1)
        if fam not in fams:
            fams[fam] = m.group(0)[:1500]
    for fam, body in fams.items():
        print(f"\n===== {fam} =====\n{body}\n")


if __name__ == "__main__":
    main()
