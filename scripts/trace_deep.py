"""Deep-dive an existing xprof trace (default /tmp/xprof_step): split device
time by hlo_category, and within each category print the top op groups
(deduplicated fusions collapsed) with total ms/step, exec count, achieved
bytes/s, and an output-shape snippet from long_name. Pinpoints which fusions
the generic "fusion" bucket of trace_step.py is spending time in.

Usage: python scripts/trace_deep.py [tracedir] [steps]
"""

import collections
import glob
import gzip
import json
import re
import sys


def main():
    tracedir = sys.argv[1] if len(sys.argv) > 1 else "/tmp/xprof_step"
    steps = int(sys.argv[2]) if len(sys.argv) > 2 else 3
    paths = glob.glob(tracedir + "/**/*.trace.json.gz", recursive=True)
    assert paths, f"no trace under {tracedir}"
    with gzip.open(paths[0], "rt") as f:
        trace = json.load(f)
    events = trace["traceEvents"]
    device_pids = {
        e["pid"]
        for e in events
        if e.get("ph") == "M"
        and e.get("name") == "process_name"
        and "TPU" in str(e.get("args", {}).get("name", ""))
    }
    op_tids = {
        (e["pid"], e["tid"])
        for e in events
        if e.get("ph") == "M"
        and e.get("name") == "thread_name"
        and e["pid"] in device_pids
        and "XLA Ops" in str(e.get("args", {}).get("name", ""))
    }

    cat_ms = collections.Counter()
    group_ms = collections.Counter()
    group_n = collections.Counter()
    group_bytes = collections.Counter()
    group_shape = {}
    group_cat = {}
    for e in events:
        if e.get("ph") != "X" or (e.get("pid"), e.get("tid")) not in op_tids:
            continue
        args = e.get("args", {})
        cat = args.get("hlo_category", "?")
        dur = e.get("dur", 0) / 1e3 / steps  # ms/step
        cat_ms[cat] += dur
        # group key: deduplicated fusion name if present, else the op name
        # with trailing indices stripped
        key = args.get("deduplicated_name") or re.sub(
            r"[.\d]+$", "", e.get("name", "?")
        ) or e.get("name")
        key = f"{cat}|{key}"
        group_ms[key] += dur
        group_n[key] += 1
        group_bytes[key] += int(args.get("bytes_accessed", 0) or 0)
        group_cat[key] = cat
        if key not in group_shape:
            ln = args.get("long_name", "")
            m = re.search(r"=\s*(\([^)]*\)|\S+)", ln)
            group_shape[key] = (m.group(1) if m else ln)[:90]

    total = sum(cat_ms.values())
    print(f"device time {total:.1f} ms/step, by hlo_category:")
    for c, ms in cat_ms.most_common():
        print(f"  {c:28s} {ms:8.2f} ms")
    print("\ntop 45 op groups (ms/step, n/step, GB/s achieved):")
    for key, ms in group_ms.most_common(45):
        n = group_n[key] // steps
        gbs = (group_bytes[key] / steps / 1e9) / (ms / 1e3) if ms else 0
        print(
            f"  {ms:7.2f} ms x{n:<5d} {gbs:7.0f} GB/s "
            f"[{group_cat[key][:14]:14s}] {group_shape[key]}"
        )


if __name__ == "__main__":
    main()
