#!/usr/bin/env python
"""Offline checkpoint validator — the documented pre-resume/publish gate.

Walks every step under a checkpoint directory and verifies each against its
integrity manifest (pytorch_distributed_training_tpu/train/manifest.py):
file inventory by byte size, and with ``--strict`` a full sha256 re-hash
that catches same-size corruption. Run it before resuming a long job on a
directory you didn't just write (a copied/restored/aged one), or as the CI
gate an external publisher runs before a step may enter a serving fleet's
hot-swap rotation:

    python scripts/verify_checkpoint.py /ckpts/run17 --strict
    python scripts/verify_checkpoint.py /ckpts/run17 --strict --json

``--json`` prints one machine-readable report (per-step verdict + reason,
the per-file sha256 digests each manifest records, and the step a restore
or hot-swap watcher would actually use) instead of the table.

Exit codes (distinct, so scripts can gate without parsing):
  0 — every step verified (what a resume/swap will use is trustworthy);
  2 — some steps failed but a verified step exists (resume/hot-swap will
      FALL BACK to the newest verified step — decide if that is OK);
  3 — corrupt: steps exist but NONE verifies (resume would need
      --checkpoint-verify off at your own risk; a swap watcher admits
      nothing);
  4 — missing: the directory doesn't exist, holds no checkpoint, or the
      requested --step is absent.

Runs with JAX_PLATFORMS=cpu-safe imports only — no devices touched.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

EXIT_VERIFIED = 0
EXIT_PARTIAL = 2
EXIT_CORRUPT = 3
EXIT_MISSING = 4


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("directory", help="checkpoint directory (a run's --checkpoint-dir)")
    p.add_argument("--step", type=int, default=None,
                   help="verify only this step (default: every step)")
    p.add_argument("--strict", action="store_true",
                   help="re-hash every file (sha256) instead of size-only — "
                        "catches same-size corruption; costs a full read")
    p.add_argument("--quiet", action="store_true",
                   help="exit code only, no per-step report")
    p.add_argument("--json", action="store_true",
                   help="print one JSON report (per-step verdict + manifest "
                        "digests) instead of the table — for publishers and "
                        "CI gates")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    import orbax.checkpoint as ocp

    from pytorch_distributed_training_tpu.train import manifest

    directory = os.path.abspath(args.directory)
    level = "digest" if args.strict else "size"

    def report_missing(message: str) -> int:
        if args.json:
            print(json.dumps({
                "directory": directory,
                "level": level,
                "verdict": "missing",
                "error": message,
                "steps": [],
            }))
        else:
            print(message, file=sys.stderr)
        return EXIT_MISSING

    if not os.path.isdir(directory):
        return report_missing(f"{directory}: not a directory")
    with ocp.CheckpointManager(directory) as mngr:
        steps = sorted(mngr.all_steps())
        if args.step is not None:
            if args.step not in steps:
                return report_missing(
                    f"step {args.step} not found (have {steps})"
                )
            steps = [args.step]
        results = {}
        for step in steps:
            path = str(
                ocp.step.find_step_path(
                    directory, ocp.step.standard_name_format(), step=step
                )
            )
            ok, reason = manifest.verify_step(path, level=level)
            m = manifest.read_manifest(path) or {}
            results[step] = {
                "step": step,
                "ok": ok,
                "reason": reason,
                # the digests the manifest CLAIMS (what a publisher signs
                # off on) — recomputation is what verify_step just did
                "digests": {
                    rel: info.get("sha256")
                    for rel, info in (m.get("files") or {}).items()
                },
            }
    if not results:
        return report_missing(f"no checkpoint under {directory}")
    verified = [s for s, r in results.items() if r["ok"]]
    newest = max(verified) if verified else None
    if len(verified) == len(results):
        verdict, code = "verified", EXIT_VERIFIED
    elif verified:
        verdict, code = "partial", EXIT_PARTIAL
    else:
        verdict, code = "corrupt", EXIT_CORRUPT
    if args.json:
        print(json.dumps({
            "directory": directory,
            "level": level,
            "verdict": verdict,
            "verified": len(verified),
            "total": len(results),
            "verified_latest": newest,
            "steps": [results[s] for s in sorted(results)],
        }, indent=1))
    elif not args.quiet:
        for step, r in sorted(results.items()):
            print(
                f"step {step:>8}: {'OK' if r['ok'] else 'FAIL'} "
                f"({r['reason']})"
            )
        print(
            f"{len(verified)}/{len(results)} step(s) verified at level "
            f"{level!r}; restore would use: "
            f"{newest if newest is not None else 'NOTHING — no verified step'}"
        )
    return code


if __name__ == "__main__":
    sys.exit(main())
