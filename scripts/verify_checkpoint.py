#!/usr/bin/env python
"""Offline checkpoint validator — the documented pre-resume check.

Walks every step under a checkpoint directory and verifies each against its
integrity manifest (pytorch_distributed_training_tpu/train/manifest.py):
file inventory by byte size, and with ``--strict`` a full sha256 re-hash
that catches same-size corruption. Run it before resuming a long job on a
directory you didn't just write (a copied/restored/aged one):

    python scripts/verify_checkpoint.py /ckpts/run17 --strict

Exit codes:
  0 — every step verified (what a resume will restore is trustworthy);
  2 — some steps failed but a verified step exists (resume will FALL BACK
      to the newest verified step — decide if that is acceptable);
  1 — no step verified (resume would need --checkpoint-verify off, at your
      own risk) or the directory holds no checkpoint.

Runs with JAX_PLATFORMS=cpu-safe imports only — no devices touched.
"""

from __future__ import annotations

import argparse
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("directory", help="checkpoint directory (a run's --checkpoint-dir)")
    p.add_argument("--step", type=int, default=None,
                   help="verify only this step (default: every step)")
    p.add_argument("--strict", action="store_true",
                   help="re-hash every file (sha256) instead of size-only — "
                        "catches same-size corruption; costs a full read")
    p.add_argument("--quiet", action="store_true",
                   help="exit code only, no per-step report")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    import orbax.checkpoint as ocp

    from pytorch_distributed_training_tpu.train import manifest

    directory = os.path.abspath(args.directory)
    if not os.path.isdir(directory):
        print(f"{directory}: not a directory", file=sys.stderr)
        return 1
    level = "digest" if args.strict else "size"
    with ocp.CheckpointManager(directory) as mngr:
        steps = sorted(mngr.all_steps())
        if args.step is not None:
            if args.step not in steps:
                print(f"step {args.step} not found (have {steps})",
                      file=sys.stderr)
                return 1
            steps = [args.step]
        results = {}
        for step in steps:
            path = str(
                ocp.step.find_step_path(
                    directory, ocp.step.standard_name_format(), step=step
                )
            )
            results[step] = manifest.verify_step(path, level=level)
    if not results:
        print(f"no checkpoint under {directory}", file=sys.stderr)
        return 1
    verified = [s for s, (ok, _) in results.items() if ok]
    if not args.quiet:
        for step, (ok, reason) in sorted(results.items()):
            print(f"step {step:>8}: {'OK' if ok else 'FAIL'} ({reason})")
        newest = max(verified) if verified else None
        print(
            f"{len(verified)}/{len(results)} step(s) verified at level "
            f"{level!r}; restore would use: "
            f"{newest if newest is not None else 'NOTHING — no verified step'}"
        )
    if len(verified) == len(results):
        return 0
    return 2 if verified else 1


if __name__ == "__main__":
    sys.exit(main())
