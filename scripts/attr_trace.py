"""Join an xprof trace with the step HLO: classify every fusion by whether
its fused computation contains a dot/convolution, and report true
MXU-fusion vs elementwise-fusion vs other time.

Run scripts/trace_step.py first? No — this script does both: builds the
step, dumps HLO, traces, and prints the joined ledger.
"""

import collections
import glob
import gzip
import json
import os
import re
import shutil
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

from trace_step import build_step, bucket  # noqa: E402


def main():
    micro = int(sys.argv[1]) if len(sys.argv) > 1 else 32
    model_name = sys.argv[2] if len(sys.argv) > 2 else "bert-large-cased"
    seq = int(sys.argv[3]) if len(sys.argv) > 3 else None
    gb = int(sys.argv[4]) if len(sys.argv) > 4 else None
    steps = 3
    step, state, batch = build_step(micro, model_name, seq, gb)
    hlo = step.lower(state, batch).compile().as_text()

    # fusion instruction -> called computation name
    inst_to_comp = {}
    for m in re.finditer(
        r"%([a-zA-Z0-9_.\-]+) = [^\n]*? fusion\([^\n]*?calls=%([a-zA-Z0-9_.\-]+)",
        hlo,
    ):
        inst_to_comp[m.group(1)] = m.group(2)
    # computations containing a dot/conv
    comp_bodies = {}
    for m in re.finditer(
        r"^(?:ENTRY )?%?([a-zA-Z0-9_.\-]+)[^\n]*\{(.*?)^\}", hlo, re.M | re.S
    ):
        comp_bodies[m.group(1)] = m.group(2)
    def has_dot(comp):
        body = comp_bodies.get(comp, "")
        return (" dot(" in body or " convolution(" in body
                or "= dot" in body or "= convolution" in body)

    state, m = step(state, batch)
    jax.block_until_ready(state.params)
    tracedir = "/tmp/xprof_attr"
    shutil.rmtree(tracedir, ignore_errors=True)
    with jax.profiler.trace(tracedir):
        for _ in range(steps):
            state, m = step(state, batch)
        float(jax.device_get(m["loss"]))
    paths = glob.glob(tracedir + "/**/*.trace.json.gz", recursive=True)
    with gzip.open(paths[0], "rt") as f:
        events = json.load(f)["traceEvents"]
    device_pids = {
        e["pid"] for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
        and "TPU" in str(e.get("args", {}).get("name", ""))
    }
    op_tids = {
        (e["pid"], e["tid"]) for e in events
        if e.get("ph") == "M" and e.get("name") == "thread_name"
        and e["pid"] in device_pids
        and "XLA Ops" in str(e.get("args", {}).get("name", ""))
    }
    cats = collections.Counter()
    tops = collections.Counter()
    for e in events:
        if e.get("ph") != "X" or (e.get("pid"), e.get("tid")) not in op_tids:
            continue
        name = e.get("name", "?")
        ms = e.get("dur", 0) / 1e3 / steps
        if "fusion" in name:
            cat = "fusion(MXU)" if has_dot(inst_to_comp.get(name, "")) else \
                "fusion(elementwise)"
        elif name.startswith(("dot", "convolution")):
            cat = "dot(bare)"
        else:
            cat = bucket(name)
        cats[cat] += ms
        tops[(cat, re.sub(r"[.\d]+$", "", name))] += ms
    total = sum(cats.values())
    print(f"\n== micro {micro}: device {total:.1f} ms/step ==")
    for c, ms in cats.most_common():
        print(f"  {c:22s} {ms:8.2f} ms")
    print("\nper (cat, family):")
    for (c, f), ms in tops.most_common(20):
        print(f"  {ms:8.2f} ms  [{c}] {f[:80]}")

    # drill into elementwise fusions: instance -> duration, op_name
    inst_meta = {}
    for m in re.finditer(
        r"%([a-zA-Z0-9_.\-]+) = [^\n]*? fusion\([^\n]*?op_name=\"([^\"]+)\"",
        hlo,
    ):
        inst_meta[m.group(1)] = m.group(2)
    per_inst = collections.Counter()
    for e in events:
        if e.get("ph") != "X" or (e.get("pid"), e.get("tid")) not in op_tids:
            continue
        name = e.get("name", "?")
        if "fusion" in name and not has_dot(inst_to_comp.get(name, "")):
            per_inst[name] += e.get("dur", 0) / 1e3 / steps
    print("\ntop elementwise-fusion instances:")
    for name, ms in per_inst.most_common(15):
        meta = inst_meta.get(name, "?")
        meta = meta.replace("jit(train_step)/", "")[-95:]
        print(f"  {ms:8.3f} ms  {name[:28]:28s} {meta}")


if __name__ == "__main__":
    main()
