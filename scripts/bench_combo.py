"""E2E step-time sweeps on the bert-large MRPC recipe.

run(dropout_impl, accum_dtype, micro, mu_dtype) times one production train
step configuration in-process; edit the combos list at the bottom for the
sweep of interest (the checked-in list re-validates the shipped defaults —
bits32 masks, bf16 carry, bf16 adam m — across micro-batch splits).
"""

import time

import jax
import jax.numpy as jnp

from pytorch_distributed_training_tpu.comms.mesh import build_mesh
from pytorch_distributed_training_tpu.models import BertForSequenceClassification
from pytorch_distributed_training_tpu.parallel import ShardingPolicy, state_shardings
from pytorch_distributed_training_tpu.parallel.sharding import shard_state
from pytorch_distributed_training_tpu.train.optim import adamw_with_schedule
from pytorch_distributed_training_tpu.train.state import create_train_state
from pytorch_distributed_training_tpu.train.step import make_train_step
from pytorch_distributed_training_tpu.utils.config import TrainConfig, model_preset

GLOBAL, SEQ, ITERS = 96, 128, 20


def batch_for(accum, mesh):
    import numpy as np
    from pytorch_distributed_training_tpu.comms.ingest import make_global_batch
    from pytorch_distributed_training_tpu.comms.mesh import TRAIN_BATCH_PSPEC

    rng = np.random.default_rng(0)
    micro = GLOBAL // accum
    b = {
        "input_ids": rng.integers(0, 28996, (accum, micro, SEQ)).astype(np.int32),
        "attention_mask": np.ones((accum, micro, SEQ), np.int32),
        "token_type_ids": np.zeros((accum, micro, SEQ), np.int32),
        "labels": rng.integers(0, 2, (accum, micro)).astype(np.int32),
    }
    return make_global_batch(mesh, b, pspec=TRAIN_BATCH_PSPEC)


def run(dropout_impl, accum_dtype, micro=32, mu_dtype="float32", ln="fused", dropout_rate=None, attn_rate=None, nu_dtype="float32", attn_impl=None, attn_remat=None):
    mesh = build_mesh()
    kw = dict(dropout_impl=dropout_impl, layernorm_impl=ln)
    if dropout_rate is not None:
        kw.update(hidden_dropout=dropout_rate, attention_dropout=dropout_rate)
    if attn_rate is not None:
        kw.update(attention_dropout=attn_rate)
    if attn_impl is not None:
        kw.update(attention_impl=attn_impl)
    if attn_remat is not None:
        kw.update(attention_remat=attn_remat)
    mcfg = model_preset("bert-large-cased", **kw)
    model = BertForSequenceClassification(mcfg)
    tcfg = TrainConfig(global_batch_size=GLOBAL, micro_batch_size=micro,
                       adam_mu_dtype=mu_dtype, adam_nu_dtype=nu_dtype,
                       grad_accum_dtype=accum_dtype)
    tx, _ = adamw_with_schedule(tcfg, total_steps=1000)
    example = {
        "input_ids": jnp.ones((2, SEQ), jnp.int32),
        "attention_mask": jnp.ones((2, SEQ), jnp.int32),
        "token_type_ids": jnp.zeros((2, SEQ), jnp.int32),
    }
    state = create_train_state(model, tx, jax.random.key(42, impl="rbg"), example)
    shardings = state_shardings(state, ShardingPolicy(), mesh)
    state = shard_state(state, shardings)
    accum = tcfg.grad_accum_steps
    step = make_train_step(
        grad_accum_steps=accum, mesh=mesh, state_shardings=shardings,
        objective="classification", accum_dtype=accum_dtype,
    )
    batch = batch_for(accum, mesh)
    state, m = step(state, batch)
    jax.block_until_ready(state.params)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            state, m = step(state, batch)
        _ = float(jax.device_get(m["loss"]))
        best = min(best, (time.perf_counter() - t0) / ITERS)
    print(
        f"rate={dropout_rate} attn={attn_rate} impl={attn_impl} micro={micro:3d} mu={mu_dtype:8s} nu={nu_dtype:8s} ln={ln:9s}"
        f"  {best*1e3:7.2f} ms/step  {GLOBAL/best:6.1f} samples/s",
        flush=True,
    )


if __name__ == "__main__":
    import sys

    combos = [
        ("bits32", "bfloat16", 32, "bfloat16"),
        ("bits32", "bfloat16", 48, "bfloat16"),
        ("bits32", "bfloat16", 96, "bfloat16"),
    ]
    for d, a, m, mu in combos:
        run(d, a, m, mu)
