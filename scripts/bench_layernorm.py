"""Microbench: Pallas fused LN vs XLA nn.LayerNorm on the real chip.

Times fwd and fwd+bwd over the bert-large shape ([32*128, 1024]) with
chained iterations + device_get (NOTES.md axon timing rules).
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from pytorch_distributed_training_tpu.ops.layer_norm import (
    layer_norm,
    reference_layer_norm,
)

R, H, ITERS = 32 * 128, 1024, 50


def timed(fn, *args):
    x = fn(*args)
    jax.block_until_ready(x)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        c = args[0]
        for _ in range(ITERS):
            c = fn(c, *args[1:])  # chain
        float(jax.device_get(jnp.sum(c.astype(jnp.float32))))
        best = min(best, (time.perf_counter() - t0) / ITERS)
    return best * 1e3


def main():
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(R, H)), jnp.bfloat16)
    scale = jnp.ones((H,), jnp.float32)
    bias = jnp.zeros((H,), jnp.float32)

    fused_fwd = jax.jit(
        lambda x, s, b: layer_norm(x, s, b, eps=1e-12, out_dtype=jnp.bfloat16)
    )
    ref_fwd = jax.jit(
        lambda x, s, b: reference_layer_norm(
            x, s, b, eps=1e-12, out_dtype=jnp.bfloat16
        )
    )
    print(f"fwd   fused {timed(fused_fwd, x, scale, bias):7.3f} ms   "
          f"ref {timed(ref_fwd, x, scale, bias):7.3f} ms")

    def g(fn):
        def loss(x, s, b):
            return jnp.sum(fn(x, s, b).astype(jnp.float32) ** 2)

        grad = jax.grad(loss)
        return jax.jit(lambda x, s, b: grad(x, s, b).astype(jnp.bfloat16))

    fused_g = g(lambda x, s, b: layer_norm(x, s, b, eps=1e-12,
                                           out_dtype=jnp.bfloat16))
    ref_g = g(lambda x, s, b: reference_layer_norm(x, s, b, eps=1e-12,
                                                   out_dtype=jnp.bfloat16))
    print(f"f+bwd fused {timed(fused_g, x, scale, bias):7.3f} ms   "
          f"ref {timed(ref_g, x, scale, bias):7.3f} ms")


if __name__ == "__main__":
    main()
