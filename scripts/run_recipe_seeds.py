"""Rerun the bert-large recipe (MRPC *shape*: lr 2e-5, 3 epochs, global
batch 96, seq 128 — on the SYNTHETIC stand-in task, since this image has
zero egress and no HF hub) across seeds and precision/schedule variants,
writing HISTORY_bert_large_recipe_seed{N}[{_variant}].json artifacts.

VERDICT r2 #4 used this for the multi-seed collapse diagnosis; VERDICT r3 #1a
extends it to the int8 convergence gate: the A/B protocol is one bf16 run and
one int8 run at the SAME seed on the SAME schedule, compared epoch by epoch.

Usage:
    python scripts/run_recipe_seeds.py [--seeds 42 43 44]
        [--matmul-impl native|int8|int8_full] [--quant-delayed]
        [--warmup-steps N] [--suffix tag]

The artifact name encodes the variant: seed{N}[_int8full][_delayed][_warmup{W}]
(or an explicit --suffix). These runs exercise the recipe/optimizer/eval
pipeline end-to-end; they say nothing about real MRPC label distributions.
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    p = argparse.ArgumentParser()
    # nargs="+": a bare --seeds (or an empty shell expansion) must error,
    # not silently run zero seeds and exit 0 with no artifacts
    p.add_argument("--seeds", type=int, nargs="+", default=[42, 43, 44])
    p.add_argument("--matmul-impl", default="native",
                   choices=("native", "int8", "int8_full"))
    p.add_argument("--quant-delayed", action="store_true")
    p.add_argument("--warmup-steps", type=int, default=None)
    p.add_argument("--suffix", default=None,
                   help="artifact suffix override (default: derived)")
    args = p.parse_args()

    from pytorch_distributed_training_tpu.cli import train_dp

    suffix = args.suffix
    if suffix is None:
        parts = []
        if args.matmul_impl != "native":
            parts.append(args.matmul_impl.replace("_", ""))
        if args.quant_delayed:
            parts.append("delayed")
        if args.warmup_steps is not None:
            parts.append(f"warmup{args.warmup_steps}")
        suffix = "_" + "_".join(parts) if parts else ""

    for seed in args.seeds:
        argv = [
            "--model", "bert-large-cased",
            "--task", "synthetic",
            "--micro-batch-size", "24",
            "--seed", str(seed),
            "--log-every", "0",
            "--matmul-impl", args.matmul_impl,
        ]
        if args.quant_delayed:
            argv.append("--quant-delayed")
        if args.warmup_steps is not None:
            argv += ["--warmup-steps", str(args.warmup_steps)]
        history = train_dp.main(argv)
        out = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            f"HISTORY_bert_large_recipe_seed{seed}{suffix}.json",
        )
        with open(out, "w") as f:
            json.dump(history, f, indent=1)
        print(
            f"seed {seed}{suffix}: "
            f"{[{k: r[k] for k in ('epoch', 'accuracy', 'f1')} for r in history]}"
        )


if __name__ == "__main__":
    main()
