"""Rerun the bert-large recipe (MRPC *shape*: lr 2e-5, 3 epochs, global
batch 96, seq 128 — on the SYNTHETIC stand-in task, since this image has
zero egress and no HF hub) across seeds, writing
HISTORY_bert_large_recipe_seed{N}.json artifacts. VERDICT r2 #4: the
epoch-1 accuracy/F1 collapse in the original HISTORY artifact (also a
synthetic-task run) needed a multi-seed reproduction to classify as
training-dynamics pathology vs framework bug. These runs exercise the
recipe/optimizer/eval pipeline end-to-end; they say nothing about real
MRPC label distributions.

Usage: python scripts/run_recipe_seeds.py [seeds...] (default 42 43 44)
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    seeds = [int(s) for s in sys.argv[1:]] or [42, 43, 44]
    from pytorch_distributed_training_tpu.cli import train_dp

    for seed in seeds:
        history = train_dp.main([
            "--model", "bert-large-cased",
            "--task", "synthetic",
            "--micro-batch-size", "24",
            "--seed", str(seed),
            "--log-every", "0",
        ])
        out = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            f"HISTORY_bert_large_recipe_seed{seed}.json",
        )
        with open(out, "w") as f:
            json.dump(history, f, indent=1)
        print(f"seed {seed}: {[{k: r[k] for k in ('epoch', 'accuracy', 'f1')} for r in history]}")


if __name__ == "__main__":
    main()
