"""JAX + concurrency linter CLI (analysis/lint.py driver).

    python scripts/lint.py                 # report findings (waivers applied)
    python scripts/lint.py --check        # exit 1 unless the tree is clean
    python scripts/lint.py --json         # machine-readable report
    python scripts/lint.py serve/ train/  # lint a subset
    python scripts/lint.py --changed      # only files differing from HEAD
    python scripts/lint.py --changed origin/main   # ... or a given ref

Every finding must be fixed or waived: ``analysis/waivers.toml`` holds
``[[waiver]]`` entries (rule + file [+ symbol] + mandatory reason). With
``--metrics-dir`` the run appends a ``lint_summary`` record to the same
telemetry JSONL stream training/serving write, so lint health shows up in
``scripts/summarize_metrics.py``.

``--check`` is part of the standard verify flow (see README "Static
analysis & guards"): the tree must lint clean, modulo waivers, to merge.
``--changed`` keeps iteration fast (lint what you touched); the full-repo
gate stays in tier-1. Unused-waiver warnings are suppressed under
``--changed`` — a subset run can't see every waiver's file.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from pytorch_distributed_training_tpu.analysis.lint import (  # noqa: E402
    DEFAULT_WAIVERS,
    REPO_ROOT,
    lint_paths,
    summary_record,
)
from pytorch_distributed_training_tpu.analysis.waivers import (  # noqa: E402
    load_waivers,
)

DEFAULT_PATHS = [os.path.join(REPO_ROOT, "pytorch_distributed_training_tpu")]


def changed_files(ref: str = "HEAD", repo_root: str = REPO_ROOT) -> list:
    """Package .py files differing from ``ref`` (tracked diffs + untracked
    new files), absolute paths. Raises on a git failure — --changed in a
    non-repo is an input error, not an empty success."""
    diff = subprocess.run(
        ["git", "diff", "--name-only", ref, "--"],
        cwd=repo_root, capture_output=True, text=True, check=True,
    ).stdout.splitlines()
    untracked = subprocess.run(
        ["git", "ls-files", "--others", "--exclude-standard"],
        cwd=repo_root, capture_output=True, text=True, check=True,
    ).stdout.splitlines()
    out = []
    for rel in sorted(set(diff) | set(untracked)):
        if not rel.endswith(".py"):
            continue
        path = os.path.join(repo_root, rel)
        if os.path.exists(path):    # deleted files have nothing to lint
            out.append(path)
    return out


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("paths", nargs="*", default=None,
                   help="files/dirs to lint (default: the package)")
    p.add_argument("--changed", nargs="?", const="HEAD", default=None,
                   metavar="REF",
                   help="lint only files differing from REF (default HEAD) "
                        "plus untracked .py files — fast iteration; the "
                        "full-repo gate stays in tier-1")
    p.add_argument("--check", action="store_true",
                   help="exit 1 when any unwaived finding (or parse error) "
                        "remains")
    p.add_argument("--rules", default=None, metavar="ID,ID,...",
                   help="comma-separated rule ids to run (e.g. "
                        "pspec-mismatch,collective-in-loop); other rules' "
                        "waivers are never reported unused")
    p.add_argument("--json", action="store_true",
                   help="print the full report as JSON")
    p.add_argument("--waivers", default=DEFAULT_WAIVERS,
                   help="waiver file (TOML subset; see analysis/waivers.py)")
    p.add_argument("--no-waivers", action="store_true",
                   help="ignore the waiver file (show every raw finding)")
    p.add_argument("--metrics-dir", default=None,
                   help="append a lint_summary record to this telemetry dir")
    args = p.parse_args(argv)

    waivers = []
    if not args.no_waivers and os.path.exists(args.waivers):
        waivers = load_waivers(args.waivers)
    if args.changed is not None:
        if args.paths:
            p.error("--changed and explicit paths are mutually exclusive")
        paths = changed_files(args.changed)
        if not paths:
            print(f"0 files changed vs {args.changed}: nothing to lint")
            return 0
    else:
        paths = args.paths or DEFAULT_PATHS
    rule_ids = None
    if args.rules is not None:
        rule_ids = tuple(r for r in args.rules.split(",") if r)
        if not rule_ids:
            p.error("--rules needs at least one rule id")
    try:
        report = lint_paths(paths, waivers, rule_ids=rule_ids)
    except ValueError as e:    # unknown rule id
        p.error(str(e))
    if args.changed is not None:
        # a subset run can't see every waiver's file — unused here != dead
        report.unused_waivers = []
    summary = summary_record(report)

    if args.metrics_dir:
        from pytorch_distributed_training_tpu.telemetry.sink import JsonlSink

        sink = JsonlSink(args.metrics_dir)
        sink.emit(summary)
        sink.close()

    if args.json:
        print(json.dumps({
            **summary,
            "findings_detail": [vars(f) for f in report.findings],
            "waived_detail": [
                {**vars(f), "reason": w.reason} for f, w in report.waived
            ],
            "unused_waivers": [vars(w) for w in report.unused_waivers],
            "errors": report.errors,
        }, indent=1))
    else:
        for f in report.findings:
            print(f.format())
        for e in report.errors:
            print(f"ERROR {e}")
        for w in report.unused_waivers:
            print(
                f"warning: unused waiver rule={w.rule} file={w.file} "
                f"symbol={w.symbol}", file=sys.stderr,
            )
        print(
            f"{report.files} files: {len(report.findings)} finding(s), "
            f"{len(report.waived)} waived, "
            f"{len(report.unused_waivers)} unused waiver(s)"
        )

    if args.check and not report.clean:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
