"""Decompose bert-large MRPC step time: dropout, accum carry, metrics.

Times jitted train-step variants on synthetic data (chained, device_get at
the end, per NOTES.md axon timing rules). All variants consume the SAME
global batch (96) so samples/sec are comparable.
"""

import functools
import time

import jax
import jax.numpy as jnp
import optax

from pytorch_distributed_training_tpu.comms.mesh import build_mesh
from pytorch_distributed_training_tpu.models import BertForSequenceClassification
from pytorch_distributed_training_tpu.parallel import ShardingPolicy, state_shardings
from pytorch_distributed_training_tpu.parallel.sharding import shard_state
from pytorch_distributed_training_tpu.train.optim import adamw_with_schedule
from pytorch_distributed_training_tpu.train.state import create_train_state
from pytorch_distributed_training_tpu.train.step import _classification_loss
from pytorch_distributed_training_tpu.utils.config import TrainConfig, model_preset

GLOBAL = 96
SEQ = 128
ITERS = 20


def build(dropout: float):
    mcfg = model_preset(
        "bert-large-cased", hidden_dropout=dropout, attention_dropout=dropout
    )
    model = BertForSequenceClassification(mcfg)
    tcfg = TrainConfig(global_batch_size=GLOBAL, micro_batch_size=32)
    tx, _ = adamw_with_schedule(tcfg, total_steps=1000)
    example = {
        "input_ids": jnp.ones((2, SEQ), jnp.int32),
        "attention_mask": jnp.ones((2, SEQ), jnp.int32),
        "token_type_ids": jnp.zeros((2, SEQ), jnp.int32),
    }
    state = create_train_state(model, tx, jax.random.key(42, impl="rbg"), example)
    mesh = build_mesh()
    shardings = state_shardings(state, ShardingPolicy(), mesh)
    return shard_state(state, shardings), shardings, mesh


def make_step(shardings, mesh, *, accum, accum_dtype, grad_norm, deterministic):
    from jax.sharding import NamedSharding, PartitionSpec as P
    from pytorch_distributed_training_tpu.comms.mesh import TRAIN_BATCH_PSPEC

    def train_step(state, batch):
        base_rng = jax.random.fold_in(state.dropout_rng, state.step)

        def loss_for(p, micro, rng):
            loss, _ = _classification_loss(
                state, p, micro, None if deterministic else rng
            )
            return loss

        if accum == 1:
            micro = jax.tree.map(lambda x: x[0], batch)
            loss, grads = jax.value_and_grad(loss_for)(
                state.params, micro, base_rng
            )
            grads = jax.tree.map(lambda g: g.astype(jnp.float32), grads)
        else:
            def micro_grads(carry, micro):
                grads_acc, (loss_acc, cnt) = carry
                rng = jax.random.fold_in(base_rng, cnt.astype(jnp.int32))
                loss, grads = jax.value_and_grad(loss_for)(
                    state.params, micro, rng
                )
                grads_acc = jax.tree.map(
                    lambda a, g: a + g.astype(accum_dtype), grads_acc, grads
                )
                return (grads_acc, (loss_acc + loss, cnt + 1.0)), None

            zeros = jax.tree.map(
                lambda p: jnp.zeros(p.shape, accum_dtype), state.params
            )
            (grads, (loss_sum, _)), _ = jax.lax.scan(
                micro_grads,
                (zeros, (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))),
                batch,
            )
            grads = jax.tree.map(
                lambda g: g.astype(jnp.float32) / accum, grads
            )
            loss = loss_sum / accum
        new_state = state.apply_gradients(grads)
        metrics = {"loss": loss}
        if grad_norm:
            metrics["grad_norm"] = optax.global_norm(grads)
        return new_state, metrics

    return jax.jit(
        train_step,
        donate_argnums=(0,),
        in_shardings=(shardings, NamedSharding(mesh, TRAIN_BATCH_PSPEC)),
        out_shardings=(shardings, NamedSharding(mesh, P())),
    )


def bench(name, state, step, batch):
    state, m = step(state, batch)  # compile
    jax.block_until_ready(state.params)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            state, m = step(state, batch)
        _ = float(jax.device_get(m["loss"]))
        best = min(best, (time.perf_counter() - t0) / ITERS)
    sps = GLOBAL / best
    print(f"{name:44s} {best*1e3:7.2f} ms/step  {sps:6.1f} samples/s", flush=True)
    return state


def batch_for(accum, mesh):
    from pytorch_distributed_training_tpu.comms.ingest import make_global_batch
    from pytorch_distributed_training_tpu.comms.mesh import TRAIN_BATCH_PSPEC
    import numpy as np

    rng = np.random.default_rng(0)
    micro = GLOBAL // accum
    b = {
        "input_ids": rng.integers(0, 28996, (accum, micro, SEQ)).astype(np.int32),
        "attention_mask": np.ones((accum, micro, SEQ), np.int32),
        "token_type_ids": np.zeros((accum, micro, SEQ), np.int32),
        "labels": rng.integers(0, 2, (accum, micro)).astype(np.int32),
    }
    return make_global_batch(mesh, b, pspec=TRAIN_BATCH_PSPEC)


if __name__ == "__main__":
    print(f"backend={jax.default_backend()} global={GLOBAL} seq={SEQ}")
    state, shardings, mesh = build(0.1)
    b3 = batch_for(3, mesh)
    b1 = batch_for(1, mesh)

    cases = [
        ("A 32x3 fp32-acc +gradnorm (prod)", dict(accum=3, accum_dtype=jnp.float32, grad_norm=True, deterministic=False), b3),
        ("B 32x3 fp32-acc no-gradnorm", dict(accum=3, accum_dtype=jnp.float32, grad_norm=False, deterministic=False), b3),
        ("C 32x3 bf16-acc no-gradnorm", dict(accum=3, accum_dtype=jnp.bfloat16, grad_norm=False, deterministic=False), b3),
        ("D 96x1 no-scan no-gradnorm", dict(accum=1, accum_dtype=jnp.float32, grad_norm=False, deterministic=False), b1),
        ("E 32x3 fp32-acc NO dropout", dict(accum=3, accum_dtype=jnp.float32, grad_norm=False, deterministic=True), b3),
        ("F 96x1 no-scan NO dropout", dict(accum=1, accum_dtype=jnp.float32, grad_norm=False, deterministic=True), b1),
    ]
    for name, kw, batch in cases:
        step = make_step(shardings, mesh, **kw)
        state = bench(name, state, step, batch)
