"""Fold a telemetry JSONL stream into a per-epoch (or serving) table.

Reads the stream written by ``--metrics-dir`` (telemetry/sink.py) and prints
one row per epoch: throughput (samples/sec/chip), where the step time went
(data-wait %), and which host was slowest — the questions every perf PR has
so far answered by hand-assembling BENCH_*/HISTORY_* artifacts.

Serving streams (cli/serve_lm.py ``--metrics-dir``) get their own table:
when ``serve_request`` records are present the summary carries a ``serve``
section — per-bucket rows with request counts and p50/p95/p99 over TTFT
(submit -> first token), TPOT (per-token decode latency) and total request
latency, plus aggregate tokens/sec, queue-wait percentiles and
expired/cancelled counts. Hot-swap streams (serve/hotswap.py) add a
``swap`` section: admissions, ok/failed swaps, rollbacks, blocklisted
steps, rollout convergence percentiles and the version-skew duration
(from the router's ``router_skew`` spans).

Traced streams (telemetry/spans.py) add a ``spans`` section — per-tier
per-phase (queue/prefill/decode) p50/p95 plus the structural counts that
gate the bench (orphan spans, incomplete traces) — an ``slo`` burn-rate
table from the latest ``slo_burn`` record, and a flight-recorder dump
inventory (``flight_dump`` records by reason).

    python scripts/summarize_metrics.py /path/to/metrics_dir
    python scripts/summarize_metrics.py /path/to/metrics.jsonl --json

``--json`` dumps the summary dict instead of the table (for scripts).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

# the spans/slo sections lean on telemetry/spans.py for the structural
# verdicts; running as `python scripts/summarize_metrics.py` puts scripts/
# first on sys.path, so anchor the repo root explicitly
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def load_records(path: str) -> list[dict]:
    """Parse a metrics JSONL file (or a directory holding metrics.jsonl);
    skips unparseable lines (a torn final line from a crashed run) rather
    than failing the whole summary."""
    if os.path.isdir(path):
        path = os.path.join(path, "metrics.jsonl")
    records = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                records.append(json.loads(line))
            except json.JSONDecodeError:
                print(f"warning: skipping unparseable line: {line[:80]}",
                      file=sys.stderr)
    return records


def summarize(records: list[dict]) -> dict:
    """Fold the stream into {run, epochs: [per-epoch rows], compile}."""
    meta = next((r for r in records if r.get("record") == "run_meta"), {})
    steps_by_epoch: dict[int, list[dict]] = {}
    for r in records:
        if r.get("record") == "step":
            steps_by_epoch.setdefault(int(r.get("epoch", 0)), []).append(r)
    saves = [r for r in records if r.get("record") == "checkpoint_save"]
    restarts = [r for r in records if r.get("record") == "restart"]
    compiles = [r for r in records if r.get("record") == "compile"]
    guards = summarize_guards(records)

    epochs = []
    for r in records:
        if r.get("record") != "epoch":
            continue
        epoch = int(r.get("epoch", len(epochs)))
        steps = steps_by_epoch.get(epoch, [])
        total_step = sum(s.get("step_s", 0.0) for s in steps)
        total_wait = sum(s.get("data_wait_s", 0.0) for s in steps)
        straggler = r.get("straggler") or {}
        # prefetch pipeline health: occupancy histogram + stall counter out
        # of the epoch's telemetry window (present when --prefetch-depth>0)
        tel = r.get("telemetry") or {}
        occ = (tel.get("timers") or {}).get("data/prefetch_occupancy") or {}
        stalls = (tel.get("counters") or {}).get("data/prefetch_stalls")
        row = {
            "epoch": epoch,
            "steps": len(steps),
            "train_loss": r.get("train_loss"),
            "samples_per_sec_per_chip": r.get("samples_per_sec_per_chip"),
            "data_wait_pct": 100.0 * total_wait / total_step
            if total_step
            else None,
            "prefetch_occupancy_mean": occ.get("mean_s"),
            "prefetch_stalls": stalls,
            "slowest_host": straggler.get("slowest_host"),
            "wait_skew_s": straggler.get("wait_skew_s"),
            "accuracy": r.get("accuracy"),
            "eval_loss": r.get("eval_loss"),
        }
        epochs.append(row)
    compile_summary = None
    if compiles:
        last = compiles[-1]
        compile_summary = {
            "count": len(compiles),
            "total_s": sum(c.get("compile_s", 0.0) for c in compiles),
            "train_compile_s": last.get("train_compile_s"),
            "eval_compile_s": last.get("eval_compile_s"),
            "cache_hit": last.get("cache_hit"),
            "cache_dir": last.get("cache_dir"),
        }
    return {
        "run": {
            "mesh_shape": meta.get("mesh_shape"),
            "chip_count": meta.get("chip_count"),
            "jax_version": meta.get("jax_version"),
        },
        "epochs": epochs,
        "compile": compile_summary,
        "checkpoint_saves": len(saves),
        "restarts": len(restarts),
        "serve": summarize_serve(records),
        "fleet": summarize_fleet(records),
        "storm": summarize_storm(records),
        "swap": summarize_swap(records),
        "guards": guards,
        "locks": summarize_locks(records),
        "comm": summarize_comm(records),
        "spans": summarize_spans(records),
        "slo": summarize_slo(records),
        "flight": summarize_flight(records),
    }


def summarize_spans(records: list[dict]) -> dict | None:
    """Fold ``span`` records (telemetry/spans.py) into the tracing view:
    per-tier per-phase latency percentiles over the replica phase spans,
    plus the structural verdicts the bench gates on — orphan span count,
    incomplete trace count and phase-sum reconciliation failures. None
    when the stream holds no span records."""
    from pytorch_distributed_training_tpu.telemetry.spans import (
        REQUEST_PHASES,
        trace_coverage,
    )

    spans = [r for r in records if r.get("record") == "span"]
    if not spans:
        return None
    # tier rides the serve root's attrs; phase spans inherit it through
    # their trace (one serve span per replica attempt)
    tier_by_trace: dict[str, str] = {}
    for s in spans:
        if s.get("name") == "serve":
            tier = (s.get("attrs") or {}).get("tier")
            if tier:
                tier_by_trace.setdefault(str(s.get("trace")), str(tier))
    phases: dict[str, dict[str, list]] = {}
    for s in spans:
        if s.get("name") not in REQUEST_PHASES:
            continue
        tier = tier_by_trace.get(str(s.get("trace")), "?")
        phases.setdefault(tier, {p: [] for p in REQUEST_PHASES})
        phases[tier][s["name"]].append(s.get("dur_s"))
    coverage = trace_coverage(records)
    return {
        "spans": len(spans),
        "traces": coverage["traces"],
        "complete_traces": coverage["complete"],
        "incomplete_traces": len(coverage["incomplete"]),
        "orphan_spans": coverage["orphan_spans"],
        "phase_sum_bad": len(coverage["phase_sum_bad"]),
        "coverage": coverage["coverage"],
        "tiers": {
            tier: {
                phase: _pcts(vals)
                for phase, vals in phases[tier].items()
            }
            for tier in sorted(phases)
        },
        "components": sorted({
            s.get("component") or "?" for s in spans
        }),
        "hedges": sum(1 for s in spans if s.get("name") == "hedge"),
        "attempts": sum(1 for s in spans if s.get("name") == "attempt"),
    }


def summarize_slo(records: list[dict]) -> dict | None:
    """The latest ``slo_burn`` record per stream (the monitor emits
    cumulative window views, so the newest one IS the summary), reshaped
    into a per-tier per-window burn table. None when the stream holds no
    burn records."""
    burns = [r for r in records if r.get("record") == "slo_burn"]
    if not burns:
        return None
    last = burns[-1]
    tiers = {}
    for tier, windows in (last.get("tiers") or {}).items():
        tiers[tier] = {
            label: {
                "requests": w.get("requests"),
                "deadline_met": w.get("deadline_met"),
                "availability": w.get("availability"),
                "deadline_burn": w.get("deadline_burn"),
                "availability_burn": w.get("availability_burn"),
            }
            for label, w in windows.items()
        }
    return {
        "emissions": len(burns),
        "windows_s": last.get("windows_s"),
        "deadline_objective": last.get("deadline_objective"),
        "availability_objective": last.get("availability_objective"),
        "max_burn": last.get("max_burn"),
        "peak_burn": max(
            (r.get("max_burn") or 0.0 for r in burns), default=0.0
        ),
        "tiers": tiers,
    }


def summarize_flight(records: list[dict]) -> dict | None:
    """Inventory of flight-recorder dumps (telemetry/flight.py): how many
    rings were dumped, for which reasons, and the last tick each dump
    captured (the stalled tick when the reason is a watchdog). None when
    the stream holds no dumps."""
    dumps = [r for r in records if r.get("record") == "flight_dump"]
    if not dumps:
        return None
    by_reason: dict[str, int] = {}
    for r in dumps:
        reason = r.get("reason") or "?"
        by_reason[reason] = by_reason.get(reason, 0) + 1
    detail = []
    for r in dumps:
        entries = r.get("entries") or []
        detail.append({
            "component": r.get("component"),
            "reason": r.get("reason"),
            "depth": r.get("depth"),
            "dropped": r.get("dropped"),
            "last_tick": entries[-1].get("tick") if entries else None,
        })
    return {
        "dumps": len(dumps),
        "by_reason": by_reason,
        "detail": detail,
    }


def summarize_guards(records: list[dict]) -> dict | None:
    """Fold guard-layer records (analysis/guards.py) + the last
    ``lint_summary`` into one violations block; None when the stream holds
    no guard-layer records at all (guards off / pre-guard stream)."""
    recompiles = [r for r in records if r.get("record") == "recompile"]
    transfers = [
        r for r in records if r.get("record") == "implicit_transfer"
    ]
    donations = [r for r in records if r.get("record") == "donation_audit"]
    shardings = [r for r in records if r.get("record") == "sharding_audit"]
    lints = [r for r in records if r.get("record") == "lint_summary"]
    if not (recompiles or transfers or donations or shardings or lints):
        return None
    out: dict = {
        "recompiles": len(recompiles),
        "recompiled_fns": sorted({r.get("name") for r in recompiles}),
        "implicit_transfers": len(transfers),
        "donation_audits_failed": sum(
            1 for r in donations if r.get("ok") is False
        ),
        "sharding_audits_failed": sum(
            1 for r in shardings if r.get("ok") is False
        ),
    }
    if lints:
        last = lints[-1]
        out["lint"] = {
            "findings": last.get("findings"),
            "waived": last.get("waived"),
            "clean": last.get("clean"),
        }
    return out


def summarize_comm(records: list[dict]) -> dict | None:
    """Fold ``comm_audit`` records (analysis/spmd/manifest.py) into the
    collective-footprint view: one row per audited program (last audit
    per program wins — audits re-run on hot-swap/recompile) with
    collective counts by kind, payload/moved bytes and manifest verdict.
    None when the stream holds no comm records."""
    audits = [r for r in records if r.get("record") == "comm_audit"]
    if not audits:
        return None
    by_name: dict[str, dict] = {}
    for r in audits:
        by_name[r.get("name") or "?"] = r
    programs = {}
    for name in sorted(by_name):
        r = by_name[name]
        programs[name] = {
            "manifest": r.get("manifest"),
            "ok": r.get("ok"),
            "collectives": r.get("count"),
            "by_kind": {
                k: v.get("count") for k, v in (r.get("by_kind") or {}).items()
            },
            "total_bytes": r.get("total_bytes"),
            "total_moved_bytes": r.get("total_moved_bytes"),
            "est_time_s": r.get("est_time_s"),
            "deviations": r.get("deviations") or [],
            "error": r.get("error"),
        }
    return {
        "audits": len(audits),
        "programs": programs,
        "deviations": sum(len(p["deviations"]) for p in programs.values()),
        "clean": all(p["ok"] is not False for p in programs.values()),
    }


def summarize_locks(records: list[dict]) -> dict | None:
    """Fold the runtime lock registry's telemetry
    (``analysis/concurrency``) into the contention view: per-lock
    acquires/contention/hold stats aggregated across processes (each
    ``lock_summary`` is cumulative per pid — last record per pid wins,
    then pids sum), plus every ``lock_order_violation`` /
    ``lock_across_device`` event. None when the stream holds no lock
    records."""
    summaries = [r for r in records if r.get("record") == "lock_summary"]
    violations = [
        r for r in records if r.get("record") == "lock_order_violation"
    ]
    device_holds = [
        r for r in records if r.get("record") == "lock_across_device"
    ]
    if not (summaries or violations or device_holds):
        return None

    by_pid: dict = {}
    for r in summaries:     # cumulative per process: keep the newest
        by_pid[r.get("pid", 0)] = r
    locks: dict[str, dict] = {}
    for rec in by_pid.values():
        for name, s in (rec.get("locks") or {}).items():
            row = locks.setdefault(name, {
                "acquires": 0, "contentions": 0,
                "wait_total_s": 0.0, "wait_max_s": 0.0, "wait_p99_s": None,
                "hold_total_s": 0.0, "hold_max_s": 0.0, "hold_p99_s": None,
            })
            row["acquires"] += s.get("acquires", 0)
            row["contentions"] += s.get("contentions", 0)
            row["wait_total_s"] += s.get("wait_total_s", 0.0)
            row["wait_max_s"] = max(
                row["wait_max_s"], s.get("wait_max_s", 0.0)
            )
            row["hold_total_s"] += s.get("hold_total_s", 0.0)
            row["hold_max_s"] = max(
                row["hold_max_s"], s.get("hold_max_s", 0.0)
            )
            for key in ("wait_p99_s", "hold_p99_s"):
                v = s.get(key)
                if v is not None:
                    row[key] = max(row[key] or 0.0, v)
    return {
        "processes": len(by_pid),
        "locks": locks,
        "order_violations": len(violations),
        "order_violation_detail": [
            {"acquiring": r.get("acquiring"), "holding": r.get("holding"),
             "inverts": r.get("inverts")}
            for r in violations
        ],
        "device_boundary_holds": len(device_holds),
    }


def _pcts(values: list) -> dict | None:
    vals = [v for v in values if v is not None]
    if not vals:
        return None
    import math

    vals = sorted(vals)

    def pct(p: float) -> float:
        # nearest-rank on the sorted sample — honest for the small request
        # counts a test/bench stream holds
        return vals[min(len(vals) - 1, math.ceil(p / 100 * len(vals)) - 1)]

    return {
        "count": len(vals),
        "mean": sum(vals) / len(vals),
        "p50": pct(50),
        "p95": pct(95),
        "p99": pct(99),
    }


def summarize_paged(records: list[dict]) -> dict | None:
    """Fold the engine's paged-KV accounting (the final ``serve_summary``
    stats plus the per-tick ``serve/kv_pages_used`` gauge) into the
    page-pool view: layout/sampling mode, peak page occupancy and
    page-exhaustion admission rejections. None when the stream predates
    the paged cache (or the engine ran dense without a summary)."""
    summaries = [r for r in records if r.get("record") == "serve_summary"]
    if not summaries:
        return None
    last = summaries[-1]
    if "kv_layout" not in last:
        return None     # pre-paged stream
    total = last.get("kv_pages_total")
    peak = last.get("kv_pages_peak")
    return {
        "kv_layout": last.get("kv_layout"),
        "sampling": last.get("sampling"),
        "page_size": last.get("kv_page_size"),
        "pages_total": total,
        "pages_peak": peak,
        "peak_occupancy_pct": (
            100.0 * peak / total if total and peak is not None else None
        ),
        "page_exhausted": last.get("page_exhausted"),
    }


def summarize_spec(records: list[dict]) -> dict | None:
    """Fold the engine's speculative-decoding counters (final
    ``serve_summary``) into the speculation view: draft mode, dispatch and
    acceptance counts, and the two ratios that tell whether speculation
    paid for itself — acceptance rate (drafted tokens that matched the
    target stream) and tokens/dispatch (committed tokens per device
    round-trip; 1.0 is the non-speculative floor). None when the stream
    predates speculation or the engine ran with it off."""
    summaries = [r for r in records if r.get("record") == "serve_summary"]
    if not summaries:
        return None
    last = summaries[-1]
    if not last.get("spec_k"):
        return None
    return {
        "spec_k": last.get("spec_k"),
        "spec_draft": last.get("spec_draft"),
        "dispatches": last.get("spec_dispatches"),
        "drafted": last.get("spec_drafted"),
        "accepted": last.get("spec_accepted"),
        "accept_rate": last.get("spec_accept_rate"),
        "tokens_per_dispatch": last.get("tokens_per_dispatch"),
        "prefill_chunk": last.get("prefill_chunk"),
        "prefill_chunks": last.get("prefill_chunks"),
    }


def summarize_precision(records: list[dict]) -> dict | None:
    """Fold the engine's precision stamp (final ``serve_summary``) into
    the quantization view: which variant the replica served (fp32 or
    int8), the weight/KV dtypes behind it, and the paged pool's KV bytes
    per token (int8 pools carry a fp32 scale per head, so the figure is
    head_dim+4 per head, not head_dim). None when the stream predates
    quantized serving."""
    summaries = [r for r in records if r.get("record") == "serve_summary"]
    if not summaries:
        return None
    last = summaries[-1]
    if "weights_dtype" not in last:
        return None     # pre-quantization stream
    return {
        "variant": last.get("variant"),
        "weights_dtype": last.get("weights_dtype"),
        "kv_dtype": last.get("kv_dtype"),
        "kv_bytes_per_token": last.get("kv_bytes_per_token"),
    }


def summarize_prefix(records: list[dict]) -> dict | None:
    """Fold the engine's prefix-cache counters (the nested
    ``prefix_cache`` dict in the final ``serve_summary``) into the
    shared-KV view: lookup/hit traffic, trie churn (inserts, LRU
    evictions, swap invalidations), pages currently indexed and shared,
    COW copies, and tenant-quota admission holds. None when the stream
    predates the prefix cache or the engine ran with it off."""
    summaries = [r for r in records if r.get("record") == "serve_summary"]
    if not summaries:
        return None
    prefix = summaries[-1].get("prefix_cache")
    if not prefix:
        return None
    return {
        "lookups": prefix.get("prefix_lookups"),
        "hits": prefix.get("prefix_hits"),
        "hit_rate": prefix.get("prefix_hit_rate"),
        "inserts": prefix.get("prefix_inserts"),
        "evictions": prefix.get("prefix_evictions"),
        "invalidations": prefix.get("prefix_invalidations"),
        "cached_pages": prefix.get("prefix_cached_pages"),
        "pages_shared": prefix.get("pages_shared"),
        "cow_copies": prefix.get("cow_copies"),
        "tenant_blocked": prefix.get("tenant_blocked"),
        "tenant_page_quota": prefix.get("tenant_page_quota"),
        "prefill_tokens": summaries[-1].get("prefill_tokens"),
    }


def summarize_serve(records: list[dict]) -> dict | None:
    """Fold ``serve_request`` records into per-bucket latency percentiles
    plus aggregate serving stats; None when the stream holds none."""
    reqs = [r for r in records if r.get("record") == "serve_request"]
    if not reqs:
        return None
    done = [r for r in reqs if r.get("status") == "done"]
    by_bucket: dict[int, list[dict]] = {}
    for r in done:
        by_bucket.setdefault(int(r.get("bucket", 0)), []).append(r)
    buckets = []
    for bucket in sorted(by_bucket):
        rs = by_bucket[bucket]
        buckets.append({
            "bucket": bucket,
            "requests": len(rs),
            "new_tokens": sum(r.get("new_tokens", 0) for r in rs),
            "ttft_s": _pcts([r.get("ttft_s") for r in rs]),
            "tpot_s": _pcts([r.get("tpot_s") for r in rs]),
            "total_s": _pcts([r.get("total_s") for r in rs]),
        })
    tokens = sum(r.get("new_tokens", 0) for r in done)
    # aggregate tokens/sec over the stream's request span (ts is stamped at
    # finish; subtract the first request's own latency to recover its start)
    span = None
    if done:
        ts = [r.get("ts") for r in done if r.get("ts") is not None]
        if ts:
            first = min(ts) - (done[0].get("total_s") or 0.0)
            span = max(max(ts) - first, 1e-9)
    return {
        "requests": len(reqs),
        "done": len(done),
        "expired": sum(1 for r in reqs if r.get("status") == "expired"),
        "cancelled": sum(1 for r in reqs if r.get("status") == "cancelled"),
        "tokens": tokens,
        "tokens_per_s": tokens / span if span else None,
        "queue_wait_s": _pcts([r.get("queue_wait_s") for r in reqs]),
        "ttft_s": _pcts([r.get("ttft_s") for r in done]),
        "tpot_s": _pcts([r.get("tpot_s") for r in done]),
        "buckets": buckets,
        "paged": summarize_paged(records),
        "spec": summarize_spec(records),
        "precision": summarize_precision(records),
        "prefix": summarize_prefix(records),
    }


def summarize_fleet(records: list[dict]) -> dict | None:
    """Fold router/fleet records (serve/router.py + serve/fleet.py) into
    the fleet-health view: per-replica routed-request counts, failovers,
    hedges, breaker transitions, crash-vs-graceful exits and drain
    durations. None when the stream holds no fleet records."""
    router_reqs = [r for r in records if r.get("record") == "router_request"]
    failovers = [r for r in records if r.get("record") == "router_failover"]
    hedges = [r for r in records if r.get("record") == "router_hedge"]
    breakers = [r for r in records if r.get("record") == "router_breaker"]
    spawns = [r for r in records if r.get("record") == "replica_spawn"]
    exits = [r for r in records if r.get("record") == "replica_exit"]
    drains = [r for r in records if r.get("record") == "replica_drain"]
    if not (router_reqs or spawns or breakers):
        return None

    replicas: dict[str, dict] = {}

    def rep(name) -> dict:
        return replicas.setdefault(name or "?", {
            "requests": 0, "ok": 0, "midstream_errors": 0,
            "spawns": 0, "crashes": 0, "graceful_exits": 0,
            "breaker_opens": 0,
        })

    for r in router_reqs:
        row = rep(r.get("replica"))
        row["requests"] += 1
        if r.get("status") == "ok":
            row["ok"] += 1
        elif r.get("status") == "error_midstream":
            row["midstream_errors"] += 1
    for r in spawns:
        rep(r.get("replica"))["spawns"] += 1
    for r in exits:
        key = "graceful_exits" if r.get("graceful") else "crashes"
        rep(r.get("replica"))[key] += 1
    for r in breakers:
        if r.get("to") == "open":
            rep(r.get("replica"))["breaker_opens"] += 1
    replicas.pop("?", None)     # rejected requests have no replica

    statuses = [r.get("status") for r in router_reqs]
    return {
        "routed": len(router_reqs),
        "ok": statuses.count("ok"),
        "rejected": statuses.count("rejected"),
        "midstream_errors": statuses.count("error_midstream"),
        "failovers": len(failovers),
        "hedges": len(hedges),
        "breaker_transitions": len(breakers),
        "total_s": _pcts([r.get("total_s") for r in router_reqs]),
        "drain_s": _pcts([r.get("drain_s") for r in drains]),
        "replicas": {k: replicas[k] for k in sorted(replicas)},
    }


def summarize_storm(records: list[dict]) -> dict | None:
    """Fold the load-shaping records (SLO tier lanes + brownout ladder in
    serve/queue.py, autoscaler + dynamic pool in serve/autoscale.py +
    serve/fleet.py) into the storm view: per-tier request latency
    percentiles, shed/brownout counters, and the scale-event timeline
    (scale-ups with spawn->ready latency, drain-based scale-downs with
    measured drain time, bind-race port retries). None when the stream
    holds no tiered/brownout/scale records at all — pre-storm streams
    keep their old summary shape."""
    reqs = [
        r for r in records
        if r.get("record") == "serve_request" and r.get("tier") is not None
    ]
    sheds = [r for r in records if r.get("record") == "serve_shed"]
    brownouts = [
        r for r in records if r.get("record") == "brownout_transition"
    ]
    scales = [r for r in records if r.get("record") == "fleet_scale"]
    auto_events = [
        r for r in records if r.get("record") == "autoscale_event"
    ]
    readies = [r for r in records if r.get("record") == "autoscale_ready"]
    port_retries = [
        r for r in records if r.get("record") == "replica_port_retry"
    ]
    if not (reqs or sheds or brownouts or scales or auto_events):
        return None

    tiers = {}
    for tier in sorted({r.get("tier") for r in reqs}):
        rows = [r for r in reqs if r.get("tier") == tier]
        done = [r for r in rows if r.get("status") == "done"]
        tiers[tier] = {
            "requests": len(rows),
            "done": len(done),
            "expired": sum(1 for r in rows if r.get("status") == "expired"),
            "ttft_s": _pcts([r.get("ttft_s") for r in done]),
            "total_s": _pcts([r.get("total_s") for r in done]),
            "queue_wait_s": _pcts([r.get("queue_wait_s") for r in rows]),
        }

    shed_by_tier: dict[str, int] = {}
    for r in sheds:
        tier = r.get("tier") or "?"
        shed_by_tier[tier] = shed_by_tier.get(tier, 0) + 1
    peak_level = max((r.get("level", 0) for r in brownouts), default=0)
    level_names = ("normal", "shed_batch", "clamp", "fail_fast")

    def _level_of(name) -> int:
        return level_names.index(name) if name in level_names else 0

    # scale-event timeline, oldest first (ts is stamped by the sink)
    timeline = []
    for r in scales:
        timeline.append({
            "ts": r.get("ts"),
            "event": f"scale_{r.get('action')}",
            "replica": r.get("replica"),
            "size": r.get("size"),
            **({"drain_s": r.get("drain_s")}
               if r.get("drain_s") is not None else {}),
        })
    for r in readies:
        timeline.append({
            "ts": r.get("ts"),
            "event": "replica_ready",
            "replica": r.get("replica"),
            "ready_s": r.get("ready_s"),
        })
    for r in port_retries:
        timeline.append({
            "ts": r.get("ts"),
            "event": "port_retry",
            "replica": r.get("replica"),
            "new_port": r.get("new_port"),
        })
    timeline.sort(key=lambda e: e.get("ts") or 0.0)

    return {
        "tiers": tiers,
        "sheds": {
            "total": len(sheds),
            "by_tier": shed_by_tier,
        },
        "brownout": {
            "transitions": len(brownouts),
            "escalations": sum(
                1 for r in brownouts
                if r.get("level", 0) > _level_of(r.get("from"))
            ),
            "peak_level": peak_level,
            "final_level": brownouts[-1].get("level") if brownouts else 0,
        },
        "scale_ups": sum(
            1 for r in scales if r.get("action") == "up"
        ),
        "scale_downs": sum(
            1 for r in scales if r.get("action") == "down"
        ),
        "scale_up_ready_s": _pcts([r.get("ready_s") for r in readies]),
        "scale_down_drain_s": _pcts([
            r.get("drain_s") for r in scales
            if r.get("action") == "down"
        ]),
        "port_retries": len(port_retries),
        "timeline": timeline,
    }


def summarize_swap(records: list[dict]) -> dict | None:
    """Fold hot-swap records (serve/hotswap.py + the engine's swap
    protocol + the fleet's rolling rollout) into the rollout-health view:
    admissions, successful/failed swaps, rollbacks, rollout convergence
    times and how long the pool spent version-skewed. None when the
    stream holds no swap records."""
    admitted = [r for r in records if r.get("record") == "swap_admitted"]
    oks = [r for r in records if r.get("record") == "swap_ok"]
    fails = [r for r in records if r.get("record") == "swap_failed"]
    rollbacks = [r for r in records if r.get("record") == "swap_rollback"]
    rejected = [r for r in records if r.get("record") == "swap_rejected"]
    blocked = [r for r in records if r.get("record") == "swap_blocklisted"]
    rollouts = [r for r in records if r.get("record") == "fleet_swap"]
    skews = [r for r in records if r.get("record") == "router_skew"]
    if not (admitted or oks or fails or rollouts or skews):
        return None
    # version-skew duration: the spans between a router_skew record going
    # >0 and the next one back at 0 (ts is stamped by the sink)
    skew_s = 0.0
    open_t = None
    for r in skews:
        ts = r.get("ts")
        if ts is None:
            continue
        if (r.get("skew") or 0) > 0 and open_t is None:
            open_t = ts
        elif (r.get("skew") or 0) == 0 and open_t is not None:
            skew_s += ts - open_t
            open_t = None
    return {
        "admitted": len(admitted),
        "ok": len(oks),
        "failed": len(fails),
        "rollbacks": len(rollbacks),
        "rejected": len(rejected),
        "blocklisted": sorted({r.get("step") for r in blocked}),
        "load_s": _pcts([r.get("load_s") for r in oks]),
        "rollouts": len(rollouts),
        "rollouts_converged": sum(
            1 for r in rollouts if r.get("converged")
        ),
        "rollout_s": _pcts([r.get("duration_s") for r in rollouts]),
        "skew_events": len(skews),
        "skew_s": skew_s if skews else None,
    }


def _fmt(v, spec=".4g") -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return format(v, spec)
    return str(v)


def render_serve_table(serve: dict) -> str:
    """Per-bucket serving rows + an aggregate footer."""
    def ms(block: dict | None, key: str):
        return block[key] * 1e3 if block and block.get(key) is not None else None

    cols = ["bucket", "reqs", "tokens", "ttft p50 ms", "ttft p95 ms",
            "ttft p99 ms", "tpot p50 ms", "tpot p95 ms", "total p95 ms"]
    rows = []
    for b in serve["buckets"]:
        rows.append([
            _fmt(b["bucket"]), _fmt(b["requests"]), _fmt(b["new_tokens"]),
            _fmt(ms(b["ttft_s"], "p50")), _fmt(ms(b["ttft_s"], "p95")),
            _fmt(ms(b["ttft_s"], "p99")), _fmt(ms(b["tpot_s"], "p50")),
            _fmt(ms(b["tpot_s"], "p95")), _fmt(ms(b["total_s"], "p95")),
        ])
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
        for i, h in enumerate(cols)
    ]
    lines = [
        "serving:",
        "  ".join(h.rjust(w) for h, w in zip(cols, widths)),
        "  ".join("-" * w for w in widths),
    ]
    lines += ["  ".join(c.rjust(w) for c, w in zip(r, widths)) for r in rows]
    qw = serve.get("queue_wait_s") or {}
    lines.append(
        f"requests={serve['requests']} done={serve['done']} "
        f"expired={serve['expired']} cancelled={serve['cancelled']} "
        f"tokens/s={_fmt(serve.get('tokens_per_s'))} "
        f"queue-wait p95={_fmt(ms(qw, 'p95') if qw else None)}ms"
    )
    precision = serve.get("precision")
    if precision:
        line = (
            f"precision: variant={precision.get('variant')} "
            f"weights={precision.get('weights_dtype')} "
            f"kv={precision.get('kv_dtype')}"
        )
        if precision.get("kv_bytes_per_token") is not None:
            line += (
                f" kv-bytes/token={_fmt(precision['kv_bytes_per_token'])}"
            )
        lines.append(line)
    paged = serve.get("paged")
    if paged:
        if paged.get("kv_layout") == "paged":
            lines.append(
                f"kv-cache: paged (page={_fmt(paged.get('page_size'))} tok, "
                f"pool={_fmt(paged.get('pages_total'))} pages, "
                f"peak={_fmt(paged.get('pages_peak'))} "
                f"[{_fmt(paged.get('peak_occupancy_pct'), '.1f')}%]) "
                f"sampling={paged.get('sampling')} "
                f"page-exhausted={_fmt(paged.get('page_exhausted'))}"
            )
        else:
            lines.append(
                f"kv-cache: dense  sampling={paged.get('sampling')}"
            )
    prefix = serve.get("prefix")
    if prefix:
        line = (
            f"prefix-cache: hit-rate={_fmt(prefix.get('hit_rate'), '.3f')} "
            f"({_fmt(prefix.get('hits'))}/{_fmt(prefix.get('lookups'))}) "
            f"cached-pages={_fmt(prefix.get('cached_pages'))} "
            f"shared={_fmt(prefix.get('pages_shared'))} "
            f"cow={_fmt(prefix.get('cow_copies'))} "
            f"evictions={_fmt(prefix.get('evictions'))} "
            f"invalidations={_fmt(prefix.get('invalidations'))}"
        )
        if prefix.get("tenant_page_quota"):
            line += (
                f" tenant-quota={_fmt(prefix['tenant_page_quota'], '.2f')}"
                f" tenant-blocked={_fmt(prefix.get('tenant_blocked'))}"
            )
        lines.append(line)
    spec = serve.get("spec")
    if spec:
        line = (
            f"speculation: k={_fmt(spec.get('spec_k'))} "
            f"draft={spec.get('spec_draft')} "
            f"accept-rate={_fmt(spec.get('accept_rate'), '.3f')} "
            f"tokens/dispatch={_fmt(spec.get('tokens_per_dispatch'), '.2f')} "
            f"(dispatches={_fmt(spec.get('dispatches'))} "
            f"drafted={_fmt(spec.get('drafted'))} "
            f"accepted={_fmt(spec.get('accepted'))})"
        )
        if spec.get("prefill_chunk"):
            line += (
                f" prefill-chunk={_fmt(spec.get('prefill_chunk'))}"
                f" chunks={_fmt(spec.get('prefill_chunks'))}"
            )
        lines.append(line)
    return "\n".join(lines)


def render_fleet_table(fleet: dict) -> str:
    """Per-replica fleet rows + a resilience footer."""
    cols = ["replica", "routed", "ok", "midstream", "spawns", "crashes",
            "drains", "brk-opens"]
    rows = []
    for name in sorted(fleet["replicas"]):
        r = fleet["replicas"][name]
        rows.append([
            name, _fmt(r["requests"]), _fmt(r["ok"]),
            _fmt(r["midstream_errors"]), _fmt(r["spawns"]),
            _fmt(r["crashes"]), _fmt(r["graceful_exits"]),
            _fmt(r["breaker_opens"]),
        ])
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
        for i, h in enumerate(cols)
    ]
    lines = [
        "fleet:",
        "  ".join(h.rjust(w) for h, w in zip(cols, widths)),
        "  ".join("-" * w for w in widths),
    ]
    lines += ["  ".join(c.rjust(w) for c, w in zip(r, widths)) for r in rows]
    drain = fleet.get("drain_s") or {}
    lines.append(
        f"routed={fleet['routed']} ok={fleet['ok']} "
        f"rejected={fleet['rejected']} "
        f"midstream-errors={fleet['midstream_errors']} "
        f"failovers={fleet['failovers']} hedges={fleet['hedges']} "
        f"breaker-transitions={fleet['breaker_transitions']} "
        f"drain p95={_fmt(drain.get('p95'))}s"
    )
    return "\n".join(lines)


def render_storm_table(storm: dict) -> str:
    """Per-tier latency rows + shed/brownout counters + the scale-event
    timeline (the load-shaping view of a storm stream)."""
    def ms(block: dict | None, key: str):
        return (
            block[key] * 1e3
            if block and block.get(key) is not None else None
        )

    cols = ["tier", "reqs", "done", "expired", "ttft p50 ms",
            "total p50 ms", "total p95 ms", "total p99 ms",
            "queue-wait p95 ms"]
    rows = []
    for tier in sorted(storm["tiers"]):
        t = storm["tiers"][tier]
        rows.append([
            tier, _fmt(t["requests"]), _fmt(t["done"]), _fmt(t["expired"]),
            _fmt(ms(t["ttft_s"], "p50")),
            _fmt(ms(t["total_s"], "p50")), _fmt(ms(t["total_s"], "p95")),
            _fmt(ms(t["total_s"], "p99")),
            _fmt(ms(t["queue_wait_s"], "p95")),
        ])
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
        for i, h in enumerate(cols)
    ]
    lines = [
        "storm:",
        "  ".join(h.rjust(w) for h, w in zip(cols, widths)),
        "  ".join("-" * w for w in widths),
    ]
    lines += ["  ".join(c.rjust(w) for c, w in zip(r, widths)) for r in rows]
    sheds = storm["sheds"]
    brown = storm["brownout"]
    shed_detail = ",".join(
        f"{k}={v}" for k, v in sorted(sheds["by_tier"].items())
    ) or "-"
    lines.append(
        f"sheds={sheds['total']} ({shed_detail})  "
        f"brownout: transitions={brown['transitions']} "
        f"peak-level={brown['peak_level']} "
        f"final-level={brown['final_level']}"
        + (" [recovered]" if brown["final_level"] == 0 else " [DEGRADED]")
    )
    ready = storm.get("scale_up_ready_s") or {}
    drain = storm.get("scale_down_drain_s") or {}
    lines.append(
        f"autoscale: ups={storm['scale_ups']} "
        f"(ready p95={_fmt(ready.get('p95'))}s) "
        f"downs={storm['scale_downs']} "
        f"(drain p95={_fmt(drain.get('p95'))}s) "
        f"port-retries={storm['port_retries']}"
    )
    t0 = next(
        (e["ts"] for e in storm["timeline"] if e.get("ts") is not None),
        None,
    )
    for e in storm["timeline"]:
        at = (
            f"+{e['ts'] - t0:.1f}s" if t0 is not None and e.get("ts")
            is not None else "?"
        )
        extra = "".join(
            f" {k}={_fmt(e[k], '.3g')}" for k in ("size", "ready_s",
                                                  "drain_s", "new_port")
            if e.get(k) is not None
        )
        lines.append(f"  {at:>8}  {e['event']:<13} {e['replica']}{extra}")
    return "\n".join(lines)


def render_locks_table(locks: dict, top_n: int = 8) -> str:
    """Top-N locks by contention then hold p99, plus any violations."""
    rows_src = sorted(
        locks["locks"].items(),
        key=lambda kv: (
            -(kv[1]["contentions"]), -(kv[1]["hold_p99_s"] or 0.0),
            kv[0],
        ),
    )[:top_n]
    cols = ["lock", "acquires", "contended", "wait max ms", "wait p99 ms",
            "hold max ms", "hold p99 ms"]

    def ms(v):
        return v * 1e3 if v is not None else None

    rows = [[
        name, _fmt(s["acquires"]), _fmt(s["contentions"]),
        _fmt(ms(s["wait_max_s"])), _fmt(ms(s["wait_p99_s"])),
        _fmt(ms(s["hold_max_s"])), _fmt(ms(s["hold_p99_s"])),
    ] for name, s in rows_src]
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
        for i, h in enumerate(cols)
    ]
    lines = [
        "locks:",
        "  ".join(h.rjust(w) for h, w in zip(cols, widths)),
        "  ".join("-" * w for w in widths),
    ]
    lines += ["  ".join(c.rjust(w) for c, w in zip(r, widths)) for r in rows]
    dropped = len(locks["locks"]) - len(rows)
    foot = (
        f"processes={locks['processes']} "
        f"order-violations={locks['order_violations']} "
        f"device-boundary-holds={locks['device_boundary_holds']}"
        + (f" (+{dropped} quieter lock(s) not shown)" if dropped > 0 else "")
        + (" [VIOLATIONS]"
           if locks["order_violations"] or locks["device_boundary_holds"]
           else " [clean]")
    )
    lines.append(foot)
    for v in locks["order_violation_detail"]:
        lines.append(
            f"  INVERSION: acquiring {v['acquiring']} while holding "
            f"{v['holding']} (inverts {v['inverts']})"
        )
    return "\n".join(lines)


def render_comm_table(comm: dict) -> str:
    """Per-program collective-footprint rows + a manifest verdict footer."""
    cols = ["program", "collectives", "kinds", "payload B", "moved B",
            "manifest", "verdict"]
    rows = []
    for name, p in comm["programs"].items():
        kinds = ",".join(
            f"{k}x{n}" for k, n in sorted(p["by_kind"].items())
        ) or "-"
        verdict = (
            "ERROR" if p["error"] else
            "ok" if p["ok"] else
            "?" if p["ok"] is None else "DEVIATES"
        )
        rows.append([
            name, _fmt(p["collectives"]), kinds, _fmt(p["total_bytes"]),
            _fmt(p["total_moved_bytes"]), p["manifest"] or "-", verdict,
        ])
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
        for i, h in enumerate(cols)
    ]
    lines = [
        "comm:",
        "  ".join(h.rjust(w) for h, w in zip(cols, widths)),
        "  ".join("-" * w for w in widths),
    ]
    lines += ["  ".join(c.rjust(w) for c, w in zip(r, widths)) for r in rows]
    lines.append(
        f"audits={comm['audits']} deviations={comm['deviations']}"
        + (" [clean]" if comm["clean"] else " [VIOLATIONS]")
    )
    for name, p in comm["programs"].items():
        for d in p["deviations"]:
            lines.append(f"  DEVIATION {name}: {d}")
    return "\n".join(lines)


def render_spans_table(spans: dict) -> str:
    """Per-tier per-phase latency rows + the structural-verdict footer
    (the tracing view of a spanned stream)."""
    def ms(block: dict | None, key: str):
        return (
            block[key] * 1e3
            if block and block.get(key) is not None else None
        )

    cols = ["tier", "phase", "count", "p50 ms", "p95 ms", "p99 ms"]
    rows = []
    for tier in sorted(spans["tiers"]):
        for phase, block in spans["tiers"][tier].items():
            rows.append([
                tier, phase, _fmt(block["count"] if block else 0),
                _fmt(ms(block, "p50")), _fmt(ms(block, "p95")),
                _fmt(ms(block, "p99")),
            ])
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
        for i, h in enumerate(cols)
    ]
    lines = [
        "spans:",
        "  ".join(h.rjust(w) for h, w in zip(cols, widths)),
        "  ".join("-" * w for w in widths),
    ]
    lines += ["  ".join(c.rjust(w) for c, w in zip(r, widths)) for r in rows]
    structural_bad = (
        spans["orphan_spans"] or spans["incomplete_traces"]
        or spans["phase_sum_bad"]
    )
    lines.append(
        f"traces={spans['traces']} complete={spans['complete_traces']} "
        f"incomplete={spans['incomplete_traces']} "
        f"orphan-spans={spans['orphan_spans']} "
        f"phase-sum-bad={spans['phase_sum_bad']} "
        f"attempts={spans['attempts']} hedges={spans['hedges']}"
        + (" [INCOMPLETE]" if structural_bad else " [complete]")
    )
    return "\n".join(lines)


def render_slo_table(slo: dict) -> str:
    """Per-tier per-window burn rows from the stream's latest
    ``slo_burn`` record."""
    cols = ["tier", "window", "reqs", "deadline-met", "avail",
            "deadline-burn", "avail-burn"]
    rows = []
    for tier in sorted(slo["tiers"]):
        for label, w in slo["tiers"][tier].items():
            rows.append([
                tier, label, _fmt(w["requests"]),
                _fmt(w["deadline_met"], ".3f"),
                _fmt(w["availability"], ".3f"),
                _fmt(w["deadline_burn"], ".2f"),
                _fmt(w["availability_burn"], ".2f"),
            ])
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
        for i, h in enumerate(cols)
    ]
    lines = [
        "slo:",
        "  ".join(h.rjust(w) for h, w in zip(cols, widths)),
        "  ".join("-" * w for w in widths),
    ]
    lines += ["  ".join(c.rjust(w) for c, w in zip(r, widths)) for r in rows]
    lines.append(
        f"objectives: deadline={_fmt(slo['deadline_objective'])} "
        f"availability={_fmt(slo['availability_objective'])}  "
        f"max-burn={_fmt(slo['max_burn'], '.2f')} "
        f"peak-burn={_fmt(slo['peak_burn'], '.2f')}"
        + (" [BURNING]" if (slo["max_burn"] or 0) > 1.0 else " [ok]")
    )
    return "\n".join(lines)


def render_table(summary: dict) -> str:
    cols = [
        ("epoch", "epoch"),
        ("steps", "steps"),
        ("train_loss", "loss"),
        ("samples_per_sec_per_chip", "samp/s/chip"),
        ("data_wait_pct", "data-wait %"),
        ("prefetch_occupancy_mean", "pf-occ"),
        ("prefetch_stalls", "pf-stall"),
        ("slowest_host", "slow host"),
        ("wait_skew_s", "skew s"),
        ("accuracy", "acc"),
    ]
    rows = [[_fmt(e.get(k)) for k, _ in cols] for e in summary["epochs"]]
    headers = [h for _, h in cols]
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
        for i, h in enumerate(headers)
    ]
    lines = [
        "  ".join(h.rjust(w) for h, w in zip(headers, widths)),
        "  ".join("-" * w for w in widths),
    ]
    lines += ["  ".join(c.rjust(w) for c, w in zip(r, widths)) for r in rows]
    run = summary["run"]
    lines.append(
        f"mesh={run.get('mesh_shape')} chips={run.get('chip_count')} "
        f"ckpt_saves={summary['checkpoint_saves']} "
        f"restarts={summary['restarts']}"
    )
    comp = summary.get("compile")
    if comp:
        hit = comp.get("cache_hit")
        lines.append(
            f"compile: {_fmt(comp.get('total_s'))}s "
            f"(train {_fmt(comp.get('train_compile_s'))}s, "
            f"eval {_fmt(comp.get('eval_compile_s'))}s, "
            f"cache={'hit' if hit else 'miss' if hit is not None else 'off'})"
        )
    serve = summary.get("serve")
    fleet = summary.get("fleet")
    if serve:
        if summary["epochs"]:
            lines.append(render_serve_table(serve))
        else:  # pure serving stream: the serve table IS the output
            lines = [render_serve_table(serve)]
    if fleet:
        if not summary["epochs"] and not serve:
            lines = []  # pure fleet stream: the fleet table IS the output
        lines.append(render_fleet_table(fleet))
    storm = summary.get("storm")
    if storm:
        if not summary["epochs"] and not serve and not fleet:
            lines = []  # pure storm stream: the storm table IS the output
        lines.append(render_storm_table(storm))
    swap = summary.get("swap")
    if swap:
        ro = swap.get("rollout_s") or {}
        lines.append(
            f"hotswap: admitted={swap['admitted']} ok={swap['ok']} "
            f"failed={swap['failed']} rollbacks={swap['rollbacks']} "
            f"rejected={swap['rejected']} "
            f"blocklisted={swap['blocklisted'] or '-'} "
            f"rollouts={swap['rollouts']}"
            f"/{swap['rollouts_converged']} converged "
            f"(p95 {_fmt(ro.get('p95'))}s) "
            f"skew={_fmt(swap.get('skew_s'))}s"
        )
    spans = summary.get("spans")
    if spans:
        lines.append(render_spans_table(spans))
    slo = summary.get("slo")
    if slo:
        lines.append(render_slo_table(slo))
    flight = summary.get("flight")
    if flight:
        reasons = ",".join(
            f"{k}={v}" for k, v in sorted(flight["by_reason"].items())
        )
        lines.append(
            f"flight-dumps: {flight['dumps']} ({reasons}) "
            f"last-ticks={[d['last_tick'] for d in flight['detail']]}"
        )
    locks = summary.get("locks")
    if locks:
        lines.append(render_locks_table(locks))
    comm = summary.get("comm")
    if comm:
        lines.append(render_comm_table(comm))
    guards = summary.get("guards")
    if guards:
        bad = (
            guards["recompiles"] or guards["implicit_transfers"]
            or guards["donation_audits_failed"]
            or guards["sharding_audits_failed"]
        )
        gl = (
            f"guards: recompiles={guards['recompiles']}"
            + (f" ({','.join(guards['recompiled_fns'])})"
               if guards["recompiled_fns"] else "")
            + f" implicit-transfers={guards['implicit_transfers']}"
            + f" donation-fails={guards['donation_audits_failed']}"
            + f" sharding-fails={guards['sharding_audits_failed']}"
            + (" [VIOLATIONS]" if bad else " [clean]")
        )
        lint = guards.get("lint")
        if lint:
            gl += (
                f"  lint: {_fmt(lint.get('findings'))} finding(s), "
                f"{_fmt(lint.get('waived'))} waived"
            )
        lines.append(gl)
    return "\n".join(lines)


def main(argv=None) -> dict:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("path", help="metrics.jsonl file or its --metrics-dir")
    p.add_argument("--json", action="store_true",
                   help="print the summary as JSON instead of a table")
    args = p.parse_args(argv)
    summary = summarize(load_records(args.path))
    if args.json:
        print(json.dumps(summary, indent=1))
    else:
        print(render_table(summary))
    return summary


if __name__ == "__main__":
    main()
