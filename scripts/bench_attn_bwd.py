"""Size the attention-bwd win: XLA attention fwd vs fwd+bwd cost at seq 128.

Times the attention OP only (no projections), bert-large geometry, micro 32:
  - fwd only (inference path)
  - fwd + bwd via jax.grad (what the train step pays)
  - pallas probs-saving fwd + dqkv-from-probs bwd (the flash single-block path)
Chained iterations; scalar device_get at the end (NOTES.md axon rules).
"""

import functools
import time

import jax
import jax.numpy as jnp

from pytorch_distributed_training_tpu.ops.attention import reference_attention
from pytorch_distributed_training_tpu.ops.flash_attention import (
    flash_attention_base,
)

B, S, N, D = 32, 128, 16, 64
ITERS = 50


def xla_attn(q, k, v, bias, rng, rate):
    return reference_attention(
        q, k, v, bias, dropout_rng=rng, dropout_rate=rate,
        deterministic=rate == 0.0, dropout_impl="bits32",
    )


def pallas_attn(q, k, v, bias, seed, rate):
    o = flash_attention_base(
        q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
        v.transpose(0, 2, 1, 3), bias, seed, dropout_rate=rate,
    )
    return o.transpose(0, 2, 1, 3)


def bench(name, fn, grad: bool, rate: float):
    if grad:
        def loss(q, k, v, bias, r):
            return jnp.sum(fn(q, k, v, bias, r, rate).astype(jnp.float32) ** 2)

        g = jax.grad(loss, argnums=(0, 1, 2))

        @jax.jit
        def step(q, k, v, bias, r):
            dq, dk, dv = g(q, k, v, bias, r)
            return (
                (q + dq * 1e-6).astype(q.dtype),
                (k + dk * 1e-6).astype(k.dtype),
                (v + dv * 1e-6).astype(v.dtype),
                jnp.sum(dq.astype(jnp.float32)),
            )
    else:
        @jax.jit
        def step(q, k, v, bias, r):
            o = fn(q, k, v, bias, r, rate)
            return (
                (q + o * 1e-6).astype(q.dtype),
                k,
                v,
                jnp.sum(o.astype(jnp.float32)),
            )

    key = jax.random.key(0, impl="rbg")
    q = jax.random.normal(key, (B, S, N, D), jnp.bfloat16)
    k = jax.random.normal(jax.random.fold_in(key, 1), (B, S, N, D), jnp.bfloat16)
    v = jax.random.normal(jax.random.fold_in(key, 2), (B, S, N, D), jnp.bfloat16)
    bias = jnp.zeros((B, 1, 1, S), jnp.float32)
    r = jnp.array([123], jnp.int32) if "pallas" in name else key
    q, k, v, s = step(q, k, v, bias, r)
    jax.block_until_ready(s)
    best = float("inf")
    for _ in range(3):
        t0 = time.perf_counter()
        for _ in range(ITERS):
            q, k, v, s = step(q, k, v, bias, r)
        _ = float(jax.device_get(s))
        best = min(best, (time.perf_counter() - t0) / ITERS * 1e3)
    print(f"{name:36s} {best:7.3f} ms", flush=True)
    return best


if __name__ == "__main__":
    print(f"backend={jax.default_backend()} B={B} S={S} N={N} D={D}")
    for rate in (0.0, 0.1):
        print(f"--- dropout={rate}")
        f = bench(f"xla fwd only", xla_attn, False, rate)
        fb = bench(f"xla fwd+bwd", xla_attn, True, rate)
        print(f"    => xla bwd cost ~{fb - f:.3f} ms")
        pf = bench(f"pallas fwd only", pallas_attn, False, rate)
        pfb = bench(f"pallas fwd+bwd (probs-saving)", pallas_attn, True, rate)
        print(f"    => pallas bwd cost ~{pfb - pf:.3f} ms")
