"""GPT-2 causal-LM family: model semantics, LM objective, FSDP trainer."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.models.gpt2 import GPT2LMModel
from pytorch_distributed_training_tpu.utils.config import (
    MeshConfig,
    TrainConfig,
    model_preset,
)


def tiny_lm(**kw):
    base = dict(
        compute_dtype="float32", causal=True, type_vocab_size=0,
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    base.update(kw)
    return model_preset("tiny", **base)


def test_gpt2_forward_shape_and_tied_head():
    cfg = tiny_lm()
    model = GPT2LMModel(cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 16)), jnp.int32
    )
    params = model.init(jax.random.key(0), ids)["params"]
    logits = model.apply({"params": params}, ids)
    assert logits.shape == (2, 16, cfg.vocab_size)
    # tied head: no separate lm_head kernel in the tree
    assert "lm_head" not in params and "wte" in params


def test_gpt2_is_causal():
    """Changing a future token must not change past logits."""
    cfg = tiny_lm()
    model = GPT2LMModel(cfg)
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (1, 12)), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    out1 = model.apply({"params": params}, ids)
    ids2 = ids.at[0, 8].set((int(ids[0, 8]) + 7) % cfg.vocab_size)
    out2 = model.apply({"params": params}, ids2)
    np.testing.assert_allclose(
        np.asarray(out1[0, :8]), np.asarray(out2[0, :8]), atol=1e-5
    )
    assert not np.allclose(np.asarray(out1[0, 8:]), np.asarray(out2[0, 8:]))


def test_lm_loss_matches_manual():
    from pytorch_distributed_training_tpu.train.optim import adamw_with_schedule
    from pytorch_distributed_training_tpu.train.state import create_train_state
    from pytorch_distributed_training_tpu.train.step import make_eval_step

    cfg = tiny_lm()
    model = GPT2LMModel(cfg)
    rng = np.random.default_rng(2)
    ids = np.asarray(rng.integers(2, 200, (4, 16)), np.int32)
    batch = {
        "input_ids": jnp.asarray(ids),
        "attention_mask": jnp.ones((4, 16), jnp.int32),
    }
    tx, _ = adamw_with_schedule(TrainConfig(), 10)
    state = create_train_state(model, tx, jax.random.key(0), batch)
    counts = make_eval_step(objective="causal_lm")(state, batch)

    logits = np.asarray(
        model.apply({"params": state.params}, batch["input_ids"])
    )
    # manual shifted NLL
    tgt = ids[:, 1:]
    lp = logits[:, :-1] - jax.scipy.special.logsumexp(
        logits[:, :-1], axis=-1, keepdims=True
    )
    nll = -np.take_along_axis(np.asarray(lp), tgt[..., None], axis=-1)
    np.testing.assert_allclose(
        float(counts["nll_sum"]), nll.sum(), rtol=1e-4
    )
    assert float(counts["token_count"]) == 4 * 15


@pytest.mark.slow
def test_lm_trainer_learns_markov_chain(eight_devices):
    """End-to-end: GPT-2-tiny + FSDP mesh on the synthetic Markov corpus.
    The chain has ≈4 plausible next tokens per context (entropy ≈ ln4 with
    dirichlet skew); a model that learns it beats the 256-token uniform
    floor (ln256 ≈ 5.5) decisively."""
    from pytorch_distributed_training_tpu.parallel import ShardingPolicy
    from pytorch_distributed_training_tpu.train.loop import Trainer

    cfg = tiny_lm(scan_layers=True)
    tcfg = TrainConfig(
        num_epochs=2, global_batch_size=32, micro_batch_size=16,
        eval_batch_size=32, learning_rate=3e-3, warmup_steps=10,
        log_every=0, bf16=False, max_seq_length=32,
        train_size=1024, eval_size=128,
    )
    trainer = Trainer(
        cfg, tcfg, MeshConfig(data=2, fsdp=4),
        ShardingPolicy(fsdp=True, fsdp_min_size=128),
        task="lm",
    )
    history = trainer.run()
    assert trainer.objective == "causal_lm"
    rec = history[-1]
    assert {"eval_loss", "perplexity", "token_accuracy"} <= set(rec)
    assert rec["eval_loss"] < 4.0  # well under the uniform-over-256 floor
    assert history[-1]["eval_loss"] < history[0]["eval_loss"] + 1e-6
