"""Native WordPiece encoder: byte-identical parity with the Python encoder.

The C++ encoder (native/src/wordpiece.cpp) must reproduce
``data.tokenizer.encode_pairs`` exactly on ASCII text, route unicode rows
through the Python path, and be thread-count invariant.
"""

import numpy as np
import pytest

from pytorch_distributed_training_tpu.data.tokenizer import (
    WordPieceTokenizer,
    encode_pairs,
)
from pytorch_distributed_training_tpu.native import load_wordpiece_lib

pytestmark = pytest.mark.skipif(
    load_wordpiece_lib() is None, reason="no C++ toolchain"
)

VOCAB = [
    "[PAD]", "[UNK]", "[CLS]", "[SEP]",
    "the", "quick", "brown", "fox", "jump", "##s", "##ed", "##ing",
    "over", "lazy", "dog", "un", "##believ", "##able", ",", ".", "!", "'",
    "a", "b", "c", "1", "2", "##3",
]

TEXTS_A = [
    "the quick brown fox jumps",
    "unbelievable!",
    "the lazy dog , the fox .",
    "a b c 123",
    "zzz unknown words here",
    "",
    "the " * 200,  # forces truncation
]
TEXTS_B = [
    "the dog jumped over",
    "the fox",
    "unbelievable , a b",
    "",
    "the the the",
    "fox",
    "dog " * 200,
]


@pytest.fixture(scope="module")
def vocab_file(tmp_path_factory):
    p = tmp_path_factory.mktemp("wp") / "vocab.txt"
    p.write_text("\n".join(VOCAB) + "\n", encoding="utf-8")
    return str(p)


def test_pair_parity_with_python(vocab_file):
    from pytorch_distributed_training_tpu.data.native_tokenizer import (
        NativeWordPieceEncoder,
    )

    py = WordPieceTokenizer(vocab_file)
    want = encode_pairs(py, TEXTS_A, TEXTS_B, max_length=32)
    nat = NativeWordPieceEncoder(vocab_file)
    got = nat.encode_pairs(TEXTS_A, TEXTS_B, max_length=32)
    for k in want:
        np.testing.assert_array_equal(got[k], want[k], err_msg=k)
    # single-sentence mode too
    want1 = encode_pairs(py, TEXTS_A, None, max_length=16)
    got1 = nat.encode_pairs(TEXTS_A, None, max_length=16)
    for k in want1:
        np.testing.assert_array_equal(got1[k], want1[k], err_msg=k)
    nat.close()


def test_unicode_rows_fall_back_to_python(vocab_file):
    from pytorch_distributed_training_tpu.data.native_tokenizer import (
        NativeWordPieceEncoder,
    )

    a = ["the quick fox", "café naïve", "the dog"]
    b = ["the dog", "über fox", "lazy"]
    py = WordPieceTokenizer(vocab_file)
    want = encode_pairs(py, a, b, max_length=24)
    nat = NativeWordPieceEncoder(vocab_file)
    got = nat.encode_pairs(a, b, max_length=24)
    for k in want:
        np.testing.assert_array_equal(got[k], want[k], err_msg=k)
    nat.close()


def test_thread_count_invariance(vocab_file):
    from pytorch_distributed_training_tpu.data.native_tokenizer import (
        NativeWordPieceEncoder,
    )

    rng = np.random.default_rng(0)
    words = ["the", "quick", "fox", "jumps", "unbelievable", "zzz", "a", "1"]
    texts = [
        " ".join(rng.choice(words, rng.integers(1, 40)))
        for _ in range(257)
    ]
    one = NativeWordPieceEncoder(vocab_file, n_threads=1)
    many = NativeWordPieceEncoder(vocab_file, n_threads=8)
    x = one.encode_pairs(texts, None, max_length=48)
    y = many.encode_pairs(texts, None, max_length=48)
    for k in x:
        np.testing.assert_array_equal(x[k], y[k], err_msg=k)
    one.close()
    many.close()


def test_special_ids_match(vocab_file):
    from pytorch_distributed_training_tpu.data.native_tokenizer import (
        NativeWordPieceEncoder,
    )

    py = WordPieceTokenizer(vocab_file)
    nat = NativeWordPieceEncoder(vocab_file)
    assert (nat.pad_id, nat.unk_id, nat.cls_id, nat.sep_id) == (
        py.pad_id, py.unk_id, py.cls_id, py.sep_id
    )
    nat.close()


def test_pad_fill_when_pad_id_not_zero(tmp_path):
    """Padding must use the vocab's [PAD] id, not 0 (regression: the native
    wrapper pre-filled ids with np.zeros, diverging from the Python twin on
    any vocab where [PAD] != 0)."""
    from pytorch_distributed_training_tpu.data.native_tokenizer import (
        NativeWordPieceEncoder,
    )

    vocab = ["the", "fox", "[PAD]", "[UNK]", "[CLS]", "[SEP]", "dog"]
    p = tmp_path / "vocab_pad2.txt"
    p.write_text("\n".join(vocab) + "\n", encoding="utf-8")
    py = WordPieceTokenizer(str(p))
    nat = NativeWordPieceEncoder(str(p))
    assert nat.pad_id == 2
    # row 0: ASCII (C++ path); row 1: non-ASCII (Python fallback path) —
    # both must pad with pad_id
    a, b = ["the fox", "café fox"], ["dog", "dog"]
    ref = encode_pairs(py, a, b, max_length=16)
    got = nat.encode_pairs(a, b, max_length=16)
    for k in ("input_ids", "token_type_ids", "attention_mask"):
        np.testing.assert_array_equal(got[k], ref[k], err_msg=k)
    for row in range(2):
        pad_pos = got["attention_mask"][row] == 0
        assert (got["input_ids"][row][pad_pos] == 2).all()


def test_ascii_control_separator_parity(vocab_file):
    """\\x1c-\\x1f are whitespace to Python's \\s but not to C isspace;
    the C++ tokenizer must drop them like the Python twin (regression:
    they tokenized as [UNK])."""
    from pytorch_distributed_training_tpu.data.native_tokenizer import (
        NativeWordPieceEncoder,
    )

    py = WordPieceTokenizer(vocab_file)
    nat = NativeWordPieceEncoder(vocab_file)
    a = ["the \x1c fox", "dog\x1d\x1e\x1f", "\x1conly"]
    ref = encode_pairs(py, a, None, max_length=8)
    got = nat.encode_pairs(a, None, max_length=8)
    for k in ("input_ids", "token_type_ids", "attention_mask"):
        np.testing.assert_array_equal(got[k], ref[k], err_msg=k)


def test_max_length_too_small_raises(vocab_file):
    """max_length with no room for the specials must raise, not corrupt
    memory (regression: C++ assemble_row popped an empty vector — UB)."""
    from pytorch_distributed_training_tpu.data.native_tokenizer import (
        NativeWordPieceEncoder,
    )

    nat = NativeWordPieceEncoder(vocab_file)
    with pytest.raises(ValueError, match="special tokens"):
        nat.encode_pairs(["the"], ["fox"], max_length=2)
    with pytest.raises(ValueError, match="special tokens"):
        nat.encode_pairs(["the"], None, max_length=1)
    # per-row rule: an all-empty/whitespace b column needs only 2 specials,
    # matching the Python twin (which encodes, not raises, here)
    py = WordPieceTokenizer(vocab_file)
    ref = encode_pairs(py, ["the"], [" "], max_length=2)
    got = nat.encode_pairs(["the"], [" "], max_length=2)
    for k in ("input_ids", "token_type_ids", "attention_mask"):
        np.testing.assert_array_equal(got[k], ref[k], err_msg=k)
