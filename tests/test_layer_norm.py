"""Fused LayerNorm / dropout+add+LN kernel tests (interpret mode).

Contract: identical math to the jnp reference (fp32 stats, biased
variance); the dropout variant's in-kernel PRNG mask is deterministic per
(key, site, block) — fwd and bwd regenerate the same mask, pinned by a
finite-difference check.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.ops.dropout import mask_scale_pallas
from pytorch_distributed_training_tpu.ops.flash_attention import (
    tpu_interpret_mode,
)
from pytorch_distributed_training_tpu.ops.layer_norm import (
    dropout_add_layer_norm,
    layer_norm,
    reference_layer_norm,
)

R, H = 64, 256


def _data(seed=0, rows=R, h=H):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.normal(size=(rows, h)), jnp.float32)
    scale = jnp.asarray(rng.normal(size=(h,)) + 1.0, jnp.float32)
    bias = jnp.asarray(rng.normal(size=(h,)), jnp.float32)
    return x, scale, bias


def test_fwd_matches_reference():
    x, scale, bias = _data()
    ref = reference_layer_norm(x, scale, bias, eps=1e-12)
    with tpu_interpret_mode():
        out = layer_norm(x, scale, bias, eps=1e-12, block_r=16)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5,
                               rtol=1e-5)


def test_bwd_matches_reference():
    x, scale, bias = _data(1)
    w = jnp.asarray(np.random.default_rng(9).normal(size=(R, H)), jnp.float32)

    def loss(fn):
        return lambda x, s, b: jnp.sum(fn(x, s, b) * w)

    with tpu_interpret_mode():
        g_k = jax.grad(loss(lambda x, s, b: layer_norm(
            x, s, b, eps=1e-12, block_r=16)), argnums=(0, 1, 2))(x, scale, bias)
    g_r = jax.grad(loss(lambda x, s, b: reference_layer_norm(
        x, s, b, eps=1e-12)), argnums=(0, 1, 2))(x, scale, bias)
    for a, b, name in zip(g_k, g_r, ["dx", "dscale", "dbias"]):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4, err_msg=name
        )


def test_module_param_names_match_nn_layernorm():
    """Checkpoint/HF-layout compatibility: scale + bias, same shapes."""
    from pytorch_distributed_training_tpu.ops.layer_norm import FusedLayerNorm

    mod = FusedLayerNorm(epsilon=1e-12, param_dtype=jnp.float32,
                         out_dtype=jnp.float32, impl="reference")
    params = mod.init(jax.random.key(0), jnp.ones((2, H)))["params"]
    assert set(params) == {"scale", "bias"}
    assert params["scale"].shape == (H,)


def test_dal_deterministic_matches_add_then_ln():
    x, scale, bias = _data(2)
    h = jnp.asarray(np.random.default_rng(3).normal(size=(R, H)), jnp.float32)
    ref = reference_layer_norm(x + h, scale, bias, eps=1e-12)
    with tpu_interpret_mode():
        out = dropout_add_layer_norm(
            h, x, scale, bias, rate=0.5, deterministic=True, eps=1e-12,
            block_r=16,
        )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5,
                               rtol=1e-5)


def test_dal_dropout_determinism():
    """Interpret-mode caveat: pltpu.prng_random_bits is all-zeros off-TPU
    (every element drops), so only determinism and the dropped-vs-
    deterministic distinction are checkable here. Mask STATISTICS (keep
    fraction ~1-rate, per-site stream separation) hold on real TPU —
    verified on-chip 2026-07 (keep 0.7498 at rate 0.25, sites differ) and
    re-checkable with scripts/bench_layernorm.py-style probes.
    """
    x, scale, bias = _data(4, rows=256)
    h = jnp.ones((256, H), jnp.float32) * 3.0
    rng = jax.random.key(7)
    with tpu_interpret_mode():
        kw = dict(rate=0.25, dropout_rng=rng, deterministic=False,
                  eps=1e-12, block_r=16)
        out1 = dropout_add_layer_norm(h, x, scale, bias, site=0, **kw)
        out2 = dropout_add_layer_norm(h, x, scale, bias, site=0, **kw)
        out_det = dropout_add_layer_norm(
            h, x, scale, bias, rate=0.25, deterministic=True, eps=1e-12,
            block_r=16,
        )
    # same key + site -> bit-identical; dropout != deterministic
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    assert not np.array_equal(np.asarray(out1), np.asarray(out_det))


def test_dal_finite_difference():
    """The custom-VJP bwd (stats recompute + mask regen) against numerical
    gradients — valid because the kernel PRNG is a fixed function of
    (seed, site, block)."""
    rows, h = 16, 128
    x, scale, bias = _data(5, rows=rows, h=h)
    hh = jnp.asarray(
        np.random.default_rng(6).normal(size=(rows, h)), jnp.float32
    )
    rng = jax.random.key(3)
    w = jnp.asarray(np.random.default_rng(8).normal(size=(rows, h)),
                    jnp.float32)

    with tpu_interpret_mode():
        def f(hv):
            return jnp.sum(
                dropout_add_layer_norm(
                    hv, x, scale, bias, rate=0.3, dropout_rng=rng,
                    deterministic=False, eps=1e-12, block_r=16,
                ) * w
            )

        g = jax.grad(f)(hh)
        # directional finite difference
        rng2 = np.random.default_rng(10)
        for _ in range(3):
            d = jnp.asarray(rng2.normal(size=hh.shape), jnp.float32)
            eps_fd = 1e-3
            fd = (f(hh + eps_fd * d) - f(hh - eps_fd * d)) / (2 * eps_fd)
            an = jnp.sum(g * d)
            np.testing.assert_allclose(
                float(fd), float(an), rtol=2e-2, atol=2e-2
            )


def test_mask_scale_pallas_values_and_determinism():
    """Values are exactly {0, 1/(1-rate)} and the stream is deterministic
    per key. Keep-fraction statistics need the real TPU PRNG (interpret
    mode yields all-zero bits): verified on-chip (keep 0.7498 at rate
    0.25); asserted here only when a TPU backend is live."""
    rng = jax.random.key(11)
    with tpu_interpret_mode():
        m = mask_scale_pallas(rng, (512, 128), 0.25, jnp.float32, block_r=64)
        m2 = mask_scale_pallas(rng, (512, 128), 0.25, jnp.float32, block_r=64)
    vals = set(np.round(np.unique(np.asarray(m)), 5))
    assert vals <= {0.0, np.float32(np.round(1 / 0.75, 5))}
    np.testing.assert_array_equal(np.asarray(m), np.asarray(m2))
    if jax.default_backend() == "tpu":  # real PRNG: check the rate too
        keep = float((np.asarray(m) > 0).mean())
        assert 0.70 < keep < 0.80
