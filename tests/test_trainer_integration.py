"""Integration tests: the full Trainer end-to-end on the CPU mesh — the
convergence-check verification pattern inherited from the reference
(SURVEY.md §4: run epochs, watch the eval metric), made fast and automatic.
"""

import numpy as np
import pytest

from pytorch_distributed_training_tpu.parallel import ShardingPolicy
from pytorch_distributed_training_tpu.train.loop import Trainer
from pytorch_distributed_training_tpu.utils.config import (
    MeshConfig,
    TrainConfig,
    model_preset,
)


def small_trainer(tmp_path=None, *, task="synthetic", mcfg_kw=None, **tcfg_kw):
    mcfg = model_preset("tiny", compute_dtype="float32", **(mcfg_kw or {}))
    defaults = dict(
        num_epochs=2,
        global_batch_size=32,
        micro_batch_size=16,
        eval_batch_size=32,
        learning_rate=3e-3,
        warmup_steps=10,
        log_every=0,
        bf16=False,
        train_size=1024,
        eval_size=160,
    )
    defaults.update(tcfg_kw)
    tcfg = TrainConfig(**defaults)
    return Trainer(
        mcfg, tcfg, MeshConfig(data=4, fsdp=2),
        ShardingPolicy(fsdp=True, fsdp_min_size=128),
        task=task,
    )


@pytest.fixture(scope="module")
def trained(eight_devices):
    """Full 2-epoch learning run — backs the (slow) convergence test."""
    trainer = small_trainer()
    history = trainer.run()
    return trainer, history


@pytest.fixture(scope="module")
def mini_trained(eight_devices):
    """A cheap trained state for checkpoint plumbing tests (one short
    epoch; nothing about learning quality is asserted off this)."""
    trainer = small_trainer(num_epochs=1, train_size=128, eval_size=32)
    history = trainer.run()
    return trainer, history


@pytest.mark.slow
def test_typefree_model_learns_multiclass_synthetic(eight_devices):
    """A model WITHOUT usable token-type embeddings (RoBERTa's single-row
    type table) must learn the 3-class synthetic task well above chance —
    pins the type-id-free marker cue (data/synthetic.py): the round-4
    form of the task was unlearnable-by-construction for this layout
    (NOTES.md bisect), which left the MNLI recipe flat at 1/3."""
    trainer = small_trainer(
        task="mnli",  # zero-egress image -> 3-class synthetic fallback
        mcfg_kw=dict(
            type_vocab_size=1, roberta_style=True, pad_token_id=1
        ),
        max_seq_length=64,
    )
    history = trainer.run()
    assert trainer.mcfg.num_labels == 3
    final = history[-1]
    assert final["accuracy"] > 0.55, history  # chance = 1/3
    # both MNLI validation splits evaluated, both learnable
    assert final["accuracy_mismatched"] > 0.55, history


@pytest.mark.slow
def test_trainer_learns_and_reports(trained):
    trainer, history = trained
    assert len(history) == 2
    for rec in history:
        assert {"epoch", "train_loss", "samples_per_sec",
                "samples_per_sec_per_chip", "accuracy", "f1"} <= set(rec)
    assert history[-1]["train_loss"] < history[0]["train_loss"] + 0.02
    assert history[-1]["accuracy"] > 0.55  # better than chance on eval split
    assert history[-1]["samples_per_sec_per_chip"] > 0


@pytest.mark.slow
def test_midepoch_resume_continues_trajectory(eight_devices, tmp_path):
    """A run that checkpoints mid-epoch and resumes must land on the same
    final step count and params as an uninterrupted run (no batch trained
    twice, LR schedule on course)."""
    import jax

    d = str(tmp_path / "mid")
    kw = dict(num_epochs=1, train_size=256, eval_size=32)
    # uninterrupted run: 8 updates
    full = small_trainer(**kw)
    full.run()
    full_steps = int(jax.device_get(full.state.step))
    assert full_steps == 8

    # interrupted: checkpoint every 3 steps, pretend crash after step 6 by
    # restoring the step-6 checkpoint into a resuming trainer
    part = small_trainer(checkpoint_dir=d, checkpoint_every_steps=3, **kw)
    part.run()
    resumed = small_trainer(checkpoint_dir=d, resume=True, **kw)
    resumed.state = resumed.checkpointer.restore(resumed.state, step=6)
    resumed.run()
    assert int(jax.device_get(resumed.state.step)) == full_steps
    a = np.concatenate(
        [np.ravel(jax.device_get(x)) for x in jax.tree.leaves(full.state.params)]
    )
    b = np.concatenate(
        [np.ravel(jax.device_get(x)) for x in jax.tree.leaves(resumed.state.params)]
    )
    np.testing.assert_allclose(a, b, atol=1e-5)


def test_checkpoint_save_restore_resume(mini_trained, tmp_path):
    import jax

    from pytorch_distributed_training_tpu.train import checkpoint as ckpt

    trainer, _ = mini_trained
    d = str(tmp_path / "ckpt")
    ckpt.save_checkpoint(d, trainer.state)
    step = ckpt.latest_step(d)
    assert step == int(jax.device_get(trainer.state.step))

    # fresh trainer restores the exact state
    fresh = small_trainer()
    assert int(jax.device_get(fresh.state.step)) == 0
    restored = ckpt.restore_checkpoint(d, fresh.state)
    assert int(jax.device_get(restored.step)) == step
    a = np.concatenate(
        [np.ravel(jax.device_get(x)) for x in jax.tree.leaves(trainer.state.params)]
    )
    b = np.concatenate(
        [np.ravel(jax.device_get(x)) for x in jax.tree.leaves(restored.params)]
    )
    np.testing.assert_array_equal(a, b)


def test_checkpoint_restore_across_prng_impl(mini_trained, tmp_path):
    """A checkpoint saved under one dropout-PRNG impl restores under another:
    params/opt_state/step carry over, the key falls back to the fresh one
    with a warning instead of a shape-mismatch crash (the key stream itself
    cannot carry across impls — different word sizes)."""
    import jax

    from pytorch_distributed_training_tpu.train import checkpoint as ckpt

    trainer, _ = mini_trained
    d = str(tmp_path / "ckpt_impl")
    ckpt.save_checkpoint(d, trainer.state)

    other_impl = (
        "threefry2x32"
        if jax.random.key_data(trainer.state.dropout_rng).shape[-1] != 2
        else "rbg"
    )
    fresh = small_trainer(prng_impl=other_impl)
    restored = ckpt.restore_checkpoint(d, fresh.state)
    assert int(jax.device_get(restored.step)) == int(
        jax.device_get(trainer.state.step)
    )
    a = np.concatenate(
        [np.ravel(jax.device_get(x)) for x in jax.tree.leaves(trainer.state.params)]
    )
    b = np.concatenate(
        [np.ravel(jax.device_get(x)) for x in jax.tree.leaves(restored.params)]
    )
    np.testing.assert_array_equal(a, b)
    # the fresh impl's key survives untouched
    assert (
        jax.random.key_data(restored.dropout_rng).shape
        == jax.random.key_data(fresh.state.dropout_rng).shape
    )


def test_mnli_evaluates_both_validation_splits(eight_devices):
    """MNLI's standard eval covers matched AND mismatched validation
    (VERDICT r2 #7). Offline this exercises the synthetic fallback with
    3 labels and two distinct eval splits; metric keys carry both the
    unprefixed (primary) and per-split names."""
    mcfg = model_preset("tiny", compute_dtype="float32")
    tcfg = TrainConfig(
        num_epochs=1, global_batch_size=32, micro_batch_size=16,
        eval_batch_size=32, log_every=0, bf16=False,
        train_size=64, eval_size=32,
    )
    trainer = Trainer(
        mcfg, tcfg, MeshConfig(data=4, fsdp=2),
        ShardingPolicy(fsdp=True, fsdp_min_size=128),
        task="mnli",
    )
    assert trainer.mcfg.num_labels == 3
    assert set(trainer.eval_loaders) == {"matched", "mismatched"}
    history = trainer.run()
    rec = history[-1]
    assert {"accuracy", "accuracy_matched", "accuracy_mismatched"} <= set(rec)
    assert rec["accuracy"] == rec["accuracy_matched"]
    assert 0.0 <= rec["accuracy_mismatched"] <= 1.0


def test_checkpoint_restore_across_topologies(mini_trained, tmp_path):
    """VERDICT r2 #8: a checkpoint written under one mesh/policy restores
    under a different one. ``mini_trained`` saves from a data=4 x fsdp=2
    param-sharded state; a pure-DP (data=8, replicated params) trainer must
    restore it bit-exactly, re-place every leaf on ITS shardings, and
    continue training — the "resume on any compatible mesh" contract in
    train/checkpoint.py's docstring."""
    import jax

    from pytorch_distributed_training_tpu.parallel import state_shardings
    from pytorch_distributed_training_tpu.train import checkpoint as ckpt

    trainer, _ = mini_trained
    d = str(tmp_path / "ckpt_topo")
    ckpt.save_checkpoint(d, trainer.state)

    mcfg = model_preset("tiny", compute_dtype="float32")
    tcfg = TrainConfig(
        num_epochs=1, global_batch_size=32, micro_batch_size=16,
        eval_batch_size=32, log_every=0, bf16=False,
        train_size=128, eval_size=32,
    )
    dp = Trainer(
        mcfg, tcfg, MeshConfig(data=8), ShardingPolicy(), task="synthetic"
    )
    assert dp.mesh.shape != trainer.mesh.shape  # genuinely different meshes
    restored = ckpt.restore_checkpoint(d, dp.state)

    # bit-exact params across the topology change
    a = np.concatenate(
        [np.ravel(jax.device_get(x))
         for x in jax.tree.leaves(trainer.state.params)]
    )
    b = np.concatenate(
        [np.ravel(jax.device_get(x)) for x in jax.tree.leaves(restored.params)]
    )
    np.testing.assert_array_equal(a, b)
    # every leaf landed on the DP trainer's shardings (replicated params)
    for want, got in zip(
        jax.tree.leaves(dp.shardings.params), jax.tree.leaves(restored.params)
    ):
        assert got.sharding.is_equivalent_to(want, got.ndim)
    # and training continues from the restored state on the new mesh
    dp.state = restored
    step_before = int(jax.device_get(dp.state.step))
    batch = next(iter(dp.train_loader.epoch(0)))
    dp.state, metrics = dp.train_step(dp.state, batch)
    assert int(jax.device_get(dp.state.step)) == step_before + 1
    assert np.isfinite(float(jax.device_get(metrics["loss"])))


@pytest.mark.slow
def test_trainer_chain_steps_matches_per_step(eight_devices):
    """--chain-steps N (one dispatch per N optimizer updates) must walk the
    exact per-step trajectory: same final params, same eval metrics. Pins
    the Trainer wiring on top of the step-level parity test
    (test_train.py::test_chained_steps_match_per_step)."""
    import jax

    t1 = small_trainer(num_epochs=1, train_size=128, eval_size=32)
    h1 = t1.run()
    t2 = small_trainer(num_epochs=1, train_size=128, eval_size=32,
                       chain_steps=2)
    h2 = t2.run()
    assert int(jax.device_get(t1.state.step)) == int(
        jax.device_get(t2.state.step)
    )
    a = np.concatenate(
        [np.ravel(jax.device_get(x)) for x in jax.tree.leaves(t1.state.params)]
    )
    b = np.concatenate(
        [np.ravel(jax.device_get(x)) for x in jax.tree.leaves(t2.state.params)]
    )
    np.testing.assert_allclose(a, b, atol=2e-5)
    assert h1[0]["accuracy"] == pytest.approx(h2[0]["accuracy"], abs=1e-6)


def test_trainer_chain_steps_cadence_validation(eight_devices):
    """chain_steps must divide steps_per_epoch and the checkpoint cadence —
    a chain crossing an epoch would tear the per-epoch eval contract."""
    with pytest.raises(ValueError, match="chain_steps"):
        small_trainer(num_epochs=1, train_size=96, eval_size=32,
                      chain_steps=2)  # 3 updates/epoch, not divisible
