"""Quantized serving tests (serve/engine.py precision variants,
ops/quant.py serve-param quantization, ops/paged_attention.py int8 pools,
serve/hotswap.py variant-stamped publish): weight-only int8 greedy streams
bit-identical to fp32 on the snapped grid (and to one-shot generate()),
int8-KV accuracy bands at the ops and engine levels, allocator/admission
arithmetic invariant under pool dtype, tp=2 int8 bit-equal to tp=1 int8
with sharded scale pools, the strict-guard fp32<->int8 live-swap drill
(zero failed requests, zero retraces, variant recorded), scale-pool and
config validation in the named-axis error style, and the variant-stamped
publish -> load_swap_params roundtrip. Tier-1 except the perf-marked
BENCH_int8 gate.
"""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.models.generate import generate
from pytorch_distributed_training_tpu.models.gpt2 import GPT2LMModel
from pytorch_distributed_training_tpu.ops.paged_attention import (
    paged_attention,
)
from pytorch_distributed_training_tpu.ops.quant import (
    dequantize_serve_params,
    quantize_kv,
    quantize_serve_params,
    serve_params_variant,
)
from pytorch_distributed_training_tpu.serve import (
    EngineConfig,
    InferenceServer,
)
from pytorch_distributed_training_tpu.serve.server import wait_until
from pytorch_distributed_training_tpu.utils.config import model_preset

pytestmark = [pytest.mark.serve]

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# gpt2-tiny: 2 layers, hidden 64, 4 heads of head_dim 16
LAYERS, HIDDEN, HEADS, HEAD_DIM = 2, 64, 4, 16


class ListSink:
    """In-memory telemetry sink (same contract as JsonlSink.emit)."""

    def __init__(self):
        self.records = []

    def emit(self, record):
        rec = dict(record)
        rec.setdefault("ts", time.time())
        self.records.append(rec)

    def flush(self, **kw):
        pass

    def of(self, kind):
        return [r for r in self.records if r.get("record") == kind]


@pytest.fixture(scope="module")
def lm():
    cfg = model_preset(
        "gpt2-tiny", compute_dtype="float32", attention_impl="reference",
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    model = GPT2LMModel(cfg)
    params = model.init(jax.random.key(0), jnp.ones((2, 16), jnp.int32))[
        "params"
    ]
    return model, params


@pytest.fixture(scope="module")
def snapped(lm):
    """fp32 weights snapped onto the int8 grid: quantization is idempotent
    on this tree, so an fp32 engine and a weight-int8 engine run
    numerically IDENTICAL projection weights."""
    _, params = lm
    return dequantize_serve_params(quantize_serve_params(params))


def _registry():
    from pytorch_distributed_training_tpu.telemetry.registry import (
        MetricsRegistry,
    )

    reg = MetricsRegistry()
    sink = ListSink()
    reg.attach_sink(sink)
    return reg, sink


def _prompts(model, lengths, seed=0):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(1, model.config.vocab_size, n).astype(np.int32)
        for n in lengths
    ]


def _run_server(model, params, prompts, T, *, guards=None, registry=None,
                **cfg_kw):
    reg, sink = (registry, None) if registry is not None else _registry()
    cfg_kw.setdefault("prompt_buckets", (4, 8, 16))
    server = InferenceServer(
        model, params,
        EngineConfig(
            num_slots=2, max_new_tokens=T, kv_layout="paged",
            sampling="device", page_size=4, **cfg_kw,
        ),
        queue_depth=16, registry=reg, guards=guards,
    ).start()
    try:
        reqs = [
            server.submit(p, max_new_tokens=T, seed=i)
            for i, p in enumerate(prompts)
        ]
        assert wait_until(
            lambda: all(r.done.is_set() for r in reqs), timeout=120
        ), [r.status for r in reqs]
    finally:
        server.close()
    assert all(r.status == "done" for r in reqs), [r.status for r in reqs]
    toks = [np.asarray(r.tokens, np.int32) for r in reqs]
    return toks, server.stats(), sink


# ----------------------------------------------------- weight-only int8


def test_weight_only_int8_greedy_bit_identical_on_snapped_grid(lm, snapped):
    """The losslessness pin: with weights on the int8 grid, the weight-only
    int8 engine's greedy streams are bit-identical to the fp32 engine's AND
    to one-shot generate() — weight quantization is a storage change, not a
    numerics change, once the grid is shared."""
    model, _ = lm
    T = 6
    prompts = _prompts(model, [3, 6, 9, 14, 5], seed=7)
    want = [
        np.asarray(generate(model, snapped, p[None], max_new_tokens=T))[
            0, len(p):
        ]
        for p in prompts
    ]
    fp, stats_fp, _ = _run_server(model, snapped, prompts, T)
    q, stats_q, _ = _run_server(
        model, snapped, prompts, T, weights_dtype="int8",
    )
    for i, (a, b, ref) in enumerate(zip(fp, q, want)):
        np.testing.assert_array_equal(a, ref, err_msg=f"request {i} (fp32)")
        np.testing.assert_array_equal(b, ref, err_msg=f"request {i} (int8)")
    assert stats_fp["variant"] == "fp32"
    assert stats_q["variant"] == "int8"
    assert stats_q["weights_dtype"] == "int8"
    assert stats_q["kv_dtype"] == "float32"


def test_weight_only_int8_resident_tree_halves_projection_bytes(lm):
    """quantize_serve_params rewrites every attention/MLP projection to an
    int8 kernel + fp32 per-output-channel kernel_scale; the projection
    bytes land near 1/4 of fp32 (int8 elements + one fp32 scale per
    channel) and dequantize_serve_params is the exact inverse on the
    snapped grid."""
    from pytorch_distributed_training_tpu.ops.quant import (
        _SERVE_QUANT_MODULES,
    )

    _, params = lm
    q = quantize_serve_params(params)
    assert serve_params_variant(q) == "int8"
    assert serve_params_variant(params) == "fp32"

    def proj_bytes(tree):
        total = 0
        for path, leaf in jax.tree_util.tree_leaves_with_path(tree):
            names = {getattr(k, "key", None) for k in path}
            if names & set(_SERVE_QUANT_MODULES):
                total += int(leaf.size) * leaf.dtype.itemsize
        return total

    ratio = proj_bytes(q) / proj_bytes(params)
    assert ratio < 0.5, ratio
    # idempotent snap: quantizing the dequantized tree reproduces it
    snap = dequantize_serve_params(q)
    q2 = quantize_serve_params(snap)
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_leaves_with_path(q),
        jax.tree_util.tree_leaves_with_path(q2),
    ):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# ------------------------------------------------------------- int8 KV


def test_int8_kv_ops_tolerance_band():
    """Both paged_attention impls dequantize int8 pools in-kernel within a
    tight band of the fp32 pools (symmetric per-page-per-head absmax keeps
    the relative error ~1/127), and the pallas page-walk kernel matches the
    reference on the SAME int8 pools to float tolerance."""
    from pytorch_distributed_training_tpu.ops.flash_attention import (
        tpu_interpret_mode,
    )

    rng = np.random.default_rng(0)
    P, S, B = 6, 4, 3
    k = jnp.asarray(rng.normal(size=(P, S, HEADS, HEAD_DIM)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(P, S, HEADS, HEAD_DIM)), jnp.float32)
    q = jnp.asarray(rng.normal(size=(B, HEADS, HEAD_DIM)), jnp.float32)
    bt = jnp.asarray([[1, 2, 0], [3, 4, 5], [2, 0, 0]], jnp.int32)
    lengths = jnp.asarray([6, 11, 3], jnp.int32)
    scale = HEAD_DIM ** -0.5

    kq, ks = quantize_kv(k)
    vq, vs = quantize_kv(v)
    assert kq.dtype == jnp.int8 and ks.shape == (P, S, HEADS)

    exact = paged_attention(q, k, v, bt, lengths, scale=scale,
                            impl="reference")
    ref8 = paged_attention(q, kq, vq, bt, lengths, scale=scale,
                           impl="reference", k_scales=ks, v_scales=vs)
    assert ref8.dtype == jnp.float32
    band = float(jnp.max(jnp.abs(ref8 - exact)))
    assert band < 0.05, band
    with tpu_interpret_mode():
        pl8 = paged_attention(q, kq, vq, bt, lengths, scale=scale,
                              impl="pallas", k_scales=ks, v_scales=vs)
    np.testing.assert_allclose(
        np.asarray(pl8), np.asarray(ref8), atol=1e-5, rtol=1e-5,
    )


def test_int8_kv_serving_band_and_pool_accounting(lm, snapped):
    """The int8-KV engine serves the same greedy answers at this scale
    (first token exact by construction: prefill attends the in-flight fp32
    K/V before quantize-on-write) while the allocator stays dtype-blind —
    identical page capacity and page size — and kv_bytes_per_token drops
    to head_dim+4 bytes per head lane."""
    model, _ = lm
    T = 8
    prompts = _prompts(model, [3, 6, 9, 14], seed=5)
    fp, stats_fp, _ = _run_server(
        model, snapped, prompts, T, weights_dtype="int8",
    )
    q8, stats_q8, _ = _run_server(
        model, snapped, prompts, T, weights_dtype="int8", kv_dtype="int8",
    )
    agree = total = 0
    for i, (a, b) in enumerate(zip(fp, q8)):
        assert a[0] == b[0], f"request {i}: first token drifted"
        agree += int((a == b).sum())
        total += len(a)
    assert agree / total >= 0.8, (agree, total)

    # allocator arithmetic is pool-dtype-invariant
    assert stats_fp["kv_pages_total"] == stats_q8["kv_pages_total"]
    assert stats_fp["kv_page_size"] == stats_q8["kv_page_size"]
    assert stats_fp["page_exhausted"] == stats_q8["page_exhausted"] == 0
    # int8 KV: 1 byte per element + 4 fp32-scale bytes per head lane
    assert stats_fp["kv_bytes_per_token"] == (
        2 * LAYERS * HEADS * HEAD_DIM * 4
    )
    assert stats_q8["kv_bytes_per_token"] == (
        2 * LAYERS * HEADS * (HEAD_DIM + 4)
    )


def test_int8_kv_pool_leaves_are_int8_with_fp32_scales(lm):
    """The resident cache of an int8-KV engine holds int8 rank-4 page
    pools and fp32 rank-3 scale pools of the matching leading shape."""
    model, params = lm
    server = InferenceServer(
        model, params,
        EngineConfig(
            num_slots=2, prompt_buckets=(8,), max_new_tokens=4,
            kv_layout="paged", sampling="device", page_size=4,
            weights_dtype="int8", kv_dtype="int8",
        ),
    )
    pools = [x for x in jax.tree.leaves(server.engine._cache) if x.ndim == 4]
    scales = [x for x in jax.tree.leaves(server.engine._cache) if x.ndim == 3]
    assert pools and scales and len(pools) == len(scales)
    for pool, sc in zip(pools, scales):
        assert pool.dtype == jnp.int8
        assert sc.dtype == jnp.float32
        assert sc.shape == pool.shape[:3]


# ------------------------------------------------------- tensor parallel


@pytest.mark.tp
def test_tp2_int8_bit_identical_to_tp1_int8(lm, snapped):
    """Quantization composes with head sharding: the tp=2 full-int8 engine
    emits bit-identical greedy streams to the tp=1 full-int8 engine, the
    kernel_scale leaves shard with their kernel's channel axis, and the
    rank-3 scale pools shard on the head axis like their page pools."""
    from pytorch_distributed_training_tpu.parallel.sharding import (
        serve_pool_pspec,
    )

    model, _ = lm
    T = 6
    prompts = _prompts(model, [3, 6, 9, 14, 5], seed=11)
    kw = dict(weights_dtype="int8", kv_dtype="int8")
    tp1, _, _ = _run_server(model, snapped, prompts, T, tp=1, **kw)

    reg, _ = _registry()
    server = InferenceServer(
        model, snapped,
        EngineConfig(
            num_slots=2, prompt_buckets=(4, 8, 16), max_new_tokens=T,
            kv_layout="paged", sampling="device", page_size=4, tp=2, **kw,
        ),
        queue_depth=16, registry=reg,
    ).start()
    try:
        reqs = [
            server.submit(p, max_new_tokens=T, seed=i)
            for i, p in enumerate(prompts)
        ]
        assert wait_until(
            lambda: all(r.done.is_set() for r in reqs), timeout=120
        )
        for i, (a, r) in enumerate(zip(tp1, reqs)):
            np.testing.assert_array_equal(
                a, np.asarray(r.tokens, np.int32), err_msg=f"request {i}"
            )
        scale_pools = [
            x for x in jax.tree.leaves(server.engine._cache) if x.ndim == 3
        ]
        assert scale_pools
        for sc in scale_pools:
            assert sc.sharding.spec == serve_pool_pspec(3)
            shard = sc.sharding.shard_shape(sc.shape)
            assert shard[2] == HEADS // 2
    finally:
        server.close()


# ------------------------------------------------- live variant swapping


def test_strict_fp32_int8_swap_drill_zero_retrace(lm, snapped):
    """The fleet-rollback drill: an int8 replica under strict guards takes
    a live swap from an fp32-published tree mid-load. The engine coerces
    the incoming tree to its resident variant, so the warm programs' input
    dtypes never change: zero failed requests, zero retraces, zero
    implicit transfers, the swap record names the incoming variant, and
    post-swap streams equal serving the new weights from scratch."""
    from pytorch_distributed_training_tpu.analysis.guards import GuardSet

    model, _ = lm
    pB = jax.tree.map(lambda x: x + 0.01 * jnp.sign(x + 0.5), snapped)
    reg, sink = _registry()
    gs = GuardSet(mode="strict", registry=reg)
    server = InferenceServer(
        model, snapped,
        EngineConfig(
            num_slots=2, prompt_buckets=(4, 8), max_new_tokens=4,
            kv_layout="paged", sampling="device", page_size=4,
            warmup=True, weights_dtype="int8", kv_dtype="int8",
        ),
        queue_depth=16, registry=reg, guards=gs, weights_step=1,
    ).start()
    try:
        prompts = _prompts(model, [3, 6, 2, 7], seed=4)
        reqs = [
            server.submit(p, max_new_tokens=4, seed=i)
            for i, p in enumerate(prompts)
        ]
        assert wait_until(
            lambda: all(r.done.is_set() for r in reqs), timeout=120
        )
        assert all(r.status == "done" for r in reqs)
        ticket = server.engine.request_swap(pB, 2)  # fp32 tree, int8 engine
        assert ticket.done.wait(30) and ticket.ok
        prompt = _prompts(model, [5], seed=9)[0]
        r_post = server.submit(prompt, max_new_tokens=4)
        assert wait_until(r_post.done.is_set, timeout=120)
        assert r_post.status == "done"
    finally:
        server.close()

    # the engine stays int8-resident; pB answers on ITS snapped grid
    snapB = dequantize_serve_params(quantize_serve_params(pB))
    want = np.asarray(
        generate(model, snapB, prompt[None], max_new_tokens=4)
    )[0, len(prompt):]
    np.testing.assert_array_equal(np.asarray(r_post.tokens), want)

    stats = server.stats()
    assert stats["variant"] == "int8" and stats["weights_step"] == 2
    assert stats["swaps"] == 1 and stats["swap_rollbacks"] == 0
    assert stats["guard_recompiles"] == 0
    assert stats["guard_implicit_transfers"] == 0
    assert not sink.of("recompile") and not sink.of("implicit_transfer")
    (applied,) = sink.of("swap_applied")
    assert applied["variant"] == "fp32"   # the admitted cross-variant swap
    (committed,) = sink.of("swap_committed")
    assert committed["variant"] == "fp32"


def test_publish_variant_roundtrip_and_cross_variant_restore(lm, tmp_path):
    """publish_params_checkpoint(variant=) converts and stamps the sealed
    manifest; load_swap_params restores a matching-variant step partially
    and a cross-variant step whole (different treedef), handing back the
    published tree for the engine to coerce."""
    from pytorch_distributed_training_tpu.serve.hotswap import (
        load_swap_params,
        publish_params_checkpoint,
        read_manifest,
    )

    _, params = lm
    d = str(tmp_path / "pub")
    publish_params_checkpoint(d, 1, params, variant="int8")
    publish_params_checkpoint(d, 2, params, variant="fp32")
    man1 = read_manifest(os.path.join(d, "1"))
    man2 = read_manifest(os.path.join(d, "2"))
    assert man1["variant"] == "int8" and man2["variant"] == "fp32"

    # fp32 replica pulling the int8 step: whole-tree cross-variant restore
    got1 = load_swap_params(d, 1, current_params=params)
    assert serve_params_variant(got1) == "int8"
    # int8 replica pulling the fp32 step: the other direction
    got2 = load_swap_params(
        d, 2, current_params=quantize_serve_params(params)
    )
    assert serve_params_variant(got2) == "fp32"
    np.testing.assert_allclose(
        np.asarray(jax.tree.leaves(got2)[0]),
        np.asarray(jax.tree.leaves(params)[0]),
    )


def test_publish_rejects_unknown_variant(lm, tmp_path):
    from pytorch_distributed_training_tpu.serve.hotswap import (
        publish_params_checkpoint,
    )

    _, params = lm
    with pytest.raises(ValueError, match="variant"):
        publish_params_checkpoint(
            str(tmp_path / "bad"), 1, params, variant="bf16",
        )


# ------------------------------------------------------------ validation


def test_engine_config_rejects_bad_dtypes():
    with pytest.raises(ValueError, match="weights_dtype must be"):
        EngineConfig(
            num_slots=2, prompt_buckets=(8,), max_new_tokens=4,
            kv_layout="paged", sampling="device", weights_dtype="bf16",
        )
    with pytest.raises(ValueError, match="kv_dtype must be"):
        EngineConfig(
            num_slots=2, prompt_buckets=(8,), max_new_tokens=4,
            kv_layout="paged", sampling="device", kv_dtype="int4",
        )
    with pytest.raises(ValueError, match=r"requires kv_layout='paged'"):
        EngineConfig(
            num_slots=2, prompt_buckets=(8,), max_new_tokens=4,
            kv_layout="dense", sampling="host", kv_dtype="int8",
        )


def test_scale_pool_validation_named_axes():
    """The ops contract fires at trace time with named axes: missing
    scales, rank/shape/dtype mismatches, and scales alongside fp32 pools
    are all rejected before any kernel runs."""
    rng = np.random.default_rng(1)
    P, S = 4, 4
    k = jnp.asarray(rng.normal(size=(P, S, HEADS, HEAD_DIM)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(P, S, HEADS, HEAD_DIM)), jnp.float32)
    q = jnp.asarray(rng.normal(size=(2, HEADS, HEAD_DIM)), jnp.float32)
    bt = jnp.asarray([[1, 0], [2, 3]], jnp.int32)
    lengths = jnp.asarray([3, 7], jnp.int32)
    kq, ks = quantize_kv(k)
    vq, vs = quantize_kv(v)
    kw = dict(scale=1.0, impl="reference")

    with pytest.raises(ValueError, match="k_scales is missing"):
        paged_attention(q, kq, vq, bt, lengths, v_scales=vs, **kw)
    with pytest.raises(
        ValueError, match=r"page_size \(axis 1\): got 2, want 4"
    ):
        paged_attention(q, kq, vq, bt, lengths,
                        k_scales=ks[:, :2], v_scales=vs, **kw)
    with pytest.raises(ValueError, match="must be float32"):
        paged_attention(q, kq, vq, bt, lengths,
                        k_scales=ks.astype(jnp.float16), v_scales=vs, **kw)
    with pytest.raises(ValueError, match="int8 pages only"):
        paged_attention(q, k, v, bt, lengths, k_scales=ks, v_scales=vs, **kw)


# ------------------------------------------------------------ perf gate


@pytest.mark.perf
def test_int8_bench_gate(tmp_path):
    """bench.py --int8: weight-only int8 must stream bit-identically to
    fp32 on the snapped grid at <=0.5x resident projection-weight bytes
    and throughput parity (>=0.9x — the tiny-model CPU A/B prices the
    dequant epilogue but none of the HBM-bandwidth win the halved weight
    bytes buy on an accelerator), and the pool-bytes-matched int8 KV pool
    must hold >=1.9x the concurrent contexts with zero page-exhausted
    rejections while serving 2x the slots — the PR's acceptance gate."""
    out = tmp_path / "BENCH_int8.json"
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO_ROOT, "bench.py"),
            "--int8", "--int8-out", str(out),
        ],
        capture_output=True, text=True, timeout=1200, cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    result = json.loads(out.read_text())

    assert result["weight_only_streams_identical"] is True, (
        result["stream_digests"]
    )
    assert result["weight_bytes_ratio"] <= 0.5
    assert result["tokens_per_s_ratio_weight_only"] >= 0.9
    assert result["max_logit_drift"] < 0.1
    assert result["kv_contexts_ratio"] >= 1.9
    assert result["kv_capacity_page_exhausted"] == {"fp32": 0, "int8": 0}
    cap = result["int8_kv_capacity"]
    assert cap["variant"] == "int8" and cap["kv_dtype"] == "int8"
    assert cap["kv_bytes_per_token"] == 2 * LAYERS * HEADS * (HEAD_DIM + 4)
    assert result["weight_kv_int8_spec"]["spec_accept_rate"] > 0
