"""Model tests: shape/dtype checks plus numerical parity against the torch
HF implementation the reference uses (random-init from config — no network).

The parity test is the framework's strongest correctness anchor: if our flax
BERT matches torch's BertForSequenceClassification logits on the same
weights, the entire encoder stack (embeddings, attention, MLP, LayerNorm,
pooler, classifier) is bit-for-bit equivalent modulo float tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.models import BertForSequenceClassification
from pytorch_distributed_training_tpu.models.hf_loader import load_bert_classifier
from pytorch_distributed_training_tpu.utils.config import ModelConfig, model_preset


def tiny_cfg(**kw):
    return model_preset("tiny", compute_dtype="float32", **kw)


def test_forward_shapes_and_dtype():
    cfg = tiny_cfg()
    model = BertForSequenceClassification(cfg)
    ids = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    logits = model.apply({"params": params}, ids)
    assert logits.shape == (2, cfg.num_labels)
    assert logits.dtype == jnp.float32


def test_bf16_policy_params_stay_fp32():
    cfg = model_preset("tiny")  # default compute bf16
    model = BertForSequenceClassification(cfg)
    ids = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    dtypes = {x.dtype for x in jax.tree.leaves(params)}
    assert dtypes == {jnp.dtype(jnp.float32)}, f"params must be fp32, got {dtypes}"
    logits = model.apply({"params": params}, ids)
    assert logits.dtype == jnp.float32  # head promotes to fp32


def test_attention_mask_changes_output():
    cfg = tiny_cfg()
    model = BertForSequenceClassification(cfg)
    ids = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    full = model.apply({"params": params}, ids, jnp.ones((2, 16), jnp.int32))
    half_mask = jnp.concatenate(
        [jnp.ones((2, 8), jnp.int32), jnp.zeros((2, 8), jnp.int32)], axis=1
    )
    half = model.apply({"params": params}, ids, half_mask)
    assert not np.allclose(np.asarray(full), np.asarray(half))


def test_dropout_rng_determinism():
    cfg = tiny_cfg()
    model = BertForSequenceClassification(cfg)
    ids = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    rng = jax.random.key(1)
    a = model.apply({"params": params}, ids, deterministic=False,
                    rngs={"dropout": rng})
    b = model.apply({"params": params}, ids, deterministic=False,
                    rngs={"dropout": rng})
    c = model.apply({"params": params}, ids, deterministic=False,
                    rngs={"dropout": jax.random.key(2)})
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.allclose(np.asarray(a), np.asarray(c))


@pytest.mark.parametrize("roberta", [False, True])
def test_parity_with_torch_hf(roberta):
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    torch.manual_seed(0)

    if roberta:
        hf_cfg = transformers.RobertaConfig(
            vocab_size=512, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=128,
            max_position_embeddings=66, type_vocab_size=1,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
            num_labels=3, pad_token_id=1, layer_norm_eps=1e-5,
        )
        hf_model = transformers.RobertaForSequenceClassification(hf_cfg)
        cfg = ModelConfig(
            vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
            intermediate_size=128, max_position_embeddings=66,
            type_vocab_size=1, num_labels=3, roberta_style=True,
            pad_token_id=1, layer_norm_eps=1e-5, hidden_dropout=0.0,
            attention_dropout=0.0, compute_dtype="float32",
        )
    else:
        hf_cfg = transformers.BertConfig(
            vocab_size=512, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=128,
            max_position_embeddings=64, type_vocab_size=2,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
            num_labels=2,
        )
        hf_model = transformers.BertForSequenceClassification(hf_cfg)
        cfg = ModelConfig(
            vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
            intermediate_size=128, max_position_embeddings=64,
            type_vocab_size=2, num_labels=2, hidden_dropout=0.0,
            attention_dropout=0.0, compute_dtype="float32",
        )
    hf_model.eval()

    rng = np.random.default_rng(0)
    ids = rng.integers(5, 500, size=(3, 20))
    mask = np.ones((3, 20), np.int64)
    mask[:, 15:] = 0
    if roberta:
        ids = np.where(mask, ids, 1)  # pad token

    with torch.no_grad():
        kwargs = dict(
            input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask)
        )
        expected = hf_model(**kwargs).logits.numpy()

    params = load_bert_classifier(hf_model, cfg)
    model = BertForSequenceClassification(cfg)
    got = model.apply(
        {"params": params},
        jnp.asarray(ids, jnp.int32),
        jnp.asarray(mask, jnp.int32),
    )
    np.testing.assert_allclose(np.asarray(got), expected, atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("scan_layers", [False, True])
def test_gpt2_parity_with_torch_hf(scan_layers):
    """load_gpt2_lm maps an HF GPT2LMHeadModel (Conv1D [in,out] weights,
    fused c_attn, tied head) onto GPT2LMModel bit-for-bit at fp32 — for the
    python-loop trunk AND the scan-stacked trunk."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    torch.manual_seed(0)

    from pytorch_distributed_training_tpu.models.gpt2 import GPT2LMModel
    from pytorch_distributed_training_tpu.models.hf_loader import load_gpt2_lm

    hf_cfg = transformers.GPT2Config(
        vocab_size=512, n_positions=64, n_embd=64, n_layer=2, n_head=4,
        n_inner=128, resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
        layer_norm_epsilon=1e-5,
    )
    hf_model = transformers.GPT2LMHeadModel(hf_cfg)
    hf_model.eval()

    cfg = ModelConfig(
        vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
        intermediate_size=128, max_position_embeddings=64,
        type_vocab_size=0, causal=True, layer_norm_eps=1e-5,
        hidden_dropout=0.0, attention_dropout=0.0,
        compute_dtype="float32", scan_layers=scan_layers,
    )

    rng = np.random.default_rng(1)
    ids = rng.integers(5, 500, size=(3, 20))
    with torch.no_grad():
        expected = hf_model(input_ids=torch.tensor(ids)).logits.numpy()

    params = load_gpt2_lm(hf_model, cfg)
    model = GPT2LMModel(cfg)
    got = model.apply({"params": params}, jnp.asarray(ids, jnp.int32))
    np.testing.assert_allclose(np.asarray(got), expected, atol=3e-4, rtol=3e-4)


def test_bert_scan_relayout_matches_forward():
    """Scanned-trunk BERT params, unstacked to layer_i form, must drive the
    unscanned model to identical logits (the encoder twin of the LM
    generation bridge in models/relayout.py)."""
    import dataclasses

    from pytorch_distributed_training_tpu.models.relayout import (
        stack_layer_params,
        unstack_scanned_params,
    )

    cfg = tiny_cfg(hidden_dropout=0.0, attention_dropout=0.0)
    scfg = dataclasses.replace(cfg, scan_layers=True)
    scanned = BertForSequenceClassification(scfg)
    ids = jnp.ones((2, 8), jnp.int32)
    sp = scanned.init(jax.random.key(0), ids)["params"]

    unscanned = BertForSequenceClassification(cfg)
    up = unstack_scanned_params(sp)
    out_s = scanned.apply({"params": sp}, ids)
    out_u = unscanned.apply({"params": up}, ids)
    np.testing.assert_allclose(
        np.asarray(out_s), np.asarray(out_u), rtol=1e-6, atol=1e-6
    )
    restacked = stack_layer_params(up)
    assert jax.tree.all(
        jax.tree.map(lambda a, b: jnp.array_equal(a, b), sp, restacked)
    )


def test_remat_policies_preserve_gradients(eight_devices):
    """remat=True with each remat_policy computes the same loss and grads
    as the unrematted layer (selective remat only changes WHAT is saved,
    never the math). VERDICT r2 #5's selective-remat knob."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_training_tpu.models import (
        BertForSequenceClassification,
    )
    from pytorch_distributed_training_tpu.utils.config import model_preset

    def grads_for(**kw):
        cfg = model_preset(
            "tiny", compute_dtype="float32", hidden_dropout=0.0,
            attention_dropout=0.0, **kw
        )
        model = BertForSequenceClassification(cfg)
        batch = {
            "input_ids": jnp.arange(2 * 16, dtype=jnp.int32).reshape(2, 16) % 50,
            "attention_mask": jnp.ones((2, 16), jnp.int32),
            "token_type_ids": jnp.zeros((2, 16), jnp.int32),
        }
        params = model.init(jax.random.key(0), **batch, deterministic=True)

        def loss(p):
            logits = model.apply(p, **batch, deterministic=True)
            return jnp.mean(logits ** 2)

        return jax.grad(loss)(params)

    base = grads_for()
    for policy in ("nothing", "dots", "weight_dots"):
        got = grads_for(remat=True, remat_policy=policy)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                a, b, rtol=1e-6, atol=1e-6
            ),
            base, got,
        )
    with pytest.raises(ValueError, match="remat_policy"):
        grads_for(remat=True, remat_policy="bogus")


def test_int8_matmul_impl_parity_and_layout(eight_devices):
    """matmul_impl="int8" (ops/quant.py) keeps the exact parameter tree of
    the native path (checkpoint/HF-loader compatible) and computes logits
    close to bf16 (dynamic int8 quantization error only)."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_training_tpu.models import (
        BertForSequenceClassification,
    )
    from pytorch_distributed_training_tpu.utils.config import model_preset

    batch = {
        "input_ids": jnp.arange(2 * 16, dtype=jnp.int32).reshape(2, 16) % 50,
        "attention_mask": jnp.ones((2, 16), jnp.int32),
        "token_type_ids": jnp.zeros((2, 16), jnp.int32),
    }

    def build(impl):
        cfg = model_preset(
            "tiny", hidden_dropout=0.0, attention_dropout=0.0,
            matmul_impl=impl,
        )
        model = BertForSequenceClassification(cfg)
        params = model.init(jax.random.key(0), **batch, deterministic=True)
        return model, params

    native, p_native = build("native")
    quant, p_quant = build("int8")
    # identical parameter trees (same names, shapes, dtypes)
    assert jax.tree.structure(p_native) == jax.tree.structure(p_quant)
    for a, b in zip(jax.tree.leaves(p_native), jax.tree.leaves(p_quant)):
        assert a.shape == b.shape and a.dtype == b.dtype
    # int8 logits track the native ones through the SAME params
    logits_native = native.apply(p_native, **batch, deterministic=True)
    logits_quant = quant.apply(p_native, **batch, deterministic=True)
    diff = np.abs(
        np.asarray(logits_native, np.float32) - np.asarray(logits_quant, np.float32)
    ).max()
    scale = np.abs(np.asarray(logits_native, np.float32)).max()
    assert diff < 0.15 * max(scale, 1.0)
    # gradients flow (STE) in both int8 modes
    for impl in ("int8", "int8_full"):
        m, _ = build(impl)

        def loss(p):
            return jnp.mean(m.apply(p, **batch, deterministic=True) ** 2)

        g = jax.grad(loss)(p_native)
        assert all(
            np.isfinite(np.asarray(x, np.float32)).all()
            for x in jax.tree.leaves(g)
        )
