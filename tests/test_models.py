"""Model tests: shape/dtype checks plus numerical parity against the torch
HF implementation the reference uses (random-init from config — no network).

The parity test is the framework's strongest correctness anchor: if our flax
BERT matches torch's BertForSequenceClassification logits on the same
weights, the entire encoder stack (embeddings, attention, MLP, LayerNorm,
pooler, classifier) is bit-for-bit equivalent modulo float tolerance.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.models import BertForSequenceClassification
from pytorch_distributed_training_tpu.models.hf_loader import load_bert_classifier
from pytorch_distributed_training_tpu.utils.config import ModelConfig, model_preset


def tiny_cfg(**kw):
    return model_preset("tiny", compute_dtype="float32", **kw)


def test_forward_shapes_and_dtype():
    cfg = tiny_cfg()
    model = BertForSequenceClassification(cfg)
    ids = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    logits = model.apply({"params": params}, ids)
    assert logits.shape == (2, cfg.num_labels)
    assert logits.dtype == jnp.float32


def test_bf16_policy_params_stay_fp32():
    cfg = model_preset("tiny")  # default compute bf16
    model = BertForSequenceClassification(cfg)
    ids = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    dtypes = {x.dtype for x in jax.tree.leaves(params)}
    assert dtypes == {jnp.dtype(jnp.float32)}, f"params must be fp32, got {dtypes}"
    logits = model.apply({"params": params}, ids)
    assert logits.dtype == jnp.float32  # head promotes to fp32


def test_attention_mask_changes_output():
    cfg = tiny_cfg()
    model = BertForSequenceClassification(cfg)
    ids = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    full = model.apply({"params": params}, ids, jnp.ones((2, 16), jnp.int32))
    half_mask = jnp.concatenate(
        [jnp.ones((2, 8), jnp.int32), jnp.zeros((2, 8), jnp.int32)], axis=1
    )
    half = model.apply({"params": params}, ids, half_mask)
    assert not np.allclose(np.asarray(full), np.asarray(half))


def test_dropout_rng_determinism():
    cfg = tiny_cfg()
    model = BertForSequenceClassification(cfg)
    ids = jnp.ones((2, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    rng = jax.random.key(1)
    a = model.apply({"params": params}, ids, deterministic=False,
                    rngs={"dropout": rng})
    b = model.apply({"params": params}, ids, deterministic=False,
                    rngs={"dropout": rng})
    c = model.apply({"params": params}, ids, deterministic=False,
                    rngs={"dropout": jax.random.key(2)})
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not np.allclose(np.asarray(a), np.asarray(c))


@pytest.mark.parametrize("roberta", [False, True])
def test_parity_with_torch_hf(roberta):
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    torch.manual_seed(0)

    if roberta:
        hf_cfg = transformers.RobertaConfig(
            vocab_size=512, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=128,
            max_position_embeddings=66, type_vocab_size=1,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
            num_labels=3, pad_token_id=1, layer_norm_eps=1e-5,
        )
        hf_model = transformers.RobertaForSequenceClassification(hf_cfg)
        cfg = ModelConfig(
            vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
            intermediate_size=128, max_position_embeddings=66,
            type_vocab_size=1, num_labels=3, roberta_style=True,
            pad_token_id=1, layer_norm_eps=1e-5, hidden_dropout=0.0,
            attention_dropout=0.0, compute_dtype="float32",
        )
    else:
        hf_cfg = transformers.BertConfig(
            vocab_size=512, hidden_size=64, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=128,
            max_position_embeddings=64, type_vocab_size=2,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
            num_labels=2,
        )
        hf_model = transformers.BertForSequenceClassification(hf_cfg)
        cfg = ModelConfig(
            vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
            intermediate_size=128, max_position_embeddings=64,
            type_vocab_size=2, num_labels=2, hidden_dropout=0.0,
            attention_dropout=0.0, compute_dtype="float32",
        )
    hf_model.eval()

    rng = np.random.default_rng(0)
    ids = rng.integers(5, 500, size=(3, 20))
    mask = np.ones((3, 20), np.int64)
    mask[:, 15:] = 0
    if roberta:
        ids = np.where(mask, ids, 1)  # pad token

    with torch.no_grad():
        kwargs = dict(
            input_ids=torch.tensor(ids), attention_mask=torch.tensor(mask)
        )
        expected = hf_model(**kwargs).logits.numpy()

    params = load_bert_classifier(hf_model, cfg)
    model = BertForSequenceClassification(cfg)
    got = model.apply(
        {"params": params},
        jnp.asarray(ids, jnp.int32),
        jnp.asarray(mask, jnp.int32),
    )
    np.testing.assert_allclose(np.asarray(got), expected, atol=2e-4, rtol=2e-4)


@pytest.mark.parametrize("scan_layers", [False, True])
def test_gpt2_parity_with_torch_hf(scan_layers):
    """load_gpt2_lm maps an HF GPT2LMHeadModel (Conv1D [in,out] weights,
    fused c_attn, tied head) onto GPT2LMModel bit-for-bit at fp32 — for the
    python-loop trunk AND the scan-stacked trunk."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    torch.manual_seed(0)

    from pytorch_distributed_training_tpu.models.gpt2 import GPT2LMModel
    from pytorch_distributed_training_tpu.models.hf_loader import load_gpt2_lm

    hf_cfg = transformers.GPT2Config(
        vocab_size=512, n_positions=64, n_embd=64, n_layer=2, n_head=4,
        n_inner=128, resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
        layer_norm_epsilon=1e-5,
    )
    hf_model = transformers.GPT2LMHeadModel(hf_cfg)
    hf_model.eval()

    cfg = ModelConfig(
        vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
        intermediate_size=128, max_position_embeddings=64,
        type_vocab_size=0, causal=True, layer_norm_eps=1e-5,
        hidden_dropout=0.0, attention_dropout=0.0,
        compute_dtype="float32", scan_layers=scan_layers,
    )

    rng = np.random.default_rng(1)
    ids = rng.integers(5, 500, size=(3, 20))
    with torch.no_grad():
        expected = hf_model(input_ids=torch.tensor(ids)).logits.numpy()

    params = load_gpt2_lm(hf_model, cfg)
    model = GPT2LMModel(cfg)
    got = model.apply({"params": params}, jnp.asarray(ids, jnp.int32))
    np.testing.assert_allclose(np.asarray(got), expected, atol=3e-4, rtol=3e-4)


def test_bert_scan_relayout_matches_forward():
    """Scanned-trunk BERT params, unstacked to layer_i form, must drive the
    unscanned model to identical logits (the encoder twin of the LM
    generation bridge in models/relayout.py)."""
    import dataclasses

    from pytorch_distributed_training_tpu.models.relayout import (
        stack_layer_params,
        unstack_scanned_params,
    )

    cfg = tiny_cfg(hidden_dropout=0.0, attention_dropout=0.0)
    scfg = dataclasses.replace(cfg, scan_layers=True)
    scanned = BertForSequenceClassification(scfg)
    ids = jnp.ones((2, 8), jnp.int32)
    sp = scanned.init(jax.random.key(0), ids)["params"]

    unscanned = BertForSequenceClassification(cfg)
    up = unstack_scanned_params(sp)
    out_s = scanned.apply({"params": sp}, ids)
    out_u = unscanned.apply({"params": up}, ids)
    np.testing.assert_allclose(
        np.asarray(out_s), np.asarray(out_u), rtol=1e-6, atol=1e-6
    )
    restacked = stack_layer_params(up)
    assert jax.tree.all(
        jax.tree.map(lambda a, b: jnp.array_equal(a, b), sp, restacked)
    )
