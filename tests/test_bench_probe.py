"""bench.probe_backend unit tests — the wedge-proof backend probe.

The probe's contract is what round 4 lost its verification to: a
transiently dead backend must yield a structured, diagnosable record
(and NEVER a killed child — a SIGKILL mid-claim is what wedges the
axon tunnel). The child command is monkeypatched so these run without
any backend, exercising the three terminal states: success, fast
failure with backoff-respawn, and hang-past-budget.
"""

import pytest

import bench  # root-level module (pyproject pythonpath = ["."])


@pytest.fixture
def probe_src(monkeypatch):
    def set_src(src):
        monkeypatch.setattr(bench, "_PROBE_SRC", src)

    return set_src


def test_probe_success_parses_last_tokens(probe_src):
    """Banner lines before the probe's own print must not break parsing
    (the plugin/runtime may write to stdout first)."""
    probe_src("print('some banner'); print('cpu 8')")
    r = bench.probe_backend(budget_s=30, poll_s=0.2)
    assert r["ok"] is True
    assert r["platform"] == "cpu"
    assert r["n_devices"] == 8
    assert r["failed_attempts"] == []


def test_probe_fast_failure_records_attempts_and_cause(probe_src):
    probe_src("import sys; sys.stderr.write('boom\\n'); sys.exit(2)")
    r = bench.probe_backend(budget_s=2, poll_s=0.2, backoff_s=0.1)
    assert r["ok"] is False
    assert "failed every try" in r["cause"]
    assert r["attempts"], r
    assert r["attempts"][0]["outcome"] == "rc=2"
    assert "boom" in r["attempts"][0]["stderr_tail"]


def test_probe_hang_leaves_child_running(probe_src):
    """A child still initializing at budget exhaustion is LEFT ALIVE
    (killing a mid-claim client is the wedge mechanism) and the record
    says so."""
    import os

    probe_src("import time; time.sleep(4)")
    r = bench.probe_backend(budget_s=1.0, poll_s=0.2)
    assert r["ok"] is False
    assert "left running" in r["cause"]
    pid = r["hung_child_pid"]
    # the child must still be alive — not killed by the probe
    os.kill(pid, 0)  # raises if the process is gone
    # (the sleeper exits on its own; nothing to clean up)


def test_probe_success_after_failures(probe_src, tmp_path):
    """A flaky backend that fails then recovers within the budget is
    reported ok — the backoff-respawn path."""
    flag = tmp_path / "second_try"
    probe_src(
        "import sys, os\n"
        f"p = {str(flag)!r}\n"
        "if not os.path.exists(p):\n"
        "    open(p, 'w').close(); sys.exit(1)\n"
        "print('cpu 4')\n"
    )
    r = bench.probe_backend(budget_s=30, poll_s=0.2, backoff_s=0.1)
    assert r["ok"] is True, r
    assert r["n_devices"] == 4
    assert len(r["failed_attempts"]) == 1
