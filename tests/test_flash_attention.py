"""Flash-attention kernel parity tests (interpret mode on the CPU mesh).

The kernel's contract is bit-level agreement with the reference einsum
attention (ops/attention.py) on everything except dropout, whose keep mask
comes from the in-kernel TPU PRNG. Dropout correctness is covered by a
finite-difference check — valid because the kernel PRNG is deterministic in
(seed, block ids), so f is a fixed function of its inputs.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.ops.attention import (
    dot_product_attention,
    make_attention_bias,
    reference_attention,
)
from pytorch_distributed_training_tpu.ops.flash_attention import (
    flash_attention,
    flash_attention_base,
    tpu_interpret_mode,
)


def _qkv(batch=2, seq=32, heads=2, head_dim=8, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(
        rng.normal(size=(batch, seq, heads, head_dim)), dtype
    )
    return mk(), mk(), mk()


def _padding_mask(batch=2, seq=32, valid_lens=(32, 17)):
    mask = np.zeros((batch, seq), np.int32)
    for i, n in enumerate(valid_lens):
        mask[i, :n] = 1
    return jnp.asarray(mask)


def test_interpret_probe_sees_context():
    """The dispatch guard must recognize the framework's interpret-mode
    context — otherwise every parity test below would silently compare
    reference to itself."""
    from pytorch_distributed_training_tpu.ops import dispatch

    import jax

    if jax.default_backend() != "tpu":
        assert dispatch.mode() == "off"
    with tpu_interpret_mode():
        assert dispatch.mode() == "direct"


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference_fwd(causal):
    q, k, v = _qkv()
    bias = make_attention_bias(_padding_mask())
    with tpu_interpret_mode():
        out = flash_attention(q, k, v, bias, causal=causal)
    ref = reference_attention(q, k, v, bias, causal=causal)
    # padded key rows produce garbage in padded QUERY rows of ref too; compare
    # only rows the mask marks valid (the model multiplies them out anyway)
    np.testing.assert_allclose(
        np.asarray(out[0]), np.asarray(ref[0]), atol=2e-5, rtol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(out[1, :17]), np.asarray(ref[1, :17]), atol=2e-5, rtol=2e-5
    )


@pytest.mark.parametrize("causal", [False, True])
def test_flash_matches_reference_grad(causal):
    q, k, v = _qkv(seed=1)
    bias = make_attention_bias(_padding_mask())
    cot = jnp.asarray(
        np.random.default_rng(2).normal(size=q.shape), jnp.float32
    )
    # zero cotangent on padded query rows: their grads are masked downstream
    cot = cot * _padding_mask()[:, :, None, None]

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, bias, causal=causal) * cot)

    def loss_ref(q, k, v):
        return jnp.sum(
            reference_attention(q, k, v, bias, causal=causal) * cot
        )

    with tpu_interpret_mode():
        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-5, rtol=5e-4,
            err_msg=f"d{name} mismatch (causal={causal})",
        )


def test_flash_dropout_finite_difference():
    """Custom VJP agrees with central differences under in-kernel dropout."""
    q, k, v = _qkv(batch=1, seq=16, heads=1, head_dim=8, seed=3)
    bias = jnp.zeros((1, 1, 1, 16), jnp.float32)
    seed = jnp.asarray([7], jnp.int32)
    cot = jnp.asarray(
        np.random.default_rng(4).normal(size=q.shape), jnp.float32
    )

    def f(q):
        out = flash_attention_base(
            q, k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3),
            bias, seed, dropout_rate=0.5, causal=False,
            block_q=16, block_k=16,
        )
        return jnp.sum(out * cot.transpose(0, 2, 1, 3))

    qt = q.transpose(0, 2, 1, 3)
    with tpu_interpret_mode():
        g = jax.grad(f)(qt)
        rng = np.random.default_rng(5)
        for _ in range(3):
            d = jnp.asarray(rng.normal(size=qt.shape), jnp.float32)
            eps = 1e-3
            fd = (f(qt + eps * d) - f(qt - eps * d)) / (2 * eps)
            an = jnp.sum(g * d)
            np.testing.assert_allclose(
                float(fd), float(an), rtol=2e-2, atol=1e-3
            )


def test_flash_dispatch_and_fallback():
    q, k, v = _qkv(seq=24)  # 24 % block fine (block=min(128,24)=24)
    # per-head bias → must fall back to reference, not mis-mask
    bias = jnp.zeros((2, 2, 24, 24), jnp.float32)
    out = dot_product_attention(q, k, v, bias, impl="flash")
    ref = reference_attention(q, k, v, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_multiblock_grad_matches_reference(causal):
    """The general two-pass backward (dq + dkv kernels) — NOT the fused
    single-block fast path — must stay correct: force multiple blocks with
    block sizes smaller than the sequence."""
    q, k, v = _qkv(seq=32, seed=6)
    bias = jnp.zeros((2, 1, 1, 32), jnp.float32)
    seed = jnp.zeros((1,), jnp.int32)
    cot = jnp.asarray(
        np.random.default_rng(7).normal(size=q.shape), jnp.float32
    )

    def loss_flash(q, k, v):
        out = flash_attention_base(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), bias, seed,
            causal=causal, block_q=16, block_k=16,
        )
        return jnp.sum(out.transpose(0, 2, 1, 3) * cot)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, None, causal=causal) * cot)

    with tpu_interpret_mode():
        g_flash = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for gf, gr, name in zip(g_flash, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gr), atol=5e-5, rtol=5e-4,
            err_msg=f"multi-block d{name} (causal={causal})",
        )


def test_flash_fully_masked_row_stays_finite():
    """A fully-padded sample (all-zero mask row) must give finite outputs
    and gradients in the single-block (save-probs) path — the row max floor
    prevents exp(-inf - -inf) NaNs."""
    q, k, v = _qkv(seed=8)
    mask = np.ones((2, 32), np.int32)
    mask[1, :] = 0  # entire sample masked out
    bias = make_attention_bias(jnp.asarray(mask))

    def loss(q):
        return jnp.sum(flash_attention(q, k, v, bias) ** 2)

    with tpu_interpret_mode():
        out = flash_attention(q, k, v, bias)
        g = jax.grad(loss)(q)
    assert np.isfinite(np.asarray(out)).all()
    assert np.isfinite(np.asarray(g)).all()


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("dropout", [0.0, 0.5])
def test_fused_bwd_matches_two_pass(causal, dropout):
    """The fused single-pass backward (_dqkv_kernel: one probs recompute,
    dq accumulated across the sequential k-block grid) must produce the
    same gradients as the classic two-pass scheme — with and without
    in-kernel dropout (identical per-(bh, qi, kj) seeds by construction),
    causal and not, multi-block."""
    from pytorch_distributed_training_tpu.ops import flash_attention as fa

    q, k, v = _qkv(seq=32, seed=11)
    bias = make_attention_bias(_padding_mask())
    seed = jnp.asarray([5], jnp.int32)
    cot = jnp.asarray(
        np.random.default_rng(12).normal(size=q.shape), jnp.float32
    )
    cot = cot * _padding_mask()[:, :, None, None]

    def loss(q, k, v):
        out = flash_attention_base(
            q.transpose(0, 2, 1, 3), k.transpose(0, 2, 1, 3),
            v.transpose(0, 2, 1, 3), bias.astype(jnp.float32), seed,
            dropout_rate=dropout, causal=causal, block_q=16, block_k=16,
        )
        return jnp.sum(out.transpose(0, 2, 1, 3) * cot)

    grads = {}
    orig = fa.FUSED_BWD
    try:
        for mode in (True, False):
            fa.FUSED_BWD = mode
            with tpu_interpret_mode():
                grads[mode] = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
    finally:
        fa.FUSED_BWD = orig
    for gf, gt, name in zip(grads[True], grads[False], "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf), np.asarray(gt), atol=1e-6, rtol=1e-6,
            err_msg=f"fused-vs-two-pass d{name} "
                    f"(causal={causal}, dropout={dropout})",
        )
