"""int8 matmul path tests (ops/quant.py): delayed scaling semantics, the
sharded (fsdp/tp) execution the v5e-8 configs would run, and checkpoint
round-tripping of the carried amax state.

The dynamic-path basics (parameter-tree parity with nn.DenseGeneral, STE
gradient flow) live in test_models.py; this file covers what VERDICT r3
flagged untested: int8 under sharded meshes and the delayed-scaling tier.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.comms.mesh import build_mesh
from pytorch_distributed_training_tpu.models import BertForSequenceClassification
from pytorch_distributed_training_tpu.parallel import ShardingPolicy, state_shardings
from pytorch_distributed_training_tpu.parallel.sharding import shard_state
from pytorch_distributed_training_tpu.train import (
    adamw_with_schedule,
    create_train_state,
    make_train_step,
)
from pytorch_distributed_training_tpu.train.step import calibrate_quant
from pytorch_distributed_training_tpu.utils.config import (
    MeshConfig,
    TrainConfig,
    model_preset,
)


def make_batch(rng, accum, micro, seq=16, vocab=1000, num_labels=2):
    return {
        "input_ids": rng.integers(0, vocab, (accum, micro, seq)).astype(np.int32),
        "attention_mask": np.ones((accum, micro, seq), np.int32),
        "token_type_ids": np.zeros((accum, micro, seq), np.int32),
        "labels": rng.integers(0, num_labels, (accum, micro)).astype(np.int32),
    }


def quant_state(matmul_impl="int8_full", delayed=False, seed=0, **model_kw):
    cfg = model_preset(
        "tiny", compute_dtype="float32", hidden_dropout=0.0,
        attention_dropout=0.0, matmul_impl=matmul_impl,
        quant_delayed=delayed, **model_kw,
    )
    model = BertForSequenceClassification(cfg)
    tx, _ = adamw_with_schedule(TrainConfig(), 100)
    example = {
        "input_ids": jnp.ones((2, 16), jnp.int32),
        "attention_mask": jnp.ones((2, 16), jnp.int32),
        "token_type_ids": jnp.zeros((2, 16), jnp.int32),
    }
    return create_train_state(model, tx, jax.random.key(seed), example)


# ------------------------------------------------------------- delayed: unit

def test_delayed_dot_matches_dynamic_when_amax_is_fresh():
    """int8_dense_delayed with amax_prev == the true amax must reproduce
    int8_dense exactly (same quantize grid), and report that amax back."""
    from pytorch_distributed_training_tpu.ops.quant import (
        int8_dense,
        int8_dense_delayed,
    )

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, 8, 32)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(32, 16)), jnp.float32)
    amax = jnp.max(jnp.abs(x))

    y_dyn = int8_dense(x, w, 1, "full")
    y_del, new_amax = int8_dense_delayed(x, w, amax, 1, "full")
    np.testing.assert_array_equal(np.asarray(y_dyn), np.asarray(y_del))
    np.testing.assert_allclose(float(new_amax), float(amax), rtol=1e-6)

    # stale (smaller) amax clips but stays finite and in the right ballpark
    y_stale, _ = int8_dense_delayed(x, w, amax * 0.5, 1, "full")
    assert np.isfinite(np.asarray(y_stale)).all()
    assert np.abs(np.asarray(y_stale) - np.asarray(y_dyn)).max() < 0.5 * float(
        jnp.abs(y_dyn).max()
    )


def test_delayed_gradients_flow_and_amax_gets_zero_cotangent():
    from pytorch_distributed_training_tpu.ops.quant import int8_dense_delayed

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(8, 32)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(32, 16)), jnp.float32)
    amax = jnp.max(jnp.abs(x))

    def loss(x, w, a):
        y, _ = int8_dense_delayed(x, w, a, 1, "full")
        return jnp.mean(y**2)

    dx, dw, da = jax.grad(loss, argnums=(0, 1, 2))(x, w, amax)
    assert np.isfinite(np.asarray(dx)).all()
    assert np.isfinite(np.asarray(dw)).all()
    assert np.abs(np.asarray(dx)).max() > 0
    assert float(da) == 0.0  # scales are STE constants


def test_delayed_grads_forward_matches_delayed():
    """int8_dense_delayed_grads' primal is bit-identical to
    int8_dense_delayed (the sink rides as +0.0) and reports the same
    fresh amax back."""
    from pytorch_distributed_training_tpu.ops.quant import (
        int8_dense_delayed,
        int8_dense_delayed_grads,
    )

    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(4, 8, 32)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(32, 16)), jnp.float32)
    amax = jnp.max(jnp.abs(x))
    y_ref, a_ref = int8_dense_delayed(x, w, amax, 1, "full")
    y, a = int8_dense_delayed_grads(
        x, w, amax, jnp.ones((2,), jnp.float32), jnp.zeros((2,), jnp.float32),
        1,
    )
    np.testing.assert_array_equal(np.asarray(y_ref), np.asarray(y))
    np.testing.assert_allclose(float(a), float(a_ref), rtol=1e-6)


def test_delayed_grads_sink_cotangent_carries_dy_amaxes():
    """The sink's gradient IS [amax(dy*sw), amax(dy)] — the channel that
    lets a train step carry next-microbatch dy scales; and with the TRUE
    current dy amaxes carried in, dx/dw equal the dynamic "full" path
    exactly (same quantize grid)."""
    from pytorch_distributed_training_tpu.ops.quant import (
        int8_dense,
        int8_dense_delayed_grads,
        quantize_per_channel,
    )

    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.normal(size=(8, 32)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(32, 16)), jnp.float32)
    cot = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
    amax = jnp.max(jnp.abs(x))
    _, sw = quantize_per_channel(w, contract_axis=(0,))
    true_dy_amaxes = jnp.stack([
        jnp.max(jnp.abs(cot * sw)), jnp.max(jnp.abs(cot))
    ])

    def loss(x, w, sink, dy_amaxes):
        y, _ = int8_dense_delayed_grads(x, w, amax, dy_amaxes, sink, 1)
        return jnp.sum(y * cot)

    sink0 = jnp.zeros((2,), jnp.float32)
    dx, dw, d_sink, d_dyam = jax.grad(loss, argnums=(0, 1, 2, 3))(
        x, w, sink0, true_dy_amaxes
    )
    np.testing.assert_allclose(
        np.asarray(d_sink), np.asarray(true_dy_amaxes), rtol=1e-6
    )
    np.testing.assert_array_equal(np.asarray(d_dyam), np.zeros((2,)))

    def loss_dyn(x, w):
        return jnp.sum(int8_dense(x, w, 1, "full") * cot)

    dx_ref, dw_ref = jax.grad(loss_dyn, argnums=(0, 1))(x, w)
    np.testing.assert_array_equal(np.asarray(dx), np.asarray(dx_ref))
    np.testing.assert_array_equal(np.asarray(dw), np.asarray(dw_ref))

    # stale (half) dy scales: clipped but finite gradients
    dx2 = jax.grad(loss, argnums=0)(x, w, sink0, true_dy_amaxes * 0.5)
    assert np.isfinite(np.asarray(dx2)).all()

    # calibrate=True: ZERO carried amaxes still give the exact dynamic
    # gradients (the one-pass calibration contract — without it every
    # downstream site would see saturated ~1e-12 garbage cotangents)
    def loss_cal(x, w, sink):
        from pytorch_distributed_training_tpu.ops.quant import (
            int8_dense_delayed_grads as g,
        )

        y, _ = g(x, w, amax, jnp.zeros((2,), jnp.float32), sink, 1, True)
        return jnp.sum(y * cot)

    dx3, dw3, d_sink3 = jax.grad(loss_cal, argnums=(0, 1, 2))(x, w, sink0)
    np.testing.assert_array_equal(np.asarray(dx3), np.asarray(dx_ref))
    np.testing.assert_array_equal(np.asarray(dw3), np.asarray(dw_ref))
    np.testing.assert_allclose(
        np.asarray(d_sink3), np.asarray(true_dy_amaxes), rtol=1e-6
    )


# ------------------------------------------------------- delayed: train step

def test_delayed_step0_matches_dynamic_after_calibration():
    """With accum=1 and calibration on the training batch itself, step 0 of
    the delayed path quantizes with (nearly) the scales the dynamic path
    computes — deeper sites differ only because the calibration forward ran
    under the init-batch scales at earlier layers (a one-pass fixed-point
    error, ~1e-5 relative)."""
    batch = jax.tree.map(
        jnp.asarray, make_batch(np.random.default_rng(2), 1, 8)
    )
    micro0 = jax.tree.map(lambda x: x[0], batch)

    s_dyn = quant_state(delayed=False)
    s_del = quant_state(delayed=True)
    assert s_dyn.quant is None and s_del.quant is not None
    s_del = calibrate_quant(s_del, micro0)
    # calibration observed real data, not the init dummy batch
    assert all(
        float(a) > 0 for a in jax.tree.leaves(s_del.quant)
    )

    step = make_train_step(grad_accum_steps=1, log_grad_norm=False)
    s_dyn2, m_dyn = step(s_dyn, batch)
    s_del2, m_del = step(s_del, batch)
    np.testing.assert_allclose(
        float(m_dyn["loss"]), float(m_del["loss"]), rtol=1e-4
    )
    for a, b in zip(
        jax.tree.leaves(s_dyn2.params), jax.tree.leaves(s_del2.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4
        )


def test_delayed_amax_carries_across_microbatches_and_steps():
    """The quant collection must update every microbatch (scan carry) and
    persist into the returned state."""
    rng = np.random.default_rng(3)
    s = quant_state(delayed=True)
    batch = jax.tree.map(jnp.asarray, make_batch(rng, 4, 4))
    s = calibrate_quant(s, jax.tree.map(lambda x: x[0], batch))
    before = jax.tree.map(float, jax.device_get(s.quant))

    step = make_train_step(grad_accum_steps=4, log_grad_norm=False)
    losses = []
    for _ in range(3):
        b = make_batch(rng, 4, 4)
        b["labels"] = (b["input_ids"][:, :, 0] % 2).astype(np.int32)
        s, m = step(s, jax.tree.map(jnp.asarray, b))
        losses.append(float(m["loss"]))
    after = jax.tree.map(float, jax.device_get(s.quant))
    assert before != after  # amaxes tracked the data
    assert all(np.isfinite(l) for l in losses)
    assert int(s.step) == 3


def test_delayed_with_scan_layers_and_branch_trunks():
    """The nn.scan / nn.vmap trunks declare the "quant" collection on their
    stacked axis — init must produce per-layer / per-branch amaxes instead
    of a flax lifting error."""
    s = quant_state(delayed=True, scan_layers=True)
    assert s.quant is not None
    leaves = jax.tree.leaves(s.quant)
    # scan trunk: per-layer amaxes stacked on the leading [num_layers] dim
    assert any(getattr(l, "shape", ()) and l.shape[0] == 2 for l in leaves)

    from pytorch_distributed_training_tpu.models.branch import (
        BranchEnsembleClassifier,
    )

    cfg = model_preset(
        "tiny", compute_dtype="float32", hidden_dropout=0.0,
        attention_dropout=0.0, matmul_impl="int8_full", quant_delayed=True,
    )
    model = BranchEnsembleClassifier(cfg, n_branches=3)
    batch = {
        "input_ids": jnp.ones((2, 16), jnp.int32),
        "attention_mask": jnp.ones((2, 16), jnp.int32),
        "token_type_ids": jnp.zeros((2, 16), jnp.int32),
    }
    variables = model.init(jax.random.key(0), **batch, deterministic=True)
    assert "quant" in variables
    assert any(
        getattr(l, "shape", ()) and l.shape[0] == 3
        for l in jax.tree.leaves(variables["quant"])
    )


# ----------------------------------------------------------- sharded meshes

@pytest.mark.slow
@pytest.mark.parametrize("delayed", [False, True], ids=["dynamic", "delayed"])
def test_int8_full_under_fsdp_and_tp_matches_dp(eight_devices, delayed):
    """VERDICT r3 weak-#4: int8_full under fsdp/tp sharding. Per-tensor
    absmax becomes a cross-shard reduce under GSPMD; the result must match
    the replicated (DP) int8 run bit-for-bit in fp32 compute."""
    batch = make_batch(np.random.default_rng(4), 2, 16)

    from pytorch_distributed_training_tpu.comms.ingest import make_global_batch
    from pytorch_distributed_training_tpu.comms.mesh import TRAIN_BATCH_PSPEC

    results = {}
    for name, mesh_cfg, policy in [
        ("dp", MeshConfig(data=8), ShardingPolicy()),
        ("fsdp", MeshConfig(data=2, fsdp=4),
         ShardingPolicy(fsdp=True, fsdp_min_size=128)),
        ("tp", MeshConfig(data=2, model=4), ShardingPolicy(tp=True)),
    ]:
        mesh = build_mesh(mesh_cfg)
        s = quant_state(delayed=delayed)
        shardings = state_shardings(s, policy, mesh)
        s = shard_state(s, shardings)
        placed = make_global_batch(
            mesh, jax.tree.map(np.asarray, batch), pspec=TRAIN_BATCH_PSPEC
        )
        if delayed:
            s = calibrate_quant(s, jax.tree.map(lambda x: x[0], placed))
        step = make_train_step(
            grad_accum_steps=2, mesh=mesh, state_shardings=shardings,
            log_grad_norm=False,
        )
        s2, m = step(s, placed)
        results[name] = (
            float(m["loss"]),
            np.concatenate(
                [np.ravel(jax.device_get(x)) for x in jax.tree.leaves(s2.params)]
            ),
        )
    for name in ("fsdp", "tp"):
        np.testing.assert_allclose(
            results["dp"][0], results[name][0], rtol=2e-5,
            err_msg=f"{name} loss diverged from dp",
        )
        np.testing.assert_allclose(
            results["dp"][1], results[name][1], atol=3e-5,
            err_msg=f"{name} params diverged from dp",
        )


# --------------------------------------------------- delayed dy: train step

def test_delayed_grads_step_forward_identical_and_dy_amaxes_carried():
    """quant_delayed_grads: step-0 LOSS equals plain delayed's exactly
    (the forward path is bit-identical; only backward dy scales differ),
    the dy_amax leaves exist in the quant state, calibration populates
    them, and one step advances them with the backward's observations."""
    rng = np.random.default_rng(21)
    batch = jax.tree.map(jnp.asarray, make_batch(rng, 2, 4))
    micro0 = jax.tree.map(lambda x: x[0], batch)

    s_del = quant_state(delayed=True)
    s_dg = quant_state(delayed=True, quant_delayed_grads=True)
    from flax import traverse_util

    flat = traverse_util.flatten_dict(s_dg.quant)
    dy_keys = [k for k in flat if k[-1] == "dy_amax"]
    assert dy_keys, "delayed_grads model must declare dy_amax state"
    assert all(np.all(np.asarray(flat[k]) == 0) for k in dy_keys)

    s_del = calibrate_quant(s_del, micro0, loss_scale=0.5)
    s_dg = calibrate_quant(s_dg, micro0, loss_scale=0.5)
    flat = traverse_util.flatten_dict(jax.device_get(s_dg.quant))
    assert all(np.all(np.asarray(flat[k]) > 0) for k in dy_keys)
    cal_dy = {k: np.asarray(flat[k]) for k in dy_keys}

    step = make_train_step(grad_accum_steps=2, log_grad_norm=False)
    s_del2, m_del = step(s_del, batch)
    s_dg2, m_dg = step(s_dg, batch)
    # forward path identical at step 0 (same fwd amaxes after the same
    # calibration), so the reported losses agree exactly
    np.testing.assert_array_equal(
        np.asarray(m_del["loss"]), np.asarray(m_dg["loss"])
    )
    # dy amaxes advanced to the step's own backward observations
    flat2 = traverse_util.flatten_dict(jax.device_get(s_dg2.quant))
    assert any(
        not np.array_equal(cal_dy[k], np.asarray(flat2[k])) for k in dy_keys
    )
    assert all(np.isfinite(np.asarray(flat2[k])).all() for k in dy_keys)
    # and a second step consumes the carried scales without blowing up
    p2 = jax.device_get(s_dg2.params)  # host copy BEFORE donation
    s_dg3, m2 = step(s_dg2, batch)
    assert np.isfinite(float(m2["loss"]))
    # params took a real (finite, nonzero) update
    p3 = jax.device_get(s_dg3.params)
    d = jax.tree.map(lambda a, b: float(np.max(np.abs(a - b))), p2, p3)
    assert max(jax.tree.leaves(d)) > 0


def test_delayed_grads_step0_tracks_dynamic_when_calibrated_on_batch():
    """The invariant that pins dy CALIBRATION correctness: with accum=1
    and calibration on the training batch itself, the carried dy scales
    are the true amaxes of (nearly) that step's backward, so the
    delayed-grads step must closely track the dynamic int8_full step —
    same loss to float tolerance and a near-parallel parameter update.
    Exactness is unreachable (the calibration forward runs under the
    init-batch scales at earlier sites — one-pass fixed point, see
    test_delayed_step0_matches_dynamic_after_calibration), but a BROKEN
    calibration (zero carried amaxes saturating downstream cotangents)
    collapses the update cosine toward zero and fails loudly."""
    rng = np.random.default_rng(22)
    batch = jax.tree.map(jnp.asarray, make_batch(rng, 1, 4))
    micro0 = jax.tree.map(lambda x: x[0], batch)

    s_dyn = quant_state(delayed=False)
    s_dg = quant_state(delayed=True, quant_delayed_grads=True)
    p0 = jax.device_get(s_dg.params)
    s_dg = calibrate_quant(s_dg, micro0, loss_scale=1.0)

    step = make_train_step(grad_accum_steps=1, log_grad_norm=False)
    s_dyn2, m_dyn = step(s_dyn, batch)
    s_dg2, m_dg = step(s_dg, batch)
    np.testing.assert_allclose(
        float(m_dyn["loss"]), float(m_dg["loss"]), rtol=1e-3
    )
    # step 0 sits at warmup lr == 0 (the reference recipe's schedule), so
    # take a second step before comparing the parameter movement
    s_dyn2, _ = step(s_dyn2, batch)
    s_dg2, _ = step(s_dg2, batch)

    def upd(p_new):
        return np.concatenate([
            (np.asarray(a) - np.asarray(b)).ravel()
            for a, b in zip(
                jax.tree.leaves(jax.device_get(p_new)), jax.tree.leaves(p0)
            )
        ])

    u_dyn, u_dg = upd(s_dyn2.params), upd(s_dg2.params)
    cos = float(
        np.dot(u_dyn, u_dg)
        / (np.linalg.norm(u_dyn) * np.linalg.norm(u_dg) + 1e-30)
    )
    assert cos > 0.95, cos


@pytest.mark.slow
def test_delayed_grads_trainer_e2e(eight_devices):
    """Trainer wiring: --quant-delayed-grads trains on the CPU mesh with
    finite metrics and positive carried dy amaxes (objective-aware
    calibration included)."""
    from flax import traverse_util

    from pytorch_distributed_training_tpu.train.loop import Trainer

    mcfg = model_preset(
        "tiny", compute_dtype="float32",
        matmul_impl="int8_full", quant_delayed=True,
        quant_delayed_grads=True,
    )
    tcfg = TrainConfig(
        num_epochs=1, global_batch_size=16, micro_batch_size=8,
        eval_batch_size=16, train_size=32, eval_size=16,
        max_seq_length=16, bf16=False, log_every=0,
    )
    t = Trainer(mcfg, tcfg, MeshConfig(data=8), ShardingPolicy(),
                task="synthetic")
    history = t.run()
    assert np.isfinite(history[0]["train_loss"])
    flat = traverse_util.flatten_dict(jax.device_get(t.state.quant))
    dy = [np.asarray(v) for k, v in flat.items() if k[-1] == "dy_amax"]
    assert dy and all((x > 0).all() for x in dy)


def test_delayed_grads_scanned_gpt2_step():
    """quant_delayed_grads through the SCANNED causal trunk: gpt2's
    nn.scan must declare the "quant_sink" axis (caught in review — bert
    and branch had it, gpt2 didn't) and the causal-LM objective must
    calibrate and step."""
    from pytorch_distributed_training_tpu.models.gpt2 import GPT2LMModel

    cfg = model_preset(
        "gpt2-tiny", compute_dtype="float32", scan_layers=True,
        matmul_impl="int8_full", quant_delayed=True,
        quant_delayed_grads=True, attention_impl="reference",
    )
    model = GPT2LMModel(cfg)
    tx, _ = adamw_with_schedule(TrainConfig(), 100)
    example = {
        "input_ids": jnp.ones((2, 16), jnp.int32),
        "attention_mask": jnp.ones((2, 16), jnp.int32),
    }
    s = create_train_state(model, tx, jax.random.key(0), example)
    assert s.quant is not None
    rng = np.random.default_rng(23)
    batch = {
        "input_ids": jnp.asarray(
            rng.integers(0, cfg.vocab_size, (2, 4, 16)), jnp.int32
        ),
        "attention_mask": jnp.ones((2, 4, 16), jnp.int32),
    }
    s = calibrate_quant(
        s, jax.tree.map(lambda x: x[0], batch),
        objective="causal_lm", loss_scale=0.5,
    )
    step = make_train_step(
        grad_accum_steps=2, objective="causal_lm", log_grad_norm=False
    )
    s2, m = step(s, batch)
    assert np.isfinite(float(m["loss"]))
    from flax import traverse_util

    flat = traverse_util.flatten_dict(jax.device_get(s2.quant))
    dy = [np.asarray(v) for k, v in flat.items() if k[-1] == "dy_amax"]
    assert dy and all(np.isfinite(x).all() for x in dy)


# ------------------------------------------------------------- checkpointing

@pytest.mark.slow
@pytest.mark.parametrize("delayed_grads", [False, True])
def test_quant_state_checkpoint_roundtrip(tmp_path, delayed_grads):
    """Delayed amaxes ride checkpoints: step N quantizes with step N-1's
    scales, so resume must restore them exactly — including the backward
    dy amaxes when quant_delayed_grads is on."""
    from pytorch_distributed_training_tpu.train import checkpoint as ckpt

    kw = {"quant_delayed_grads": True} if delayed_grads else {}
    rng = np.random.default_rng(5)
    batch = jax.tree.map(jnp.asarray, make_batch(rng, 2, 4))
    s = quant_state(delayed=True, **kw)
    s = calibrate_quant(s, jax.tree.map(lambda x: x[0], batch))
    step = make_train_step(grad_accum_steps=2, log_grad_norm=False)
    s, _ = step(s, batch)

    ckpt.save_checkpoint(str(tmp_path / "q"), s)
    fresh = quant_state(delayed=True, **kw)
    restored = ckpt.restore_checkpoint(str(tmp_path / "q"), fresh)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        jax.device_get(s.quant),
        jax.device_get(restored.quant),
    )
    # and the next step from the restored state matches exactly
    b2 = jax.tree.map(jnp.asarray, make_batch(rng, 2, 4))
    s_a, m_a = step(s, b2)
    s_b, m_b = step(restored, b2)
    np.testing.assert_array_equal(
        np.asarray(m_a["loss"]), np.asarray(m_b["loss"])
    )


@pytest.mark.slow
def test_quant_flag_mismatch_restore_message(tmp_path):
    """Saving WITHOUT delayed quant and resuming WITH it is a structural
    tree mismatch (the 'quant' subtree exists iff the saving run had the
    flag on); restore must relabel it with the flag name — detected from
    the checkpoint's metadata, not the error text (ADVICE r4)."""
    from pytorch_distributed_training_tpu.train import checkpoint as ckpt

    rng = np.random.default_rng(6)
    batch = jax.tree.map(jnp.asarray, make_batch(rng, 2, 4))
    s = quant_state(delayed=False)
    step = make_train_step(grad_accum_steps=2, log_grad_norm=False)
    s, _ = step(s, batch)
    ckpt.save_checkpoint(str(tmp_path / "q"), s)

    fresh = quant_state(delayed=True)
    fresh = calibrate_quant(fresh, jax.tree.map(lambda x: x[0], batch))
    with pytest.raises(ValueError, match="--quant-delayed"):
        ckpt.restore_checkpoint(str(tmp_path / "q"), fresh)


@pytest.mark.slow
def test_trainer_resume_keeps_checkpointed_quant_scales(eight_devices, tmp_path):
    """A resumed delayed-quant run restores the checkpoint's amaxes and
    skips re-calibration (the trajectory depends on the carried scales —
    re-observing them would fork it; also saves a wasted forward compile)."""
    from pytorch_distributed_training_tpu.train.loop import Trainer

    def trainer(resume):
        mcfg = model_preset(
            "tiny", compute_dtype="float32",
            matmul_impl="int8_full", quant_delayed=True,
        )
        tcfg = TrainConfig(
            num_epochs=1, global_batch_size=16, micro_batch_size=8,
            eval_batch_size=16, train_size=32, eval_size=16,
            max_seq_length=16, bf16=False, log_every=0,
            checkpoint_dir=str(tmp_path / "ck"), resume=resume,
        )
        return Trainer(mcfg, tcfg, MeshConfig(data=8), ShardingPolicy(),
                       task="synthetic")

    t1 = trainer(resume=False)
    t1.run()
    saved = jax.tree.map(float, jax.device_get(t1.state.quant))

    t2 = trainer(resume=True)  # restores the epoch-end checkpoint
    restored = jax.tree.map(float, jax.device_get(t2.state.quant))
    assert saved == restored  # not re-calibrated from the first batch
