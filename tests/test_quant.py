"""int8 matmul path tests (ops/quant.py): delayed scaling semantics, the
sharded (fsdp/tp) execution the v5e-8 configs would run, and checkpoint
round-tripping of the carried amax state.

The dynamic-path basics (parameter-tree parity with nn.DenseGeneral, STE
gradient flow) live in test_models.py; this file covers what VERDICT r3
flagged untested: int8 under sharded meshes and the delayed-scaling tier.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.comms.mesh import build_mesh
from pytorch_distributed_training_tpu.models import BertForSequenceClassification
from pytorch_distributed_training_tpu.parallel import ShardingPolicy, state_shardings
from pytorch_distributed_training_tpu.parallel.sharding import shard_state
from pytorch_distributed_training_tpu.train import (
    adamw_with_schedule,
    create_train_state,
    make_train_step,
)
from pytorch_distributed_training_tpu.train.step import calibrate_quant
from pytorch_distributed_training_tpu.utils.config import (
    MeshConfig,
    TrainConfig,
    model_preset,
)


def make_batch(rng, accum, micro, seq=16, vocab=1000, num_labels=2):
    return {
        "input_ids": rng.integers(0, vocab, (accum, micro, seq)).astype(np.int32),
        "attention_mask": np.ones((accum, micro, seq), np.int32),
        "token_type_ids": np.zeros((accum, micro, seq), np.int32),
        "labels": rng.integers(0, num_labels, (accum, micro)).astype(np.int32),
    }


def quant_state(matmul_impl="int8_full", delayed=False, seed=0, **model_kw):
    cfg = model_preset(
        "tiny", compute_dtype="float32", hidden_dropout=0.0,
        attention_dropout=0.0, matmul_impl=matmul_impl,
        quant_delayed=delayed, **model_kw,
    )
    model = BertForSequenceClassification(cfg)
    tx, _ = adamw_with_schedule(TrainConfig(), 100)
    example = {
        "input_ids": jnp.ones((2, 16), jnp.int32),
        "attention_mask": jnp.ones((2, 16), jnp.int32),
        "token_type_ids": jnp.zeros((2, 16), jnp.int32),
    }
    return create_train_state(model, tx, jax.random.key(seed), example)


# ------------------------------------------------------------- delayed: unit

def test_delayed_dot_matches_dynamic_when_amax_is_fresh():
    """int8_dense_delayed with amax_prev == the true amax must reproduce
    int8_dense exactly (same quantize grid), and report that amax back."""
    from pytorch_distributed_training_tpu.ops.quant import (
        int8_dense,
        int8_dense_delayed,
    )

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(4, 8, 32)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(32, 16)), jnp.float32)
    amax = jnp.max(jnp.abs(x))

    y_dyn = int8_dense(x, w, 1, "full")
    y_del, new_amax = int8_dense_delayed(x, w, amax, 1, "full")
    np.testing.assert_array_equal(np.asarray(y_dyn), np.asarray(y_del))
    np.testing.assert_allclose(float(new_amax), float(amax), rtol=1e-6)

    # stale (smaller) amax clips but stays finite and in the right ballpark
    y_stale, _ = int8_dense_delayed(x, w, amax * 0.5, 1, "full")
    assert np.isfinite(np.asarray(y_stale)).all()
    assert np.abs(np.asarray(y_stale) - np.asarray(y_dyn)).max() < 0.5 * float(
        jnp.abs(y_dyn).max()
    )


def test_delayed_gradients_flow_and_amax_gets_zero_cotangent():
    from pytorch_distributed_training_tpu.ops.quant import int8_dense_delayed

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(8, 32)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(32, 16)), jnp.float32)
    amax = jnp.max(jnp.abs(x))

    def loss(x, w, a):
        y, _ = int8_dense_delayed(x, w, a, 1, "full")
        return jnp.mean(y**2)

    dx, dw, da = jax.grad(loss, argnums=(0, 1, 2))(x, w, amax)
    assert np.isfinite(np.asarray(dx)).all()
    assert np.isfinite(np.asarray(dw)).all()
    assert np.abs(np.asarray(dx)).max() > 0
    assert float(da) == 0.0  # scales are STE constants


# ------------------------------------------------------- delayed: train step

def test_delayed_step0_matches_dynamic_after_calibration():
    """With accum=1 and calibration on the training batch itself, step 0 of
    the delayed path quantizes with (nearly) the scales the dynamic path
    computes — deeper sites differ only because the calibration forward ran
    under the init-batch scales at earlier layers (a one-pass fixed-point
    error, ~1e-5 relative)."""
    batch = jax.tree.map(
        jnp.asarray, make_batch(np.random.default_rng(2), 1, 8)
    )
    micro0 = jax.tree.map(lambda x: x[0], batch)

    s_dyn = quant_state(delayed=False)
    s_del = quant_state(delayed=True)
    assert s_dyn.quant is None and s_del.quant is not None
    s_del = calibrate_quant(s_del, micro0)
    # calibration observed real data, not the init dummy batch
    assert all(
        float(a) > 0 for a in jax.tree.leaves(s_del.quant)
    )

    step = make_train_step(grad_accum_steps=1, log_grad_norm=False)
    s_dyn2, m_dyn = step(s_dyn, batch)
    s_del2, m_del = step(s_del, batch)
    np.testing.assert_allclose(
        float(m_dyn["loss"]), float(m_del["loss"]), rtol=1e-4
    )
    for a, b in zip(
        jax.tree.leaves(s_dyn2.params), jax.tree.leaves(s_del2.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-4
        )


def test_delayed_amax_carries_across_microbatches_and_steps():
    """The quant collection must update every microbatch (scan carry) and
    persist into the returned state."""
    rng = np.random.default_rng(3)
    s = quant_state(delayed=True)
    batch = jax.tree.map(jnp.asarray, make_batch(rng, 4, 4))
    s = calibrate_quant(s, jax.tree.map(lambda x: x[0], batch))
    before = jax.tree.map(float, jax.device_get(s.quant))

    step = make_train_step(grad_accum_steps=4, log_grad_norm=False)
    losses = []
    for _ in range(3):
        b = make_batch(rng, 4, 4)
        b["labels"] = (b["input_ids"][:, :, 0] % 2).astype(np.int32)
        s, m = step(s, jax.tree.map(jnp.asarray, b))
        losses.append(float(m["loss"]))
    after = jax.tree.map(float, jax.device_get(s.quant))
    assert before != after  # amaxes tracked the data
    assert all(np.isfinite(l) for l in losses)
    assert int(s.step) == 3


def test_delayed_with_scan_layers_and_branch_trunks():
    """The nn.scan / nn.vmap trunks declare the "quant" collection on their
    stacked axis — init must produce per-layer / per-branch amaxes instead
    of a flax lifting error."""
    s = quant_state(delayed=True, scan_layers=True)
    assert s.quant is not None
    leaves = jax.tree.leaves(s.quant)
    # scan trunk: per-layer amaxes stacked on the leading [num_layers] dim
    assert any(getattr(l, "shape", ()) and l.shape[0] == 2 for l in leaves)

    from pytorch_distributed_training_tpu.models.branch import (
        BranchEnsembleClassifier,
    )

    cfg = model_preset(
        "tiny", compute_dtype="float32", hidden_dropout=0.0,
        attention_dropout=0.0, matmul_impl="int8_full", quant_delayed=True,
    )
    model = BranchEnsembleClassifier(cfg, n_branches=3)
    batch = {
        "input_ids": jnp.ones((2, 16), jnp.int32),
        "attention_mask": jnp.ones((2, 16), jnp.int32),
        "token_type_ids": jnp.zeros((2, 16), jnp.int32),
    }
    variables = model.init(jax.random.key(0), **batch, deterministic=True)
    assert "quant" in variables
    assert any(
        getattr(l, "shape", ()) and l.shape[0] == 3
        for l in jax.tree.leaves(variables["quant"])
    )


# ----------------------------------------------------------- sharded meshes

@pytest.mark.slow
@pytest.mark.parametrize("delayed", [False, True], ids=["dynamic", "delayed"])
def test_int8_full_under_fsdp_and_tp_matches_dp(eight_devices, delayed):
    """VERDICT r3 weak-#4: int8_full under fsdp/tp sharding. Per-tensor
    absmax becomes a cross-shard reduce under GSPMD; the result must match
    the replicated (DP) int8 run bit-for-bit in fp32 compute."""
    batch = make_batch(np.random.default_rng(4), 2, 16)

    from pytorch_distributed_training_tpu.comms.ingest import make_global_batch
    from pytorch_distributed_training_tpu.comms.mesh import TRAIN_BATCH_PSPEC

    results = {}
    for name, mesh_cfg, policy in [
        ("dp", MeshConfig(data=8), ShardingPolicy()),
        ("fsdp", MeshConfig(data=2, fsdp=4),
         ShardingPolicy(fsdp=True, fsdp_min_size=128)),
        ("tp", MeshConfig(data=2, model=4), ShardingPolicy(tp=True)),
    ]:
        mesh = build_mesh(mesh_cfg)
        s = quant_state(delayed=delayed)
        shardings = state_shardings(s, policy, mesh)
        s = shard_state(s, shardings)
        placed = make_global_batch(
            mesh, jax.tree.map(np.asarray, batch), pspec=TRAIN_BATCH_PSPEC
        )
        if delayed:
            s = calibrate_quant(s, jax.tree.map(lambda x: x[0], placed))
        step = make_train_step(
            grad_accum_steps=2, mesh=mesh, state_shardings=shardings,
            log_grad_norm=False,
        )
        s2, m = step(s, placed)
        results[name] = (
            float(m["loss"]),
            np.concatenate(
                [np.ravel(jax.device_get(x)) for x in jax.tree.leaves(s2.params)]
            ),
        )
    for name in ("fsdp", "tp"):
        np.testing.assert_allclose(
            results["dp"][0], results[name][0], rtol=2e-5,
            err_msg=f"{name} loss diverged from dp",
        )
        np.testing.assert_allclose(
            results["dp"][1], results[name][1], atol=3e-5,
            err_msg=f"{name} params diverged from dp",
        )


# ------------------------------------------------------------- checkpointing

@pytest.mark.slow
def test_quant_state_checkpoint_roundtrip(tmp_path):
    """Delayed amaxes ride checkpoints: step N quantizes with step N-1's
    scales, so resume must restore them exactly."""
    from pytorch_distributed_training_tpu.train import checkpoint as ckpt

    rng = np.random.default_rng(5)
    batch = jax.tree.map(jnp.asarray, make_batch(rng, 2, 4))
    s = quant_state(delayed=True)
    s = calibrate_quant(s, jax.tree.map(lambda x: x[0], batch))
    step = make_train_step(grad_accum_steps=2, log_grad_norm=False)
    s, _ = step(s, batch)

    ckpt.save_checkpoint(str(tmp_path / "q"), s)
    fresh = quant_state(delayed=True)
    restored = ckpt.restore_checkpoint(str(tmp_path / "q"), fresh)
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(
            np.asarray(a), np.asarray(b)
        ),
        jax.device_get(s.quant),
        jax.device_get(restored.quant),
    )
    # and the next step from the restored state matches exactly
    b2 = jax.tree.map(jnp.asarray, make_batch(rng, 2, 4))
    s_a, m_a = step(s, b2)
    s_b, m_b = step(restored, b2)
    np.testing.assert_array_equal(
        np.asarray(m_a["loss"]), np.asarray(m_b["loss"])
    )


@pytest.mark.slow
def test_quant_flag_mismatch_restore_message(tmp_path):
    """Saving WITHOUT delayed quant and resuming WITH it is a structural
    tree mismatch (the 'quant' subtree exists iff the saving run had the
    flag on); restore must relabel it with the flag name — detected from
    the checkpoint's metadata, not the error text (ADVICE r4)."""
    from pytorch_distributed_training_tpu.train import checkpoint as ckpt

    rng = np.random.default_rng(6)
    batch = jax.tree.map(jnp.asarray, make_batch(rng, 2, 4))
    s = quant_state(delayed=False)
    step = make_train_step(grad_accum_steps=2, log_grad_norm=False)
    s, _ = step(s, batch)
    ckpt.save_checkpoint(str(tmp_path / "q"), s)

    fresh = quant_state(delayed=True)
    fresh = calibrate_quant(fresh, jax.tree.map(lambda x: x[0], batch))
    with pytest.raises(ValueError, match="--quant-delayed"):
        ckpt.restore_checkpoint(str(tmp_path / "q"), fresh)


@pytest.mark.slow
def test_trainer_resume_keeps_checkpointed_quant_scales(eight_devices, tmp_path):
    """A resumed delayed-quant run restores the checkpoint's amaxes and
    skips re-calibration (the trajectory depends on the carried scales —
    re-observing them would fork it; also saves a wasted forward compile)."""
    from pytorch_distributed_training_tpu.train.loop import Trainer

    def trainer(resume):
        mcfg = model_preset(
            "tiny", compute_dtype="float32",
            matmul_impl="int8_full", quant_delayed=True,
        )
        tcfg = TrainConfig(
            num_epochs=1, global_batch_size=16, micro_batch_size=8,
            eval_batch_size=16, train_size=32, eval_size=16,
            max_seq_length=16, bf16=False, log_every=0,
            checkpoint_dir=str(tmp_path / "ck"), resume=resume,
        )
        return Trainer(mcfg, tcfg, MeshConfig(data=8), ShardingPolicy(),
                       task="synthetic")

    t1 = trainer(resume=False)
    t1.run()
    saved = jax.tree.map(float, jax.device_get(t1.state.quant))

    t2 = trainer(resume=True)  # restores the epoch-end checkpoint
    restored = jax.tree.map(float, jax.device_get(t2.state.quant))
    assert saved == restored  # not re-calibrated from the first batch
