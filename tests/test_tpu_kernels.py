"""On-TPU kernel tier (VERDICT r2 #6): the kernel behaviors the CPU suite
cannot observe — ``pltpu.prng_random_bits`` is all-zeros in interpret mode
(NOTES.md), so in-kernel dropout statistics, real-Mosaic numerics, and
kernel-under-shard_map execution need the actual chip.

Run: PDT_TPU_TESTS=1 python -m pytest tests/ -m tpu -q
(the conftest leaves the axon backend alone and skips the CPU-mesh tests).
All tests here are single-chip; the shard_map case runs on the trivial
1-device mesh, which still exercises the real shard_map + Mosaic path.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

pytestmark = pytest.mark.tpu


@pytest.fixture(scope="module", autouse=True)
def require_tpu():
    if jax.default_backend() != "tpu":
        pytest.skip("no TPU backend attached")


def test_mask_scale_keep_rate_statistics():
    """In-kernel Bernoulli keep rate within 3 sigma of 1-rate, and the
    nonzero values are exactly 1/(1-rate)."""
    from pytorch_distributed_training_tpu.ops.dropout import (
        mask_scale_pallas,
    )

    rate = 0.25
    n = 512 * 1024
    out = np.asarray(
        mask_scale_pallas(
            jax.random.key(7, impl="rbg"), (n // 128, 128), rate, jnp.float32
        )
    )
    keep = (out != 0).mean()
    sigma = (rate * (1 - rate) / n) ** 0.5
    assert abs(keep - (1 - rate)) < 3 * sigma, keep
    np.testing.assert_allclose(out[out != 0], 1.0 / (1 - rate), rtol=1e-6)


def test_dal_kernel_dropout_statistics_and_bwd_mask_match():
    """dropout-add-LN with in-kernel dropout: output differs from the
    deterministic path on ~rate of positions, and fwd/bwd reuse the same
    mask (gradient of sum w.r.t. h is zero exactly where h was dropped)."""
    from pytorch_distributed_training_tpu.ops.layer_norm import (
        dropout_add_layer_norm,
    )

    rng = np.random.default_rng(0)
    h = jnp.asarray(rng.normal(size=(64, 128, 512)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(64, 128, 512)), jnp.float32)
    scale = jnp.ones((512,), jnp.float32)
    bias = jnp.zeros((512,), jnp.float32)
    key = jax.random.key(3, impl="rbg")

    # weighted sum, NOT a plain sum: with unit scale the sum of LN outputs
    # is identically zero (rows are mean-centered), which zeroes the
    # gradient everywhere and would hide the mask
    w = jnp.asarray(rng.normal(size=(64, 128, 512)), jnp.float32)

    def out_sum(hh):
        return jnp.sum(
            dropout_add_layer_norm(
                hh, x, scale, bias, rate=0.25, dropout_rng=key,
                deterministic=False, site=0,
            ).astype(jnp.float32)
            * w
        )

    g = np.asarray(jax.grad(out_sum)(h))
    dropped = (g == 0.0).mean()
    # dL/dh == 0 exactly at dropped positions (mask regenerated in bwd)
    sigma = (0.25 * 0.75 / g.size) ** 0.5
    assert abs(dropped - 0.25) < 5 * sigma, dropped


def test_fused_layer_norm_bwd_parity_on_chip():
    """Real-Mosaic fused LN gradients vs the jnp reference math."""
    from pytorch_distributed_training_tpu.ops.layer_norm import (
        layer_norm,
        reference_layer_norm,
    )

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(1024, 512)), jnp.float32)
    scale = jnp.asarray(rng.normal(size=(512,)), jnp.float32)
    bias = jnp.asarray(rng.normal(size=(512,)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(1024, 512)), jnp.float32)

    def loss_fused(x, s, b):
        return jnp.sum(layer_norm(x, s, b, eps=1e-12) * w)

    def loss_ref(x, s, b):
        return jnp.sum(reference_layer_norm(x, s, b, eps=1e-12) * w)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2))(x, scale, bias)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(x, scale, bias)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b_), atol=2e-4, rtol=2e-4
        )


def test_flash_whole_seq_fwd_bwd_parity_on_chip():
    """The whole-seq (grid-(B,)) flash path vs reference einsum attention,
    forward and gradients, dropout off."""
    from pytorch_distributed_training_tpu.ops.attention import (
        make_attention_bias,
        reference_attention,
    )
    from pytorch_distributed_training_tpu.ops.flash_attention import (
        flash_attention,
    )

    rng = np.random.default_rng(2)
    q, k, v = (
        jnp.asarray(rng.normal(size=(4, 128, 8, 64)), jnp.bfloat16)
        for _ in range(3)
    )
    mask = np.ones((4, 128), np.int32)
    mask[1, 100:] = 0
    bias = make_attention_bias(jnp.asarray(mask))

    def loss(fn, q, k, v):
        return jnp.sum(fn(q, k, v, bias).astype(jnp.float32) ** 2)

    of = flash_attention(q, k, v, bias)
    orf = reference_attention(q, k, v, bias)
    np.testing.assert_allclose(
        np.asarray(of[0], np.float32), np.asarray(orf[0], np.float32),
        atol=2e-2, rtol=2e-2,
    )
    gf = jax.grad(lambda *a: loss(flash_attention, *a), argnums=(0, 1, 2))(
        q, k, v
    )
    gr = jax.grad(
        lambda *a: loss(reference_attention, *a), argnums=(0, 1, 2)
    )(q, k, v)
    for a, b_ in zip(gf, gr):
        np.testing.assert_allclose(
            np.asarray(a[0], np.float32), np.asarray(b_[0], np.float32),
            atol=5e-2, rtol=5e-2,
        )


def test_flash_multiblock_512_numerics_on_chip():
    """512-wide blocks (the gpt2 default) vs reference, causal, seq 1024."""
    from pytorch_distributed_training_tpu.ops.attention import (
        reference_attention,
    )
    from pytorch_distributed_training_tpu.ops.flash_attention import (
        flash_attention,
    )

    rng = np.random.default_rng(4)
    q, k, v = (
        jnp.asarray(rng.normal(size=(2, 1024, 4, 64)), jnp.bfloat16)
        for _ in range(3)
    )
    out = flash_attention(q, k, v, None, causal=True)
    ref = reference_attention(q, k, v, None, causal=True)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32),
        atol=2e-2, rtol=2e-2,
    )


def test_flash_fused_bwd_multiblock_on_chip():
    """The FUSED single-pass backward (r5 default: _dqkv_kernel, dq in a
    VMEM scratch accumulated across the sequential k-block grid) at the
    gpt2 block geometry, on real Mosaic: gradients vs reference einsum
    attention AND vs the classic two-pass scheme."""
    from pytorch_distributed_training_tpu.ops import flash_attention as fa
    from pytorch_distributed_training_tpu.ops.attention import (
        reference_attention,
    )
    from pytorch_distributed_training_tpu.ops.flash_attention import (
        flash_attention,
    )

    rng = np.random.default_rng(11)
    q, k, v = (
        jnp.asarray(rng.normal(size=(1, 1024, 2, 64)), jnp.bfloat16)
        for _ in range(3)
    )
    cot = jnp.asarray(rng.normal(size=q.shape), jnp.float32)

    def loss(attn, q, k, v):
        return jnp.sum(attn(q, k, v, None, causal=True).astype(jnp.float32) * cot)

    g_ref = jax.grad(
        lambda *a: loss(reference_attention, *a), argnums=(0, 1, 2)
    )(q, k, v)
    orig = fa.FUSED_BWD
    grads = {}
    try:
        for mode in (True, False):
            fa.FUSED_BWD = mode
            grads[mode] = jax.grad(
                lambda *a: loss(flash_attention, *a), argnums=(0, 1, 2)
            )(q, k, v)
    finally:
        fa.FUSED_BWD = orig
    for gf, gt, gr, name in zip(grads[True], grads[False], g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(gf, np.float32), np.asarray(gt, np.float32),
            atol=2e-2, rtol=2e-2,
            err_msg=f"fused vs two-pass d{name} on chip",
        )
        np.testing.assert_allclose(
            np.asarray(gf, np.float32), np.asarray(gr, np.float32),
            atol=5e-2, rtol=5e-2,
            err_msg=f"fused vs reference d{name} on chip",
        )


def test_kernels_under_shard_map_on_chip():
    """shard_map-routed kernel dispatch with REAL Mosaic lowering — the
    1-device mesh is trivial but executes the exact code path sharded
    meshes take (ops/dispatch.py), which interpret mode can't reach."""
    from pytorch_distributed_training_tpu.comms.mesh import build_mesh
    from pytorch_distributed_training_tpu.ops import dispatch
    from pytorch_distributed_training_tpu.ops.layer_norm import (
        layer_norm,
        reference_layer_norm,
    )

    from pytorch_distributed_training_tpu.ops.dropout import raw_dropout

    mesh = build_mesh()
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=(8, 128, 512)), jnp.float32)
    scale = jnp.ones((512,), jnp.float32)
    bias = jnp.zeros((512,), jnp.float32)
    ref = reference_layer_norm(x, scale, bias, eps=1e-12)
    before = dispatch.KERNEL_DISPATCH_COUNTS["layer_norm"]
    with dispatch.use_kernel_mesh(mesh), dispatch.force_shard_map():
        assert dispatch.mode() == "shard_map"
        out = layer_norm(x, scale, bias, eps=1e-12)
        drop = raw_dropout(x, 0.25, jax.random.key(0, impl="rbg"), "kernel")
    assert dispatch.KERNEL_DISPATCH_COUNTS["layer_norm"] == before + 1
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
    )
    # real in-kernel PRNG through the shard_map seed-offset path
    keep = (np.asarray(drop) != 0).mean()
    assert abs(keep - 0.75) < 0.02, keep

def test_int8_dense_numerics_on_real_mxu():
    """VERDICT r3 #1b: quantize → int8 dot → rescale against an fp32
    reference ON THE REAL MXU (the int8 systolic path; CPU emulates the
    same math but not the hardware's int8x int8 → int32 accumulate)."""
    from pytorch_distributed_training_tpu.ops.quant import (
        int8_dense,
        int8_dense_delayed,
        quantize_per_channel,
        quantize_per_tensor,
    )

    rng = np.random.default_rng(11)
    x = jnp.asarray(rng.normal(size=(64, 256)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(256, 128)), jnp.float32)

    # hand-computed expected result from the quantization grid itself
    xq, sx = quantize_per_tensor(x)
    wq, sw = quantize_per_channel(w, contract_axis=(0,))
    expected = (
        np.asarray(xq, np.int32) @ np.asarray(wq, np.int32)
    ).astype(np.float32) * float(sx) * np.asarray(sw, np.float32)

    got = np.asarray(jax.jit(int8_dense, static_argnums=(2, 3))(x, w, 1, "full"))
    np.testing.assert_allclose(got, expected, rtol=1e-5, atol=1e-4)
    # and against the fp32 reference: pure quantization error, bounded by
    # the per-axis scale resolution (|err| <~ 0.5*sx*|w|_col1 + 0.5*sw*|x|_row1)
    ref = np.asarray(x) @ np.asarray(w)
    denom = np.abs(ref).max()
    assert np.abs(got - ref).max() / denom < 0.05

    # delayed variant with the true amax is bit-identical to dynamic
    y_del, new_amax = jax.jit(
        int8_dense_delayed, static_argnums=(3, 4)
    )(x, w, jnp.max(jnp.abs(x)), 1, "full")
    np.testing.assert_array_equal(np.asarray(y_del), got)
    np.testing.assert_allclose(
        float(new_amax), float(jnp.max(jnp.abs(x))), rtol=1e-6
    )
