"""SPMD analysis unit tests: the four static lint rules
(analysis/rules/spmd.py) on synthetic fixtures, the compiled-HLO
collective extractor + ICI/DCN cost model (analysis/spmd/hlo.py) on a
hand-written dump, expected-collective manifests and the ``comm_audit``
guard (analysis/spmd/manifest.py), and the ``--rules`` driver filter.
Everything here is jax-compile-free and tier-1 cheap; the end-to-end
footprint pins over real compiled programs live in test_parallel_mp.py."""

import textwrap

import pytest

from pytorch_distributed_training_tpu.analysis.guards import GuardViolation
from pytorch_distributed_training_tpu.analysis.lint import (
    lint_paths,
    lint_source,
    select_rules,
)
from pytorch_distributed_training_tpu.analysis.rules import spmd
from pytorch_distributed_training_tpu.analysis.spmd.hlo import (
    COLLECTIVE_KINDS,
    CostModel,
    extract_collectives,
    summarize_collectives,
)
from pytorch_distributed_training_tpu.analysis.spmd.manifest import (
    CommManifest,
    comm_audit,
    serve_manifest,
    train_manifest,
)
from pytorch_distributed_training_tpu.telemetry.registry import (
    MetricsRegistry,
)
from pytorch_distributed_training_tpu.utils.config import MeshConfig
from test_guards import ListSink  # sibling module (pytest sys.path)


def _findings(src, rule_id):
    out = lint_source(textwrap.dedent(src), rules=(spmd,))
    return [f for f in out if f.rule == rule_id]


# ------------------------------------------------------- pspec-mismatch


def test_pspec_unknown_axis_flagged():
    (f,) = _findings(
        """
        from jax.sharding import PartitionSpec as P
        SPEC = P("data", "modle")
        """,
        spmd.PSPEC_RULE_ID,
    )
    assert "'modle'" in f.message


def test_pspec_duplicate_axis_flagged():
    (f,) = _findings(
        """
        from jax.sharding import PartitionSpec
        SPEC = PartitionSpec("data", "data")
        """,
        spmd.PSPEC_RULE_ID,
    )
    assert "two different dims" in f.message


def test_pspec_canonical_spec_clean():
    assert not _findings(
        """
        from jax.sharding import PartitionSpec as P
        SPEC = P(("data", "fsdp"), None, "model")
        """,
        spmd.PSPEC_RULE_ID,
    )


def test_canonical_axes_pinned_to_mesh_config():
    # spmd.py keeps the universe as literals (the linter must not import
    # jax); this pin makes MeshConfig drift fail loudly.
    assert spmd.CANONICAL_AXES == set(MeshConfig.AXIS_NAMES) | {"seq"}


# ------------------------------------------------- shardmap-axis-misuse


def test_collective_unknown_axis_flagged():
    (f,) = _findings(
        """
        import jax
        def inner(x):
            return jax.lax.psum(x, "batch")
        """,
        spmd.AXIS_RULE_ID,
    )
    assert "psum" in f.message and "'batch'" in f.message


def test_collective_traced_without_binding_flagged():
    (f,) = _findings(
        """
        import jax
        @jax.jit
        def step(x):
            return jax.lax.psum(x, "data")
        """,
        spmd.AXIS_RULE_ID,
    )
    assert "no" in f.message and "shard_map" in f.message


def test_collective_under_shard_map_clean():
    assert not _findings(
        """
        import jax
        from jax.experimental.shard_map import shard_map
        def inner(x):
            return jax.lax.psum(x, "data")
        f = shard_map(inner, mesh=None, in_specs=None, out_specs=None)
        """,
        spmd.AXIS_RULE_ID,
    )


def test_dispatch_shard_map_binds_axis_too():
    # the normalized ops/dispatch wrapper counts as a binder
    assert not _findings(
        """
        import jax
        from pytorch_distributed_training_tpu.ops import dispatch
        def inner(x):
            return jax.lax.psum(x, "data")
        f = dispatch.shard_map(inner, mesh=None, in_specs=None,
                               out_specs=None)
        """,
        spmd.AXIS_RULE_ID,
    )


# ---------------------------------------------------- collective-in-loop


def test_collective_in_scan_body_flagged():
    (f,) = _findings(
        """
        import jax
        from jax.experimental.shard_map import shard_map
        def body(carry, x):
            return carry + jax.lax.psum(x, "data"), None
        def outer(xs):
            return jax.lax.scan(body, 0.0, xs)
        f = shard_map(body, mesh=None, in_specs=None, out_specs=None)
        """,
        spmd.LOOP_RULE_ID,
    )
    assert "PER ITERATION" in f.message


def test_collective_in_host_loop_flagged():
    (f,) = _findings(
        """
        import jax
        from jax.experimental.shard_map import shard_map
        def inner(x):
            out = 0.0
            for _ in range(4):
                out = out + jax.lax.psum(x, "data")
            return out
        f = shard_map(inner, mesh=None, in_specs=None, out_specs=None)
        """,
        spmd.LOOP_RULE_ID,
    )
    assert "host loop" in f.message


def test_axis_index_in_scan_body_not_a_loop_finding():
    assert not _findings(
        """
        import jax
        from jax.experimental.shard_map import shard_map
        def body(carry, x):
            return carry + jax.lax.axis_index("data"), None
        def outer(xs):
            return jax.lax.scan(body, 0, xs)
        f = shard_map(body, mesh=None, in_specs=None, out_specs=None)
        """,
        spmd.LOOP_RULE_ID,
    )


def test_collective_after_loop_clean():
    assert not _findings(
        """
        import jax
        from jax.experimental.shard_map import shard_map
        def inner(xs):
            out = 0.0
            for x in xs:
                out = out + x
            return jax.lax.psum(out, "data")
        f = shard_map(inner, mesh=None, in_specs=None, out_specs=None)
        """,
        spmd.LOOP_RULE_ID,
    )


# -------------------------------------------------- implicit-replication


def test_large_literal_init_in_jit_flagged():
    (f,) = _findings(
        """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def step(x):
            buf = jnp.zeros((256, 256), jnp.float32)
            return x + buf
        """,
        spmd.REPL_RULE_ID,
    )
    assert "65536" in f.message and "REPLICATED" in f.message


def test_small_or_untraced_inits_clean():
    assert not _findings(
        """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def step(x):
            return x + jnp.zeros((8, 8), jnp.float32)   # small: noise
        def host_side():
            return jnp.zeros((512, 512))                # not traced
        buf = jnp.zeros((1024, 1024))                   # module level
        """,
        spmd.REPL_RULE_ID,
    )


# ------------------------------------------------------- driver plumbing


def test_select_rules_accepts_all_spmd_ids():
    mods = select_rules(spmd.RULE_IDS)
    assert spmd in mods


def test_select_rules_rejects_unknown_id():
    with pytest.raises(ValueError, match="unknown rule id"):
        select_rules(("pspec-mismatch", "no-such-rule"))


def test_lint_paths_rule_filter(tmp_path):
    # one pspec finding + one mutable-default finding in the same file;
    # the --rules filter must report only the requested id
    path = tmp_path / "fixture.py"
    path.write_text(textwrap.dedent(
        """
        from jax.sharding import PartitionSpec as P
        SPEC = P("modle")
        def f(x, acc=[]):
            acc.append(x)
            return acc
        """
    ))
    full = lint_paths([str(path)])
    assert {f.rule for f in full.findings} >= {
        "pspec-mismatch", "mutable-default"
    }
    subset = lint_paths([str(path)], rule_ids=("pspec-mismatch",))
    assert {f.rule for f in subset.findings} == {"pspec-mismatch"}


# --------------------------------------------------- HLO extractor + cost

_HLO = """\
HloModule step

ENTRY %main {
  %all-gather.1 = f32[16,256]{1,0} all-gather(f32[2,256]{1,0} %p0), replica_groups={{0,1,2,3},{4,5,6,7}}, dimensions={0}
  %all-reduce-start.2 = (f32[128]{0}, f32[128]{0}) all-reduce-start(f32[128]{0} %p1), replica_groups=[2,4]<=[8], to_apply=%add
  %all-reduce-done.2 = f32[128]{0} all-reduce-done(%all-reduce-start.2)
  %reduce-scatter.3 = f32[32]{0} reduce-scatter(f32[256]{0} %p2), replica_groups={}, dimensions={0}, to_apply=%add
  %add.4 = f32[128]{0} add(f32[128]{0} %a, f32[128]{0} %b)
  ROOT %collective-permute.5 = bf16[64]{0} collective-permute(bf16[64]{0} %p3), source_target_pairs={{0,1},{1,2},{2,3},{3,0}}
}
"""


def test_extract_collectives_synthetic_dump():
    cs = extract_collectives(_HLO, world_size=8)
    assert [c.kind for c in cs] == [
        "all-gather", "all-reduce", "reduce-scatter", "collective-permute",
    ]
    ag, ar, rs, cp = cs
    # explicit replica groups: size of the first group
    assert (ag.bytes, ag.group_size, ag.asynchronous) == (16 * 256 * 4, 4,
                                                          False)
    # async start: tuple shape counts the result buffer once, iota groups
    assert (ar.bytes, ar.group_size, ar.asynchronous) == (128 * 4, 4, True)
    # replica_groups={} means "all devices" -> world_size
    assert (rs.bytes, rs.group_size) == (32 * 4, 8)
    # permute: distinct devices in the pair list; bf16 = 2 bytes
    assert (cp.bytes, cp.group_size, cp.dtype) == (64 * 2, 4, "bf16")
    # -done halves and plain ops never match
    assert all("done" not in c.name and c.kind != "add" for c in cs)


def test_cost_model_ring_bytes_and_links():
    cm = CostModel(ici_gbps=90.0, dcn_gbps=12.5, devices_per_host=8)
    ag, ar, rs, cp = extract_collectives(_HLO, world_size=8)
    assert cm.moved_bytes(ag) == int(ag.bytes * 3 / 4)       # (g-1)/g
    assert cm.moved_bytes(ar) == int(2 * ar.bytes * 3 / 4)   # RS + AG
    assert cm.moved_bytes(rs) == rs.bytes * 7                # result * (g-1)
    assert cm.moved_bytes(cp) == cp.bytes                    # point-to-point
    assert cm.link(8) == "ici" and cm.link(9) == "dcn"
    # group-of-1 (or unknown) moves nothing
    solo = ag.__class__(name="x", kind="all-gather", dtype="f32", bytes=64,
                        group_size=1, line=1, asynchronous=False)
    assert cm.moved_bytes(solo) == 0


def test_summarize_collectives_totals():
    s = summarize_collectives(extract_collectives(_HLO, world_size=8))
    assert s["count"] == 4
    assert set(s["by_kind"]) == {
        "all-gather", "all-reduce", "reduce-scatter", "collective-permute",
    }
    assert s["total_bytes"] == 16384 + 512 + 128 + 128
    assert s["total_moved_bytes"] == sum(
        v["moved_bytes"] for v in s["by_kind"].values()
    )
    # every group here fits in one 8-device host -> all traffic is ICI
    assert s["dcn_moved_bytes"] == 0
    assert s["ici_moved_bytes"] == s["total_moved_bytes"]
    assert s["est_time_s"] > 0


# ----------------------------------------------------- manifests + audit


class _Shape:
    """mesh stand-in: train_manifest only reads ``mesh.shape``."""

    def __init__(self, **shape):
        self.shape = shape


def test_manifest_rejects_unknown_kind():
    with pytest.raises(ValueError, match="unknown collective kind"):
        CommManifest("bad", allowed=("all-gatherr",))


def test_manifest_check_deviations():
    m = CommManifest("m", allowed=("all-reduce",),
                     required=("all-reduce",), max_bytes=100)
    summary = {
        "by_kind": {"all-gather": {"count": 2, "bytes": 64}},
        "total_bytes": 640,
    }
    devs = m.check(summary)
    assert any("unexpected all-gather x2" in d for d in devs)
    assert "required all-reduce absent" in devs
    assert any("exceeds manifest ceiling" in d for d in devs)
    clean = {"by_kind": {"all-reduce": {"count": 1, "bytes": 8}},
             "total_bytes": 8}
    assert m.check(clean) == []


def test_train_manifest_shapes_by_mesh_axes():
    assert train_manifest(_Shape(data=1)).allowed == ()
    assert train_manifest(_Shape(data=8)).allowed == ("all-reduce",)
    fsdp = train_manifest(_Shape(data=2, fsdp=4), fsdp_sharded=True)
    assert set(fsdp.allowed) == {"all-reduce", "all-gather",
                                "reduce-scatter"}
    assert fsdp.required == ("all-gather",)
    # fsdp axis present but nothing actually sharded: no gather required
    assert train_manifest(_Shape(data=2, fsdp=4)).required == ()
    assert "collective-permute" in train_manifest(
        _Shape(data=4, stage=2)).allowed
    assert "all-to-all" in train_manifest(_Shape(model=4)).allowed


def test_serve_manifest_pins_single_device_to_silence():
    assert serve_manifest(1).allowed == ()
    assert serve_manifest(8).allowed == COLLECTIVE_KINDS


class _Stage:
    def __init__(self, text):
        self._text = text

    def as_text(self):
        if isinstance(self._text, Exception):
            raise self._text
        return self._text


def _registry():
    reg = MetricsRegistry()
    sink = ListSink()
    reg.attach_sink(sink)
    return reg, sink


def test_comm_audit_conforming_records_ok():
    reg, sink = _registry()
    manifest = CommManifest("step", allowed=(
        "all-gather", "all-reduce", "reduce-scatter", "collective-permute",
    ))
    rec = comm_audit("step", _Stage(_HLO), manifest, registry=reg,
                     mode="strict", world_size=8)
    assert rec["ok"] is True and rec["deviations"] == []
    (emitted,) = sink.of("comm_audit")
    assert emitted["count"] == 4 and emitted["manifest"] == "step"
    assert "guards/comm_deviations" not in reg.snapshot()["counters"]


def test_comm_audit_record_mode_logs_without_raising():
    reg, sink = _registry()
    rec = comm_audit("step", _Stage(_HLO), CommManifest("silent"),
                     registry=reg, mode="record", world_size=8)
    assert rec["ok"] is False and len(rec["deviations"]) == 4
    assert reg.snapshot()["counters"]["guards/comm_deviations"] == 4
    (emitted,) = sink.of("comm_audit")
    assert emitted["ok"] is False


def test_comm_audit_strict_raises_on_deviation():
    reg, sink = _registry()
    manifest = CommManifest("gathered", allowed=COLLECTIVE_KINDS,
                            required=("all-to-all",))
    with pytest.raises(GuardViolation,
                       match="required all-to-all absent"):
        comm_audit("step", _Stage(_HLO), manifest, registry=reg,
                   mode="strict", world_size=8)
    (emitted,) = sink.of("comm_audit")    # record lands before the raise
    assert emitted["ok"] is False


def test_comm_audit_survives_backends_without_text():
    reg, sink = _registry()
    rec = comm_audit("step", _Stage(RuntimeError("no dump")),
                     CommManifest("m"), registry=reg, mode="strict")
    assert rec["ok"] is None and "no dump" in rec["error"]
    (emitted,) = sink.of("comm_audit")
    assert emitted["ok"] is None
