"""Telemetry subsystem tests: registry semantics, JSONL sink round-trip,
header schema, straggler stats, and the end-to-end Trainer integration —
a synthetic-task run with ``metrics_dir`` set must write a parseable JSONL
stream whose final epoch record matches ``trainer.history[-1]``, rendered
by scripts/summarize_metrics.py.
"""

import importlib.util
import json
import logging
import math
import os

import numpy as np
import pytest

from pytorch_distributed_training_tpu.telemetry import (
    JsonlSink,
    MetricsRegistry,
    epoch_straggler_stats,
    get_registry,
    run_metadata,
    set_registry,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _small_trainer(**tcfg_kw):
    """Tiny synthetic-task Trainer on the 4x2 CPU mesh (the
    test_trainer_integration recipe)."""
    from pytorch_distributed_training_tpu.parallel import ShardingPolicy
    from pytorch_distributed_training_tpu.train.loop import Trainer
    from pytorch_distributed_training_tpu.utils.config import (
        MeshConfig,
        TrainConfig,
        model_preset,
    )

    mcfg = model_preset("tiny", compute_dtype="float32")
    defaults = dict(
        num_epochs=1,
        global_batch_size=32,
        micro_batch_size=16,
        eval_batch_size=32,
        learning_rate=3e-3,
        warmup_steps=10,
        log_every=0,
        bf16=False,
        train_size=128,
        eval_size=32,
    )
    defaults.update(tcfg_kw)
    return Trainer(
        mcfg, TrainConfig(**defaults), MeshConfig(data=4, fsdp=2),
        ShardingPolicy(fsdp=True, fsdp_min_size=128),
        task="synthetic",
    )


def _load_summarizer():
    spec = importlib.util.spec_from_file_location(
        "summarize_metrics",
        os.path.join(REPO_ROOT, "scripts", "summarize_metrics.py"),
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------- registry


def test_registry_counter_gauge_timer_semantics():
    reg = MetricsRegistry()
    reg.inc("c")
    reg.inc("c", 2)
    reg.gauge("g", 1.0)
    reg.gauge("g", 7.5)  # gauges hold the LAST value
    reg.observe("t", 0.1)
    reg.observe("t", 0.3)
    snap = reg.snapshot()
    assert snap["counters"]["c"] == 3
    assert snap["gauges"]["g"] == 7.5
    t = snap["timers"]["t"]
    assert t["count"] == 2
    assert t["total_s"] == pytest.approx(0.4)
    assert t["mean_s"] == pytest.approx(0.2)
    assert t["min_s"] == pytest.approx(0.1)
    assert t["max_s"] == pytest.approx(0.3)
    assert t["min_s"] <= t["p50_s"] <= t["p95_s"] <= t["max_s"]


def test_registry_snapshot_reset_clears_window():
    reg = MetricsRegistry()
    reg.inc("c")
    reg.observe("t", 1.0)
    first = reg.snapshot(reset=True)
    assert first["counters"]["c"] == 1
    second = reg.snapshot()
    assert second["counters"] == {}
    assert second["timers"] == {}


def test_registry_timer_context_manager_measures_positive_time():
    reg = MetricsRegistry()
    with reg.timer("t"):
        sum(range(1000))
    s = reg.snapshot()["timers"]["t"]
    assert s["count"] == 1
    assert s["total_s"] > 0


def test_registry_emit_without_sink_is_noop():
    MetricsRegistry().emit({"record": "x"})  # must not raise


def test_default_registry_install_and_restore():
    mine = MetricsRegistry()
    prev = set_registry(mine)
    try:
        assert get_registry() is mine
    finally:
        set_registry(prev)


# -------------------------------------------------------------------- sink


def test_jsonl_sink_roundtrip(tmp_path):
    sink = JsonlSink(str(tmp_path), process_index=0)
    sink.emit({"record": "a", "x": 1})
    sink.emit({"record": "b", "y": [1.5, None, "s"]})
    sink.close()
    lines = [
        json.loads(l)
        for l in open(tmp_path / "metrics.jsonl").read().splitlines()
    ]
    assert [r["record"] for r in lines] == ["a", "b"]
    assert lines[0]["x"] == 1
    assert lines[1]["y"] == [1.5, None, "s"]
    for r in lines:
        assert r["ts"] > 0  # wall-clock stamp added at write time


def test_jsonl_sink_gates_on_process_zero(tmp_path):
    sink = JsonlSink(str(tmp_path / "sub"), process_index=1)
    assert not sink.active
    sink.emit({"record": "dropped"})
    sink.close()
    assert not os.path.exists(tmp_path / "sub")


def test_jsonl_sink_appends_across_instances(tmp_path):
    a = JsonlSink(str(tmp_path), process_index=0)
    a.emit({"record": "first"})
    a.close()
    b = JsonlSink(str(tmp_path), process_index=0)  # a supervised restart
    b.emit({"record": "second"})
    b.close()
    recs = [
        json.loads(l)
        for l in open(tmp_path / "metrics.jsonl").read().splitlines()
    ]
    assert [r["record"] for r in recs] == ["first", "second"]


def test_run_metadata_header_schema(eight_devices):
    from pytorch_distributed_training_tpu.comms.mesh import build_mesh
    from pytorch_distributed_training_tpu.utils.config import (
        MeshConfig,
        TrainConfig,
        model_preset,
    )

    mesh = build_mesh(MeshConfig(data=4, fsdp=2))
    hdr = run_metadata(
        mesh, model_preset("tiny"), TrainConfig(), steps_per_epoch=7
    )
    assert hdr["record"] == "run_meta"
    assert hdr["mesh_shape"] == {
        "data": 4, "fsdp": 2, "stage": 1, "model": 1, "seq": 1
    }
    assert hdr["chip_count"] == 8
    assert isinstance(hdr["jax_version"], str) and hdr["jax_version"]
    assert hdr["config"]["model"]["hidden_size"] == 64
    assert hdr["config"]["train"]["global_batch_size"] == 96
    assert hdr["steps_per_epoch"] == 7
    json.dumps(hdr)  # fully serializable, no repr leakage


# --------------------------------------------------------------- straggler


def test_straggler_stats_single_host():
    stats = epoch_straggler_stats([0.1, 0.2, 0.3], [0.01, 0.02, 0.03])
    assert stats["hosts"] == 1
    assert stats["slowest_host"] == 0
    assert stats["fastest_host"] == 0
    assert stats["slowest_host_mean_step_s"] == pytest.approx(0.2)
    assert stats["wait_skew_s"] == 0.0
    assert stats["slowest_host_max_step_s"] == pytest.approx(0.3)
    assert stats["slowest_host_data_wait_mean_s"] == pytest.approx(0.02)
    assert stats["per_host_mean_step_s"] == pytest.approx([0.2])


def test_straggler_stats_empty_epoch():
    stats = epoch_straggler_stats([])
    assert stats["hosts"] == 1
    assert stats["slowest_host_mean_step_s"] == 0.0


# ----------------------------------------------------------------- logging


def test_log_level_env_and_process_index_format(monkeypatch, capsys):
    from pytorch_distributed_training_tpu.utils.logging import get_logger

    monkeypatch.setenv("PDT_TPU_LOG_LEVEL", "DEBUG")
    logger = get_logger("pdt_tpu_test_env_level")
    assert logger.level == logging.DEBUG
    logger.info("attributable line")
    out = capsys.readouterr().out
    assert "p0" in out  # process index in the format string
    assert "attributable line" in out


def test_log_format_json_switch(capsys):
    from pytorch_distributed_training_tpu.utils.logging import (
        get_logger,
        set_log_format,
    )

    logger = get_logger("pdt_tpu_test_json_fmt")
    try:
        set_log_format("json")
        logger.info("structured %s", "msg")
        rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert rec["msg"] == "structured msg"
        assert rec["level"] == "INFO"
        assert rec["process"] == 0
    finally:
        set_log_format("text")


def test_log_format_rejects_unknown():
    from pytorch_distributed_training_tpu.utils.logging import set_log_format

    with pytest.raises(ValueError, match="log format"):
        set_log_format("yaml")


# ------------------------------------------------------------- integration


@pytest.fixture(scope="module")
def metrics_run(eight_devices, tmp_path_factory):
    """One tiny synthetic-task training run with the telemetry stream on;
    several tests assert against the same stream."""
    tmp = tmp_path_factory.mktemp("telemetry")
    mdir = str(tmp / "metrics")
    trainer = _small_trainer(
        metrics_dir=mdir,
        checkpoint_dir=str(tmp / "ckpt"),
    )
    trainer.run()
    records = [
        json.loads(l)
        for l in open(os.path.join(mdir, "metrics.jsonl"))
        .read()
        .splitlines()
    ]
    return trainer, records, mdir


def test_stream_header_first(metrics_run):
    trainer, records, _ = metrics_run
    hdr = records[0]
    assert hdr["record"] == "run_meta"
    assert hdr["chip_count"] == 8
    assert hdr["mesh_shape"]["data"] == 4
    assert hdr["config"]["train"]["train_size"] == 128
    assert hdr["steps_per_epoch"] == trainer.train_loader.steps_per_epoch


def test_stream_step_records_breakdown(metrics_run):
    trainer, records, _ = metrics_run
    steps = [r for r in records if r["record"] == "step"]
    assert len(steps) == trainer.train_loader.steps_per_epoch  # 4
    for s in steps:
        assert s["data_wait_s"] >= 0
        assert s["dispatch_s"] >= 0
        assert s["device_block_s"] >= 0
        assert s["step_s"] >= 0
        # total covers its parts (measured against the same perf_counter)
        assert s["step_s"] >= s["device_block_s"]
        assert math.isfinite(s["loss"])
        # default prefetch pipeline annotates queue occupancy per step
        assert 0 <= s["prefetch_occupancy"] <= 2
    # AOT warm start moved compilation OUT of the step stream: no step is
    # compile-inclusive, and the compile wall time has its own record
    assert all(s["compile_inclusive"] is False for s in steps)
    assert [s["step"] for s in steps] == list(
        range(1, len(steps) + 1)
    )


def test_stream_compile_record_from_aot_warm_start(metrics_run):
    _, records, _ = metrics_run
    compiles = [r for r in records if r["record"] == "compile"]
    assert len(compiles) == 1
    c = compiles[0]
    assert c["aot"] is True
    assert c["train_compile_s"] > 0
    assert c["eval_compile_s"] > 0
    assert c["compile_s"] == pytest.approx(
        c["train_compile_s"] + c["eval_compile_s"]
    )
    assert c["cache_hit"] is None  # no --compile-cache-dir in this run


def test_lazy_compile_path_flags_first_step(eight_devices, tmp_path):
    """aot_warmup=False keeps the legacy behavior: the first step carries
    compilation and is flagged, later steps aren't."""
    mdir = str(tmp_path / "lazy")
    trainer = _small_trainer(
        metrics_dir=mdir, aot_warmup=False, train_size=64
    )
    trainer.run()
    records = [
        json.loads(l)
        for l in open(os.path.join(mdir, "metrics.jsonl")).read().splitlines()
    ]
    steps = [r for r in records if r["record"] == "step"]
    assert steps[0]["compile_inclusive"] is True
    assert all(s["compile_inclusive"] is False for s in steps[1:])
    assert not [r for r in records if r["record"] == "compile"]


def test_stream_epoch_record_matches_history(metrics_run):
    trainer, records, _ = metrics_run
    epochs = [r for r in records if r["record"] == "epoch"]
    assert len(epochs) == len(trainer.history) == 1
    final, hist = epochs[-1], trainer.history[-1]
    for key, want in hist.items():
        got = final[key]
        if isinstance(want, float) and math.isnan(want):
            assert math.isnan(got)
        else:
            assert got == pytest.approx(want), key
    # straggler stats ride every epoch record
    st = final["straggler"]
    assert st["hosts"] == 1
    assert st["slowest_host"] == 0
    assert st["slowest_host_mean_step_s"] > 0
    assert st["wait_skew_s"] == 0.0
    # the epoch's telemetry window: step breakdown + loader + eval timers
    timers = final["telemetry"]["timers"]
    assert timers["train/step_s"]["count"] == 4
    # both loader engines record placement; assembly is engine-specific
    # (host_assemble_s from the Python loader, prefetch_wait_s from the
    # native C++ batcher)
    assert timers["data/h2d_place_s"]["count"] >= 4
    assert (
        timers.get("data/host_assemble_s", {}).get("count", 0) >= 4
        or timers.get("data/prefetch_wait_s", {}).get("count", 0) >= 4
    )
    assert timers["eval/wall_s"]["count"] == 1
    assert timers["checkpoint/save_submit_s"]["count"] == 1


def test_stream_checkpoint_save_durations(metrics_run):
    _, records, _ = metrics_run
    saves = [r for r in records if r["record"] == "checkpoint_save"]
    assert len(saves) == 1  # the per-epoch save
    assert saves[0]["submit_s"] >= 0
    assert saves[0]["step"] == 4


def test_summarize_metrics_renders_stream(metrics_run, capsys):
    trainer, _, mdir = metrics_run
    sm = _load_summarizer()
    summary = sm.main([mdir])
    out = capsys.readouterr().out
    assert "samp/s/chip" in out and "data-wait %" in out
    row = summary["epochs"][0]
    assert row["steps"] == 4
    assert row["train_loss"] == pytest.approx(
        trainer.history[-1]["train_loss"]
    )
    assert row["slowest_host"] == 0
    assert 0.0 <= row["data_wait_pct"] <= 100.0
    assert summary["checkpoint_saves"] == 1
    assert summary["run"]["chip_count"] == 8
    # --json mode emits machine-readable output
    sm.main([mdir, "--json"])
    assert json.loads(capsys.readouterr().out)["epochs"][0]["steps"] == 4


def test_summarize_skips_torn_lines(tmp_path, capsys):
    sm = _load_summarizer()
    p = tmp_path / "metrics.jsonl"
    p.write_text(
        json.dumps({"record": "run_meta", "chip_count": 1}) + "\n"
        + json.dumps({"record": "epoch", "epoch": 0, "train_loss": 1.0})
        + "\n"
        + '{"record": "step", "epo'  # torn final line (crashed run)
    )
    summary = sm.summarize(sm.load_records(str(p)))
    assert len(summary["epochs"]) == 1


def test_supervisor_restart_event(tmp_path):
    from pytorch_distributed_training_tpu.utils.supervisor import (
        run_with_restarts,
    )

    reg = MetricsRegistry()
    sink = JsonlSink(str(tmp_path), process_index=0)
    reg.attach_sink(sink)
    prev = set_registry(reg)
    try:
        calls = []

        def attempt(i):
            calls.append(i)
            if i == 0:
                raise RuntimeError("injected host failure")
            return "ok"

        assert (
            run_with_restarts(attempt, max_restarts=1, backoff_s=0.0) == "ok"
        )
    finally:
        set_registry(prev)
        sink.close()
    assert calls == [0, 1]
    recs = [
        json.loads(l)
        for l in open(tmp_path / "metrics.jsonl").read().splitlines()
    ]
    restart = [r for r in recs if r["record"] == "restart"]
    assert len(restart) == 1
    assert restart[0]["attempt"] == 0
    assert restart[0]["error"] == "RuntimeError"
    assert restart[0]["will_retry"] is True
    assert reg.snapshot()["counters"]["supervisor/restarts"] == 1


def test_trainer_without_metrics_dir_writes_nothing(eight_devices, tmp_path):
    """Telemetry off (the default): no sink, no per-step sync, and the
    run directory stays clean — the zero-overhead contract."""
    trainer = _small_trainer(train_size=64)
    trainer.run()
    assert trainer.metrics_sink is None
    assert trainer.history  # the run itself still happened
