"""Input-pipeline performance acceptance (opt-in: ``-m perf``).

Drives ``bench.py --quick``: two subprocess runs of the real Trainer on a
tiny synthetic CPU workload — prefetch off (cold compile cache) then
prefetch on (warm cache) — and asserts the PR's wins on the resulting
comparison JSON: steady-state data wait strictly lower with prefetch on,
no compile-inclusive steps after AOT warm start, and the second run's
compile served from the persistent cache in less wall time. Timing-based
by nature, so it stays out of tier-1 (conftest auto-skips without
``-m perf``).
"""

import json
import os
import subprocess
import sys

import pytest

pytestmark = pytest.mark.perf

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_quick_bench_prefetch_and_warm_start(tmp_path):
    out = tmp_path / "comparison.json"
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO_ROOT, "bench.py"),
            "--quick", "--quick-steps", "20", "--quick-out", str(out),
        ],
        capture_output=True, text=True, timeout=1200,
        cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    cmp = json.loads(out.read_text())
    off, on = cmp["prefetch_off"], cmp["prefetch_on"]

    # the latency-hiding win: steady-state data wait strictly lower
    assert on["data_wait_mean_s"] < off["data_wait_mean_s"], cmp
    assert cmp["data_wait_reduction_s"] > 0
    assert on["prefetch_occupancy_mean"] is not None
    assert off["prefetch_occupancy_mean"] is None  # depth 0 = unwrapped

    # AOT warm start: compilation never lands inside a step
    assert off["compile_inclusive_steps"] == 0
    assert on["compile_inclusive_steps"] == 0
    assert off["compile_s"] > 0 and on["compile_s"] > 0

    # warm start: second run hits the persistent cache, compiles faster
    ws = cmp["warm_start"]
    assert ws["cache_hit_second_run"] is True, cmp
    assert ws["warm_compile_s"] < ws["cold_compile_s"], cmp
