"""Native C++ batch assembler: build, correctness, determinism, lifecycle."""

import numpy as np
import pytest

from pytorch_distributed_training_tpu.comms.mesh import build_mesh
from pytorch_distributed_training_tpu.native import native_available
from pytorch_distributed_training_tpu.utils.config import MeshConfig

pytestmark = pytest.mark.skipif(
    not native_available(), reason="C++ toolchain unavailable"
)


def _dataset(n=64, seq=8, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "input_ids": rng.integers(0, 100, (n, seq)).astype(np.int32),
        "attention_mask": np.ones((n, seq), np.int32),
        "labels": rng.integers(0, 2, n).astype(np.int32),
    }


def _gathered(batches):
    """host-side [rows, ...] view of every yielded batch, merged."""
    import jax

    out = []
    for b in batches:
        host = {k: np.asarray(jax.device_get(v)) for k, v in b.items()}
        out.append(host)
    return out


def test_every_row_exactly_once_per_epoch():
    from pytorch_distributed_training_tpu.data.native_loader import (
        NativeShardedLoader,
    )

    mesh = build_mesh(MeshConfig(data=8))
    data = _dataset(n=64)
    loader = NativeShardedLoader(
        data, mesh, global_batch_size=16, grad_accum_steps=2, seed=7
    )
    batches = _gathered(loader.epoch(0))
    assert len(batches) == 4  # 64 / 16
    ids = np.concatenate(
        [b["labels"].reshape(-1) for b in batches]
    )
    # labels were drawn iid; verify coverage via input_ids row identity
    rows = np.concatenate(
        [b["input_ids"].reshape(-1, 8) for b in batches]
    )
    assert rows.shape == (64, 8)
    # every dataset row appears exactly once
    orig = {r.tobytes() for r in data["input_ids"]}
    got = [r.tobytes() for r in rows]
    assert len(got) == len(set(got)) == len(orig)
    assert set(got) == orig
    # row alignment: labels travel with their rows
    row_to_label = {
        r.tobytes(): l for r, l in zip(data["input_ids"], data["labels"])
    }
    for b in batches:
        for r, l in zip(
            b["input_ids"].reshape(-1, 8), b["labels"].reshape(-1)
        ):
            assert row_to_label[r.tobytes()] == l
    loader.close()


def test_deterministic_and_epoch_varying():
    from pytorch_distributed_training_tpu.data.native_loader import (
        NativeShardedLoader,
    )

    mesh = build_mesh(MeshConfig(data=8))
    data = _dataset(n=64)

    def first_rows(seed, epoch):
        loader = NativeShardedLoader(
            data, mesh, global_batch_size=16, grad_accum_steps=1, seed=seed
        )
        b = next(iter(loader.epoch(epoch)))
        import jax

        rows = np.asarray(jax.device_get(b["input_ids"])).reshape(-1, 8)
        loader.close()
        return rows

    a = first_rows(7, 0)
    b = first_rows(7, 0)
    np.testing.assert_array_equal(a, b)  # same seed+epoch → same order
    c = first_rows(7, 1)
    assert not np.array_equal(a, c)  # epochs reshuffle


@pytest.mark.slow
def test_trainer_runs_with_native_loader():
    """End-to-end: Trainer with native_loader='on' trains and evals."""
    from pytorch_distributed_training_tpu.parallel import ShardingPolicy
    from pytorch_distributed_training_tpu.train.loop import Trainer
    from pytorch_distributed_training_tpu.utils.config import (
        TrainConfig,
        model_preset,
    )

    mcfg = model_preset("tiny", compute_dtype="float32")
    tcfg = TrainConfig(
        num_epochs=1,
        global_batch_size=32,
        micro_batch_size=16,
        eval_batch_size=32,
        train_size=128,
        eval_size=64,
        log_every=0,
        bf16=False,
        native_loader="on",
    )
    trainer = Trainer(
        mcfg, tcfg, MeshConfig(data=8), ShardingPolicy(), task="synthetic"
    )
    history = trainer.run()
    assert history and "accuracy" in history[-1]
