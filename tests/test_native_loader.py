"""Native C++ batch assembler: build, correctness, determinism, lifecycle."""

import numpy as np
import pytest

from pytorch_distributed_training_tpu.comms.mesh import build_mesh
from pytorch_distributed_training_tpu.native import native_available
from pytorch_distributed_training_tpu.utils.config import MeshConfig

pytestmark = pytest.mark.skipif(
    not native_available(), reason="C++ toolchain unavailable"
)


def _dataset(n=64, seq=8, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "input_ids": rng.integers(0, 100, (n, seq)).astype(np.int32),
        "attention_mask": np.ones((n, seq), np.int32),
        "labels": rng.integers(0, 2, n).astype(np.int32),
    }


def _gathered(batches):
    """host-side [rows, ...] view of every yielded batch, merged."""
    import jax

    out = []
    for b in batches:
        host = {k: np.asarray(jax.device_get(v)) for k, v in b.items()}
        out.append(host)
    return out


def test_every_row_exactly_once_per_epoch():
    from pytorch_distributed_training_tpu.data.native_loader import (
        NativeShardedLoader,
    )

    mesh = build_mesh(MeshConfig(data=8))
    data = _dataset(n=64)
    loader = NativeShardedLoader(
        data, mesh, global_batch_size=16, grad_accum_steps=2, seed=7
    )
    batches = _gathered(loader.epoch(0))
    assert len(batches) == 4  # 64 / 16
    ids = np.concatenate(
        [b["labels"].reshape(-1) for b in batches]
    )
    # labels were drawn iid; verify coverage via input_ids row identity
    rows = np.concatenate(
        [b["input_ids"].reshape(-1, 8) for b in batches]
    )
    assert rows.shape == (64, 8)
    # every dataset row appears exactly once
    orig = {r.tobytes() for r in data["input_ids"]}
    got = [r.tobytes() for r in rows]
    assert len(got) == len(set(got)) == len(orig)
    assert set(got) == orig
    # row alignment: labels travel with their rows
    row_to_label = {
        r.tobytes(): l for r, l in zip(data["input_ids"], data["labels"])
    }
    for b in batches:
        for r, l in zip(
            b["input_ids"].reshape(-1, 8), b["labels"].reshape(-1)
        ):
            assert row_to_label[r.tobytes()] == l
    loader.close()


def test_deterministic_and_epoch_varying():
    from pytorch_distributed_training_tpu.data.native_loader import (
        NativeShardedLoader,
    )

    mesh = build_mesh(MeshConfig(data=8))
    data = _dataset(n=64)

    def first_rows(seed, epoch):
        loader = NativeShardedLoader(
            data, mesh, global_batch_size=16, grad_accum_steps=1, seed=seed
        )
        b = next(iter(loader.epoch(epoch)))
        import jax

        rows = np.asarray(jax.device_get(b["input_ids"])).reshape(-1, 8)
        loader.close()
        return rows

    a = first_rows(7, 0)
    b = first_rows(7, 0)
    np.testing.assert_array_equal(a, b)  # same seed+epoch → same order
    c = first_rows(7, 1)
    assert not np.array_equal(a, c)  # epochs reshuffle


@pytest.mark.slow
def test_trainer_runs_with_native_loader():
    """End-to-end: Trainer with native_loader='on' trains and evals."""
    from pytorch_distributed_training_tpu.parallel import ShardingPolicy
    from pytorch_distributed_training_tpu.train.loop import Trainer
    from pytorch_distributed_training_tpu.utils.config import (
        TrainConfig,
        model_preset,
    )

    mcfg = model_preset("tiny", compute_dtype="float32")
    tcfg = TrainConfig(
        num_epochs=1,
        global_batch_size=32,
        micro_batch_size=16,
        eval_batch_size=32,
        train_size=128,
        eval_size=64,
        log_every=0,
        bf16=False,
        native_loader="on",
    )
    trainer = Trainer(
        mcfg, tcfg, MeshConfig(data=8), ShardingPolicy(), task="synthetic"
    )
    history = trainer.run()
    assert history and "accuracy" in history[-1]


def test_eval_mode_matches_python_loader():
    """Native eval loader == Python eval loader batch-for-batch: identity
    order, padded ragged tail, identical valid masks (VERDICT r3 weak-#6 —
    eval previously always took the Python path)."""
    import jax

    from pytorch_distributed_training_tpu.data.native_loader import (
        NativeShardedLoader,
    )
    from pytorch_distributed_training_tpu.data.pipeline import ShardedLoader

    mesh = build_mesh(MeshConfig(data=8))
    data = _dataset(n=44)  # 44 rows / batch 16 -> 2 full + 1 padded step
    native = NativeShardedLoader(
        data, mesh, global_batch_size=16, train=False, seed=3
    )
    python = ShardedLoader(
        data, mesh, global_batch_size=16, train=False, seed=3
    )
    assert native.steps_per_epoch == python.steps_per_epoch == 3
    try:
        for nb, pb in zip(native.epoch(0), python.epoch(0)):
            assert sorted(nb) == sorted(pb)
            for k in pb:
                np.testing.assert_array_equal(
                    np.asarray(jax.device_get(nb[k])),
                    np.asarray(jax.device_get(pb[k])),
                    err_msg=k,
                )
    finally:
        native.close()
    # valid-mask accounting: exactly n rows counted across the epoch
    total_valid = 0
    for b in ShardedLoader(
        data, mesh, global_batch_size=16, train=False, seed=3
    ).epoch(0):
        total_valid += int(np.asarray(jax.device_get(b["valid"])).sum())
    assert total_valid == 44


def test_trainer_evaluates_with_native_eval_loader():
    """Trainer wires the native batcher for eval too — and the metrics
    match a python-loader run exactly (same eval pass, same counts)."""
    from pytorch_distributed_training_tpu.parallel import ShardingPolicy
    from pytorch_distributed_training_tpu.train.loop import Trainer
    from pytorch_distributed_training_tpu.utils.config import (
        TrainConfig,
        model_preset,
    )

    def run(native):
        mcfg = model_preset("tiny", compute_dtype="float32")
        tcfg = TrainConfig(
            num_epochs=1, global_batch_size=16, micro_batch_size=8,
            eval_batch_size=16, train_size=32, eval_size=24,  # padded tail
            max_seq_length=16, bf16=False, log_every=0,
            native_loader="on" if native else "off",
        )
        t = Trainer(mcfg, tcfg, MeshConfig(data=8), ShardingPolicy(),
                    task="synthetic")
        from pytorch_distributed_training_tpu.data.native_loader import (
            NativeShardedLoader,
        )

        if native:
            assert isinstance(t.eval_loader, NativeShardedLoader)
        return t.run()

    h_native = run(True)
    h_python = run(False)
    assert h_native[0]["accuracy"] == h_python[0]["accuracy"]
    assert h_native[0]["f1"] == h_python[0]["f1"]
