"""Load-shaping tests: trace replay, SLO tier lanes, brownout, autoscaler.

The storm layer (ISSUE 13) is three state machines plus a workload
generator, and all of them are testable without a single subprocess:

- ``serve/trace.py``: seed-determinism (same config -> identical event
  list) and the open-loop replay driver under an injected clock;
- ``serve/queue.py`` tier lanes: weighted round-robin share under
  contention, work conservation when one lane idles, per-lane no-bypass;
- ``BrownoutController``: the fixed reversible ladder — batch sheds
  before ANY interactive rejection, clamps are admission-time (hence
  reversible), sustained-pressure holds mean a flapping gauge cannot
  flap the policy, and recovery retraces to zero shedding;
- ``serve/autoscale.py``: hysteresis + cooldown over a fake fleet with a
  fake clock — no flapping under an oscillating gauge, bounded pool.

One subprocess drill rides at the end: ``fleet.retire_replica()`` (the
autoscaler's scale-down path) must complete an in-flight 64-token stream
through the SIGTERM -> drain -> exit-75 contract — no in-flight request
dies when capacity leaves the pool.
"""

import http.client
import json
import threading
import time
import types

import pytest

from pytorch_distributed_training_tpu.serve.autoscale import (
    AutoscaleConfig,
    Autoscaler,
)
from pytorch_distributed_training_tpu.serve.queue import (
    BROWNOUT_LEVELS,
    BrownoutController,
    GenRequest,
    RequestQueue,
)
from pytorch_distributed_training_tpu.serve.server import wait_until
from pytorch_distributed_training_tpu.serve.trace import (
    TraceConfig,
    generate_trace,
    replay,
    trace_stats,
)

pytestmark = [pytest.mark.serve, pytest.mark.storm]


class ListSink:
    """In-memory telemetry sink (same contract as JsonlSink.emit)."""

    def __init__(self):
        self.records = []
        self._lock = threading.Lock()

    def emit(self, record):
        rec = dict(record)
        rec.setdefault("ts", time.time())
        with self._lock:
            self.records.append(rec)

    def flush(self, **kw):
        pass

    def of(self, kind):
        with self._lock:
            return [r for r in self.records if r.get("record") == kind]


def _registry():
    from pytorch_distributed_training_tpu.telemetry.registry import (
        MetricsRegistry,
    )

    reg = MetricsRegistry()
    sink = ListSink()
    reg.attach_sink(sink)
    return reg, sink


class FakeClock:
    def __init__(self, t: float = 100.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


# =====================================================================
# trace generator + replay driver
# =====================================================================


def test_trace_seed_determinism():
    cfg = TraceConfig(seed=7, duration_s=20.0)
    a = generate_trace(cfg)
    b = generate_trace(cfg)
    assert a == b                       # same seed -> identical trace
    assert a != generate_trace(TraceConfig(seed=8, duration_s=20.0))
    assert len(a) > 10
    # events are schedule-ordered with sane draws
    for prev, ev in zip(a, a[1:]):
        assert ev.t_s >= prev.t_s
    for ev in a:
        assert ev.tier in ("interactive", "batch")
        assert cfg.prompt_len_min <= ev.prompt_len <= cfg.prompt_len_max
        assert (
            cfg.output_tokens_min
            <= ev.max_new_tokens
            <= cfg.output_tokens_max
        )
        assert ev.deadline_s == (
            cfg.interactive_deadline_s
            if ev.tier == "interactive"
            else cfg.batch_deadline_s
        )
        assert ev.burst == (3.0 <= ev.t_s < 5.0)    # default burst window


def test_trace_burst_density_and_stats():
    cfg = TraceConfig(
        seed=1, duration_s=12.0, base_rate_rps=2.0, burst_rate_rps=30.0,
        bursts=((4.0, 2.0),),
    )
    events = generate_trace(cfg)
    stats = trace_stats(events)
    assert stats["events"] == len(events)
    assert stats["by_tier"]["interactive"] + stats["by_tier"]["batch"] == (
        len(events)
    )
    # the burst must be visibly denser than the base load: its 2s window
    # holds more arrivals than the remaining 10s of base-rate traffic
    burst = [e for e in events if e.burst]
    assert len(burst) > len(events) - len(burst)
    assert stats["burst_events"] == len(burst)


def test_trace_replay_open_loop_with_injected_clock():
    cfg = TraceConfig(seed=3, duration_s=5.0)
    events = generate_trace(cfg)
    clock = FakeClock(0.0)

    def sleep(dt):
        clock.t += dt

    fired = []
    out = replay(
        events, fired.append, now_fn=clock, sleep_fn=sleep,
    )
    assert out["fired"] == len(events) == len(fired)
    assert fired == events              # in schedule order
    # a perfectly-sleeping replayer never runs late
    assert out["max_lag_s"] < 0.06
    # stop predicate aborts the replay early
    half = len(events) // 2
    count = {"n": 0}

    def fire(ev):
        count["n"] += 1

    clock.t = 0.0
    out = replay(
        events, fire, now_fn=clock, sleep_fn=sleep,
        stop=lambda: count["n"] >= half,
    )
    assert out["fired"] == count["n"] <= half + 1


# =====================================================================
# SLO tier lanes (serve/queue.py)
# =====================================================================


def _req(rid, tier, prompt_len=4, max_new=8):
    import numpy as np

    return GenRequest(
        id=rid,
        prompt_ids=np.ones((prompt_len,), np.int32),
        max_new_tokens=max_new,
        tier=tier,
    )


def _queue(**kw):
    kw.setdefault("max_depth", 64)
    kw.setdefault("prompt_buckets", (8, 16))
    kw.setdefault("max_new_tokens", 64)
    return RequestQueue(**kw)


def test_tier_lanes_weighted_share_under_contention():
    q = _queue()    # default 4:1 interactive:batch
    for i in range(8):
        q.submit(_req(f"i{i}", "interactive"))
        q.submit(_req(f"b{i}", "batch"))
    order = [q.pop_ready().id for _ in range(16)]
    # first schedule cycle: 4 interactive, then 1 batch
    assert order[:5] == ["i0", "i1", "i2", "i3", "b0"]
    assert order[5:10] == ["i4", "i5", "i6", "i7", "b1"]
    # interactive lane empty -> batch gets EVERY pop (work-conserving)
    assert order[10:] == ["b2", "b3", "b4", "b5", "b6", "b7"]
    assert q.depth() == 0 and q.pop_ready() is None


def test_tier_lanes_no_bypass_is_per_lane():
    q = _queue()
    big = _req("big-batch", "batch")
    q.submit(big)
    q.submit(_req("b2", "batch"))
    q.submit(_req("i1", "interactive"))
    # reject the batch head (page-blocked): its own lane must NOT bypass
    # it, but the interactive lane still pops
    popped = q.pop_ready(accept=lambda r: r.tier != "batch")
    assert popped.id == "i1"
    assert q.pop_ready(accept=lambda r: r.tier != "batch") is None
    assert q.depth_by_tier() == {"interactive": 0, "batch": 2}
    # unblocked: strict FIFO within the batch lane resumes
    assert q.pop_ready().id == "big-batch"
    assert q.pop_ready().id == "b2"


def test_tier_validation_and_depth_by_tier():
    q = _queue()
    with pytest.raises(ValueError, match="tier"):
        q.submit(_req("x", "bulk"))
    q.submit(_req("a", "interactive"))
    q.submit(_req("b", "batch"))
    q.submit(_req("c", "batch"))
    assert q.depth() == 3
    assert q.depth_by_tier() == {"interactive": 1, "batch": 2}


# =====================================================================
# brownout ladder (serve/queue.py)
# =====================================================================


def test_brownout_escalates_one_level_per_hold_and_recovers():
    clock = FakeClock()
    reg, sink = _registry()
    br = BrownoutController(
        high_watermark=0.8, low_watermark=0.3,
        escalate_hold_s=1.0, deescalate_hold_s=2.0,
        clamp_max_new=8, now_fn=clock, registry=reg,
    )
    # sustained overload walks the ladder one level at a time — each level
    # needs its OWN hold, no skipping straight to fail_fast
    levels = []
    for _ in range(8):
        levels.append(br.observe(0.9))
        clock.t += 0.55
    assert levels == [0, 0, 1, 1, 2, 2, 3, 3]
    assert br.level_name() == "fail_fast"
    # recovery retraces the ladder down under sustained low pressure
    down = []
    for _ in range(14):
        down.append(br.observe(0.1))
        clock.t += 1.05
    assert down[0] == 3 and down[-1] == 0
    assert sorted(set(down), reverse=True) == [3, 2, 1, 0]
    assert br.level == 0 and not br.sheds("batch")
    transitions = [
        (r["from"], r["to"]) for r in sink.of("brownout_transition")
    ]
    assert transitions == [
        ("normal", "shed_batch"), ("shed_batch", "clamp"),
        ("clamp", "fail_fast"), ("fail_fast", "clamp"),
        ("clamp", "shed_batch"), ("shed_batch", "normal"),
    ]


def test_brownout_batch_sheds_before_any_interactive_rejection():
    """THE degradation-order pin: walking the whole ladder, interactive is
    rejected ONLY at the final level, and by then batch has been shedding
    for two levels already."""
    clock = FakeClock()
    br = BrownoutController(
        escalate_hold_s=0.5, deescalate_hold_s=0.5, now_fn=clock,
    )
    seen = [(br.level_name(), br.sheds("batch"), br.sheds("interactive"))]
    while br.level < len(BROWNOUT_LEVELS) - 1:
        prev = br.level
        br.observe(1.0)
        clock.t += 0.6
        if br.level != prev:
            seen.append((br.level_name(), br.sheds("batch"),
                         br.sheds("interactive")))
    assert seen == [
        ("normal", False, False),
        ("shed_batch", True, False),
        ("clamp", True, False),
        ("fail_fast", True, True),
    ]


def test_brownout_clamp_is_reversible_and_identity_below_level():
    clock = FakeClock()
    br = BrownoutController(
        escalate_hold_s=0.5, deescalate_hold_s=0.5, clamp_max_new=16,
        now_fn=clock,
    )
    assert br.clamp(64) == 64           # normal: identity
    while br.level < 2:
        br.observe(1.0)
        clock.t += 0.6
    assert br.clamp(64) == 16 and br.clamp(8) == 8
    while br.level > 0:
        br.observe(0.0)
        clock.t += 0.6
    assert br.clamp(64) == 64           # recovery lifts the clamp


def test_brownout_flapping_gauge_never_moves_the_ladder():
    clock = FakeClock()
    br = BrownoutController(
        escalate_hold_s=1.0, deescalate_hold_s=1.0, now_fn=clock,
    )
    # pressure oscillates across the watermarks faster than either hold:
    # crossing back resets the timers, so the level never moves
    for i in range(40):
        br.observe(0.95 if i % 2 == 0 else 0.05)
        clock.t += 0.4
    assert br.level == 0
    assert br.escalations == 0 and br.deescalations == 0
    # mid-band samples also reset an accumulating hold
    br.observe(0.95)
    clock.t += 0.9
    br.observe(0.5)                     # inside the hysteresis band
    clock.t += 0.2
    br.observe(0.95)
    assert br.level == 0                # the 0.9s above-hold did not carry


# =====================================================================
# autoscaler hysteresis + cooldown (serve/autoscale.py)
# =====================================================================


class FakeFleet:
    """The exact surface Autoscaler needs: router health views + process
    states + the two pool knobs. Gauges are set per-test."""

    def __init__(self, n=2):
        self.retired = []
        self._n = 0
        self.router = types.SimpleNamespace(replicas=[])
        self.replicas = []
        for _ in range(n):
            self._add()
        self.depth = 0.0
        self.occupancy = 0.0

    def _add(self):
        name = f"r{self._n}"
        self._n += 1
        fleet = self

        class View:
            def __init__(self):
                self.name = name
                self.breaker = types.SimpleNamespace(state="closed")

            @property
            def health(self):
                return {
                    "queue_depth": fleet.depth,
                    "page_occupancy": fleet.occupancy,
                }

            def available(self):
                return True

        self.router.replicas.append(View())
        proc = types.SimpleNamespace(name=name, state="up")
        self.replicas.append(proc)
        return proc

    def scale_up(self):
        return self._add()

    def retire_replica(self):
        live = [r for r in self.replicas if r.state in ("starting", "up")]
        if len(live) <= 1:
            return None
        victim = live[-1]
        self.replicas.remove(victim)
        self.router.replicas = [
            v for v in self.router.replicas if v.name != victim.name
        ]
        self.retired.append(victim.name)
        return victim.name


def _autoscaler(fleet, clock, **kw):
    reg, sink = _registry()
    cfg = AutoscaleConfig(**{
        "min_replicas": 1, "max_replicas": 4,
        "scale_up_queue_depth": 6.0, "scale_down_queue_depth": 1.0,
        "up_hold_s": 1.0, "down_hold_s": 5.0,
        "up_cooldown_s": 5.0, "down_cooldown_s": 10.0,
        **kw,
    })
    return Autoscaler(fleet, cfg, now_fn=clock, registry=reg), sink


def test_autoscaler_never_flaps_under_oscillating_gauge():
    fleet = FakeFleet(2)
    clock = FakeClock()
    auto, _ = _autoscaler(fleet, clock)
    # queue depth oscillates violently across BOTH thresholds, faster than
    # either hold: the signal never holds, so the pool never changes
    for i in range(100):
        fleet.depth = 20.0 if i % 2 == 0 else 0.0
        assert auto.step() is None
        clock.t += 0.6
    assert len(fleet.replicas) == 2
    assert auto.scale_ups == 0 and auto.scale_downs == 0


def test_autoscaler_scales_up_after_hold_then_cooldown_blocks():
    fleet = FakeFleet(2)
    clock = FakeClock()
    auto, sink = _autoscaler(fleet, clock)
    fleet.depth = 12.0                  # sustained overload
    assert auto.step() is None          # onset: hold starts
    clock.t += 1.1
    assert auto.step() == "up"          # held past up_hold_s -> act
    assert len(fleet.replicas) == 3
    # still overloaded, but the cooldown gates further action; the hold
    # timer re-accumulates underneath it
    clock.t += 2.0
    assert auto.step() is None
    clock.t += 3.5                      # cooldown expired + hold satisfied
    assert auto.step() == "up"
    assert len(fleet.replicas) == 4
    # at max_replicas: pressure can no longer grow the pool
    clock.t += 10.0
    auto.step()
    clock.t += 1.1
    assert auto.step() is None
    assert len(fleet.replicas) == 4
    events = [r for r in sink.records if r["record"] == "autoscale_event"]
    assert [e["action"] for e in events] == ["up", "up"]


def test_autoscaler_scale_down_waits_longer_and_respects_min():
    fleet = FakeFleet(3)
    clock = FakeClock()
    auto, sink = _autoscaler(fleet, clock)
    fleet.depth = 0.0                   # idle pool
    assert auto.step() is None
    clock.t += 2.0
    assert auto.step() is None          # 2s < down_hold_s: too early
    clock.t += 3.5
    assert auto.step() == "down"        # held 5.5s -> retire newest
    assert fleet.retired == ["r2"]
    clock.t += 6.0                      # inside down_cooldown_s (10s)
    assert auto.step() is None          # cooldown gates; hold re-accumulates
    clock.t += 5.0                      # cooldown over, idle held 5s through
    assert auto.step() == "down"
    assert len(fleet.replicas) == 1
    # min_replicas floor: an idle pool of one is left alone
    clock.t += 30.0
    auto.step()
    clock.t += 5.5
    assert auto.step() is None
    assert len(fleet.replicas) == 1


def test_autoscaler_breaker_and_occupancy_signals():
    fleet = FakeFleet(2)
    clock = FakeClock()
    auto, _ = _autoscaler(fleet, clock)
    # page pressure alone (queue shallow) is a scale-up signal: admission
    # is about to block on pages
    fleet.depth = 0.0
    fleet.occupancy = 0.95
    auto.step()
    clock.t += 1.1
    assert auto.step() == "up"
    # an open breaker vetoes scale-DOWN even when the queue is idle: a
    # half-dead pool is not excess capacity
    fleet.occupancy = 0.0
    fleet.router.replicas[0].breaker.state = "open"
    clock.t += 10.0
    auto.step()
    clock.t += 6.0
    assert auto.step() is None
    # breaker closes -> the idle hold finally acts
    fleet.router.replicas[0].breaker.state = "closed"
    auto.step()
    clock.t += 5.5
    assert auto.step() == "down"


def test_autoscaler_ignores_booting_pool():
    fleet = FakeFleet(2)
    clock = FakeClock()
    auto, _ = _autoscaler(fleet, clock)
    for view in fleet.router.replicas:
        view.available = lambda: False      # nothing qualified yet
    fleet.depth = 50.0
    for _ in range(20):
        assert auto.step() is None          # no reading -> no action
        clock.t += 1.0
    assert auto.scale_ups == 0


# =====================================================================
# retry-after estimate + port-retry + pool degradation (satellites)
# =====================================================================


def test_retry_after_estimate_is_bounded_and_live():
    from pytorch_distributed_training_tpu.serve.server import (
        RETRY_AFTER_CEILING_S,
        retry_after_estimate,
    )

    def fake_server(depth, rate):
        return types.SimpleNamespace(
            engine=types.SimpleNamespace(drain_rate=rate),
            queue=types.SimpleNamespace(depth=lambda: depth),
        )

    # cold engine (no drain history): the floor is the answer
    assert retry_after_estimate(fake_server(10, 0.0), floor=5) == 5
    # live estimate: depth / rate, floored and ceilinged
    assert retry_after_estimate(fake_server(12, 2.0), floor=1) == 6
    assert retry_after_estimate(fake_server(1, 10.0), floor=5) == 5
    assert retry_after_estimate(
        fake_server(10_000, 0.5), floor=1
    ) == RETRY_AFTER_CEILING_S


def test_replica_port_retry_burns_no_restart(monkeypatch):
    """The find_free_port TOCTOU closure: a bind-race exit (76) respawns
    on a fresh port INSIDE the attempt — run_with_restarts never sees it,
    the restart budget stays whole, and the router is told to re-qualify
    the new address."""
    from pytorch_distributed_training_tpu.serve import fleet as fleet_mod

    rcs = [fleet_mod.PORT_IN_USE_EXIT_CODE,
           fleet_mod.PORT_IN_USE_EXIT_CODE, 0]
    spawned = []

    class FakeProc:
        def __init__(self, rc):
            self.pid = 4242
            self._rc = rc

        def wait(self):
            return self._rc

        def poll(self):
            return self._rc

    def fake_popen(argv, env=None, stdout=None, stderr=None):
        spawned.append(list(argv))
        return FakeProc(rcs.pop(0))

    monkeypatch.setattr(fleet_mod.subprocess, "Popen", fake_popen)
    reg, sink = _registry()
    replica = fleet_mod.ReplicaProcess(
        0, 50_000, fleet_mod.FleetConfig(num_replicas=1, max_restarts=1),
        reg,
    )
    rebinds = []
    replica.on_port_change = lambda r: rebinds.append(r.port)
    replica._spawn_and_wait(0)

    d = replica.describe()
    assert d["port_retries"] == 2
    assert d["restarts_used"] == 0          # the race burned NO restart
    assert d["restart_budget_remaining"] == 1
    assert len(spawned) == 3
    assert len(rebinds) == 2 and all(p != 50_000 for p in rebinds)
    retries = sink.of("replica_port_retry")
    assert [r["try"] for r in retries] == [1, 2]
    assert retries[0]["old_port"] == 50_000
    gauges = reg.snapshot()["counters"]
    assert gauges.get("fleet/port_retries") == 2


def test_pool_status_reports_exhausted_restart_budget():
    from pytorch_distributed_training_tpu.serve.fleet import (
        FleetConfig,
        ServeFleet,
    )

    reg, _ = _registry()
    fleet = ServeFleet(
        FleetConfig(num_replicas=2, max_restarts=2), registry=reg,
    )   # constructed, never started: pure state inspection
    status = fleet.pool_status()
    assert status["degraded"] is False and status["reason"] is None
    assert status["restart_budget_remaining"] == {"r0": 2, "r1": 2}
    # a replica that exhausted its budget degrades the pool, by name
    fleet.replicas[1].state = "failed"
    fleet.replicas[1].restarts_used = 2
    status = fleet.pool_status()
    assert status["degraded"] is True
    assert status["failed"] == ["r1"]
    assert "restart budget exhausted" in status["reason"]
    assert status["restart_budget_remaining"]["r1"] == 0
    # the router's fail-fast body folds the same status in
    assert fleet.router.pool_status() == status
    fleet.router.close()


# =====================================================================
# summarize_metrics storm section
# =====================================================================


def test_summarize_metrics_storm_section(tmp_path):
    import subprocess
    import sys

    records = [
        {"record": "serve_request", "tier": "interactive", "status": "done",
         "ttft_s": 0.1, "total_s": 0.5, "queue_wait_s": 0.05, "ts": 1.0},
        {"record": "serve_request", "tier": "interactive", "status": "done",
         "ttft_s": 0.2, "total_s": 0.9, "queue_wait_s": 0.30, "ts": 2.0},
        {"record": "serve_request", "tier": "batch", "status": "done",
         "ttft_s": 1.0, "total_s": 3.0, "queue_wait_s": 2.00, "ts": 3.0},
        {"record": "serve_shed", "tier": "batch", "level": 1, "ts": 4.0},
        {"record": "serve_shed", "tier": "batch", "level": 1, "ts": 4.1},
        {"record": "serve_shed", "tier": "interactive", "level": 3,
         "ts": 5.0},
        {"record": "brownout_transition", "from": "normal",
         "to": "shed_batch", "level": 1, "pressure": 0.9, "ts": 4.0},
        {"record": "brownout_transition", "from": "shed_batch",
         "to": "clamp", "level": 2, "pressure": 0.95, "ts": 4.5},
        {"record": "brownout_transition", "from": "clamp",
         "to": "shed_batch", "level": 1, "pressure": 0.1, "ts": 7.0},
        {"record": "brownout_transition", "from": "shed_batch",
         "to": "normal", "level": 0, "pressure": 0.05, "ts": 8.0},
        {"record": "fleet_scale", "action": "up", "replica": "r2",
         "size": 3, "ts": 5.0},
        {"record": "autoscale_ready", "replica": "r2", "ready_s": 6.5,
         "ts": 11.5},
        {"record": "fleet_scale", "action": "down", "replica": "r2",
         "drain_s": 1.25, "size": 2, "ts": 20.0},
        {"record": "replica_port_retry", "replica": "r1",
         "old_port": 1000, "new_port": 1001, "try": 1, "ts": 2.5},
    ]
    stream = tmp_path / "metrics.jsonl"
    with open(stream, "w") as f:
        for r in records:
            f.write(json.dumps(r) + "\n")

    proc = subprocess.run(
        [sys.executable, "scripts/summarize_metrics.py", str(stream),
         "--json"],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    storm = json.loads(proc.stdout)["storm"]
    assert storm["tiers"]["interactive"]["requests"] == 2
    assert storm["tiers"]["batch"]["done"] == 1
    assert storm["tiers"]["interactive"]["total_s"]["p50"] == 0.5
    assert storm["sheds"] == {
        "total": 3, "by_tier": {"batch": 2, "interactive": 1},
    }
    assert storm["brownout"]["transitions"] == 4
    assert storm["brownout"]["escalations"] == 2
    assert storm["brownout"]["peak_level"] == 2
    assert storm["brownout"]["final_level"] == 0
    assert storm["scale_ups"] == 1 and storm["scale_downs"] == 1
    assert storm["scale_up_ready_s"]["p50"] == 6.5
    assert storm["scale_down_drain_s"]["p50"] == 1.25
    assert storm["port_retries"] == 1
    assert [e["event"] for e in storm["timeline"]] == [
        "port_retry", "scale_up", "replica_ready", "scale_down",
    ]
    # the table renderer accepts the same stream (smoke: no crash)
    proc = subprocess.run(
        [sys.executable, "scripts/summarize_metrics.py", str(stream)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "storm:" in proc.stdout and "autoscale:" in proc.stdout


# =====================================================================
# subprocess drill: scale-down drains an in-flight stream via exit 75
# =====================================================================


def test_retire_replica_drains_in_flight_64_token_stream():
    """The autoscaler's scale-down path end-to-end: ``retire_replica()``
    SIGTERMs the newest replica while it is mid-way through a 64-token
    stream; the stream must COMPLETE (drain, not cancel), the exit must be
    the graceful 75 with a measured drain duration in the ``fleet_scale``
    record, the router must deregister the endpoint, and no restart is
    burned anywhere."""
    from pytorch_distributed_training_tpu.serve.fleet import (
        FleetConfig,
        ServeFleet,
    )
    from pytorch_distributed_training_tpu.serve.router import RouterConfig

    reg, sink = _registry()
    fleet = ServeFleet(
        FleetConfig(
            num_replicas=2,
            replica_args=(
                "--model", "gpt2-tiny", "--num-slots", "2",
                "--prompt-buckets", "16,32", "--max-new-tokens-cap", "64",
                "--queue-depth", "16", "--stall-timeout-s", "10",
            ),
            max_restarts=1,
            backoff_s=0.2,
            drain_timeout_s=20.0,
        ),
        RouterConfig(
            health_interval_s=0.05, breaker_threshold=3,
            breaker_cooldown_s=0.5, retry_backoff_s=0.02,
            retry_backoff_max_s=0.1, ttfb_timeout_s=60.0,
        ),
        registry=reg,
    ).start()
    try:
        assert fleet.wait_ready(timeout=120), fleet.stats()
        # retire_replica picks the newest live replica (r1) — stream
        # straight to ITS port so the request is provably on the retiree
        target = fleet.replica(1)
        events = []
        client_done = threading.Event()

        def client():
            try:
                conn = http.client.HTTPConnection(
                    "127.0.0.1", target.port, timeout=120
                )
                conn.request(
                    "POST", "/generate",
                    body=json.dumps({
                        "prompt": "a long scale-down drain drill",
                        "max_new_tokens": 64,
                        "tier": "interactive",
                    }),
                    headers={"X-Request-Id": "retire-64"},
                )
                resp = conn.getresponse()
                while True:
                    line = resp.readline()
                    if not line:
                        break
                    events.append(json.loads(line))
                conn.close()
            finally:
                client_done.set()

        threading.Thread(target=client, daemon=True).start()
        assert wait_until(lambda: len(events) >= 2, timeout=60), events
        name = fleet.retire_replica()       # SIGTERM mid-stream
        assert name == "r1"

        # the in-flight stream completes — scale-down kills no request
        assert client_done.wait(120)
        done = events[-1]
        assert done["event"] == "done", events[-3:]
        assert done["new_tokens"] == 64 and done["status"] == "done"

        # graceful exit 75, no restart burned, drain duration measured
        assert wait_until(
            lambda: any(r["replica"] == "r1"
                        for r in sink.of("replica_exit")),
            timeout=60,
        )
        exit_rec = [
            r for r in sink.of("replica_exit") if r["replica"] == "r1"
        ][0]
        assert exit_rec["graceful"] is True and exit_rec["rc"] == 75

        assert wait_until(
            lambda: any(r["action"] == "down"
                        for r in sink.of("fleet_scale")),
            timeout=60,
        )
        down = [r for r in sink.of("fleet_scale") if r["action"] == "down"]
        assert down[0]["replica"] == "r1" and down[0]["drain_s"] > 0

        # the pool shrank: router deregistered r1, fleet dropped it, and
        # the retiree did NOT respawn (retirement, not preemption)
        assert wait_until(
            lambda: [r.name for r in fleet.router.replicas] == ["r0"],
            timeout=30,
        )
        assert [r.name for r in fleet.replicas] == ["r0"]
        assert fleet.scale_downs == 1
        assert fleet.replica(0).describe()["restarts_used"] == 0

        # the survivor still serves
        conn = http.client.HTTPConnection(
            "127.0.0.1", fleet.replica(0).port, timeout=60
        )
        conn.request(
            "POST", "/generate",
            body=json.dumps({"prompt": "post retire", "max_new_tokens": 4}),
            headers={"X-Request-Id": "post-retire"},
        )
        resp = conn.getresponse()
        lines = resp.read().decode().splitlines()
        conn.close()
        assert resp.status == 200
        assert json.loads(lines[-1])["event"] == "done"
    finally:
        fleet.stop(drain=False)
