"""Latency-hiding prefetch pipeline (data/prefetch.py): batch-stream
equivalence against the raw loader engines, bounded queue depth, exception
propagation, clean mid-epoch shutdown, resume-with-skip, and the Trainer
acceptance contract — prefetch on/off walks a bitwise-identical training
trajectory. CPU-only, tier-1."""

import json
import os
import time

import numpy as np
import pytest

from pytorch_distributed_training_tpu.comms.mesh import build_mesh
from pytorch_distributed_training_tpu.data import (
    PrefetchingIterator,
    PrefetchingLoader,
    ShardedLoader,
)
from pytorch_distributed_training_tpu.data.synthetic import synthetic_pair_task
from pytorch_distributed_training_tpu.utils.config import MeshConfig


def _materialize(batches):
    import jax

    return [
        {k: np.asarray(jax.device_get(v)) for k, v in b.items()}
        for b in batches
    ]


def _assert_streams_equal(a, b):
    assert len(a) == len(b)
    for x, y in zip(a, b):
        assert sorted(x) == sorted(y)
        for k in x:
            np.testing.assert_array_equal(x[k], y[k], err_msg=k)


# ------------------------------------------------------------- iterator unit


def test_bounded_queue_depth():
    pulled = []

    def src():
        for i in range(100):
            pulled.append(i)
            yield i

    it = PrefetchingIterator(src(), depth=3)
    got = [next(it) for _ in range(5)]
    assert got == list(range(5))
    time.sleep(0.3)  # give the producer every chance to overrun
    # consumed + queue depth + at most one item in the producer's hand
    assert len(pulled) <= 5 + 3 + 1
    it.close()


def test_worker_exception_propagates_in_order():
    def src():
        yield 1
        yield 2
        raise RuntimeError("boom in worker")

    it = PrefetchingIterator(src(), depth=2)
    assert next(it) == 1
    assert next(it) == 2
    with pytest.raises(RuntimeError, match="boom in worker"):
        next(it)
    # exhausted after the error, not wedged
    with pytest.raises(StopIteration):
        next(it)


def test_clean_close_midepoch_no_dangling_thread():
    finalized = []

    def src():
        try:
            for i in range(1000):
                yield i
        finally:
            finalized.append(True)

    it = PrefetchingIterator(src(), depth=2)
    assert next(it) == 0
    it.close()
    it._thread.join(timeout=5.0)
    assert not it._thread.is_alive()  # worker released
    assert finalized == [True]  # inner generator's finally ran
    with pytest.raises(StopIteration):
        next(it)
    it.close()  # idempotent


def test_depth_validation():
    with pytest.raises(ValueError, match="depth"):
        PrefetchingIterator(iter([]), depth=0)
    with pytest.raises(ValueError, match="depth"):
        PrefetchingLoader(object(), depth=0)


# ----------------------------------------------------- loader-level contract


def test_stream_equivalent_to_python_loader(eight_devices):
    mesh = build_mesh(MeshConfig(data=8))
    d = synthetic_pair_task(128, max_length=16, vocab_size=500)
    raw = ShardedLoader(
        d, mesh, global_batch_size=32, grad_accum_steps=2, train=True
    )
    wrapped = PrefetchingLoader(
        ShardedLoader(
            d, mesh, global_batch_size=32, grad_accum_steps=2, train=True
        ),
        depth=2,
    )
    assert wrapped.steps_per_epoch == raw.steps_per_epoch
    for epoch in (0, 1):  # same epoch seeds ⇒ identical arrays, in order
        _assert_streams_equal(
            _materialize(raw.epoch(epoch)),
            _materialize(wrapped.epoch(epoch)),
        )
    wrapped.close()


def test_stream_equivalent_to_native_loader(eight_devices):
    from pytorch_distributed_training_tpu.native import native_available

    if not native_available():
        pytest.skip("no C++ toolchain")
    from pytorch_distributed_training_tpu.data.native_loader import (
        NativeShardedLoader,
    )

    mesh = build_mesh(MeshConfig(data=8))
    d = {
        "input_ids": np.arange(64 * 8, dtype=np.int32).reshape(64, 8),
        "labels": np.arange(64, dtype=np.int32),
    }
    raw = NativeShardedLoader(
        d, mesh, global_batch_size=16, grad_accum_steps=2, seed=7
    )
    wrapped = PrefetchingLoader(
        NativeShardedLoader(
            d, mesh, global_batch_size=16, grad_accum_steps=2, seed=7
        ),
        depth=3,
    )
    try:
        _assert_streams_equal(
            _materialize(raw.epoch(0)), _materialize(wrapped.epoch(0))
        )
    finally:
        raw.close()
        wrapped.close()


def test_resume_skip_prefix_matches(eight_devices):
    """Mid-epoch resume consumes and discards the first `skip` batches; the
    remainder must be exactly the raw stream's tail."""
    mesh = build_mesh(MeshConfig(data=8))
    d = synthetic_pair_task(128, max_length=16, vocab_size=500)
    raw = ShardedLoader(
        d, mesh, global_batch_size=32, grad_accum_steps=2, train=True
    )
    wrapped = PrefetchingLoader(
        ShardedLoader(
            d, mesh, global_batch_size=32, grad_accum_steps=2, train=True
        ),
        depth=2,
    )
    skip = 2
    tail_raw = _materialize(raw.epoch(0))[skip:]
    it = wrapped.epoch(0)
    for _ in range(skip):
        next(it)
    _assert_streams_equal(tail_raw, _materialize(it))
    wrapped.close()


def test_new_epoch_retires_abandoned_iterator(eight_devices):
    mesh = build_mesh(MeshConfig(data=8))
    d = synthetic_pair_task(64, max_length=16, vocab_size=500)
    wrapped = PrefetchingLoader(
        ShardedLoader(d, mesh, global_batch_size=32, train=True), depth=2
    )
    first = wrapped.epoch(0)
    next(first)  # abandon mid-epoch
    second = wrapped.epoch(1)
    assert not first._thread.is_alive()  # retired, not leaked
    assert len(_materialize(second)) == wrapped.steps_per_epoch
    wrapped.close()
    assert not second._thread.is_alive()


def test_prefetch_telemetry_occupancy_and_stalls(eight_devices):
    from pytorch_distributed_training_tpu.telemetry import (
        MetricsRegistry,
        set_registry,
    )

    mesh = build_mesh(MeshConfig(data=8))
    d = synthetic_pair_task(128, max_length=16, vocab_size=500)
    reg = MetricsRegistry()
    prev = set_registry(reg)
    try:
        wrapped = PrefetchingLoader(
            ShardedLoader(d, mesh, global_batch_size=32, train=True), depth=2
        )
        n = len(list(wrapped.epoch(0)))
        wrapped.close()
    finally:
        set_registry(prev)
    snap = reg.snapshot()
    occ = snap["timers"]["data/prefetch_occupancy"]
    assert occ["count"] == n
    assert 0.0 <= occ["max_s"] <= 2.0  # bounded by depth
    # stall accounting is consistent: every stall observed a wait
    stalls = snap["counters"].get("data/prefetch_stalls", 0)
    stall_t = snap["timers"].get("data/prefetch_stall_s", {"count": 0})
    assert stall_t.get("count", 0) == stalls


# -------------------------------------------------------- trainer acceptance


def _tiny_trainer(**tcfg_kw):
    from pytorch_distributed_training_tpu.parallel import ShardingPolicy
    from pytorch_distributed_training_tpu.train.loop import Trainer
    from pytorch_distributed_training_tpu.utils.config import (
        TrainConfig,
        model_preset,
    )

    mcfg = model_preset("tiny", compute_dtype="float32")
    defaults = dict(
        num_epochs=1,
        global_batch_size=32,
        micro_batch_size=16,
        eval_batch_size=32,
        learning_rate=3e-3,
        warmup_steps=10,
        log_every=0,
        bf16=False,
        train_size=128,
        eval_size=32,
    )
    defaults.update(tcfg_kw)
    return Trainer(
        mcfg, TrainConfig(**defaults), MeshConfig(data=4, fsdp=2),
        ShardingPolicy(fsdp=True, fsdp_min_size=128),
        task="synthetic",
    )


def test_trainer_wraps_train_loader_only(eight_devices):
    t = _tiny_trainer(prefetch_depth=2)
    assert isinstance(t.train_loader, PrefetchingLoader)
    assert not isinstance(t.eval_loader, PrefetchingLoader)
    t0 = _tiny_trainer(prefetch_depth=0)
    assert not isinstance(t0.train_loader, PrefetchingLoader)
    with pytest.raises(ValueError, match="prefetch_depth"):
        _tiny_trainer(prefetch_depth=-1)


def test_trainer_bitwise_equivalent_prefetch_on_off(
    eight_devices, tmp_path
):
    """Acceptance: identical seeds ⇒ --prefetch-depth 2 and 0 produce the
    same per-step losses and final params (bitwise, on CPU)."""
    import jax

    runs = {}
    for depth in (0, 2):
        mdir = str(tmp_path / f"m{depth}")
        t = _tiny_trainer(prefetch_depth=depth, metrics_dir=mdir)
        t.run()
        with open(os.path.join(mdir, "metrics.jsonl")) as f:
            records = [json.loads(l) for l in f if l.strip()]
        losses = [
            r["loss"] for r in records if r.get("record") == "step"
        ]
        params = np.concatenate([
            np.ravel(jax.device_get(x))
            for x in jax.tree.leaves(t.state.params)
        ])
        runs[depth] = (losses, params)
    assert runs[0][0] == runs[2][0]  # per-step losses, exactly
    np.testing.assert_array_equal(runs[0][1], runs[2][1])  # final params
