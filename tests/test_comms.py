"""Tests for the comms layer (mesh, collectives, ingest) on 8 virtual CPU
devices — the simulated-distributed strategy the reference lacks entirely
(SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from pytorch_distributed_training_tpu.comms import (
    build_mesh,
    host_allgather,
    gather_pytree,
    initialize,
    make_global_batch,
    runtime_info,
)
from pytorch_distributed_training_tpu.comms.mesh import (
    batch_pspec,
    dp_degree,
    shard_batch,
)
from pytorch_distributed_training_tpu.utils.config import MeshConfig


def test_runtime_info_single_process(eight_devices):
    info = initialize()
    assert info.process_count == 1
    assert info.is_main
    assert info.global_device_count == 8
    assert runtime_info().backend == "cpu"


def test_mesh_default_all_data(eight_devices):
    mesh = build_mesh()
    assert mesh.shape == {"data": 8, "fsdp": 1, "stage": 1, "model": 1, "seq": 1}
    assert dp_degree(mesh) == 8


def test_mesh_hybrid_shapes(eight_devices):
    mesh = build_mesh(MeshConfig(data=2, model=4))
    assert mesh.shape == {"data": 2, "fsdp": 1, "stage": 1, "model": 4, "seq": 1}
    mesh = build_mesh(MeshConfig(data=-1, stage=2))
    assert mesh.shape["data"] == 4 and mesh.shape["stage"] == 2


def test_mesh_invalid_shapes(eight_devices):
    with pytest.raises(ValueError):
        build_mesh(MeshConfig(data=3))  # 3 doesn't divide 8
    with pytest.raises(ValueError):
        MeshConfig(data=-1, model=-1).resolved_shape(8)


def test_batch_sharding_spreads_over_devices(eight_devices):
    mesh = build_mesh(MeshConfig(data=4, fsdp=2))
    x = jnp.arange(16 * 3, dtype=jnp.float32).reshape(16, 3)
    xs = shard_batch(mesh, {"x": x})["x"]
    # batch dim sharded over data*fsdp = 8 shards of 2 rows
    assert len(xs.addressable_shards) == 8
    assert all(s.data.shape == (2, 3) for s in xs.addressable_shards)
    np.testing.assert_array_equal(np.asarray(xs), np.asarray(x))


def test_jit_psum_over_sharded_batch(eight_devices):
    """With batch sharded and output replicated, XLA must insert a real
    cross-device reduction (the DDP-allreduce equivalent)."""
    mesh = build_mesh(MeshConfig(data=8))
    x = jnp.ones((16, 4))
    xs = jax.device_put(x, NamedSharding(mesh, batch_pspec(extra_dims=1)))
    total = jax.jit(lambda a: a.sum(), out_shardings=NamedSharding(mesh, P()))(xs)
    assert float(total) == 64.0


def test_make_global_batch_single_process(eight_devices):
    mesh = build_mesh(MeshConfig(data=4, fsdp=2))
    batch = {
        "input_ids": np.arange(8 * 5, dtype=np.int32).reshape(8, 5),
        "labels": np.ones((8,), np.int32),
    }
    g = make_global_batch(mesh, batch)
    assert g["input_ids"].shape == (8, 5)
    assert g["labels"].sharding.spec == batch_pspec()
    np.testing.assert_array_equal(np.asarray(g["input_ids"]), batch["input_ids"])


def test_host_allgather_scalar_promotion(eight_devices):
    # scalar → 1-elem promotion, matching reference gather() :33-34 semantics
    out = host_allgather(np.float32(3.0))
    assert out.shape == (1,)
    tree = gather_pytree({"preds": np.arange(4), "loss": np.float32(1.5)})
    assert tree["preds"].shape == (4,)
    assert tree["loss"].shape == (1,)
