"""shard_map kernel dispatch on sharded meshes (ops/dispatch.py).

Round 2 silently lost every Pallas kernel on >1-device meshes (the GSPMD
partitioner treats a bare custom call as replicated). These tests pin the
round-3 contract on the 8-device CPU mesh, using the interpret context as
the kernel emulator:

- with a registered kernel mesh, each op actually takes the shard_map
  kernel path (trace-time dispatch counters — the observable, since
  interpret-mode HLO hides the custom call), and the numerics match the
  op's XLA reference math on the same global inputs;
- without a registered mesh on a multi-device backend, dispatch reports
  "off" — the documented explicit fallback, never a bare custom call.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.comms.mesh import build_mesh
from pytorch_distributed_training_tpu.ops import dispatch
from pytorch_distributed_training_tpu.ops.flash_attention import (
    tpu_interpret_mode,
)
from pytorch_distributed_training_tpu.ops.layer_norm import (
    dropout_add_layer_norm,
    layer_norm,
    reference_layer_norm,
)
from pytorch_distributed_training_tpu.utils.config import MeshConfig


@pytest.fixture()
def mesh(eight_devices):
    return build_mesh(MeshConfig(data=4, fsdp=2))


def _counts(op):
    return dispatch.KERNEL_DISPATCH_COUNTS[op]


def test_mode_off_without_registered_mesh(eight_devices):
    # 8 CPU devices, no interpret ctx, no mesh: kernels must NOT dispatch
    assert dispatch.mode() == "off"


def test_layer_norm_shard_map_dispatch(mesh):
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.normal(size=(8, 16, 256)), jnp.float32)
    scale = jnp.asarray(rng.normal(size=(256,)), jnp.float32)
    bias = jnp.asarray(rng.normal(size=(256,)), jnp.float32)
    ref = reference_layer_norm(x, scale, bias, eps=1e-12)
    before = _counts("layer_norm")
    with tpu_interpret_mode(), dispatch.use_kernel_mesh(mesh):
        assert dispatch.mode() == "shard_map"
        out = layer_norm(x, scale, bias, eps=1e-12)
    assert _counts("layer_norm") == before + 1
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
    )


def test_layer_norm_indivisible_falls_back(mesh):
    """Batch 6 doesn't divide over data=4 x fsdp=2: explicit XLA fallback
    (correct numerics), not a bare custom call."""
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(6, 16, 256)), jnp.float32)
    scale = jnp.ones((256,), jnp.float32)
    bias = jnp.zeros((256,), jnp.float32)
    before = _counts("layer_norm")
    with tpu_interpret_mode(), dispatch.use_kernel_mesh(mesh):
        out = layer_norm(x, scale, bias, eps=1e-12)
    assert _counts("layer_norm") == before  # no kernel dispatch
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(reference_layer_norm(x, scale, bias, eps=1e-12)),
        atol=1e-6, rtol=1e-6,
    )


def test_dal_shard_map_dispatch_deterministic(mesh):
    rng = np.random.default_rng(2)
    h = jnp.asarray(rng.normal(size=(8, 16, 256)), jnp.float32)
    x = jnp.asarray(rng.normal(size=(8, 16, 256)), jnp.float32)
    scale = jnp.asarray(rng.normal(size=(256,)), jnp.float32)
    bias = jnp.asarray(rng.normal(size=(256,)), jnp.float32)
    ref = reference_layer_norm(x + h, scale, bias, eps=1e-12)
    before = _counts("dal")
    with tpu_interpret_mode(), dispatch.use_kernel_mesh(mesh):
        out = dropout_add_layer_norm(
            h, x, scale, bias, rate=0.1, deterministic=True, eps=1e-12
        )
    assert _counts("dal") == before + 1
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
    )


def test_mask_scale_shard_map_per_device_streams(mesh):
    """Kernel dropout under a sharded mesh: kernel path taken, mask values
    are exactly {0, 1/(1-rate)} ... and the per-device seed offset gives
    different shards different masks.

    NOTE: pltpu.prng_random_bits is all-zeros in interpret mode off-TPU
    (NOTES.md), which maps every position to "drop" — so mask STATISTICS
    are unverifiable here (the on-TPU tier covers them); this test pins
    dispatch + shape/value-domain only.
    """
    from pytorch_distributed_training_tpu.ops.dropout import raw_dropout

    x = jnp.ones((8, 16, 256), jnp.float32)
    before = _counts("mask_scale")
    with tpu_interpret_mode(), dispatch.use_kernel_mesh(mesh):
        out = raw_dropout(x, 0.25, jax.random.key(0), "kernel")
    assert _counts("mask_scale") == before + 1
    vals = np.unique(np.asarray(out).round(6))
    assert set(vals).issubset({0.0, np.float32(1 / 0.75).round(6)})


def test_flash_shard_map_dispatch(mesh, monkeypatch):
    """flash routes through shard_map with per-shard seed offsetting.

    The Pallas kernel itself is swapped for its jnp math here: interpret-
    mode kernel emulation inside an 8-way shard_map is pathologically slow
    on the single-core CPU image (minutes per call), and what this test
    pins is the ROUTING — specs, divisibility, counter, numerics of the
    sharded composition. Real kernel-under-shard_map execution is the
    on-TPU tier's job (test_tpu_kernels.py).
    """
    import pytorch_distributed_training_tpu.ops.flash_attention as fa
    from pytorch_distributed_training_tpu.ops.attention import (
        make_attention_bias,
        reference_attention,
    )

    def jnp_base(q, k, v, bias, seed, *, dropout_rate=0.0, causal=False,
                 block_q=None, block_k=None):
        # [B, N, S, D] math twin of flash_attention_base, no dropout
        s = jnp.einsum(
            "bnsd,bntd->bnst", q, k, preferred_element_type=jnp.float32
        ) * (q.shape[-1] ** -0.5)
        s = s + bias
        p = jax.nn.softmax(s, axis=-1).astype(v.dtype)
        return jnp.einsum("bnst,bntd->bnsd", p, v)

    monkeypatch.setattr(fa, "flash_attention_base", jnp_base)
    rng = np.random.default_rng(3)
    q, k, v = (
        jnp.asarray(rng.normal(size=(8, 128, 4, 64)), jnp.float32)
        for _ in range(3)
    )
    mask = jnp.ones((8, 128), jnp.int32)
    bias = make_attention_bias(mask)
    ref = reference_attention(q, k, v, bias)
    before = _counts("flash")
    with tpu_interpret_mode(), dispatch.use_kernel_mesh(mesh):
        out = fa.flash_attention(q, k, v, bias)
    assert _counts("flash") == before + 1
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
    )


def test_flash_cp_mesh_falls_back(eight_devices):
    """With an active seq (context-parallel) axis flash must NOT shard_map
    (ring attention owns that regime) — reference fallback instead."""
    from pytorch_distributed_training_tpu.ops.flash_attention import (
        flash_attention,
    )

    cp_mesh = build_mesh(MeshConfig(data=2, seq=4))
    rng = np.random.default_rng(4)
    q, k, v = (
        jnp.asarray(rng.normal(size=(4, 128, 4, 64)), jnp.float32)
        for _ in range(3)
    )
    before = _counts("flash")
    with tpu_interpret_mode(), dispatch.use_kernel_mesh(cp_mesh):
        out = flash_attention(q, k, v, None)
    assert _counts("flash") == before
    assert np.isfinite(np.asarray(out)).all()


def test_bert_layer_end_to_end_sharded_kernels(mesh):
    """A whole BertLayer under jit on the sharded mesh with the kernel
    dispatch active: runs, matches the reference-impl layer at dropout 0."""
    from pytorch_distributed_training_tpu.models.bert import BertLayer
    from pytorch_distributed_training_tpu.utils.config import model_preset

    cfg = model_preset(
        "tiny", compute_dtype="float32",
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(size=(8, 16, cfg.hidden_size)), jnp.float32)
    layer = BertLayer(cfg)
    params = layer.init(jax.random.key(0), x, None, True)["params"]
    ref = layer.apply({"params": params}, x, None, True)
    with tpu_interpret_mode(), dispatch.use_kernel_mesh(mesh):
        out = layer.apply({"params": params}, x, None, True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
    )
