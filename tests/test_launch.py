"""Launcher tests: REAL multi-process rendezvous + collectives.

Everything else in this suite simulates distribution with 8 in-process
virtual devices; these tests spawn actual OS processes through
``cli.launch`` (the ``torch.distributed.run`` / ``mp.spawn`` twin,
reference README.md:13, test_model_parallelism.py:333-335) so the
``jax.distributed.initialize`` rendezvous, cross-process Gloo collectives,
per-process host data sharding, and failure teardown all run for real.
"""

import re
import subprocess
import sys
import time

import numpy as np
import pytest

LAUNCH = [sys.executable, "-m", "pytorch_distributed_training_tpu.cli.launch"]
TRAIN = [
    sys.executable, "-m", "pytorch_distributed_training_tpu.cli.train_dp",
    "--model", "tiny", "--num-epochs", "1", "--train-size", "64",
    "--eval-size", "32", "--global-batch-size", "16", "--micro-batch-size",
    "8", "--native-loader", "off", "--log-every", "0",
]


def _epoch_record(stdout: str) -> dict:
    m = re.search(r"'train_loss': ([0-9.einf-]+).*?'accuracy': ([0-9.]+)", stdout)
    assert m, f"no epoch record in output:\n{stdout[-2000:]}"
    return {"train_loss": float(m.group(1)), "accuracy": float(m.group(2))}


@pytest.mark.slow
def test_two_process_train_matches_single_process(tmp_path):
    """2 processes x 2 devices must train the same model as 1 process x 4
    devices: same global batches (host-sharded halves), same psum'd grads,
    same metrics — the property that keeps multi-host runs trustworthy."""
    multi = subprocess.run(
        LAUNCH + ["--nprocs", "2", "--devices-per-proc", "2", "--"] + TRAIN,
        capture_output=True, text=True, timeout=540,
    )
    assert multi.returncode == 0, multi.stdout[-3000:] + multi.stderr[-2000:]
    rec_multi = _epoch_record(multi.stdout)

    single = subprocess.run(
        LAUNCH + ["--nprocs", "1", "--devices-per-proc", "4", "--"] + TRAIN,
        capture_output=True, text=True, timeout=540,
    )
    assert single.returncode == 0, single.stdout[-3000:] + single.stderr[-2000:]
    rec_single = _epoch_record(single.stdout)

    np.testing.assert_allclose(
        rec_multi["train_loss"], rec_single["train_loss"], rtol=1e-4
    )
    assert rec_multi["accuracy"] == rec_single["accuracy"]


@pytest.mark.slow
def test_failure_terminates_siblings():
    """A crashing rank must take the job down (the reference's
    ``join=True`` only propagates the crash; siblings blocked in a
    collective would hang forever)."""
    code = (
        "import os, sys, time\n"
        "if os.environ['JAX_PROCESS_ID'] == '1':\n"
        "    sys.exit(3)\n"
        "time.sleep(120)\n"
    )
    t0 = time.monotonic()
    res = subprocess.run(
        LAUNCH + ["--nprocs", "2", "--", sys.executable, "-c", code],
        capture_output=True, text=True, timeout=90,
    )
    assert res.returncode == 3, (res.returncode, res.stderr[-500:])
    assert time.monotonic() - t0 < 60  # rank 0 was terminated, not waited out
    assert "terminating" in res.stderr
