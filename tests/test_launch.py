"""Launcher tests: REAL multi-process rendezvous + collectives.

Everything else in this suite simulates distribution with 8 in-process
virtual devices; these tests spawn actual OS processes through
``cli.launch`` (the ``torch.distributed.run`` / ``mp.spawn`` twin,
reference README.md:13, test_model_parallelism.py:333-335) so the
``jax.distributed.initialize`` rendezvous, cross-process Gloo collectives,
per-process host data sharding, and failure teardown all run for real.
"""

import re
import subprocess
import sys
import time

import numpy as np
import pytest

LAUNCH = [sys.executable, "-m", "pytorch_distributed_training_tpu.cli.launch"]
TRAIN = [
    sys.executable, "-m", "pytorch_distributed_training_tpu.cli.train_dp",
    "--model", "tiny", "--num-epochs", "1", "--train-size", "64",
    "--eval-size", "32", "--global-batch-size", "16", "--micro-batch-size",
    "8", "--native-loader", "off", "--log-every", "0",
]


def _epoch_record(stdout: str) -> dict:
    m = re.search(r"'train_loss': ([0-9.einf-]+).*?'accuracy': ([0-9.]+)", stdout)
    assert m, f"no epoch record in output:\n{stdout[-2000:]}"
    return {"train_loss": float(m.group(1)), "accuracy": float(m.group(2))}


def _assert_multi_matches_single(train_cmd, *, nprocs=2, devices_per_proc=2):
    """Run ``train_cmd`` under the launcher twice — nprocs × devices each,
    then one process holding the whole mesh — and pin equal metrics."""
    total = nprocs * devices_per_proc
    multi = subprocess.run(
        LAUNCH + ["--nprocs", str(nprocs), "--devices-per-proc",
                  str(devices_per_proc), "--"] + train_cmd,
        capture_output=True, text=True, timeout=540,
    )
    assert multi.returncode == 0, multi.stdout[-3000:] + multi.stderr[-2000:]
    rec_multi = _epoch_record(multi.stdout)

    single = subprocess.run(
        LAUNCH + ["--nprocs", "1", "--devices-per-proc", str(total), "--"]
        + train_cmd,
        capture_output=True, text=True, timeout=540,
    )
    assert single.returncode == 0, (
        single.stdout[-3000:] + single.stderr[-2000:]
    )
    rec_single = _epoch_record(single.stdout)

    np.testing.assert_allclose(
        rec_multi["train_loss"], rec_single["train_loss"], rtol=1e-4
    )
    assert rec_multi["accuracy"] == rec_single["accuracy"]


@pytest.mark.slow
def test_two_process_train_matches_single_process(tmp_path):
    """2 processes x 2 devices must train the same model as 1 process x 4
    devices: same global batches (host-sharded halves), same psum'd grads,
    same metrics — the property that keeps multi-host runs trustworthy."""
    _assert_multi_matches_single(TRAIN)


@pytest.mark.slow
def test_two_process_hybrid_dp_mp_matches_single_process(tmp_path):
    """The reference's ACTUAL model-parallel regime is multi-process DDP
    wrapping a multi-device module (test_model_parallelism.py:248-253,333).
    Its twin here: 2 processes × 2 devices over a data=2 × model=2 mesh —
    DP across processes, the branch-ensemble's branches split over the
    model axis WITHIN each process — must train the same model as one
    process holding the whole 4-device mesh."""
    _assert_multi_matches_single([
        sys.executable, "-m",
        "pytorch_distributed_training_tpu.cli.train_mp",
        "--model", "tiny", "--mp-mode", "branch", "--n-branches", "2",
        "--mesh-data", "2", "--mesh-model", "2",
        "--num-epochs", "1", "--train-size", "64", "--eval-size", "32",
        "--global-batch-size", "16", "--micro-batch-size", "8",
        "--native-loader", "off", "--log-every", "0",
    ])


@pytest.mark.slow
def test_failure_terminates_siblings():
    """A crashing rank must take the job down (the reference's
    ``join=True`` only propagates the crash; siblings blocked in a
    collective would hang forever)."""
    code = (
        "import os, sys, time\n"
        "if os.environ['JAX_PROCESS_ID'] == '1':\n"
        "    sys.exit(3)\n"
        "time.sleep(120)\n"
    )
    t0 = time.monotonic()
    res = subprocess.run(
        LAUNCH + ["--nprocs", "2", "--", sys.executable, "-c", code],
        capture_output=True, text=True, timeout=90,
    )
    assert res.returncode == 3, (res.returncode, res.stderr[-500:])
    assert time.monotonic() - t0 < 60  # rank 0 was terminated, not waited out
    assert "terminating" in res.stderr


@pytest.mark.slow
def test_crash_restart_resume_matches_uninterrupted(tmp_path):
    """The full multi-process recovery loop (VERDICT r1 #7): rank 1 is
    hard-killed mid-epoch (fault injection, TrainConfig.crash_at_step),
    the launcher tears the job down, a relaunch with --resume restores the
    latest checkpoint — and the resumed run must land on EXACTLY the same
    final parameters as an uninterrupted run (bitwise, via the saved final
    checkpoints)."""
    import numpy as np

    def launch(ckdir, extra, timeout=540):
        cmd = LAUNCH + ["--nprocs", "2", "--devices-per-proc", "2", "--"]
        cmd += TRAIN + [
            "--checkpoint-dir", str(ckdir), "--checkpoint-every-steps", "2",
        ] + extra
        return subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout)

    # uninterrupted run: 4 updates (64 examples / global batch 16)
    a = launch(tmp_path / "a", [])
    assert a.returncode == 0, a.stdout[-3000:] + a.stderr[-2000:]
    rec_a = _epoch_record(a.stdout)

    # interrupted: rank 1 dies right after update 3 (checkpoint exists at
    # step 2); launcher must propagate the failure and kill rank 0
    b1 = launch(tmp_path / "b", ["--crash-at-step", "3", "--crash-rank", "1"])
    assert b1.returncode == 13, (b1.returncode, b1.stderr[-1000:])
    assert "terminating" in b1.stderr
    assert "injected crash at step 3" in b1.stdout

    # the step-2 checkpoint must have committed before the crash —
    # otherwise the relaunch would replay from scratch and this test
    # would pass vacuously without exercising restore at all
    from pytorch_distributed_training_tpu.train import checkpoint as ckpt

    assert ckpt.latest_step(str(tmp_path / "b")) == 2

    # relaunch with --resume: restores step 2, replays updates 3..4
    b2 = launch(tmp_path / "b", ["--resume"])
    assert b2.returncode == 0, b2.stdout[-3000:] + b2.stderr[-2000:]
    assert "resuming" in b2.stdout.lower() or "restored" in b2.stdout.lower(), (
        b2.stdout[-2000:]
    )
    rec_b = _epoch_record(b2.stdout)
    assert rec_b["accuracy"] == rec_a["accuracy"]

    # bitwise: final checkpoints (step 4) hold identical params. Restore
    # with an abstract target (the checkpoints were written on the
    # subprocesses' own 4-device meshes; without it orbax tries to rebuild
    # those exact devices).
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_training_tpu.models import (
        BertForSequenceClassification,
    )
    from pytorch_distributed_training_tpu.utils.config import model_preset

    assert ckpt.latest_step(str(tmp_path / "a")) == ckpt.latest_step(
        str(tmp_path / "b")
    )
    model = BertForSequenceClassification(model_preset("tiny"))
    abstract = jax.eval_shape(
        lambda: model.init(jax.random.key(0), jnp.ones((1, 8), jnp.int32))
    )["params"]
    sharding = jax.sharding.SingleDeviceSharding(jax.devices()[0])
    abstract = jax.tree.map(
        lambda s: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sharding),
        abstract,
    )
    pa = ckpt.restore_params(str(tmp_path / "a"), params_like=abstract)
    pb = ckpt.restore_params(str(tmp_path / "b"), params_like=abstract)

    flat_a = np.concatenate(
        [np.ravel(np.asarray(x)) for x in jax.tree.leaves(pa)]
    )
    flat_b = np.concatenate(
        [np.ravel(np.asarray(x)) for x in jax.tree.leaves(pb)]
    )
    np.testing.assert_array_equal(flat_a, flat_b)
