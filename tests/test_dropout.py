"""Dropout-op tests: statistics, expectation preservation, flax parity.

The reference inherits torch dropout inside HF BERT (reference
test_data_parallelism.py:112); this framework owns the op (ops/dropout.py)
with selectable mask generators, so each generator's distributional contract
is pinned here.
"""

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.ops.dropout import (
    DROPOUT_IMPLS,
    Dropout,
    raw_dropout,
)

RATE = 0.1


@pytest.mark.parametrize("impl", DROPOUT_IMPLS)
def test_keep_rate_and_expectation(impl):
    """Empirical drop rate matches the impl's nominal rate and E[out] == x
    (inverted dropout scales by exactly the applied rate)."""
    x = jnp.ones((64, 1024), jnp.float32)
    rng = jax.random.key(0)
    out = raw_dropout(x, RATE, rng, impl)
    dropped = float((out == 0).mean())
    # bits8 quantizes the rate to 26/256; all within ±1% absolute here
    expected = 26 / 256 if impl == "bits8" else RATE
    assert abs(dropped - expected) < 0.01, (impl, dropped)
    # kept values are scaled by 1/(1-applied_rate) -> empirical mean ~= 1
    assert abs(float(out.mean()) - 1.0) < 0.02, (impl, float(out.mean()))


@pytest.mark.parametrize("impl", DROPOUT_IMPLS)
def test_deterministic_under_same_key(impl):
    x = jax.random.normal(jax.random.key(1), (32, 257))  # odd minor dim
    rng = jax.random.key(2)
    a = raw_dropout(x, RATE, rng, impl)
    b = raw_dropout(x, RATE, rng, impl)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    c = raw_dropout(x, RATE, jax.random.key(3), impl)
    assert not np.array_equal(np.asarray(a), np.asarray(c))


def test_exact_matches_flax_dropout():
    """The module with impl="exact" is bit-identical to ``nn.Dropout`` under
    the same rng collection (both resolve the key via ``make_rng`` from the
    same module path)."""
    x = jax.random.normal(jax.random.key(4), (16, 128))
    rngs = {"dropout": jax.random.key(5)}
    ours = Dropout(RATE, "exact").apply({}, x, deterministic=False, rngs=rngs)
    theirs = nn.Dropout(RATE, deterministic=False).apply({}, x, rngs=rngs)
    np.testing.assert_array_equal(np.asarray(ours), np.asarray(theirs))


def test_module_deterministic_is_identity():
    x = jax.random.normal(jax.random.key(6), (4, 8))
    for impl in DROPOUT_IMPLS:
        out = Dropout(RATE, impl).apply({}, x, deterministic=True)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(x))
    out = Dropout(0.0, "bits32").apply(
        {}, x, deterministic=False, rngs={"dropout": jax.random.key(7)}
    )
    np.testing.assert_array_equal(np.asarray(out), np.asarray(x))


def test_unknown_impl_raises():
    with pytest.raises(ValueError, match="unknown dropout impl"):
        raw_dropout(jnp.ones((4, 4)), RATE, jax.random.key(0), "nope")


def test_bits8_padded_minor_dim():
    """bits8's word->byte bitcast path (minor dim % 4 == 0) and the fallback
    path (odd minor dim) both honor the quantized rate."""
    rng = jax.random.key(8)
    for shape in ((8, 1024), (8, 1023)):
        x = jnp.ones(shape, jnp.bfloat16)
        out = raw_dropout(x, RATE, rng, "bits8")
        dropped = float((out == 0).mean())
        assert abs(dropped - 26 / 256) < 0.02, (shape, dropped)
