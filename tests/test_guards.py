"""Runtime guard tests (analysis/guards.py): recompile detection around
jitted entry points, implicit-transfer arming, donation/sharding audits,
and the acceptance contracts — zero unexpected retraces/transfers across a
warm 3-step CPU train run and a warm two-bucket serve session, plus
negative tests proving a deliberate violation is detected, recorded in
telemetry and (strict) fails. CPU-only, tier-1."""

import json
import os
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.analysis.guards import (
    GuardSet,
    GuardViolation,
    RecompileError,
    TransferGuardError,
    donation_audit,
    guard_mode_from_env,
    sharding_audit,
)
from pytorch_distributed_training_tpu.comms.mesh import build_mesh
from pytorch_distributed_training_tpu.telemetry.registry import (
    MetricsRegistry,
)
from pytorch_distributed_training_tpu.utils.config import MeshConfig


class ListSink:
    """In-memory telemetry sink (same contract as JsonlSink.emit)."""

    def __init__(self):
        self.records = []

    def emit(self, record):
        rec = dict(record)
        rec.setdefault("ts", time.time())
        self.records.append(rec)

    def flush(self, **kw):
        pass

    def of(self, kind):
        return [r for r in self.records if r.get("record") == kind]


def _guards(mode):
    reg = MetricsRegistry()
    sink = ListSink()
    reg.attach_sink(sink)
    return GuardSet(mode=mode, registry=reg), sink


# ------------------------------------------------------------ recompile guard


def test_recompile_strict_raises_and_records():
    gs, sink = _guards("strict")
    f = gs.wrap_jit("f", jax.jit(lambda x: x * 2))
    f(jnp.ones((2,)))              # warm-up compile: expected
    f(jnp.ones((2,)))              # warm, same shape: fine
    assert gs.violations == 0 and not sink.of("recompile")

    with pytest.raises(RecompileError, match="retraced after warm-up"):
        f(jnp.ones((3,)))          # new shape -> retrace -> violation
    (rec,) = sink.of("recompile")
    assert rec["name"] == "f" and rec["calls"] == 3
    assert gs.recompile_violations == 1
    assert gs.registry.snapshot()["counters"]["guards/recompiles"] == 1


def test_recompile_record_mode_does_not_raise():
    gs, sink = _guards("record")
    f = gs.wrap_jit("f", jax.jit(lambda x: x + 1))
    f(jnp.ones((2,)))
    out = f(jnp.ones((5,)))        # retrace: recorded, not fatal
    np.testing.assert_array_equal(np.asarray(out), np.full((5,), 2.0))
    assert gs.recompile_violations == 1 and len(sink.of("recompile")) == 1


def test_guard_off_is_passthrough():
    gs, sink = _guards("off")
    f = gs.wrap_jit("f", jax.jit(lambda x: x + 1))
    f(jnp.ones((2,)))
    f(jnp.ones((7,)))              # retrace fine: guards off
    assert gs.violations == 0 and sink.records == []


def test_wrap_is_idempotent_and_forwards_attrs():
    gs, _ = _guards("record")
    jitted = jax.jit(lambda x: x + 1)
    f = gs.wrap_jit("f", jitted)
    assert gs.wrap_jit("f", f) is f
    # .lower passes through to the jit object (the AOT path needs it)
    lowered = f.lower(jnp.ones((2,)))
    assert lowered.compile() is not None


def test_aot_compiled_cannot_retrace():
    gs, sink = _guards("strict")
    compiled = jax.jit(lambda x: x * 3).lower(jnp.ones((4,))).compile()
    f = gs.wrap_jit("aot", compiled)
    assert f.warm  # no trace cache -> warm immediately
    f(jnp.ones((4,)))
    f(jnp.ones((4,)))
    assert gs.violations == 0 and sink.records == []


# ------------------------------------------------------------- transfer guard


def test_transfer_strict_catches_host_array_into_warm_jit():
    gs, sink = _guards("strict")
    f = gs.wrap_jit("f", jax.jit(lambda x: x + 1))
    f(jnp.ones((4,)))              # warm on a placed device array
    with pytest.raises(TransferGuardError, match="implicit transfer"):
        f(np.ones((4,), np.float32))   # un-placed host array -> H2D per call
    (rec,) = sink.of("implicit_transfer")
    assert rec["name"] == "f" and "transfer" in rec["error"]
    assert gs.transfer_violations == 1


def test_transfer_scope_arms_arbitrary_regions():
    gs, sink = _guards("strict")
    g = jax.jit(lambda x: x * 2)
    # arrays created OUTSIDE the scope: creating one inside would itself
    # upload its fill constant and trip the guard
    dev = jnp.ones((3,))
    host = np.ones((3,), np.float32)
    g(dev)                         # compile outside the scope
    with gs.transfer_scope("tick"):
        g(dev)                     # device args: clean
    with pytest.raises(TransferGuardError):
        with gs.transfer_scope("tick"):
            g(host)
    (rec,) = sink.of("implicit_transfer")
    assert rec["name"] == "tick"


def test_transfer_record_mode_never_raises():
    gs, sink = _guards("record")
    f = gs.wrap_jit("f", jax.jit(lambda x: x + 1))
    f(jnp.ones((4,)))
    f(np.ones((4,), np.float32))   # logged by jax, not fatal, not recorded
    assert gs.transfer_violations == 0


# ------------------------------------------------------------- donation audit


def test_donation_audit_ok_and_violation():
    reg = MetricsRegistry()
    sink = ListSink()
    reg.attach_sink(sink)
    a, b = jnp.ones((8,)), jnp.ones((8,))

    donated = jax.jit(lambda s, x: s + x, donate_argnums=(0,)).lower(a, b)
    rec = donation_audit("good", donated, registry=reg, mode="strict")
    assert rec["ok"] and rec["aliased"] >= 1
    # compiled HLO carries the alias map too
    rec2 = donation_audit(
        "good_compiled", donated.compile(), registry=reg, mode="strict"
    )
    assert rec2["ok"]

    dropped = jax.jit(lambda s, x: s + x).lower(a, b)  # no donation requested
    rec3 = donation_audit("bad", dropped, registry=reg, mode="record")
    assert not rec3["ok"] and rec3["aliased"] == 0
    with pytest.raises(GuardViolation, match="donation audit"):
        donation_audit("bad", dropped, registry=reg, mode="strict")
    assert len(sink.of("donation_audit")) == 4


# ------------------------------------------------------------- sharding audit


def test_sharding_audit_flags_replicated_on_sharded_mesh(eight_devices):
    from jax.sharding import NamedSharding, PartitionSpec as P

    mesh = build_mesh(MeshConfig(data=4, fsdp=2))
    reg = MetricsRegistry()
    sink = ListSink()
    reg.attach_sink(sink)
    big = jax.device_put(
        jnp.zeros((64, 64), jnp.float32), NamedSharding(mesh, P())
    )
    small = jax.device_put(
        jnp.zeros((4,), jnp.float32), NamedSharding(mesh, P())
    )
    sharded = jax.device_put(
        jnp.zeros((64, 64), jnp.float32), NamedSharding(mesh, P("fsdp"))
    )
    params = {"big": big, "small": small, "sharded": sharded}

    rec = sharding_audit(
        params, mesh, min_bytes=1024, registry=reg, mode="record"
    )
    assert not rec["ok"]
    assert [f["path"] for f in rec["flagged"]] == ["['big']"]
    with pytest.raises(GuardViolation, match="sharding audit"):
        sharding_audit(
            params, mesh, min_bytes=1024, registry=reg, mode="strict"
        )

    # dp-only mesh: replication is the design, audit is clean
    dp_mesh = build_mesh(MeshConfig(data=-1))
    rec_dp = sharding_audit(
        {"big": jax.device_put(
            jnp.zeros((64, 64)), NamedSharding(dp_mesh, P())
        )},
        dp_mesh, min_bytes=1024, registry=reg, mode="strict",
    )
    assert rec_dp["ok"]


# ----------------------------------------------------------------- env config


def test_guard_mode_from_env(monkeypatch):
    monkeypatch.delenv("PDT_TPU_GUARDS", raising=False)
    assert guard_mode_from_env() == "record"
    monkeypatch.setenv("PDT_TPU_GUARDS", "strict")
    assert guard_mode_from_env() == "strict"
    monkeypatch.setenv("PDT_TPU_GUARDS", "nope")
    with pytest.raises(ValueError, match="PDT_TPU_GUARDS"):
        guard_mode_from_env()
    with pytest.raises(ValueError, match="guards mode"):
        GuardSet(mode="nope")


# ----------------------------------------------- trainer acceptance (3 steps)


def _tiny_trainer(**tcfg_kw):
    from pytorch_distributed_training_tpu.parallel import ShardingPolicy
    from pytorch_distributed_training_tpu.train.loop import Trainer
    from pytorch_distributed_training_tpu.utils.config import (
        TrainConfig,
        model_preset,
    )

    mcfg = model_preset("tiny", compute_dtype="float32")
    defaults = dict(
        num_epochs=1,
        global_batch_size=32,
        micro_batch_size=16,
        eval_batch_size=32,
        learning_rate=3e-3,
        warmup_steps=10,
        log_every=0,
        bf16=False,
        train_size=96,   # 3 updates per epoch
        eval_size=32,
        guards="strict",
    )
    defaults.update(tcfg_kw)
    return Trainer(
        mcfg, TrainConfig(**defaults), MeshConfig(data=4, fsdp=2),
        ShardingPolicy(fsdp=True, fsdp_min_size=128),
        task="synthetic",
    )


@pytest.mark.parametrize("aot", [True, False], ids=["aot", "lazy-jit"])
def test_train_3_steps_zero_retraces_strict(eight_devices, tmp_path, aot):
    """Acceptance: a 3-step CPU train run under strict guards finishes with
    ZERO retraces after warm-up and zero implicit transfers — for both the
    AOT warm-start path (Compiled steps) and the lazy jit path (first call
    is the warm-up compile)."""
    mdir = str(tmp_path / ("aot" if aot else "jit"))
    t = _tiny_trainer(metrics_dir=mdir, aot_warmup=aot)
    history = t.run()
    assert len(history) == 1

    with open(os.path.join(mdir, "metrics.jsonl")) as f:
        records = [json.loads(line) for line in f if line.strip()]
    kinds = [r["record"] for r in records]
    assert kinds[0] == "run_meta"
    assert "recompile" not in kinds
    assert "implicit_transfer" not in kinds
    assert t.guards.violations == 0
    assert len([r for r in records if r["record"] == "step"]) == 3

    # the audits ran and passed
    (shard_rec,) = [r for r in records if r["record"] == "sharding_audit"]
    assert shard_rec["ok"]
    if aot:
        (don_rec,) = [r for r in records if r["record"] == "donation_audit"]
        assert don_rec["ok"] and don_rec["name"] == "train_step"
    # the guarded steps really were exercised
    assert t.guards.wrapped["train_step"].calls == 3
    assert t.guards.wrapped["eval_step"].calls >= 1


def test_trainer_guards_off_unwrapped(eight_devices):
    from pytorch_distributed_training_tpu.analysis.guards import GuardedCall

    t = _tiny_trainer(guards="off")
    t.run()
    assert not isinstance(t.train_step, GuardedCall)


# ------------------------------------------- serve acceptance (two buckets)


def test_serve_two_bucket_session_zero_retraces_strict():
    """Acceptance: a multi-request serve session spanning two prompt
    buckets — each bucket serving several requests through slot reuse —
    retraces nothing after each program's single warm-up compile, under
    strict guards (a retrace or implicit transfer would fail the loop)."""
    from pytorch_distributed_training_tpu.models.gpt2 import GPT2LMModel
    from pytorch_distributed_training_tpu.serve import (
        EngineConfig,
        InferenceServer,
    )
    from pytorch_distributed_training_tpu.serve.server import wait_until
    from pytorch_distributed_training_tpu.utils.config import model_preset

    cfg = model_preset(
        "gpt2-tiny", compute_dtype="float32", attention_impl="reference",
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    model = GPT2LMModel(cfg)
    params = model.init(jax.random.key(0), jnp.ones((2, 8), jnp.int32))[
        "params"
    ]
    gs, sink = _guards("strict")
    server = InferenceServer(
        model, params,
        EngineConfig(num_slots=2, prompt_buckets=(4, 8), max_new_tokens=4),
        queue_depth=16, registry=gs.registry, guards=gs,
    ).start()
    try:
        rng = np.random.default_rng(3)
        lengths = [3, 6, 2, 7, 4, 5]  # alternating buckets, reused slots
        reqs = [
            server.submit(
                rng.integers(1, cfg.vocab_size, n).astype(np.int32),
                max_new_tokens=4,
            )
            for n in lengths
        ]
        assert wait_until(
            lambda: all(r.done.is_set() for r in reqs), timeout=120
        )
    finally:
        server.close()

    assert all(r.status == "done" for r in reqs)
    stats = server.stats()
    assert stats["compiled_prefill_buckets"] == [4, 8]
    assert stats["guard_mode"] == "strict"
    assert stats["guard_recompiles"] == 0
    assert stats["guard_implicit_transfers"] == 0
    assert not sink.of("recompile") and not sink.of("implicit_transfer")
    # both buckets + decode really went through guarded entry points
    for name in ("serve_prefill_b4", "serve_prefill_b8", "serve_decode"):
        assert gs.wrapped[name].calls >= 2, name


def test_serve_retrace_violation_fails_loop_and_records():
    """Negative: force a retrace of a guarded serve program mid-session
    (shrink the resident cache behind the compiled decode step's back) and
    assert the violation is recorded AND the strict loop fails closed —
    every waiter's done event still fires."""
    from pytorch_distributed_training_tpu.models.gpt2 import GPT2LMModel
    from pytorch_distributed_training_tpu.serve import (
        EngineConfig,
        InferenceServer,
    )
    from pytorch_distributed_training_tpu.serve.server import wait_until
    from pytorch_distributed_training_tpu.utils.config import model_preset

    cfg = model_preset(
        "gpt2-tiny", compute_dtype="float32", attention_impl="reference",
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    model = GPT2LMModel(cfg)
    params = model.init(jax.random.key(0), jnp.ones((2, 8), jnp.int32))[
        "params"
    ]
    gs, sink = _guards("strict")
    server = InferenceServer(
        model, params,
        EngineConfig(num_slots=1, prompt_buckets=(4,), max_new_tokens=4),
        queue_depth=16, registry=gs.registry, guards=gs,
    ).start()
    prompt = np.arange(1, 4, dtype=np.int32)
    try:
        first = server.submit(prompt, max_new_tokens=4)
        assert wait_until(lambda: first.done.is_set(), timeout=120)
        assert first.status == "done"

        # sabotage: shrink the resident KV state so the warmed programs see
        # a NEW shape -> guarded retrace. Paged layout (default): drop a
        # page from the [num_pages, page_size, heads, head_dim] pools;
        # dense layout: drop the trailing sequence position (axis 2 of the
        # [slots, 1, cache_len, heads, head_dim] leaves).
        engine = server.engine
        engine._cache = jax.tree.map(
            lambda g: (
                g[:-1] if g.ndim == 4 else g[:, :, :-1] if g.ndim == 5 else g
            ),
            engine._cache,
        )
        second = server.submit(prompt, max_new_tokens=4)
        assert wait_until(lambda: second.done.is_set(), timeout=120)
        assert second.status in ("cancelled", "expired", "error")
        assert gs.recompile_violations >= 1
        assert sink.of("recompile")
    finally:
        server.close(drain=False)
