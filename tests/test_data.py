"""Data-layer tests: tokenizer contract, synthetic task learnability shape,
loader sharding/coverage invariants."""

import numpy as np
import pytest

from pytorch_distributed_training_tpu.comms.mesh import build_mesh
from pytorch_distributed_training_tpu.data import ShardedLoader, load_task_arrays
from pytorch_distributed_training_tpu.data.synthetic import synthetic_pair_task
from pytorch_distributed_training_tpu.data.tokenizer import (
    CLS_ID,
    HashTokenizer,
    SEP_ID,
    encode_pairs,
)
from pytorch_distributed_training_tpu.utils.config import MeshConfig


def test_encode_pairs_contract():
    tok = HashTokenizer(vocab_size=1000)
    out = encode_pairs(
        tok,
        ["The cat sat on the mat.", "a"],
        ["A cat was sitting on a mat.", "b " * 200],  # second pair overflows
        max_length=32,
    )
    assert out["input_ids"].shape == (2, 32)
    assert out["input_ids"][0, 0] == CLS_ID
    row = out["input_ids"][1]
    assert (row[out["attention_mask"][1] == 1] == SEP_ID).sum() == 2  # truncated but well-formed
    # token types flip after first [SEP]
    first_sep = int(np.argmax(out["input_ids"][0] == SEP_ID))
    assert out["token_type_ids"][0, first_sep + 1] == 1
    # determinism across instances
    out2 = encode_pairs(
        HashTokenizer(vocab_size=1000),
        ["The cat sat on the mat.", "a"],
        ["A cat was sitting on a mat.", "b " * 200],
        max_length=32,
    )
    np.testing.assert_array_equal(out["input_ids"], out2["input_ids"])


def test_synthetic_task_shapes_and_balance():
    d = synthetic_pair_task(512, max_length=64, vocab_size=2000)
    assert d["input_ids"].shape == (512, 64)
    assert set(np.unique(d["labels"])) == {0, 1}
    assert 0.3 < d["labels"].mean() < 0.7
    # paraphrase pairs share tokens; unrelated mostly don't
    overlaps = {0: [], 1: []}
    for i in range(100):
        tt, ids, m = d["token_type_ids"][i], d["input_ids"][i], d["attention_mask"][i]
        a = set(ids[(tt == 0) & (m == 1)][1:].tolist())
        b = set(ids[(tt == 1) & (m == 1)][:-1].tolist())
        j = len(a & b) / max(len(a | b), 1)
        overlaps[int(d["labels"][i])].append(j)
    assert np.mean(overlaps[1]) > np.mean(overlaps[0]) + 0.3


def test_load_task_auto_falls_back_offline():
    data, num_labels = load_task_arrays("auto", "train", max_length=32)
    assert num_labels == 2
    assert data["input_ids"].shape[1] == 32


def test_train_loader_covers_epoch_without_ragged_tail(eight_devices):
    mesh = build_mesh(MeshConfig(data=8))
    d = synthetic_pair_task(100, max_length=16, vocab_size=500)
    loader = ShardedLoader(
        d, mesh, global_batch_size=32, grad_accum_steps=2, train=True
    )
    assert loader.steps_per_epoch == 3  # 100 // 32, tail dropped
    seen = []
    for batch in loader.epoch(0):
        assert batch["input_ids"].shape == (2, 16, 16)  # [accum, micro, seq]
        seen.append(np.asarray(batch["labels"]))
    assert len(seen) == 3
    # different epochs shuffle differently
    first_again = next(iter(loader.epoch(1)))
    assert not np.array_equal(np.asarray(first_again["labels"]), seen[0])
    # same epoch is deterministic
    first_repeat = next(iter(loader.epoch(0)))
    np.testing.assert_array_equal(np.asarray(first_repeat["labels"]), seen[0])


def test_eval_loader_sees_every_example_once(eight_devices):
    mesh = build_mesh(MeshConfig(data=8))
    d = synthetic_pair_task(41, max_length=16, vocab_size=500)  # ragged vs 16
    d["row_id"] = np.arange(41).astype(np.int32)
    loader = ShardedLoader(d, mesh, global_batch_size=16, train=False)
    assert loader.steps_per_epoch == 3
    rows, valids = [], []
    for batch in loader.epoch():
        rows.append(np.asarray(batch["row_id"]))
        valids.append(np.asarray(batch["valid"]))
    rows, valids = np.concatenate(rows), np.concatenate(valids)
    assert valids.sum() == 41
    assert sorted(rows[valids == 1].tolist()) == list(range(41))


def test_eval_pad_rows_reuse_last_valid_index(eight_devices):
    """The ragged eval tail pads with the LAST valid row (not row 0 — which
    re-read row 0 up to global_batch-1 times); the valid mask still zeroes
    every pad row out of the metrics."""
    mesh = build_mesh(MeshConfig(data=8))
    d = synthetic_pair_task(41, max_length=16, vocab_size=500)
    d["row_id"] = np.arange(41).astype(np.int32)
    loader = ShardedLoader(d, mesh, global_batch_size=16, train=False)
    *_, last = loader.epoch()
    rows = np.asarray(last["row_id"])
    valid = np.asarray(last["valid"])
    assert (rows[valid == 0] == 40).all()  # pad rows gather row n-1
    assert valid.sum() == 41 % 16  # mask still covers exactly the tail
    # masked metrics stay pad-free: an eval step counting only valid rows
    # sees each example once (the full-coverage test above pins the rest)
    assert (rows[valid == 1] == np.arange(32, 41)).all()


def test_loader_rejects_indivisible_batches(eight_devices):
    mesh = build_mesh(MeshConfig(data=8))
    d = synthetic_pair_task(64, max_length=16, vocab_size=500)
    with pytest.raises(ValueError):
        ShardedLoader(d, mesh, global_batch_size=30, grad_accum_steps=4)
    with pytest.raises(ValueError):  # micro 12 not divisible by dp 8
        ShardedLoader(d, mesh, global_batch_size=24, grad_accum_steps=2)


def test_multihost_slicing_partitions_batch():
    """Simulate 4 hosts: their local slices must tile the global batch."""
    import jax

    d = synthetic_pair_task(64, max_length=8, vocab_size=500)
    d["row_id"] = np.arange(64).astype(np.int32)
    # single-device mesh: placement is irrelevant, slicing is what's tested
    mesh = build_mesh(MeshConfig(data=1), devices=jax.devices()[:1])

    got = []
    for p in range(4):
        loader = ShardedLoader(
            d, mesh, global_batch_size=16, grad_accum_steps=2, train=True,
            process_index=p, process_count=4,
        )
        batch = next(iter(loader.epoch(0)))
        got.append(np.asarray(batch["row_id"]))
    stacked = np.stack(got)  # [4 hosts, accum, local_micro]
    assert stacked.shape == (4, 2, 2)
    all_rows = stacked.transpose(1, 0, 2).reshape(-1)
    assert len(set(all_rows.tolist())) == 16  # disjoint cover of global batch


def test_wordpiece_tokenizer_greedy_longest_match(tmp_path):
    """WordPiece semantics over a tiny vocab: longest-match-first, ##
    continuations, [UNK] for unmatchable words, special-token ids read from
    the vocab (the reference's AutoTokenizer contract, owned in-repo)."""
    from pytorch_distributed_training_tpu.data.tokenizer import (
        WordPieceTokenizer,
    )

    vocab = [
        "[PAD]", "[UNK]", "[CLS]", "[SEP]",
        "un", "##aff", "##able", "##ffable", "aff", "able", "run", "##ning",
    ]
    vp = tmp_path / "vocab.txt"
    vp.write_text("\n".join(vocab) + "\n")
    tok = WordPieceTokenizer(str(vp))

    assert tok.pad_id == 0 and tok.unk_id == 1
    assert tok.cls_id == 2 and tok.sep_id == 3

    ids = {t: i for i, t in enumerate(vocab)}
    # greedy longest-first: "unffable" -> un + ##ffable (not un + ##aff...)
    assert tok.word_ids("unffable") == [ids["un"], ids["##ffable"]]
    # multi-piece continuation
    assert tok.word_ids("unaffable") == [
        ids["un"], ids["##aff"], ids["##able"]
    ]
    assert tok.word_ids("running") == [ids["run"], ids["##ning"]]
    # no decomposition -> single [UNK] for the whole word
    assert tok.word_ids("xyzzy") == [tok.unk_id]
    # whole-text path splits on words/punct
    assert tok.text_ids("running unffable") == [
        ids["run"], ids["##ning"], ids["un"], ids["##ffable"]
    ]


def test_real_data_path_end_to_end_with_fixture_vocab(
    monkeypatch, eight_devices, tmp_path
):
    """VERDICT r1 #2: the REAL-data pipeline exercised offline — a fake hub
    dataset + a fixture WordPiece vocab flow through load_task_arrays'
    hub branch, the C++ bulk encoder, and a full Trainer epoch. The moment
    a real HF cache + vocab.txt exist, the identical code path runs real
    MRPC (see README 'Real data' runbook)."""
    import pytest

    from pytorch_distributed_training_tpu.native import load_wordpiece_lib

    if load_wordpiece_lib() is None:
        pytest.skip("no C++ toolchain")

    vocab = [
        "[PAD]", "[UNK]", "[CLS]", "[SEP]",
        "the", "cat", "dog", "sat", "on", "a", "mat", "ran", "fast", ".",
    ]
    vp = tmp_path / "vocab.txt"
    vp.write_text("\n".join(vocab) + "\n")
    vocab_path = str(vp)

    class FakeSplit(dict):
        pass

    n = 64
    rng = np.random.default_rng(0)
    words = ["the", "cat", "dog", "sat", "on", "a", "mat", "ran", "fast"]
    rows_a = [" ".join(rng.choice(words, 6)) + " ." for _ in range(n)]
    rows_b = [" ".join(rng.choice(words, 5)) + " ." for _ in range(n)]
    labels = rng.integers(0, 2, n).astype(int).tolist()
    fake = FakeSplit(sentence1=rows_a, sentence2=rows_b, label=labels)

    import datasets

    monkeypatch.setattr(
        datasets, "load_dataset", lambda *a, **kw: fake
    )

    from pytorch_distributed_training_tpu.data.glue import load_task_arrays
    from pytorch_distributed_training_tpu.data.tokenizer import (
        WordPieceTokenizer,
        encode_pairs,
    )

    arrays, num_labels = load_task_arrays(
        "mrpc", "train", max_length=32, vocab_path=vocab_path
    )
    assert num_labels == 2
    # byte-identical to the Python encoder over the same fixture vocab
    ref = encode_pairs(
        WordPieceTokenizer(vocab_path), rows_a, rows_b, max_length=32
    )
    for k in ("input_ids", "token_type_ids", "attention_mask"):
        np.testing.assert_array_equal(arrays[k], ref[k], err_msg=k)

    # ...and a full Trainer epoch runs on it (the one-command runbook path)
    from pytorch_distributed_training_tpu.parallel import ShardingPolicy
    from pytorch_distributed_training_tpu.train.loop import Trainer
    from pytorch_distributed_training_tpu.utils.config import (
        MeshConfig,
        TrainConfig,
        model_preset,
    )

    mcfg = model_preset("tiny", compute_dtype="float32", vocab_size=32)
    tcfg = TrainConfig(
        num_epochs=1, global_batch_size=16, micro_batch_size=8,
        eval_batch_size=16, log_every=0, bf16=False, vocab_path=vocab_path,
        warmup_steps=2,
    )
    trainer = Trainer(
        mcfg, tcfg, MeshConfig(data=8), ShardingPolicy(), task="mrpc"
    )
    history = trainer.run()
    assert len(history) == 1 and np.isfinite(history[-1]["train_loss"])


def test_single_sentence_encode():
    # SST-2-style single-sentence rows: texts_b=None -> [CLS] a [SEP], all
    # token types 0, exactly one [SEP]
    tok = HashTokenizer(vocab_size=1000)
    out = encode_pairs(tok, ["The movie was great.", "terrible"], None,
                       max_length=16)
    for i in range(2):
        row = out["input_ids"][i]
        live = out["attention_mask"][i] == 1
        assert row[0] == CLS_ID
        assert (row[live] == SEP_ID).sum() == 1
        assert (out["token_type_ids"][i] == 0).all()


def test_eval_splits_table():
    from pytorch_distributed_training_tpu.data.glue import eval_splits

    assert eval_splits("mrpc") == [("", "validation")]
    assert eval_splits("sst2") == [("", "validation")]
    assert eval_splits("mnli") == [
        ("matched", "validation"),
        ("mismatched", "validation_mismatched"),
    ]


def test_new_task_rows_offline_fallback():
    # zero-egress image: every hub task falls back to the synthetic pair
    # task, preserving the task's num_labels
    for task, n_labels in [("sst2", 2), ("qnli", 2), ("mnli", 3)]:
        data, num_labels = load_task_arrays(
            task, "validation", max_length=32, synthetic_sizes=(64, 32)
        )
        assert num_labels == n_labels
        assert data["input_ids"].shape == (32, 32)
        assert int(data["labels"].max()) <= n_labels - 1
    # mismatched is a DIFFERENT sample than matched
    matched, _ = load_task_arrays(
        "mnli", "validation", max_length=32, synthetic_sizes=(64, 32)
    )
    mismatched, _ = load_task_arrays(
        "mnli", "validation_mismatched", max_length=32, synthetic_sizes=(64, 32)
    )
    assert not np.array_equal(matched["input_ids"], mismatched["input_ids"])


def test_mismatched_split_rejected_for_non_mnli():
    with pytest.raises(ValueError, match="mismatched"):
        load_task_arrays("mrpc", "validation_mismatched", max_length=32)
