"""GPipe pipeline (parallel/pipeline.py): numerical parity with the
sequential trunk, gradient parity, and actual stage overlap."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.comms.mesh import build_mesh
from pytorch_distributed_training_tpu.models import BertForSequenceClassification
from pytorch_distributed_training_tpu.ops.attention import make_attention_bias
from pytorch_distributed_training_tpu.parallel.pipeline import (
    gpipe_apply,
    gpipe_trunk_fn,
)
from pytorch_distributed_training_tpu.utils.config import (
    MeshConfig,
    model_preset,
)


@pytest.fixture(scope="module")
def setup(eight_devices):
    cfg = model_preset(
        "tiny", compute_dtype="float32", num_layers=4,
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    scfg = dataclasses.replace(cfg, scan_layers=True)
    model = BertForSequenceClassification(scfg)
    ids = jnp.ones((4, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    stacked = params["bert"]["layers_scan"]["layer"]
    rng = np.random.default_rng(0)
    n_micro, mb, seq, h = 4, 2, 16, cfg.hidden_size
    xs = jnp.asarray(rng.normal(size=(n_micro, mb, seq, h)), jnp.float32)
    mask = jnp.asarray(rng.integers(0, 2, (n_micro, mb, seq)), jnp.int32)
    mask = mask.at[:, :, 0].set(1)
    biases = jax.vmap(make_attention_bias)(mask)
    return cfg, stacked, xs, biases


def _sequential(layer_fn, stacked, xs, biases):
    def one(x, b):
        def body(h, lp):
            return layer_fn(lp, h, b), None

        out, _ = jax.lax.scan(body, x, stacked)
        return out

    return jax.vmap(one)(xs, biases)  # over microbatches


def test_gpipe_matches_sequential(setup):
    cfg, stacked, xs, biases = setup
    mesh = build_mesh(MeshConfig(data=4, stage=2))
    layer_fn = gpipe_trunk_fn(cfg)
    ref = _sequential(layer_fn, stacked, xs, biases)
    out = gpipe_apply(mesh, layer_fn, stacked, xs, biases)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
    )


def test_gpipe_matches_sequential_stage4(setup):
    cfg, stacked, xs, biases = setup
    mesh = build_mesh(MeshConfig(data=2, stage=4))
    layer_fn = gpipe_trunk_fn(cfg)
    ref = _sequential(layer_fn, stacked, xs, biases)
    out = gpipe_apply(mesh, layer_fn, stacked, xs, biases)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
    )


def test_gpipe_gradients_match(setup):
    """jax.grad THROUGH the pipeline (reverse ppermute = backward
    schedule) equals the sequential trunk's gradients."""
    cfg, stacked, xs, biases = setup
    mesh = build_mesh(MeshConfig(data=4, stage=2))
    layer_fn = gpipe_trunk_fn(cfg)
    w = jnp.asarray(
        np.random.default_rng(3).normal(size=xs.shape), jnp.float32
    )

    def loss_pipe(p, x):
        return jnp.sum(gpipe_apply(mesh, layer_fn, p, x, biases) * w)

    def loss_seq(p, x):
        return jnp.sum(_sequential(layer_fn, p, x, biases) * w)

    gp_p, gp_x = jax.grad(loss_pipe, argnums=(0, 1))(stacked, xs)
    gs_p, gs_x = jax.grad(loss_seq, argnums=(0, 1))(stacked, xs)
    np.testing.assert_allclose(
        np.asarray(gp_x), np.asarray(gs_x), atol=2e-4, rtol=2e-4
    )
    for a, b in zip(jax.tree.leaves(gp_p), jax.tree.leaves(gs_p)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4
        )


def test_gpipe_rejects_bad_shapes(setup):
    cfg, stacked, xs, biases = setup
    mesh = build_mesh(MeshConfig(data=4, stage=2))
    layer_fn = gpipe_trunk_fn(cfg)
    with pytest.raises(ValueError, match="n_micro"):
        gpipe_apply(mesh, layer_fn, stacked, xs[:1], biases[:1])
    bad = jax.tree.map(lambda a: a[:3], stacked)  # 3 layers, 2 stages
    with pytest.raises(ValueError, match="divisible"):
        gpipe_apply(mesh, layer_fn, bad, xs, biases)
