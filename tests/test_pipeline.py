"""GPipe pipeline (parallel/pipeline.py): numerical parity with the
sequential trunk, gradient parity, and actual stage overlap."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.comms.mesh import build_mesh
from pytorch_distributed_training_tpu.models import BertForSequenceClassification
from pytorch_distributed_training_tpu.ops.attention import make_attention_bias
from pytorch_distributed_training_tpu.parallel.pipeline import (
    gpipe_apply,
    gpipe_trunk_fn,
)
from pytorch_distributed_training_tpu.utils.config import (
    MeshConfig,
    model_preset,
)


@pytest.fixture(scope="module")
def setup(eight_devices):
    cfg = model_preset(
        "tiny", compute_dtype="float32", num_layers=4,
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    scfg = dataclasses.replace(cfg, scan_layers=True)
    model = BertForSequenceClassification(scfg)
    ids = jnp.ones((4, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    stacked = params["bert"]["layers_scan"]["layer"]
    rng = np.random.default_rng(0)
    n_micro, mb, seq, h = 4, 2, 16, cfg.hidden_size
    xs = jnp.asarray(rng.normal(size=(n_micro, mb, seq, h)), jnp.float32)
    mask = jnp.asarray(rng.integers(0, 2, (n_micro, mb, seq)), jnp.int32)
    mask = mask.at[:, :, 0].set(1)
    biases = jax.vmap(make_attention_bias)(mask)
    return cfg, stacked, xs, biases


def _sequential(layer_fn, stacked, xs, biases):
    def one(x, b):
        def body(h, lp):
            return layer_fn(lp, h, b), None

        out, _ = jax.lax.scan(body, x, stacked)
        return out

    return jax.vmap(one)(xs, biases)  # over microbatches


def test_gpipe_matches_sequential(setup):
    cfg, stacked, xs, biases = setup
    mesh = build_mesh(MeshConfig(data=4, stage=2))
    layer_fn = gpipe_trunk_fn(cfg)
    ref = _sequential(layer_fn, stacked, xs, biases)
    out = gpipe_apply(mesh, layer_fn, stacked, xs, biases)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
    )


def test_gpipe_matches_sequential_stage4(setup):
    cfg, stacked, xs, biases = setup
    mesh = build_mesh(MeshConfig(data=2, stage=4))
    layer_fn = gpipe_trunk_fn(cfg)
    ref = _sequential(layer_fn, stacked, xs, biases)
    out = gpipe_apply(mesh, layer_fn, stacked, xs, biases)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
    )


@pytest.mark.slow
def test_gpipe_gradients_match(setup):
    """jax.grad THROUGH the pipeline (reverse ppermute = backward
    schedule) equals the sequential trunk's gradients."""
    cfg, stacked, xs, biases = setup
    mesh = build_mesh(MeshConfig(data=4, stage=2))
    layer_fn = gpipe_trunk_fn(cfg)
    w = jnp.asarray(
        np.random.default_rng(3).normal(size=xs.shape), jnp.float32
    )

    def loss_pipe(p, x):
        return jnp.sum(gpipe_apply(mesh, layer_fn, p, x, biases) * w)

    def loss_seq(p, x):
        return jnp.sum(_sequential(layer_fn, p, x, biases) * w)

    gp_p, gp_x = jax.grad(loss_pipe, argnums=(0, 1))(stacked, xs)
    gs_p, gs_x = jax.grad(loss_seq, argnums=(0, 1))(stacked, xs)
    np.testing.assert_allclose(
        np.asarray(gp_x), np.asarray(gs_x), atol=2e-4, rtol=2e-4
    )
    for a, b in zip(jax.tree.leaves(gp_p), jax.tree.leaves(gs_p)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4
        )


def test_gpipe_rejects_bad_shapes(setup):
    cfg, stacked, xs, biases = setup
    mesh = build_mesh(MeshConfig(data=4, stage=2))
    layer_fn = gpipe_trunk_fn(cfg)
    with pytest.raises(ValueError, match="n_micro"):
        gpipe_apply(mesh, layer_fn, stacked, xs[:1], biases[:1])
    bad = jax.tree.map(lambda a: a[:3], stacked)  # 3 layers, 2 stages
    with pytest.raises(ValueError, match="divisible"):
        gpipe_apply(mesh, layer_fn, bad, xs, biases)


# ------------------------------------------ trainable pipeline (classifier)


@pytest.fixture(scope="module")
def clf_setup(eight_devices):
    import numpy as np

    from pytorch_distributed_training_tpu.parallel.pipeline import (
        GPipeClassifier,
    )

    cfg = model_preset(
        "tiny", compute_dtype="float32", num_layers=4,
        hidden_dropout=0.0, attention_dropout=0.0, scan_layers=True,
    )
    mesh = build_mesh(MeshConfig(data=2, stage=4))
    model = GPipeClassifier(cfg, mesh, n_micro=4)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 16)), jnp.int32)
    mask = jnp.asarray(rng.integers(0, 2, (8, 16)), jnp.int32).at[:, 0].set(1)
    params = model.init(jax.random.key(0), ids, mask)["params"]
    return cfg, mesh, model, params, ids, mask


def test_gpipe_classifier_matches_serial(clf_setup):
    """Same params, deterministic: pipelined logits == serial scan model."""
    cfg, mesh, model, params, ids, mask = clf_setup
    ref = BertForSequenceClassification(cfg).apply(
        {"params": params}, ids, mask, deterministic=True
    )
    out = model.apply({"params": params}, ids, mask, deterministic=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
    )


@pytest.mark.slow
def test_gpipe_classifier_dropout_grads(clf_setup):
    """Training mode with dropout on: per-(tick, stage, layer) key streaming
    produces finite nonzero grads and actually perturbs the forward."""
    cfg, mesh, model, params, ids, mask = clf_setup
    dcfg = dataclasses.replace(
        cfg, hidden_dropout=0.1, attention_dropout=0.1
    )
    from pytorch_distributed_training_tpu.parallel.pipeline import (
        GPipeClassifier,
    )

    dmodel = GPipeClassifier(dcfg, mesh, n_micro=4)

    def loss(p, rng):
        logits = dmodel.apply(
            {"params": p}, ids, mask, deterministic=False,
            rngs={"dropout": rng},
        )
        return jnp.sum(logits.astype(jnp.float32) ** 2)

    g = jax.grad(loss)(params, jax.random.key(1))
    gn = float(sum(jnp.sum(jnp.abs(l)) for l in jax.tree.leaves(g)))
    assert np.isfinite(gn) and gn > 0.0
    det = model.apply({"params": params}, ids, mask, deterministic=True)
    drop = dmodel.apply(
        {"params": params}, ids, mask, deterministic=False,
        rngs={"dropout": jax.random.key(1)},
    )
    assert not np.allclose(np.asarray(drop), np.asarray(det))


def test_gpipe_classifier_requires_divisible_batch(clf_setup):
    cfg, mesh, model, params, ids, mask = clf_setup
    with pytest.raises(ValueError, match="divisible"):
        model.apply({"params": params}, ids[:6], mask[:6])


def test_gpipe_classifier_with_registered_kernel_mesh(clf_setup):
    """Regression: with a kernel-dispatch mesh registered (as Trainer does)
    the pipelined layers run INSIDE gpipe_apply's shard_map body — kernel
    dispatch must go direct there, not open a nested shard_map over the
    same mesh (trace-time 'context mesh Manual' crash)."""
    from pytorch_distributed_training_tpu.ops import dispatch
    from pytorch_distributed_training_tpu.ops.flash_attention import (
        tpu_interpret_mode,
    )

    cfg, mesh, model, params, ids, mask = clf_setup
    ref = model.apply({"params": params}, ids, mask, deterministic=True)
    with tpu_interpret_mode(), dispatch.use_kernel_mesh(mesh):
        out = model.apply({"params": params}, ids, mask, deterministic=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=1e-5, rtol=1e-5
    )


@pytest.mark.slow
def test_train_mp_pipeline_e2e(eight_devices, tmp_path):
    """`train_mp --mp-mode pipeline` trains end-to-end on the 8-device CPU
    mesh with dropout on — the reference ConcatBert split as *training*
    code (reference test_model_parallelism.py:40-89), scheduled.

    eval-batch 12 deliberately VIOLATES the pipeline's stream constraint
    (12/2 microbatch rows don't divide data×fsdp=4): evaluate() runs
    through the serial trunk (GPipeClassifier.serial_apply), so only the
    train micro-batch is bound to the schedule (VERDICT r3 weak-#5)."""
    from pytorch_distributed_training_tpu.cli import train_mp

    history = train_mp.main([
        "--mp-mode", "pipeline",
        "--model", "tiny",
        "--task", "synthetic",
        "--mesh-data", "4", "--mesh-stage", "2",
        "--pipeline-microbatches", "2",
        "--num-epochs", "1",
        "--global-batch-size", "16",
        "--micro-batch-size", "8",
        "--eval-batch-size", "12",
        "--train-size", "32", "--eval-size", "12",
        "--max-seq-length", "16",
        "--no-bf16",
    ])
    assert len(history) == 1
    assert np.isfinite(history[0]["train_loss"])
    assert history[0]["accuracy"] >= 0.0


def test_gpipe_dropout_streams_distinct_per_data_shard(eight_devices):
    """With the microbatch stream data-sharded (stream_spec), every data
    shard must draw a DISTINCT dropout stream — the same per-shard key
    contract as the ops/dispatch shard_map wrappers. A layer_fn that
    returns raw PRNG bits exposes the masks directly: identical bits on
    two shards means correlated dropout."""
    from jax.sharding import PartitionSpec as P

    mesh = build_mesh(MeshConfig(data=4, stage=2))
    n_micro, mb, h = 2, 4, 8  # mb 4 -> 1 row per data shard
    xs = jnp.zeros((n_micro, mb, h), jnp.float32)
    biases = jnp.zeros((n_micro, mb), jnp.float32)
    stacked = {"w": jnp.zeros((2, 1), jnp.float32)}  # 2 layers, 1 per stage

    def layer_fn(lp, x, b, rng):
        bits = jax.random.bits(rng, (x.shape[0], x.shape[1]))
        return x + bits.astype(jnp.float32)

    key = jax.random.key(7)
    kd = jnp.stack(
        [jax.random.key_data(jax.random.fold_in(key, i)) for i in range(n_micro)]
    )
    out = gpipe_apply(
        mesh, layer_fn, stacked, xs, biases,
        stream_spec=P(None, ("data",)),
        mb_keys=kd, rng_impl=jax.random.key_impl(key),
    )
    out = np.asarray(jax.device_get(out))
    # each batch row lives on its own data shard: every pair of rows must
    # carry different PRNG bits (pre-fix they were byte-identical)
    for i in range(mb):
        for j in range(i + 1, mb):
            assert not np.array_equal(out[:, i], out[:, j]), (i, j)


# ------------------------------------------------------------ 1F1B schedule


@pytest.mark.slow
def test_one_f_one_b_matches_sequential_grads(setup):
    """The 1F1B engine (interleaved F/B ticks, stage-bounded stash,
    in-schedule head vjp) must produce the SAME loss/gradients as the
    plain sequential trunk + head under jax.grad — at dropout 0 the two
    schedules are the same math in a different order (VERDICT r3 #6)."""
    import optax

    from pytorch_distributed_training_tpu.parallel.pipeline import (
        one_f_one_b_grads,
    )

    cfg, stacked, xs, biases = setup
    mesh = build_mesh(MeshConfig(data=4, stage=2))
    layer_fn = gpipe_trunk_fn(cfg)
    n_micro, mb = xs.shape[0], xs.shape[1]
    rng = np.random.default_rng(7)
    hp = {
        "w": jnp.asarray(rng.normal(size=(cfg.hidden_size, 2)) * 0.1,
                         jnp.float32),
        "b": jnp.zeros((2,), jnp.float32),
    }
    labels = jnp.asarray(rng.integers(0, 2, (n_micro, mb)), jnp.int32)

    def head_fn(hp, y, lab):
        logits = y[:, 0] @ hp["w"] + hp["b"]  # CLS pool -> linear
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, lab)
        return ce.mean() / n_micro

    loss, tg, hg, dxs = one_f_one_b_grads(
        mesh, layer_fn, head_fn, stacked, hp, xs, biases, labels
    )

    def ref_loss(p, h, x):
        out = _sequential(layer_fn, p, x, biases)
        return jax.vmap(lambda y, l: head_fn(h, y, l))(out, labels).sum()

    rl, (gp, ghp, gx) = jax.value_and_grad(ref_loss, argnums=(0, 1, 2))(
        stacked, hp, xs
    )
    np.testing.assert_allclose(float(loss), float(rl), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(dxs), np.asarray(gx), atol=2e-4, rtol=2e-4
    )
    for a, b in zip(jax.tree.leaves(hg), jax.tree.leaves(ghp)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4
        )
    for a, b in zip(jax.tree.leaves(tg), jax.tree.leaves(gp)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4
        )


@pytest.mark.slow
def test_one_f_one_b_stage4(setup):
    """Same parity at 4 stages (deeper fill/drain, wrap-around stash)."""
    import optax

    from pytorch_distributed_training_tpu.parallel.pipeline import (
        one_f_one_b_grads,
    )

    cfg, stacked, xs, biases = setup
    mesh = build_mesh(MeshConfig(data=2, stage=4))
    layer_fn = gpipe_trunk_fn(cfg)
    n_micro, mb = xs.shape[0], xs.shape[1]
    rng = np.random.default_rng(8)
    hp = {"w": jnp.asarray(rng.normal(size=(cfg.hidden_size, 2)) * 0.1,
                           jnp.float32)}
    labels = jnp.asarray(rng.integers(0, 2, (n_micro, mb)), jnp.int32)

    def head_fn(hp, y, lab):
        logits = y[:, 0] @ hp["w"]
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, lab)
        return ce.mean() / n_micro

    loss, tg, hg, dxs = one_f_one_b_grads(
        mesh, layer_fn, head_fn, stacked, hp, xs, biases, labels
    )

    def ref_loss(p, h, x):
        out = _sequential(layer_fn, p, x, biases)
        return jax.vmap(lambda y, l: head_fn(h, y, l))(out, labels).sum()

    rl, (gp, gh, gx) = jax.value_and_grad(ref_loss, argnums=(0, 1, 2))(
        stacked, hp, xs
    )
    np.testing.assert_allclose(float(loss), float(rl), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(dxs), np.asarray(gx), atol=2e-4, rtol=2e-4
    )
    for a, b in zip(jax.tree.leaves(tg), jax.tree.leaves(gp)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4
        )
    for a, b in zip(jax.tree.leaves(hg), jax.tree.leaves(gh)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4
        )


@pytest.mark.slow
def test_train_mp_1f1b_e2e(eight_devices):
    """`train_mp --mp-mode 1f1b` trains end-to-end (dropout on, accum 2)
    and reports the schedule's bubble fraction; eval rides the serial
    trunk as usual."""
    from pytorch_distributed_training_tpu.cli import train_mp

    history = train_mp.main([
        "--mp-mode", "1f1b",
        "--model", "tiny",
        "--task", "synthetic",
        "--mesh-data", "4", "--mesh-stage", "2",
        "--pipeline-microbatches", "2",
        "--num-epochs", "1",
        "--global-batch-size", "16",
        "--micro-batch-size", "8",
        "--eval-batch-size", "12",
        "--train-size", "32", "--eval-size", "12",
        "--max-seq-length", "16",
        "--no-bf16",
    ])
    assert len(history) == 1
    assert np.isfinite(history[0]["train_loss"])
    assert history[0]["accuracy"] >= 0.0


@pytest.mark.slow
def test_1f1b_step_matches_standard_step_at_dropout0(eight_devices):
    """One 1F1B train step == one standard (serial-trunk) train step on the
    same params/batch at dropout 0 — loss and updated params."""
    import jax

    from pytorch_distributed_training_tpu.models import (
        BertForSequenceClassification,
    )
    from pytorch_distributed_training_tpu.parallel import (
        ShardingPolicy,
        state_shardings,
    )
    from pytorch_distributed_training_tpu.parallel.pipeline import (
        make_1f1b_train_step,
    )
    from pytorch_distributed_training_tpu.parallel.sharding import shard_state
    from pytorch_distributed_training_tpu.train import (
        adamw_with_schedule,
        create_train_state,
        make_train_step,
    )
    from pytorch_distributed_training_tpu.utils.config import TrainConfig

    cfg = model_preset(
        "tiny", compute_dtype="float32", num_layers=4,
        hidden_dropout=0.0, attention_dropout=0.0, scan_layers=True,
    )
    model = BertForSequenceClassification(cfg)
    tx, _ = adamw_with_schedule(TrainConfig(), 100)
    example = {
        "input_ids": jnp.ones((2, 16), jnp.int32),
        "attention_mask": jnp.ones((2, 16), jnp.int32),
        "token_type_ids": jnp.zeros((2, 16), jnp.int32),
    }
    rng = np.random.default_rng(5)
    batch = {
        "input_ids": rng.integers(0, cfg.vocab_size, (2, 8, 16)).astype(np.int32),
        "attention_mask": np.ones((2, 8, 16), np.int32),
        "token_type_ids": np.zeros((2, 8, 16), np.int32),
        "labels": rng.integers(0, 2, (2, 8)).astype(np.int32),
    }

    from pytorch_distributed_training_tpu.comms.ingest import make_global_batch
    from pytorch_distributed_training_tpu.comms.mesh import TRAIN_BATCH_PSPEC

    results = {}
    for name, mesh_cfg, policy, use_1f1b in [
        ("std", MeshConfig(data=8), ShardingPolicy(), False),
        ("1f1b", MeshConfig(data=2, stage=4), ShardingPolicy(stage=True),
         True),
    ]:
        mesh = build_mesh(mesh_cfg)
        s = create_train_state(model, tx, jax.random.key(0), example)
        shardings = state_shardings(s, policy, mesh)
        s = shard_state(s, shardings)
        placed = make_global_batch(
            mesh, jax.tree.map(np.asarray, batch), pspec=TRAIN_BATCH_PSPEC
        )
        if use_1f1b:
            step = make_1f1b_train_step(
                cfg, mesh, shardings, n_micro=4, grad_accum_steps=2,
            )
        else:
            step = make_train_step(
                grad_accum_steps=2, mesh=mesh, state_shardings=shardings,
                log_grad_norm=False,
            )
        s2, m = step(s, placed)
        results[name] = (
            float(m["loss"]),
            np.concatenate(
                [np.ravel(jax.device_get(x)) for x in jax.tree.leaves(s2.params)]
            ),
        )
        if use_1f1b:
            # 4 microbatches, 4 stages: bubble = 6/10
            np.testing.assert_allclose(float(m["pipeline_bubble"]), 0.6)
    np.testing.assert_allclose(
        results["std"][0], results["1f1b"][0], rtol=2e-5
    )
    np.testing.assert_allclose(
        results["std"][1], results["1f1b"][1], atol=3e-5
    )


def test_pipeline_rejects_unsupported_configs(eight_devices):
    """Clear ValueErrors for the combos the pipeline trunks cannot run
    (1F1B needs the stacked layer dim; the delayed-GRADIENT sink channel
    is not threaded through the schedules) — instead of deep
    flax/KeyError failures."""
    from pytorch_distributed_training_tpu.parallel.pipeline import (
        GPipeClassifier,
        make_1f1b_train_step,
    )

    mesh = build_mesh(MeshConfig(data=4, stage=2))
    with pytest.raises(ValueError, match="scan_layers"):
        make_1f1b_train_step(
            model_preset("tiny"), mesh, None, n_micro=2, grad_accum_steps=1
        )
    dgcfg = model_preset(
        "tiny", scan_layers=True, matmul_impl="int8_full",
        quant_delayed=True, quant_delayed_grads=True,
    )
    with pytest.raises(ValueError, match="quant_delayed_grads"):
        GPipeClassifier(dgcfg, mesh, n_micro=2)
    with pytest.raises(ValueError, match="quant_delayed_grads"):
        make_1f1b_train_step(dgcfg, mesh, None, n_micro=2, grad_accum_steps=1)


@pytest.mark.slow
def test_1f1b_memory_scales_with_stages_not_microbatches(eight_devices):
    """Compiled-artifact evidence for the 1F1B memory claim (single-chip
    hardware cannot wall-clock a pipeline, so assert over XLA's buffer
    assignment instead): holding the pipeline-microbatch SIZE fixed and
    raising the count, GPipe's temp allocation grows by the per-tick
    layer-residual stash (jax.grad keeps every microbatch's activations),
    while 1F1B grows only by the unavoidable O(n_micro) stream buffers
    (inputs/outputs/cotangents) — its residual stash is the [2*stages]
    circular buffer. Collective counts stay CONSTANT in n_micro for both
    (the schedules are rolled lax.scans, one ppermute per hop in the
    body) — the schedule adds ticks, not program size."""
    from pytorch_distributed_training_tpu.parallel import (
        ShardingPolicy,
        state_shardings,
    )
    from pytorch_distributed_training_tpu.parallel.pipeline import (
        GPipeClassifier,
        make_1f1b_train_step,
    )
    from pytorch_distributed_training_tpu.parallel.sharding import shard_state
    from pytorch_distributed_training_tpu.train import (
        adamw_with_schedule,
        create_train_state,
        make_train_step,
    )
    from pytorch_distributed_training_tpu.utils.config import TrainConfig

    cfg = model_preset(
        "tiny", compute_dtype="float32", num_layers=4,
        hidden_dropout=0.0, attention_dropout=0.0, scan_layers=True,
    )
    mesh = build_mesh(MeshConfig(data=4, stage=2))
    tx, _ = adamw_with_schedule(TrainConfig(), 100)
    chunk = 8  # rows per pipeline microbatch, held FIXED across the sweep

    def stats_for(n_micro):
        rows = chunk * n_micro
        ex = {
            "input_ids": jnp.ones((rows, 16), jnp.int32),
            "attention_mask": jnp.ones((rows, 16), jnp.int32),
            "token_type_ids": jnp.zeros((rows, 16), jnp.int32),
        }
        batch = {
            "input_ids": jnp.ones((2, rows, 16), jnp.int32),
            "attention_mask": jnp.ones((2, rows, 16), jnp.int32),
            "token_type_ids": jnp.zeros((2, rows, 16), jnp.int32),
            "labels": jnp.zeros((2, rows), jnp.int32),
        }
        out = {}
        gp = GPipeClassifier(cfg, mesh, n_micro=n_micro)
        s = create_train_state(gp, tx, jax.random.key(0), ex)
        sh = state_shardings(s, ShardingPolicy(stage=True), mesh)
        s = shard_state(s, sh)
        step = make_train_step(
            grad_accum_steps=2, mesh=mesh, state_shardings=sh,
            log_grad_norm=False,
        )
        c = step.lower(s, batch).compile()
        out["gpipe"] = (
            c.memory_analysis().temp_size_in_bytes,
            c.as_text().count("collective-permute"),
        )
        # GPipeClassifier.init delegates to the serial flax model, so the
        # same state/shardings serve the 1F1B step (it never reads
        # state.apply_fn — the schedule owns its modules)
        fstep = make_1f1b_train_step(
            cfg, mesh, sh, n_micro=n_micro, grad_accum_steps=2
        )
        c = fstep.lower(s, batch).compile()
        out["1f1b"] = (
            c.memory_analysis().temp_size_in_bytes,
            c.as_text().count("collective-permute"),
        )
        return out

    r4, r8 = stats_for(4), stats_for(8)
    gpipe_slope = (r8["gpipe"][0] - r4["gpipe"][0]) / 4  # bytes per added mb
    f1b_slope = (r8["1f1b"][0] - r4["1f1b"][0]) / 4
    # measured on this image: ~774k vs ~51k per added microbatch (15x);
    # assert the structural gap with wide margins, not the exact bytes
    assert gpipe_slope > 0, (r4, r8)
    assert f1b_slope < gpipe_slope / 5, (
        f"1F1B temp memory slope {f1b_slope/1e3:.1f}k/microbatch not "
        f"clearly below GPipe's {gpipe_slope/1e3:.1f}k/microbatch"
    )
    # program size (and collective count) independent of the tick count
    assert r4["gpipe"][1] == r8["gpipe"][1] > 0
    assert r4["1f1b"][1] == r8["1f1b"][1] > 0


# ------------------------------------- delayed int8 through the schedules


@pytest.fixture(scope="module")
def quant_setup(eight_devices):
    """tiny int8_full + delayed-scaling scan model, with the trunk amaxes
    CALIBRATED by one sequential chunk pass (zeros-init amaxes would make
    every path emit ~zero activations — deterministic but meaningless)."""
    qcfg = model_preset(
        "tiny", compute_dtype="float32", num_layers=4,
        hidden_dropout=0.0, attention_dropout=0.0, scan_layers=True,
        matmul_impl="int8_full", quant_delayed=True,
    )
    model = BertForSequenceClassification(qcfg)
    ids = jnp.ones((4, 16), jnp.int32)
    v = model.init(jax.random.key(0), ids)
    stacked = v["params"]["bert"]["layers_scan"]["layer"]
    rng = np.random.default_rng(0)
    n_micro, mb, seq = 4, 2, 16
    xs = jnp.asarray(
        rng.normal(size=(n_micro, mb, seq, qcfg.hidden_size)), jnp.float32
    )
    mask = jnp.asarray(rng.integers(0, 2, (n_micro, mb, seq)), jnp.int32)
    mask = mask.at[:, :, 0].set(1)
    biases = jax.vmap(make_attention_bias)(mask)
    layer_fn = gpipe_trunk_fn(qcfg, with_quant=True)

    def seq_chunk(x, b, q):
        """One microbatch through all layers, carrying per-layer amaxes —
        the sequential reference for the schedules' delayed semantics."""

        def body(h, lp_q):
            lp, ql = lp_q
            return layer_fn(lp, h, b, ql)

        return jax.lax.scan(body, x, (stacked, q))

    q_init = v["quant"]["bert"]["layers_scan"]["layer"]
    _, q0 = seq_chunk(xs[0], biases[0], q_init)  # calibration pass
    return qcfg, model, stacked, q0, xs, biases, layer_fn, seq_chunk


@pytest.mark.slow
@pytest.mark.parametrize("remat", [False, True])
def test_gpipe_delayed_quant_matches_chunked_sequential(quant_setup, remat):
    """GPipe with the quant carry == running the chunks sequentially with
    the same per-microbatch delayed amax updates: identical activations
    AND identical carried-out amaxes (replicated stream — per-site update
    order is microbatch order on both paths). ``remat`` wraps the
    tuple-returning layer_fn in jax.checkpoint — the --remat × quant
    combination must not disturb either output."""
    qcfg, _, stacked, q0, xs, biases, layer_fn, seq_chunk = quant_setup
    if remat:
        rcfg = dataclasses.replace(qcfg, remat=True)
        layer_fn = gpipe_trunk_fn(rcfg, with_quant=True)
    mesh = build_mesh(MeshConfig(data=4, stage=2))
    out, q_new = gpipe_apply(
        mesh, layer_fn, stacked, xs, biases, stacked_quant=q0
    )

    outs, q = [], q0
    for m in range(xs.shape[0]):
        o, q = seq_chunk(xs[m], biases[m], q)
        outs.append(np.asarray(o))
    np.testing.assert_allclose(
        np.asarray(out), np.stack(outs), atol=2e-5, rtol=2e-5
    )
    for a, b in zip(jax.tree.leaves(q_new), jax.tree.leaves(q)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6
        )


@pytest.mark.slow
def test_one_f_one_b_delayed_quant_matches_sequential(quant_setup):
    """1F1B with the quant stash: loss/grads/cotangents AND final amaxes
    match the sequential reference that carries the same delayed updates.
    The stash is what makes this exact — the backward tick re-quantizes
    with the scales its forward actually used, not the advanced carry."""
    import optax

    from pytorch_distributed_training_tpu.parallel.pipeline import (
        one_f_one_b_grads,
    )

    qcfg, _, stacked, q0, xs, biases, layer_fn, seq_chunk = quant_setup
    mesh = build_mesh(MeshConfig(data=4, stage=2))
    n_micro, mb = xs.shape[0], xs.shape[1]
    rng = np.random.default_rng(7)
    hp = {
        "w": jnp.asarray(rng.normal(size=(qcfg.hidden_size, 2)) * 0.1,
                         jnp.float32),
        "b": jnp.zeros((2,), jnp.float32),
    }
    labels = jnp.asarray(rng.integers(0, 2, (n_micro, mb)), jnp.int32)

    def head_fn(hp, y, lab):
        logits = y[:, 0] @ hp["w"] + hp["b"]
        ce = optax.softmax_cross_entropy_with_integer_labels(logits, lab)
        return ce.mean() / n_micro

    loss, tg, hg, dxs, q_new = one_f_one_b_grads(
        mesh, layer_fn, head_fn, stacked, hp, xs, biases, labels,
        stacked_quant=q0,
    )

    def ref_loss(p, h, x):
        q, total = q0, 0.0
        for m in range(n_micro):

            def body(hh, lp_q, _b=biases[m]):
                lp, ql = lp_q
                return layer_fn(lp, hh, _b, ql)

            y, q = jax.lax.scan(body, x[m], (p, q))
            total = total + head_fn(h, y, labels[m])
        return total, q

    (rl, rq), (gp, gh, gx) = jax.value_and_grad(
        ref_loss, argnums=(0, 1, 2), has_aux=True
    )(stacked, hp, xs)
    np.testing.assert_allclose(float(loss), float(rl), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(q_new), jax.tree.leaves(rq)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-6)
    np.testing.assert_allclose(
        np.asarray(dxs), np.asarray(gx), atol=2e-4, rtol=2e-4
    )
    for a, b in zip(jax.tree.leaves(hg), jax.tree.leaves(gh)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4
        )
    for a, b in zip(jax.tree.leaves(tg), jax.tree.leaves(gp)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-4, rtol=2e-4
        )


@pytest.mark.slow
def test_gpipe_classifier_delayed_quant_mutable_contract(quant_setup):
    """GPipeClassifier.apply honors the flax mutable-quant contract the
    Trainer's step uses: (logits, {"quant": updated}) with every trunk
    amax advanced; re-applying immutably with the updated collection is
    deterministic."""
    from pytorch_distributed_training_tpu.parallel.pipeline import (
        GPipeClassifier,
    )

    qcfg, model, _, _, _, _, _, _ = quant_setup
    mesh = build_mesh(MeshConfig(data=2, stage=4))
    gp = GPipeClassifier(qcfg, mesh, n_micro=4)
    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(0, qcfg.vocab_size, (8, 16)), jnp.int32)
    mask = jnp.asarray(rng.integers(0, 2, (8, 16)), jnp.int32).at[:, 0].set(1)
    v = model.init(jax.random.key(0), ids, mask)
    variables = {"params": v["params"], "quant": v["quant"]}

    logits, mut = gp.apply(
        variables, ids, mask, deterministic=True, mutable=["quant"]
    )
    assert np.isfinite(np.asarray(logits)).all()
    new_q = mut["quant"]
    assert jax.tree_util.tree_structure(new_q) == jax.tree_util.tree_structure(
        v["quant"]
    )
    for leaf in jax.tree.leaves(new_q["bert"]["layers_scan"]["layer"]):
        assert (np.asarray(leaf) > 0).all()  # every site observed real amaxes

    again = gp.apply(
        {"params": v["params"], "quant": new_q}, ids, mask,
        deterministic=True,
    )
    out2, mut2 = gp.apply(
        {"params": v["params"], "quant": new_q}, ids, mask,
        deterministic=True, mutable=["quant"],
    )
    np.testing.assert_array_equal(np.asarray(again), np.asarray(out2))
    # purity: identical variables + inputs -> bit-identical observations
    out3, mut3 = gp.apply(
        {"params": v["params"], "quant": new_q}, ids, mask,
        deterministic=True, mutable=["quant"],
    )
    np.testing.assert_array_equal(np.asarray(out2), np.asarray(out3))
    for a, b in zip(jax.tree.leaves(mut2["quant"]), jax.tree.leaves(mut3["quant"])):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow
@pytest.mark.parametrize("dropout", [0.0, 0.1])
def test_gpipe_train_step_delayed_quant_e2e(quant_setup, eight_devices,
                                            dropout):
    """The standard train step differentiates THROUGH the GPipe schedule
    with the quant carry: jax.grad over gpipe_apply + the mutable amax
    contract. Pins the stop_gradient on the carry (the cross-shard pmax
    has no AD rule — caught end-to-end, not by the forward-only tests).
    dropout=0.1 additionally exercises the rng-streaming + quant layer_fn
    variant (the 5-arg signature) through the same path."""
    from pytorch_distributed_training_tpu.comms.ingest import make_global_batch
    from pytorch_distributed_training_tpu.comms.mesh import TRAIN_BATCH_PSPEC
    from pytorch_distributed_training_tpu.parallel import (
        ShardingPolicy,
        state_shardings,
    )
    from pytorch_distributed_training_tpu.parallel.pipeline import (
        GPipeClassifier,
    )
    from pytorch_distributed_training_tpu.parallel.sharding import shard_state
    from pytorch_distributed_training_tpu.train import (
        adamw_with_schedule,
        calibrate_quant,
        create_train_state,
        make_train_step,
    )
    from pytorch_distributed_training_tpu.utils.config import TrainConfig

    qcfg = dataclasses.replace(
        quant_setup[0], hidden_dropout=dropout, attention_dropout=dropout
    )
    mesh = build_mesh(MeshConfig(data=4, stage=2))
    model = GPipeClassifier(qcfg, mesh, n_micro=2)
    tx, _ = adamw_with_schedule(TrainConfig(), 100)
    example = {
        "input_ids": jnp.ones((8, 16), jnp.int32),
        "attention_mask": jnp.ones((8, 16), jnp.int32),
        "token_type_ids": jnp.zeros((8, 16), jnp.int32),
    }
    s = create_train_state(model, tx, jax.random.key(0), example)
    assert s.quant is not None
    shardings = state_shardings(s, ShardingPolicy(stage=True), mesh)
    s = shard_state(s, shardings)
    rng = np.random.default_rng(9)
    batch = {
        "input_ids": rng.integers(0, qcfg.vocab_size, (2, 8, 16)).astype(
            np.int32
        ),
        "attention_mask": np.ones((2, 8, 16), np.int32),
        "token_type_ids": np.zeros((2, 8, 16), np.int32),
        "labels": rng.integers(0, 2, (2, 8)).astype(np.int32),
    }
    placed = make_global_batch(mesh, batch, pspec=TRAIN_BATCH_PSPEC)
    s = calibrate_quant(s, jax.tree.map(lambda x: x[0], placed))
    step = make_train_step(
        grad_accum_steps=2, mesh=mesh, state_shardings=shardings,
    )
    s2, m = step(s, placed)
    assert np.isfinite(float(m["loss"]))
    assert float(m["grad_norm"]) > 0.0
    after = [np.asarray(x) for x in jax.tree.leaves(jax.device_get(s2.quant))]
    assert all((x > 0).all() for x in after)


@pytest.mark.slow
def test_1f1b_train_step_delayed_quant_e2e(quant_setup, eight_devices):
    """make_1f1b_train_step with quant_delayed: the amaxes ride the
    accumulation scan and land back in TrainState.quant, advanced."""
    from pytorch_distributed_training_tpu.comms.ingest import make_global_batch
    from pytorch_distributed_training_tpu.comms.mesh import TRAIN_BATCH_PSPEC
    from pytorch_distributed_training_tpu.models import (
        BertForSequenceClassification,
    )
    from pytorch_distributed_training_tpu.parallel import (
        ShardingPolicy,
        state_shardings,
    )
    from pytorch_distributed_training_tpu.parallel.pipeline import (
        make_1f1b_train_step,
    )
    from pytorch_distributed_training_tpu.parallel.sharding import shard_state
    from pytorch_distributed_training_tpu.train import (
        adamw_with_schedule,
        calibrate_quant,
        create_train_state,
    )
    from pytorch_distributed_training_tpu.utils.config import TrainConfig

    qcfg = quant_setup[0]
    model = BertForSequenceClassification(qcfg)
    mesh = build_mesh(MeshConfig(data=2, stage=4))
    tx, _ = adamw_with_schedule(TrainConfig(), 100)
    example = {
        "input_ids": jnp.ones((2, 16), jnp.int32),
        "attention_mask": jnp.ones((2, 16), jnp.int32),
        "token_type_ids": jnp.zeros((2, 16), jnp.int32),
    }
    s = create_train_state(model, tx, jax.random.key(0), example)
    assert s.quant is not None
    shardings = state_shardings(s, ShardingPolicy(stage=True), mesh)
    s = shard_state(s, shardings)
    rng = np.random.default_rng(5)
    batch = {
        "input_ids": rng.integers(0, qcfg.vocab_size, (2, 8, 16)).astype(
            np.int32
        ),
        "attention_mask": np.ones((2, 8, 16), np.int32),
        "token_type_ids": np.zeros((2, 8, 16), np.int32),
        "labels": rng.integers(0, 2, (2, 8)).astype(np.int32),
    }
    placed = make_global_batch(mesh, batch, pspec=TRAIN_BATCH_PSPEC)
    s = calibrate_quant(s, jax.tree.map(lambda x: x[0], placed))
    before = [np.asarray(x) for x in jax.tree.leaves(jax.device_get(s.quant))]

    step = make_1f1b_train_step(
        qcfg, mesh, shardings, n_micro=4, grad_accum_steps=2
    )
    s2, m = step(s, placed)
    assert np.isfinite(float(m["loss"]))
    after = [np.asarray(x) for x in jax.tree.leaves(jax.device_get(s2.quant))]
    # amaxes advanced through the schedule
    assert any(not np.array_equal(a, b) for a, b in zip(before, after))
    assert all((x > 0).all() for x in after)
    s3, m3 = step(s2, placed)  # second step consumes the carried scales
    assert np.isfinite(float(m3["loss"]))
