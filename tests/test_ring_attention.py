"""Ring attention parity on the 8-device CPU mesh.

The contract: with the sequence dim sharded over the mesh ``seq`` axis, ring
attention computes EXACTLY what single-device attention computes — same
online-softmax math as flash, with K/V blocks arriving via ppermute instead
of a VMEM loop. Tests gather the sharded output and compare against the
reference einsum implementation, including causal masking with global
positions (the part a naive per-shard implementation gets wrong) and
gradient flow through the unrolled ring.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.comms.mesh import build_mesh
from pytorch_distributed_training_tpu.ops.attention import (
    dot_product_attention,
    make_attention_bias,
    reference_attention,
)
from pytorch_distributed_training_tpu.utils.config import MeshConfig


@pytest.fixture()
def seq_mesh():
    return build_mesh(MeshConfig(data=2, seq=4))


def _qkv(batch=4, seq=32, heads=2, head_dim=8, seed=0):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(
        rng.normal(size=(batch, seq, heads, head_dim)), jnp.float32
    )
    return mk(), mk(), mk()


@pytest.mark.parametrize("causal", [False, True])
def test_ring_matches_reference(seq_mesh, causal):
    q, k, v = _qkv()
    mask = np.ones((4, 32), np.int32)
    mask[1, 20:] = 0  # padding crossing shard boundaries (shards of 8)
    mask[3, 5:] = 0
    bias = make_attention_bias(jnp.asarray(mask))

    out = jax.jit(
        lambda q, k, v: dot_product_attention(
            q, k, v, bias, impl="ring", causal=causal
        )
    )(q, k, v)
    ref = reference_attention(q, k, v, bias, causal=causal)
    # compare only valid query rows (padded-query rows are garbage in both)
    for b in range(4):
        n = int(mask[b].sum())
        np.testing.assert_allclose(
            np.asarray(out[b, :n]), np.asarray(ref[b, :n]),
            atol=1e-5, rtol=1e-5,
        )


@pytest.mark.parametrize("causal", [False, True])
def test_ring_grad_matches_reference(seq_mesh, causal):
    q, k, v = _qkv(seed=1)
    cot = jnp.asarray(np.random.default_rng(2).normal(size=q.shape), jnp.float32)

    def loss(fn):
        def inner(q, k, v):
            return jnp.sum(fn(q, k, v) * cot)
        return inner

    ring = lambda q, k, v: dot_product_attention(
        q, k, v, None, impl="ring", causal=causal
    )
    ref = lambda q, k, v: reference_attention(q, k, v, None, causal=causal)
    g_ring = jax.jit(jax.grad(loss(ring), argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss(ref), argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_ring, g_ref, "qkv"):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-4,
            err_msg=f"d{name} (causal={causal})",
        )


@pytest.mark.slow
def test_ring_dropout_runs_and_masks(seq_mesh):
    """Dropout path: output differs from deterministic, zero-rate matches."""
    q, k, v = _qkv(seed=3)
    rng = jax.random.key(0)
    out_det = dot_product_attention(q, k, v, None, impl="ring")
    out_drop = dot_product_attention(
        q, k, v, None, impl="ring",
        dropout_rng=rng, dropout_rate=0.5, deterministic=False,
    )
    assert not np.allclose(np.asarray(out_det), np.asarray(out_drop))
    out_zero = dot_product_attention(
        q, k, v, None, impl="ring",
        dropout_rng=rng, dropout_rate=0.0, deterministic=False,
    )
    np.testing.assert_allclose(
        np.asarray(out_det), np.asarray(out_zero), atol=1e-6
    )


def test_ring_falls_back_without_seq_axis():
    mesh = build_mesh(MeshConfig(data=-1))  # seq axis size 1
    assert mesh.shape["seq"] == 1
    q, k, v = _qkv(seed=4)
    out = dot_product_attention(q, k, v, None, impl="ring")
    ref = reference_attention(q, k, v, None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)


@pytest.mark.slow
def test_context_parallel_train_step_parity():
    """Full jitted train step on a (data=2, seq=4) mesh with ring attention
    == the same step on a data-only mesh with reference attention: the CP
    slice (seq-sharded loader layout + shard_map ring inside GSPMD) changes
    the schedule, not the math."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_training_tpu.data.pipeline import ShardedLoader
    from pytorch_distributed_training_tpu.data.synthetic import (
        synthetic_pair_task,
    )
    from pytorch_distributed_training_tpu.models import (
        BertForSequenceClassification,
    )
    from pytorch_distributed_training_tpu.parallel import (
        ShardingPolicy,
        state_shardings,
    )
    from pytorch_distributed_training_tpu.parallel.sharding import shard_state
    from pytorch_distributed_training_tpu.train.optim import (
        adamw_with_schedule,
    )
    from pytorch_distributed_training_tpu.train.state import create_train_state
    from pytorch_distributed_training_tpu.utils.config import (
        TrainConfig,
        model_preset,
    )

    losses = {}
    for name, mesh_cfg, impl in [
        ("dp", MeshConfig(data=8), "reference"),
        ("cp", MeshConfig(data=2, seq=4), "ring"),
    ]:
        mesh = build_mesh(mesh_cfg)
        mcfg = model_preset(
            "tiny", compute_dtype="float32", attention_impl=impl,
            hidden_dropout=0.0, attention_dropout=0.0,
        )
        model = BertForSequenceClassification(mcfg)
        tcfg = TrainConfig(
            global_batch_size=16, micro_batch_size=8, max_seq_length=32,
            prng_impl="threefry2x32",
        )
        tx, _ = adamw_with_schedule(tcfg, total_steps=4)
        ex = {
            "input_ids": jnp.ones((2, 32), jnp.int32),
            "attention_mask": jnp.ones((2, 32), jnp.int32),
            "token_type_ids": jnp.zeros((2, 32), jnp.int32),
        }
        state = create_train_state(
            model, tx, jax.random.key(0, impl="threefry2x32"), ex
        )
        sh = state_shardings(state, ShardingPolicy(), mesh)
        state = shard_state(state, sh)
        from pytorch_distributed_training_tpu.train.step import make_train_step

        step = make_train_step(
            grad_accum_steps=tcfg.grad_accum_steps, mesh=mesh,
            state_shardings=sh,
        )
        data = synthetic_pair_task(32, max_length=32, vocab_size=1024, seed=0)
        loader = ShardedLoader(
            data, mesh, global_batch_size=16,
            grad_accum_steps=tcfg.grad_accum_steps, train=True, seed=0,
        )
        state, metrics = step(state, next(iter(loader.epoch(0))))
        losses[name] = float(jax.device_get(metrics["loss"]))

    np.testing.assert_allclose(losses["dp"], losses["cp"], rtol=1e-5)
