"""Paged KV cache + on-device sampling tests (serve/paged_cache.py,
serve/sampling.py, ops/paged_attention.py and their engine integration):
allocator lifecycle, page-budget admission backpressure, block-table
attention pins (reference vs dense formula, pallas-interpret vs reference),
the device-sampler's bit-exactness pin against the host sampler, engine
token-identity (paged vs dense vs one-shot generate, device vs host
sampling), mixed-context serving below dense-equivalent memory, and the
strict tick-wide transfer scope. CPU, tier-1 (except the perf-marked
BENCH_paged gate).
"""

import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.models.generate import generate
from pytorch_distributed_training_tpu.models.gpt2 import GPT2LMModel
from pytorch_distributed_training_tpu.ops.paged_attention import (
    paged_attention,
)
from pytorch_distributed_training_tpu.serve import (
    EngineConfig,
    InferenceServer,
)
from pytorch_distributed_training_tpu.serve.paged_cache import (
    PageAllocator,
    strip_tables,
    with_tables,
)
from pytorch_distributed_training_tpu.serve.sampling import device_sample
from pytorch_distributed_training_tpu.serve.server import wait_until
from pytorch_distributed_training_tpu.utils.config import model_preset

pytestmark = pytest.mark.serve

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class ListSink:
    """In-memory telemetry sink (same contract as JsonlSink.emit)."""

    def __init__(self):
        self.records = []

    def emit(self, record):
        rec = dict(record)
        rec.setdefault("ts", time.time())
        self.records.append(rec)

    def flush(self, **kw):
        pass

    def of(self, kind):
        return [r for r in self.records if r.get("record") == kind]


@pytest.fixture(scope="module")
def lm():
    cfg = model_preset(
        "gpt2-tiny", compute_dtype="float32", attention_impl="reference",
        hidden_dropout=0.0, attention_dropout=0.0,
    )
    model = GPT2LMModel(cfg)
    params = model.init(jax.random.key(0), jnp.ones((2, 16), jnp.int32))[
        "params"
    ]
    return model, params


def _registry():
    from pytorch_distributed_training_tpu.telemetry.registry import (
        MetricsRegistry,
    )

    reg = MetricsRegistry()
    sink = ListSink()
    reg.attach_sink(sink)
    return reg, sink


def _prompts(model, lengths, seed=0):
    rng = np.random.default_rng(seed)
    return [
        rng.integers(1, model.config.vocab_size, n).astype(np.int32)
        for n in lengths
    ]


# --------------------------------------------------------------- allocator


def test_allocator_alloc_free_reuse():
    alloc = PageAllocator(
        num_pages=9, page_size=4, pages_per_slot=3, num_slots=2
    )
    assert alloc.pages_free == 8 and alloc.pages_used == 0

    alloc.admit(0, 3)
    assert alloc.pages_used == 3 and alloc.pages_free == 5
    first = alloc.slot_pages(0)
    assert len(first) == 3 and 0 not in first
    np.testing.assert_array_equal(alloc.block_table[0], np.asarray(first))

    alloc.admit(1, 2)
    assert alloc.pages_used == 5
    # disjoint ownership, never the null page
    assert not set(first) & set(alloc.slot_pages(1))

    alloc.release(0)
    assert alloc.pages_used == 2 and alloc.pages_free == 6
    assert alloc.slot_pages(0) == ()
    np.testing.assert_array_equal(alloc.block_table[0], 0)

    # LIFO free list: the just-freed pages are re-handed first (hot set
    # stays small), in the same order the slot originally held them
    alloc.admit(0, 3)
    assert alloc.slot_pages(0) == first
    assert alloc.peak_used == 5


def test_allocator_exhaustion_backpressure_and_misuse():
    alloc = PageAllocator(
        num_pages=5, page_size=4, pages_per_slot=4, num_slots=2
    )
    assert alloc.can_alloc(4) and not alloc.can_alloc(5)
    alloc.admit(0, 3)
    assert not alloc.can_alloc(2)       # 1 free page left
    with pytest.raises(RuntimeError, match="exhausted"):
        alloc.admit(1, 2)
    with pytest.raises(RuntimeError, match="already holds"):
        alloc.admit(0, 1)
    with pytest.raises(ValueError, match="block-table rows"):
        alloc.admit(1, 5)
    # a failed admit must not leak or corrupt anything
    assert alloc.pages_used == 3 and alloc.can_alloc(1)

    # release is idempotent and returns everything
    alloc.release(0)
    alloc.release(0)
    assert alloc.pages_free == 4 and alloc.pages_used == 0

    # ceil-division page budget
    assert alloc.pages_needed(1) == 1
    assert alloc.pages_needed(4) == 1
    assert alloc.pages_needed(5) == 2
    assert alloc.pages_needed(0) == 1   # a slot always needs one page


def test_allocator_rejects_degenerate_shapes():
    with pytest.raises(ValueError, match="page_size"):
        PageAllocator(num_pages=4, page_size=0, pages_per_slot=1, num_slots=1)
    with pytest.raises(ValueError, match="num_pages"):
        PageAllocator(num_pages=1, page_size=4, pages_per_slot=1, num_slots=1)
    with pytest.raises(ValueError, match="pages_per_slot"):
        PageAllocator(num_pages=4, page_size=4, pages_per_slot=0, num_slots=1)


def test_with_tables_strip_tables_roundtrip():
    pools = {
        "layers_0": {"attn": {"k_pages": "K0", "v_pages": "V0"}},
        "layers_1": {"attn": {"k_pages": "K1", "v_pages": "V1"}},
    }
    full = with_tables(pools, "BT", "CL")
    for layer in ("layers_0", "layers_1"):
        node = full[layer]["attn"]
        assert node["block_table"] == "BT" and node["context_len"] == "CL"
    assert strip_tables(full) == pools
    # the original pools tree is untouched (with_tables builds a new dict)
    assert "block_table" not in pools["layers_0"]["attn"]


# ----------------------------------------------------- page-budget admission


def test_pop_ready_accept_predicate_is_strict_fifo():
    from pytorch_distributed_training_tpu.serve.queue import (
        GenRequest,
        RequestQueue,
    )

    q = RequestQueue(max_depth=8, prompt_buckets=(4, 8), max_new_tokens=4)
    big = q.submit(GenRequest(
        id="big", prompt_ids=np.ones(7, np.int32), max_new_tokens=4,
    ))
    q.submit(GenRequest(
        id="small", prompt_ids=np.ones(3, np.int32), max_new_tokens=4,
    ))

    # the earliest-submitted head (big) fails the predicate: pop_ready
    # must return None — the small request may NOT slip past it
    assert q.pop_ready(accept=lambda r: r.bucket <= 4) is None
    assert q.depth() == 2

    # once the head is accepted, submission order resumes
    assert q.pop_ready(accept=lambda r: True) is big
    assert q.pop_ready().id == "small"
    assert q.pop_ready() is None


# ------------------------------------------------------- paged attention op


def _paged_fixture(seed=0, batch=3, heads=2, head_dim=4, page_size=4,
                   windows=3, num_pages=16):
    """Random contiguous K/V scattered into a noise-filled page pool via a
    shuffled block table, plus the dense [B, T, H, D] mirror."""
    rng = np.random.default_rng(seed)
    T = page_size * windows
    q = rng.standard_normal((batch, heads, head_dim)).astype(np.float32)
    k = rng.standard_normal((batch, T, heads, head_dim)).astype(np.float32)
    v = rng.standard_normal((batch, T, heads, head_dim)).astype(np.float32)
    # pools start as GARBAGE, not zeros: masked lanes must be excluded by
    # the length mask alone, never by relying on zeroed storage
    k_pages = rng.standard_normal(
        (num_pages, page_size, heads, head_dim)
    ).astype(np.float32)
    v_pages = rng.standard_normal(
        (num_pages, page_size, heads, head_dim)
    ).astype(np.float32)
    ids = rng.permutation(np.arange(1, num_pages))[: batch * windows]
    block_table = ids.reshape(batch, windows).astype(np.int32)
    for b in range(batch):
        for w in range(windows):
            k_pages[block_table[b, w]] = k[b, w * page_size:(w + 1) * page_size]
            v_pages[block_table[b, w]] = v[b, w * page_size:(w + 1) * page_size]
    lengths = np.asarray([1, T - 3, T], np.int32)[:batch]
    return q, k, v, k_pages, v_pages, block_table, lengths


def _dense_formula(q, k, v, lengths, scale):
    """The exact fp32-softmax formula models/bert.py uses on the dense
    cache path, applied to contiguous K/V."""
    scores = jnp.einsum(
        "bnd,btnd->bnt", q, k, preferred_element_type=jnp.float32
    ) * scale
    pos = jax.lax.broadcasted_iota(jnp.int32, scores.shape, 2)
    scores = jnp.where(
        pos < lengths[:, None, None], scores, jnp.finfo(jnp.float32).min
    )
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(v.dtype)
    return jnp.einsum("bnt,btnd->bnd", probs, v)


def test_paged_reference_bitwise_matches_dense_formula():
    q, k, v, k_pages, v_pages, bt, lengths = _paged_fixture()
    scale = q.shape[-1] ** -0.5
    want = _dense_formula(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v),
        jnp.asarray(lengths), scale,
    )
    got = paged_attention(
        jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
        jnp.asarray(bt), jnp.asarray(lengths),
        scale=scale, impl="reference",
    )
    # bitwise: the gather through the block table reassembles the same
    # contiguous K/V, the masked (garbage) lanes contribute exact zeros
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_paged_pallas_interpret_matches_reference():
    from pytorch_distributed_training_tpu.ops.flash_attention import (
        tpu_interpret_mode,
    )

    q, k, v, k_pages, v_pages, bt, lengths = _paged_fixture(seed=5)
    scale = q.shape[-1] ** -0.5
    ref = paged_attention(
        jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
        jnp.asarray(bt), jnp.asarray(lengths),
        scale=scale, impl="reference",
    )
    with tpu_interpret_mode():
        got = paged_attention(
            jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(bt), jnp.asarray(lengths),
            scale=scale, impl="pallas",
        )
    # online softmax reorders the reduction: tight allclose, not bitwise
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(ref), rtol=1e-6, atol=1e-6
    )


def test_paged_attention_validates_shapes():
    q, k, v, k_pages, v_pages, bt, lengths = _paged_fixture()
    # q may be [B, H, D] (single query) or [B, Q, H, D] (multi-token
    # query, the spec-verify / chunked-prefill path) — 5-D is invalid.
    with pytest.raises(ValueError):
        paged_attention(
            jnp.asarray(q)[:, None, None], jnp.asarray(k_pages),
            jnp.asarray(v_pages), jnp.asarray(bt), jnp.asarray(lengths),
            scale=1.0,
        )
    with pytest.raises(ValueError):
        paged_attention(
            jnp.asarray(q), jnp.asarray(k_pages), jnp.asarray(v_pages),
            jnp.asarray(bt), jnp.asarray(lengths)[:-1], scale=1.0,
        )


# ----------------------------------------------------------------- sampling


def test_device_sample_bitwise_matches_host_sampler():
    """serve/sampling.device_sample is the in-jit mirror of the engine's
    host ``_sample``: same token id for every (temperature, top_k, seed,
    step) cell, including greedy ties, k=0 (no truncation), k=1 and
    k >= vocab."""
    from pytorch_distributed_training_tpu.serve.engine import DecodeEngine
    from pytorch_distributed_training_tpu.serve.queue import GenRequest

    vocab = 32
    rng = np.random.default_rng(0)
    cases = [
        (0.0, 0), (0.0, 5),             # greedy ignores top_k
        (0.7, 0), (0.7, 5), (1.3, 1),
        (0.9, vocab + 100),             # oversized k = no truncation
    ]
    for seed in (0, 11):
        for step in (0, 1, 5):
            logits = rng.standard_normal((len(cases), vocab)).astype(
                np.float32
            )
            logits[0, 3] = logits[0, 7] = logits[0].max() + 1.0  # greedy tie
            temps = np.asarray([t for t, _ in cases], np.float32)
            top_ks = np.asarray([k for _, k in cases], np.int32)
            got = np.asarray(device_sample(
                jnp.asarray(logits),
                jnp.full((len(cases),), seed, jnp.int32),
                jnp.full((len(cases),), step, jnp.int32),
                jnp.asarray(temps), jnp.asarray(top_ks),
            ))
            for i, (temp, top_k) in enumerate(cases):
                req = GenRequest(
                    id="x", prompt_ids=np.ones(1, np.int32),
                    max_new_tokens=8, temperature=temp, top_k=top_k,
                    seed=seed,
                )
                req.tokens = [0] * step     # host folds in len(req.tokens)
                want = DecodeEngine._sample(None, req, logits[i])
                assert int(got[i]) == want, (temp, top_k, seed, step)


# --------------------------------------------------------- engine identity


def _run_server(model, params, prompts, T, *, kv_layout, sampling,
                temperature=0.0, top_k=0, seed=0, **cfg_kw):
    reg, sink = _registry()
    server = InferenceServer(
        model, params,
        EngineConfig(
            num_slots=2, prompt_buckets=(4, 8, 16), max_new_tokens=T,
            kv_layout=kv_layout, sampling=sampling, **cfg_kw,
        ),
        queue_depth=16, registry=reg,
    ).start()
    try:
        reqs = [
            server.submit(
                p, max_new_tokens=T, temperature=temperature, top_k=top_k,
                seed=seed + i,
            )
            for i, p in enumerate(prompts)
        ]
        assert wait_until(
            lambda: all(r.done.is_set() for r in reqs), timeout=120
        )
    finally:
        server.close()
    assert all(r.status == "done" for r in reqs), [r.status for r in reqs]
    return [np.asarray(r.tokens, np.int32) for r in reqs], server.stats()


def test_paged_greedy_token_identical_to_dense_and_generate(lm):
    """Acceptance pin: the paged engine's greedy continuations are
    bit-identical to the dense engine's AND to one-shot generate() at the
    exact prompt length."""
    model, params = lm
    T = 5
    prompts = _prompts(model, [3, 6, 9, 14, 5], seed=7)
    want = [
        np.asarray(generate(model, params, p[None], max_new_tokens=T))[
            0, len(p):
        ]
        for p in prompts
    ]
    paged, pstats = _run_server(
        model, params, prompts, T, kv_layout="paged", sampling="device",
    )
    dense, dstats = _run_server(
        model, params, prompts, T, kv_layout="dense", sampling="host",
    )
    for i, (p_toks, d_toks, ref) in enumerate(zip(paged, dense, want)):
        np.testing.assert_array_equal(p_toks, ref, err_msg=f"paged req {i}")
        np.testing.assert_array_equal(d_toks, ref, err_msg=f"dense req {i}")
    assert pstats["kv_layout"] == "paged" and pstats["kv_pages_peak"] > 0
    assert dstats["kv_layout"] == "dense" and dstats["kv_pages_total"] is None


def test_sampled_device_matches_host_under_fixed_seed(lm):
    """Fixed-key sampled decode is exact across the sampling location AND
    the cache layout: paged+device == dense+host, token for token."""
    model, params = lm
    T = 6
    prompts = _prompts(model, [3, 7, 12], seed=3)
    kw = dict(temperature=0.8, top_k=5, seed=11)
    device_toks, _ = _run_server(
        model, params, prompts, T, kv_layout="paged", sampling="device", **kw
    )
    host_toks, _ = _run_server(
        model, params, prompts, T, kv_layout="dense", sampling="host", **kw
    )
    for i, (d, h) in enumerate(zip(device_toks, host_toks)):
        assert len(d) == T
        np.testing.assert_array_equal(d, h, err_msg=f"request {i}")


def test_page_exhaustion_backpressure_never_hangs(lm):
    """A pool holding ONE worst-case request at a time still drains a
    6-request burst: admission blocks on pages (page_exhausted ticks up),
    never wedges, and every answer is still greedy-exact."""
    model, params = lm
    T = 8
    prompts = _prompts(model, [8, 5, 8, 6, 7, 8], seed=1)
    want = [
        np.asarray(generate(model, params, p[None], max_new_tokens=T))[
            0, len(p):
        ]
        for p in prompts
    ]
    reg, sink = _registry()
    # pages_per_slot = ceil((8+8)/4) = 4; num_pages=5 leaves 4 usable —
    # exactly one worst-case request's budget, despite 4 slots
    server = InferenceServer(
        model, params,
        EngineConfig(
            num_slots=4, prompt_buckets=(8,), max_new_tokens=T,
            kv_layout="paged", sampling="device", page_size=4, num_pages=5,
        ),
        queue_depth=8, registry=reg,
    ).start()
    try:
        reqs = [server.submit(p, max_new_tokens=T) for p in prompts]
        assert wait_until(
            lambda: all(r.done.is_set() for r in reqs), timeout=120
        ), [r.status for r in reqs]
    finally:
        server.close()
    for i, (req, ref) in enumerate(zip(reqs, want)):
        assert req.status == "done"
        np.testing.assert_array_equal(
            np.asarray(req.tokens, np.int32), ref, err_msg=f"request {i}"
        )
    stats = server.stats()
    assert stats["page_exhausted"] > 0
    assert stats["kv_pages_used"] == 0 and stats["kv_pages_peak"] <= 4
    # eviction returned every page to the pool
    assert stats["kv_pages_free"] == 4


def test_mixed_context_pool_below_dense_equivalent(lm):
    """One paged engine admits a 1x-8x mixed-context workload through a
    pool SMALLER than num_slots x longest-context — the shape the dense
    layout cannot configure at equal memory (it charges every slot the
    longest context) — and stays greedy-exact including the longest
    request."""
    model, params = lm
    T = 4
    lengths = [3, 4, 26, 32, 4, 20]
    prompts = _prompts(model, lengths, seed=9)
    want = [
        np.asarray(generate(model, params, p[None], max_new_tokens=T))[
            0, len(p):
        ]
        for p in prompts
    ]
    reg, sink = _registry()
    page_size = 4
    pages_per_slot = -(-(32 + T) // page_size)          # 9
    dense_equiv = 4 * pages_per_slot                    # 36 usable pages
    num_pages = 20                                      # 19 usable < 36
    server = InferenceServer(
        model, params,
        EngineConfig(
            num_slots=4, prompt_buckets=(4, 32), max_new_tokens=T,
            kv_layout="paged", sampling="device",
            page_size=page_size, num_pages=num_pages,
        ),
        queue_depth=8, registry=reg,
    ).start()
    try:
        reqs = [server.submit(p, max_new_tokens=T) for p in prompts]
        assert wait_until(
            lambda: all(r.done.is_set() for r in reqs), timeout=120
        ), [r.status for r in reqs]
    finally:
        server.close()
    for i, (req, ref) in enumerate(zip(reqs, want)):
        assert req.status == "done"
        np.testing.assert_array_equal(
            np.asarray(req.tokens, np.int32), ref,
            err_msg=f"request {i} (len {lengths[i]})",
        )
    stats = server.stats()
    assert stats["kv_pages_total"] == num_pages - 1 < dense_equiv
    peak = stats["kv_pages_peak"]
    assert peak > 0 and peak <= num_pages - 1
    # the per-tick pool gauges landed in the registry
    gauges = reg.snapshot()["gauges"]
    assert "serve/kv_pages_used" in gauges
    assert "serve/kv_pages_free" in gauges
    assert gauges["serve/kv_pages_used"] == 0.0  # everything evicted


# ------------------------------------------------- strict tick-wide scope


def test_strict_tick_scope_two_buckets_zero_implicit_transfers(lm):
    """Acceptance: with warmup=True every compiled program is warm before
    the first real tick, so the WHOLE tick body runs under
    transfer_guard("disallow") from request one — a 2-bucket mixed
    greedy/sampled session records ZERO implicit transfers and zero
    recompiles in strict mode."""
    from pytorch_distributed_training_tpu.analysis.guards import GuardSet

    model, params = lm
    reg, sink = _registry()
    gs = GuardSet(mode="strict", registry=reg)
    server = InferenceServer(
        model, params,
        EngineConfig(
            num_slots=2, prompt_buckets=(4, 8), max_new_tokens=4,
            kv_layout="paged", sampling="device", warmup=True,
        ),
        queue_depth=16, registry=reg, guards=gs,
    ).start()
    try:
        rng = np.random.default_rng(3)
        reqs = []
        for i, n in enumerate([3, 6, 2, 7, 4, 5]):
            reqs.append(server.submit(
                rng.integers(1, model.config.vocab_size, n).astype(np.int32),
                max_new_tokens=4,
                temperature=0.8 if i % 2 else 0.0, top_k=3, seed=i,
            ))
        assert wait_until(
            lambda: all(r.done.is_set() for r in reqs), timeout=120
        )
    finally:
        server.close()

    assert all(r.status == "done" for r in reqs)
    stats = server.stats()
    assert stats["compiled_prefill_buckets"] == [4, 8]
    assert stats["guard_mode"] == "strict"
    assert stats["guard_recompiles"] == 0
    assert stats["guard_implicit_transfers"] == 0
    assert not sink.of("recompile") and not sink.of("implicit_transfer")
    for name in ("serve_prefill_b4", "serve_prefill_b8", "serve_decode"):
        assert gs.wrapped[name].calls >= 2, name
    # the warmed decode program passed its strict collective manifest:
    # a single-device engine moves zero bytes between chips
    (comm,) = sink.of("comm_audit")
    assert comm["name"] == "serve_decode" and comm["ok"] is True
    assert comm["count"] == 0


# ------------------------------------------------- periodic lock summaries


@pytest.mark.concurrency
def test_periodic_lock_summary_emits_on_cadence_and_stops():
    from pytorch_distributed_training_tpu.analysis.concurrency import (
        start_periodic_summary,
    )
    from pytorch_distributed_training_tpu.analysis.concurrency.locks import (
        LockRegistry,
        lock,
    )

    reg, sink = _registry()
    lr = LockRegistry(mode="record")
    with lock("test.periodic", registry=lr):
        pass
    ps = start_periodic_summary(0.02, registry=reg, lock_registry=lr)
    try:
        deadline = time.monotonic() + 10
        while ps.emitted < 3 and time.monotonic() < deadline:
            time.sleep(0.01)
    finally:
        ps.stop()
    stopped_at = ps.emitted
    assert stopped_at >= 3
    recs = sink.of("lock_summary")
    assert len(recs) >= 3
    assert all("test.periodic" in r["locks"] for r in recs)
    # stop() is bounded, idempotent, and halts emission
    ps.stop()
    time.sleep(0.08)
    assert ps.emitted == stopped_at

    with pytest.raises(ValueError, match="interval_s"):
        start_periodic_summary(0.0, registry=reg, lock_registry=lr)


# ------------------------------------------------------------ perf gate


@pytest.mark.perf
def test_paged_bench_device_sampling_beats_dense_host(tmp_path):
    """bench.py --paged: on the UNIFORM workload the paged cache + on-device
    sampling must sustain at least the dense cache + host sampling's
    tokens/sec (the PR's perf acceptance gate), and the mixed workload must
    run through a page pool smaller than the dense-equivalent allocation."""
    out = tmp_path / "BENCH_paged.json"
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO_ROOT, "bench.py"),
            "--paged", "--paged-out", str(out),
        ],
        capture_output=True, text=True, timeout=1200, cwd=REPO_ROOT,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    result = json.loads(out.read_text())

    uni = result["uniform"]
    assert uni["dense_host"]["kv_layout"] == "dense"
    assert uni["paged_device"]["kv_layout"] == "paged"
    # same workload on both sides
    assert uni["dense_host"]["tokens"] == uni["paged_device"]["tokens"]
    # the gate: paged + device sampling >= dense + host sampling
    assert (
        uni["paged_device"]["tokens_per_s"]
        >= uni["dense_host"]["tokens_per_s"]
    ), result
    assert uni["speedup"] >= 1.0

    mixed = result["mixed"]
    assert mixed["pool_below_dense_equiv"] is True
    assert mixed["paged_device"]["requests"] == 16
    for block in ("ttft_s", "tpot_s"):
        stats = mixed["paged_device"][block]
        assert stats["count"] > 0
        assert stats["p50"] <= stats["p95"] <= stats["p99"]
