"""Model-parallel capabilities: branch ensemble (TriBert twin) and stage
layer-split (ConcatBert twin), on the 8-device CPU mesh.

The reference's implicit claim — its MP script computes the same task as the
DP script — is made explicit here (SURVEY.md §4 parity tests): sharded runs
must match unsharded runs bit-for-bit-ish, and the ensemble must actually be
an ensemble (mean of its branches).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.comms.mesh import build_mesh
from pytorch_distributed_training_tpu.models import (
    BertForSequenceClassification,
    BranchEnsembleClassifier,
)
from pytorch_distributed_training_tpu.parallel import (
    ShardingPolicy,
    state_shardings,
)
from pytorch_distributed_training_tpu.parallel.sharding import param_pspecs
from pytorch_distributed_training_tpu.utils.config import (
    MeshConfig,
    model_preset,
)


def tiny(**kw):
    return model_preset("tiny", compute_dtype="float32", **kw)


def example(batch=4, seq=16, vocab=1024, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "input_ids": jnp.asarray(rng.integers(5, vocab, (batch, seq)), jnp.int32),
        "attention_mask": jnp.ones((batch, seq), jnp.int32),
        "token_type_ids": jnp.zeros((batch, seq), jnp.int32),
    }


def test_branch_ensemble_is_mean_of_branches():
    """Forward through the vmapped ensemble == manually running each branch's
    extracted weights through a single encoder stack and averaging."""
    from pytorch_distributed_training_tpu.models.branch import _EncoderStack
    from pytorch_distributed_training_tpu.models.bert import BertEmbeddings
    from pytorch_distributed_training_tpu.ops.attention import (
        make_attention_bias,
    )

    cfg = tiny(hidden_dropout=0.0, attention_dropout=0.0)
    model = BranchEnsembleClassifier(cfg, n_branches=3)
    ex = example()
    params = model.init(
        jax.random.key(0), ex["input_ids"], ex["attention_mask"],
        ex["token_type_ids"],
    )["params"]

    logits = model.apply(
        {"params": params}, ex["input_ids"], ex["attention_mask"],
        ex["token_type_ids"],
    )
    assert logits.shape == (4, cfg.num_labels)

    # Manual recomputation: shared embeddings → per-branch stack → mean.
    emb = BertEmbeddings(cfg)
    x = emb.apply(
        {"params": params["embeddings"]},
        ex["input_ids"], ex["token_type_ids"],
        jnp.broadcast_to(jnp.arange(16, dtype=jnp.int32)[None], (4, 16)),
        True,
    )
    bias = make_attention_bias(ex["attention_mask"])
    stack = _EncoderStack(cfg)
    outs = []
    for b in range(3):
        branch_params = jax.tree.map(lambda p: p[b], params["branches"])
        outs.append(stack.apply({"params": branch_params}, x, bias, True))
    fused = jnp.mean(jnp.stack(outs, 0), axis=0)

    import flax.linen as nn

    pooled = jnp.tanh(
        fused[:, 0] @ params["pooler"]["kernel"] + params["pooler"]["bias"]
    )
    manual = (
        pooled @ params["classifier"]["kernel"] + params["classifier"]["bias"]
    )
    np.testing.assert_allclose(np.asarray(logits), np.asarray(manual), atol=1e-5)


def test_branch_params_shard_over_model_axis(eight_devices):
    mesh = build_mesh(MeshConfig(data=2, fsdp=1, stage=1, model=4))
    cfg = tiny()
    model = BranchEnsembleClassifier(cfg, n_branches=4)
    ex = example()
    params = model.init(
        jax.random.key(0), ex["input_ids"], ex["attention_mask"],
        ex["token_type_ids"],
    )["params"]
    specs = param_pspecs(params, ShardingPolicy(branch=True), mesh)
    # every branch param leads with "model"; shared params stay replicated
    branch_leaves = jax.tree.leaves(specs["branches"])
    assert branch_leaves and all(s[0] == "model" for s in branch_leaves)
    assert all(
        s == jax.sharding.PartitionSpec()
        for s in jax.tree.leaves(specs["embeddings"])
    )


def test_branch_sharded_forward_matches_unsharded(eight_devices):
    mesh = build_mesh(MeshConfig(data=2, fsdp=1, stage=1, model=4))
    cfg = tiny(hidden_dropout=0.0, attention_dropout=0.0)
    model = BranchEnsembleClassifier(cfg, n_branches=4)
    ex = example()
    params = model.init(
        jax.random.key(0), ex["input_ids"], ex["attention_mask"],
        ex["token_type_ids"],
    )["params"]
    ref = model.apply(
        {"params": params}, ex["input_ids"], ex["attention_mask"],
        ex["token_type_ids"],
    )

    from jax.sharding import NamedSharding

    specs = param_pspecs(params, ShardingPolicy(branch=True), mesh)
    sharded = jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    )
    out = jax.jit(
        lambda p, ids, m, t: model.apply({"params": p}, ids, m, t)
    )(sharded, ex["input_ids"], ex["attention_mask"], ex["token_type_ids"])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_scan_layers_matches_loop_layers():
    """scan-stacked trunk == python-loop trunk when weights are copied over
    (layer i of the loop → slice i of the stack)."""
    cfg_loop = tiny(hidden_dropout=0.0, attention_dropout=0.0)
    cfg_scan = tiny(hidden_dropout=0.0, attention_dropout=0.0, scan_layers=True)
    m_loop = BertForSequenceClassification(cfg_loop)
    m_scan = BertForSequenceClassification(cfg_scan)
    ex = example()
    p_loop = m_loop.init(
        jax.random.key(0), ex["input_ids"], ex["attention_mask"],
        ex["token_type_ids"],
    )["params"]

    # restack loop weights into the scan layout
    bert = dict(p_loop["bert"])
    layers = [bert.pop(f"layer_{i}") for i in range(cfg_loop.num_layers)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs, 0), *layers)
    bert["layers_scan"] = {"layer": stacked}
    p_scan = dict(p_loop)
    p_scan["bert"] = bert

    out_loop = m_loop.apply(
        {"params": p_loop}, ex["input_ids"], ex["attention_mask"],
        ex["token_type_ids"],
    )
    out_scan = m_scan.apply(
        {"params": p_scan}, ex["input_ids"], ex["attention_mask"],
        ex["token_type_ids"],
    )
    np.testing.assert_allclose(
        np.asarray(out_scan), np.asarray(out_loop), atol=1e-5
    )


def test_stage_sharded_scan_forward(eight_devices):
    """Layer dim sharded over stage axis: compiles, runs, matches unsharded."""
    from jax.sharding import NamedSharding

    mesh = build_mesh(MeshConfig(data=2, fsdp=2, stage=2, model=1))
    cfg = tiny(hidden_dropout=0.0, attention_dropout=0.0, scan_layers=True)
    model = BertForSequenceClassification(cfg)
    ex = example()
    params = model.init(
        jax.random.key(0), ex["input_ids"], ex["attention_mask"],
        ex["token_type_ids"],
    )["params"]
    ref = model.apply(
        {"params": params}, ex["input_ids"], ex["attention_mask"],
        ex["token_type_ids"],
    )
    specs = param_pspecs(params, ShardingPolicy(stage=True), mesh)
    scan_leaves = jax.tree.leaves(specs["bert"]["layers_scan"])
    assert scan_leaves and all(s[0] == "stage" for s in scan_leaves)
    sharded = jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    )
    out = jax.jit(
        lambda p, ids, m, t: model.apply({"params": p}, ids, m, t)
    )(sharded, ex["input_ids"], ex["attention_mask"], ex["token_type_ids"])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


# ------------------------------------------------- collective footprints
#
# The communication contract of the existing parallel train steps, pinned
# as exact collective-kind sets + byte bounds (analysis/spmd). These are
# the regression tripwires for the sharded-replica work: an unexpected
# kind (or a param-bytes blowup) here means XLA's sharding propagation
# changed the program's comm pattern.


def _fsdp_step_and_state(mesh):
    """A compiled-ready fsdp train step + sharded state + global batch on
    the 8-device CPU mesh (micro divisible by data*fsdp=8)."""
    from pytorch_distributed_training_tpu.comms.ingest import (
        make_global_batch,
    )
    from pytorch_distributed_training_tpu.comms.mesh import TRAIN_BATCH_PSPEC
    from pytorch_distributed_training_tpu.parallel.sharding import shard_state
    from pytorch_distributed_training_tpu.train.optim import (
        adamw_with_schedule,
    )
    from pytorch_distributed_training_tpu.train.state import (
        create_train_state,
    )
    from pytorch_distributed_training_tpu.train.step import make_train_step
    from pytorch_distributed_training_tpu.utils.config import TrainConfig

    cfg = tiny()
    model = BertForSequenceClassification(cfg)
    tcfg = TrainConfig(
        global_batch_size=16, micro_batch_size=8, max_seq_length=16,
    )
    tx, _ = adamw_with_schedule(tcfg, total_steps=10)
    seq = 16
    ex = example(batch=2, seq=seq, vocab=cfg.vocab_size)
    state = create_train_state(
        model, tx, jax.random.key(0, impl="rbg"), ex
    )
    shardings = state_shardings(
        state, ShardingPolicy(fsdp=True, fsdp_min_size=128), mesh
    )
    state = shard_state(state, shardings)
    step = make_train_step(
        grad_accum_steps=tcfg.grad_accum_steps, mesh=mesh,
        state_shardings=shardings, objective="classification",
    )
    rng = np.random.default_rng(0)
    accum, micro = tcfg.grad_accum_steps, tcfg.micro_batch_size
    b = {
        "input_ids": rng.integers(
            5, cfg.vocab_size, (accum, micro, seq)
        ).astype(np.int32),
        "attention_mask": np.ones((accum, micro, seq), np.int32),
        "token_type_ids": np.zeros((accum, micro, seq), np.int32),
        "labels": rng.integers(0, 2, (accum, micro)).astype(np.int32),
    }
    batch = make_global_batch(mesh, b, pspec=TRAIN_BATCH_PSPEC)
    return step, state, batch, accum


@pytest.fixture(scope="module")
def fsdp_compiled(eight_devices):
    """The sharded fsdp step compiled ONCE for the footprint tests (the
    compile dominates their cost; both the positive pin and the negative
    de-sharding test audit the same program)."""
    mesh = build_mesh(MeshConfig(data=2, fsdp=4))
    step, state, batch, accum = _fsdp_step_and_state(mesh)
    compiled = step.lower(state, batch).compile()
    return mesh, state, batch, accum, compiled


def test_fsdp_train_step_collective_footprint(fsdp_compiled):
    """The fsdp step's compiled comm contract: parameter all-gathers plus
    gradient/metric all-reduces (XLA:CPU folds the grad reduce-scatter
    into all-reduce), nothing else, and the gather payload stays within a
    small multiple of param bytes per accumulation step."""
    from pytorch_distributed_training_tpu.analysis.spmd import (
        extract_collectives,
        summarize_collectives,
        train_manifest,
    )

    mesh, state, batch, accum, compiled = fsdp_compiled
    summary = summarize_collectives(
        extract_collectives(compiled.as_text(), world_size=8)
    )
    kinds = set(summary["by_kind"])
    assert "all-gather" in kinds          # sharded params get gathered
    assert kinds <= {"all-gather", "all-reduce", "reduce-scatter"}
    # param-bytes bound: each accumulation step may gather every sharded
    # param once for fwd and once for bwd (plus optimizer-update gathers)
    param_bytes = sum(
        leaf.nbytes for leaf in jax.tree.leaves(state.params)
    )
    ag_bytes = summary["by_kind"]["all-gather"]["bytes"]
    assert ag_bytes <= 4 * accum * param_bytes, (
        f"all-gather payload {ag_bytes}B exceeds "
        f"{4 * accum} x param bytes ({param_bytes}B) — params are being "
        f"re-gathered more than the fsdp schedule allows"
    )
    # and the derived manifest agrees (required all-gather included)
    manifest = train_manifest(mesh, fsdp_sharded=True)
    assert manifest.check(summary) == []


def test_pipeline_train_step_collective_footprint(eight_devices):
    """The gpipe program's compiled comm contract: the per-tick stage
    hand-off permutes plus the data-axis reduce, and nothing else — an
    all-gather here would mean activations stopped flowing point-to-point
    and started materializing everywhere."""
    import dataclasses

    from pytorch_distributed_training_tpu.analysis.spmd import (
        extract_collectives,
        summarize_collectives,
        train_manifest,
    )
    from pytorch_distributed_training_tpu.ops.attention import (
        make_attention_bias,
    )
    from pytorch_distributed_training_tpu.parallel.pipeline import (
        gpipe_apply,
        gpipe_trunk_fn,
    )

    cfg = tiny(num_layers=4, hidden_dropout=0.0, attention_dropout=0.0)
    scfg = dataclasses.replace(cfg, scan_layers=True)
    model = BertForSequenceClassification(scfg)
    ids = jnp.ones((4, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    stacked = params["bert"]["layers_scan"]["layer"]
    rng = np.random.default_rng(0)
    n_micro, mb, seq, h = 4, 2, 16, cfg.hidden_size
    xs = jnp.asarray(rng.normal(size=(n_micro, mb, seq, h)), jnp.float32)
    mask = jnp.asarray(
        rng.integers(0, 2, (n_micro, mb, seq)), jnp.int32
    ).at[:, :, 0].set(1)
    biases = jax.vmap(make_attention_bias)(mask)

    mesh = build_mesh(MeshConfig(data=4, stage=2))
    layer_fn = gpipe_trunk_fn(cfg)
    f = jax.jit(lambda p, x, b: gpipe_apply(mesh, layer_fn, p, x, b))
    txt = f.lower(stacked, xs, biases).compile().as_text()
    summary = summarize_collectives(
        extract_collectives(txt, world_size=8)
    )
    kinds = set(summary["by_kind"])
    assert "collective-permute" in kinds  # the stage hand-off IS permutes
    assert kinds <= {"collective-permute", "all-reduce"}
    assert train_manifest(mesh).check(summary) == []


def test_desharded_step_caught_by_strict_comm_audit(fsdp_compiled):
    """Acceptance negative: a replicated-policy step on the same fsdp
    mesh emits NO all-gather — the silent de-sharding regression. The
    strict comm_audit must raise AND leave the deviation in telemetry."""
    from pytorch_distributed_training_tpu.analysis.guards import (
        GuardViolation,
    )
    from pytorch_distributed_training_tpu.analysis.spmd import (
        comm_audit,
        train_manifest,
    )
    from pytorch_distributed_training_tpu.parallel.sharding import (
        shard_state,
    )
    from pytorch_distributed_training_tpu.train.step import make_train_step
    from pytorch_distributed_training_tpu.telemetry.registry import (
        MetricsRegistry,
    )
    from test_guards import ListSink  # sibling module (pytest sys.path)

    mesh, state, batch, accum, compiled_ok = fsdp_compiled
    # deliberately de-shard: replicate every param on the SAME mesh
    shardings_r = state_shardings(state, ShardingPolicy(fsdp=False), mesh)
    state_r = shard_state(jax.device_get(state), shardings_r)
    step_r = make_train_step(
        grad_accum_steps=accum, mesh=mesh, state_shardings=shardings_r,
        objective="classification",
    )
    compiled = step_r.lower(state_r, batch).compile()

    registry = MetricsRegistry()
    sink = ListSink()
    registry.attach_sink(sink)
    manifest = train_manifest(mesh, fsdp_sharded=True)
    with pytest.raises(GuardViolation, match="required all-gather absent"):
        comm_audit(
            "train_step", compiled, manifest,
            registry=registry, mode="strict", world_size=8,
        )
    (rec,) = sink.of("comm_audit")
    assert rec["ok"] is False
    assert any("all-gather" in d for d in rec["deviations"])
    counters = registry.snapshot()["counters"]
    assert counters["guards/comm_deviations"] >= 1
    # the sharded original conforms under the same strict manifest
    rec_ok = comm_audit(
        "train_step", compiled_ok, manifest,
        registry=registry, mode="strict", world_size=8,
    )
    assert rec_ok["ok"] is True


@pytest.mark.parametrize("mode", ["branch", "stage"])
@pytest.mark.slow
def test_mp_trainer_end_to_end(eight_devices, mode):
    """The MP entry point's Trainer learns on the synthetic task — the
    reference's only verification, on both model-parallel modes."""
    from pytorch_distributed_training_tpu.train.loop import Trainer
    from pytorch_distributed_training_tpu.utils.config import TrainConfig

    cfg = tiny(scan_layers=mode == "stage")
    tcfg = TrainConfig(
        num_epochs=1, global_batch_size=32, micro_batch_size=16,
        eval_batch_size=32, learning_rate=1e-3, warmup_steps=5,
        log_every=0, bf16=False, train_size=256, eval_size=64,
    )
    if mode == "branch":
        model = BranchEnsembleClassifier(cfg, n_branches=2)
        mesh_cfg = MeshConfig(data=4, fsdp=1, stage=1, model=2)
        policy = ShardingPolicy(branch=True)
    else:
        model = None
        mesh_cfg = MeshConfig(data=4, fsdp=1, stage=2, model=1)
        policy = ShardingPolicy(stage=True)
    trainer = Trainer(cfg, tcfg, mesh_cfg, policy, task="synthetic", model=model)
    history = trainer.run()
    assert np.isfinite(history[-1]["train_loss"])
    assert history[-1]["accuracy"] > 0.3
