"""Model-parallel capabilities: branch ensemble (TriBert twin) and stage
layer-split (ConcatBert twin), on the 8-device CPU mesh.

The reference's implicit claim — its MP script computes the same task as the
DP script — is made explicit here (SURVEY.md §4 parity tests): sharded runs
must match unsharded runs bit-for-bit-ish, and the ensemble must actually be
an ensemble (mean of its branches).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_training_tpu.comms.mesh import build_mesh
from pytorch_distributed_training_tpu.models import (
    BertForSequenceClassification,
    BranchEnsembleClassifier,
)
from pytorch_distributed_training_tpu.parallel import (
    ShardingPolicy,
    state_shardings,
)
from pytorch_distributed_training_tpu.parallel.sharding import param_pspecs
from pytorch_distributed_training_tpu.utils.config import (
    MeshConfig,
    model_preset,
)


def tiny(**kw):
    return model_preset("tiny", compute_dtype="float32", **kw)


def example(batch=4, seq=16, vocab=1024, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "input_ids": jnp.asarray(rng.integers(5, vocab, (batch, seq)), jnp.int32),
        "attention_mask": jnp.ones((batch, seq), jnp.int32),
        "token_type_ids": jnp.zeros((batch, seq), jnp.int32),
    }


def test_branch_ensemble_is_mean_of_branches():
    """Forward through the vmapped ensemble == manually running each branch's
    extracted weights through a single encoder stack and averaging."""
    from pytorch_distributed_training_tpu.models.branch import _EncoderStack
    from pytorch_distributed_training_tpu.models.bert import BertEmbeddings
    from pytorch_distributed_training_tpu.ops.attention import (
        make_attention_bias,
    )

    cfg = tiny(hidden_dropout=0.0, attention_dropout=0.0)
    model = BranchEnsembleClassifier(cfg, n_branches=3)
    ex = example()
    params = model.init(
        jax.random.key(0), ex["input_ids"], ex["attention_mask"],
        ex["token_type_ids"],
    )["params"]

    logits = model.apply(
        {"params": params}, ex["input_ids"], ex["attention_mask"],
        ex["token_type_ids"],
    )
    assert logits.shape == (4, cfg.num_labels)

    # Manual recomputation: shared embeddings → per-branch stack → mean.
    emb = BertEmbeddings(cfg)
    x = emb.apply(
        {"params": params["embeddings"]},
        ex["input_ids"], ex["token_type_ids"],
        jnp.broadcast_to(jnp.arange(16, dtype=jnp.int32)[None], (4, 16)),
        True,
    )
    bias = make_attention_bias(ex["attention_mask"])
    stack = _EncoderStack(cfg)
    outs = []
    for b in range(3):
        branch_params = jax.tree.map(lambda p: p[b], params["branches"])
        outs.append(stack.apply({"params": branch_params}, x, bias, True))
    fused = jnp.mean(jnp.stack(outs, 0), axis=0)

    import flax.linen as nn

    pooled = jnp.tanh(
        fused[:, 0] @ params["pooler"]["kernel"] + params["pooler"]["bias"]
    )
    manual = (
        pooled @ params["classifier"]["kernel"] + params["classifier"]["bias"]
    )
    np.testing.assert_allclose(np.asarray(logits), np.asarray(manual), atol=1e-5)


def test_branch_params_shard_over_model_axis(eight_devices):
    mesh = build_mesh(MeshConfig(data=2, fsdp=1, stage=1, model=4))
    cfg = tiny()
    model = BranchEnsembleClassifier(cfg, n_branches=4)
    ex = example()
    params = model.init(
        jax.random.key(0), ex["input_ids"], ex["attention_mask"],
        ex["token_type_ids"],
    )["params"]
    specs = param_pspecs(params, ShardingPolicy(branch=True), mesh)
    # every branch param leads with "model"; shared params stay replicated
    branch_leaves = jax.tree.leaves(specs["branches"])
    assert branch_leaves and all(s[0] == "model" for s in branch_leaves)
    assert all(
        s == jax.sharding.PartitionSpec()
        for s in jax.tree.leaves(specs["embeddings"])
    )


def test_branch_sharded_forward_matches_unsharded(eight_devices):
    mesh = build_mesh(MeshConfig(data=2, fsdp=1, stage=1, model=4))
    cfg = tiny(hidden_dropout=0.0, attention_dropout=0.0)
    model = BranchEnsembleClassifier(cfg, n_branches=4)
    ex = example()
    params = model.init(
        jax.random.key(0), ex["input_ids"], ex["attention_mask"],
        ex["token_type_ids"],
    )["params"]
    ref = model.apply(
        {"params": params}, ex["input_ids"], ex["attention_mask"],
        ex["token_type_ids"],
    )

    from jax.sharding import NamedSharding

    specs = param_pspecs(params, ShardingPolicy(branch=True), mesh)
    sharded = jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    )
    out = jax.jit(
        lambda p, ids, m, t: model.apply({"params": p}, ids, m, t)
    )(sharded, ex["input_ids"], ex["attention_mask"], ex["token_type_ids"])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_scan_layers_matches_loop_layers():
    """scan-stacked trunk == python-loop trunk when weights are copied over
    (layer i of the loop → slice i of the stack)."""
    cfg_loop = tiny(hidden_dropout=0.0, attention_dropout=0.0)
    cfg_scan = tiny(hidden_dropout=0.0, attention_dropout=0.0, scan_layers=True)
    m_loop = BertForSequenceClassification(cfg_loop)
    m_scan = BertForSequenceClassification(cfg_scan)
    ex = example()
    p_loop = m_loop.init(
        jax.random.key(0), ex["input_ids"], ex["attention_mask"],
        ex["token_type_ids"],
    )["params"]

    # restack loop weights into the scan layout
    bert = dict(p_loop["bert"])
    layers = [bert.pop(f"layer_{i}") for i in range(cfg_loop.num_layers)]
    stacked = jax.tree.map(lambda *xs: jnp.stack(xs, 0), *layers)
    bert["layers_scan"] = {"layer": stacked}
    p_scan = dict(p_loop)
    p_scan["bert"] = bert

    out_loop = m_loop.apply(
        {"params": p_loop}, ex["input_ids"], ex["attention_mask"],
        ex["token_type_ids"],
    )
    out_scan = m_scan.apply(
        {"params": p_scan}, ex["input_ids"], ex["attention_mask"],
        ex["token_type_ids"],
    )
    np.testing.assert_allclose(
        np.asarray(out_scan), np.asarray(out_loop), atol=1e-5
    )


def test_stage_sharded_scan_forward(eight_devices):
    """Layer dim sharded over stage axis: compiles, runs, matches unsharded."""
    from jax.sharding import NamedSharding

    mesh = build_mesh(MeshConfig(data=2, fsdp=2, stage=2, model=1))
    cfg = tiny(hidden_dropout=0.0, attention_dropout=0.0, scan_layers=True)
    model = BertForSequenceClassification(cfg)
    ex = example()
    params = model.init(
        jax.random.key(0), ex["input_ids"], ex["attention_mask"],
        ex["token_type_ids"],
    )["params"]
    ref = model.apply(
        {"params": params}, ex["input_ids"], ex["attention_mask"],
        ex["token_type_ids"],
    )
    specs = param_pspecs(params, ShardingPolicy(stage=True), mesh)
    scan_leaves = jax.tree.leaves(specs["bert"]["layers_scan"])
    assert scan_leaves and all(s[0] == "stage" for s in scan_leaves)
    sharded = jax.tree.map(
        lambda p, s: jax.device_put(p, NamedSharding(mesh, s)), params, specs
    )
    out = jax.jit(
        lambda p, ids, m, t: model.apply({"params": p}, ids, m, t)
    )(sharded, ex["input_ids"], ex["attention_mask"], ex["token_type_ids"])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.parametrize("mode", ["branch", "stage"])
@pytest.mark.slow
def test_mp_trainer_end_to_end(eight_devices, mode):
    """The MP entry point's Trainer learns on the synthetic task — the
    reference's only verification, on both model-parallel modes."""
    from pytorch_distributed_training_tpu.train.loop import Trainer
    from pytorch_distributed_training_tpu.utils.config import TrainConfig

    cfg = tiny(scan_layers=mode == "stage")
    tcfg = TrainConfig(
        num_epochs=1, global_batch_size=32, micro_batch_size=16,
        eval_batch_size=32, learning_rate=1e-3, warmup_steps=5,
        log_every=0, bf16=False, train_size=256, eval_size=64,
    )
    if mode == "branch":
        model = BranchEnsembleClassifier(cfg, n_branches=2)
        mesh_cfg = MeshConfig(data=4, fsdp=1, stage=1, model=2)
        policy = ShardingPolicy(branch=True)
    else:
        model = None
        mesh_cfg = MeshConfig(data=4, fsdp=1, stage=2, model=1)
        policy = ShardingPolicy(stage=True)
    trainer = Trainer(cfg, tcfg, mesh_cfg, policy, task="synthetic", model=model)
    history = trainer.run()
    assert np.isfinite(history[-1]["train_loss"])
    assert history[-1]["accuracy"] > 0.3
