"""Test harness configuration.

The reference repo has no test suite at all (SURVEY.md §4) — its only
verification is end-to-end convergence. This framework instead follows the
standard JAX simulated-distributed strategy: run every test single-process on
8 virtual CPU devices (``--xla_force_host_platform_device_count=8``) so mesh /
pjit / psum code paths execute real SPMD partitioning with no TPU attached.

This module MUST run before anything imports jax, which pytest guarantees for
a root conftest. The axon TPU plugin (this image's tunnel to one real chip) is
explicitly disabled for tests — benchmarks use it, tests don't.
"""

import os

# Persistent XLA compilation cache: OPT-IN ONLY (PDT_TPU_TEST_CACHE=1).
# It roughly halves cold suite time and cuts warm reruns ~4x, BUT on this
# image XLA:CPU deterministically SIGABRTs when RELOADING the cached
# executable of certain SPMD train steps (repro: the fsdp=4 x data=2
# scanned-LM step in test_lm.py — fresh-cache run passes, the very next
# run aborts reading its own entry; jax_persistent_cache_enable_xla_caches
# = "none" does not help). A suite that can abort is worse than a slow
# suite, so default is OFF.
_WANT_CACHE = os.environ.get("PDT_TPU_TEST_CACHE") == "1"
if _WANT_CACHE:
    os.environ.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(__file__), ".jax_cache"),
    )
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "0.5")
    os.environ.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "0")
else:
    # actively OFF: a JAX_COMPILATION_CACHE_DIR exported in the caller's
    # shell would otherwise re-enable the aborting cache silently (for
    # this process via the config.update below, and for cli.launch
    # subprocesses via the env)
    os.environ.pop("JAX_COMPILATION_CACHE_DIR", None)

# Zero-egress image: don't let HF datasets/hub spend ~20s discovering there
# is no network before the offline synthetic fallback kicks in.
os.environ.setdefault("HF_HUB_OFFLINE", "1")
os.environ.setdefault("HF_DATASETS_OFFLINE", "1")

# Disable the axon single-TPU tunnel for tests; force an 8-device CPU mesh.
# The axon sitecustomize registers its PJRT plugin at interpreter startup
# (before any conftest can run), so clearing env vars is not enough — we also
# flip the already-imported jax to CPU and reset its backend cache.
#
# PDT_TPU_TESTS=1 inverts the setup: the backend is left on the real chip
# and only the ``@pytest.mark.tpu`` tier runs — the kernel paths the CPU
# suite can't see (pltpu.prng_random_bits is all-zeros in interpret mode;
# NOTES.md). Usage: PDT_TPU_TESTS=1 python -m pytest tests/ -m tpu -q
_TPU_TIER = os.environ.get("PDT_TPU_TESTS") == "1"
if not _TPU_TIER:
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    _flags = os.environ.get("XLA_FLAGS", "")
    if "--xla_force_host_platform_device_count" not in _flags:
        os.environ["XLA_FLAGS"] = (
            _flags + " --xla_force_host_platform_device_count=8"
        ).strip()

import jax  # noqa: E402

# The env vars above are read at jax import, but the axon sitecustomize
# imports jax at interpreter startup (before this conftest) — re-apply the
# cache config through the live config object so it actually takes effect
# in the pytest process itself (launch subprocesses pick it up via env).
if _WANT_CACHE:
    jax.config.update(
        "jax_compilation_cache_dir", os.environ["JAX_COMPILATION_CACHE_DIR"]
    )
    jax.config.update(
        "jax_persistent_cache_min_compile_time_secs",
        float(os.environ["JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS"]),
    )
    jax.config.update(
        "jax_persistent_cache_min_entry_size_bytes",
        int(os.environ["JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES"]),
    )
else:
    jax.config.update("jax_compilation_cache_dir", None)

if not _TPU_TIER:
    jax.config.update("jax_platforms", "cpu")
    # Private API, required to un-register the axon backend sitecustomize
    # already installed. Guarded so a jax rename fails with a clear message.
    try:
        import jax._src.xla_bridge as _xb  # noqa: E402

        _xb._clear_backends()
    except (ImportError, AttributeError) as e:  # pragma: no cover
        raise RuntimeError(
            "jax private API _clear_backends moved (jax upgrade?); "
            "update conftest"
        ) from e
    if len(jax.devices()) != 8:  # pragma: no cover - depends on launch env
        raise RuntimeError(
            f"conftest failed to set up the 8-device CPU mesh "
            f"(got {jax.devices()})"
        )

import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    """tpu-marked tests run only on the real chip (PDT_TPU_TESTS=1 tier);
    everything else runs only on the CPU mesh — one suite, two tiers.
    perf-marked benchmarks are opt-in (-m perf): they assert on wall-clock
    comparisons, which would make tier-1 flaky under load."""
    skip_tpu = pytest.mark.skip(
        reason="on-TPU tier: run with PDT_TPU_TESTS=1 -m tpu on the chip"
    )
    skip_cpu = pytest.mark.skip(
        reason="CPU-mesh test: run without PDT_TPU_TESTS"
    )
    skip_perf = pytest.mark.skip(
        reason="timing benchmark: opt in with -m perf"
    )
    want_perf = "perf" in (config.getoption("-m") or "")
    for item in items:
        is_tpu = "tpu" in item.keywords
        if is_tpu and not _TPU_TIER:
            item.add_marker(skip_tpu)
        elif not is_tpu and _TPU_TIER:
            item.add_marker(skip_cpu)
        if "perf" in item.keywords and not want_perf:
            item.add_marker(skip_perf)


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devices = jax.devices()
    assert len(devices) == 8, f"expected 8 virtual CPU devices, got {len(devices)}"
    return devices


@pytest.fixture(autouse=True)
def _clear_kernel_dispatch_ctx():
    """A Trainer registers its mesh as the global kernel-dispatch context
    (ops/dispatch.py) and that registration intentionally outlives it in a
    real process; between TESTS it must not leak (an interpret-mode parity
    test after a Trainer test would silently shard_map over the stale
    mesh)."""
    yield
    from pytorch_distributed_training_tpu.ops.dispatch import (
        set_kernel_mesh,
    )

    set_kernel_mesh(None)
